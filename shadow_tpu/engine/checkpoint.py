"""Crash-safe checkpoint/resume: snapshot the simulation state arrays.

The reference has no checkpointing (SURVEY §5 calls it out as absent);
on TPU the whole simulation is a pytree of dense arrays, so a snapshot
is one device->host copy + npz write, and resume is exact: the restored
run produces the same results as an uninterrupted one (asserted by
tests/test_checkpoint.py, digest-chain-level by
tests/test_until_complete.py).

Durability contract (docs/durability.md):

- a save is ATOMIC: the npz is written to ``<file>.tmp``, fsynced,
  and ``os.replace``d into place, so a SIGKILL at any instant leaves
  either the previous complete snapshot set or the new one — never a
  half-written head;
- every snapshot is stamped with a content hash (``<file>.sha256``
  sidecar) verified on load; a corrupt head falls back LOUDLY to the
  newest older snapshot that verifies;
- the last ``keep`` snapshots are retained as ``<base>.w<windows>.npz``
  siblings with a ``<base>.latest`` pointer (JSON, atomically
  replaced) naming the head — ``--resume latest`` and the auto-resume
  supervisor (engine.supervisor) resolve through it;
- runs with a fault schedule stamp the injector's schedule position
  (``__fault_idx__``) so resume re-arms engine.faults exactly; runs
  with hosted apps write a ``<file>.hosted`` sidecar (the pickled
  hosting tier + per-child protocol journals, hosting.runtime) that
  resume replays to fast-forward respawned children.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import sys
import zipfile
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

# snapshots retained per store; SHADOW_TPU_CHECKPOINT_KEEP overrides
DEFAULT_KEEP = 3

POINTER_FORMAT = "shadow_tpu.checkpoint.latest"


def named_leaves(hosts) -> list:
    """[(field_name, leaf array)] in declaration order — the leaf
    enumeration the digest recorder (obs.digest) hashes. save() below
    serializes via jax.tree.flatten, whose order DIFFERS (chex does
    not flatten in declaration order) but whose leaf set is identical
    — asserted in save(), so a field the digest hashes can never be
    silently absent from checkpoints or vice versa. Each consumer is
    internally order-consistent; nothing exchanges ordered leaves."""
    import dataclasses
    return [(f.name, getattr(hosts, f.name))
            for f in dataclasses.fields(hosts)]


# EngineConfig knobs that are BIT-EXACT by contract (each pinned by a
# dedicated equality test): they change how the compiled program
# schedules work, never which state it computes — so a checkpoint
# taken under one value resumes exactly under another, and the
# scenario fingerprint must not bind to them (a pre-hot-split
# checkpoint loads into the split engine; an event_batch retune does
# not orphan a fleet's stores). Everything else — array shapes, app
# wiring, protocol semantics, deferral capacities — stays in the hash.
_PERF_ONLY_KNOBS = ("active_block", "exsortcap", "dstcap",
                    "event_batch", "hot_split")


def scenario_fingerprint(scenario, cfg, seed: int) -> str:
    """Stable hash binding a checkpoint to its scenario + engine
    shape/semantics (perf-only knobs excluded — see
    _PERF_ONLY_KNOBS)."""
    import dataclasses
    cfg_sem = {k: v for k, v in sorted(
        dataclasses.asdict(cfg).items()) if k not in _PERF_ONLY_KNOBS}
    text = json.dumps({
        "scenario": repr(scenario),
        "cfg": json.dumps(cfg_sem, sort_keys=True, default=repr),
        "seed": seed,
    }, sort_keys=True)
    return hashlib.sha256(text.encode()).hexdigest()[:16]


def base_of(path: str) -> str:
    """Store base for a user-facing checkpoint path: ``run/ck.npz``
    and ``run/ck`` both name the store whose snapshots are
    ``run/ck.w<windows>.npz`` and whose pointer is ``run/ck.latest``."""
    return path[:-4] if path.endswith(".npz") else path


# run ids usable as a store namespace: path-safe, no separators, no
# traversal — one shared definition so fleet queue, status tooling and
# tests agree on what a valid run id is
_RUN_ID_OK = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,99}$")


def valid_run_id(run_id: str) -> bool:
    return bool(_RUN_ID_OK.match(run_id or ""))


def run_store_base(root: str, run_id: str, name: str = "ck") -> str:
    """Per-run checkpoint-store namespacing for fleets of runs
    (shadow_tpu.fleet): each run owns ``<root>/<run_id>/<name>`` as
    its store base, so rotation, the ``latest`` pointer, the
    supervisor crash log and the hosted sidecars of concurrent runs
    can never collide. `run_id` must be path-safe (valid_run_id) —
    rejected loudly here rather than silently nesting directories or
    escaping `root`."""
    if not valid_run_id(run_id):
        raise ValueError(
            f"run id {run_id!r} is not a valid store namespace "
            "(want: letters/digits/._- only, starting with an "
            "alphanumeric, <=100 chars)")
    return os.path.join(root, run_id, name)


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _fsync_dir(path: str):
    """Make a rename durable: fsync the containing directory (without
    this, a machine crash — not just a process kill — can lose the
    directory entry even though the file data is on disk)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass            # some filesystems refuse directory fsync
    finally:
        os.close(fd)


def _write_atomic(path: str, data: bytes):
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


@dataclass
class Snapshot:
    """One restored checkpoint (load())."""
    hosts: object
    wstart: int
    wend: int
    windows: int
    fault_idx: int = -1         # engine.faults schedule position at
    #   save time (-1: no fault schedule was active)
    digest_records: int = -1    # obs.digest chain position at save
    #   time: records already written (-1: digest was off) — the
    #   resumed run truncates the chain file to exactly this many
    #   records and re-produces the rest live
    digest_chain: str = None    # running chain hash at that position
    #   (verified against the refolded prefix on rewind)
    hosted_blob: bytes = None   # hosting.runtime snapshot sidecar
    path: str = None            # the .npz actually restored
    meta: dict = field(default_factory=dict)


class CheckpointStore:
    """Owns one checkpoint base: atomic rotating snapshots + pointer."""

    def __init__(self, path: str, keep: int = 0):
        self.base = base_of(path)
        self.keep = int(keep) or int(os.environ.get(
            "SHADOW_TPU_CHECKPOINT_KEEP", str(DEFAULT_KEEP)))
        self.keep = max(self.keep, 1)
        # no directory side effects here: read-only users (resolve_
        # latest, tools/divergence.py) construct a store just to
        # enumerate snapshots; save() creates the directory

    # --- writing ---
    def save(self, hosts, wstart, wend, windows: int, fingerprint: str,
             fault_idx: int = -1, hosted_blob: bytes = None,
             digest_records: int = -1,
             digest_chain: str = None) -> str:
        """Write one snapshot. Ordering is the whole durability story:
        the npz is staged to a ``.tmp``, its hash sidecar and hosted
        sidecar are written FIRST, and only then does ``os.replace``
        publish the npz — so at no instant does a complete-looking
        ``.npz`` exist without its sidecars (resolve_latest would
        otherwise trust a hashless head and, on hosted runs, resume
        would crash-loop on the missing ``.hosted``). The ``latest``
        pointer flips last, after every byte is durable."""
        os.makedirs(os.path.dirname(os.path.abspath(self.base)),
                    exist_ok=True)
        leaves, treedef = jax.tree.flatten(hosts)
        # checkpoints and digests must cover the same leaf SET (orders
        # legitimately differ — see named_leaves): a pytree leaf that
        # is not a dataclass field would be digested but not
        # checkpointed, or vice versa
        named = named_leaves(hosts)
        assert (len(named) == len(leaves)
                and {id(a) for _, a in named} == {id(b) for b in leaves})
        file = f"{self.base}.w{int(windows):010d}.npz"
        tmp = file + ".tmp"
        hosted_name = hosted_sha = None
        if hosted_blob is not None:
            hosted_name = os.path.basename(file) + ".hosted"
            hosted_sha = hashlib.sha256(hosted_blob).hexdigest()
        with open(tmp, "wb") as f:
            np.savez_compressed(
                f,
                __fingerprint__=np.frombuffer(
                    fingerprint.encode(), dtype=np.uint8),
                __wstart__=np.int64(int(wstart)),
                __wend__=np.int64(int(wend)),
                __windows__=np.int64(windows),
                __fault_idx__=np.int64(fault_idx),
                __digest_records__=np.int64(digest_records),
                __digest_chain__=np.frombuffer(
                    (digest_chain or "").encode(), dtype=np.uint8),
                # stamped INSIDE the hash-verified npz so _verify can
                # demand a matching .hosted sidecar (a corrupt or
                # missing sidecar falls back like any corrupt head)
                __hosted_sha__=np.frombuffer(
                    (hosted_sha or "").encode(), dtype=np.uint8),
                **{f"leaf{i}": np.asarray(x)
                   for i, x in enumerate(leaves)},
            )
            f.flush()
            os.fsync(f.fileno())
        # the tmp was fsynced a moment ago: this re-read is served
        # from the page cache, not disk
        sha = _sha256_file(tmp)
        _write_atomic(file + ".sha256", (sha + "\n").encode())
        if hosted_blob is not None:
            _write_atomic(file + ".hosted", hosted_blob)
        else:
            try:                  # a stale sidecar from an earlier
                os.unlink(file + ".hosted")    # hosted run of the
            except OSError:                    # same base must not
                pass                           # survive this save
        # publish LAST: at no instant does a complete-looking .npz
        # exist without its sidecars
        os.replace(tmp, file)
        _fsync_dir(os.path.dirname(os.path.abspath(file)))
        pointer = {
            "format": POINTER_FORMAT, "version": 1,
            "file": os.path.basename(file), "sha256": sha,
            "windows": int(windows), "wstart": int(wstart),
            "fingerprint": fingerprint,
            "hosted": hosted_name, "hosted_sha256": hosted_sha,
        }
        _write_atomic(self.pointer_path(),
                      (json.dumps(pointer, sort_keys=True) + "\n")
                      .encode())
        _fsync_dir(os.path.dirname(os.path.abspath(self.base)))
        self._prune(protect=file)
        return file

    def pointer_path(self) -> str:
        return self.base + ".latest"

    def _prune(self, protect: str):
        import glob
        snaps = sorted(self.snapshots())
        for old in snaps[:-self.keep]:
            if old == protect:
                continue
            for suffix in ("", ".sha256", ".hosted"):
                try:
                    os.unlink(old + suffix)
                except OSError:
                    pass
        # stray temp files from killed saves never accumulate past one
        # resume cycle (the newest tmp may belong to a concurrent
        # writer only in misuse; one store has one writer)
        for tmp in glob.glob(glob.escape(self.base) + ".w*.tmp"):
            try:
                os.unlink(tmp)
            except OSError:
                pass

    # --- enumeration ---
    def snapshots(self) -> list:
        """All on-disk snapshot .npz paths for this base (any state)."""
        import glob
        return glob.glob(glob.escape(self.base) + ".w*.npz")


def _verify(path: str) -> bool:
    """Full verification of one snapshot set: the npz against its hash
    sidecar (absent sidecar = pre-hash snapshot, trusted like before),
    then — via the ``__hosted_sha__`` stamp INSIDE the verified npz —
    the hosted sidecar's presence and content. A hosted snapshot whose
    ``.hosted`` is missing or corrupt is unusable exactly like a torn
    npz: resolve_latest falls back to the previous snapshot instead of
    letting resume crash-loop on it."""
    sidecar = path + ".sha256"
    try:
        if os.path.exists(sidecar):
            with open(sidecar) as f:
                want = f.read().strip()
            if _sha256_file(path) != want:
                return False
    except OSError:
        return False
    try:
        with np.load(path) as z:
            hosted_sha = (bytes(z["__hosted_sha__"]).decode()
                          if "__hosted_sha__" in z else "")
    except Exception:
        return False        # unreadable/truncated npz, never usable
    if hosted_sha:
        try:
            with open(path + ".hosted", "rb") as f:
                got = hashlib.sha256(f.read()).hexdigest()
        except OSError:
            return False
        if got != hosted_sha:
            return False
    return True


def resolve_latest(path: str) -> str | None:
    """``--resume latest`` / supervisor resolution: newest snapshot of
    the store at `path` (a base, a base.npz, or a direct pointer file)
    whose content hash verifies. Returns the .npz path or None when
    the store holds no usable snapshot. A corrupt head is reported
    loudly and skipped — resume falls back to the previous snapshot."""
    base = base_of(path)
    candidates = []
    ptr = base + ".latest"
    if path.endswith(".latest"):
        ptr, base = path, path[:-len(".latest")]
    head = None
    if os.path.exists(ptr):
        try:
            with open(ptr) as f:
                meta = json.load(f)
            head = os.path.join(os.path.dirname(os.path.abspath(base)),
                                meta["file"])
        except (OSError, json.JSONDecodeError, KeyError):
            sys.stderr.write(
                f"shadow_tpu: warning: checkpoint pointer {ptr} is "
                "unreadable; scanning for snapshots instead\n")
    if head is not None:
        candidates.append(head)
    store = CheckpointStore(base)
    # dedup by absolute path: the pointer head is absolutized above,
    # snapshots() globs relative to the (possibly relative) base
    seen = {os.path.abspath(c) for c in candidates}
    for snap in sorted(store.snapshots(), reverse=True):
        if os.path.abspath(snap) not in seen:
            candidates.append(snap)
    for cand in candidates:
        if not os.path.exists(cand):
            sys.stderr.write(
                f"shadow_tpu: warning: checkpoint head {cand} is "
                "missing; falling back to an older snapshot\n")
            continue
        if not _verify(cand):
            sys.stderr.write(
                f"shadow_tpu: WARNING: checkpoint {cand} fails "
                "verification (content hash mismatch, torn npz, or "
                "a missing/corrupt .hosted sidecar) — falling back "
                "to the previous snapshot\n")
            continue
        return cand
    return None


def load(path: str, hosts_template, fingerprint: str,
         strict: bool = True) -> Snapshot:
    """Restore a snapshot -> Snapshot. `path` may be a concrete .npz,
    a store base (``ck`` / ``ck.npz`` — resolved through the
    ``latest`` pointer with corrupt-head fallback), or a ``.latest``
    pointer file. `hosts_template` supplies the pytree structure (a
    freshly built Hosts).

    Check order (hard to soft): content hash, array layout (ALWAYS a
    hard error, both shapes in the message), then the scenario
    fingerprint — which `strict=False` downgrades to a stderr warning,
    for tooling that deliberately resumes under a changed stop time or
    chunk size (e.g. tools/divergence.py --bisect replaying from the
    nearest checkpoint at digest cadence 1)."""
    file = path
    if not (os.path.isfile(path) and path.endswith(".npz")):
        file = resolve_latest(path)
        if file is None:
            raise FileNotFoundError(
                f"no usable checkpoint under {path!r} (no snapshot "
                "written yet, or every candidate failed verification)")
    elif not _verify(file):
        raise ValueError(
            f"checkpoint {file} fails verification — the npz is "
            "unreadable or truncated or fails its content hash, or "
            "its .hosted sidecar is missing or corrupt; resume from "
            "an older snapshot (pass the store base or 'latest' to "
            "fall back automatically)")
    import zlib
    try:
        with np.load(file) as z:
            got = bytes(z["__fingerprint__"]).decode()
            leaves, treedef = jax.tree.flatten(hosts_template)
            n = len(leaves)
            new_leaves = [jnp.asarray(z[f"leaf{i}"]) for i in range(n)]
            wstart = int(z["__wstart__"])
            wend = int(z["__wend__"])
            windows = int(z["__windows__"])
            fault_idx = (int(z["__fault_idx__"])
                         if "__fault_idx__" in z else -1)
            digest_records = (int(z["__digest_records__"])
                              if "__digest_records__" in z else -1)
            digest_chain = (bytes(z["__digest_chain__"]).decode()
                            if "__digest_chain__" in z else "") or None
    except (OSError, ValueError, KeyError, zipfile.BadZipFile,
            zlib.error) as e:
        raise ValueError(
            f"checkpoint {file} is unreadable or truncated "
            f"({type(e).__name__}: {e})") from e
    # layout FIRST: a shape/dtype mismatch means the snapshot belongs
    # to a different engine configuration — never resumable, whatever
    # the caller vouches for, so it must fail before the fingerprint
    # check can be softened past it
    for i, (tpl, new) in enumerate(zip(leaves, new_leaves)):
        if tpl.shape != new.shape or tpl.dtype != new.dtype:
            raise ValueError(
                f"checkpoint layout mismatch at leaf {i}: snapshot "
                f"has {new.shape}/{new.dtype}, this scenario builds "
                f"{tpl.shape}/{tpl.dtype} — the snapshot belongs to a "
                "different engine configuration")
    if got != fingerprint:
        if strict:
            raise ValueError(
                f"checkpoint fingerprint {got} does not match scenario "
                f"{fingerprint}: refusing to resume into a different "
                "simulation")
        sys.stderr.write(
            f"shadow_tpu: warning: resuming past a checkpoint "
            f"fingerprint mismatch ({got} vs {fingerprint}) — caller "
            "vouches the scenario only differs in run parameters\n")
    hosts = jax.tree.unflatten(treedef, new_leaves)
    hosted_blob = None
    hosted_path = file + ".hosted"
    if os.path.exists(hosted_path):
        with open(hosted_path, "rb") as f:
            hosted_blob = f.read()
    return Snapshot(hosts=hosts, wstart=wstart, wend=wend,
                    windows=windows,
                    fault_idx=fault_idx,
                    digest_records=digest_records,
                    digest_chain=digest_chain,
                    hosted_blob=hosted_blob,
                    path=file,
                    meta={"fingerprint": got})
