"""Obviously-correct pure-Python engine for differential testing.

The reference's core testing idea is the dual-run pattern: every test
binary runs natively AND under the simulator, and the results must
agree (SURVEY §4; src/test/CMakeLists.txt). The TPU analogue: the same
scenario runs under (a) the compiled array engine (engine.window) and
(b) this straightforward heap-based Python engine, and the stats must
be IDENTICAL bit for bit.

This engine intentionally mirrors the array engine's semantics —
per-host (time, seq) event order, NIC busy-horizon accounting,
outbox/exchange with per-window budgets and queue-reserve merging, the
counter-keyed loss rolls — but implements them with dicts, lists and a
loop, so each behavior is easy to audit. RNG-derived quantities go
through the same eager jax.random calls, making float rounding
identical.

Covered app tiers: the UDP tier (ping, pingserver, phold, gossip) AND
the TCP tier (bulk, bulkserver, tgen behavior graphs, socks
client/proxy chains — the at-scale flagship). The TCP machine
here is a per-socket-dict transliteration of net.tcp's masked kernels —
handshake, data, SACK scoreboard recovery, RTO go-back-N, congestion
control, FIN/TIME_WAIT — with all float32 congestion math and the SACK
range algebra delegated to the SAME jnp functions (net.congestion,
net.sack) called eagerly, so rounding and truncation match bit for bit.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import rng as R
from ..core.constants import (HEADER_SIZE_UDPIPETH, HEADER_SIZE_TCPIPETH,
                              MIN_RANDOM_PORT, MAX_PORT, UDP_MAX_PAYLOAD,
                              TCP_MSS, TCP_RTO_INIT, TCP_RTO_MIN, TCP_RTO_MAX,
                              TCP_CLOSE_TIMER_DELAY, SEND_BUFFER_SIZE,
                              RECV_BUFFER_SIZE, SEND_BUFFER_MIN_SIZE,
                              RECV_BUFFER_MIN_SIZE)
from ..core.simtime import (SIMTIME_MAX, SIMTIME_ONE_MICROSECOND,
                            SIMTIME_ONE_SECOND)
from ..net import congestion as CC
from ..net import packet as P
from ..net import sack
from ..net.socket import (TCPS_CLOSED, TCPS_LISTEN, TCPS_SYN_SENT,
                          TCPS_SYN_RECEIVED, TCPS_ESTABLISHED,
                          TCPS_FIN_WAIT_1, TCPS_FIN_WAIT_2, TCPS_CLOSE_WAIT,
                          TCPS_CLOSING, TCPS_LAST_ACK, TCPS_TIME_WAIT,
                          CTL_SYN, CTL_SYNACK, CTL_ACKNOW, CTL_FIN, CTL_RST)
from . import defs
from .defs import (EV_APP, EV_PKT, EV_NIC_TX, EV_TCP_TIMER, EV_TCP_CLOSE,
                   WAKE_START, WAKE_TIMER, WAKE_SOCKET, WAKE_CONNECTED,
                   WAKE_ACCEPT, WAKE_EOF, WAKE_SENT)
from ..apps.base import (APP_NULL, APP_PING, APP_PING_SERVER, APP_PHOLD,
                         APP_GOSSIP, APP_BULK, APP_BULK_SERVER, APP_TGEN,
                         APP_SOCKS_CLIENT, APP_SOCKS_PROXY)
from ..apps import tgen as TG

AUX_FINACK = 1          # net.tcp.AUX_FINACK
_I64MAX = np.iinfo(np.int64).max


def _i32(x):
    """int32 wrap, matching jnp astype(int32) on offsets/casts."""
    return int(np.int32(np.int64(x) & 0xFFFFFFFF))


def _new_sock():
    """One socket row with the engine's alloc-time defaults
    (net.socket.sock_alloc's setf list)."""
    return {
        "used": False, "proto": 0, "state": TCPS_CLOSED,
        "lport": 0, "rport": 0, "rhost": -1, "parent": -1,
        "snd_una": 0, "snd_nxt": 0, "snd_max": 0, "snd_end": 0,
        "rcv_nxt": 0,
        "ooo_s": np.full(sack.K, -1, np.int64),
        "ooo_e": np.full(sack.K, -1, np.int64),
        "sack_s": np.full(sack.K, -1, np.int64),
        "sack_e": np.full(sack.K, -1, np.int64),
        "hole_end": 0, "rex_nxt": 0, "peer_fin": -1,
        "fin_acked": False, "close_after": False,
        "cwnd": np.float32(0.0), "ssthresh": np.float32(0.0),
        "srtt": -1, "rtt_min": -1, "rttvar": 0,
        "rto": TCP_RTO_INIT, "rto_deadline": 0,
        "timer_on": False, "timer_gen": 0, "dupacks": 0,
        "rtt_seq": -1, "rtt_time": 0, "ctl": 0,
        "peer_rwnd": RECV_BUFFER_SIZE,
        "sndbuf": SEND_BUFFER_SIZE, "rcvbuf": RECV_BUFFER_SIZE,
        "hs_time": 0, "last_tx": 0, "syn_tag": 0, "app_ref": -1,
        "proc": 0,
        "cc_wmax": np.float32(0.0), "cc_epoch": -1,
        "cc_k": np.float32(0.0),
    }


class _Host:
    def __init__(self, hid, qcap, scap, txqcap, obcap, procs=1):
        self.hid = hid
        self.qcap = qcap
        self.events = {}      # slot -> (time, seq, kind, pkt)
        self.eq_ctr = 0
        self.rng_ctr = 0
        self.nic_busy = 0
        self.nic_sched = False
        self.nic_rr = 0
        self.nic_rx_until = 0
        self.txq = []
        self.txqcap = txqcap
        self.pkt_ctr = 0
        self.next_eport = MIN_RANDOM_PORT
        self.socks = [_new_sock() for _ in range(scap)]
        self.obcap = obcap
        self.outbox = []             # (send_time, pkt)
        self.ob_next = SIMTIME_MAX   # earliest carried arrival (mirror
        #                              of Hosts.ob_next)
        # per-process app registers (engine app_r [H, PP, 8]); app_r
        # aliases the CURRENT process's list during a dispatch
        self.app_rp = [[0] * 8 for _ in range(max(procs, 1))]
        self.app_r = self.app_rp[0]
        self.cur_proc = 0            # dispatch context (Hosts.app_proc)
        self.tgen_sync = None        # np per-host sync counters (tgen)
        self.free_slots = list(range(qcap))


class PyEngine:
    """Runs a built Simulation's scenario with plain-Python semantics.

    Usage: PyEngine(sim).run() -> stats ndarray comparable to
    sim.run().stats (build two Simulations; each is single-use).
    """

    def __init__(self, sim, count_passes=False):
        cfg = sim.cfg
        self.cfg = cfg
        # lockstep pass recount (obs.passcope differential): when on,
        # run() drains windows in the compiled engine's pass order and
        # tallies {rung label: passes} into self.pass_mix, comparable
        # to SimReport pass_acc / engine.window.pass_labels
        self.count_passes = count_passes
        self.pass_mix = {}
        H = cfg.num_hosts
        self.H = H
        self.hp_vertex = np.asarray(sim.hp.vertex)
        self.hp_bw_up = np.asarray(sim.hp.bw_up)
        self.hp_bw_down = np.asarray(sim.hp.bw_down)
        self.hp_app_kind = np.asarray(sim.hp.app_kind)
        self.hp_app_cfg = np.asarray(sim.hp.app_cfg)
        self.hp_nic_buf = np.asarray(sim.hp.nic_buf)
        self.hp_sndbuf0 = np.asarray(sim.hp.sndbuf0)
        self.hp_rcvbuf0 = np.asarray(sim.hp.rcvbuf0)
        self.lat = np.asarray(sim.sh.lat_ns)
        self.rel = np.asarray(sim.sh.rel)
        self.stop = int(sim.sh.stop_time)
        self.min_jump = int(sim.sh.min_jump)
        self.root = sim.sh.rng_root
        self.reserve = min(8, cfg.qcap // 4)
        self.qdisc = cfg.qdisc
        self.cc_kind = int(np.asarray(sim.sh.cc_kind))
        self.tcp_init_wnd = np.float32(np.asarray(sim.sh.tcp_init_wnd))
        self.tcp_ssthresh0 = np.float32(np.asarray(sim.sh.tcp_ssthresh0))
        # tgen shared tables (zeros when no tgen app)
        self.tg_nodes = np.asarray(sim.sh.tgen_nodes)
        self.tg_peers = np.asarray(sim.sh.tgen_peers)
        self.tg_pool = np.asarray(sim.sh.tgen_pool)
        self.tg_edges = np.asarray(sim.sh.tgen_edges)

        self.stats = np.zeros((H, defs.N_STATS), dtype=np.int64)
        # netscope mirror: always counted here (the python engine has
        # no shape cost); the differential test compares it against
        # the device histograms when cfg.netscope is on
        from ..obs import netscope as _NS
        self._ns = _NS
        self.ns_hist = np.zeros((H, _NS.NS_KINDS, _NS.NS_BUCKETS),
                                dtype=np.int64)
        self.hosts = [_Host(h, cfg.qcap, cfg.scap, cfg.txqcap, cfg.obcap,
                            procs=cfg.procs_per_host)
                      for h in range(H)]
        sync0 = np.asarray(sim.hosts.tgen_sync)
        for h in range(H):
            self.hosts[h].tgen_sync = sync0[h].copy()

        # initial events from the built Simulation state
        eq_time = np.asarray(sim.hosts.eq_time)
        eq_kind = np.asarray(sim.hosts.eq_kind)
        eq_seq = np.asarray(sim.hosts.eq_seq)
        eq_pkt = np.asarray(sim.hosts.eq_pkt)
        eq_ctr = np.asarray(sim.hosts.eq_ctr)
        for h in range(H):
            host = self.hosts[h]
            host.eq_ctr = int(eq_ctr[h])
            for s in range(cfg.qcap):
                if eq_time[h, s] != SIMTIME_MAX:
                    host.free_slots.remove(s)
                    host.events[s] = (int(eq_time[h, s]), int(eq_seq[h, s]),
                                      int(eq_kind[h, s]),
                                      eq_pkt[h, s].copy())

        self.seed32 = int(sim.seed) & 0xFFFFFFFF

    # --- RNG: exact Python-int mirror of core.rng's cheap PRNG ---
    @staticmethod
    def _mix32(x):
        M = 0xFFFFFFFF
        x &= M
        x ^= x >> 16
        x = (x * 0x85EBCA6B) & M
        x ^= x >> 13
        x = (x * 0xC2B2AE35) & M
        return x ^ (x >> 16)

    def _stream_of(self, domain, ident):
        M = 0xFFFFFFFF
        s = ((self.seed32 * 0x9E3779B9) ^ (domain * 0x85EBCA6B) ^
             ((ident & M) * 0xC2B2AE35)) & M
        return self._mix32(s)

    def _cheap_uniform(self, stream, counter):
        bits = self._mix32(stream ^ ((counter + 0x9E3779B9) & 0xFFFFFFFF))
        return np.float32(bits >> 8) * np.float32(1.0 / (1 << 24))

    def _draw(self, host):
        stream = self._stream_of(R.DOMAIN_HOST, host.hid)
        u = self._cheap_uniform(stream, host.rng_ctr)
        host.rng_ctr += 1
        return u  # np.float32, bit-identical to the device value

    # --- event queue (first-free-slot + (time, seq) order) ---
    def _q_push(self, host, t, kind, pkt):
        if not host.free_slots:
            self.stats[host.hid, defs.ST_EQ_FULL_LOCAL] += 1
            host.eq_ctr += 1
            return
        slot = min(host.free_slots)
        host.free_slots.remove(slot)
        host.events[slot] = (int(t), host.eq_ctr, kind, pkt)
        host.eq_ctr += 1

    def _q_pop_min(self, host):
        slot = min(host.events,
                   key=lambda s: (host.events[s][0], host.events[s][1]))
        ev = host.events.pop(slot)
        host.free_slots.append(slot)
        return ev

    def _next_time(self, host):
        if not host.events:
            return SIMTIME_MAX
        return min(t for t, _, _, _ in host.events.values())

    # --- socket table (net.socket mirror) ---
    def _sock_alloc(self, host, proto):
        """Mirror of sock_alloc: first free row, else recycle the
        longest-resident TIME_WAIT row. Returns (slot, ok)."""
        free = [i for i, s in enumerate(host.socks) if not s["used"]]
        tw = [i for i, s in enumerate(host.socks)
              if s["used"] and s["state"] == TCPS_TIME_WAIT]
        ok = bool(free) or bool(tw)
        if free:
            slot = free[0]
        elif tw:
            slot = min(tw, key=lambda i: (host.socks[i]["last_tx"], i))
        else:
            slot = 0  # argmin of all-int64max ranks
        if ok:
            gen = host.socks[slot]["timer_gen"] + 1
            host.socks[slot] = _new_sock()
            host.socks[slot]["used"] = True
            host.socks[slot]["proto"] = proto
            host.socks[slot]["timer_gen"] = gen
            host.socks[slot]["proc"] = host.cur_proc
        return slot, ok

    @staticmethod
    def _sock_free(host, slot):
        """Mirror of sock_free: clears flags only, bumps generation
        (other fields stay stale until the next alloc)."""
        sk = host.socks[slot]
        sk["used"] = False
        sk["proto"] = 0
        sk["state"] = TCPS_CLOSED
        sk["ctl"] = 0
        sk["rto_deadline"] = 0
        sk["timer_on"] = False
        sk["timer_gen"] += 1
        sk["app_ref"] = -1

    def _alloc_eport(self, host):
        span = MAX_PORT - MIN_RANDOM_PORT
        p = host.next_eport
        for _ in range(4):
            if any(s["used"] and s["lport"] == p for s in host.socks):
                p = MIN_RANDOM_PORT + (p + 1 - MIN_RANDOM_PORT) % span
        host.next_eport = MIN_RANDOM_PORT + (p + 1 - MIN_RANDOM_PORT) % span
        return p

    def _udp_open(self, host, port=None):
        slot, ok = self._sock_alloc(host, P.PROTO_UDP)
        eport = self._alloc_eport(host) if port is None else int(port)
        if ok:
            host.socks[slot]["lport"] = eport
        return slot if ok else -1

    def _demux(self, host, src, sport, dport, proto):
        """Mirror of sock_demux: exact 4-tuple first, then listening
        (TCP) / unconnected (UDP) fallback; lowest slot wins."""
        exact = fb = -1
        for i, s in enumerate(host.socks):
            if not s["used"] or s["proto"] != proto or s["lport"] != dport:
                continue
            if (s["rhost"] == src and s["rport"] == sport and exact < 0):
                exact = i
            if proto == P.PROTO_TCP:
                if s["state"] == TCPS_LISTEN and fb < 0:
                    fb = i
            elif s["rhost"] == -1 and fb < 0:
                fb = i
        return exact if exact >= 0 else fb

    # --- NIC (net.nic mirror) ---
    @staticmethod
    def _tx_dur(nbytes, bw):
        return (int(nbytes) * SIMTIME_ONE_SECOND) // max(int(bw), 1)

    @staticmethod
    def _wire_bytes(pkt):
        proto = int(pkt[P.FLAGS]) & P.PROTO_MASK
        hdr = (HEADER_SIZE_TCPIPETH if proto == P.PROTO_TCP
               else HEADER_SIZE_UDPIPETH)
        return int(pkt[P.LEN]) + hdr

    def _udp_sendto(self, host, now, slot, dst, dport, nbytes, aux=0):
        length = min(int(nbytes), UDP_MAX_PAYLOAD)
        pkt = np.zeros(P.PKT_WORDS, dtype=np.int32)
        pkt[P.SRC] = host.hid
        pkt[P.DST] = int(dst)
        pkt[P.SPORT] = host.socks[slot]["lport"]
        pkt[P.DPORT] = int(dport)
        pkt[P.FLAGS] = P.PROTO_UDP
        pkt[P.LEN] = length
        pkt[P.AUX] = np.int32(np.int64(aux) & 0xFFFFFFFF)
        host.socks[slot]["snd_end"] += length
        if len(host.txq) < host.txqcap:
            host.txq.append(pkt)
        else:
            self.stats[host.hid, defs.ST_TXQ_DROP] += 1
        self._kick(host, now)

    def _tcp_want_tx(self, sk):
        """Mirror of tcp_want_tx for one socket dict."""
        st = sk["state"]
        open_tx = st in (TCPS_ESTABLISHED, TCPS_CLOSE_WAIT)
        data_tx = st in (TCPS_ESTABLISHED, TCPS_CLOSE_WAIT, TCPS_FIN_WAIT_1,
                         TCPS_CLOSING, TCPS_LAST_ACK)
        cw = int(sk["cwnd"]) * TCP_MSS
        win = min(cw, max(sk["peer_rwnd"], 1))
        if data_tx and sk["hole_end"] > 0:
            # the eager sack calls only matter inside an open recovery
            # episode (hole_end > 0); rex_ok is False otherwise anyway
            rex_tgt = int(sack.skip(np.int64(sk["rex_nxt"]),
                                    jnp.asarray(sk["sack_s"]),
                                    jnp.asarray(sk["sack_e"])))
            lost_end = int(sack.lost_bound(jnp.asarray(sk["sack_s"]),
                                           jnp.asarray(sk["sack_e"]),
                                           np.int64(sk["snd_una"]),
                                           np.int64(sk["hole_end"])))
            rex_ok = rex_tgt < lost_end
        else:
            rex_ok = False
        data_ok = (data_tx and sk["snd_nxt"] < sk["snd_end"] and
                   sk["snd_nxt"] < sk["snd_una"] + win)
        fin_due = (open_tx and sk["close_after"] and
                   sk["snd_nxt"] == sk["snd_end"])
        return sk["proto"] == P.PROTO_TCP and (rex_ok or data_ok or fin_due)

    def _tx_want(self, host):
        """[S] mirror of nic.tx_want."""
        return [s["used"] and (s["ctl"] != 0 or self._tcp_want_tx(s))
                for s in host.socks]

    def _has_work(self, host):
        return bool(host.txq) or any(self._tx_want(host))

    def _kick(self, host, now):
        if self._has_work(host) and not host.nic_sched:
            ok = bool(host.free_slots)
            self._q_push(host, max(now, host.nic_busy), EV_NIC_TX,
                         np.zeros(P.PKT_WORDS, np.int32))
            host.nic_sched = ok

    def _on_tx(self, host, now, wend):
        host.nic_sched = False
        if len(host.outbox) >= host.obcap:
            ok = bool(host.free_slots)
            self._q_push(host, max(wend, now + 1), EV_NIC_TX,
                         np.zeros(P.PKT_WORDS, np.int32))
            host.nic_sched = ok
            return
        self._tx_pull(host, now)

    def _tx_pull(self, host, now):
        """Mirror of nic._tx_pull: ring first, else qdisc-selected TCP
        socket via tcp_pull; emit; bandwidth; reschedule. The socket
        want-scan (2 eager sack dispatches per TCP socket) runs only
        when the ring cannot supply the packet — the compiled engine
        computes it unconditionally but discards it, so skipping it here
        is behavior-identical and removes most of this hot path's cost."""
        S = len(host.socks)
        if host.txq:
            out_pkt, has_pkt = host.txq.pop(0), True
        else:
            want = self._tx_want(host)
            if any(want):
                if self.qdisc == 1:  # QDISC_RR
                    sock = min((((i - host.nic_rr) % S), i)
                               for i in range(S) if want[i])[1]
                else:                # FIFO: least recently served
                    sock = min((host.socks[i]["last_tx"] * S + i, i)
                               for i in range(S) if want[i])[1]
                out_pkt, has_pkt = self._tcp_pull(host, now, sock)
                if has_pkt:
                    host.nic_rr = (sock + 1) % S
            else:
                out_pkt, has_pkt = None, False

        busy_end = now
        if has_pkt and out_pkt is not None:
            wire = self._wire_bytes(out_pkt)
            busy_end = now + max(self._tx_dur(wire,
                                              self.hp_bw_up[host.hid]), 1)
            self._emit(host, now, out_pkt)
        elif has_pkt:
            # tcp_pull claimed has but produced nothing — cannot happen
            has_pkt = False
        host.nic_busy = busy_end
        if has_pkt and self._has_work(host):
            ok = bool(host.free_slots)
            self._q_push(host, busy_end, EV_NIC_TX,
                         np.zeros(P.PKT_WORDS, np.int32))
            host.nic_sched = ok

    def _emit(self, host, now, pkt):
        pkt = pkt.copy()
        pkt[P.UID] = host.pkt_ctr
        if int(pkt[P.DST]) == host.hid:
            self._q_push(host, now + 1, EV_PKT, pkt)  # loopback, 1ns
        else:
            if len(host.outbox) < host.obcap:
                host.outbox.append((now, pkt))
            else:
                self.stats[host.hid, defs.ST_OUTBOX_DROP] += 1
        self.stats[host.hid, defs.ST_PKTS_SENT] += 1
        host.pkt_ctr += 1

    def _ns_observe(self, hid, kind, value_us):
        """Host-side mirror of obs.netscope.observe (same bucketing)."""
        self.ns_hist[hid, kind, self._ns.bucket_of(value_us)] += 1

    def _on_pkt(self, host, now, pkt):
        wire = self._wire_bytes(pkt)
        bw = max(int(self.hp_bw_down[host.hid]), 1)
        backlog_ns = max(host.nic_rx_until - now, 0)
        backlog_bytes = (backlog_ns * bw) // SIMTIME_ONE_SECOND
        if backlog_bytes + wire > int(self.hp_nic_buf[host.hid]):
            self.stats[host.hid, defs.ST_PKTS_DROP_BUF] += 1
            return
        self._ns_observe(host.hid, self._ns.NS_QUEUE, backlog_ns // 1000)
        host.nic_rx_until = max(host.nic_rx_until, now) + \
            self._tx_dur(wire, bw)
        self.stats[host.hid, defs.ST_PKTS_RECV] += 1
        proto = int(pkt[P.FLAGS]) & P.PROTO_MASK
        if proto == P.PROTO_TCP:
            slot = self._demux(host, int(pkt[P.SRC]), int(pkt[P.SPORT]),
                               int(pkt[P.DPORT]), P.PROTO_TCP)
            if slot >= 0:
                self._tcp_rx(host, now, slot, pkt)
            return
        slot = self._demux(host, int(pkt[P.SRC]), int(pkt[P.SPORT]),
                           int(pkt[P.DPORT]), P.PROTO_UDP)
        if slot < 0:
            return
        host.socks[slot]["rcv_nxt"] += int(pkt[P.LEN])
        self.stats[host.hid, defs.ST_BYTES_RECV] += int(pkt[P.LEN])
        wake = pkt.copy()
        wake[P.SEQ] = slot
        wake[P.ACK] = WAKE_SOCKET
        wake[P.WND] = host.socks[slot]["timer_gen"]
        self._q_push(host, now + 1, EV_APP, wake)

    # --- TCP machine (net.tcp transliteration) -----------------------------
    # Each function mirrors its namesake in net/tcp.py statement by
    # statement; float32 congestion math and SACK range algebra call the
    # SAME jnp code eagerly so rounding/truncation are bit-identical.

    def _wake(self, host, now, reason, slot, pkt=None, ln=0, aux=0):
        w = (np.zeros(P.PKT_WORDS, np.int32) if pkt is None
             else pkt.copy())
        w[P.ACK] = reason
        w[P.SEQ] = slot
        w[P.LEN] = _i32(ln)
        w[P.AUX] = _i32(aux)
        w[P.WND] = host.socks[slot]["timer_gen"]
        self._q_push(host, now + 1, EV_APP, w)

    def _arm_timer(self, host, slot, now):
        sk = host.socks[slot]
        deadline = now + sk["rto"]
        sk["rto_deadline"] = deadline
        if not sk["timer_on"]:
            ok = bool(host.free_slots)
            ev = np.zeros(P.PKT_WORDS, np.int32)
            ev[P.SEQ] = slot
            ev[P.ACK] = sk["timer_gen"]
            self._q_push(host, deadline, EV_TCP_TIMER, ev)
            sk["timer_on"] = ok

    def _tcp_listen(self, host, port):
        slot, ok = self._sock_alloc(host, P.PROTO_TCP)
        if ok:
            host.socks[slot]["state"] = TCPS_LISTEN
            host.socks[slot]["lport"] = int(port)
        return slot, ok

    def _tcp_connect(self, host, now, dst_host, dst_port, tag=0):
        slot, ok = self._sock_alloc(host, P.PROTO_TCP)
        lport = self._alloc_eport(host)   # unconditional, like the engine
        if ok:
            sk = host.socks[slot]
            sk["state"] = TCPS_SYN_SENT
            sk["lport"] = lport
            sk["rport"] = int(dst_port)
            sk["rhost"] = int(dst_host)
            sk["ctl"] = CTL_SYN
            sk["cwnd"] = self.tcp_init_wnd
            sk["ssthresh"] = self.tcp_ssthresh0
            sk["hs_time"] = now
            sk["syn_tag"] = _i32(tag)
            self._arm_timer(host, slot, now)
            self._kick(host, now)
        else:
            self.stats[host.hid, defs.ST_SOCK_FAIL] += 1
        return slot, ok

    def _tcp_write(self, host, now, slot, nbytes):
        host.socks[slot]["snd_end"] += int(nbytes)
        self._kick(host, now)

    def _tcp_close_call(self, host, now, slot):
        sk = host.socks[slot]
        if sk["state"] in (TCPS_LISTEN, TCPS_CLOSED, TCPS_SYN_SENT,
                           TCPS_SYN_RECEIVED):
            self._sock_free(host, slot)
        else:
            sk["close_after"] = True
            self._kick(host, now)

    def _finack_aux(self, sk):
        pf = sk["peer_fin"]
        got_fin = pf >= 0 and sk["rcv_nxt"] >= pf
        aux = AUX_FINACK if got_fin else 0
        b1, b2 = sack.encode2(jnp.asarray(sk["ooo_s"]),
                              jnp.asarray(sk["ooo_e"]),
                              np.int64(sk["rcv_nxt"]))
        return aux | int(b1), int(b2)

    def _tcp_pull(self, host, now, slot):
        """Mirror of tcp_pull. Returns (pkt or None, has)."""
        sk = host.socks[slot]
        state = sk["state"]
        ctl = sk["ctl"]
        open_tx = state in (TCPS_ESTABLISHED, TCPS_CLOSE_WAIT)
        data_tx = state in (TCPS_ESTABLISHED, TCPS_CLOSE_WAIT,
                            TCPS_FIN_WAIT_1, TCPS_CLOSING, TCPS_LAST_ACK)

        snd_nxt = sk["snd_nxt"]
        snd_end = sk["snd_end"]
        cw = int(sk["cwnd"]) * TCP_MSS
        limit = sk["snd_una"] + min(cw, max(sk["peer_rwnd"], 1))
        hole_end = sk["hole_end"]
        sck_s = jnp.asarray(sk["sack_s"])
        sck_e = jnp.asarray(sk["sack_e"])
        rex_nxt = int(sack.skip(np.int64(sk["rex_nxt"]), sck_s, sck_e))
        lost_end = int(sack.lost_bound(sck_s, sck_e,
                                       np.int64(sk["snd_una"]),
                                       np.int64(hole_end)))
        rex_pending = data_tx and hole_end > 0 and rex_nxt < lost_end
        can_new = data_tx and snd_nxt < snd_end and snd_nxt < limit
        can_data = rex_pending or can_new

        fin_first = (open_tx and sk["close_after"] and snd_nxt == snd_end)
        fin_rexmit = (ctl & CTL_FIN) != 0 and state in (
            TCPS_FIN_WAIT_1, TCPS_CLOSING, TCPS_LAST_ACK)

        if ctl & CTL_RST:
            sel = 0
        elif ctl & CTL_SYN:
            sel = 1
        elif ctl & CTL_SYNACK:
            sel = 2
        elif can_data:
            sel = 3
        elif fin_first or fin_rexmit:
            sel = 4
        elif ctl & CTL_ACKNOW:
            sel = 5
        else:
            sel = -1
        has = sel >= 0

        ack_no = _i32(sk["rcv_nxt"])
        wnd = _i32(min(sk["rcvbuf"], 2**31 - 1))
        aux, sack2 = self._finack_aux(sk)
        if sel in (1, 2):
            # handshake bandwidth stamp (net.tcp.tcp_pull)
            aux = ((min(int(self.hp_bw_up[host.hid]) >> 10, 0xFFFF)
                    << 16) |
                   min(int(self.hp_bw_down[host.hid]) >> 10, 0xFFFF))

        rex_cap = min(lost_end,
                      int(sack.next_start_after(np.int64(rex_nxt),
                                                sck_s, sck_e)))
        if sel == 3:
            ln = (min(TCP_MSS, rex_cap - rex_nxt) if rex_pending
                  else min(TCP_MSS, min(snd_end, limit) - snd_nxt))
        else:
            ln = 0
        seq = (rex_nxt if rex_pending else snd_nxt) if sel == 3 \
            else (snd_end if sel == 4 else 0)
        flags = P.PROTO_TCP
        if sel in (1, 2):
            flags |= P.F_SYN
        if sel == 0:
            flags |= P.F_RST
        if sel == 4:
            flags |= P.F_FIN
        if sel == 2 or sel >= 3:
            flags |= P.F_ACK

        is_resend = sel == 3 and (rex_pending or snd_nxt < sk["snd_max"])
        pkt = np.zeros(P.PKT_WORDS, np.int32)
        pkt[P.SRC] = host.hid
        pkt[P.DST] = sk["rhost"]
        pkt[P.SPORT] = sk["lport"]
        pkt[P.DPORT] = sk["rport"]
        pkt[P.FLAGS] = flags
        pkt[P.SEQ] = _i32(seq)
        pkt[P.ACK] = ack_no
        pkt[P.WND] = wnd
        pkt[P.LEN] = _i32(ln)
        pkt[P.AUX] = _i32(aux)
        pkt[P.APP] = _i32(sk["syn_tag"] if sel == 1 else sack2)
        pkt[P.STATUS] = P.DS_CREATED | (P.DS_RETRANS if is_resend else 0)

        clr = {0: CTL_RST, 1: CTL_SYN, 2: CTL_SYNACK, 4: CTL_FIN}.get(sel, 0)
        if sel == 2 or sel >= 3:
            clr |= CTL_ACKNOW
        sk["ctl"] = ctl & ~clr
        sk["last_tx"] = now

        is_data = sel == 3
        is_rex = is_data and rex_pending
        snd_max = sk["snd_max"]
        new_nxt = snd_nxt + ln
        advance = is_data and not is_rex and new_nxt > snd_max
        gbn = is_data and not is_rex and snd_nxt < snd_max
        if advance:
            self.stats[host.hid, defs.ST_BYTES_SENT] += \
                new_nxt - max(snd_max, snd_nxt)
        if is_rex or gbn:
            self.stats[host.hid, defs.ST_RETRANSMIT] += 1
            self._ns_observe(host.hid, self._ns.NS_RETX,
                             sk["rto"] // 1000)
        time_it = is_data and not is_rex and not gbn and sk["rtt_seq"] < 0
        if is_data and not is_rex:
            sk["snd_nxt"] = new_nxt
        sk["rex_nxt"] = rex_nxt + (ln if is_rex else 0)
        if advance:
            sk["snd_max"] = new_nxt
        if time_it:
            sk["rtt_seq"] = new_nxt
            sk["rtt_time"] = now

        if sel == 4:
            if state == TCPS_ESTABLISHED:
                sk["state"] = TCPS_FIN_WAIT_1
            elif state == TCPS_CLOSE_WAIT:
                sk["state"] = TCPS_LAST_ACK

        if sel == 0:
            self._sock_free(host, slot)
        if sel in (1, 2) or is_data or sel == 4:
            self._arm_timer(host, slot, now)
        return (pkt if has else None), has

    def _autotune(self, host, slot, pkt):
        """Mirror of net.tcp._autotune: peer bandwidths from the
        handshake AUX stamp, RTT = 2x the SEQ latency stamp."""
        sk = host.socks[slot]
        peer = int(pkt[P.SRC])
        rtt_us = 2 * max(int(pkt[P.SEQ]), 0)
        peer_up = ((int(pkt[P.AUX]) >> 16) & 0xFFFF) << 10
        peer_dn = (int(pkt[P.AUX]) & 0xFFFF) << 10
        bw_cap = 1 << 38
        snd_bw = min(int(self.hp_bw_up[host.hid]), peer_dn, bw_cap)
        rcv_bw = min(int(self.hp_bw_down[host.hid]), peer_up, bw_cap)
        buf_cap = 1 << 30
        sndbuf_auto = min(max((snd_bw * rtt_us // 1_000_000) * 5 // 4,
                              SEND_BUFFER_MIN_SIZE), buf_cap)
        rcvbuf_auto = min(max((rcv_bw * rtt_us // 1_000_000) * 5 // 4,
                              RECV_BUFFER_MIN_SIZE), buf_cap)
        if peer == host.hid:
            sndbuf_auto = rcvbuf_auto = 16 * 1024 * 1024
        sb0 = int(self.hp_sndbuf0[host.hid])
        rb0 = int(self.hp_rcvbuf0[host.hid])
        sk["sndbuf"] = sb0 if sb0 >= 0 else sndbuf_auto
        sk["rcvbuf"] = rb0 if rb0 >= 0 else rcvbuf_auto

    @staticmethod
    def _rfc6298(srtt, rttvar, sample):
        first = srtt < 0
        srtt1 = sample if first else (7 * srtt + sample) // 8
        rttvar1 = (sample // 2 if first
                   else (3 * rttvar + abs(srtt - sample)) // 4)
        rto = min(max(srtt1 + max(4 * rttvar1, 1), TCP_RTO_MIN), TCP_RTO_MAX)
        return srtt1, rttvar1, rto

    def _accept_syn(self, host, now, lslot, pkt):
        child, ok = self._sock_alloc(host, P.PROTO_TCP)
        if not ok:
            self.stats[host.hid, defs.ST_SOCK_FAIL] += 1
            return
        sk = host.socks[child]
        sk["state"] = TCPS_SYN_RECEIVED
        sk["lport"] = int(pkt[P.DPORT])
        sk["rport"] = int(pkt[P.SPORT])
        sk["rhost"] = int(pkt[P.SRC])
        sk["parent"] = lslot
        sk["proc"] = host.socks[lslot]["proc"]   # inherit owner
        sk["ctl"] = CTL_SYNACK
        sk["cwnd"] = self.tcp_init_wnd
        sk["ssthresh"] = self.tcp_ssthresh0
        sk["peer_rwnd"] = max(int(pkt[P.WND]), 1)
        sk["hs_time"] = now
        sk["syn_tag"] = int(pkt[P.APP])
        self._autotune(host, child, pkt)
        self._arm_timer(host, child, now)

    def _rx_conn(self, host, now, slot, pkt):
        sk = host.socks[slot]
        flags = int(pkt[P.FLAGS])
        syn = (flags & P.F_SYN) != 0
        ackf = (flags & P.F_ACK) != 0
        fin = (flags & P.F_FIN) != 0
        seq = int(pkt[P.SEQ])
        ackno = int(pkt[P.ACK])
        ln = int(pkt[P.LEN])
        # AUX is the bw stamp on handshake segments: FINACK only on ~syn
        finack = (not syn) and (int(pkt[P.AUX]) & AUX_FINACK) != 0

        state0 = sk["state"]

        # --- A. establishment ---
        estA = state0 == TCPS_SYN_SENT and syn and ackf
        estB = state0 == TCPS_SYN_RECEIVED and ackf and not syn
        resyn = state0 == TCPS_SYN_RECEIVED and syn and not ackf
        resynack = state0 >= TCPS_ESTABLISHED and syn and ackf
        state1 = TCPS_ESTABLISHED if (estA or estB) else state0
        est = estA or estB

        sk["state"] = state1
        if estA:
            sk["ctl"] |= CTL_ACKNOW
        if resyn:
            sk["ctl"] |= CTL_SYNACK
        if resynack:
            sk["ctl"] |= CTL_ACKNOW
        if est:
            hs_rtt = now - sk["hs_time"]
            sk["srtt"], sk["rttvar"], sk["rto"] = self._rfc6298(
                sk["srtt"], sk["rttvar"], hs_rtt)
            sk["rtt_min"] = (min(sk["rtt_min"], hs_rtt)
                             if sk["rtt_min"] > 0 else hs_rtt)
            sk["rto_deadline"] = 0
            self._wake(host, now,
                       WAKE_CONNECTED if estA else WAKE_ACCEPT, slot,
                       pkt=pkt)

        # --- A2. buffer autotuning: active side on the SYN|ACK; the
        # passive side tuned at child creation (_accept_syn) ---
        if estA:
            self._autotune(host, slot, pkt)

        # --- B. ACK processing ---
        conn = state1 >= TCPS_ESTABLISHED
        valid_ack = ackf and conn
        snd_una0 = sk["snd_una"]
        snd_end = sk["snd_end"]
        new_ack = valid_ack and ackno > snd_una0
        acked_bytes = max(ackno - snd_una0, 0)
        npkts = (acked_bytes + TCP_MSS - 1) // TCP_MSS
        snd_una1 = ackno if new_ack else snd_una0

        snd_max0 = sk["snd_max"]
        upd = valid_ack and not syn
        b1s, b1e = sack.decode(np.int32(pkt[P.AUX]), np.int64(ackno),
                               np.int64(snd_max0))
        b2s, b2e = sack.decode(np.int32(pkt[P.APP]), np.int64(ackno),
                               np.int64(snd_max0))
        sb_s = jnp.asarray(sk["sack_s"])
        sb_e = jnp.asarray(sk["sack_e"])
        sb_s, sb_e = sack.insert(sb_s, sb_e,
                                 jnp.where(upd, b1s, -1),
                                 jnp.where(upd, b1e, -2))
        sb_s, sb_e = sack.insert(sb_s, sb_e,
                                 jnp.where(upd, b2s, -1),
                                 jnp.where(upd, b2e, -2))
        sb_s, sb_e = sack.drop_below(sb_s, sb_e, np.int64(snd_una1))
        sk["sack_s"] = np.asarray(sb_s)
        sk["sack_e"] = np.asarray(sb_e)

        rtt_seq = sk["rtt_seq"]
        sample_ok = new_ack and rtt_seq >= 0 and ackno >= rtt_seq
        dup = (valid_ack and ackno == snd_una0 and ln == 0 and not syn
               and not fin and sk["snd_nxt"] > snd_una0)
        dupacks1 = 0 if new_ack else sk["dupacks"] + (1 if dup else 0)
        fast_rx = dup and dupacks1 == 3

        cw0, ss0 = sk["cwnd"], sk["ssthresh"]
        wm0, ep0, k0 = sk["cc_wmax"], sk["cc_epoch"], sk["cc_k"]
        if new_ack:
            # delayMin for the rate cap (pre-this-sample, as on device)
            delay_ns = sk["rtt_min"] if sk["rtt_min"] > 0 else sk["srtt"]
            cw_a, ep_a, k_a = CC.on_ack(
                jnp.int32(self.cc_kind), jnp.float32(cw0), jnp.float32(ss0),
                jnp.float32(wm0), jnp.int64(ep0), jnp.float32(k0),
                jnp.int64(npkts), jnp.int64(now), jnp.int64(delay_ns))
            cw_a, ep_a, k_a = (np.float32(cw_a), int(ep_a), np.float32(k_a))
        if fast_rx:
            cw_l, ss_l, wm_l, ep_l = CC.on_loss(
                jnp.int32(self.cc_kind), jnp.float32(cw0), jnp.float32(ss0),
                jnp.float32(wm0))
            cw_l, ss_l, wm_l, ep_l = (np.float32(cw_l), np.float32(ss_l),
                                      np.float32(wm_l), int(ep_l))

        sk["snd_una"] = snd_una1
        sk["dupacks"] = dupacks1
        if valid_ack:
            sk["peer_rwnd"] = max(int(pkt[P.WND]), 1)
        if sample_ok:
            rtt_sample = max(now - sk["rtt_time"], 1)
            sk["srtt"], sk["rttvar"], sk["rto"] = self._rfc6298(
                sk["srtt"], sk["rttvar"], rtt_sample)
            sk["rtt_min"] = (min(sk["rtt_min"], rtt_sample)
                             if sk["rtt_min"] > 0 else rtt_sample)
            sk["rtt_seq"] = -1
        if fast_rx:
            sk["cwnd"], sk["ssthresh"] = cw_l, ss_l
            sk["cc_wmax"], sk["cc_epoch"] = wm_l, ep_l
            sk["hole_end"] = snd_max0
            sk["rex_nxt"] = ackno
        else:
            if new_ack:
                sk["cwnd"], sk["cc_epoch"], sk["cc_k"] = cw_a, ep_a, k_a
                if ackno >= sk["hole_end"]:
                    sk["hole_end"] = 0
                sk["rex_nxt"] = max(sk["rex_nxt"], ackno)

        # our FIN acked?
        fin_done = valid_ack and finack and ackno >= snd_end
        fin_acked1 = sk["fin_acked"] or fin_done
        state2 = state1
        if fin_acked1 and state1 == TCPS_FIN_WAIT_1:
            state2 = TCPS_FIN_WAIT_2
        elif fin_acked1 and state1 == TCPS_CLOSING:
            state2 = TCPS_TIME_WAIT
        elif fin_acked1 and state1 == TCPS_LAST_ACK:
            state2 = TCPS_CLOSED
        sk["fin_acked"] = fin_acked1
        sk["state"] = state2

        flight = (sk["snd_nxt"] > snd_una1 or
                  (state2 in (TCPS_FIN_WAIT_1, TCPS_CLOSING, TCPS_LAST_ACK)
                   and not fin_acked1))
        if valid_ack:
            sk["rto_deadline"] = (now + sk["rto"]) if flight else 0

        sent_all = new_ack and ackno >= snd_end and snd_end > 0
        if sent_all:
            self._wake(host, now, WAKE_SENT, slot, pkt=pkt)

        # --- C. data ---
        can_rx = state2 in (TCPS_ESTABLISHED, TCPS_FIN_WAIT_1,
                            TCPS_FIN_WAIT_2)
        has_data = ln > 0 and can_rx
        rcv0 = sk["rcv_nxt"]
        seg_end = seq + ln

        in_order = has_data and seq <= rcv0 and seg_end > rcv0
        adv = seg_end if in_order else rcv0
        oos, ooe, rcv1 = sack.consume(jnp.asarray(sk["ooo_s"]),
                                      jnp.asarray(sk["ooo_e"]),
                                      np.int64(adv))
        rcv1 = int(rcv1)
        is_ooo = has_data and seq > rcv1
        oos, ooe, reneged = sack.insert_counted(
            oos, ooe,
            np.int64(seq if is_ooo else -1),
            np.int64(seg_end if is_ooo else -2))
        sk["ooo_s"] = np.asarray(oos)
        sk["ooo_e"] = np.asarray(ooe)

        delivered = rcv1 - rcv0
        sk["rcv_nxt"] = rcv1
        if ln > 0 or fin:
            sk["ctl"] |= CTL_ACKNOW
        self.stats[host.hid, defs.ST_BYTES_RECV] += delivered
        self.stats[host.hid, defs.ST_SACK_RENEGE] += int(reneged)
        if delivered > 0:
            self._wake(host, now, WAKE_SOCKET, slot, pkt=pkt,
                       ln=delivered, aux=int(pkt[P.AUX]))

        # --- D. peer FIN ---
        fin_valid = fin and state2 >= TCPS_ESTABLISHED
        peer_fin1 = seq if (fin_valid and sk["peer_fin"] < 0) \
            else sk["peer_fin"]
        fin_complete = peer_fin1 >= 0 and rcv1 >= peer_fin1
        eof_now = fin_complete and state2 in (
            TCPS_ESTABLISHED, TCPS_FIN_WAIT_1, TCPS_FIN_WAIT_2)
        state3 = state2
        if eof_now and state2 == TCPS_ESTABLISHED:
            state3 = TCPS_CLOSE_WAIT
        elif eof_now and state2 == TCPS_FIN_WAIT_1:
            state3 = TCPS_TIME_WAIT if fin_acked1 else TCPS_CLOSING
        elif eof_now and state2 == TCPS_FIN_WAIT_2:
            state3 = TCPS_TIME_WAIT
        sk["peer_fin"] = peer_fin1
        sk["state"] = state3
        if eof_now:
            self._wake(host, now, WAKE_EOF, slot, pkt=pkt)

        # --- E. terminal bookkeeping ---
        if state3 == TCPS_TIME_WAIT and state0 != TCPS_TIME_WAIT:
            ev = np.zeros(P.PKT_WORDS, np.int32)
            ev[P.SEQ] = slot
            ev[P.ACK] = sk["timer_gen"]
            self._q_push(host, now + TCP_CLOSE_TIMER_DELAY,
                         EV_TCP_CLOSE, ev)
            sk["rto_deadline"] = 0
        if state3 == TCPS_CLOSED:
            self._sock_free(host, slot)

    def _tcp_rx(self, host, now, slot, pkt):
        flags = int(pkt[P.FLAGS])
        syn = (flags & P.F_SYN) != 0
        ackf = (flags & P.F_ACK) != 0
        rst = (flags & P.F_RST) != 0
        state = host.socks[slot]["state"]
        if rst:
            if state >= TCPS_ESTABLISHED:
                self._wake(host, now, WAKE_EOF, slot, pkt=pkt)
            self._sock_free(host, slot)
        elif state == TCPS_LISTEN and syn and not ackf:
            self._accept_syn(host, now, slot, pkt)
        else:
            self._rx_conn(host, now, slot, pkt)
        self._kick(host, now)

    def _on_tcp_timer(self, host, now, ev):
        slot = int(ev[P.SEQ])
        gen = int(ev[P.ACK])
        sk = host.socks[slot]
        if not (sk["used"] and gen == sk["timer_gen"] and
                sk["proto"] == P.PROTO_TCP):
            return
        deadline = sk["rto_deadline"]
        if deadline == 0:
            sk["timer_on"] = False
            return
        if now < deadline:
            ev2 = np.zeros(P.PKT_WORDS, np.int32)
            ev2[P.SEQ] = slot
            ev2[P.ACK] = gen
            self._q_push(host, deadline, EV_TCP_TIMER, ev2)
            return
        # expired: backoff, handshake/FIN control resends, go-back-N
        state = sk["state"]
        sk["rto"] = min(sk["rto"] * 2, TCP_RTO_MAX)
        if state == TCPS_SYN_SENT:
            sk["ctl"] |= CTL_SYN
        if state == TCPS_SYN_RECEIVED:
            sk["ctl"] |= CTL_SYNACK
        if state in (TCPS_FIN_WAIT_1, TCPS_CLOSING, TCPS_LAST_ACK) \
                and not sk["fin_acked"]:
            sk["ctl"] |= CTL_FIN
        had_flight = sk["snd_nxt"] > sk["snd_una"]
        if had_flight:
            cw_l, ss_l, wm_l, ep_l = CC.on_loss(
                jnp.int32(self.cc_kind), jnp.float32(sk["cwnd"]),
                jnp.float32(sk["ssthresh"]), jnp.float32(sk["cc_wmax"]))
            sk["cwnd"] = np.float32(cw_l)
            sk["ssthresh"] = np.float32(ss_l)
            sk["cc_wmax"] = np.float32(wm_l)
            sk["cc_epoch"] = int(ep_l)
            sk["snd_nxt"] = sk["snd_una"]
        sk["hole_end"] = 0
        sk["sack_s"] = np.full(sack.K, -1, np.int64)
        sk["sack_e"] = np.full(sack.K, -1, np.int64)
        sk["rtt_seq"] = -1
        sk["timer_on"] = False
        self._arm_timer(host, slot, now)
        self._kick(host, now)

    def _on_tcp_close(self, host, now, ev):
        slot = int(ev[P.SEQ])
        gen = int(ev[P.ACK])
        sk = host.socks[slot]
        if (sk["used"] and gen == sk["timer_gen"] and
                sk["state"] == TCPS_TIME_WAIT):
            self._sock_free(host, slot)

    # --- apps: UDP tier -----------------------------------------------------
    def _app(self, host, now, wake):
        # process routing mirror (engine.window._on_app): socket wakes
        # go to the socket's owner, slotless wakes to the SRC-stamped
        # process slot
        PP = len(host.app_rp)
        slot = int(wake[P.SEQ])
        if PP == 1:
            proc = 0
        else:
            proc = (self._rg(host, slot, "proc", 0) if slot >= 0
                    else int(wake[P.SRC]))
            proc = min(max(proc, 0), PP - 1)
        host.cur_proc = proc
        host.app_r = host.app_rp[proc]
        kind = int(self.hp_app_kind[host.hid, proc])
        if kind == APP_PING:
            self._app_ping(host, now, wake)
        elif kind == APP_PING_SERVER:
            self._app_ping_server(host, now, wake)
        elif kind == APP_PHOLD:
            self._app_phold(host, now, wake)
        elif kind == APP_GOSSIP:
            self._app_gossip(host, now, wake)
        elif kind == APP_BULK:
            self._app_bulk(host, now, wake)
        elif kind == APP_BULK_SERVER:
            self._app_bulk_server(host, now, wake)
        elif kind == APP_TGEN:
            self._app_tgen(host, now, wake)
        elif kind == APP_SOCKS_CLIENT:
            self._app_socks_client(host, now, wake)
        elif kind == APP_SOCKS_PROXY:
            self._app_socks_proxy(host, now, wake)
        host.cur_proc = 0                 # mirror app_proc reset
        host.app_r = host.app_rp[0]

    def _timer(self, host, t, aux=0):
        wake = np.zeros(P.PKT_WORDS, np.int32)
        wake[P.ACK] = WAKE_TIMER
        wake[P.SEQ] = -1
        wake[P.AUX] = np.int32(np.int64(aux) & 0xFFFFFFFF)
        wake[P.SRC] = host.cur_proc       # route back to this process
        self._q_push(host, t, EV_APP, wake)

    @staticmethod
    def _us31(t_ns):
        return (t_ns // SIMTIME_ONE_MICROSECOND) % (2**31)

    def _app_ping(self, host, now, wake):
        cfg = self.hp_app_cfg[host.hid, host.cur_proc]
        reason = min(max(int(wake[P.ACK]), 0), 2)
        if reason == WAKE_START:
            host.app_r[0] = self._udp_open(host)
            self._ping_send(host, now)
        elif reason == WAKE_TIMER:
            self._ping_send(host, now)
        else:  # echo
            rtt = (self._us31(now) - int(np.int64(wake[P.AUX]))) % (2**31)
            host.app_r[2] += 1
            self.stats[host.hid, defs.ST_RTT_SUM_US] += rtt
            self.stats[host.hid, defs.ST_RTT_COUNT] += 1
            self.stats[host.hid, defs.ST_XFER_DONE] += 1
            self._ns_observe(host.hid, self._ns.NS_RTT, rtt)
            self._ns_observe(host.hid, self._ns.NS_COMPLETION, rtt)
            limit = int(cfg[4])
            if limit > 0 and host.app_r[2] >= limit:
                self.stats[host.hid, defs.ST_APP_DONE] += 1

    def _ping_send(self, host, now):
        cfg = self.hp_app_cfg[host.hid, host.cur_proc]
        self._udp_sendto(host, now, host.app_r[0], cfg[0], cfg[1], cfg[3],
                         aux=self._us31(now))
        host.app_r[1] += 1
        limit = int(cfg[4])
        if limit == 0 or host.app_r[1] < limit:
            self._timer(host, now + int(cfg[2]))

    def _app_ping_server(self, host, now, wake):
        cfg = self.hp_app_cfg[host.hid, host.cur_proc]
        if int(wake[P.ACK]) == WAKE_START:
            host.app_r[0] = self._udp_open(host, port=int(cfg[1]))
        elif int(wake[P.ACK]) == WAKE_SOCKET:
            self._udp_sendto(host, now, int(wake[P.SEQ]),
                             int(wake[P.SRC]), int(wake[P.SPORT]),
                             int(wake[P.LEN]), aux=int(wake[P.AUX]))

    def _exp_delay(self, host):
        u = self._draw(host)
        mean = jnp.float32(float(
            self.hp_app_cfg[host.hid, host.cur_proc][2]))
        d = int(jnp.maximum((-mean * jnp.log1p(-u)).astype(jnp.int64), 1))
        return d

    def _app_phold(self, host, now, wake):
        cfg = self.hp_app_cfg[host.hid, host.cur_proc]
        reason = min(max(int(wake[P.ACK]), 0), 2)
        if reason == WAKE_START:
            host.app_r[0] = self._udp_open(host, port=int(cfg[1]))
            n0 = min(max(int(cfg[4]), 0), host.qcap)
            for _ in range(n0):
                self._timer(host, now + self._exp_delay(host))
        elif reason == WAKE_TIMER:
            u = self._draw(host)
            n = int(cfg[0])
            peer = int(jnp.minimum((u * n).astype(jnp.int64), n - 1))
            if peer == host.hid:
                peer = (peer + 1) % n
            self._udp_sendto(host, now, host.app_r[0], peer, cfg[1], cfg[3])
            host.app_r[1] += 1
        else:
            self._timer(host, now + self._exp_delay(host))

    def _relay_gossip(self, host, now, height):
        """Mirror of apps.gossip._relay: always MAX_FANOUT (8) draws,
        identical float32 peer math, sends only the first `fanout`."""
        cfg = self.hp_app_cfg[host.hid, host.cur_proc]
        n = max(int(cfg[0]), 2)
        k = min(max(int(cfg[2]), 0), 8)
        for j in range(8):
            u = self._draw(host)
            peer = int(jnp.minimum(
                (u * jnp.float32(n - 1)).astype(jnp.int64), n - 2))
            if peer >= host.hid:
                peer += 1
            if j < k:
                self._udp_sendto(host, now, host.app_r[0], peer,
                                 cfg[1], cfg[5], aux=height)

    def _app_gossip(self, host, now, wake):
        """Mirror of apps.gossip.app_gossip (block-gossip workload)."""
        cfg = self.hp_app_cfg[host.hid, host.cur_proc]
        reason = min(max(int(wake[P.ACK]), 0), 2)
        interval = int(cfg[3])
        if reason == WAKE_START:
            host.app_r[0] = self._udp_open(host, port=int(cfg[1]))
            host.app_r[5] = now
            if int(cfg[4]):
                self._timer(host, now + interval)
        elif reason == WAKE_TIMER:
            h = host.app_r[4] + 1
            host.app_r[4] = h
            host.app_r[1] = max(host.app_r[1], h)
            self._relay_gossip(host, now, h)
            self._timer(host, now + interval)
        else:
            h = int(np.int64(wake[P.AUX]))
            if h > host.app_r[1]:
                mined_at = host.app_r[5] + h * interval
                delay_us = max(now - mined_at, 0) // 1000
                host.app_r[1] = h
                host.app_r[2] += 1
                self.stats[host.hid, defs.ST_XFER_DONE] += 1
                self.stats[host.hid, defs.ST_RTT_SUM_US] += delay_us
                self.stats[host.hid, defs.ST_RTT_COUNT] += 1
                self._ns_observe(host.hid, self._ns.NS_RTT, delay_us)
                self._relay_gossip(host, now, h)

    # --- apps: TCP tier (apps.bulk / apps.tgen mirrors) ---------------------
    def _app_bulk(self, host, now, wake):
        cfg = self.hp_app_cfg[host.hid, host.cur_proc]
        reason = min(max(int(wake[P.ACK]), 0), 6)
        sock = _i32(host.app_r[0])
        if reason in (0, 1):        # start / timer -> (re)connect
            slot, _ok = self._tcp_connect(host, now, int(cfg[0]),
                                          int(cfg[1]))
            host.app_r[0] = slot
        elif reason == 3:           # connected
            self._tcp_write(host, now, sock, int(cfg[2]))
        elif reason == 6:           # sent: all bytes acked
            dur_us = max(now - self._rg(host, sock, "hs_time", 0), 0) \
                // 1000
            self._tcp_close_call(host, now, sock)
            host.app_r[1] += 1
            self.stats[host.hid, defs.ST_XFER_DONE] += 1
            self._ns_observe(host.hid, self._ns.NS_COMPLETION, dur_us)
            done = int(cfg[3]) > 0 and host.app_r[1] >= int(cfg[3])
            if done:
                self.stats[host.hid, defs.ST_APP_DONE] += 1
            else:
                self._timer(host, now + int(cfg[4]))

    def _app_bulk_server(self, host, now, wake):
        cfg = self.hp_app_cfg[host.hid, host.cur_proc]
        reason = min(max(int(wake[P.ACK]), 0), 6)
        slot = int(wake[P.SEQ])
        if reason == 0:
            lslot, _ok = self._tcp_listen(host, int(cfg[1]))
            host.app_r[0] = lslot
        elif reason == 5:           # accept: serve a GET-tagged SYN
            tag = self._rg(host, slot, "syn_tag", 0)
            fresh = int(wake[P.WND]) == self._rg(host, slot,
                                                 "timer_gen", 0)
            size = tag & ((1 << 30) - 1)
            if fresh and (tag & (1 << 30)) == 0 and size > 0:
                self._tcp_write(host, now, slot, size)
                self._tcp_close_call(host, now, slot)
        elif reason == 4:           # eof: inbound transfer done
            fresh = int(wake[P.WND]) == self._rg(host, slot,
                                                 "timer_gen", 0)
            tag = self._rg(host, slot, "syn_tag", 0)
            served_get = tag != 0 and (tag & (1 << 30)) == 0
            if fresh and not served_get:
                self._tcp_close_call(host, now, slot)
                self.stats[host.hid, defs.ST_XFER_DONE] += 1

    # --- socks proxy chains (apps.socks mirror) -----------------------------
    def _socks_rand_in(self, host, lo, hi, skip_self=False):
        """Mirror of apps.socks._rand_in: identical draw order and
        float32 index math."""
        u = self._draw(host)
        n = max(hi - lo, 1)
        if skip_self:
            in_pool = (lo <= host.hid < hi) and (n > 1)
            n_eff = n - (1 if in_pool else 0)
            idx = min(int(np.int64(u * np.float32(n_eff))), n_eff - 1)
            if in_pool and (lo + idx >= host.hid):
                idx += 1
            return lo + idx
        return lo + min(int(np.int64(u * np.float32(n))), n - 1)

    @staticmethod
    def _socks_pack_tag(target, size_u4k, hops=0):
        return (((hops & 0x3) << 29) | ((target & 0xFFFFF) << 9) |
                (size_u4k & 0x1FF))

    def _app_socks_client(self, host, now, wake):
        cfg = self.hp_app_cfg[host.hid, host.cur_proc]
        reason = min(max(int(wake[P.ACK]), 0), 6)
        slot = int(wake[P.SEQ])
        fresh = int(wake[P.WND]) == self._rg(host, slot, "timer_gen", 0)
        pause = int(cfg[7]) & ((1 << 56) - 1)
        hops = int(cfg[7]) >> 56

        if reason in (0, 1):            # start / timer -> fetch
            proxy = self._socks_rand_in(host, int(cfg[0]), int(cfg[1]))
            server = self._socks_rand_in(host, int(cfg[3]), int(cfg[4]))
            tag = self._socks_pack_tag(server, int(cfg[5]),
                                       max(hops - 1, 0))
            s, ok = self._tcp_connect(host, now, proxy, int(cfg[2]),
                                      tag=tag)
            host.app_r[0] = s
            host.app_r[2] = now
            if not ok:
                self._timer(host, now + pause)
        elif reason == 4:               # eof
            is_mine = fresh and slot == _i32(host.app_r[0])
            got_data = self._rg(host, slot, "rcv_nxt", 0) > 0
            if is_mine and got_data:
                delay_us = max(now - host.app_r[2], 0) // 1000
                self._tcp_close_call(host, now, slot)
                host.app_r[1] += 1
                self.stats[host.hid, defs.ST_XFER_DONE] += 1
                self.stats[host.hid, defs.ST_RTT_SUM_US] += delay_us
                self.stats[host.hid, defs.ST_RTT_COUNT] += 1
                self._ns_observe(host.hid, self._ns.NS_RTT, delay_us)
                self._ns_observe(host.hid, self._ns.NS_COMPLETION,
                                 delay_us)
                fin = int(cfg[6]) > 0 and host.app_r[1] >= int(cfg[6])
                if fin:
                    self.stats[host.hid, defs.ST_APP_DONE] += 1
                else:
                    self._timer(host, now + pause)
            elif is_mine:               # refused: zero bytes delivered
                self._tcp_close_call(host, now, slot)
                self._timer(host, now + pause)

    def _app_socks_proxy(self, host, now, wake):
        cfg = self.hp_app_cfg[host.hid, host.cur_proc]
        reason = min(max(int(wake[P.ACK]), 0), 6)
        slot = int(wake[P.SEQ])
        fresh = int(wake[P.WND]) == self._rg(host, slot, "timer_gen", 0)
        paired = self._rg(host, slot, "app_ref", 0)
        is_child = self._rg(host, slot, "parent", 0) >= 0

        if reason == 0:                 # start: listen
            lslot, ok = self._tcp_listen(host, int(cfg[1]))
            host.app_r[0] = (lslot + 1) if ok else 0
        elif reason == 5:               # accept: SOCKS CONNECT
            tag = self._rg(host, slot, "syn_tag", 0)
            hops = (tag >> 29) & 0x3
            target = (tag >> 9) & 0xFFFFF
            size = (tag & 0x1FF) << 12
            n_pool = int(cfg[4]) - int(cfg[3])
            self_in = int(cfg[3]) <= host.hid < int(cfg[4])
            has_pool = (n_pool > 1) or (n_pool == 1 and not self_in)
            extend = (hops > 0) and has_pool
            if (hops > 0) and not has_pool and fresh:
                self.stats[host.hid, defs.ST_CHAIN_SHORT] += 1
            if fresh:
                nxt = self._socks_rand_in(host, int(cfg[3]), int(cfg[4]),
                                          skip_self=True)
                dst = nxt if extend else target
                dport = int(cfg[1]) if extend else int(cfg[2])
                otag = (self._socks_pack_tag(target, tag & 0x1FF,
                                             hops - 1)
                        if extend else size)
                onward, ok = self._tcp_connect(host, now, dst, dport,
                                               tag=otag)
                if ok:
                    host.socks[onward]["app_ref"] = slot
                    host.socks[slot]["app_ref"] = onward
                else:
                    self._tcp_close_call(host, now, slot)
        elif reason == 2:               # data on the onward leg: relay
            relay = fresh and not is_child and paired >= 0
            ln = int(wake[P.LEN])
            if relay and ln > 0:
                self._tcp_write(host, now, paired, ln)
        elif reason == 4:               # eof: tear down the pair
            if fresh:
                if 0 <= slot < len(host.socks):
                    host.socks[slot]["app_ref"] = -1
                if 0 <= paired < len(host.socks):
                    host.socks[paired]["app_ref"] = -1
                self._tcp_close_call(host, now, slot)
                if paired >= 0:
                    self._tcp_close_call(host, now, paired)

    # --- tgen walk (apps.tgen mirror) ---------------------------------------
    def _rg(self, host, slot, key, default=0):
        """rget semantics: out-of-range slot reads as 0/False."""
        if 0 <= slot < len(host.socks):
            return host.socks[slot][key]
        return default

    def _tg_node(self, cur):
        return self.tg_nodes[min(max(int(cur), 0),
                                 self.tg_nodes.shape[0] - 1)]

    def _tg_exec_node(self, host, now, cur):
        """Mirror of tgen._exec_node. Returns proceed."""
        nd = self._tg_node(cur)
        kind = min(max(int(nd[TG.COL_KIND]), 0), 4)
        if kind == TG.NK_START:
            delay = int(nd[TG.COL_B])
            if delay > 0:
                self._timer(host, now + delay, aux=cur)
                return False
            return True
        if kind == TG.NK_TRANSFER:
            pcnt = max(int(nd[TG.COL_PCNT]), 1)
            u = self._draw(host)
            pick = int(nd[TG.COL_POFF]) + min(
                int(np.float32(u * np.float32(pcnt))), pcnt - 1)
            pick = min(max(pick, 0), self.tg_peers.shape[0] - 1)
            peer_host = int(self.tg_peers[pick, 0])
            peer_port = int(self.tg_peers[pick, 1])
            size = min(int(nd[TG.COL_B]), TG.TAG_SIZE_MASK)
            tag = size | (TG.TAG_PUT if int(nd[TG.COL_A]) == 1 else 0)
            slot, ok = self._tcp_connect(host, now, peer_host, peer_port,
                                         tag=tag)
            if ok:
                host.socks[slot]["app_ref"] = int(cur)
                self._tg_wd_arm(host, now, slot, 0, int(nd[TG.COL_C]),
                                int(nd[TG.COL_REF]))
            else:
                self._timer(host, now + SIMTIME_ONE_SECOND,
                            aux=-(int(cur) + 1))
            return False
        if kind == TG.NK_PAUSE:
            fixed = int(nd[TG.COL_A])
            if fixed < 0:
                u = self._draw(host)
                n = max(int(nd[TG.COL_C]), 1)
                at = int(nd[TG.COL_B]) + min(
                    int(np.float32(u * np.float32(n))), n - 1)
                t = int(self.tg_pool[min(max(at, 0),
                                         self.tg_pool.shape[0] - 1)])
            else:
                t = fixed
            if t > 0:
                self._timer(host, now + t, aux=cur)
                return False
            return True
        if kind == TG.NK_END:
            met = ((int(nd[TG.COL_A]) > 0 and
                    host.app_r[TG.REG_COUNT] >= int(nd[TG.COL_A])) or
                   (int(nd[TG.COL_B]) > 0 and
                    now - host.app_r[TG.REG_T0] >= int(nd[TG.COL_B])) or
                   (int(nd[TG.COL_C]) > 0 and
                    host.app_r[TG.REG_BYTES] >= int(nd[TG.COL_C])))
            if met:
                host.app_r[TG.REG_DONE] = 1
                self.stats[host.hid, defs.ST_APP_DONE] += 1
                return False
            return True
        # NK_SYNC
        ref = int(nd[TG.COL_REF])
        cnt = int(host.tgen_sync[ref]) + 1
        fire = cnt >= int(nd[TG.COL_A])
        host.tgen_sync[ref] = 0 if fire else cnt
        return fire

    def _tg_push_succs(self, host, stack, sp, cur):
        nd = self._tg_node(cur)
        eoff = int(nd[TG.COL_EOFF])
        ecnt = int(nd[TG.COL_ECNT])
        for j in range(ecnt):
            tgt = int(self.tg_edges[min(max(eoff + j, 0),
                                        self.tg_edges.shape[0] - 1)])
            if sp < TG.STACK_CAP:
                stack[sp] = tgt
                sp += 1
            else:
                self.stats[host.hid, defs.ST_TGEN_DROP] += 1
        return sp

    def _tg_walk(self, host, now, stack, sp):
        N = self.tg_nodes.shape[0]
        cap = 4 * N + 4 * TG.STACK_CAP
        it = 0
        while sp > 0 and it < cap:
            sp -= 1
            cur = stack[sp]
            if host.app_r[TG.REG_DONE] != 0:
                proceed = False
            else:
                proceed = self._tg_exec_node(host, now, cur)
            if proceed:
                sp = self._tg_push_succs(host, stack, sp, cur)
            it += 1
        self.stats[host.hid, defs.ST_TGEN_DROP] += sp

    def _tg_walk_enter(self, host, now, node):
        stack = [-1] * TG.STACK_CAP
        stack[0] = int(node)
        self._tg_walk(host, now, stack, 1)

    def _tg_walk_succ(self, host, now, node):
        stack = [-1] * TG.STACK_CAP
        sp = self._tg_push_succs(host, stack, 0, int(node))
        self._tg_walk(host, now, stack, sp)

    def _tg_wd_arm(self, host, now, slot, mark, timeout_ns, stallout_ns):
        sk = host.socks[slot]
        t_next = min(now + stallout_ns, sk["hs_time"] + timeout_ns)
        t_next = max(t_next, now + 1)
        w = np.zeros(P.PKT_WORDS, np.int32)
        w[P.ACK] = WAKE_TIMER
        w[P.SEQ] = slot
        w[P.AUX] = np.int32(TG.WD_AUX)
        w[P.WND] = sk["timer_gen"]
        w[P.LEN] = _i32(mark)
        self._q_push(host, t_next, EV_APP, w)

    def _tg_finish_transfer(self, host, now, sock):
        node = host.socks[sock]["app_ref"]
        nd = self._tg_node(node)
        dur_us = max(now - host.socks[sock]["hs_time"], 0) // 1000
        host.socks[sock]["app_ref"] = -1
        self._tcp_close_call(host, now, sock)
        host.app_r[TG.REG_COUNT] += 1
        host.app_r[TG.REG_BYTES] += int(nd[TG.COL_B])
        self.stats[host.hid, defs.ST_XFER_DONE] += 1
        self._ns_observe(host.hid, self._ns.NS_COMPLETION, dur_us)
        self._tg_walk_succ(host, now, node)

    def _app_tgen(self, host, now, wake):
        reason = min(max(int(wake[P.ACK]), 0), 6)
        slot = int(wake[P.SEQ])
        start_node = int(self.hp_app_cfg[host.hid, host.cur_proc][0])
        fresh = int(wake[P.WND]) == self._rg(host, slot, "timer_gen", 0)
        is_client = fresh and self._rg(host, slot, "app_ref", 0) >= 0

        if reason == 0:       # start
            nd = self._tg_node(start_node)
            if int(nd[TG.COL_A]) > 0:
                self._tcp_listen(host, int(nd[TG.COL_A]))
            host.app_r[TG.REG_T0] = now
            self._tg_walk_enter(host, now, start_node)
        elif reason == 1:     # timer (walk continuation or watchdog)
            aux = int(wake[P.AUX])
            if aux == TG.WD_AUX:
                node = self._rg(host, slot, "app_ref", 0)
                live = (fresh and node >= 0 and
                        self._rg(host, slot, "used", False))
                nd = self._tg_node(max(node, 0))
                metric = (self._rg(host, slot, "rcv_nxt", 0) +
                          self._rg(host, slot, "snd_una", 0))
                mark = int(wake[P.LEN])
                took = now >= (self._rg(host, slot, "hs_time", 0) +
                               int(nd[TG.COL_C]))
                stalled = metric == mark
                if live and (took or stalled):
                    host.socks[slot]["app_ref"] = -1
                    self.stats[host.hid, defs.ST_TGEN_ABORT] += 1
                    self._tcp_close_call(host, now, slot)
                    self._tg_walk_succ(host, now, node)
                elif live:
                    self._tg_wd_arm(host, now, slot, metric,
                                    int(nd[TG.COL_C]), int(nd[TG.COL_REF]))
            elif aux >= 0:
                self._tg_walk_succ(host, now, aux)
            else:
                self._tg_walk_enter(host, now, -aux - 1)
        elif reason == 3:     # connected
            tag = self._rg(host, slot, "syn_tag", 0)
            if (tag & TG.TAG_PUT) != 0 and is_client:
                self._tcp_write(host, now, slot, tag & TG.TAG_SIZE_MASK)
                self._tcp_close_call(host, now, slot)
        elif reason == 5:     # accept (server child established)
            tag = self._rg(host, slot, "syn_tag", 0)
            if fresh and (tag & TG.TAG_PUT) == 0:
                self._tcp_write(host, now, slot, tag & TG.TAG_SIZE_MASK)
                self._tcp_close_call(host, now, slot)
        elif reason == 4:     # eof
            if is_client:
                self._tg_finish_transfer(host, now, slot)
            else:
                is_put_child = (fresh and
                                self._rg(host, slot, "used", False) and
                                self._rg(host, slot, "parent", -1) >= 0 and
                                (self._rg(host, slot, "syn_tag", 0) &
                                 TG.TAG_PUT) != 0)
                if is_put_child:
                    self._tcp_close_call(host, now, slot)
                    self.stats[host.hid, defs.ST_XFER_DONE] += 1
        elif reason == 6:     # sent
            if is_client:
                self._tg_finish_transfer(host, now, slot)

    # --- exchange (identical math to engine.window.exchange) ---
    def _exchange(self):
        """Route/loss-roll/deliver this window's outboxes. Mirrors the
        round-3 deferral semantics: a destination takes at most
        min(incap, queue headroom) arrivals per window (headroom =
        free slots - reserve, floored at one arrival while at least
        two slots are free); the rest STAY in the source outbox with
        unchanged send
        times and re-exchange next window (ST_DEFER_FANIN). Returns
        the number of packets that departed an outbox (delivered or
        reliability-dropped) — the engines' shared progress signal."""
        all_pkts = []  # (global outbox order) host-major
        for host in self.hosts:
            for i, (stime, pkt) in enumerate(host.outbox):
                all_pkts.append([host.hid, i, stime, pkt, None, False])
        if not all_pkts:
            return 0
        delivered = {}  # dst -> list of entry refs, in source order
        departed = 0
        for ent in all_pkts:
            src, _i, stime, pkt = ent[0], ent[1], ent[2], ent[3]
            dst = min(max(int(pkt[P.DST]), 0), self.H - 1)
            sv, dv = self.hp_vertex[src], self.hp_vertex[dst]
            rel = np.float32(self.rel[sv, dv])
            lat = int(self.lat[sv, dv])
            arrival = stime + lat
            if int(pkt[P.FLAGS]) & P.F_SYN:
                # one-way latency stamp (engine.window.exchange)
                pkt = pkt.copy()
                pkt[P.SEQ] = _i32(lat // 1000)
                ent[3] = pkt
            ent[4] = arrival
            u = self._cheap_uniform(self._stream_of(R.DOMAIN_DROP, src),
                                    int(pkt[P.UID]))
            if rel > 0 and u <= rel:
                delivered.setdefault(dst, []).append(ent)
            else:
                self.stats[src, defs.ST_PKTS_DROP_NET] += 1
                ent[5] = True        # departed (lost on the wire)
                departed += 1
        for dst, lst in delivered.items():
            host = self.hosts[dst]
            nfree = len(host.free_slots)
            # progress floor admits one arrival only while a second
            # free slot remains for internal pushes (mirrors
            # engine.window._intake_take — THE intake policy)
            allow = min(self.cfg.incap,
                        max(nfree - self.reserve,
                            1 if nfree >= 2 else 0))
            for ent in lst[:allow]:
                slot = min(host.free_slots)
                host.free_slots.remove(slot)
                host.events[slot] = (ent[4], host.eq_ctr, EV_PKT,
                                     ent[3].copy())
                host.eq_ctr += 1
                ent[5] = True
                departed += 1
        # source-side carry: everything not departed stays, original
        # order; earliest carried arrival bounds the window advance
        for host in self.hosts:
            host.outbox = []
            host.ob_next = SIMTIME_MAX
        for ent in all_pkts:
            if not ent[5]:
                src = ent[0]
                host = self.hosts[src]
                host.outbox.append((ent[2], ent[3]))
                host.ob_next = min(host.ob_next, ent[4])
                self.stats[src, defs.ST_DEFER_FANIN] += 1
        return departed

    # --- lockstep pass recount (obs.passcope differential) ---
    def _exec_due(self, host, wend):
        """Execute the host's due minimum event plus the same-slot
        NIC-TX chain (engine.window._step_hot mirror). -> events run."""
        t, seq, kind, pkt = self._q_pop_min(host)
        self.stats[host.hid, defs.ST_EVENTS] += 1
        n = 1
        if kind == EV_APP:
            self._app(host, t, pkt)
        elif kind == EV_PKT:
            self._on_pkt(host, t, pkt)
        elif kind == EV_NIC_TX:
            self._on_tx(host, t, wend)
        elif kind == EV_TCP_TIMER:
            self._on_tcp_timer(host, t, pkt)
        elif kind == EV_TCP_CLOSE:
            self._on_tcp_close(host, t, pkt)
        if not self.cfg.cpu_model and host.events:
            slot = min(host.events, key=lambda s: (host.events[s][0],
                                                   host.events[s][1]))
            t2, _, k2, _ = host.events[slot]
            if t2 == t and k2 == EV_NIC_TX:
                self._q_pop_min(host)
                self.stats[host.hid, defs.ST_EVENTS] += 1
                self._on_tx(host, t, wend)
                n += 1
        return n

    def _drain_lockstep(self, wend):
        """Drain one window in the compiled engine's lockstep pass
        order, counting passes per rung label into self.pass_mix.

        Mirror of engine.window._drain_hot/_pass_hot: the same
        searchsorted rung selection over the same ladders, the same
        per-pass event budget (sparse_batch events per gathered host on
        sparse rungs, one per ready host on dense), the same fixed
        active set inside a window rung with inner passes tallied into
        the w slot. State-identical to the plain per-host drain — hosts
        only interact at the exchange, and per-host event order is
        unchanged — but the pass counts line up with the device
        pass_acc so occupancy math can be recounted independently.
        -> events executed."""
        import bisect
        from .window import ladder_of, sparse_batch, window_ladder
        cfg = self.cfg
        wks = window_ladder(cfg, self.H)
        ks = ladder_of(cfg, self.H)
        B = sparse_batch(cfg)
        nexec = 0

        def run_pass(ready, batch):
            n = 0
            for host in ready:
                for _ in range(batch):
                    if self._next_time(host) >= wend:
                        break
                    n += self._exec_due(host, wend)
            return n

        active = [h for h in self.hosts if self._next_time(h) < wend]
        widx = bisect.bisect_left(wks, len(active))
        if wks and active and widx < len(wks):
            # window rung: the K-sub is gathered once at window open
            # (hosts idle at open stay out the whole window); each
            # inner pass reselects its own sub-ladder rung
            sub_ks = ladder_of(cfg, wks[widx])
            lbl = "w%d" % wks[widx]
            while True:
                ready = [h for h in active if self._next_time(h) < wend]
                if not ready:
                    break
                self.pass_mix[lbl] = self.pass_mix.get(lbl, 0) + 1
                r = bisect.bisect_left(sub_ks, len(ready))
                nexec += run_pass(ready, B if r < len(sub_ks) else 1)
        else:
            while True:
                ready = [h for h in self.hosts
                         if self._next_time(h) < wend]
                if not ready:
                    break
                if wks:
                    # overflow past the window ladder runs plain dense
                    lbl, batch = "dense", 1
                else:
                    r = bisect.bisect_left(ks, len(ready))
                    sparse = r < len(ks)
                    lbl = "k%d" % ks[r] if sparse else "dense"
                    batch = B if sparse else 1
                self.pass_mix[lbl] = self.pass_mix.get(lbl, 0) + 1
                nexec += run_pass(ready, batch)
        return nexec

    # --- main loop ---
    def run(self):
        from ..obs import metrics as MT
        from ..obs import trace as TR
        nt = min(self._next_time(h) for h in self.hosts)
        windows = 0
        ev0 = int(self.stats[:, defs.ST_EVENTS].sum())
        while nt < self.stop and nt < SIMTIME_MAX:
            if TR.ENABLED:
                _w0 = TR.TRACER.now()
                _ws = int(nt)
            wend = min(nt + self.min_jump, self.stop)
            executed = False
            nexec = 0
            if self.count_passes:
                nexec = self._drain_lockstep(wend)
                executed = nexec > 0
            else:
                progressed = True
                while progressed:
                    progressed = False
                    for host in self.hosts:
                        while (host.events
                               and self._next_time(host) < wend):
                            t, seq, kind, pkt = self._q_pop_min(host)
                            self.stats[host.hid, defs.ST_EVENTS] += 1
                            nexec += 1
                            if kind == EV_APP:
                                self._app(host, t, pkt)
                            elif kind == EV_PKT:
                                self._on_pkt(host, t, pkt)
                            elif kind == EV_NIC_TX:
                                self._on_tx(host, t, wend)
                            elif kind == EV_TCP_TIMER:
                                self._on_tcp_timer(host, t, pkt)
                            elif kind == EV_TCP_CLOSE:
                                self._on_tcp_close(host, t, pkt)
                            progressed = True
                            executed = True
            shipped = self._exchange()
            windows += 1
            if TR.ENABLED:
                # the oracle's window loop on the same timeline as the
                # compiled engine's chunks: span per window (the
                # tracer's MAX_EVENTS cap bounds long runs)
                TR.TRACER.complete(
                    "pyengine.window", _w0,
                    args={"sim_ns_start": _ws, "sim_ns_end": int(wend),
                          "events": nexec, "shipped": shipped})
            nt_eq = min(self._next_time(h) for h in self.hosts)
            if executed or shipped:
                # window-advance bound includes carried arrivals
                nt = min(nt_eq, min(h.ob_next for h in self.hosts))
            else:
                # anti-livelock (engine.window.win_body): advance to
                # the earliest queue event so jammed queues drain
                nt = nt_eq
        self.windows = windows
        if MT.ENABLED:
            reg = MT.REGISTRY
            reg.counter("pyengine.windows").inc(windows)
            reg.counter("pyengine.events").inc(
                int(self.stats[:, defs.ST_EVENTS].sum()) - ev0)
        return self.stats
