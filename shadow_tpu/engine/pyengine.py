"""Obviously-correct pure-Python engine for differential testing.

The reference's core testing idea is the dual-run pattern: every test
binary runs natively AND under the simulator, and the results must
agree (SURVEY §4; src/test/CMakeLists.txt). The TPU analogue: the same
scenario runs under (a) the compiled array engine (engine.window) and
(b) this straightforward heap-based Python engine, and the stats must
be IDENTICAL bit for bit.

This engine intentionally mirrors the array engine's semantics —
per-host (time, seq) event order, NIC busy-horizon accounting,
outbox/exchange with per-window budgets and queue-reserve merging, the
counter-keyed loss rolls — but implements them with dicts, lists and a
loop, so each behavior is easy to audit. RNG-derived quantities go
through the same eager jax.random calls, making float rounding
identical.

Supported app kinds: the UDP tier (ping, pingserver, phold). TCP
scenarios exercise vastly more state; the differential harness covers
the engine substrate (queues, NIC, exchange, loss, RNG, windows) which
TCP runs on top of.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import rng as R
from ..core.constants import (HEADER_SIZE_UDPIPETH, MIN_RANDOM_PORT,
                              MAX_PORT, UDP_MAX_PAYLOAD)
from ..core.simtime import SIMTIME_MAX, SIMTIME_ONE_MICROSECOND, SIMTIME_ONE_SECOND
from ..net import packet as P
from . import defs
from .defs import (EV_APP, EV_PKT, EV_NIC_TX, WAKE_START, WAKE_TIMER,
                   WAKE_SOCKET)
from ..apps.base import (APP_NULL, APP_PING, APP_PING_SERVER, APP_PHOLD,
                         APP_GOSSIP)


class _Host:
    def __init__(self, hid, qcap, scap, txqcap, obcap):
        self.hid = hid
        self.qcap = qcap
        self.events = {}      # slot -> (time, seq, kind, pkt)
        self.eq_ctr = 0
        self.rng_ctr = 0
        self.nic_busy = 0
        self.nic_sched = False
        self.nic_rx_until = 0
        self.txq = []
        self.txqcap = txqcap
        self.pkt_ctr = 0
        self.next_eport = MIN_RANDOM_PORT
        self.socks = [None] * scap   # None or dict(proto, lport, rhost, rport)
        self.obcap = obcap
        self.outbox = []             # (send_time, pkt)
        self.app_r = [0] * 8
        self.free_slots = list(range(qcap))


class PyEngine:
    """Runs a built Simulation's scenario with plain-Python semantics.

    Usage: PyEngine(sim).run() -> stats ndarray comparable to
    sim.run().stats (build two Simulations; each is single-use).
    """

    def __init__(self, sim):
        cfg = sim.cfg
        self.cfg = cfg
        H = cfg.num_hosts
        self.H = H
        self.hp_vertex = np.asarray(sim.hp.vertex)
        self.hp_bw_up = np.asarray(sim.hp.bw_up)
        self.hp_bw_down = np.asarray(sim.hp.bw_down)
        self.hp_app_kind = np.asarray(sim.hp.app_kind)
        self.hp_app_cfg = np.asarray(sim.hp.app_cfg)
        self.hp_nic_buf = np.asarray(sim.hp.nic_buf)
        self.lat = np.asarray(sim.sh.lat_ns)
        self.rel = np.asarray(sim.sh.rel)
        self.stop = int(sim.sh.stop_time)
        self.min_jump = int(sim.sh.min_jump)
        self.root = sim.sh.rng_root
        self.reserve = min(8, cfg.qcap // 4)

        self.stats = np.zeros((H, defs.N_STATS), dtype=np.int64)
        self.hosts = [_Host(h, cfg.qcap, cfg.scap, cfg.txqcap, cfg.obcap)
                      for h in range(H)]

        # initial events from the built Simulation state
        eq_time = np.asarray(sim.hosts.eq_time)
        eq_kind = np.asarray(sim.hosts.eq_kind)
        eq_seq = np.asarray(sim.hosts.eq_seq)
        eq_pkt = np.asarray(sim.hosts.eq_pkt)
        eq_ctr = np.asarray(sim.hosts.eq_ctr)
        for h in range(H):
            host = self.hosts[h]
            host.eq_ctr = int(eq_ctr[h])
            for s in range(cfg.qcap):
                if eq_time[h, s] != SIMTIME_MAX:
                    host.free_slots.remove(s)
                    host.events[s] = (int(eq_time[h, s]), int(eq_seq[h, s]),
                                      int(eq_kind[h, s]),
                                      eq_pkt[h, s].copy())

        self.seed32 = int(sim.seed) & 0xFFFFFFFF

    # --- RNG: exact Python-int mirror of core.rng's cheap PRNG ---
    @staticmethod
    def _mix32(x):
        M = 0xFFFFFFFF
        x &= M
        x ^= x >> 16
        x = (x * 0x85EBCA6B) & M
        x ^= x >> 13
        x = (x * 0xC2B2AE35) & M
        return x ^ (x >> 16)

    def _stream_of(self, domain, ident):
        M = 0xFFFFFFFF
        s = ((self.seed32 * 0x9E3779B9) ^ (domain * 0x85EBCA6B) ^
             ((ident & M) * 0xC2B2AE35)) & M
        return self._mix32(s)

    def _cheap_uniform(self, stream, counter):
        bits = self._mix32(stream ^ ((counter + 0x9E3779B9) & 0xFFFFFFFF))
        return np.float32(bits >> 8) * np.float32(1.0 / (1 << 24))

    def _draw(self, host):
        stream = self._stream_of(R.DOMAIN_HOST, host.hid)
        u = self._cheap_uniform(stream, host.rng_ctr)
        host.rng_ctr += 1
        return u  # np.float32, bit-identical to the device value

    # --- event queue (first-free-slot + (time, seq) order) ---
    def _q_push(self, host, t, kind, pkt):
        if not host.free_slots:
            self.stats[host.hid, defs.ST_EQ_FULL_LOCAL] += 1
            host.eq_ctr += 1
            return
        slot = min(host.free_slots)
        host.free_slots.remove(slot)
        host.events[slot] = (int(t), host.eq_ctr, kind, pkt)
        host.eq_ctr += 1

    def _q_pop_min(self, host):
        slot = min(host.events,
                   key=lambda s: (host.events[s][0], host.events[s][1]))
        ev = host.events.pop(slot)
        host.free_slots.append(slot)
        return ev

    def _next_time(self, host):
        if not host.events:
            return SIMTIME_MAX
        return min(t for t, _, _, _ in host.events.values())

    # --- sockets (UDP only) ---
    def _sock_alloc(self, host, proto):
        for i, s in enumerate(host.socks):
            if s is None:
                host.socks[i] = {"proto": proto, "lport": 0,
                                 "rhost": -1, "rport": 0}
                return i
        self.stats[host.hid, defs.ST_SOCK_FAIL] += 1
        return -1

    def _alloc_eport(self, host):
        span = MAX_PORT - MIN_RANDOM_PORT
        p = host.next_eport
        for _ in range(4):
            if any(s and s["lport"] == p for s in host.socks):
                p = MIN_RANDOM_PORT + (p + 1 - MIN_RANDOM_PORT) % span
        host.next_eport = MIN_RANDOM_PORT + (p + 1 - MIN_RANDOM_PORT) % span
        return p

    def _udp_open(self, host, port=None):
        slot = self._sock_alloc(host, P.PROTO_UDP)
        if slot < 0:
            return slot
        host.socks[slot]["lport"] = (self._alloc_eport(host)
                                     if port is None else int(port))
        return slot

    def _demux(self, host, src, sport, dport):
        exact = fb = -1
        for i, s in enumerate(host.socks):
            if not s or s["proto"] != P.PROTO_UDP or s["lport"] != dport:
                continue
            if s["rhost"] == src and s["rport"] == sport and exact < 0:
                exact = i
            if s["rhost"] == -1 and fb < 0:
                fb = i
        return exact if exact >= 0 else fb

    # --- NIC ---
    @staticmethod
    def _tx_dur(nbytes, bw):
        return (int(nbytes) * SIMTIME_ONE_SECOND) // max(int(bw), 1)

    def _udp_sendto(self, host, now, slot, dst, dport, nbytes, aux=0):
        length = min(int(nbytes), UDP_MAX_PAYLOAD)
        pkt = np.zeros(P.PKT_WORDS, dtype=np.int32)
        pkt[P.SRC] = host.hid
        pkt[P.DST] = int(dst)
        pkt[P.SPORT] = host.socks[slot]["lport"]
        pkt[P.DPORT] = int(dport)
        pkt[P.FLAGS] = P.PROTO_UDP
        pkt[P.LEN] = length
        pkt[P.AUX] = np.int32(np.int64(aux) & 0xFFFFFFFF)
        if len(host.txq) < host.txqcap:
            host.txq.append(pkt)
        else:
            self.stats[host.hid, defs.ST_TXQ_DROP] += 1
        self._kick(host, now)

    def _kick(self, host, now):
        if host.txq and not host.nic_sched:
            ok = bool(host.free_slots)
            self._q_push(host, max(now, host.nic_busy), EV_NIC_TX,
                         np.zeros(P.PKT_WORDS, np.int32))
            host.nic_sched = ok

    def _on_tx(self, host, now, wend):
        host.nic_sched = False
        if len(host.outbox) >= host.obcap:
            ok = bool(host.free_slots)
            self._q_push(host, max(wend, now + 1), EV_NIC_TX,
                         np.zeros(P.PKT_WORDS, np.int32))
            host.nic_sched = ok
            return
        has = bool(host.txq)
        busy_end = now
        if has:
            pkt = host.txq.pop(0)
            wire = int(pkt[P.LEN]) + HEADER_SIZE_UDPIPETH
            busy_end = now + max(self._tx_dur(wire,
                                              self.hp_bw_up[host.hid]), 1)
            self._emit(host, now, pkt)
        host.nic_busy = busy_end
        if host.txq and has:
            ok = bool(host.free_slots)
            self._q_push(host, busy_end, EV_NIC_TX,
                         np.zeros(P.PKT_WORDS, np.int32))
            host.nic_sched = ok

    def _emit(self, host, now, pkt):
        pkt = pkt.copy()
        pkt[P.UID] = host.pkt_ctr
        if int(pkt[P.DST]) == host.hid:
            self._q_push(host, now + 1, EV_PKT, pkt)  # loopback, 1ns
        else:
            if len(host.outbox) < host.obcap:
                host.outbox.append((now, pkt))
            else:
                self.stats[host.hid, defs.ST_OUTBOX_DROP] += 1
        self.stats[host.hid, defs.ST_PKTS_SENT] += 1
        host.pkt_ctr += 1

    def _on_pkt(self, host, now, pkt):
        wire = int(pkt[P.LEN]) + HEADER_SIZE_UDPIPETH
        bw = max(int(self.hp_bw_down[host.hid]), 1)
        backlog_ns = max(host.nic_rx_until - now, 0)
        backlog_bytes = (backlog_ns * bw) // SIMTIME_ONE_SECOND
        if backlog_bytes + wire > int(self.hp_nic_buf[host.hid]):
            self.stats[host.hid, defs.ST_PKTS_DROP_BUF] += 1
            return
        host.nic_rx_until = max(host.nic_rx_until, now) + \
            self._tx_dur(wire, bw)
        self.stats[host.hid, defs.ST_PKTS_RECV] += 1
        slot = self._demux(host, int(pkt[P.SRC]), int(pkt[P.SPORT]),
                           int(pkt[P.DPORT]))
        if slot < 0:
            return
        self.stats[host.hid, defs.ST_BYTES_RECV] += int(pkt[P.LEN])
        wake = pkt.copy()
        wake[P.SEQ] = slot
        wake[P.ACK] = WAKE_SOCKET
        self._q_push(host, now + 1, EV_APP, wake)

    # --- apps (UDP tier) ---
    def _app(self, host, now, wake):
        kind = int(self.hp_app_kind[host.hid])
        if kind == APP_PING:
            self._app_ping(host, now, wake)
        elif kind == APP_PING_SERVER:
            self._app_ping_server(host, now, wake)
        elif kind == APP_PHOLD:
            self._app_phold(host, now, wake)
        elif kind == APP_GOSSIP:
            self._app_gossip(host, now, wake)

    def _timer(self, host, t, aux=0):
        wake = np.zeros(P.PKT_WORDS, np.int32)
        wake[P.ACK] = WAKE_TIMER
        wake[P.SEQ] = -1
        wake[P.AUX] = np.int32(np.int64(aux) & 0xFFFFFFFF)
        self._q_push(host, t, EV_APP, wake)

    @staticmethod
    def _us31(t_ns):
        return (t_ns // SIMTIME_ONE_MICROSECOND) % (2**31)

    def _app_ping(self, host, now, wake):
        cfg = self.hp_app_cfg[host.hid]
        reason = min(max(int(wake[P.ACK]), 0), 2)
        if reason == WAKE_START:
            host.app_r[0] = self._udp_open(host)
            self._ping_send(host, now)
        elif reason == WAKE_TIMER:
            self._ping_send(host, now)
        else:  # echo
            rtt = (self._us31(now) - int(np.int64(wake[P.AUX]))) % (2**31)
            host.app_r[2] += 1
            self.stats[host.hid, defs.ST_RTT_SUM_US] += rtt
            self.stats[host.hid, defs.ST_RTT_COUNT] += 1
            self.stats[host.hid, defs.ST_XFER_DONE] += 1
            limit = int(cfg[4])
            if limit > 0 and host.app_r[2] >= limit:
                self.stats[host.hid, defs.ST_APP_DONE] += 1

    def _ping_send(self, host, now):
        cfg = self.hp_app_cfg[host.hid]
        self._udp_sendto(host, now, host.app_r[0], cfg[0], cfg[1], cfg[3],
                         aux=self._us31(now))
        host.app_r[1] += 1
        limit = int(cfg[4])
        if limit == 0 or host.app_r[1] < limit:
            self._timer(host, now + int(cfg[2]))

    def _app_ping_server(self, host, now, wake):
        cfg = self.hp_app_cfg[host.hid]
        if int(wake[P.ACK]) == WAKE_START:
            host.app_r[0] = self._udp_open(host, port=int(cfg[1]))
        elif int(wake[P.ACK]) == WAKE_SOCKET:
            self._udp_sendto(host, now, int(wake[P.SEQ]),
                             int(wake[P.SRC]), int(wake[P.SPORT]),
                             int(wake[P.LEN]), aux=int(wake[P.AUX]))

    def _exp_delay(self, host):
        u = self._draw(host)
        mean = jnp.float32(float(self.hp_app_cfg[host.hid][2]))
        d = int(jnp.maximum((-mean * jnp.log1p(-u)).astype(jnp.int64), 1))
        return d

    def _app_phold(self, host, now, wake):
        cfg = self.hp_app_cfg[host.hid]
        reason = min(max(int(wake[P.ACK]), 0), 2)
        if reason == WAKE_START:
            host.app_r[0] = self._udp_open(host, port=int(cfg[1]))
            n0 = min(max(int(cfg[4]), 0), host.qcap)
            for _ in range(n0):
                self._timer(host, now + self._exp_delay(host))
        elif reason == WAKE_TIMER:
            u = self._draw(host)
            n = int(cfg[0])
            peer = int(jnp.minimum((u * n).astype(jnp.int64), n - 1))
            if peer == host.hid:
                peer = (peer + 1) % n
            self._udp_sendto(host, now, host.app_r[0], peer, cfg[1], cfg[3])
            host.app_r[1] += 1
        else:
            self._timer(host, now + self._exp_delay(host))

    def _relay_gossip(self, host, now, height):
        """Mirror of apps.gossip._relay: always MAX_FANOUT (8) draws,
        identical float32 peer math, sends only the first `fanout`."""
        cfg = self.hp_app_cfg[host.hid]
        n = max(int(cfg[0]), 2)
        k = min(max(int(cfg[2]), 0), 8)
        for j in range(8):
            u = self._draw(host)
            peer = int(jnp.minimum(
                (u * jnp.float32(n - 1)).astype(jnp.int64), n - 2))
            if peer >= host.hid:
                peer += 1
            if j < k:
                self._udp_sendto(host, now, host.app_r[0], peer,
                                 cfg[1], cfg[5], aux=height)

    def _app_gossip(self, host, now, wake):
        """Mirror of apps.gossip.app_gossip (block-gossip workload)."""
        cfg = self.hp_app_cfg[host.hid]
        reason = min(max(int(wake[P.ACK]), 0), 2)
        interval = int(cfg[3])
        if reason == WAKE_START:
            host.app_r[0] = self._udp_open(host, port=int(cfg[1]))
            host.app_r[5] = now
            if int(cfg[4]):
                self._timer(host, now + interval)
        elif reason == WAKE_TIMER:
            h = host.app_r[4] + 1
            host.app_r[4] = h
            host.app_r[1] = max(host.app_r[1], h)
            self._relay_gossip(host, now, h)
            self._timer(host, now + interval)
        else:
            h = int(np.int64(wake[P.AUX]))
            if h > host.app_r[1]:
                mined_at = host.app_r[5] + h * interval
                delay_us = max(now - mined_at, 0) // 1000
                host.app_r[1] = h
                host.app_r[2] += 1
                self.stats[host.hid, defs.ST_XFER_DONE] += 1
                self.stats[host.hid, defs.ST_RTT_SUM_US] += delay_us
                self.stats[host.hid, defs.ST_RTT_COUNT] += 1
                self._relay_gossip(host, now, h)

    # --- exchange (identical math to engine.window.exchange) ---
    def _exchange(self):
        all_pkts = []  # (global outbox order) host-major
        for host in self.hosts:
            for stime, pkt in host.outbox:
                all_pkts.append((host.hid, stime, pkt))
            host.outbox = []
        if not all_pkts:
            return
        delivered = {}  # dst -> list of (arrival, pkt) in source order
        for src, stime, pkt in all_pkts:
            dst = min(max(int(pkt[P.DST]), 0), self.H - 1)
            sv, dv = self.hp_vertex[src], self.hp_vertex[dst]
            rel = np.float32(self.rel[sv, dv])
            arrival = stime + int(self.lat[sv, dv])
            u = self._cheap_uniform(self._stream_of(R.DOMAIN_DROP, src),
                                    int(pkt[P.UID]))
            if rel > 0 and u <= rel:
                delivered.setdefault(dst, []).append((arrival, pkt))
            else:
                self.stats[src, defs.ST_PKTS_DROP_NET] += 1
        for dst, lst in delivered.items():
            host = self.hosts[dst]
            accepted = lst[: self.cfg.incap]
            self.stats[dst, defs.ST_PKTS_DROP_Q] += len(lst) - len(accepted)
            k = len(accepted)
            nfree = len(host.free_slots)
            k2 = min(k, max(nfree - self.reserve, 0))
            self.stats[dst, defs.ST_PKTS_DROP_Q] += k - k2
            for arrival, pkt in accepted[:k2]:
                slot = min(host.free_slots)
                host.free_slots.remove(slot)
                host.events[slot] = (arrival, host.eq_ctr, EV_PKT,
                                     pkt.copy())
                host.eq_ctr += 1

    # --- main loop ---
    def run(self):
        nt = min(self._next_time(h) for h in self.hosts)
        windows = 0
        while nt < self.stop and nt < SIMTIME_MAX:
            wend = min(nt + self.min_jump, self.stop)
            progressed = True
            while progressed:
                progressed = False
                for host in self.hosts:
                    while host.events and self._next_time(host) < wend:
                        t, seq, kind, pkt = self._q_pop_min(host)
                        self.stats[host.hid, defs.ST_EVENTS] += 1
                        if kind == EV_APP:
                            self._app(host, t, pkt)
                        elif kind == EV_PKT:
                            self._on_pkt(host, t, pkt)
                        elif kind == EV_NIC_TX:
                            self._on_tx(host, t, wend)
                        progressed = True
            self._exchange()
            windows += 1
            nt = min(self._next_time(h) for h in self.hosts)
        self.windows = windows
        return self.stats
