"""Auto-resume supervision: ``shadow_tpu run --until-complete``.

The durability tentpole's third piece (docs/durability.md): a long
run must survive being killed — OOM, preemption, a node reboot — and
finish as if nothing happened. The supervisor runs the simulation in
a CHILD process (the same CLI, minus the supervisor flags), watches
its exit, and on a crash re-execs it with ``--resume latest`` so it
restores the newest valid snapshot of the crash-safe checkpoint store
(engine.checkpoint). Capped retries with exponential backoff bound a
crash loop; every attempt leaves a crash-cause record in
``<checkpoint base>.supervisor.jsonl`` and — when the obs layer is
installed (PR 1) — a ``supervisor.attempt`` span plus
``supervisor.*`` metrics.

The interrupted≡uninterrupted contract this enables is PROVEN by the
flight recorder: a SIGKILLed-and-resumed run's digest chain is
byte-identical to an uninterrupted same-seed run's
(tests/test_until_complete.py, tools/divergence.py exit 0).
"""

from __future__ import annotations

import signal
import subprocess
import sys
import time

# exit code of a cooperative preemption (SIGTERM under --checkpoint:
# the run saved a snapshot at a chunk boundary and stopped — see
# engine.sim.Preempted). EX_TEMPFAIL: "try again later" — a resume
# completes the run; supervisors treat it as resumable, never as a
# crash that counts toward giving up / quarantine.
EXIT_PREEMPTED = 75

# flags the supervisor consumes; never forwarded to the child
_SUPERVISOR_FLAGS = {"--until-complete"}
_SUPERVISOR_OPTS = {"--max-retries", "--retry-backoff"}


def backoff_delay(base_s: float, failures: int,
                  cap_s: float = 60.0) -> float:
    """Exponential backoff: delay before retry number `failures`
    (1-based count of crashes so far), doubling from `base_s` to a
    cap. The one backoff rule both the single-run supervisor and the
    fleet scheduler (shadow_tpu.fleet) apply."""
    return min(float(base_s) * (2 ** max(int(failures) - 1, 0)),
               float(cap_s))


class CrashLog:
    """Append-only crash-cause journal (``<base>.supervisor.jsonl`` /
    the fleet's per-run ``crash.jsonl``): one JSON line per attempt,
    appended atomically and fsync'd so a kill mid-append can tear at
    most the line in flight — which read() skips (the obs.ledger
    torn-line contract). The fleet's quarantine decision and the
    post-mortem both read this file, so it must survive exactly the
    crashes it documents."""

    def __init__(self, path: str, log=None):
        self.path = path
        self._log = log or (lambda msg: sys.stderr.write(
            f"shadow_tpu: crash log: {msg}\n"))

    def append(self, rec: dict):
        from ..obs.ledger import jsonl_append
        try:
            jsonl_append(self.path, rec, fsync=True, sort_keys=True)
        except OSError as e:
            self._log(f"cannot write {self.path}: {e}")

    def read(self) -> list:
        from ..obs.ledger import jsonl_read
        return jsonl_read(self.path, label="crash log")


def strip_supervisor_args(argv: list) -> list:
    """The child's CLI: the original argv minus supervisor-only
    flags (handles both ``--opt v`` and ``--opt=v`` spellings)."""
    out = []
    skip = False
    for a in argv:
        if skip:
            skip = False
            continue
        if a in _SUPERVISOR_FLAGS:
            continue
        if a in _SUPERVISOR_OPTS:
            skip = True
            continue
        if any(a.startswith(opt + "=") for opt in _SUPERVISOR_OPTS):
            continue
        out.append(a)
    return out


def _strip_resume(argv: list) -> list:
    """Drop any user ``--resume X`` before injecting ``--resume
    latest`` on a retry (the user's explicit snapshot applies to the
    FIRST attempt only; retries must pick up the newest state)."""
    out = []
    skip = False
    for a in argv:
        if skip:
            skip = False
            continue
        if a == "--resume":
            skip = True
            continue
        if a.startswith("--resume="):
            continue
        out.append(a)
    return out


def classify_exit(status: int) -> str:
    """Child exit status -> human crash cause."""
    if status == 0:
        return "completed"
    if status < 0:
        try:
            name = signal.Signals(-status).name
        except ValueError:
            name = f"signal {-status}"
        return f"killed by {name}"
    return f"exited status={status}"


class Supervisor:
    """Run one CLI invocation to completion across crashes."""

    def __init__(self, child_argv: list, checkpoint: str,
                 max_retries: int = 5, backoff_s: float = 1.0,
                 backoff_cap_s: float = 60.0, python: str = None,
                 log=None, max_preemptions: int = 100):
        self.child_argv = list(child_argv)
        # engine.checkpoint.base_of, inlined: importing the checkpoint
        # module would pull jax into the (deliberately light)
        # supervisor parent
        self.checkpoint_base = (checkpoint[:-4]
                                if checkpoint.endswith(".npz")
                                else checkpoint)
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)
        self.backoff_cap_s = float(backoff_cap_s)
        # preemptions (exit 75) are resumable and never count as
        # crashes, but an environment that SIGTERMs every attempt
        # must not loop us forever — the same livelock bound the
        # fleet scheduler applies (max_spont_preempts), sized for a
        # long spot-instance run here
        self.max_preemptions = int(max_preemptions)
        self.python = python or sys.executable
        self.log = log or (lambda msg: sys.stderr.write(
            f"shadow_tpu: supervisor: {msg}\n"))
        self.attempts = []          # attempt records (also JSONL'd)
        self.crash_log = CrashLog(self.log_path(), log=self.log)

    def log_path(self) -> str:
        return self.checkpoint_base + ".supervisor.jsonl"

    def read_log(self) -> list:
        """All attempt records of this store's crash-cause journal,
        torn-line tolerant (a kill mid-append never breaks the next
        supervisor's — or the fleet's — read of it)."""
        return self.crash_log.read()

    def _record(self, rec: dict):
        self.attempts.append(rec)
        self.crash_log.append(rec)

    def _child_argv(self, attempt: int) -> list:
        if attempt == 1:
            return [self.python, "-m", "shadow_tpu"] + self.child_argv
        # retries resume from the newest valid snapshot; the CLI's
        # ``--resume latest`` starts fresh (with a warning) when the
        # crash predated the first checkpoint
        return ([self.python, "-m", "shadow_tpu"]
                + _strip_resume(self.child_argv)
                + ["--resume", "latest"])

    def run(self) -> int:
        from ..obs import metrics as MT
        from ..obs import trace as TR
        attempt = 0
        crashes = 0
        preemptions = 0
        while True:
            attempt += 1
            argv = self._child_argv(attempt)
            resumed = attempt > 1
            t0 = time.perf_counter()  # simlint: ok DET101 -- attempt wall for the crash journal, not sim time
            _s0 = TR.TRACER.now() if TR.ENABLED else None
            try:
                rc = subprocess.call(argv)
            except KeyboardInterrupt:
                # the operator killed US: do not respawn under them
                raise
            wall = time.perf_counter() - t0  # simlint: ok DET101 -- attempt wall for the crash journal, not sim time
            cause = classify_exit(rc)
            if TR.ENABLED:
                TR.TRACER.complete(
                    "supervisor.attempt", _s0,
                    args={"attempt": attempt, "exit_status": rc,
                          "cause": cause, "resumed": resumed})
            if MT.ENABLED:
                reg = MT.REGISTRY
                reg.counter("supervisor.attempts").inc()
                reg.gauge("supervisor.last_exit_status").set(rc)
                if rc != 0 and rc != EXIT_PREEMPTED:
                    # preemptions are resumable, not crashes — a
                    # dashboard alerting on crashes must not fire on
                    # healthy spot-instance churn
                    reg.counter("supervisor.crashes").inc()
                if resumed:
                    reg.counter("supervisor.resumes").inc()
            self._record({
                "attempt": attempt, "exit_status": rc, "cause": cause,
                "wall_s": round(wall, 3), "resumed": resumed,
                "argv": argv[1:],      # drop the interpreter path
                "time": time.strftime("%Y-%m-%dT%H:%M:%S"),
            })
            if rc == 0:
                if attempt > 1:
                    self.log(f"run completed on attempt {attempt}")
                return 0
            self.log(f"attempt {attempt} {cause}")
            if rc == 2:
                # argparse usage errors are deterministic — the same
                # argv fails identically every time, so retrying only
                # reproduces one message max_retries times over
                self.log("usage error is not a crash; not retrying")
                if MT.ENABLED:
                    MT.REGISTRY.counter("supervisor.gave_up").inc()
                return rc
            if rc == EXIT_PREEMPTED:
                # a cooperative preemption (SIGTERM → snapshot at the
                # boundary, engine.sim.Preempted) is not a crash: it
                # never counts toward the retry cap — but a child
                # preempted on EVERY attempt is a livelock, so it has
                # its own generous bound
                preemptions += 1
                if MT.ENABLED:
                    MT.REGISTRY.counter("supervisor.preemptions").inc()
                if preemptions > self.max_preemptions:
                    self.log(
                        f"preempted {preemptions} times without "
                        "completing — something SIGTERMs every "
                        "attempt; giving up (state is resumable)")
                    if MT.ENABLED:
                        MT.REGISTRY.counter("supervisor.gave_up").inc()
                    return rc
                self.log("child was preempted (saved a snapshot); "
                         "resuming from 'latest'")
                time.sleep(self.backoff_s)
                continue
            crashes += 1
            if crashes > self.max_retries:
                self.log(
                    f"giving up after {crashes} crashes "
                    f"({self.max_retries} retries); last cause: "
                    f"{cause}")
                if MT.ENABLED:
                    MT.REGISTRY.counter("supervisor.gave_up").inc()
                return rc if rc > 0 else 70    # EX_SOFTWARE for signals
            delay = backoff_delay(self.backoff_s, crashes,
                                  self.backoff_cap_s)
            self.log(f"restarting from 'latest' in {delay:.1f}s "
                     f"(retry {crashes}/{self.max_retries})")
            if MT.ENABLED:
                MT.REGISTRY.gauge("supervisor.backoff_s").set(delay)
            time.sleep(delay)
