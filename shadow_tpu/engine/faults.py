"""Deterministic fault injection: config -> schedule -> state surgery.

The reference can only model failures statically (topology packetloss
attributes); robustness scenarios — a relay dying mid-circuit, a link
flapping, a loss episode — had to be approximated by editing the
topology between runs. Here faults are first-class scheduled events
(core.config.FaultSpec, ``<fault .../>`` / ``--fault``): the Simulation
compiles them to a time-sorted schedule, and the run loop executes each
batch at its exact simulated time by bounding the device window program
at the next fault time (engine.sim passes ``stop_time = next_fault`` to
run_windows, the same clamp the reference's master applies at endTime,
shd-master.c:410-440). Everything the injector does is a pure function
of (config, simulated time, device state), so dual same-seed runs stay
bit-identical — the property the reference's determinism dual-run test
checks (shd-test-determinism.c), extended to hostile schedules.

Fault semantics:

- ``host_down``: the host powers off. Its hosted child (if any) is
  SIGKILLed through the supervision layer (hosting.runtime.kill_host),
  its queues/outbox/NIC/app state are cleared, and every established
  TCP connection it held sends one RST toward its peer (arriving after
  the current path latency) — peers observe a reset, exactly what a
  crashed kernel's peers see. The RSTs are injected directly into peer
  event queues (the loopback-delivery path), NOT rolled against link
  reliability: a reset radiating from a dead host is the modeling
  convention here, not a routable packet. Packets later sent TO a dead
  host still traverse the network and are discarded at its (empty)
  socket table, like frames hitting a powered-off NIC's switch port.
- ``host_up``: process start events are re-armed for every process
  slot (app state zeroed first); a hosted process respawns a fresh
  child via hosting.runtime.restart_host.
- ``link_down`` / ``link_up``: the path reliability between the two
  attachment vertices is zeroed/restored (both directions). Note the
  oracle stores PATHS, not edges — on multi-hop graphs this severs the
  named vertex pair only; topology.has_edge gates a compile warning.
- ``loss``: path reliability is multiplied by (1 - rate) for the
  episode [at, until); overlapping episodes compose multiplicatively.
- ``latency``: extra_ns is ADDED to the path latency for the episode.
  Only additions are allowed — the conservative lookahead window is
  bounded by the minimum BASE latency, so increases keep every
  cross-host arrival at or past the window end (causality preserved);
  a reduction would need a window-bound recompute mid-run.

Mechanics: host faults mutate the Hosts pytree on the CPU (numpy round
trip — faults are rare, one transfer each is the cost); link faults
recompute the Shared lat/rel tables from the pristine base plus the
active episode set, so arbitrary overlap composes exactly.
"""

from __future__ import annotations

import sys
from dataclasses import dataclass

import numpy as np

from ..core.simtime import SIMTIME_MAX
from ..net import packet as P
from ..net.socket import TCPS_CLOSED, TCPS_ESTABLISHED, TCPS_TIME_WAIT
from . import defs
from .defs import EV_APP, EV_NULL, EV_PKT, WAKE_START

HOST_KINDS = ("host_down", "host_up")
LINK_KINDS = ("link_down", "link_up", "loss", "latency")


@dataclass(frozen=True)
class FaultEvent:
    """One compiled, fully-resolved fault occurrence."""
    t: int            # ns
    seq: int          # config order: the (t, seq) sort is total
    kind: str         # host_down|host_up|link_down|link_up|
    #                   loss_begin|loss_end|lat_begin|lat_end
    host: int = -1    # host id (host kinds)
    va: int = -1      # attachment vertices (link kinds)
    vb: int = -1
    eid: int = -1     # episode id pairing begin/end events
    rate: float = 0.0
    extra_ns: int = 0


def _resolve_endpoint(name: str, name_to_idx: dict, vertex) -> int:
    """A fault endpoint -> attachment vertex: a scenario host name, or
    a raw ``vertex:N``."""
    if name is None:
        raise ValueError("link fault requires src= and dst=")
    if name.startswith("vertex:"):
        return int(name[len("vertex:"):])
    if name not in name_to_idx:
        raise ValueError(f"fault names unknown host {name!r}")
    return int(vertex[name_to_idx[name]])


def compile_faults(specs, name_to_idx: dict, vertex, topo=None,
                   stop_time: int = None):
    """FaultSpec list -> time-sorted FaultEvent schedule.

    Validates kinds/targets at build (the reference's config errors are
    build-time too, shd-configuration.c); warns on faults at/after the
    stop time (they never fire) and on link faults between vertices
    with no direct edge (the fault severs the PATH entry only).
    """
    events = []
    eid = 0
    for seq, f in enumerate(specs):
        t = int(f.at)
        if stop_time is not None and t >= stop_time:
            sys.stderr.write(
                f"shadow_tpu: warning: fault #{seq} ({f.kind}) at "
                f"{t}ns is at/after the stop time and never fires\n")
        if f.kind in ("host_down", "host_up"):
            if f.host is None or f.host not in name_to_idx:
                raise ValueError(
                    f"fault #{seq} ({f.kind}) needs host=<scenario "
                    f"host name>, got {f.host!r}")
            events.append(FaultEvent(t=t, seq=seq, kind=f.kind,
                                     host=name_to_idx[f.host]))
            if f.kind == "host_down" and f.until is not None:
                if int(f.until) <= t:
                    # a misordered episode would fire the restart on
                    # the still-live host and then kill it forever
                    raise ValueError(
                        f"fault #{seq}: host_down episode needs "
                        "until > at")
                events.append(FaultEvent(t=int(f.until), seq=seq,
                                         kind="host_up",
                                         host=name_to_idx[f.host]))
            continue
        if f.kind not in LINK_KINDS:
            raise ValueError(
                f"fault #{seq}: unknown kind {f.kind!r} "
                f"(have: {HOST_KINDS + LINK_KINDS})")
        va = _resolve_endpoint(f.src, name_to_idx, vertex)
        vb = _resolve_endpoint(f.dst, name_to_idx, vertex)
        if topo is not None and not topo.has_edge(va, vb):
            sys.stderr.write(
                f"shadow_tpu: warning: fault #{seq} ({f.kind}) names "
                f"vertices {va}<->{vb} with no direct edge; it applies "
                "to that PATH entry only, not to routes through it\n")
        if f.kind == "link_down":
            events.append(FaultEvent(t=t, seq=seq, kind="link_down",
                                     va=va, vb=vb, eid=eid))
            if f.until is not None:
                if int(f.until) <= t:
                    # the restore would sort before the cut and the
                    # link would silently stay down forever
                    raise ValueError(
                        f"fault #{seq}: link_down episode needs "
                        "until > at")
                events.append(FaultEvent(t=int(f.until), seq=seq,
                                         kind="link_up", va=va, vb=vb,
                                         eid=eid))
        elif f.kind == "link_up":
            events.append(FaultEvent(t=t, seq=seq, kind="link_up",
                                     va=va, vb=vb, eid=-1))
        elif f.kind == "loss":
            if not (0.0 < f.rate <= 1.0):
                raise ValueError(
                    f"fault #{seq}: loss needs 0 < rate <= 1, "
                    f"got {f.rate}")
            if f.until is None or int(f.until) <= t:
                raise ValueError(
                    f"fault #{seq}: loss episode needs until > at")
            events.append(FaultEvent(t=t, seq=seq, kind="loss_begin",
                                     va=va, vb=vb, eid=eid,
                                     rate=float(f.rate)))
            events.append(FaultEvent(t=int(f.until), seq=seq,
                                     kind="loss_end", eid=eid))
        elif f.kind == "latency":
            if f.extra_ns <= 0:
                raise ValueError(
                    f"fault #{seq}: latency episode needs extra > 0 "
                    "(only ADDED latency preserves the lookahead "
                    "window's causality bound)")
            if f.until is None or int(f.until) <= t:
                raise ValueError(
                    f"fault #{seq}: latency episode needs until > at")
            events.append(FaultEvent(t=t, seq=seq, kind="lat_begin",
                                     va=va, vb=vb, eid=eid,
                                     extra_ns=int(f.extra_ns)))
            events.append(FaultEvent(t=int(f.until), seq=seq,
                                     kind="lat_end", eid=eid))
        eid += 1
    events.sort(key=lambda e: (e.t, e.seq, e.kind))
    return events


class _HostsEditor:
    """Lazy numpy view over the Hosts pytree for host-fault surgery:
    fields materialize (as mutable copies) on first touch and flush
    back in ONE replace, so a batch of host faults pays one device
    round trip however many fields it edits."""

    def __init__(self, hosts):
        self._hosts = hosts
        self._arrs = {}

    def __getitem__(self, field: str) -> np.ndarray:
        a = self._arrs.get(field)
        if a is None:
            a = np.array(getattr(self._hosts, field))
            self._arrs[field] = a
        return a

    def flush(self):
        if not self._arrs:
            return self._hosts
        import jax.numpy as jnp
        return self._hosts.replace(**{
            f: jnp.asarray(a) for f, a in self._arrs.items()})


class FaultInjector:
    """Executes a compiled fault schedule against live simulation
    state. Owned by the Simulation; engine.sim's run loop asks
    next_time() to bound each device segment and calls apply_batch()
    when the engine reaches a fault time."""

    # socket columns scrubbed on host_down (the sock_free surface —
    # sock_alloc fully reinitializes a row at claim time, so only the
    # liveness/demux/timer columns need clearing here)
    _SK_SCRUB = (("sk_used", False), ("sk_proto", 0),
                 ("sk_state", TCPS_CLOSED), ("sk_ctl", 0),
                 ("sk_timer_on", False), ("sk_rto_deadline", 0),
                 ("sk_lport", 0), ("sk_rport", 0), ("sk_rhost", -1),
                 ("sk_parent", -1), ("sk_close_after", False),
                 ("sk_app_ref", -1))

    def __init__(self, events, base_lat_ns, base_rel, vertex,
                 procs_of_host: dict, host_names):
        self.events = list(events)
        self.i = 0
        self.base_lat = np.array(base_lat_ns, dtype=np.int64)
        self.base_rel = np.array(base_rel, dtype=np.float32)
        self.vertex = np.asarray(vertex)
        self.procs_of_host = procs_of_host  # hid -> [proc slots]
        self.host_names = list(host_names)
        self.hosting = None          # HostingRuntime (Simulation wires)
        self.links_down = {}         # (va, vb) sorted pair -> down count
        self.loss_eps = {}           # eid -> (va, vb, rate)
        self.lat_eps = {}            # eid -> (va, vb, extra_ns)
        self.log = []                # applied-fault records (SimReport)
        # current effective latency table (base + active episodes):
        # host_down uses it to time the RSTs it radiates
        self._cur_lat = self.base_lat

    def pending(self) -> bool:
        return self.i < len(self.events)

    def next_time(self):
        """Earliest unapplied fault time, or None."""
        return self.events[self.i].t if self.pending() else None

    def fast_forward(self, idx: int, sh):
        """Resume support (engine.sim): re-arm the injector at
        schedule position `idx` — the ``__fault_idx__`` a checkpoint
        stamps. The schedule is a pure function of the config, so the
        snapshot only needs the POSITION: host-fault device effects
        already live in the restored arrays, and the link-fault
        bookkeeping (down counts, active loss/latency episodes) is
        replayed here so the Shared lat/rel tables — which are NOT
        part of the Hosts snapshot — come out exactly as the
        uninterrupted run's. Replayed events are appended to the log
        so SimReport.faults reports the whole logical run. Returns the
        (possibly rebuilt) Shared tables."""
        if not (0 <= idx <= len(self.events)):
            raise ValueError(
                f"checkpoint fault index {idx} is outside this "
                f"schedule (0..{len(self.events)}) — the snapshot "
                "belongs to a different fault config")
        shared_dirty = False
        for ev in self.events[:idx]:
            if ev.kind not in ("host_down", "host_up"):
                self._link_event(ev)
                shared_dirty = True
            self.log.append(self._record(ev))
        self.i = idx
        if shared_dirty:
            sh = self._recompute_shared(sh)
        return sh

    # --- application ---
    def apply_batch(self, hosts, sh):
        """Apply every event sharing the head time. Returns
        (hosts, sh) with host state and/or shared tables updated."""
        assert self.pending()
        t = self.events[self.i].t
        ed = _HostsEditor(hosts)
        shared_dirty = False
        while self.pending() and self.events[self.i].t == t:
            ev = self.events[self.i]
            self.i += 1
            if ev.kind == "host_down":
                self._host_down(ed, ev.host, t)
            elif ev.kind == "host_up":
                self._host_up(ed, ev.host, t)
            else:
                self._link_event(ev)
                shared_dirty = True
            self.log.append(self._record(ev))
            from ..obs import metrics as MT
            if MT.ENABLED:
                MT.REGISTRY.counter(f"fault.{ev.kind}").inc()
        hosts = ed.flush()
        if shared_dirty:
            sh = self._recompute_shared(sh)
        return hosts, sh

    def _record(self, ev: FaultEvent) -> dict:
        r = {"t": ev.t, "kind": ev.kind}
        if ev.host >= 0:
            r["host"] = self.host_names[ev.host]
        if ev.va >= 0:
            r["link"] = (int(ev.va), int(ev.vb))
        if ev.rate:
            r["rate"] = ev.rate
        if ev.extra_ns:
            r["extra_ns"] = ev.extra_ns
        return r

    # --- host faults ---
    def _host_down(self, ed: _HostsEditor, hid: int, t: int):
        """Power the host off: RST every established TCP connection
        toward its peer, then clear all volatile state."""
        sk_used = ed["sk_used"]
        sk_proto = ed["sk_proto"]
        sk_state = ed["sk_state"]
        sk_rhost = ed["sk_rhost"]
        # 1) radiate RSTs (deterministic slot order) BEFORE scrubbing
        for s in range(sk_used.shape[1]):
            if not sk_used[hid, s] or sk_proto[hid, s] != P.PROTO_TCP:
                continue
            st = int(sk_state[hid, s])
            if st < TCPS_ESTABLISHED or st == TCPS_TIME_WAIT:
                continue
            peer = int(sk_rhost[hid, s])
            if peer < 0 or peer == hid:
                continue          # loopback peer dies with the host
            lat = int(self._cur_lat[self.vertex[hid],
                                    self.vertex[peer]])
            pkt = np.zeros(P.PKT_WORDS, np.int32)
            pkt[P.SRC] = hid
            pkt[P.DST] = peer
            pkt[P.SPORT] = ed["sk_lport"][hid, s]
            pkt[P.DPORT] = ed["sk_rport"][hid, s]
            pkt[P.FLAGS] = P.PROTO_TCP | P.F_RST
            self._push_event(ed, peer, t + lat, EV_PKT, pkt)
        # 2) hosted child: SIGKILL through the supervisor
        if self.hosting is not None:
            self.hosting.kill_host(
                hid, cause=f"fault: host_down at t={t}ns", sim_ns=t)
        # 3) scrub the host row
        for f in ("eq_time", "eq_next"):
            ed[f][hid] = SIMTIME_MAX
        ed["eq_kind"][hid] = EV_NULL
        ed["ob_cnt"][hid] = 0
        ed["ob_next"][hid] = SIMTIME_MAX
        ed["txq_cnt"][hid] = 0
        ed["txq_head"][hid] = 0
        ed["nic_sched"][hid] = False
        ed["hw_cnt"][hid] = 0
        ed["app_node"][hid] = 0
        ed["app_r"][hid] = 0
        for f, val in self._SK_SCRUB:
            ed[f][hid] = val
        # bump every generation: timer/close events already emitted
        # toward these slots (none survive the queue clear, but peers'
        # in-flight segments demux by port, and generation-stamped
        # wakes must never match a post-restart incarnation)
        ed["sk_timer_gen"][hid] += 1
        ed["stats"][hid, defs.ST_FAULTS] += 1

    def _host_up(self, ed: _HostsEditor, hid: int, t: int):
        """Re-arm process start events (the boot sequence the
        Simulation schedules at build, engine.sim initial events)."""
        if self.hosting is not None:
            self.hosting.restart_host(hid)
        ed["app_node"][hid] = 0
        ed["app_r"][hid] = 0
        for p in self.procs_of_host.get(hid, ()):
            pkt = np.zeros(P.PKT_WORDS, np.int32)
            pkt[P.ACK] = WAKE_START
            pkt[P.SEQ] = -1
            pkt[P.SRC] = p        # slotless wake: process slot
            self._push_event(ed, hid, t, EV_APP, pkt)
        ed["stats"][hid, defs.ST_FAULTS] += 1

    def _push_event(self, ed: _HostsEditor, hid: int, when: int,
                    kind: int, pkt: np.ndarray):
        """equeue.q_push mirrored in numpy (eq_next cache maintained)."""
        eq_time = ed["eq_time"]
        free = np.flatnonzero(eq_time[hid] == SIMTIME_MAX)
        if free.size == 0:
            ed["stats"][hid, defs.ST_EQ_FULL_LOCAL] += 1
            return
        q = int(free[0])
        eq_time[hid, q] = when
        ed["eq_kind"][hid, q] = kind
        ed["eq_seq"][hid, q] = ed["eq_ctr"][hid]
        ed["eq_ctr"][hid] += 1
        ed["eq_pkt"][hid, q] = pkt
        ed["eq_next"][hid] = min(int(ed["eq_next"][hid]), when)

    # --- link faults ---
    def _link_event(self, ev: FaultEvent):
        if ev.kind == "link_down":
            key = (min(ev.va, ev.vb), max(ev.va, ev.vb))
            self.links_down[key] = self.links_down.get(key, 0) + 1
        elif ev.kind == "link_up":
            key = (min(ev.va, ev.vb), max(ev.va, ev.vb))
            n = self.links_down.get(key, 0) - 1
            if n > 0:
                self.links_down[key] = n
            else:
                self.links_down.pop(key, None)
        elif ev.kind == "loss_begin":
            self.loss_eps[ev.eid] = (ev.va, ev.vb, ev.rate)
        elif ev.kind == "loss_end":
            self.loss_eps.pop(ev.eid, None)
        elif ev.kind == "lat_begin":
            self.lat_eps[ev.eid] = (ev.va, ev.vb, ev.extra_ns)
        elif ev.kind == "lat_end":
            self.lat_eps.pop(ev.eid, None)

    def _recompute_shared(self, sh):
        """Rebuild the effective lat/rel tables from the pristine base
        plus the active episode set — overlap composes exactly and
        restores are exact (no drift from repeated in-place edits)."""
        import jax.numpy as jnp
        lat = self.base_lat.copy()
        rel = self.base_rel.copy()
        for eid in sorted(self.lat_eps):
            va, vb, extra = self.lat_eps[eid]
            lat[va, vb] += extra
            if va != vb:
                lat[vb, va] += extra
        for eid in sorted(self.loss_eps):
            va, vb, rate = self.loss_eps[eid]
            rel[va, vb] *= (1.0 - rate)
            if va != vb:
                rel[vb, va] *= (1.0 - rate)
        for va, vb in sorted(self.links_down):
            rel[va, vb] = 0.0
            if va != vb:
                rel[vb, va] = 0.0
        self._cur_lat = lat
        return sh.replace(lat_ns=jnp.asarray(lat, jnp.int64),
                          rel=jnp.asarray(rel, jnp.float32))


class CrashHook:
    """Simulator-suicide triggers for durability testing: SIGKILL
    THIS process (the whole simulator — exactly a preemption, no
    cleanup runs) either at the first chunk boundary at/after a given
    SIMULATED time, or after a WALL-clock delay. The durability proof
    (tests/test_until_complete.py, verify skill crash-resume smoke)
    uses both: the sim-time trigger lands at a deterministic point,
    the wall-clock one at an arbitrary instant — resume must be
    byte-identical either way.

    Environment knobs (read by engine.sim's run loop):

    - ``SHADOW_TPU_CRASH_SIM_NS``: fire when the run loop first sees
      ``ws >= value`` (after the checkpoint block, so a snapshot due
      at the same boundary is durable before the kill);
    - ``SHADOW_TPU_CRASH_WALL_S``: arm a wall-clock timer at run
      start; fires mid-anything, including mid-``checkpoint.save``
      (the atomicity contract under test);
    - ``SHADOW_TPU_CRASH_GUARD``: path created O_EXCL at fire time —
      the guard makes the crash one-shot, so a supervised resume of
      the SAME command line does not crash again.
    """

    def __init__(self, sim_ns: int = None, wall_s: float = None,
                 guard: str = None):
        self.sim_ns = sim_ns
        self.guard = guard
        self._timer = None
        if wall_s is not None:
            import threading
            self._timer = threading.Timer(wall_s, self._fire)
            self._timer.daemon = True
            self._timer.start()

    @classmethod
    def from_env(cls):
        import os as _os
        sim_ns = _os.environ.get("SHADOW_TPU_CRASH_SIM_NS")
        wall_s = _os.environ.get("SHADOW_TPU_CRASH_WALL_S")
        if not sim_ns and not wall_s:
            return None
        return cls(sim_ns=int(sim_ns) if sim_ns else None,
                   wall_s=float(wall_s) if wall_s else None,
                   guard=_os.environ.get("SHADOW_TPU_CRASH_GUARD"))

    def _fire(self):
        import os as _os
        import signal as _signal
        if self.guard:
            try:
                fd = _os.open(self.guard,
                              _os.O_CREAT | _os.O_EXCL | _os.O_WRONLY)
                _os.close(fd)
            except FileExistsError:
                self.sim_ns = None          # already fired once: disarm
                if self._timer is not None:
                    self._timer.cancel()
                return
            except OSError as e:
                # a broken guard (e.g. missing directory) must not
                # silently skip the kill — fire anyway; the repeated
                # SIGKILLs exhaust the supervisor's retries loudly
                sys.stderr.write(
                    f"shadow_tpu: CrashHook guard {self.guard!r} "
                    f"unusable ({e}) — firing without fire-once "
                    "protection\n")
        sys.stderr.write(
            "shadow_tpu: CrashHook firing — SIGKILLing the simulator "
            "(durability test)\n")
        sys.stderr.flush()
        _os.kill(_os.getpid(), _signal.SIGKILL)

    def maybe_fire(self, ws: int):
        """Run-loop check for the sim-time trigger."""
        if self.sim_ns is not None and ws >= self.sim_ns:
            self._fire()
