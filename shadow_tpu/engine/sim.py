"""Simulation: scenario -> device state -> run loop -> report.

This is the L6/L7 equivalent of the reference's Master/Slave
(/root/reference/src/main/core/shd-master.c, shd-slave.c): it loads the
scenario, builds the topology oracle and DNS, registers hosts and their
processes, then drives the window loop. There is no worker-thread
machinery to manage — the "scheduler" is the compiled window program of
engine.window.
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..core import rng as R
from ..core.config import Scenario
from ..core.constants import DEFAULT_MIN_TIME_JUMP, INTERFACE_BUFFER_SIZE
from ..core.simtime import SIMTIME_MAX, SIMTIME_ONE_SECOND
from ..routing.dns import DNS
from ..routing.topology import Topology, attach_hosts, build_topology
from ..apps.compile import compile_app
from ..net.packet import PKT_WORDS
from . import defs
from .defs import EV_APP, WAKE_START, N_STATS
from .state import EngineConfig, Hosts, HostParams, Shared, alloc_hosts, make_shared
from .window import run_windows
from ..net import packet as P


@dataclass
class SimReport:
    """Aggregated results of a run."""
    stats: np.ndarray          # [H, N_STATS]
    host_names: list
    sim_time_ns: int
    wall_seconds: float
    windows: int

    def total(self, stat: int) -> int:
        return int(self.stats[:, stat].sum())

    @property
    def events(self) -> int:
        return self.total(defs.ST_EVENTS)

    @property
    def events_per_sec(self) -> float:
        return self.events / self.wall_seconds if self.wall_seconds else 0.0

    @property
    def speedup(self) -> float:
        """Simulated seconds per wallclock second."""
        if not self.wall_seconds:
            return 0.0
        return (self.sim_time_ns / SIMTIME_ONE_SECOND) / self.wall_seconds

    def summary(self) -> dict:
        mean_rtt_us = (self.total(defs.ST_RTT_SUM_US) /
                       max(self.total(defs.ST_RTT_COUNT), 1))
        return {
            "hosts": len(self.host_names),
            "events": self.events,
            "windows": self.windows,
            "sim_seconds": self.sim_time_ns / SIMTIME_ONE_SECOND,
            "wall_seconds": self.wall_seconds,
            "events_per_sec": self.events_per_sec,
            "speedup": self.speedup,
            "pkts_sent": self.total(defs.ST_PKTS_SENT),
            "pkts_recv": self.total(defs.ST_PKTS_RECV),
            "drop_net": self.total(defs.ST_PKTS_DROP_NET),
            "drop_buf": self.total(defs.ST_PKTS_DROP_BUF),
            "drop_q": self.total(defs.ST_PKTS_DROP_Q),
            "bytes_recv": self.total(defs.ST_BYTES_RECV),
            "retransmits": self.total(defs.ST_RETRANSMIT),
            "transfers_done": self.total(defs.ST_XFER_DONE),
            "mean_rtt_us": mean_rtt_us,
        }


def auto_engine_config(scenario: Scenario, topo: Topology) -> EngineConfig:
    """Size the fixed-capacity buffers from the scenario.

    The binding constraint is packets per lookahead window: a host's NIC
    can emit up to bw_up * min_jump bytes between exchanges, so the
    outbox (per-window emit budget) must cover that or the NIC defers to
    the next window and throughput is artificially capped. Destination
    fan-in gets 2x that budget; event queues must hold the inbound burst
    plus timers/wakes. Capacities are clamped so memory stays bounded at
    large H (beyond the clamp the NIC deferral keeps results exact, just
    reflecting genuine queueing).
    """
    from ..core.constants import TCP_MSS

    H = scenario.total_hosts()
    min_jump = topo.min_latency_ns or DEFAULT_MIN_TIME_JUMP

    bw = 0
    for idx, name, spec in scenario.expand_hosts():
        bw = max(bw, spec.bandwidth_up or 0, spec.bandwidth_down or 0)
    if topo.v_bw_up_bytes.size:
        bw = max(bw, int(topo.v_bw_up_bytes.max()),
                 int(topo.v_bw_down_bytes.max()))
    if bw <= 0:
        bw = 128 * 1024 * 1024

    pkts_per_window = (bw * min_jump) // (TCP_MSS * 10**9) + 1

    def pow2(n, lo, hi):
        v = lo
        while v < n and v < hi:
            v *= 2
        return v

    obcap = pow2(int(pkts_per_window * 5 // 4), 16, 512)
    incap = pow2(2 * obcap, 32, 1024)
    qcap = pow2(incap + 32, 32, 1024)
    return EngineConfig(num_hosts=H, qcap=qcap, scap=16, obcap=obcap,
                        incap=incap, txqcap=16)


class Simulation:
    """Build and run one scenario on the JAX engine."""

    def __init__(self, scenario: Scenario, topology: Topology = None,
                 engine_cfg: EngineConfig = None, seed: int = None):
        self.scenario = scenario
        seed = scenario.seed if seed is None else seed

        src = topology or scenario.topology_graphml or scenario.topology_path
        self.topo = src if isinstance(src, Topology) else build_topology(src)

        H = scenario.total_hosts()
        self.cfg = engine_cfg or auto_engine_config(scenario, self.topo)
        assert self.cfg.num_hosts == H

        # --- register hosts: DNS, attachment, apps (reference
        # _master_registerHosts -> slave_addNewVirtualHost analogue) ---
        self.dns = DNS()
        names, hints = [], []
        for idx, name, spec in scenario.expand_hosts():
            names.append(name)
            hints.append((spec.ip_hint, spec.geocode_hint, spec.type_hint))
            self.dns.register(idx, name, spec.ip_hint if spec.quantity == 1 else None)
        self.host_names = names

        vertex = attach_hosts(self.topo, hints, seed=seed)

        bw_up = np.zeros(H, dtype=np.int64)
        bw_down = np.zeros(H, dtype=np.int64)
        nic_buf = np.full(H, INTERFACE_BUFFER_SIZE, dtype=np.int64)
        app_kind = np.zeros(H, dtype=np.int32)
        app_cfg = np.zeros((H, 8), dtype=np.int64)
        start_times = np.zeros((H,), dtype=np.int64)
        has_app = np.zeros(H, dtype=bool)

        for idx, name, spec in scenario.expand_hosts():
            v = vertex[idx]
            bw_up[idx] = spec.bandwidth_up or self.topo.v_bw_up_bytes[v] or 1 << 40
            bw_down[idx] = spec.bandwidth_down or self.topo.v_bw_down_bytes[v] or 1 << 40
            if spec.interface_buffer:
                nic_buf[idx] = spec.interface_buffer
            if spec.processes:
                # TPU app tier: one process per host for now (multi-process
                # hosts arrive with the hosting milestone)
                proc = spec.processes[0]
                kind, cfg_words = compile_app(proc.plugin, proc.arguments,
                                              self.dns, H)
                app_kind[idx] = kind
                app_cfg[idx] = cfg_words
                start_times[idx] = proc.start_time
                has_app[idx] = True

        self.hp = HostParams(
            hid=jnp.arange(H, dtype=jnp.int32),
            vertex=jnp.asarray(vertex, dtype=jnp.int32),
            bw_up=jnp.asarray(bw_up),
            bw_down=jnp.asarray(bw_down),
            app_kind=jnp.asarray(app_kind),
            app_cfg=jnp.asarray(app_cfg),
            nic_buf=jnp.asarray(nic_buf),
        )

        min_jump = self.topo.min_latency_ns or DEFAULT_MIN_TIME_JUMP
        self.sh = make_shared(self.topo.latency_ns, self.topo.reliability,
                              R.root_key(seed), scenario.stop_time, min_jump,
                              cc_kind=self.cfg.cc_kind)

        # --- initial events: process starts (reference process_schedule) ---
        hosts = alloc_hosts(self.cfg)
        eq_time = np.array(hosts.eq_time)
        eq_kind = np.array(hosts.eq_kind)
        eq_pkt = np.array(hosts.eq_pkt)
        eq_ctr = np.array(hosts.eq_ctr)
        idxs = np.flatnonzero(has_app)
        eq_time[idxs, 0] = start_times[idxs]
        eq_kind[idxs, 0] = EV_APP
        eq_pkt[idxs, 0, P.ACK] = WAKE_START
        eq_pkt[idxs, 0, P.SEQ] = -1
        eq_ctr[idxs] = 1
        self.hosts = hosts.replace(
            eq_time=jnp.asarray(eq_time), eq_kind=jnp.asarray(eq_kind),
            eq_pkt=jnp.asarray(eq_pkt), eq_ctr=jnp.asarray(eq_ctr))

        self._ran = False

    def run(self, verbose: bool = False) -> SimReport:
        assert not self._ran, "Simulation objects are single-use"
        self._ran = True
        hosts, cfg, hp, sh = self.hosts, self.cfg, self.hp, self.sh

        t0 = jnp.min(hosts.eq_time)
        wstart = t0
        wend = jnp.where(t0 == SIMTIME_MAX, t0, t0 + sh.min_jump)

        total_windows = 0
        wall0 = _time.perf_counter()
        while True:
            hosts, wstart, wend, n = run_windows(
                hosts, hp, sh, wstart, wend, cfg, cfg.chunk_windows)
            total_windows += int(n)
            ws = int(wstart)
            if verbose:
                print(f"  t={ws / SIMTIME_ONE_SECOND:.3f}s "
                      f"windows={total_windows}")
            if ws >= int(sh.stop_time) or ws >= SIMTIME_MAX:
                break
        stats = np.asarray(hosts.stats)
        wall = _time.perf_counter() - wall0
        self.final_hosts = hosts
        sim_ns = min(int(sh.stop_time), ws) if ws < SIMTIME_MAX else int(sh.stop_time)
        return SimReport(stats=stats, host_names=self.host_names,
                         sim_time_ns=sim_ns, wall_seconds=wall,
                         windows=total_windows)


def run_scenario(scenario: Scenario, **kw) -> SimReport:
    return Simulation(scenario, **kw).run()
