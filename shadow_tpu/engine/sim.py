"""Simulation: scenario -> device state -> run loop -> report.

This is the L6/L7 equivalent of the reference's Master/Slave
(/root/reference/src/main/core/shd-master.c, shd-slave.c): it loads the
scenario, builds the topology oracle and DNS, registers hosts and their
processes, then drives the window loop. There is no worker-thread
machinery to manage — the "scheduler" is the compiled window program of
engine.window.
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..core import rng as R
from ..core.config import Scenario
from ..core.constants import DEFAULT_MIN_TIME_JUMP, INTERFACE_BUFFER_SIZE
from ..core.simtime import SIMTIME_MAX, SIMTIME_ONE_SECOND
from ..routing.dns import DNS
from ..routing.topology import Topology, attach_hosts, build_topology
from ..apps.compile import compile_app
from ..net.packet import PKT_WORDS
from . import defs
from .defs import EV_APP, WAKE_START, N_STATS
from .state import (EngineConfig, Hosts, HostParams, Shared,
                    alloc_hosts, hot_fields, make_shared)
from .window import run_windows
from ..net import packet as P


class Preempted(RuntimeError):
    """Raised by run() when a cooperative preemption was requested
    (request_preempt — the CLI's SIGTERM handler under --checkpoint,
    the fleet worker's preemption protocol): the loop stopped at the
    next chunk boundary, saving a snapshot there first when a
    checkpoint store is active, so ``--resume latest`` continues the
    run with zero lost work. The CLI maps this to exit status 75
    (EX_TEMPFAIL, engine.supervisor.EXIT_PREEMPTED): "resumable, try
    again" — supervisors and the fleet scheduler requeue instead of
    counting a crash."""

    def __init__(self, sim_ns: int, saved: bool):
        self.sim_ns = int(sim_ns)
        self.saved = bool(saved)
        what = ("snapshot saved" if saved
                else "no checkpoint store — nothing saved")
        super().__init__(f"run preempted at sim_ns={sim_ns} ({what})")


# process-wide cooperative-preemption flag: signal-handler-safe (a
# plain Event), observed by every running Simulation at its next chunk
# boundary. run() clears it on entry so a flag left by a previous
# run's preemption cannot kill the next run in the same process.
import threading as _threading                              # noqa: E402

_PREEMPT = _threading.Event()


def request_preempt():
    """Ask the running simulation to checkpoint at the next chunk
    boundary and raise Preempted. Safe to call from a signal handler
    or another thread; a no-op until a run loop observes it."""
    _PREEMPT.set()


@dataclass
class SimReport:
    """Aggregated results of a run."""
    stats: np.ndarray          # [H, N_STATS]
    host_names: list
    sim_time_ns: int
    wall_seconds: float
    windows: int
    heartbeats: list = field(default_factory=list)
    capacity: dict = field(default_factory=dict)
    cost: dict = field(default_factory=dict)  # cost_model() inputs:
    #   pass mix per compaction rung, per-row state bytes, warm wall
    memory: dict = field(default_factory=dict)  # memory observatory
    #   record (obs.memscope): device-buffer watermark (peak_bytes /
    #   source / per_device), the state byte census totals
    #   (state_bytes, state_bytes_per_host, hot_state_bytes) and the
    #   window program's captured XLA analysis under "xla" (flops,
    #   bytes_accessed, argument/temp/output bytes — None entries
    #   where the backend refused)
    network: dict = field(default_factory=dict)  # network observatory
    #   record (obs.netscope, cfg.netscope runs only): per-kind
    #   (rtt/completion/queue/retx) bucket counts with exact
    #   p50/p90/p99 read-outs from the device-side histograms, plus
    #   the bucket bounds and — when run(netscope=...) streamed a
    #   JSONL time-series — the record count and path
    device_phases: dict = field(default_factory=dict)  # passcope
    #   observatory record (obs.passcope, --passcope runs only): the
    #   per-pass device-time table decoded from the profiler's xplane
    #   files — {phases: {stateflow label: {ms, frac}}, rungs,
    #   attributed_frac, residual_*} with available: False + the error
    #   on backends that refuse the profiler
    occupancy: dict = field(default_factory=dict)  # lockstep-
    #   efficiency record (obs.passcope.occupancy, always on —
    #   computed from the drain's own pass counters): lane_steps,
    #   utilization, waste_frac, per_rung min-fill bounds, and — on
    #   mesh runs — the per-shard waste view under "shards"
    hosted: dict = field(default_factory=dict)  # hosted-process exit
    #   report: host name -> {"exit_status", "cause", "sim_ns"} from
    #   the shim supervisor (hosting.runtime.exit_info) — the per-host
    #   exit status + cause the robustness layer guarantees even when
    #   a child crashes/hangs mid-run
    faults: list = field(default_factory=list)  # applied fault events
    #   in execution order (engine.faults.FaultInjector.log)

    def total(self, stat: int) -> int:
        return int(self.stats[:, stat].sum())

    @property
    def events(self) -> int:
        return self.total(defs.ST_EVENTS)

    @property
    def events_per_sec(self) -> float:
        return self.events / self.wall_seconds if self.wall_seconds else 0.0

    @property
    def speedup(self) -> float:
        """Simulated seconds per wallclock second."""
        if not self.wall_seconds:
            return 0.0
        return (self.sim_time_ns / SIMTIME_ONE_SECOND) / self.wall_seconds

    def capacity_report(self) -> list:
        """End-of-run capacity accounting (the reference's
        ObjectCounter shutdown report, shd-slave.c:207-211, recast for
        fixed arrays): per array, configured capacity, peak occupancy
        across hosts, and events lost to overflow — each overflow
        class named separately. Cross-host arrivals never drop on
        capacity (they defer at the source, `deferred` column); the
        drop columns cover local pushes and the NIC rings only."""
        drops = {
            "event_queue": (self.total(defs.ST_PKTS_DROP_Q) +
                            self.total(defs.ST_EQ_FULL_LOCAL)),
            "socket_table": self.total(defs.ST_SOCK_FAIL),
            "outbox": self.total(defs.ST_OUTBOX_DROP),
            "nic_txq": self.total(defs.ST_TXQ_DROP),
        }
        defers = {
            "event_queue": self.total(defs.ST_DEFER_FANIN),
            "outbox": self.total(defs.ST_DEFER_A2A),
        }
        out = []
        for name, cap, peak in self.capacity.get("rows", []):
            out.append({"array": name, "capacity": cap, "peak": peak,
                        "overflow": drops.get(name, 0),
                        "deferred": defers.get(name, 0)})
        return out

    def cost_model(self) -> dict:
        """Where the wall time goes, in pass-mix and modeled HBM-
        traffic terms — the per-pass cost model the round-3 verdict
        asked for (the reference self-reports the analogous numbers:
        scheduler idle/barrier-wait seconds shd-scheduler.c:250-252,
        per-host exec seconds shd-host.c:201-208). On this hardware
        the pass cost is row-state HBM traffic, so the model reports
        bytes moved per pass per rung and the achieved bandwidth
        against the chip's roofline.

        All byte figures are MODELED from array shapes (gather/step/
        scatter traffic assuming no fusion savings), not measured
        counters — upper bounds that localize where the time goes;
        `achieved_gbps_est` divides PER-CHIP modeled traffic by the
        warm wall (excluding the first chunk's compile when the run
        had more than one chunk — `warm` says which), so it reads as
        "what fraction of the roofline would this run sustain if the
        model were exact"."""
        if not self.cost:
            return {}
        rb = self.cost["row_bytes"]
        mix = self.cost["pass_mix"]       # {label: (K or H, passes)}
        B = self.cost.get("batch", 1)
        per_chip_h = self.cost["per_chip_hosts"]
        shards = self.cost.get("shards", 1)
        passes = {k: int(n) for k, (_, n) in mix.items()}
        total_passes = sum(passes.values())
        est_pass_bytes = {}
        est_total = 0
        for label, (k, n) in mix.items():
            if label == "dense":
                pb = 2 * per_chip_h * rb
            elif label.startswith("w"):
                # window-rung: gather/scatter amortized over the whole
                # window; each counted pass drains B batched sub-steps
                pb = 2 * B * k * rb
            else:
                pb = (4 + 2 * B) * k * rb
            est_pass_bytes[label] = pb
            est_total += pb * int(n)
        warm = self.cost.get("warm_wall")
        wall = warm if warm else self.wall_seconds
        from ..obs.memscope import hbm_peak_gbps
        peak = self.cost.get("hbm_peak_gbps") or hbm_peak_gbps()
        # sharded pass counters sum every chip's passes (shards move
        # their pass bytes CONCURRENTLY), so the per-chip bandwidth —
        # the number comparable to one chip's HBM peak — divides the
        # aggregate by the shard count
        gbps_modeled = est_total / shards / wall / 1e9 if wall else 0.0
        # MEASURED traffic (obs.memscope, PR 15): XLA's own
        # bytes-accessed for the compiled chunk program x chunk calls
        # replaces the hand model when the backend provides it —
        # modeled and measured report side by side, and the headline
        # roofline_frac prefers the measured figure. Like the modeled
        # path, the sharded program's analysis covers all shards'
        # concurrent traffic, so the per-chip figure divides by the
        # shard count.
        xla = (self.memory or {}).get("xla") or {}
        chunks = self.cost.get("chunks")
        meas_total = (xla["bytes_accessed"] * chunks
                      if xla.get("bytes_accessed") and chunks else None)
        gbps_meas = (meas_total / shards / wall / 1e9
                     if meas_total and wall else None)
        gbps = gbps_meas if gbps_meas is not None else gbps_modeled
        out = {
            "row_bytes": rb,
            "hot_columns": self.cost.get("hot_columns"),
            "batch": B,
            "shards": shards,
            "passes": passes,
            "passes_total": total_passes,
            "passes_per_window": (total_passes / self.windows
                                  if self.windows else 0.0),
            "est_pass_bytes": est_pass_bytes,
            "est_total_gb": est_total / 1e9,
            "wall_seconds_used": wall,
            # False = single-chunk run: the wall INCLUDES the cold
            # compile and the gbps figures understate accordingly
            "warm": warm is not None,
            # modeled vs measured, side by side; achieved_gbps_est
            # keeps its name for trajectory readers and carries the
            # best available figure (measured when the backend
            # provides bytes-accessed, modeled otherwise — `measured`
            # says which)
            "achieved_gbps_est": gbps,
            "modeled_gbps": gbps_modeled,
            "measured": gbps_meas is not None,
            "hbm_peak_gbps": peak,
            "roofline_frac": gbps / peak if peak else 0.0,
            "roofline_frac_modeled": (gbps_modeled / peak
                                      if peak else 0.0),
        }
        if meas_total is not None:
            out["measured_total_gb"] = meas_total / 1e9
            out["measured_gbps"] = gbps_meas
            out["roofline_frac_measured"] = (gbps_meas / peak
                                             if peak else 0.0)
        # modeled-vs-MEASURED per pass (obs.passcope): when a
        # --passcope run decoded a device pass table, put each pass's
        # measured device-time share beside the byte model's share —
        # the model only prices bytes; the measured column says where
        # the device time actually went, pass by pass
        dev = self.device_phases
        if dev and dev.get("available"):
            ph = dev.get("phases", {})
            table = {}
            for label, pb in est_pass_bytes.items():
                mb = pb * passes.get(label, 0)
                table[label] = {
                    "modeled_bytes": mb,
                    "modeled_frac": (round(mb / est_total, 4)
                                     if est_total else 0.0),
                }
            for label, rec in ph.items():
                table.setdefault(label, {})
                table[label]["measured_ms"] = rec["ms"]
                table[label]["measured_frac"] = rec["frac"]
            # drain rungs measure against the rung byte rows directly
            for label, rec in dev.get("rungs", {}).items():
                if label in table:
                    table[label]["measured_ms"] = rec["ms"]
                    table[label]["measured_frac"] = rec["frac"]
            out["pass_table"] = table
            out["device_attributed_frac"] = dev.get("attributed_frac")
        return out

    def summary(self) -> dict:
        """The run's headline figures. When the metrics registry is
        enabled (obs.metrics) the dict is also published as ``sim.*``
        gauges, so the CLI, the tracker, bench.py and the metrics.json
        snapshot all read one source of truth."""
        mean_rtt_us = (self.total(defs.ST_RTT_SUM_US) /
                       max(self.total(defs.ST_RTT_COUNT), 1))
        sim_s = self.sim_time_ns / SIMTIME_ONE_SECOND
        s = {
            "hosts": len(self.host_names),
            "events": self.events,
            "windows": self.windows,
            "sim_seconds": sim_s,
            "wall_seconds": self.wall_seconds,
            "events_per_sec": self.events_per_sec,
            "speedup": self.speedup,
            "wall_per_sim_second": (self.wall_seconds / sim_s
                                    if sim_s else 0.0),
            "pkts_sent": self.total(defs.ST_PKTS_SENT),
            "pkts_recv": self.total(defs.ST_PKTS_RECV),
            "drop_net": self.total(defs.ST_PKTS_DROP_NET),
            "drop_buf": self.total(defs.ST_PKTS_DROP_BUF),
            "drop_q": self.total(defs.ST_PKTS_DROP_Q),
            "defer_fanin": self.total(defs.ST_DEFER_FANIN),
            "defer_a2a": self.total(defs.ST_DEFER_A2A),
            "bytes_recv": self.total(defs.ST_BYTES_RECV),
            "retransmits": self.total(defs.ST_RETRANSMIT),
            "sack_reneges": self.total(defs.ST_SACK_RENEGE),
            "transfers_done": self.total(defs.ST_XFER_DONE),
            "transfers_aborted": self.total(defs.ST_TGEN_ABORT),
            "mean_rtt_us": mean_rtt_us,
        }
        # memory observatory figures (obs.memscope): the run's
        # device-buffer watermark and per-host state bytes — the
        # fields bench lines and perf-ledger entries carry
        # (mem_peak_bytes is what tools/perf_regress.py's memory gate
        # compares)
        if self.memory:
            s["mem_peak_bytes"] = int(self.memory.get("peak_bytes", 0))
            s["mem_source"] = self.memory.get("source")
            s["state_bytes_per_host"] = int(
                self.memory.get("state_bytes_per_host", 0))
        # network observatory figures (obs.netscope): exact tail
        # read-outs from the device histograms — the p50/p99 fields
        # ledger entries and bench lines carry so perf trajectories
        # can track tail behavior, not just means
        if self.network:
            kinds = self.network.get("kinds", {})
            s["rtt_p50_us"] = kinds.get("rtt", {}).get("p50_us", 0)
            s["rtt_p99_us"] = kinds.get("rtt", {}).get("p99_us", 0)
            s["completion_p99_s"] = (
                kinds.get("completion", {}).get("p99_us", 0) / 1e6)
        # lockstep-occupancy figures (obs.passcope, always computed
        # from the drain's own pass counters): the waste fraction and
        # the dominating device pass — what bench lines and
        # perf-ledger entries carry for the occupancy regression gate
        # (tools/perf_regress.py)
        if self.occupancy:
            s["waste_frac"] = self.occupancy.get("waste_frac")
            s["lane_utilization"] = self.occupancy.get("utilization")
            from ..obs import passcope as _PC
            lbl, frac = _PC.top_pass(self.device_phases)
            if lbl is not None:
                s["top_pass"] = lbl
                s["top_pass_frac"] = frac
        # robustness figures appear only when the features were used —
        # keeps the BENCH-diffable section stable for plain runs
        if self.faults:
            s["faults_applied"] = len(self.faults)
        if self.hosted:
            s["hosted_exits"] = len(self.hosted)
            s["hosted_failures"] = sum(
                1 for v in self.hosted.values()
                if not v.get("clean", False))
        from ..obs import metrics as M
        if M.ENABLED:
            M.REGISTRY.publish("sim", s)
        return s


def auto_engine_config(scenario: Scenario, topo: Topology) -> EngineConfig:
    """Size the fixed-capacity buffers from the scenario.

    The binding constraint is packets per lookahead window: a host's NIC
    can emit up to bw_up * min_jump bytes between exchanges, so the
    outbox (per-window emit budget) must cover that or the NIC defers to
    the next window and throughput is artificially capped. Destination
    fan-in gets 2x that budget; event queues must hold the inbound burst
    plus timers/wakes. Capacities are clamped so memory stays bounded at
    large H (beyond the clamp the NIC deferral keeps results exact, just
    reflecting genuine queueing).
    """
    from ..core.constants import TCP_MSS

    H = scenario.total_hosts()
    min_jump = topo.min_latency_ns or DEFAULT_MIN_TIME_JUMP

    bw = 0
    for idx, name, spec in scenario.expand_hosts():
        bw = max(bw, spec.bandwidth_up or 0, spec.bandwidth_down or 0)
    if topo.v_bw_up_bytes.size:
        bw = max(bw, int(topo.v_bw_up_bytes.max()),
                 int(topo.v_bw_down_bytes.max()))
    if bw <= 0:
        bw = 128 * 1024 * 1024

    pkts_per_window = (bw * min_jump) // (TCP_MSS * 10**9) + 1

    def pow2(n, lo, hi):
        v = lo
        while v < n and v < hi:
            v *= 2
        return v

    # Memory/pass-cost budget: the burst-sized caps assume every host
    # can saturate its link simultaneously, which at 100k+ hosts would
    # allocate queue arrays in the GBs and make every lockstep pass
    # scan them. Cap the total slot budget (power-of-two bounds so
    # pow2 cannot overshoot); outbox overflow defers to the next
    # window (exact), and the event queue ALWAYS keeps timer/wake
    # headroom above the inbound budget, whatever the clamp says —
    # inbound bursts beyond incap are genuine queue drops, counted.
    def pow2_floor(n):
        return 1 << max(n, 1).bit_length() - 1

    slot_budget = 1 << 24
    hi_q = max(32, min(1024, pow2_floor(slot_budget // max(H, 1))))
    hi_ob = max(16, min(512, pow2_floor(slot_budget // (4 * max(H, 1)))))

    obcap = pow2(int(pkts_per_window * 5 // 4), 16, hi_ob)
    incap = pow2(2 * obcap, 32, 2 * hi_ob)
    qcap = max(pow2(incap + 32, 32, hi_q), incap + 32)
    return EngineConfig(num_hosts=H, qcap=qcap, scap=16, obcap=obcap,
                        incap=incap, txqcap=16)


class Simulation:
    """Build and run one scenario on the JAX engine."""

    def __init__(self, scenario: Scenario, topology: Topology = None,
                 engine_cfg: EngineConfig = None, seed: int = None):
        self.scenario = scenario
        seed = scenario.seed if seed is None else seed
        self.seed = seed

        src = topology or scenario.topology_graphml or scenario.topology_path
        self.topo = src if isinstance(src, Topology) else build_topology(src)

        H = scenario.total_hosts()
        self.cfg = engine_cfg or auto_engine_config(scenario, self.topo)
        assert self.cfg.num_hosts == H

        # --- register hosts: DNS, attachment, apps (reference
        # _master_registerHosts -> slave_addNewVirtualHost analogue) ---
        self.dns = DNS()
        names, hints = [], []
        for idx, name, spec in scenario.expand_hosts():
            names.append(name)
            hints.append((spec.ip_hint, spec.geocode_hint, spec.type_hint))
            self.dns.register(idx, name, spec.ip_hint if spec.quantity == 1 else None)
        self.host_names = names

        vertex = attach_hosts(self.topo, hints, seed=seed)

        bw_up = np.zeros(H, dtype=np.int64)
        bw_down = np.zeros(H, dtype=np.int64)
        nic_buf = np.full(H, INTERFACE_BUFFER_SIZE, dtype=np.int64)
        cpu_cost = np.zeros(H, dtype=np.int64)
        cpu_threshold = np.full(H, -1, dtype=np.int64)
        rcvbuf0 = np.full(H, -1, dtype=np.int64)   # -1 = autotune
        sndbuf0 = np.full(H, -1, dtype=np.int64)
        # process slots: the reference's per-host process LIST
        # (shd-configuration.h:36-95, slave_addNewVirtualProcess
        # shd-slave.c:293) — e.g. a Tor host runs tor + tgen together
        PP = max((len(s.processes) for _, _, s in
                  scenario.expand_hosts() if s.processes), default=1)
        PP = max(PP, 1)
        if self.cfg.procs_per_host < PP:
            import dataclasses as _dc
            self.cfg = _dc.replace(self.cfg, procs_per_host=PP)
        PP = self.cfg.procs_per_host
        app_kind = np.zeros((H, PP), dtype=np.int32)
        app_cfg = np.zeros((H, PP, 8), dtype=np.int64)
        start_times = np.zeros((H, PP), dtype=np.int64)
        has_app = np.zeros((H, PP), dtype=bool)
        pcap_on = np.zeros(H, dtype=bool)

        from ..apps.tgen import TgenTables
        tgen_tables = TgenTables()
        hosted_specs = []
        for idx, name, spec in scenario.expand_hosts():
            v = vertex[idx]
            bw_up[idx] = spec.bandwidth_up or self.topo.v_bw_up_bytes[v] or 1 << 40
            bw_down[idx] = spec.bandwidth_down or self.topo.v_bw_down_bytes[v] or 1 << 40
            if spec.interface_buffer:
                nic_buf[idx] = spec.interface_buffer
            if spec.socket_recv_buffer:
                rcvbuf0[idx] = spec.socket_recv_buffer
            if spec.socket_send_buffer:
                sndbuf0[idx] = spec.socket_send_buffer
            pcap_on[idx] = spec.pcap
            if spec.cpu_frequency:
                # reference semantics (shd-cpu.c:16-44): cost scales by
                # rawFrequency / hostFrequency; precision-round here at
                # build (the device then only adds a constant).
                ratio = (scenario.cpu_raw_frequency_khz /
                         max(spec.cpu_frequency, 1))
                cost = int(scenario.cpu_event_cost_ns * ratio)
                prec = scenario.cpu_precision_ns
                if prec and prec > 0:
                    cost = ((cost + prec // 2) // prec) * prec
                if cost == 0:
                    import sys as _sys
                    _sys.stderr.write(
                        f"shadow_tpu: warning: host {name!r} sets "
                        f"cpufrequency but its rounded event cost is 0 "
                        f"(precision {prec}ns) — CPU model inactive "
                        "for it\n")
                cpu_cost[idx] = cost
                cpu_threshold[idx] = scenario.cpu_threshold_ns
            for p, proc in enumerate(spec.processes):
                kind, cfg_words = compile_app(proc.plugin, proc.arguments,
                                              self.dns, H,
                                              tgen_tables=tgen_tables)
                app_kind[idx, p] = kind
                app_cfg[idx, p] = cfg_words
                start_times[idx, p] = proc.start_time
                has_app[idx, p] = True
                if proc.plugin.startswith("hosted:"):
                    # a hosted process may share its host with modeled
                    # processes (the reference's canonical tor+tgen
                    # host shape, shd-configuration.h:36-95): the op
                    # replay stamps the hosted slot so sockets wake it
                    # (hosting/bridge.py). One hosted process per host:
                    # the wake-ring records carry no process id, so
                    # two hosted apps on one host would be ambiguous.
                    if any(i == idx for i, _, _, _, _ in hosted_specs):
                        raise NotImplementedError(
                            f"host {name!r} declares two hosted "
                            "processes; at most one per host (modeled "
                            "processes alongside are fine)")
                    hosted_specs.append(
                        (idx, p, name, proc.plugin[len("hosted:"):],
                         proc.arguments))
        # gossip relay draws target uniformly random ids in [0, n);
        # in a mixed scenario any non-gossip id inside that range eats
        # its datagrams silently — validate here, where the whole
        # scenario is visible (apps/compile.py only sees one process).
        from ..apps.base import APP_GOSSIP as _APP_GOSSIP
        gossip_mask = ((app_kind == _APP_GOSSIP) & has_app).any(axis=1)
        if gossip_mask.any():
            gsel = (app_kind == _APP_GOSSIP) & has_app
            n_draw = int(app_cfg[gsel, 0].max())
            bad = int((~gossip_mask[:n_draw]).sum())
            if bad:
                import sys as _sys
                _sys.stderr.write(
                    f"shadow_tpu: warning: gossip peer range n={n_draw} "
                    f"covers {bad} non-gossip host id(s); their relay "
                    "datagrams are silently dropped — pass an explicit "
                    "n= and put the gossip hosts first\n")
        tg_nodes, tg_peers, tg_pool, tg_edges = tgen_tables.arrays()
        if tgen_tables.sync_slots > self.cfg.synccap:
            import dataclasses as _dc
            self.cfg = _dc.replace(self.cfg,
                                   synccap=tgen_tables.sync_slots)

        # Dead-branch pruning (see EngineConfig): record which app kinds
        # exist and whether TCP can be opened at all.
        if self.cfg.app_kinds is None:
            import dataclasses as _dc
            from ..apps.base import (APP_TGEN, APP_BULK, APP_BULK_SERVER,
                                     APP_HOSTED, APP_SOCKS_CLIENT,
                                     APP_SOCKS_PROXY)
            kinds = tuple(sorted(set(
                int(k) for k in app_kind.reshape(-1).tolist())))
            tcp_kinds = {APP_TGEN, APP_BULK, APP_BULK_SERVER, APP_HOSTED,
                         APP_SOCKS_CLIENT, APP_SOCKS_PROXY}
            self.cfg = _dc.replace(
                self.cfg, app_kinds=kinds,
                uses_tcp=bool(tcp_kinds & set(kinds)))

        # CPU-hosted apps (hosting/): real app code bridged per window
        self.hosting = None
        if hosted_specs:
            from ..hosting.api import lookup
            from ..hosting.runtime import HostingRuntime
            apps = {idx: lookup(app_name)(args)
                    for idx, _, _, app_name, args in hosted_specs}
            hnames = {idx: hname for idx, _, hname, _, _ in hosted_specs}
            procs = {idx: p for idx, p, _, _, _ in hosted_specs}
            # zero-arg factories so a fault-injection restart
            # (engine.faults host_up) can respawn a FRESH instance
            factories = {
                idx: (lambda an=app_name, ar=args: lookup(an)(ar))
                for idx, _, _, app_name, args in hosted_specs}
            self.hosting = HostingRuntime(apps, hnames, self.dns, seed,
                                          procs=procs,
                                          factories=factories)
            if self.cfg.scap > 256:
                # hosting packs socket slots into 8-bit handle fields
                # (hosting/bridge.py op_pipe_open) — larger tables
                # would silently alias pipe halves
                raise ValueError(
                    f"hosted apps require scap <= 256 (got "
                    f"{self.cfg.scap}): pipe handles pack the slot "
                    "into 8 bits")
            if self.cfg.hostedcap < 32:
                # concurrent wakes within one window (e.g. several
                # accepts) must all fit the ring or callbacks are lost
                import dataclasses as _dc
                self.cfg = _dc.replace(self.cfg, hostedcap=32)

        # --- fault schedule (engine.faults): compiled at build so bad
        # configs fail here, executed by the run loop at exact sim
        # times (deterministic; dual same-seed runs bit-identical) ---
        self.injector = None
        if scenario.faults:
            from .faults import FaultInjector, compile_faults
            name_to_idx = {name: idx
                           for idx, name, _ in scenario.expand_hosts()}
            events = compile_faults(scenario.faults, name_to_idx, vertex,
                                    topo=self.topo,
                                    stop_time=scenario.stop_time)
            procs_of_host = {
                int(h): [int(p) for p in np.flatnonzero(has_app[h])]
                for h in range(H) if has_app[h].any()}
            self.injector = FaultInjector(
                events, self.topo.latency_ns, self.topo.reliability,
                vertex, procs_of_host, names)
            self.injector.hosting = self.hosting

        self.hp = HostParams(
            hid=jnp.arange(H, dtype=jnp.int32),
            rng_stream=R.stream_of(seed & 0xFFFFFFFF, R.DOMAIN_HOST,
                                   jnp.arange(H, dtype=jnp.int32)),
            vertex=jnp.asarray(vertex, dtype=jnp.int32),
            bw_up=jnp.asarray(bw_up),
            bw_down=jnp.asarray(bw_down),
            app_kind=jnp.asarray(app_kind),
            app_cfg=jnp.asarray(app_cfg),
            nic_buf=jnp.asarray(nic_buf),
            cpu_cost=jnp.asarray(cpu_cost),
            cpu_threshold=jnp.asarray(cpu_threshold),
            rcvbuf0=jnp.asarray(rcvbuf0),
            sndbuf0=jnp.asarray(sndbuf0),
            pcap_on=jnp.asarray(pcap_on),
        )

        if bool((cpu_cost > 0).any()) and not self.cfg.cpu_model:
            import dataclasses as _dc
            self.cfg = _dc.replace(self.cfg, cpu_model=True)

        # pcap capture needs the trace ring sized for a window chunk;
        # bound the chunk so the ring stays modest (capture implies a
        # drain to the host per chunk anyway)
        if pcap_on.any() and self.cfg.tracecap == 0:
            import dataclasses as _dc
            chunk = min(self.cfg.chunk_windows, 16)
            self.cfg = _dc.replace(
                self.cfg, chunk_windows=chunk,
                tracecap=chunk * (self.cfg.obcap + self.cfg.incap))

        min_jump = self.topo.min_latency_ns or DEFAULT_MIN_TIME_JUMP
        self.sh = make_shared(self.topo.latency_ns, self.topo.reliability,
                              R.root_key(seed), scenario.stop_time, min_jump,
                              seed=seed, cc_kind=self.cfg.cc_kind,
                              tgen_nodes=tg_nodes, tgen_peers=tg_peers,
                              tgen_pool=tg_pool, tgen_edges=tg_edges,
                              host_vertex=vertex,
                              host_bw_up=bw_up, host_bw_down=bw_down)

        # --- initial events: process starts (reference process_schedule;
        # one start event per process slot, in slot order) ---
        hosts = alloc_hosts(self.cfg)
        eq_time = np.array(hosts.eq_time)
        eq_kind = np.array(hosts.eq_kind)
        eq_pkt = np.array(hosts.eq_pkt)
        eq_seq = np.array(hosts.eq_seq)
        eq_ctr = np.array(hosts.eq_ctr)
        for p in range(PP):
            idxs = np.flatnonzero(has_app[:, p])
            eq_time[idxs, p] = start_times[idxs, p]
            eq_kind[idxs, p] = EV_APP
            eq_seq[idxs, p] = eq_ctr[idxs]
            eq_pkt[idxs, p, P.ACK] = WAKE_START
            eq_pkt[idxs, p, P.SEQ] = -1
            eq_pkt[idxs, p, P.SRC] = p      # slotless wake: proc slot
            eq_ctr[idxs] += 1
        self.hosts = hosts.replace(
            eq_time=jnp.asarray(eq_time), eq_kind=jnp.asarray(eq_kind),
            eq_seq=jnp.asarray(eq_seq), eq_pkt=jnp.asarray(eq_pkt),
            eq_ctr=jnp.asarray(eq_ctr),
            eq_next=jnp.asarray(eq_time.min(axis=1)))

        self._ran = False

    def effective_chunk(self, digest_every: int = 0) -> int:
        """The chunk the window program ACTUALLY compiles for: 1
        under hosted apps (the CPU tier runs between every window),
        shrunk to the digest cadence so records land on exact window
        boundaries. One definition shared by run(), prewarm() and the
        ``--shape-fingerprint`` probe — if they ever disagreed, the
        pre-warm would silently warm a program no worker loads."""
        chunk = 1 if self.hosting else self.cfg.chunk_windows
        if digest_every:
            chunk = min(chunk, digest_every)
        return chunk

    def prewarm(self, mesh=None, digest_every: int = 0) -> dict:
        """Materialize the window-chunk executable this scenario will
        run — disk-load or compile — WITHOUT executing it: the fleet
        pre-warm entry point (serving.prewarm; CLI ``--prewarm``).

        Builds exactly the program run() would build for the same
        knobs: the chunk shrinks to 1 under hosted apps and to the
        digest cadence when `digest_every` > 0 (run() records on
        exact window boundaries), and a `mesh` pre-warms the sharded
        program for that concrete device assignment. Populates the
        process-wide memory tier (core.jitcache) and — when a
        persistent cache is active (``--aot-cache`` /
        ``SHADOW_TPU_AOT_CACHE``) — the disk tier, so a later worker
        process opens warm. Donation happens at execution, never at
        compilation, so this Simulation still runs afterwards.

        Returns {"fingerprint", "chunk", "shards", "cache_scope"}.
        """
        from ..obs.ledger import fingerprint_of

        if mesh is None:
            hosts, cfg, hp, sh = self.hosts, self.cfg, self.hp, self.sh
            chunk = self.effective_chunk(digest_every)
            from .window import run_windows_aot
            fn = run_windows_aot(cfg, chunk)
            t0 = jnp.min(hosts.eq_next)
        else:
            from ..parallel.shard import (AXIS, device_put_sharded,
                                          run_windows_sharded_aot)
            n = mesh.shape[AXIS]
            hosts, hp, sh, cfg = self._pad_for_mesh(n)
            hosts, hp, sh = device_put_sharded(hosts, hp, sh, mesh)
            chunk = self.effective_chunk(digest_every)
            fn = run_windows_sharded_aot(cfg, chunk, mesh)
            t0 = jax.jit(jnp.min)(hosts.eq_next)
        wend = jnp.where(t0 == SIMTIME_MAX, t0, t0 + sh.min_jump)
        fn.warm(hosts, hp, sh, t0, wend)
        return {"fingerprint": fingerprint_of(cfg), "chunk": chunk,
                "shards": 1 if mesh is None else mesh.size,
                "cache_scope": fn.cache_scope}

    def _pad_for_mesh(self, n_shards: int):
        """Pad the host dimension to a multiple of the shard count with
        inert hosts (empty queues, no app). Inert rows never emit or
        receive, so stats[:H] are bit-identical to the unpadded run."""
        import dataclasses as _dc

        H = self.cfg.num_hosts
        Hp = ((H + n_shards - 1) // n_shards) * n_shards
        if Hp == H:
            return self.hosts, self.hp, self.sh, self.cfg
        cfg = _dc.replace(self.cfg, num_hosts=Hp)
        fresh = alloc_hosts(cfg)
        hosts = jax.tree.map(
            lambda a, b: jnp.concatenate([a, b[H:]], axis=0),
            self.hosts, fresh)
        pad = Hp - H
        hp = HostParams(
            hid=jnp.concatenate([self.hp.hid,
                                 jnp.arange(H, Hp, dtype=jnp.int32)]),
            rng_stream=jnp.concatenate([self.hp.rng_stream,
                                        jnp.zeros(pad, jnp.uint32)]),
            vertex=jnp.concatenate([self.hp.vertex,
                                    jnp.zeros(pad, jnp.int32)]),
            bw_up=jnp.concatenate([self.hp.bw_up,
                                   jnp.ones(pad, jnp.int64)]),
            bw_down=jnp.concatenate([self.hp.bw_down,
                                     jnp.ones(pad, jnp.int64)]),
            app_kind=jnp.concatenate([
                self.hp.app_kind,
                jnp.zeros((pad,) + self.hp.app_kind.shape[1:],
                          jnp.int32)]),
            app_cfg=jnp.concatenate([
                self.hp.app_cfg,
                jnp.zeros((pad,) + self.hp.app_cfg.shape[1:],
                          jnp.int64)]),
            nic_buf=jnp.concatenate([self.hp.nic_buf,
                                     jnp.ones(pad, jnp.int64)]),
            cpu_cost=jnp.concatenate([self.hp.cpu_cost,
                                      jnp.zeros(pad, jnp.int64)]),
            cpu_threshold=jnp.concatenate([self.hp.cpu_threshold,
                                           jnp.full((pad,), -1,
                                                    jnp.int64)]),
            rcvbuf0=jnp.concatenate([self.hp.rcvbuf0,
                                     jnp.full((pad,), -1, jnp.int64)]),
            sndbuf0=jnp.concatenate([self.hp.sndbuf0,
                                     jnp.full((pad,), -1, jnp.int64)]),
            pcap_on=jnp.concatenate([self.hp.pcap_on,
                                     jnp.zeros(pad, jnp.bool_)]),
        )
        sh = self.sh.replace(
            host_vertex=jnp.concatenate(
                [self.sh.host_vertex, jnp.zeros(pad, jnp.int32)]),
            host_bw_up=jnp.concatenate(
                [self.sh.host_bw_up, jnp.ones(pad, jnp.int64)]),
            host_bw_down=jnp.concatenate(
                [self.sh.host_bw_down, jnp.ones(pad, jnp.int64)]))
        return hosts, hp, sh, cfg

    def run(self, verbose: bool = False, mesh=None, heartbeat_s: float = 0,
            logger=None, checkpoint_path: str = None,
            checkpoint_every_s: float = 0, checkpoint_keep: int = 0,
            resume_from: str = None, pcap_dir: str = None,
            trace: str = None, metrics: str = None,
            digest: str = None, digest_every: int = 0,
            digest_context: dict = None, digest_rewind: bool = True,
            resume_unchecked: bool = False,
            netscope: str = None, passcope: str = None) -> SimReport:
        """Run to the stop time. With `mesh` (a 1-D jax Mesh over a
        "hosts" axis) the window program runs under shard_map with the
        host dimension block-sharded — same results, N chips.
        `heartbeat_s` > 0 emits tracker heartbeats on that sim-time
        interval (obs.tracker). `checkpoint_path` + `checkpoint_every_s`
        snapshot state periodically into a crash-safe rotating store
        (engine.checkpoint.CheckpointStore: atomic tmp+fsync+rename
        writes, content hashes, the last `checkpoint_keep` snapshots —
        default 3 — and a ``latest`` pointer); `resume_from` restores
        a snapshot (a concrete .npz, or the store base to resolve the
        newest valid one with corrupt-head fallback). Resume covers
        fault schedules (the snapshot stamps the injector's schedule
        position and link-episode bookkeeping is replayed) and hosted
        apps (checkpointed runs journal each child's shim protocol
        stream; resume respawns children and fast-forwards them by
        deterministic replay — docs/durability.md).

        `netscope` streams the network observatory's per-chunk
        time-series (obs.netscope: stat totals/deltas, active
        connections, histogram deltas) as JSON lines to that path —
        requires ``EngineConfig.netscope`` (the device histograms are
        allocated at Simulation construction). With the knob on and no
        path, records are kept in memory only; either way
        ``SimReport.network`` carries the exact percentile read-outs
        and, with metrics enabled, ``net.*`` gauges are published.

        `passcope` profiles the first few chunks with jax.profiler
        into that directory and decodes the xplane dump into a
        per-pass DEVICE-time table (obs.passcope: jax.named_scope
        labels on every jitted pass, names matching the stateflow
        entries) — ``SimReport.device_phases``; unset, the
        ``SHADOW_TPU_PASSCOPE`` env var enables it the same way.
        Lockstep-occupancy telemetry (``SimReport.occupancy``:
        utilization/waste from the drain's own pass accounting) is
        always on — it reads data the run already returns. Profiling
        is observation only: a passcope run's digest chain is
        byte-identical to a plain run's, and a refusing backend
        degrades to ``available: False``, never a crash.

        `trace` writes a Chrome trace-event JSON timeline (obs.trace:
        per-chunk spans with sim-time args, compile/hosting/tracker/
        pcap/checkpoint spans). `metrics` writes a final metrics.json
        snapshot (obs.metrics) plus per-chunk JSON lines at
        ``<metrics>.chunks.jsonl``.

        `digest` appends a determinism digest chain (obs.digest: one
        JSON line of per-section state hashes every `digest_every`
        windows — default obs.digest.DEFAULT_EVERY — plus at every
        fault boundary and at the end of the run) and writes a
        companion ``<digest>.manifest.json``; diff two chains with
        ``tools/divergence.py``. `digest_context` folds caller context
        (CLI argv, config path) into the manifest. Cadences below the
        chunk size shrink
        the effective chunk so records land on exact window
        boundaries. `resume_unchecked` downgrades the checkpoint
        fingerprint check on `resume_from` to a warning (divergence
        bisection replays under a clamped stop time). On
        `resume_from`, `digest` is by default treated as the crashed
        attempt's own chain file and rewound to the snapshot's stamped
        position; pass `digest_rewind=False` when the chain is a FRESH
        file recording the resumed tail only (divergence replays).

        Trace, metrics and digest install their process-global
        recorders for the duration of this run only; with all unset
        the chunk loop pays a few boolean checks per chunk. If a
        recorder is ALREADY installed process-wide (an outer harness
        like bench.py holding one timeline open across runs), the
        path argument is ignored — this run's records flow into the
        existing recorder and a warning says so. Under a multi-process
        mesh every process collects (the per-chunk stats fetch is a
        collective and must run uniformly) but only process 0 writes
        files.
        """
        assert not self._ran, "Simulation objects are single-use"
        self._ran = True
        # a preemption requested before this run started belongs to a
        # previous run in this process (request_preempt is process-
        # wide); a stale flag must not kill this run at its first
        # boundary. The tiny window between a SIGTERM handler firing
        # and this clear is covered by the preemptor's escalation
        # (fleet workers SIGKILL after a grace period).
        _PREEMPT.clear()
        from ..obs import digest as DG
        from ..obs import metrics as MT
        from ..obs import trace as TR
        from ..parallel import dist
        own_tr = own_mt = own_dg = False
        if digest is not None:
            if not DG.ENABLED:
                # under a multi-process mesh every process runs the
                # recorder state machine (the per-record state pull is
                # a collective, so cadence must agree everywhere) but
                # only process 0 writes the chain/manifest files
                DG.install(digest,
                           every=digest_every or DG.DEFAULT_EVERY,
                           context=digest_context,
                           writer=(not dist.is_multiprocess()
                                   or jax.process_index() == 0))
                own_dg = True
            else:
                import sys as _sys
                _sys.stderr.write(
                    "shadow_tpu: warning: a digest recorder is already "
                    "installed process-wide; the path passed to run() "
                    "is ignored and this run's records extend the "
                    "existing chain\n")
        if trace is not None or metrics is not None:
            writer = (not dist.is_multiprocess()
                      or jax.process_index() == 0)
            if trace is not None and not TR.ENABLED:
                TR.install(trace if writer else None)
                own_tr = True
            if metrics is not None and not MT.ENABLED:
                MT.install(metrics if writer else None,
                           jsonl_path=(metrics + ".chunks.jsonl"
                                       if writer else None))
                own_mt = True
            if ((trace is not None and not own_tr) or
                    (metrics is not None and not own_mt)):
                import sys as _sys
                _sys.stderr.write(
                    "shadow_tpu: warning: a trace/metrics recorder is "
                    "already installed process-wide; the path passed "
                    "to run() is ignored and this run's records flow "
                    "into the existing recorder\n")
        try:
            return self._run_impl(
                verbose=verbose, mesh=mesh, heartbeat_s=heartbeat_s,
                logger=logger, checkpoint_path=checkpoint_path,
                checkpoint_every_s=checkpoint_every_s,
                checkpoint_keep=checkpoint_keep,
                resume_from=resume_from, pcap_dir=pcap_dir,
                resume_unchecked=resume_unchecked,
                digest_rewind=digest_rewind, netscope=netscope,
                passcope=passcope)
        finally:
            if own_tr:
                TR.finish()
            if own_mt:
                MT.finish()
            if own_dg:
                DG.finish()

    def _run_impl(self, verbose, mesh, heartbeat_s, logger,
                  checkpoint_path, checkpoint_every_s, resume_from,
                  pcap_dir, resume_unchecked=False,
                  checkpoint_keep=0, digest_rewind=True,
                  netscope=None, passcope=None) -> SimReport:
        from ..obs import digest as DG
        from ..obs import metrics as MT
        from ..obs import passcope as PC
        from ..obs import trace as TR
        # hot-loop observability guard: with --trace/--metrics off the
        # per-chunk cost of the whole obs layer is this one boolean
        obs_on = TR.ENABLED or MT.ENABLED
        dg = DG.RECORDER if DG.ENABLED else None
        if TR.ENABLED:
            _s0 = TR.TRACER.now()
        H = self.cfg.num_hosts

        from ..parallel import dist
        multiproc = dist.is_multiprocess()
        if multiproc:
            if self.hosting:
                raise NotImplementedError(
                    "hosted apps + multi-process mesh not supported")
            if self.injector is not None:
                raise NotImplementedError(
                    "fault injection + multi-process mesh not "
                    "supported (host-fault surgery needs addressable "
                    "state)")
            # digest recording, checkpoint/resume and pcap ARE
            # supported on a multi-process mesh — including resume +
            # digest (the last PR 5 gate, lifted): every process reads
            # the chain file in DigestRecorder.rewind to refold the
            # kept prefix and re-arm the cadence in lockstep (the
            # per-record state pull is a collective, so all processes
            # must agree when a record is due), while only process 0 —
            # the writer — truncates and later appends; the
            # truncation is an atomic os.replace, so a peer reading
            # concurrently sees a file whose first n records are the
            # kept prefix either way. Each allgathers the relevant
            # state per record/chunk (the documented DCN-hop price of
            # these debug/durability paths); every process must be
            # able to read the snapshot AND chain paths on resume
            # (shared storage).

        tracker = None
        if heartbeat_s:
            from ..obs.tracker import Tracker
            tracker = Tracker(int(heartbeat_s * 10**9), self.host_names,
                              logger)

        from ..obs import netscope as NSC
        nsrec = None
        if self.cfg.netscope:
            # with the knob on, records always accumulate in memory
            # (SimReport.network reads them); the path adds the JSONL
            # stream. Under a multi-process mesh every process samples
            # (the hist pull is a collective) but only process 0 writes.
            nsrec = NSC.NetScope(
                netscope, writer=(not multiproc
                                  or jax.process_index() == 0))
        elif netscope:
            raise ValueError(
                "run(netscope=...) requires EngineConfig.netscope=True "
                "(the device histograms are allocated at Simulation "
                "construction)")

        # pass-time observatory (obs.passcope): jax.profiler around
        # the first few chunks, decoded into a per-pass device-time
        # table keyed by the named_scope labels the window program
        # carries (= the stateflow entry names). Observation only —
        # the compiled program and the digest chain are untouched.
        # Under a multi-process mesh only process 0 traces.
        import os as _os
        pc_dir = (passcope if passcope is not None
                  else _os.environ.get("SHADOW_TPU_PASSCOPE"))
        if pc_dir == "":
            pc_dir = "passcope_trace"
        pscope = None
        if pc_dir and (not multiproc or jax.process_index() == 0):
            pscope = PC.Capture(pc_dir)

        pcap = None
        pcap_on_run = bool(self.cfg.tracecap) and pcap_dir is not None
        if pcap_on_run and (not multiproc or jax.process_index() == 0):
            # under a multi-process mesh only process 0 writes files;
            # the drain below allgathers the rings to it
            from ..obs.pcap import PcapWriter
            traced = np.flatnonzero(np.asarray(self.hp.pcap_on))
            pcap = PcapWriter(pcap_dir, self.host_names,
                              self.dns.ip_array(H), traced)

        from . import checkpoint as ckpt
        fingerprint = ckpt.scenario_fingerprint(self.scenario, self.cfg,
                                                self.seed)
        store = None
        if checkpoint_path:
            store = ckpt.CheckpointStore(checkpoint_path,
                                         keep=checkpoint_keep)
            if self.hosting is not None:
                # checkpointed hosted runs journal every child's shim
                # protocol stream so resume can fast-forward respawned
                # children by deterministic replay (must be armed
                # before any child spawns)
                self.hosting.enable_journal()
        # durability-test crash triggers (SHADOW_TPU_CRASH_SIM_NS /
        # _WALL_S / _GUARD): SIGKILL this process mid-run, exactly a
        # preemption — tests/test_until_complete.py proves the
        # supervised resume is byte-identical
        from .faults import CrashHook
        crash = CrashHook.from_env()

        if dg is not None:
            # run manifest (seed, fingerprint, engine shape, versions,
            # platform, git rev): what makes two chains comparable and
            # a divergence bisect replayable (tools/divergence.py)
            dg.write_manifest(DG.build_manifest(
                self.scenario, self.cfg, self.seed, self.sh,
                self.host_names, dg,
                checkpoint_path=checkpoint_path,
                shards=(1 if mesh is None else mesh.size),
                pcap=pcap_dir is not None,
                faults=self.injector is not None,
                hosted=self.hosting is not None))

        if mesh is None:
            hosts, cfg, hp, sh = self.hosts, self.cfg, self.hp, self.sh
            # hosted chunk-1 + digest-cadence shrink: the one
            # shared definition (a digest run is its own AOT entry,
            # plain runs are untouched)
            chunk = self.effective_chunk(dg.every if dg else 0)
            per_chip_h = cfg.num_hosts

            def step(hosts, sh_seg, ws, we):
                return run_windows(hosts, hp, sh_seg, ws, we, cfg, chunk)
        else:
            from ..parallel.shard import (AXIS, device_put_sharded,
                                          run_windows_sharded)
            n = mesh.shape[AXIS]
            hosts, hp, sh, cfg = self._pad_for_mesh(n)
            hosts, hp, sh = device_put_sharded(hosts, hp, sh, mesh)
            per_chip_h = cfg.num_hosts // n
            # hosted + mesh: the wake rings are per-host rows, so they
            # shard with the rest of the state; the drain loop's ring-
            # overflow pause is shard-local (each shard pauses its own
            # drain), and the CPU tier reads/writes the global arrays
            # between chunks (single-process mesh only — the multiproc
            # gate above still applies). chunk=1: hosted apps need the
            # CPU between every window.
            chunk = self.effective_chunk(dg.every if dg else 0)

            def step(hosts, sh_seg, ws, we):
                return run_windows_sharded(hosts, hp, sh_seg, ws, we,
                                           cfg, chunk, mesh)

        # the REAL stop time, a loop constant: with a fault schedule
        # the per-segment device stop_time is clamped to the next
        # fault (sh_seg below), so every host-side comparison must use
        # this, not the segment scalar
        stop_ns = int(sh.stop_time)
        inj = self.injector

        def dg_record(kind, window, sim_ns):
            # one digest-chain sample (obs.digest): the state pull is
            # the whole cadence cost, accounted as a span + metrics
            _d0 = TR.TRACER.now() if TR.ENABLED else None
            pulled = hosts
            if multiproc:
                # materialize the GLOBAL state on every process (the
                # collective must run on all of them — which is why
                # the recorder's cadence state machine runs
                # everywhere); only process 0 writes the record
                from jax.experimental import multihost_utils
                pulled = multihost_utils.process_allgather(hosts,
                                                           tiled=True)
            hosted = (self.hosting.digest_state()
                      if self.hosting is not None else None)
            dg.record(pulled, H, window, sim_ns, kind, hosted=hosted)
            if TR.ENABLED:
                TR.TRACER.complete("digest.record", _d0,
                                   args={"window": window,
                                         "kind": kind})
            if MT.ENABLED:
                reg = MT.REGISTRY
                reg.counter("digest.records").inc()
                reg.gauge("digest.last_window").set(window)
                reg.gauge("digest.bytes_hashed").set(dg.bytes_hashed)

        # cost-model bookkeeping (SimReport.cost_model): pass mix per
        # compaction rung + per-row state bytes
        from .window import pass_labels, sparse_batch
        _pl = pass_labels(cfg, per_chip_h)
        _pass_labels = [lbl for lbl, _ in _pl]
        _pass_sizes = [size for _, size in _pl]
        pass_acc = np.zeros(len(_pass_labels), np.int64)
        # lockstep occupancy (obs.passcope): lane utilization from the
        # SAME pass accounting — pure host arithmetic over the rung
        # counts the drain already returns, so it is always on
        _batch = sparse_batch(cfg)

        def occ_now(events):
            return PC.occupancy(
                {lbl: (size, int(nn)) for lbl, size, nn
                 in zip(_pass_labels, _pass_sizes, pass_acc)},
                events, _batch)
        # shard-imbalance accounting (VERDICT r5 missing #4 — the
        # prerequisite for load-aware placement): the sharded window
        # program returns a PER-SHARD rung mix, and per chunk one
        # jitted reduction yields per-shard cumulative events +
        # currently-active host counts (multiproc-safe: replicated
        # outputs, the eager-t0 pattern above). Published as shard.*
        # gauges -> the metrics.json `shards` section.
        n_shards = 1 if mesh is None else cfg.num_hosts // per_chip_h
        shard_pass_acc = (np.zeros((n_shards, len(_pass_labels)),
                                   np.int64) if n_shards > 1 else None)
        _shard_load = None
        # per-shard load is a SINGLE-process feature (like the [S,NR]
        # pass mix): on a multi-process mesh the reduction's [S]
        # output inherits the host axis's sharding, so each process
        # could not np.asarray it (non-addressable shards)
        if MT.ENABLED and n_shards > 1 and jax.process_count() == 1:
            _shard_load = jax.jit(lambda st, eqn: (
                jnp.sum(st[:, defs.ST_EVENTS].reshape(n_shards, -1),
                        axis=1),
                jnp.sum((eqn < SIMTIME_MAX).reshape(n_shards, -1),
                        axis=1, dtype=jnp.int32)))
        # per-pass traffic covers the drain's HOT working set only:
        # the hot/cold split (state.hot_fields) keeps cold columns out
        # of every rung gather/scatter and loop carry, so modeling
        # them in the pass cost would overstate HBM traffic — on the
        # UDP tiers by more than half the socket table
        _hot = hot_fields(cfg)
        row_bytes = sum(
            int(np.prod(getattr(hosts, f).shape[1:]))
            * getattr(hosts, f).dtype.itemsize
            for f in _hot)

        # memory observatory (obs.memscope): per-chunk device-buffer
        # high-water sampling — real device memory stats where the
        # backend provides them (per device, so a mesh run's
        # per_device list IS the per-shard watermark), RSS fallback on
        # CPU. Host-side reads only, so a memscope-enabled run's
        # digest chain is byte-identical to a plain run's.
        from ..obs import memscope as MS
        if mesh is None:
            wm = MS.Watermark()
        else:
            from ..parallel.shard import mesh_local_devices
            wm = MS.Watermark(mesh_local_devices(mesh))

        if multiproc:
            # eager reductions cannot run on non-addressable global
            # arrays; a jitted min yields a replicated (addressable)
            # scalar on every process
            t0 = jax.jit(jnp.min)(hosts.eq_next)
        else:
            t0 = jnp.min(hosts.eq_next)
        wstart = t0
        wend = jnp.where(t0 == SIMTIME_MAX, t0, t0 + sh.min_jump)

        total_windows = 0
        if resume_from:
            snap = ckpt.load(resume_from, hosts, fingerprint,
                             strict=not resume_unchecked)
            hosts = snap.hosts
            wstart = jnp.int64(snap.wstart)
            wend = jnp.int64(snap.wend)
            total_windows = snap.windows
            if mesh is not None:
                # hp/sh are already placed; only the restored Hosts
                # arrays need (re-)sharding
                from ..parallel.shard import put_hosts
                hosts = put_hosts(hosts, mesh)
            if inj is not None:
                # the schedule is a pure function of the config, so
                # the snapshot records only the POSITION: fast_forward
                # replays the link-episode bookkeeping (host-fault
                # effects already live in the restored arrays) and
                # rebuilds the Shared lat/rel tables exactly
                if snap.fault_idx < 0:
                    raise ValueError(
                        "snapshot records no fault schedule position "
                        "(__fault_idx__); it was taken by a run "
                        "without this fault config — refusing to "
                        "resume into one")
                sh = inj.fast_forward(snap.fault_idx, sh)
                if mesh is not None:
                    from ..parallel.shard import put_shared
                    sh = put_shared(sh, mesh)
            if self.hosting is not None:
                if snap.hosted_blob is None:
                    raise ValueError(
                        "scenario hosts real processes but the "
                        "snapshot has no hosted sidecar "
                        "(<snapshot>.npz.hosted) — it was taken "
                        "without hosted-app support")
                # rebuild the hosted tier and fast-forward respawned
                # children by journal replay (hosting.runtime.restore)
                self.hosting.restore(snap.hosted_blob)

        if dg is not None:
            # the cadence clock is per-run: a recorder spanning
            # several runs (outer harness) or a resume jump must not
            # inherit the previous run's next_due. A resumed run
            # REWINDS the chain the crashed attempt left to exactly
            # the position the snapshot stamped: the kept prefix is
            # identical to a fresh run's (determinism), later records
            # are re-produced live, so the final chain is
            # byte-identical to an uninterrupted run's. A divergence
            # replay resumes the SIMULATION from a snapshot but
            # records a fresh chain of the tail only — it opts out
            # via digest_rewind=False (the snapshot's stamped count
            # belongs to the original run's chain, not this file)
            if (resume_from and snap.digest_records >= 0
                    and digest_rewind):
                dg.rewind(snap.digest_records, snap.digest_chain)
                if dg.due(total_windows):
                    # the crashed attempt died between this snapshot
                    # and the cadence record due at the very same
                    # boundary — emit it now from the restored state,
                    # exactly where the uninterrupted run did
                    dg_record("cadence", total_windows,
                              min(int(wstart), stop_ns))
            else:
                dg.begin_run(total_windows)

        if checkpoint_path and not checkpoint_every_s:
            raise ValueError(
                "checkpoint_path requires checkpoint_every_s > 0 "
                "(otherwise no snapshot would ever be written)")
        next_ckpt = (int(checkpoint_every_s * 10**9)
                     if checkpoint_every_s else 0)
        ckpt_at = int(wstart) + next_ckpt if next_ckpt else None

        # fleet liveness heartbeat (docs/fleet.md): checkpoints and
        # digests are SIM-paced, so on a slow box a healthy run can
        # legitimately write nothing for a long wall time — the fleet
        # watchdog needs a WALL-paced progress signal. Under a fleet
        # worker (SHADOW_TPU_FLEET_RUN_DIR) the loop touches
        # <run_dir>/heartbeat once per chunk; one tiny write per
        # device dispatch, nothing off the fleet path.
        import os as _os
        _hb_dir = _os.environ.get("SHADOW_TPU_FLEET_RUN_DIR")
        _hb_path = (_os.path.join(_hb_dir, "heartbeat")
                    if _hb_dir else None)

        def heartbeat(ws_now):
            if _hb_path is None:
                return
            try:
                with open(_hb_path, "w") as f:
                    f.write(f"{ws_now}\n")
            except OSError:
                pass           # liveness is best-effort, never fatal

        def save_snapshot(ws_now):
            # one snapshot at the current chunk boundary — the cadence
            # path and the cooperative-preemption path share it. Stamps
            # the injector's schedule position and the digest chain
            # position (record count + running hash): resume re-arms
            # both exactly, so records and fault applications landing
            # AFTER this save in the same loop iteration are
            # re-produced live, never duplicated or lost.
            to_save = hosts
            if multiproc:
                # materialize the GLOBAL state on every process (the
                # collective must run on all of them), then only
                # process 0 touches the filesystem
                from jax.experimental import multihost_utils
                to_save = multihost_utils.process_allgather(
                    hosts, tiled=True)
            if not multiproc or jax.process_index() == 0:
                store.save(
                    to_save, ws_now, int(wend), total_windows,
                    fingerprint,
                    fault_idx=(inj.i if inj is not None else -1),
                    digest_records=(len(dg.records)
                                    if dg is not None else -1),
                    digest_chain=(dg.chain_hex
                                  if dg is not None else None),
                    hosted_blob=(self.hosting.snapshot()
                                 if self.hosting is not None
                                 else None))
        if TR.ENABLED:
            # everything up to here: topology/mesh placement, writers,
            # checkpoint fingerprint/restore — the pre-loop cost
            TR.TRACER.complete("run.setup", _s0)
        # the passcope trace arms at the FIRST chunk_done(), after the
        # cold compile — tracing a multi-minute XLA compile is both
        # ruinously slow and useless to the pass table; the HLO
        # metadata plane is emitted at execution time so a post-compile
        # trace still decodes fully (obs.passcope.Capture)
        wall0 = _time.perf_counter()
        first_chunk_wall = None
        chunk_i = 0
        n_chunks = 0     # unconditional (chunk_i only counts with obs
        #   on): the cost model scales the window program's measured
        #   bytes-accessed by how many times the chunk executed
        # jitted once, called per chunk (multiproc pcap ring reset)
        _zeros_like = jax.jit(jnp.zeros_like)
        # per-chunk events total as a jitted reduction: a replicated
        # scalar on every process (the eager-t0 pattern above — eager
        # ops cannot run on non-addressable global arrays) and one
        # column's sum instead of a full stats gather. Padded inert
        # rows never execute events, so the all-rows sum equals [:H].
        _ev_sum = jax.jit(lambda s: jnp.sum(s[:, defs.ST_EVENTS]))
        # resumed runs restore pre-checkpoint ST_EVENTS with the state:
        # baseline the per-chunk delta on it or the first chunk's
        # telemetry would claim the whole pre-checkpoint history
        prev_events = (int(_ev_sum(hosts.stats))
                       if obs_on and resume_from else 0)
        while True:
            heartbeat(int(wstart))
            if _PREEMPT.is_set() and not multiproc:
                # cooperative preemption (request_preempt — SIGTERM
                # under --checkpoint, the fleet worker protocol):
                # persist a snapshot at this exact chunk boundary and
                # stop; ``--resume latest`` continues with zero lost
                # work and — digest rewind — a final chain
                # byte-identical to an uninterrupted run's. Checked at
                # the loop top so natural completion always wins (the
                # loop is only re-entered when work remains).
                # Multi-process meshes ignore the flag: signal
                # delivery is per-process and an asymmetric raise
                # would wedge the collectives — preempt those with
                # SIGKILL + periodic snapshots instead.
                saved = False
                if store is not None:
                    if TR.ENABLED:
                        _k0 = TR.TRACER.now()
                    save_snapshot(int(wstart))
                    saved = True
                    if TR.ENABLED:
                        TR.TRACER.complete("checkpoint.preempt_save",
                                           _k0)
                if MT.ENABLED:
                    MT.REGISTRY.counter("engine.preemptions").inc()
                if self.hosting is not None:
                    # children die with this run; resume respawns and
                    # fast-forwards them from the snapshot's journals
                    self.hosting.shutdown()
                raise Preempted(min(int(wstart), stop_ns), saved)
            # fault segmentation (engine.faults): bound this device
            # segment at the next scheduled fault so the engine
            # executes every event strictly before it, stops, and the
            # injector applies the fault at its exact sim time — the
            # stop_time clamp the window program already honors
            # (window.win_body's we_eff), reused as the fault barrier
            sh_seg = sh
            if inj is not None:
                nf = inj.next_time()
                if nf is not None and nf < stop_ns:
                    sh_seg = sh.replace(stop_time=jnp.int64(nf))
                    if mesh is not None:
                        from ..parallel.shard import put_shared
                        sh_seg = put_shared(sh_seg, mesh)
            if obs_on:
                _ws0 = int(wstart)
                _c0 = _time.perf_counter_ns()
            hosts, wstart, wend, n, pc = step(hosts, sh_seg, wstart,
                                              wend)
            total_windows += int(n)
            pc_np = np.asarray(pc)
            if pc_np.ndim == 2:    # sharded: [n_shards, NR] rung mix
                pass_acc += pc_np.sum(axis=0)
                if shard_pass_acc is not None:
                    shard_pass_acc += pc_np
            else:
                pass_acc += pc_np
            n_chunks += 1
            wm.sample()
            if pscope is not None:
                pscope.chunk_done()   # stops after its chunk budget
            if first_chunk_wall is None:
                # everything after this excludes the cold compile
                first_chunk_wall = _time.perf_counter() - wall0
                if TR.ENABLED:
                    # where the cold XLA build went (the cost model's
                    # "warm" exclusion) — nested inside the first
                    # chunk span so self-times attribute correctly
                    TR.TRACER.complete("compile+first_chunk", _c0)
            ws = int(wstart)
            if self.hosting is not None:
                if TR.ENABLED:
                    _h0 = TR.TRACER.now()
                now = min(ws, stop_ns)
                hosts = self.hosting.step(hosts, hp, sh, now)
                if mesh is not None:
                    # the op-replay program may hand back differently-
                    # placed arrays; the AOT sharded window program
                    # requires its exact input sharding
                    from ..parallel.shard import put_hosts
                    hosts = put_hosts(hosts, mesh)
                dropped = int(np.asarray(hosts.hw_drop).sum())
                if dropped:
                    raise RuntimeError(
                        f"{dropped} hosted-app wakes lost to wake-ring "
                        "overflow; raise EngineConfig.hostedcap")
                # ops may have queued events earlier than the next
                # window the engine computed — re-derive the window
                # (carried outbox arrivals count, engine.window.
                # next_wakeup)
                nt = jnp.minimum(jnp.min(hosts.eq_next),
                                 jnp.min(hosts.ob_next))
                wstart = nt
                wend = jnp.where(nt == SIMTIME_MAX, nt, nt + sh.min_jump)
                ws = int(wstart)
                if TR.ENABLED:
                    TR.TRACER.complete("hosting.step", _h0)
            if pcap_on_run:
                if TR.ENABLED:
                    _p0 = TR.TRACER.now()
                # every process participates in the gather (it is a
                # collective); only process 0 holds a writer
                tr_t = dist.gather_stats(hosts.tr_time)
                tr_p = dist.gather_stats(hosts.tr_pkt)
                tr_c = dist.gather_stats(hosts.tr_cnt)
                if pcap is not None:
                    pcap.drain(tr_t, tr_p, tr_c)
                if multiproc:
                    # jitted creation: uniform on all processes, keeps
                    # the sharded placement (the eager-t0 pattern above)
                    hosts = hosts.replace(
                        tr_cnt=_zeros_like(hosts.tr_cnt))
                else:
                    hosts = hosts.replace(
                        tr_cnt=jnp.zeros_like(hosts.tr_cnt))
                if TR.ENABLED:
                    TR.TRACER.complete("pcap.drain", _p0)
            if tracker is not None and tracker.due(min(ws, stop_ns)):
                if TR.ENABLED:
                    _t0 = TR.TRACER.now()
                from ..obs.tracker import socket_columns
                # [socket]/[ram] columns are per-process state; under a
                # multi-process mesh only the stats all-gather exists,
                # so those families are single-process only
                _tst = dist.gather_stats(hosts.stats)[:H]
                tracker.maybe_heartbeat(
                    min(ws, stop_ns), _tst,
                    socks=None if multiproc else socket_columns(hosts),
                    hosted_rss=(self.hosting.child_rss()
                                if self.hosting is not None else None),
                    dev_peak=wm.peak_bytes,
                    waste=occ_now(int(np.asarray(_tst)
                                      [:, defs.ST_EVENTS].sum())
                                  )["waste_frac"])
                if TR.ENABLED:
                    TR.TRACER.complete("tracker.heartbeat", _t0)
            if nsrec is not None:
                # network time-series sample: one record per chunk,
                # derived from device state + sim time only (dual-run
                # byte-identity). The hist/stats pulls are collectives
                # under a multi-process mesh — must run uniformly;
                # active-conn counting reads per-process socket state,
                # so it is single-process only (like [socket] lines)
                if TR.ENABLED:
                    _n0 = TR.TRACER.now()
                nsrec.sample(
                    total_windows, min(ws, stop_ns),
                    np.asarray(dist.gather_stats(hosts.ns_hist))[:H],
                    np.asarray(dist.gather_stats(hosts.stats))[:H],
                    conns=(None if multiproc else
                           int(np.asarray(hosts.sk_used).sum())))
                if TR.ENABLED:
                    TR.TRACER.complete("netscope.sample", _n0)
            if checkpoint_path and ckpt_at is not None and ws >= ckpt_at:
                if TR.ENABLED:
                    _k0 = TR.TRACER.now()
                save_snapshot(ws)
                ckpt_at += next_ckpt
                if TR.ENABLED:
                    TR.TRACER.complete("checkpoint.save", _k0)
            if crash is not None:
                # durability-test preemption: lands AFTER the
                # checkpoint block, so a snapshot due at this boundary
                # is durable before the kill
                crash.maybe_fire(ws)
            if obs_on:
                # per-chunk sim<->wall correlation: one jitted scalar
                # reduction per chunk (replicated on every process
                # under a multi-process mesh — must run uniformly; see
                # run() docstring) buys the events-executed annotation
                # on every chunk record
                sim_end = min(ws, stop_ns)
                ev_total = int(_ev_sum(hosts.stats))
                ev = ev_total - prev_events
                prev_events = ev_total
                if TR.ENABLED:
                    TR.TRACER.complete(
                        "chunk", _c0,
                        args={"sim_ns_start": _ws0,
                              "sim_ns_end": sim_end,
                              "windows": int(n), "events": ev})
                if MT.ENABLED:
                    reg = MT.REGISTRY
                    reg.counter("engine.chunks").inc()
                    reg.counter("engine.windows").inc(int(n))
                    reg.counter("engine.events").inc(ev)
                    reg.gauge("engine.sim_ns").set(sim_end)
                    chunk_wall = (_time.perf_counter_ns() - _c0) / 1e9
                    chunk_sim = max(sim_end - _ws0, 0) / 1e9
                    reg.chunk(
                        chunk=chunk_i, sim_ns_start=_ws0,
                        sim_ns_end=sim_end, windows=int(n), events=ev,
                        wall_s=round(chunk_wall, 6),
                        events_per_sec=(round(ev / chunk_wall, 1)
                                        if chunk_wall else None),
                        wall_per_sim_second=(
                            round(chunk_wall / chunk_sim, 6)
                            if chunk_sim else None),
                        # cumulative lane waste so far: the per-chunk
                        # occupancy trend tools/parse_heartbeat.py and
                        # the waste gate read
                        waste_frac=occ_now(ev_total)["waste_frac"])
                    if _shard_load is not None:
                        # per-shard load: cumulative events + hosts
                        # with pending work right now; the imbalance
                        # gauge is max/mean (1.0 = perfectly balanced)
                        ev_s, act_s = _shard_load(hosts.stats,
                                                  hosts.eq_next)
                        ev_s = np.asarray(ev_s)
                        act_s = np.asarray(act_s)
                        for si in range(n_shards):
                            reg.gauge(f"shard.events.{si}").set(
                                int(ev_s[si]))
                            reg.gauge(
                                f"shard.active_hosts.{si}").set(
                                int(act_s[si]))
                        mean_ev = float(ev_s.mean())
                        reg.gauge("shard.imbalance").set(
                            float(ev_s.max()) / mean_ev
                            if mean_ev else 0.0)
                chunk_i += 1
            if dg is not None and dg.due(total_windows):
                dg_record("cadence", total_windows, min(ws, stop_ns))
            if verbose:
                print(f"  t={ws / SIMTIME_ONE_SECOND:.3f}s "
                      f"windows={total_windows}")
            # fault application: the engine drained every event below
            # the segment bound — apply the head fault batch at its
            # own time, then re-derive the window (a kill's RSTs and a
            # restart's start events may open one before the old ws)
            if inj is not None:
                nf = inj.next_time()
                if nf is not None and nf < stop_ns and ws >= nf:
                    if TR.ENABLED:
                        _fi0 = TR.TRACER.now()
                    hosts, sh = inj.apply_batch(hosts, sh)
                    if mesh is not None:
                        from ..parallel.shard import (put_hosts,
                                                      put_shared)
                        hosts = put_hosts(hosts, mesh)
                        sh = put_shared(sh, mesh)
                    nt = jnp.minimum(jnp.min(hosts.eq_next),
                                     jnp.min(hosts.ob_next))
                    wstart = nt
                    wend = jnp.where(nt == SIMTIME_MAX, nt,
                                     nt + sh.min_jump)
                    ws = int(wstart)
                    if TR.ENABLED:
                        TR.TRACER.complete("faults.apply", _fi0)
                    if dg is not None:
                        # fault boundary: sample at the fault's own
                        # sim time — where a broken-determinism hunt
                        # wants the tightest bracketing
                        dg_record("fault", total_windows, int(nf))
            # a pending fault must keep the loop alive even when the
            # engine has nothing left to do (ws hits SIMTIME_MAX once
            # the queues drain, yet a host_up restart re-populates
            # them; one fault batch is consumed per iteration, so this
            # terminates)
            more_faults = (inj is not None and inj.next_time() is not None
                           and inj.next_time() < stop_ns)
            if (ws >= stop_ns or ws >= SIMTIME_MAX) and not more_faults:
                break
        if dg is not None:
            dg_record("final", total_windows,
                      min(stop_ns, ws) if ws < SIMTIME_MAX else stop_ns)
        if pcap is not None:
            pcap.close()
        if TR.ENABLED:
            _f0 = TR.TRACER.now()
        stats = dist.gather_stats(hosts.stats)[:H]
        wall = _time.perf_counter() - wall0
        self.final_hosts = hosts
        if self.hosting is not None:
            self.hosting.shutdown()
        peaks = dist.gather_stats(hosts.cap_peaks)[:H].max(axis=0)
        capacity = {"rows": [
            ("event_queue", cfg.qcap, int(peaks[0])),
            ("socket_table", cfg.scap, int(peaks[1])),
            ("outbox", cfg.obcap, int(peaks[2])),
            ("nic_txq", cfg.txqcap, int(peaks[3])),
        ]}
        sim_ns = min(stop_ns, ws) if ws < SIMTIME_MAX else stop_ns
        warm = (wall - first_chunk_wall
                if first_chunk_wall is not None and
                wall > first_chunk_wall * 1.05 else None)
        cost = {
            "row_bytes": row_bytes,
            "hot_columns": len(_hot),
            "pass_mix": {lbl: (size, int(nn)) for lbl, size, nn in
                         zip(_pass_labels, _pass_sizes, pass_acc)},
            "batch": sparse_batch(cfg),
            "per_chip_hosts": per_chip_h,
            "shards": (1 if mesh is None else
                       cfg.num_hosts // per_chip_h),
            "warm_wall": warm,
            "chunks": n_chunks,
            # the one HBM-peak definition (obs.memscope — honors
            # SHADOW_TPU_HBM_GBPS); cost_model falls back to the same
            # function, so the env value reaches both ends
            "hbm_peak_gbps": MS.hbm_peak_gbps(),
        }
        # memory observatory record (obs.memscope): the final
        # watermark, the state byte census at the as-run shapes, and
        # the window program's captured XLA cost/memory analysis —
        # what SimReport.memory / summary() / the ledger's
        # mem_peak_bytes field and cost_model()'s measured traffic all
        # read
        wm.sample()
        wm_snap = wm.snapshot()
        census = MS.state_census(cfg, hosts=hosts, hp=hp, sh=sh)
        if mesh is None:
            from .window import run_windows_aot
            xla = run_windows_aot(cfg, chunk).analysis
        else:
            from ..parallel.shard import run_windows_sharded_aot
            xla = run_windows_sharded_aot(cfg, chunk, mesh).analysis
        # network observatory report (obs.netscope): exact percentile
        # read-outs from the FINAL device histograms (not the last
        # sample — a zero-chunk run still reports)
        network = {}
        if nsrec is not None:
            network = NSC.report(
                np.asarray(dist.gather_stats(hosts.ns_hist))[:H])
            network["records"] = len(nsrec.records)
            if nsrec.path:
                network["path"] = nsrec.path
            nsrec.close()
        memrec = dict(wm_snap)
        memrec["state_bytes"] = census["bytes"]
        memrec["state_bytes_per_host"] = census["per_host"]
        memrec["hot_state_bytes"] = \
            census["hosts"]["hot"]["runtime_bytes"]
        memrec["cold_state_bytes"] = \
            census["hosts"]["hot"]["runtime_cold_bytes"]
        memrec["sections"] = census["hosts"]["sections"]
        memrec["xla"] = xla
        # lockstep-occupancy read-out (obs.passcope): always on — the
        # pass counts and event totals are already host-side. The
        # per-shard view composes with the shard.imbalance gauges.
        events_total = int(np.asarray(stats)[:, defs.ST_EVENTS].sum())
        occ = occ_now(events_total)
        shards_occ = None
        if (shard_pass_acc is not None and shard_pass_acc.any()
                and not multiproc):
            ev_s = (np.asarray(hosts.stats)[:, defs.ST_EVENTS]
                    .reshape(n_shards, -1).sum(axis=1))
            shards_occ = PC.shard_occupancy(shard_pass_acc, ev_s,
                                            _pl, _batch)
            occ["shards"] = shards_occ
        # device pass table: stop the profiler (if its chunk budget
        # didn't already) and decode the xplane dump
        dev = pscope.result() if pscope is not None else {}
        if pscope is not None:
            # the decoded table lands next to the raw trace so
            # tools/trace_report.py can merge it offline
            import json as _json
            try:
                with open(_os.path.join(pc_dir, "passcope.json"),
                          "w") as f:
                    _json.dump({"device_phases": dev,
                                "occupancy": occ}, f, indent=1,
                               sort_keys=True)
            except OSError:
                pass
        report = SimReport(stats=stats, host_names=self.host_names,
                           sim_time_ns=sim_ns, wall_seconds=wall,
                           windows=total_windows,
                           heartbeats=(tracker.lines if tracker else []),
                           capacity=capacity, cost=cost,
                           memory=memrec, network=network,
                           device_phases=dev, occupancy=occ,
                           hosted=(self.hosting.exit_info()
                                   if self.hosting is not None else {}),
                           faults=(inj.log if inj is not None else []))
        if TR.ENABLED:
            TR.TRACER.complete("report.finalize", _f0)
        if MT.ENABLED:
            MT.REGISTRY.gauge("engine.first_chunk_wall_s").set(
                first_chunk_wall or 0.0)
            # memory observatory gauges -> the metrics.json `memory`
            # section (watermark + census + captured XLA analysis)
            MS.publish(MT.REGISTRY, watermark=wm_snap, census=census,
                       xla=xla)
            if network:
                # network observatory gauges -> the metrics.json `net`
                # section (per-kind counts, percentiles, buckets)
                NSC.publish(MT.REGISTRY, network)
            # occupancy.* / passcope.* gauges -> the metrics.json
            # `occupancy` and `device_phases` sections
            PC.publish(MT.REGISTRY, occ=occ, dev=dev or None,
                       shards=shards_occ)
            if shard_pass_acc is not None and shard_pass_acc.any():
                # per-shard pass totals + rung mix: which shard went
                # dense while its peers rode the small rungs — the
                # busy-shard signature load-aware placement needs
                # (multi-process meshes return only the reduced
                # total, so the per-shard table stays zero and is
                # not published there)
                reg = MT.REGISTRY
                for si in range(n_shards):
                    reg.gauge(f"shard.passes.{si}").set(
                        int(shard_pass_acc[si].sum()))
                    for lbl, npss in zip(_pass_labels,
                                         shard_pass_acc[si]):
                        if npss:
                            reg.gauge(
                                f"shard.pass_mix.{lbl}.{si}").set(
                                int(npss))
            # summary() publishes itself into the registry (sim.*
            # gauges) — the snapshot's BENCH-diffable section
            report.summary()
            if TR.ENABLED:
                # phase attribution into the snapshot's `perf`
                # section: the registry closes with this run, so a
                # --perf/--metrics combo (where main owns the tracer
                # and only reads it AFTER run returns) still gets the
                # breakdown metrics.json documents. The finalize span
                # just completed above, so the spans cover the run.
                from ..obs import perf as _PF
                _PF.publish(
                    _PF.attribute(TR.TRACER.events, wall,
                                  report.events),
                    MT.REGISTRY)
        return report


def run_scenario(scenario: Scenario, **kw) -> SimReport:
    return Simulation(scenario, **kw).run()
