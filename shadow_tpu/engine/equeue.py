"""Per-host event queue operations.

The reference's scheduler policies keep one locked binary-heap priority
queue per host and pop events while their time is under the round
barrier (/root/reference/src/main/core/scheduler/
shd-scheduler-policy-host-single.c:158-278). Here a host's queue is a
fixed-capacity unsorted slot array; "pop min" is a lexicographic
(time, seq) reduction — a handful of vectorized ops per host per event,
which is what a TPU wants instead of pointer-chasing heaps. The
(time, sequence) total order matches the reference's event_compare
(shd-event.c:102).

All functions here operate on a *row* (one host's slice of
state.Hosts, as seen under vmap). Every eq_* column is
unconditionally HOT in the drain's working set (state.HOT_FIELDS):
q_push is the single most executed operation in the engine, and the
eq_next cache below is what the split drain's pass loop reads for
its ready masks ([K] or [H] instead of the [·, Q] table).

Batched drains (EngineConfig.event_batch > 1) pop up to B consecutive
due events per gathered host inside one compaction pass — exactly the
order this queue would pop them over B passes, so the (time, seq)
total order, and therefore every digest bit, is unchanged (the
pass-count collapse lever of ROADMAP item 1; pinned by
tests/test_compaction.py::test_event_batch_bit_identical).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.rowops import radd, rset, rset_where
from ..core.simtime import SIMTIME_MAX
from .defs import EV_NULL, ST_EQ_FULL_LOCAL

_I32_MAX = 2**31 - 1  # python int: device consts would be hoisted as const_args (see core.jitcache)


def q_push(row, t, kind, pkt):
    """Push an event into the first free slot of this host's queue.

    Returns the updated row. If the queue is full the event is dropped
    and counted in ST_EQ_FULL_LOCAL — an explicit capacity budget where
    the reference would malloc (overflow is visible in stats, never
    silent). One-hot writes (core.rowops) keep this fusable — it is
    the single most executed operation in the engine.
    """
    free = row.eq_time == SIMTIME_MAX
    has_free = jnp.any(free)
    slot = jnp.argmax(free)  # first free slot
    seq = row.eq_ctr

    return row.replace(
        eq_time=rset_where(row.eq_time, slot, has_free, jnp.int64(t)),
        eq_seq=rset_where(row.eq_seq, slot, has_free, seq),
        eq_kind=rset_where(row.eq_kind, slot, has_free, jnp.int32(kind)),
        eq_pkt=rset_where(row.eq_pkt, slot, has_free, pkt),
        eq_ctr=row.eq_ctr + 1,
        eq_next=jnp.where(has_free,
                          jnp.minimum(row.eq_next, jnp.int64(t)),
                          row.eq_next),
        stats=radd(row.stats, ST_EQ_FULL_LOCAL,
                   jnp.where(has_free, 0, 1)),
    )


def q_has_free(row):
    """True if a push right now would land (used by the NIC/timer
    bookkeeping: their 'one event in flight' flags must only be set
    when the event actually entered the queue, or a full queue turns
    into a permanently frozen NIC/timer — a lost wakeup)."""
    return jnp.any(row.eq_time == SIMTIME_MAX)


def q_min(row):
    """Lexicographic (time, seq) minimum. Returns (slot, time).

    Reads the cached row minimum (eq_next) instead of re-reducing
    eq_time — the cache invariant (eq_next == min(eq_time)) is
    maintained by q_push/q_clear_slot/window.merge_arrivals."""
    tmin = row.eq_next
    cand = row.eq_time == tmin
    seq_key = jnp.where(cand, row.eq_seq, _I32_MAX)
    slot = jnp.argmin(seq_key)
    return slot, tmin


def q_next_time(row):
    """Earliest pending event time (SIMTIME_MAX if queue empty)."""
    return row.eq_next


def q_clear_slot(row, slot):
    """Free a slot after popping its event. Recomputes the cached row
    minimum (the cleared slot usually WAS the minimum) — one [Q]
    reduction per pop, paid only for rows actually stepped."""
    eq_time = rset(row.eq_time, slot, SIMTIME_MAX)
    return row.replace(
        eq_time=eq_time,
        eq_kind=rset(row.eq_kind, slot, EV_NULL),
        eq_next=jnp.min(eq_time),
    )
