"""SOCKS-style proxy chains: client -> proxy -> server fetches.

The modeled counterpart of the reference ecosystem's SOCKS workload
(BASELINE.json config #3: "10k-node SOCKS-proxy chains on PlanetLab")
— in the reference, tgen clients reach their servers through a SOCKS
transport hop (shd-tgen-transport.c SOCKS handshake + relay). Here the
proxy is a first-class vectorized app:

- **client** picks a random proxy and a random target server, opens a
  TCP connection to the proxy whose SYN tag encodes (target, size) —
  the role of the SOCKS CONNECT header — and waits for the relayed
  response; EOF completes the fetch (latency into the RTT stats).
- **proxy** accepts the connection, opens an onward TCP connection to
  the target (SYN tag = plain GET size, the tgen-server convention, so
  targets can be tgen servers), and streams response bytes back to the
  client as they arrive. Socket pairing lives in sk_app_ref: each side
  of a relay points at its partner slot.

Tag packing (31 usable SYN-tag bits): bits 11-30 target host id (up to
~1M hosts), bits 1-10 response size in KiB (up to 1023 KiB), bit 0
reserved (clear, so the onward GET convention is unambiguous).

Client config: c0=proxy_lo, c1=proxy_hi, c2=proxy port, c3=server_lo,
c4=server_hi, c5=size KiB, c6=count (0 = forever), c7=pause ns.
Client registers: r0=socket, r1=fetches done, r2=fetch start time.
Proxy config: c1=listen port, c2=server port.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.rowops import radd, rget, rset
from ..engine.defs import (ST_XFER_DONE, ST_APP_DONE, ST_RTT_SUM_US,
                           ST_RTT_COUNT)
from ..net import packet as P
from ..net.tcp import tcp_connect, tcp_listen, tcp_write, tcp_close_call
from .base import draw, timer

_I32 = jnp.int32
_I64 = jnp.int64

TAG_HOST_SHIFT = 11
TAG_KIB_SHIFT = 1
TAG_KIB_MASK = 0x3FF


def pack_tag(target_host, size_kib):
    return ((target_host.astype(_I32) << TAG_HOST_SHIFT) |
            ((size_kib.astype(_I32) & TAG_KIB_MASK) << TAG_KIB_SHIFT))


def _rand_in(row, hp, sh, lo, hi):
    """Uniform host id in [lo, hi)."""
    row, u = draw(row, hp, sh)
    n = jnp.maximum(hi - lo, 1)
    return row, (lo + jnp.minimum((u * n.astype(jnp.float32)).astype(_I64),
                                  n - 1)).astype(_I32)


def app_socks_client(row, hp, sh, now, wake):
    reason = wake[P.ACK]
    slot = wake[P.SEQ]
    fresh = wake[P.WND] == rget(row.sk_timer_gen, slot)

    def fetch(r):
        r, proxy = _rand_in(r, hp, sh, hp.app_cfg[0], hp.app_cfg[1])
        r, server = _rand_in(r, hp, sh, hp.app_cfg[3], hp.app_cfg[4])
        tag = pack_tag(server, hp.app_cfg[5])
        r, s, ok = tcp_connect(r, hp, sh, now, dst_host=proxy,
                               dst_port=hp.app_cfg[2].astype(_I32),
                               tag=tag)
        r = r.replace(app_r=rset(rset(r.app_r, 0, s.astype(_I64)),
                                 2, _I64(now)))
        # connect failure: retry after the pause instead of stalling
        return jax.lax.cond(ok, lambda rr: rr,
                            lambda rr: timer(rr, now + hp.app_cfg[7]), r)

    def on_eof(r):
        is_mine = fresh & (slot == r.app_r[0].astype(_I32))
        # a refused relay (proxy out of sockets) closes with ZERO bytes
        # delivered: retry after the pause, never count it as a fetch
        got_data = rget(r.sk_rcv_nxt, slot) > 0

        def done(rr):
            delay_us = jnp.maximum(now - rr.app_r[2], 0) // 1000
            rr = tcp_close_call(rr, now, slot)
            rr = rr.replace(
                app_r=radd(rr.app_r, 1, 1),
                stats=radd(radd(radd(rr.stats, ST_XFER_DONE, 1),
                                ST_RTT_SUM_US, delay_us),
                           ST_RTT_COUNT, 1))
            fin = (hp.app_cfg[6] > 0) & (rr.app_r[1] >= hp.app_cfg[6])
            return jax.lax.cond(
                fin,
                lambda r2: r2.replace(stats=radd(r2.stats, ST_APP_DONE, 1)),
                lambda r2: timer(r2, now + hp.app_cfg[7]), rr)

        def refused(rr):
            rr = tcp_close_call(rr, now, slot)
            return timer(rr, now + hp.app_cfg[7])

        return jax.lax.cond(
            is_mine,
            lambda rr: jax.lax.cond(got_data, done, refused, rr),
            lambda rr: rr, r)

    def nop(r):
        return r

    # START=0 TIMER=1 SOCKET=2 CONNECTED=3 EOF=4 ACCEPT=5 SENT=6
    return jax.lax.switch(
        jnp.clip(reason, 0, 6),
        [fetch, fetch, nop, nop, on_eof, nop, nop],
        row)


def app_socks_proxy(row, hp, sh, now, wake):
    reason = wake[P.ACK]
    slot = wake[P.SEQ]
    fresh = wake[P.WND] == rget(row.sk_timer_gen, slot)
    paired = rget(row.sk_app_ref, slot)
    is_child = rget(row.sk_parent, slot) >= 0    # client-facing side

    def on_start(r):
        r, lslot, ok = tcp_listen(r, hp.app_cfg[1].astype(_I32))
        return r

    def on_accept(r):
        # SOCKS CONNECT: open the onward leg to the tagged target
        tag = rget(row.sk_syn_tag, slot)
        target = (tag >> TAG_HOST_SHIFT).astype(_I32)
        size = (((tag >> TAG_KIB_SHIFT) & TAG_KIB_MASK).astype(_I32)
                << 10)

        def go(rr):
            rr, onward, ok = tcp_connect(rr, hp, sh, now,
                                         dst_host=target,
                                         dst_port=hp.app_cfg[2].astype(_I32),
                                         tag=size)

            def pair(r2):
                return r2.replace(sk_app_ref=rset(
                    rset(r2.sk_app_ref, onward, slot),
                    slot, onward.astype(_I32)))

            # onward socket table full: refuse the client (close child)
            return jax.lax.cond(
                ok, pair, lambda r2: tcp_close_call(r2, now, slot), rr)

        return jax.lax.cond(fresh, go, lambda rr: rr, r)

    def on_data(r):
        # response bytes arriving on the onward leg: stream them back
        relay = fresh & ~is_child & (paired >= 0)
        ln = wake[P.LEN].astype(_I64)
        return jax.lax.cond(
            relay & (ln > 0),
            lambda rr: tcp_write(rr, now, paired, ln),
            lambda rr: rr, r)

    def on_eof(r):
        def close_pair(rr):
            # clear the pairing, close this side now; the partner
            # closes after its pending writes drain (close_after)
            rr = rr.replace(sk_app_ref=rset(
                rset(rr.sk_app_ref, slot, -1), paired, -1))
            rr = tcp_close_call(rr, now, slot)
            return jax.lax.cond(
                paired >= 0,
                lambda r2: tcp_close_call(r2, now, paired),
                lambda r2: r2, rr)

        return jax.lax.cond(fresh, close_pair, lambda rr: rr, r)

    def nop(r):
        return r

    return jax.lax.switch(
        jnp.clip(reason, 0, 6),
        [on_start, nop, on_data, nop, on_eof, on_accept, nop],
        row)
