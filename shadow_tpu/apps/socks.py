"""SOCKS-style proxy chains: client -> proxy -> server fetches.

The modeled counterpart of the reference ecosystem's SOCKS workload
(BASELINE.json config #3: "10k-node SOCKS-proxy chains on PlanetLab")
— in the reference, tgen clients reach their servers through a SOCKS
transport hop (shd-tgen-transport.c SOCKS handshake + relay). Here the
proxy is a first-class vectorized app:

- **client** picks a random proxy and a random target server, opens a
  TCP connection to the proxy whose SYN tag encodes (target, size) —
  the role of the SOCKS CONNECT header — and waits for the relayed
  response; EOF completes the fetch (latency into the RTT stats).
- **proxy** accepts the connection, opens an onward TCP connection to
  the target (SYN tag = plain GET size, the tgen-server convention, so
  targets can be tgen servers), and streams response bytes back to the
  client as they arrive. Socket pairing lives in sk_app_ref: each side
  of a relay points at its partner slot.

**Multi-hop circuits** (the Tor shape — BASELINE.json config #4's
relay/perfclient traffic model): the CONNECT tag carries a
hops-remaining count; a relay receiving hops > 0 extends the chain to
another RANDOM relay (tag hops-1) instead of the target, so a client
with hops=3 builds client -> entry -> middle -> exit -> server, and
response bytes stream back through every hop. This reproduces the
bandwidth/latency structure of onion-routed downloads without
per-circuit cryptographic state (which a DES doesn't model anyway).

Tag packing (31 usable SYN-tag bits): bits 29-30 relay hops remaining,
bits 9-28 target host id (up to ~1M hosts), bits 0-8 response size in
4 KiB units (up to ~2 MiB).

Client config: c0=relay_lo, c1=relay_hi, c2=relay port, c3=server_lo,
c4=server_hi, c5=size (4 KiB units), c6=count (0 = forever),
c7=pause ns | (hops << 56).
Client registers: r0=socket, r1=fetches done, r2=fetch start time.
Proxy config: c1=listen port, c2=server port, c3=relay_lo,
c4=relay_hi (the pool for chain extension).
Proxy registers: r0 = 1 + listener slot (0 = listen failed; pairs
with a nonzero ST_SOCK_FAIL in the capacity report).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.rowops import radd, rget, rset
from ..engine.defs import (ST_XFER_DONE, ST_APP_DONE, ST_RTT_SUM_US,
                           ST_RTT_COUNT, ST_CHAIN_SHORT)
from ..net import packet as P
from ..net.tcp import tcp_connect, tcp_listen, tcp_write, tcp_close_call
from ..obs import netscope
from .base import draw, timer

_I32 = jnp.int32
_I64 = jnp.int64

TAG_HOPS_SHIFT = 29          # bits 29-30: relay hops remaining (0-3)
TAG_HOST_SHIFT = 9           # bits 9-28: target host id
TAG_HOST_MASK = 0xFFFFF
TAG_U4K_MASK = 0x1FF         # bits 0-8: size in 4 KiB units


def pack_tag(target_host, size_u4k, hops=0):
    return (((jnp.asarray(hops).astype(_I32) & 0x3) << TAG_HOPS_SHIFT) |
            ((target_host.astype(_I32) & TAG_HOST_MASK) << TAG_HOST_SHIFT) |
            (size_u4k.astype(_I32) & TAG_U4K_MASK))


def _rand_in(row, hp, sh, lo, hi, skip_self=False):
    """Uniform host id in [lo, hi); with skip_self, this host is
    excluded when it lies in the range (relays never pick themselves
    as the next circuit hop — repeated DISTINCT relays remain
    possible, unlike real Tor path selection)."""
    row, u = draw(row, hp, sh)
    n = jnp.maximum(hi - lo, 1)
    if skip_self:
        in_pool = (hp.hid >= lo) & (hp.hid < hi) & (n > 1)
        n_eff = n - jnp.where(in_pool, 1, 0)
        idx = jnp.minimum((u * n_eff.astype(jnp.float32)).astype(_I64),
                          n_eff - 1)
        idx = jnp.where(in_pool & (lo + idx >= hp.hid), idx + 1, idx)
        return row, (lo + idx).astype(_I32)
    return row, (lo + jnp.minimum((u * n.astype(jnp.float32)).astype(_I64),
                                  n - 1)).astype(_I32)


def app_socks_client(row, hp, sh, now, wake):
    reason = wake[P.ACK]
    slot = wake[P.SEQ]
    fresh = wake[P.WND] == rget(row.sk_timer_gen, slot)

    pause = hp.app_cfg[7] & ((1 << 56) - 1)
    hops = (hp.app_cfg[7] >> 56).astype(_I32)

    def fetch(r):
        r, proxy = _rand_in(r, hp, sh, hp.app_cfg[0], hp.app_cfg[1])
        r, server = _rand_in(r, hp, sh, hp.app_cfg[3], hp.app_cfg[4])
        # hops=1 means one relay total: the first relay goes straight
        # to the target (tag hops counts EXTENSIONS beyond it)
        tag = pack_tag(server, hp.app_cfg[5],
                       jnp.maximum(hops - 1, 0))
        r, s, ok = tcp_connect(r, hp, sh, now, dst_host=proxy,
                               dst_port=hp.app_cfg[2].astype(_I32),
                               tag=tag)
        r = r.replace(app_r=rset(rset(r.app_r, 0, s.astype(_I64)),
                                 2, _I64(now)))
        # connect failure: retry after the pause instead of stalling
        return jax.lax.cond(ok, lambda rr: rr,
                            lambda rr: timer(rr, now + pause), r)

    def on_eof(r):
        is_mine = fresh & (slot == r.app_r[0].astype(_I32))
        # a refused relay (proxy out of sockets) closes with ZERO bytes
        # delivered: retry after the pause, never count it as a fetch
        got_data = rget(r.sk_rcv_nxt, slot) > 0

        def done(rr):
            delay_us = jnp.maximum(now - rr.app_r[2], 0) // 1000
            rr = tcp_close_call(rr, now, slot)
            rr = rr.replace(
                app_r=radd(rr.app_r, 1, 1),
                stats=radd(radd(radd(rr.stats, ST_XFER_DONE, 1),
                                ST_RTT_SUM_US, delay_us),
                           ST_RTT_COUNT, 1))
            # the fetch delay is the chain's end-to-end figure: both
            # the RTT sample (as ST_RTT_SUM_US counts it) and the
            # client-observed completion time
            rr = netscope.observe(rr, netscope.NS_RTT, delay_us)
            rr = netscope.observe(rr, netscope.NS_COMPLETION, delay_us)
            fin = (hp.app_cfg[6] > 0) & (rr.app_r[1] >= hp.app_cfg[6])
            return jax.lax.cond(
                fin,
                lambda r2: r2.replace(stats=radd(r2.stats, ST_APP_DONE, 1)),
                lambda r2: timer(r2, now + pause), rr)

        def refused(rr):
            rr = tcp_close_call(rr, now, slot)
            return timer(rr, now + pause)

        return jax.lax.cond(
            is_mine,
            lambda rr: jax.lax.cond(got_data, done, refused, rr),
            lambda rr: rr, r)

    def nop(r):
        return r

    # START=0 TIMER=1 SOCKET=2 CONNECTED=3 EOF=4 ACCEPT=5 SENT=6
    return jax.lax.switch(
        jnp.clip(reason, 0, 6),
        [fetch, fetch, nop, nop, on_eof, nop, nop],
        row)


def app_socks_proxy(row, hp, sh, now, wake):
    reason = wake[P.ACK]
    slot = wake[P.SEQ]
    fresh = wake[P.WND] == rget(row.sk_timer_gen, slot)
    paired = rget(row.sk_app_ref, slot)
    is_child = rget(row.sk_parent, slot) >= 0    # client-facing side

    def on_start(r):
        r, lslot, ok = tcp_listen(r, hp.app_cfg[1].astype(_I32))
        # record the listener (1+slot, 0 = failed) so a proxy whose
        # listen failed (ST_SOCK_FAIL) is attributable from app_r
        return r.replace(app_r=rset(
            r.app_r, 0, jnp.where(ok, lslot + 1, 0).astype(_I64)))

    def on_accept(r):
        # SOCKS CONNECT: open the onward leg — to another relay while
        # the tag still carries hops (circuit extension, the Tor
        # shape), else to the tagged target
        tag = rget(row.sk_syn_tag, slot)
        hops = (tag >> TAG_HOPS_SHIFT) & 0x3
        target = ((tag >> TAG_HOST_SHIFT) & TAG_HOST_MASK).astype(_I32)
        size = ((tag & TAG_U4K_MASK).astype(_I32) << 12)
        # a usable extension pool must offer a relay OTHER than this
        # one (a pool of just ourselves would hairpin over loopback)
        n_pool = hp.app_cfg[4] - hp.app_cfg[3]
        self_in = ((hp.hid >= hp.app_cfg[3]) & (hp.hid < hp.app_cfg[4]))
        has_pool = (n_pool > 1) | ((n_pool == 1) & ~self_in)
        extend = (hops > 0) & has_pool
        # a hops>0 CONNECT at a relay with no extension pool degrades
        # to a direct fetch — count it so the config mismatch is visible
        r = r.replace(stats=radd(r.stats, ST_CHAIN_SHORT,
                                 jnp.where((hops > 0) & ~has_pool & fresh,
                                           1, 0)))

        def go(rr):
            rr, nxt_relay = _rand_in(rr, hp, sh, hp.app_cfg[3],
                                     hp.app_cfg[4], skip_self=True)
            dst = jnp.where(extend, nxt_relay, target)
            # NOTE: chain extension dials the next relay on THIS
            # relay's own listen port — all relays in one pool must
            # share their port= setting (see compile.py socksproxy)
            dport = jnp.where(extend, hp.app_cfg[1],
                              hp.app_cfg[2]).astype(_I32)
            otag = jnp.where(
                extend,
                pack_tag(target, (tag & TAG_U4K_MASK), hops - 1),
                size)
            rr, onward, ok = tcp_connect(rr, hp, sh, now, dst_host=dst,
                                         dst_port=dport, tag=otag)

            def pair(r2):
                return r2.replace(sk_app_ref=rset(
                    rset(r2.sk_app_ref, onward, slot),
                    slot, onward.astype(_I32)))

            # onward socket table full: refuse the client (close child)
            return jax.lax.cond(
                ok, pair, lambda r2: tcp_close_call(r2, now, slot), rr)

        return jax.lax.cond(fresh, go, lambda rr: rr, r)

    def on_data(r):
        # response bytes arriving on the onward leg: stream them back
        relay = fresh & ~is_child & (paired >= 0)
        ln = wake[P.LEN].astype(_I64)
        return jax.lax.cond(
            relay & (ln > 0),
            lambda rr: tcp_write(rr, now, paired, ln),
            lambda rr: rr, r)

    def on_eof(r):
        def close_pair(rr):
            # clear the pairing, close this side now; the partner
            # closes after its pending writes drain (close_after)
            rr = rr.replace(sk_app_ref=rset(
                rset(rr.sk_app_ref, slot, -1), paired, -1))
            rr = tcp_close_call(rr, now, slot)
            return jax.lax.cond(
                paired >= 0,
                lambda r2: tcp_close_call(r2, now, paired),
                lambda r2: r2, rr)

        return jax.lax.cond(fresh, close_pair, lambda rr: rr, r)

    def nop(r):
        return r

    return jax.lax.switch(
        jnp.clip(reason, 0, 6),
        [on_start, nop, on_data, nop, on_eof, on_accept, nop],
        row)
