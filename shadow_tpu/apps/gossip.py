"""Block-gossip app: Bitcoin-style tip propagation over a random
overlay (the modeled-app counterpart of the reference ecosystem's
shadow-plugin-bitcoin block-propagation workload — BASELINE.json
config #5: "100k-node Bitcoin P2P gossip: block-propagation latency").

Model: miners produce blocks of monotonically increasing height at a
fixed interval; every node relays a block the FIRST time it sees it to
``fanout`` uniformly random peers (UDP datagrams, the inv/announce
role). Duplicate heights are ignored. Propagation latency needs no
timestamp on the wire: height h was mined at
``mine_start + h * interval`` (the miner's first timer fires one
interval after start), so each first sight contributes
``now - mined_at`` to the per-host latency accumulators
(ST_RTT_SUM_US/ST_RTT_COUNT — summary()'s mean_rtt_us is the mean
block-propagation delay).

app_cfg: [0]=num_hosts, [1]=port, [2]=fanout, [3]=interval ns,
         [4]=miner (0/1), [5]=payload bytes
app_r:   r0=socket, r1=highest height seen, r2=first-sight receptions,
         r4=blocks mined, r5=start epoch
Stats:   ST_XFER_DONE = first-sight receptions; RTT accumulators =
         propagation delay (microseconds).

Determinism note: peer draws always consume MAX_FANOUT PRNG values
(mask-selected), so the per-host draw sequence is independent of the
configured fanout — the pure-Python differential engine mirrors this
exactly (engine.pyengine._app_gossip).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.rowops import radd, rset
from ..engine.defs import (WAKE_START, ST_XFER_DONE, ST_RTT_SUM_US,
                           ST_RTT_COUNT)
from ..net import packet as P
from ..net.udp import udp_open, udp_sendto
from ..obs import netscope
from .base import draw, timer

MAX_FANOUT = 8

_I32 = jnp.int32
_I64 = jnp.int64


def _relay(row, hp, sh, now, height):
    """Send `height` to fanout random peers (always MAX_FANOUT draws)."""
    n = jnp.maximum(hp.app_cfg[0], 2)
    k = jnp.clip(hp.app_cfg[2], 0, MAX_FANOUT)
    port = hp.app_cfg[1].astype(_I32)
    sock = row.app_r[0].astype(_I32)
    for j in range(MAX_FANOUT):
        row, u = draw(row, hp, sh)
        peer = jnp.minimum((u * (n - 1).astype(jnp.float32)).astype(_I64),
                           n - 2)
        # skip self: indices >= hid shift up by one
        peer = jnp.where(peer >= hp.hid, peer + 1, peer).astype(_I32)

        def send(r):
            return udp_sendto(r, hp, now, sock, peer, port,
                              hp.app_cfg[5], aux=height.astype(_I32))

        row = jax.lax.cond(j < k, send, lambda r: r, row)
    return row


def app_gossip(row, hp, sh, now, wake):
    reason = wake[P.ACK]
    interval = hp.app_cfg[3]

    def on_start(r):
        r, slot, ok = udp_open(r, port=hp.app_cfg[1].astype(_I32))
        # r5 = the common start epoch: height h is mined at
        # r5 + h*interval. Scenarios must start all gossip processes at
        # the same time for the latency derivation to hold.
        r = r.replace(app_r=rset(rset(r.app_r, 0, slot.astype(_I64)),
                                 5, _I64(now)))
        is_miner = hp.app_cfg[4] != 0
        return jax.lax.cond(is_miner,
                            lambda rr: timer(rr, now + interval),
                            lambda rr: rr, r)

    def on_timer(r):
        # mine the next block and gossip it
        h = r.app_r[4] + 1
        r = r.replace(app_r=rset(rset(r.app_r, 4, h),
                                 1, jnp.maximum(r.app_r[1], h)))
        r = _relay(r, hp, sh, now, h)
        return timer(r, now + interval)

    def on_dgram(r):
        h = wake[P.AUX].astype(_I64)
        fresh = h > r.app_r[1]

        def first_sight(rr):
            # mined_at derives from the height (see module docstring);
            # the +interval accounts for the miner's first timer delay
            mined_at = rr.app_r[5] + h * interval
            delay_us = jnp.maximum(now - mined_at, 0) // 1000
            rr = rr.replace(
                app_r=rset(radd(rr.app_r, 2, 1), 1, h),
                stats=radd(radd(rr.stats, ST_XFER_DONE, 1),
                           ST_RTT_SUM_US, delay_us))
            rr = rr.replace(stats=radd(rr.stats, ST_RTT_COUNT, 1))
            rr = netscope.observe(rr, netscope.NS_RTT, delay_us)
            return _relay(rr, hp, sh, now, h)

        return jax.lax.cond(fresh, first_sight, lambda rr: rr, r)

    # START=0 TIMER=1 SOCKET=2
    return jax.lax.switch(jnp.clip(reason, 0, 2),
                          [on_start, on_timer, on_dgram], row)
