"""Bulk TCP transfer client/server — the tgen "bulk" workload shape.

Equivalent to the reference example config's bulk clients
(/root/reference/resource/examples/shadow.config.xml: tgen clients
fetching fixed-size transfers from tgen servers on port 80): each client
repeatedly opens a TCP connection to a server, PUTs a fixed number of
bytes, closes, pauses, and repeats. This exercises the full TCP machine
(handshake, windows, congestion control, retransmission, teardown); the
general behavior-graph tgen app builds on the same calls.

Client config (hp.app_cfg): c0=server host, c1=port, c2=bytes per
transfer, c3=transfer count (0 = forever), c4=pause ns between
transfers.
Client registers: r0=socket, r1=transfers completed.
Server config: c1=listen port. Server registers: r0=listener slot.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.rowops import radd, rget, rset
from ..engine.defs import (WAKE_START, WAKE_TIMER, WAKE_SOCKET,
                           WAKE_CONNECTED, WAKE_EOF, WAKE_ACCEPT, WAKE_SENT,
                           ST_XFER_DONE, ST_APP_DONE)
from ..net import packet as P
from ..net.tcp import tcp_connect, tcp_listen, tcp_write, tcp_close_call
from ..obs import netscope
from .base import timer


def _connect(row, hp, sh, now):
    row, slot, ok = tcp_connect(row, hp, sh, now,
                                dst_host=hp.app_cfg[0],
                                dst_port=hp.app_cfg[1])
    return row.replace(app_r=rset(row.app_r, 0, slot.astype(jnp.int64)))


def app_bulk(row, hp, sh, now, wake):
    reason = wake[P.ACK]
    sock = row.app_r[0].astype(jnp.int32)

    def on_start(r):
        return _connect(r, hp, sh, now)

    def on_connected(r):
        return tcp_write(r, now, sock, hp.app_cfg[2])

    def on_sent(r):
        # all bytes acked: transfer complete — completion time runs
        # from the handshake stamp (sk_hs_time, which close leaves in
        # place until the slot is freed)
        dur_us = jnp.maximum(now - rget(r.sk_hs_time, sock), 0) // 1000
        r = tcp_close_call(r, now, sock)
        r = r.replace(
            app_r=radd(r.app_r, 1, 1),
            stats=radd(r.stats, ST_XFER_DONE, 1))
        r = netscope.observe(r, netscope.NS_COMPLETION, dur_us)
        done = (hp.app_cfg[3] > 0) & (r.app_r[1] >= hp.app_cfg[3])
        return jax.lax.cond(
            done,
            lambda rr: rr.replace(stats=radd(rr.stats, ST_APP_DONE, 1)),
            lambda rr: timer(rr, now + hp.app_cfg[4]), r)

    def on_timer(r):
        return _connect(r, hp, sh, now)

    def nop(r):
        return r

    # reasons: START=0 TIMER=1 SOCKET=2 CONNECTED=3 EOF=4 ACCEPT=5 SENT=6
    return jax.lax.switch(
        jnp.clip(reason, 0, 6),
        [on_start, on_timer, nop, on_connected, nop, nop, on_sent],
        row)


def app_bulk_server(row, hp, sh, now, wake):
    reason = wake[P.ACK]
    slot = wake[P.SEQ]

    def on_start(r):
        r, lslot, ok = tcp_listen(r, hp.app_cfg[1])
        return r.replace(app_r=rset(r.app_r, 0, lslot.astype(jnp.int64)))

    def on_accept(r):
        # GET-tagged SYN (the tgen-server wire convention — a request
        # size riding the handshake APP word): serve it. Lets SOCKS /
        # Tor-shape configs use this lean server instead of compiling
        # the whole tgen walk machinery; plain bulk clients connect
        # with tag 0 and are unaffected.
        tag = rget(row.sk_syn_tag, slot)
        fresh = wake[P.WND] == rget(row.sk_timer_gen, slot)
        size = (tag & ((1 << 30) - 1)).astype(jnp.int64)
        is_get = fresh & ((tag & (1 << 30)) == 0) & (size > 0)

        def serve(rr):
            rr = tcp_write(rr, now, slot, size)
            return tcp_close_call(rr, now, slot)

        return jax.lax.cond(is_get, serve, lambda rr: rr, r)

    def on_eof(r):
        # client finished sending: close our side (LAST_ACK path) and
        # count the completed inbound transfer. EOFs on served-GET
        # children are teardown noise (the fetcher counts those), like
        # tgen's server side. Stale-wake guard: a recycled slot's tag
        # belongs to the NEW incarnation (generation rides WND).
        fresh = wake[P.WND] == rget(row.sk_timer_gen, slot)
        tag = rget(row.sk_syn_tag, slot)
        served_get = (tag != 0) & ((tag & (1 << 30)) == 0)

        def put_done(rr):
            rr = tcp_close_call(rr, now, slot)
            return rr.replace(stats=radd(rr.stats, ST_XFER_DONE, 1))

        return jax.lax.cond(fresh & ~served_get, put_done,
                            lambda rr: rr, r)

    def nop(r):
        return r

    return jax.lax.switch(
        jnp.clip(reason, 0, 6),
        [on_start, nop, nop, nop, on_eof, on_accept, nop],
        row)
