"""tgen: vectorized traffic-generator behavior graphs.

Reimplements the logic of the reference's bundled tgen plugin
(/root/reference/src/plugin/shadow-plugin-tgen/, 5.7k LoC C: graph walk
shd-tgen-graph.c / shd-tgen-action.c, transfers shd-tgen-transfer.c)
as a per-host vectorized state machine. The behavior-graph file format
is tgen's: a directed GraphML whose vertex ids name actions — ``start``
(peers list, serverport, initial delay), ``transfer`` (type get/put,
protocol, size), ``pause`` (fixed time or a comma list to draw from),
``synchronize`` (join barrier), ``end`` (count / time / size stop
conditions) — connected by edges the client walks
(see resource/examples/tgen.webclient.graphml.xml).

Walk semantics match the reference's graph engine:

- **parallel multi-edge walks**: completing an action follows ALL
  outgoing edges, forking concurrent walk cursors (the reference walks
  every out-edge of a completed action, shd-tgen-graph.c /
  shd-tgen.c onComplete); cursors execute through a bounded device-side
  work stack, and blocking actions (transfer, nonzero pause, delayed
  start) park their continuation on a timer or socket.
- **synchronize joins**: a synchronize vertex blocks arriving cursors
  until as many arrivals as it has incoming edges have accumulated,
  then fires once and resets (shd-tgen-action.c synchronize semantics);
  arrival counters live in Hosts.tgen_sync.

Compilation (host side): :func:`compile_tgen_graph` flattens a graph
into rows of a device node table plus peer/pause/successor pools shared
across all hosts (state.Shared.tgen_*). Runtime (device side):
:func:`app_tgen` walks the table with lax primitives; transfers ride
the TCP stack with the request type+size carried on the SYN's APP word,
exactly the role of tgen's command header on a real connection.

**Transfer timeout/stallout** (shd-tgen-transfer.c:9-11,918-961): every
transfer carries a total-time limit (``timeout``, default 60s) and a
no-progress limit (``stallout``, default 15s), settable per transfer
node with graph-wide defaults on the start node. A per-transfer
watchdog timer re-checks at stallout granularity (the reference checks
from its 1s io heartbeat, tgenio_checkTimeouts): progress is the
stream-offset sum of the transfer socket; a full stallout period with
prior progress but none since, or age past the timeout, ABORTS the
transfer — counted in ST_TGEN_ABORT, socket closed, and the walk
continues through the node's out-edges exactly like a success
(shd-tgen-driver.c:55-72 notifies completion with wasSuccess=FALSE and
continues; failed transfers do not count toward end-node count/size
conditions).
"""

from __future__ import annotations

import os
import re
from xml.etree import ElementTree

import jax
import jax.numpy as jnp
import numpy as np

from ..core.rowops import radd, rget, rset
from ..core.simtime import SIMTIME_ONE_SECOND
from ..engine.defs import (WAKE_START, WAKE_TIMER, WAKE_SOCKET,
                           WAKE_CONNECTED, WAKE_EOF, WAKE_ACCEPT, WAKE_SENT,
                           ST_XFER_DONE, ST_APP_DONE, ST_TGEN_DROP,
                           ST_TGEN_ABORT)
from ..net import packet as P
from ..net.tcp import tcp_connect, tcp_listen, tcp_write, tcp_close_call
from ..obs import netscope
from .base import draw, timer, schedule_wake

# --- node table encoding (Shared.tgen_nodes: int64 [N, 10]) ---
# [kind, a, b, c, next, peers_off, n_peers, sync_ref, edge_off, edge_cnt]
# `next` = first successor, kept as a debugging/inspection convenience
# (tests walk it); the device walk routes ONLY through the edge pool
# (edge_off/edge_cnt -> Shared.tgen_edges).
NK_START = 0      # a=serverport, b=initial delay ns
NK_TRANSFER = 1   # a=type (0 get, 1 put), b=size bytes,
#                   c=timeout ns, sync_ref=stallout ns
NK_PAUSE = 2      # a=fixed time ns (or -1: draw from pool[b:b+c])
NK_END = 3        # a=count limit, b=time-limit ns, c=size-limit bytes
NK_SYNC = 4       # a=indegree (arrivals required), sync_ref=counter slot
(COL_KIND, COL_A, COL_B, COL_C, COL_NEXT, COL_POFF, COL_PCNT, COL_REF,
 COL_EOFF, COL_ECNT) = range(10)
NODE_COLS = 10

# walk-cursor work stack depth (per wake); forks beyond this are
# dropped and counted in ST_TGEN_DROP
STACK_CAP = 8

# app_r register use: r2=transfers completed, r3=bytes transferred,
# r4=walk start time, r5=done flag (end conditions met)
REG_COUNT = 2
REG_BYTES = 3
REG_T0 = 4
REG_DONE = 5

# transfer request tag riding the SYN (31 usable bits)
TAG_PUT = 1 << 30
TAG_SIZE_MASK = (1 << 30) - 1

# transfer abort limits (shd-tgen-transfer.c:9-11); 0/unset in the
# graph falls back to these, exactly like the reference
DEFAULT_XFER_TIMEOUT_NS = 60 * SIMTIME_ONE_SECOND
DEFAULT_XFER_STALLOUT_NS = 15 * SIMTIME_ONE_SECOND

# watchdog timer wake: AUX sentinel (distinct from the walk
# continuations, which use aux >= 0 / small negative retry encodings)
WD_AUX = -(1 << 20)

_SIZE_RE = re.compile(r"^\s*([0-9.]+)\s*([a-zA-Z]*)\s*$")
_SIZE_UNITS = {
    "": 1, "b": 1, "byte": 1, "bytes": 1,
    "kb": 10**3, "mb": 10**6, "gb": 10**9, "tb": 10**12,
    "kib": 2**10, "mib": 2**20, "gib": 2**30, "tib": 2**40,
}


def parse_size(text: str) -> int:
    """Parse tgen size strings: '100 KiB', '1 MiB', '5242880'."""
    m = _SIZE_RE.match(str(text))
    if not m:
        raise ValueError(f"bad size {text!r}")
    val, unit = m.groups()
    mult = _SIZE_UNITS.get(unit.lower())
    if mult is None:
        raise ValueError(f"bad size unit {unit!r} in {text!r}")
    return int(float(val) * mult)


def _parse_tgen_seconds(text: str) -> int:
    """tgen times are seconds (may be fractional)."""
    return int(float(text) * SIMTIME_ONE_SECOND)


class TgenTables:
    """Accumulates compiled behavior graphs into the shared device
    tables (deduplicated per distinct graph)."""

    def __init__(self):
        self.nodes = []    # rows of NODE_COLS int64
        self.peers = []    # (host, port) int32 rows
        self.pool = []     # int64 pause choices (ns)
        self.edges = []    # int32 absolute successor-node indices
        self.sync_slots = 0  # per-host synchronize counters allocated
        self._cache = {}

    def compile(self, source: str, dns) -> int:
        """Compile a behavior graphml (path or inline text); returns the
        start-node index into the node table."""
        key = source
        if key in self._cache:
            return self._cache[key]
        start = compile_tgen_graph(source, dns, self)
        self._cache[key] = start
        return start

    def arrays(self):
        nodes = (np.asarray(self.nodes, dtype=np.int64)
                 if self.nodes else np.zeros((1, NODE_COLS), np.int64))
        peers = (np.asarray(self.peers, dtype=np.int32)
                 if self.peers else np.zeros((1, 2), np.int32))
        pool = (np.asarray(self.pool, dtype=np.int64)
                if self.pool else np.zeros((1,), np.int64))
        edges = (np.asarray(self.edges, dtype=np.int32)
                 if self.edges else np.full((1,), -1, np.int32))
        return nodes, peers, pool, edges


def _resolve_peers(text: str, dns):
    """'server1:30080,server2:30080' -> [(host_id, port), ...]"""
    out = []
    for item in str(text).split(","):
        item = item.strip()
        if not item:
            continue
        name, _, port = item.partition(":")
        out.append((dns.resolve(name), int(port or 80)))
    return out


def compile_tgen_graph(source: str, dns, tab: TgenTables) -> int:
    """Flatten one tgen behavior graphml into `tab`; returns start index."""
    if os.path.exists(source):
        with open(source) as f:
            text = f.read()
    else:
        text = source
    root = ElementTree.fromstring(text)
    ns = ""
    if root.tag.startswith("{"):
        ns = root.tag[: root.tag.index("}") + 1]

    keys = {}  # key id -> attr name
    for k in root.iter(f"{ns}key"):
        keys[k.attrib["id"]] = k.attrib["attr.name"]

    graph = root.find(f"{ns}graph")
    if graph is None:
        raise ValueError("tgen graphml has no <graph>")

    raw = {}      # node id -> attr dict
    order = []    # node ids in file order
    for nd in graph.findall(f"{ns}node"):
        attrs = {}
        for d in nd.findall(f"{ns}data"):
            attrs[keys.get(d.attrib["key"], d.attrib["key"])] = (d.text or "")
        raw[nd.attrib["id"]] = attrs
        order.append(nd.attrib["id"])

    succs = {nid: [] for nid in order}   # node id -> successor ids (file order)
    indeg = {nid: 0 for nid in order}    # node id -> incoming edge count
    for e in graph.findall(f"{ns}edge"):
        s, t = e.attrib["source"], e.attrib["target"]
        if s in succs and t in indeg:
            succs[s].append(t)
            indeg[t] += 1

    base = len(tab.nodes)
    index = {nid: base + i for i, nid in enumerate(order)}

    def action_of(nid: str) -> str:
        for prefix in ("start", "transfer", "pause", "synchronize", "end"):
            if nid.startswith(prefix):
                return prefix
        raise ValueError(f"tgen node id {nid!r} names no known action")

    default_peers = None
    # graph-wide abort limits from the start node (pre-scanned: file
    # order does not guarantee start first); the reference's fallback
    # chain is transfer attr -> start attr -> built-in default
    # (shd-tgen-action.c:476-487,810, shd-tgen-transfer.c:972-973)
    default_timeout = DEFAULT_XFER_TIMEOUT_NS
    default_stallout = DEFAULT_XFER_STALLOUT_NS
    for nid in order:
        if action_of(nid) == "start":
            a = raw[nid]
            if a.get("timeout"):
                default_timeout = (_parse_tgen_seconds(a["timeout"])
                                   or DEFAULT_XFER_TIMEOUT_NS)
            if a.get("stallout"):
                default_stallout = (_parse_tgen_seconds(a["stallout"])
                                    or DEFAULT_XFER_STALLOUT_NS)
    rows = []
    for nid in order:
        a = raw[nid]
        act = action_of(nid)
        slist = [index[t] for t in succs[nid]]
        nxt = slist[0] if slist else -1
        eoff, ecnt = len(tab.edges), len(slist)
        tab.edges.extend(slist)
        poff = pcnt = 0
        if act == "start":
            peers = _resolve_peers(a.get("peers", ""), dns)
            if peers:
                poff = len(tab.peers)
                pcnt = len(peers)
                tab.peers.extend(peers)
                default_peers = (poff, pcnt)
            port = int(a.get("serverport", 0) or 0)
            delay = _parse_tgen_seconds(a["time"]) if a.get("time") else 0
            row = [NK_START, port, delay, 0, nxt, poff, pcnt, 0, eoff, ecnt]
        elif act == "transfer":
            ttype = 1 if a.get("type", "get").lower() == "put" else 0
            size = parse_size(a.get("size", "1 MiB"))
            if a.get("peers"):
                peers = _resolve_peers(a["peers"], dns)
                poff, pcnt = len(tab.peers), len(peers)
                tab.peers.extend(peers)
            elif default_peers:
                poff, pcnt = default_peers
            else:
                # the reference tgen errors the same way: a transfer
                # with neither its own peers nor start-node peers
                raise ValueError(
                    f"tgen transfer node {nid!r} has no peers (set a "
                    "'peers' attr on it or on the start node)")
            tmo = (_parse_tgen_seconds(a["timeout"]) if a.get("timeout")
                   else 0) or default_timeout
            stl = (_parse_tgen_seconds(a["stallout"]) if a.get("stallout")
                   else 0) or default_stallout
            row = [NK_TRANSFER, ttype, size, tmo, nxt, poff, pcnt, stl,
                   eoff, ecnt]
        elif act == "pause":
            t = a.get("time", "1")
            if "," in t:
                choices = [_parse_tgen_seconds(x)
                           for x in t.split(",") if x.strip()]
                ref = len(tab.pool)
                tab.pool.extend(choices)
                row = [NK_PAUSE, -1, ref, len(choices), nxt, 0, 0, 0, eoff,
                       ecnt]
            else:
                row = [NK_PAUSE, _parse_tgen_seconds(t), 0, 0, nxt, 0, 0, 0,
                       eoff, ecnt]
        elif act == "synchronize":
            # join barrier: fires after `indegree` cursor arrivals
            sref = tab.sync_slots
            tab.sync_slots += 1
            row = [NK_SYNC, max(indeg[nid], 1), 0, 0, nxt, 0, 0, sref, eoff,
                   ecnt]
        else:  # end
            count = int(a.get("count", 0) or 0)
            tlim = _parse_tgen_seconds(a["time"]) if a.get("time") else 0
            slim = parse_size(a["size"]) if a.get("size") else 0
            row = [NK_END, count, tlim, slim, nxt, 0, 0, 0, eoff, ecnt]
        rows.append(row)
    tab.nodes.extend(rows)

    if "start" not in index:
        raise ValueError("tgen graph has no 'start' node")

    # Reject walks that can spin forever on device: the subgraph of
    # transitions that complete instantly (no timer, no socket) must be
    # acyclic, or the walk loop would chain through a cycle unboundedly
    # within one wake. Blocking nodes: transfers, pauses with a
    # guaranteed-nonzero wait, delayed starts, and multi-arrival
    # synchronize barriers.
    def blocks(local_i: int) -> bool:
        r = rows[local_i]
        if r[COL_KIND] == NK_TRANSFER:
            return True
        if r[COL_KIND] == NK_PAUSE:
            if r[COL_A] > 0:
                return True
            if r[COL_A] < 0:  # drawn from pool: blocking iff no 0 choice
                lo, n = r[COL_B], r[COL_C]
                return min(tab.pool[lo:lo + n]) > 0
            return False
        if r[COL_KIND] == NK_START:
            return r[COL_B] > 0
        if r[COL_KIND] == NK_SYNC:
            return r[COL_A] > 1
        return False

    WHITE, GRAY, BLACK = 0, 1, 2

    # iterative DFS over the non-blocking subgraph
    def succ_local(i):
        r = rows[i]
        return [tab.edges[r[COL_EOFF] + j] - base for j in range(r[COL_ECNT])]

    state = [WHITE] * len(rows)
    for root_i in range(len(rows)):
        if state[root_i] != WHITE or blocks(root_i):
            continue
        stack = [(root_i, 0)]
        state[root_i] = GRAY
        while stack:
            i, j = stack[-1]
            ss = [s for s in succ_local(i) if not blocks(s)]
            if j < len(ss):
                stack[-1] = (i, j + 1)
                s = ss[j]
                if state[s] == GRAY:
                    names = [order[x] for x, _ in stack] + [order[s]]
                    raise ValueError(
                        "tgen graph cycle never blocks (no transfer or "
                        f"nonzero pause): {' -> '.join(names)}")
                if state[s] == WHITE:
                    state[s] = GRAY
                    stack.append((s, 0))
            else:
                state[i] = BLACK
                stack.pop()

    return index["start"]


# --- device-side walk ------------------------------------------------------

_I32 = jnp.int32
_I64 = jnp.int64


def _node(sh, cur):
    return sh.tgen_nodes[jnp.clip(cur, 0, sh.tgen_nodes.shape[0] - 1)]


def _exec_node(row, hp, sh, now, cur):
    """Execute node `cur`'s entry action. Returns (row, proceed): when
    proceed, the walk continues through ALL the node's out-edges; when
    not, the cursor parked on a timer/socket or died (end/sync)."""
    nd = _node(sh, cur)
    kind = nd[COL_KIND]
    F = jnp.zeros((), jnp.bool_)
    T = jnp.ones((), jnp.bool_)

    def do_start(r):
        delay = nd[COL_B]

        def wait(rr):
            return timer(rr, now + delay, aux=cur), F

        return jax.lax.cond(delay > 0, wait, lambda rr: (rr, T), r)

    def do_transfer(r):
        pcnt = jnp.maximum(nd[COL_PCNT], 1)
        r, u = draw(r, hp, sh)
        pick = (nd[COL_POFF] +
                jnp.minimum((u * pcnt.astype(jnp.float32)).astype(_I64),
                            pcnt - 1))
        pick = jnp.clip(pick, 0, sh.tgen_peers.shape[0] - 1)
        peer_host = sh.tgen_peers[pick, 0]
        peer_port = sh.tgen_peers[pick, 1]
        size = jnp.minimum(nd[COL_B], TAG_SIZE_MASK)
        ttype = nd[COL_A]
        tag = (size | jnp.where(ttype == 1, TAG_PUT, 0)).astype(_I32)
        r, slot, ok = tcp_connect(r, hp, sh, now, dst_host=peer_host,
                                  dst_port=peer_port, tag=tag)

        # client sockets remember their owning behavior node, so any
        # number of transfers (parallel walk branches) can be in flight
        def connected(rr):
            rr = rr.replace(
                sk_app_ref=rset(rr.sk_app_ref, slot, cur.astype(_I32)))
            # arm the timeout/stallout watchdog (limits in the node row)
            return _wd_arm(rr, now, slot, jnp.zeros((), _I64),
                           nd[COL_C], nd[COL_REF])

        r = jax.lax.cond(
            ok, connected,
            # connect failure (socket table full): retry the transfer
            # after a 1s backoff instead of losing the walk branch
            # (negative timer aux = re-enter the node itself)
            lambda rr: timer(rr, now + SIMTIME_ONE_SECOND,
                             aux=-(cur.astype(_I32) + 1)),
            r)
        return r, F

    def do_pause(r):
        fixed = nd[COL_A]

        def drawn(rr):
            rr, u = draw(rr, hp, sh)
            n = jnp.maximum(nd[COL_C], 1)
            at = (nd[COL_B] +
                  jnp.minimum((u * n.astype(jnp.float32)).astype(_I64),
                              n - 1))
            return rr, sh.tgen_pool[jnp.clip(at, 0,
                                             sh.tgen_pool.shape[0] - 1)]

        def fixed_t(rr):
            return rr, fixed

        r, t = jax.lax.cond(fixed < 0, drawn, fixed_t, r)

        def wait(rr):
            return timer(rr, now + t, aux=cur), F

        return jax.lax.cond(t > 0, wait, lambda rr: (rr, T), r)

    def do_end(r):
        met = jnp.zeros((), jnp.bool_)
        met |= (nd[COL_A] > 0) & (r.app_r[REG_COUNT] >= nd[COL_A])
        met |= (nd[COL_B] > 0) & (now - r.app_r[REG_T0] >= nd[COL_B])
        met |= (nd[COL_C] > 0) & (r.app_r[REG_BYTES] >= nd[COL_C])

        def stop(rr):
            rr = rr.replace(
                app_r=rset(rr.app_r, REG_DONE, _I64(1)),
                stats=radd(rr.stats, ST_APP_DONE, 1))
            return rr, F

        return jax.lax.cond(met, stop, lambda rr: (rr, T), r)

    def do_sync(r):
        # join barrier: the reference's synchronize action waits until
        # every incoming walk branch has arrived, then all proceed as
        # one (shd-tgen-action.c); counter resets so loops re-arm
        ref = nd[COL_REF].astype(_I32)
        cnt = rget(r.tgen_sync, ref) + 1
        fire = cnt >= nd[COL_A].astype(_I32)
        r = r.replace(tgen_sync=rset(r.tgen_sync, ref,
                                     jnp.where(fire, 0, cnt)))
        return r, fire

    return jax.lax.switch(jnp.clip(kind, 0, 4).astype(_I32),
                          [do_start, do_transfer, do_pause, do_end,
                           do_sync], row)


def _push_succs(row, sh, stack, sp, cur):
    """Push all of `cur`'s successors onto the cursor stack (overflow
    drops the branch and counts it)."""
    nd = _node(sh, cur)
    eoff = nd[COL_EOFF].astype(_I32)
    ecnt = nd[COL_ECNT].astype(_I32)

    def body(j, c):
        row, stack, sp = c
        tgt = sh.tgen_edges[jnp.clip(eoff + j, 0,
                                     sh.tgen_edges.shape[0] - 1)]
        can = sp < STACK_CAP
        stack = jnp.where(jnp.arange(STACK_CAP) == sp, tgt, stack)
        sp = sp + jnp.where(can, 1, 0)
        row = row.replace(stats=radd(row.stats, ST_TGEN_DROP,
                                     jnp.where(can, 0, 1)))
        return row, stack, sp

    return jax.lax.fori_loop(0, ecnt, body, (row, stack, sp))


def _walk(row, hp, sh, now, stack, sp):
    """Run queued walk cursors until all have blocked or died. Bounded:
    compile-time validation guarantees every instant cycle is broken by
    a blocking node, so each cursor chain terminates."""
    N = sh.tgen_nodes.shape[0]
    cap = 4 * N + 4 * STACK_CAP

    def cond(c):
        _, _, sp, it = c
        return (sp > 0) & (it < cap)

    def body(c):
        row, stack, sp, it = c
        sp = sp - 1
        cur = rget(stack, sp).astype(_I32)
        done = row.app_r[REG_DONE] != 0
        row, proceed = jax.lax.cond(
            done, lambda r: (r, jnp.zeros((), jnp.bool_)),
            lambda r: _exec_node(r, hp, sh, now, cur), row)
        row, stack, sp = jax.lax.cond(
            proceed,
            lambda c2: _push_succs(c2[0], sh, c2[1], c2[2], cur),
            lambda c2: c2, (row, stack, sp))
        return row, stack, sp, it + 1

    row, _, sp_left, _ = jax.lax.while_loop(
        cond, body, (row, stack, jnp.asarray(sp, _I32), jnp.int32(0)))
    # iteration-cap exit with cursors still queued: count the lost
    # branches (same accounting as a stack overflow)
    return row.replace(stats=radd(row.stats, ST_TGEN_DROP,
                                  sp_left.astype(jnp.int64)))


def _walk_enter(row, hp, sh, now, node):
    """Start a cursor AT `node` (executes its action)."""
    stack = jnp.full((STACK_CAP,), -1, _I32)
    stack = stack.at[0].set(jnp.asarray(node, _I32))
    return _walk(row, hp, sh, now, stack, 1)


def _walk_succ(row, hp, sh, now, node):
    """Continue a cursor PAST `node` (its action completed): fork into
    all its successors."""
    stack = jnp.full((STACK_CAP,), -1, _I32)
    row, stack, sp = _push_succs(row, sh, stack, jnp.int32(0),
                                 jnp.asarray(node, _I32))
    return _walk(row, hp, sh, now, stack, sp)


def _wd_arm(row, now, slot, mark, timeout_ns, stallout_ns):
    """Arm/re-arm the transfer watchdog for client socket `slot`: next
    check at one stallout period out, clipped to the absolute timeout
    instant (so timeouts abort exactly on time while stall checks keep
    full-period spacing — any earlier fire IS the timeout instant).
    `mark` (the progress metric at arm time) rides the wake's LEN word;
    the slot generation rides WND so recycled slots ignore stale
    watchdogs."""
    gen = rget(row.sk_timer_gen, slot)
    start = rget(row.sk_hs_time, slot)
    t_next = jnp.minimum(now + stallout_ns, start + timeout_ns)
    t_next = jnp.maximum(t_next, now + 1)
    return schedule_wake(row, t_next, WAKE_TIMER, sock=slot, aux=WD_AUX,
                         wnd=gen, ln=mark)


def _abort_transfer(row, hp, sh, now, sock, node):
    """Timeout/stallout hit: count it, tear the socket down, and walk
    on from the owning node WITHOUT success accounting (the reference
    notifies wasSuccess=FALSE and continues the graph walk,
    shd-tgen-driver.c:55-72)."""
    row = row.replace(
        sk_app_ref=rset(row.sk_app_ref, sock, -1),
        stats=radd(row.stats, ST_TGEN_ABORT, 1))
    row = tcp_close_call(row, now, sock)
    return _walk_succ(row, hp, sh, now, node)


def _finish_transfer(row, hp, sh, now, sock):
    """A transfer completed on client socket `sock`: account it and walk
    on from its owning node."""
    node = rget(row.sk_app_ref, sock)
    nd = _node(sh, node)
    # completion time runs from the handshake stamp; read it before
    # the close path touches the slot
    dur_us = jnp.maximum(now - rget(row.sk_hs_time, sock), 0) // 1000
    row = row.replace(sk_app_ref=rset(row.sk_app_ref, sock, -1))
    row = tcp_close_call(row, now, sock)
    row = row.replace(
        app_r=radd(radd(row.app_r, REG_COUNT, 1), REG_BYTES, nd[COL_B]),
        stats=radd(row.stats, ST_XFER_DONE, 1))
    row = netscope.observe(row, netscope.NS_COMPLETION, dur_us)
    return _walk_succ(row, hp, sh, now, node)


def app_tgen(row, hp, sh, now, wake):
    reason = wake[P.ACK]
    slot = wake[P.SEQ]
    start_node = hp.app_cfg[0].astype(_I32)
    # stale-wake guard: socket wakes carry the slot generation in the
    # WND word (net.tcp._wake); a recycled slot has a newer generation
    fresh = wake[P.WND] == rget(row.sk_timer_gen, slot)
    is_client = fresh & (rget(row.sk_app_ref, slot) >= 0)

    def on_start(r):
        nd = _node(sh, start_node)
        port = nd[COL_A]

        def listen(rr):
            rr, lslot, ok = tcp_listen(rr, port.astype(_I32))
            return rr

        r = jax.lax.cond(port > 0, listen, lambda rr: rr, r)
        r = r.replace(app_r=rset(r.app_r, REG_T0, _I64(now)))
        return _walk_enter(r, hp, sh, now, start_node)

    def on_timer(r):
        aux = wake[P.AUX]

        def wd(rr):
            # transfer watchdog (module docstring): the wake carries
            # the progress mark (LEN) and slot generation (WND)
            node = rget(rr.sk_app_ref, slot)
            live = fresh & (node >= 0) & rget(rr.sk_used, slot)
            nd = _node(sh, jnp.maximum(node, 0).astype(_I32))
            metric = (rget(rr.sk_rcv_nxt, slot) +
                      rget(rr.sk_snd_una, slot))
            mark = wake[P.LEN].astype(_I64)
            took = now >= rget(rr.sk_hs_time, slot) + nd[COL_C]
            # no metric>0 gate: a transfer that never makes ANY
            # progress (server never responds after connect) stalls
            # out one stallout period after arming, matching the
            # reference's time-since-start stall semantics
            # (shd-tgen-transfer.c:918-961) instead of waiting for
            # the full timeout
            stalled = metric == mark

            def rearm(r2):
                return _wd_arm(r2, now, slot, metric, nd[COL_C],
                               nd[COL_REF])

            return jax.lax.cond(
                live & (took | stalled),
                lambda r2: _abort_transfer(r2, hp, sh, now, slot, node),
                lambda r2: jax.lax.cond(live, rearm, lambda r3: r3, r2),
                rr)

        def walk(rr):
            return jax.lax.cond(
                aux >= 0,
                lambda r2: _walk_succ(r2, hp, sh, now, aux),
                lambda r2: _walk_enter(r2, hp, sh, now, -aux - 1), rr)

        return jax.lax.cond(aux == WD_AUX, wd, walk, r)

    def on_connected(r):
        # our client socket connected; PUT writes now, GET just waits
        tag = r.sk_syn_tag[slot]
        is_put = (tag & TAG_PUT) != 0
        size = (tag & TAG_SIZE_MASK).astype(_I64)

        def put(rr):
            rr = tcp_write(rr, now, slot, size)
            return tcp_close_call(rr, now, slot)

        return jax.lax.cond(is_put & is_client, put, lambda rr: rr, r)

    def on_accept(r):
        # server child established: serve the request in its SYN tag
        tag = r.sk_syn_tag[slot]
        is_get = (tag & TAG_PUT) == 0
        size = (tag & TAG_SIZE_MASK).astype(_I64)

        def serve_get(rr):
            rr = tcp_write(rr, now, slot, size)
            return tcp_close_call(rr, now, slot)

        return jax.lax.cond(fresh & is_get, serve_get, lambda rr: rr, r)

    def on_eof(r):
        def client_done(rr):
            return _finish_transfer(rr, hp, sh, now, slot)

        def other(rr):
            # Count only a PUT-receiving child's stream end as a
            # server-side transfer; EOFs on served-GET children (the
            # client's own close) and on already-finished client
            # sockets are teardown noise.
            is_put_child = (fresh & rr.sk_used[slot] &
                            (rr.sk_parent[slot] >= 0) &
                            ((rr.sk_syn_tag[slot] & TAG_PUT) != 0))

            def done_put(r2):
                r2 = tcp_close_call(r2, now, slot)
                return r2.replace(stats=radd(r2.stats, ST_XFER_DONE, 1))

            return jax.lax.cond(is_put_child, done_put, lambda r2: r2, rr)

        return jax.lax.cond(is_client, client_done, other, r)

    def on_sent(r):
        # all written bytes acked: a client PUT's transfer is complete
        # (server GET children already have close_after set)
        return jax.lax.cond(is_client,
                            lambda rr: _finish_transfer(rr, hp, sh, now,
                                                        slot),
                            lambda rr: rr, r)

    def nop(r):
        return r

    # START=0 TIMER=1 SOCKET=2 CONNECTED=3 EOF=4 ACCEPT=5 SENT=6
    return jax.lax.switch(
        jnp.clip(reason, 0, 6),
        [on_start, on_timer, nop, on_connected, on_eof, on_accept, on_sent],
        row)
