"""tgen: vectorized traffic-generator behavior graphs.

Reimplements the logic of the reference's bundled tgen plugin
(/root/reference/src/plugin/shadow-plugin-tgen/, 5.7k LoC): igraph-
described behavior graphs whose nodes are start / transfer / pause /
end actions walked by each client, driving TCP transfers against tgen
servers. Here the graph is compiled to device tables and every host
walks its graph as a state machine.

Lands with the tgen milestone (after TCP); the dispatch stub keeps the
app registry complete.
"""

from __future__ import annotations


def app_tgen(row, hp, sh, now, wake):
    return row
