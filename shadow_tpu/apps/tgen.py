"""tgen: vectorized traffic-generator behavior graphs.

Reimplements the logic of the reference's bundled tgen plugin
(/root/reference/src/plugin/shadow-plugin-tgen/, 5.7k LoC C: graph walk
shd-tgen-graph.c / shd-tgen-action.c, transfers shd-tgen-transfer.c)
as a per-host vectorized state machine. The behavior-graph file format
is tgen's: a directed GraphML whose vertex ids name actions — ``start``
(peers list, serverport, initial delay), ``transfer`` (type get/put,
protocol, size), ``pause`` (fixed time or a comma list to draw from),
``end`` (count / time / size stop conditions) — connected by edges the
client walks in a cycle (see resource/examples/tgen.webclient.graphml.xml).

Compilation (host side): :func:`compile_tgen_graph` flattens a graph
into rows of a device node table plus peer/pause pools shared across
all hosts (state.Shared.tgen_*). Runtime (device side): :func:`app_tgen`
walks the table with lax primitives; transfers ride the TCP stack with
the request type+size carried on the SYN's APP word, exactly the role
of tgen's command header on a real connection.

Walk semantics notes vs the reference: each node has one active
successor (the first outgoing edge); tgen's parallel multi-edge walks
and ``synchronize`` joins collapse to sequential execution — the
canonical example graphs are single-successor cycles, which this
reproduces exactly. ``timeout``/``stallout`` attrs parse but v1 ignores
them (no transfer abort path yet).
"""

from __future__ import annotations

import os
import re
from xml.etree import ElementTree

import jax
import jax.numpy as jnp
import numpy as np

from ..core.rowops import radd, rset
from ..core.simtime import SIMTIME_ONE_SECOND
from ..engine.defs import (WAKE_START, WAKE_TIMER, WAKE_SOCKET,
                           WAKE_CONNECTED, WAKE_EOF, WAKE_ACCEPT, WAKE_SENT,
                           ST_XFER_DONE, ST_APP_DONE)
from ..net import packet as P
from ..net.tcp import tcp_connect, tcp_listen, tcp_write, tcp_close_call
from .base import draw, timer

# --- node table encoding (Shared.tgen_nodes: int64 [N, 8]) ---
# [kind, a, b, c, next, peers_off, n_peers, pool_ref]
NK_START = 0      # a=serverport, b=initial delay ns
NK_TRANSFER = 1   # a=type (0 get, 1 put), b=size bytes
NK_PAUSE = 2      # a=fixed time ns (or -1: draw from pool[b:b+c])
NK_END = 3        # a=count limit, b=time-limit ns, c=size-limit bytes
COL_KIND, COL_A, COL_B, COL_C, COL_NEXT, COL_POFF, COL_PCNT, COL_REF = range(8)

# transfer request tag riding the SYN (31 usable bits)
TAG_PUT = 1 << 30
TAG_SIZE_MASK = (1 << 30) - 1

_SIZE_RE = re.compile(r"^\s*([0-9.]+)\s*([a-zA-Z]*)\s*$")
_SIZE_UNITS = {
    "": 1, "b": 1, "byte": 1, "bytes": 1,
    "kb": 10**3, "mb": 10**6, "gb": 10**9, "tb": 10**12,
    "kib": 2**10, "mib": 2**20, "gib": 2**30, "tib": 2**40,
}


def parse_size(text: str) -> int:
    """Parse tgen size strings: '100 KiB', '1 MiB', '5242880'."""
    m = _SIZE_RE.match(str(text))
    if not m:
        raise ValueError(f"bad size {text!r}")
    val, unit = m.groups()
    mult = _SIZE_UNITS.get(unit.lower())
    if mult is None:
        raise ValueError(f"bad size unit {unit!r} in {text!r}")
    return int(float(val) * mult)


def _parse_tgen_seconds(text: str) -> int:
    """tgen times are seconds (may be fractional)."""
    return int(float(text) * SIMTIME_ONE_SECOND)


class TgenTables:
    """Accumulates compiled behavior graphs into the shared device
    tables (deduplicated per distinct graph)."""

    def __init__(self):
        self.nodes = []    # rows of 8 int64
        self.peers = []    # (host, port) int32 rows
        self.pool = []     # int64 pause choices (ns)
        self._cache = {}

    def compile(self, source: str, dns) -> int:
        """Compile a behavior graphml (path or inline text); returns the
        start-node index into the node table."""
        key = source
        if key in self._cache:
            return self._cache[key]
        start = compile_tgen_graph(source, dns, self)
        self._cache[key] = start
        return start

    def arrays(self):
        nodes = (np.asarray(self.nodes, dtype=np.int64)
                 if self.nodes else np.zeros((1, 8), np.int64))
        peers = (np.asarray(self.peers, dtype=np.int32)
                 if self.peers else np.zeros((1, 2), np.int32))
        pool = (np.asarray(self.pool, dtype=np.int64)
                if self.pool else np.zeros((1,), np.int64))
        return nodes, peers, pool


def _resolve_peers(text: str, dns):
    """'server1:30080,server2:30080' -> [(host_id, port), ...]"""
    out = []
    for item in str(text).split(","):
        item = item.strip()
        if not item:
            continue
        name, _, port = item.partition(":")
        out.append((dns.resolve(name), int(port or 80)))
    return out


def compile_tgen_graph(source: str, dns, tab: TgenTables) -> int:
    """Flatten one tgen behavior graphml into `tab`; returns start index."""
    if os.path.exists(source):
        with open(source) as f:
            text = f.read()
    else:
        text = source
    root = ElementTree.fromstring(text)
    ns = ""
    if root.tag.startswith("{"):
        ns = root.tag[: root.tag.index("}") + 1]

    keys = {}  # key id -> attr name
    for k in root.iter(f"{ns}key"):
        keys[k.attrib["id"]] = k.attrib["attr.name"]

    graph = root.find(f"{ns}graph")
    if graph is None:
        raise ValueError("tgen graphml has no <graph>")

    raw = {}      # node id -> attr dict
    order = []    # node ids in file order
    for nd in graph.findall(f"{ns}node"):
        attrs = {}
        for d in nd.findall(f"{ns}data"):
            attrs[keys.get(d.attrib["key"], d.attrib["key"])] = (d.text or "")
        raw[nd.attrib["id"]] = attrs
        order.append(nd.attrib["id"])

    succ = {}     # node id -> first-successor id
    for e in graph.findall(f"{ns}edge"):
        succ.setdefault(e.attrib["source"], e.attrib["target"])

    base = len(tab.nodes)
    index = {nid: base + i for i, nid in enumerate(order)}

    def action_of(nid: str) -> str:
        for prefix in ("start", "transfer", "pause", "synchronize", "end"):
            if nid.startswith(prefix):
                return prefix
        raise ValueError(f"tgen node id {nid!r} names no known action")

    default_peers = None
    rows = []
    for nid in order:
        a = raw[nid]
        act = action_of(nid)
        nxt = index[succ[nid]] if succ.get(nid) in index else -1
        poff = pcnt = 0
        if act == "start":
            peers = _resolve_peers(a.get("peers", ""), dns)
            if peers:
                poff = len(tab.peers)
                pcnt = len(peers)
                tab.peers.extend(peers)
                default_peers = (poff, pcnt)
            port = int(a.get("serverport", 0) or 0)
            delay = _parse_tgen_seconds(a["time"]) if a.get("time") else 0
            row = [NK_START, port, delay, 0, nxt, poff, pcnt, 0]
        elif act == "transfer":
            ttype = 1 if a.get("type", "get").lower() == "put" else 0
            size = parse_size(a.get("size", "1 MiB"))
            if a.get("peers"):
                peers = _resolve_peers(a["peers"], dns)
                poff, pcnt = len(tab.peers), len(peers)
                tab.peers.extend(peers)
            elif default_peers:
                poff, pcnt = default_peers
            else:
                # the reference tgen errors the same way: a transfer
                # with neither its own peers nor start-node peers
                raise ValueError(
                    f"tgen transfer node {nid!r} has no peers (set a "
                    "'peers' attr on it or on the start node)")
            row = [NK_TRANSFER, ttype, size, 0, nxt, poff, pcnt, 0]
        elif act == "pause":
            t = a.get("time", "1")
            if "," in t:
                choices = [_parse_tgen_seconds(x)
                           for x in t.split(",") if x.strip()]
                ref = len(tab.pool)
                tab.pool.extend(choices)
                row = [NK_PAUSE, -1, ref, len(choices), nxt, 0, 0, 0]
            else:
                row = [NK_PAUSE, _parse_tgen_seconds(t), 0, 0, nxt, 0, 0, 0]
        elif act == "synchronize":
            # v1: a join of one path is a no-op passthrough
            row = [NK_PAUSE, 0, 0, 0, nxt, 0, 0, 0]
        else:  # end
            count = int(a.get("count", 0) or 0)
            tlim = _parse_tgen_seconds(a["time"]) if a.get("time") else 0
            slim = parse_size(a["size"]) if a.get("size") else 0
            row = [NK_END, count, tlim, slim, nxt, 0, 0, 0]
        rows.append(row)
    tab.nodes.extend(rows)

    if "start" not in index:
        raise ValueError("tgen graph has no 'start' node")

    # Reject walks that can spin forever: follow the single-successor
    # chain from start; any reachable cycle must contain a blocking
    # node (a transfer, or a pause/start with nonzero wait) or the
    # device while_loop in _run_chain would never terminate.
    def blocks(local_i: int) -> bool:
        r = rows[local_i]
        return (r[COL_KIND] == NK_TRANSFER or
                (r[COL_KIND] == NK_PAUSE and (r[COL_A] != 0)) or
                (r[COL_KIND] == NK_START and r[COL_B] > 0))

    seen = {}
    cur = index["start"] - base
    step = 0
    while cur >= 0:
        if cur in seen:
            cycle = [i for i, s in seen.items() if s >= seen[cur]]
            if not any(blocks(i) for i in cycle):
                names = [order[i] for i in cycle]
                raise ValueError(
                    "tgen graph cycle never blocks (no transfer or "
                    f"nonzero pause): {' -> '.join(names)}")
            break
        seen[cur] = step
        step += 1
        nxt_abs = rows[cur][COL_NEXT]
        cur = nxt_abs - base if nxt_abs >= 0 else -1

    return index["start"]


# --- device-side walk ------------------------------------------------------
# registers: r0=active client socket (-1 none), r1=node to execute on the
# next wake (timer) / node of the in-flight transfer, r2=transfers
# completed, r3=total bytes transferred, r4=walk start time

_I32 = jnp.int32
_I64 = jnp.int64


def _exec_node(row, hp, sh, now, cur):
    """Execute node `cur`'s entry action. Returns (row, nxt) where
    nxt >= 0 chains immediately and -1 blocks awaiting a wake."""
    nd = sh.tgen_nodes[jnp.clip(cur, 0, sh.tgen_nodes.shape[0] - 1)]
    kind = nd[COL_KIND]
    nxt = nd[COL_NEXT].astype(_I32)

    def do_start(r):
        delay = nd[COL_B]

        def wait(rr):
            rr = rr.replace(app_r=rset(rr.app_r, 1, nxt.astype(_I64)))
            return timer(rr, now + delay), _I32(-1)

        return jax.lax.cond(delay > 0, wait, lambda rr: (rr, nxt), r)

    def do_transfer(r):
        pcnt = jnp.maximum(nd[COL_PCNT], 1)
        r, u = draw(r, hp, sh)
        pick = (nd[COL_POFF] +
                jnp.minimum((u * pcnt.astype(jnp.float32)).astype(_I64),
                            pcnt - 1))
        pick = jnp.clip(pick, 0, sh.tgen_peers.shape[0] - 1)
        peer_host = sh.tgen_peers[pick, 0]
        peer_port = sh.tgen_peers[pick, 1]
        size = jnp.minimum(nd[COL_B], TAG_SIZE_MASK)
        ttype = nd[COL_A]
        tag = (size | jnp.where(ttype == 1, TAG_PUT, 0)).astype(_I32)
        r, slot, ok = tcp_connect(r, hp, sh, now, dst_host=peer_host,
                                  dst_port=peer_port, tag=tag)
        r = r.replace(app_r=rset(rset(r.app_r, 0,
                                      slot.astype(_I64)), 1, _I64(cur)))
        # connect failure (socket table full): retry the transfer after
        # a 1s backoff instead of blocking the walk forever
        r = jax.lax.cond(ok, lambda rr: rr,
                         lambda rr: timer(rr.replace(
                             app_r=rset(rset(rr.app_r, 0, -1), 1,
                                        _I64(cur))), now + SIMTIME_ONE_SECOND),
                         r)
        return r, _I32(-1)

    def do_pause(r):
        fixed = nd[COL_A]

        def drawn(rr):
            rr, u = draw(rr, hp, sh)
            n = jnp.maximum(nd[COL_C], 1)
            at = (nd[COL_B] +
                  jnp.minimum((u * n.astype(jnp.float32)).astype(_I64),
                              n - 1))
            return rr, sh.tgen_pool[jnp.clip(at, 0,
                                             sh.tgen_pool.shape[0] - 1)]

        def fixed_t(rr):
            return rr, fixed

        r, t = jax.lax.cond(fixed < 0, drawn, fixed_t, r)

        def wait(rr):
            rr = rr.replace(app_r=rset(rr.app_r, 1, nxt.astype(_I64)))
            return timer(rr, now + t), _I32(-1)

        return jax.lax.cond(t > 0, wait, lambda rr: (rr, nxt), r)

    def do_end(r):
        met = jnp.zeros((), jnp.bool_)
        met |= (nd[COL_A] > 0) & (r.app_r[2] >= nd[COL_A])
        met |= (nd[COL_B] > 0) & (now - r.app_r[4] >= nd[COL_B])
        met |= (nd[COL_C] > 0) & (r.app_r[3] >= nd[COL_C])

        def stop(rr):
            rr = rr.replace(
                app_r=rset(rr.app_r, 1, _I64(-1)),
                stats=radd(rr.stats, ST_APP_DONE, 1))
            return rr, _I32(-1)

        return jax.lax.cond(met, stop, lambda rr: (rr, nxt), r)

    return jax.lax.switch(jnp.clip(kind, 0, 3).astype(_I32),
                          [do_start, do_transfer, do_pause, do_end], row)


def _run_chain(row, hp, sh, now, start):
    """Execute nodes until one blocks (the chain is bounded: every cycle
    in a well-formed graph contains a blocking pause/transfer)."""

    def cond(c):
        _, cur = c
        return cur >= 0

    def body(c):
        r, cur = c
        return _exec_node(r, hp, sh, now, cur)

    row, _ = jax.lax.while_loop(cond, body,
                                (row, jnp.asarray(start, _I32)))
    return row


def _finish_transfer(row, hp, sh, now, sock):
    """A transfer completed on `sock`: account it and walk on."""
    nd = sh.tgen_nodes[jnp.clip(row.app_r[1].astype(_I32), 0,
                                sh.tgen_nodes.shape[0] - 1)]
    row = tcp_close_call(row, now, sock)
    row = row.replace(
        app_r=rset(radd(radd(row.app_r, 2, 1), 3, nd[COL_B]), 0, -1),
        stats=radd(row.stats, ST_XFER_DONE, 1))
    return _run_chain(row, hp, sh, now, nd[COL_NEXT].astype(_I32))


def app_tgen(row, hp, sh, now, wake):
    reason = wake[P.ACK]
    slot = wake[P.SEQ]
    start_node = hp.app_cfg[0].astype(_I32)

    def on_start(r):
        nd = sh.tgen_nodes[jnp.clip(start_node, 0,
                                    sh.tgen_nodes.shape[0] - 1)]
        port = nd[COL_A]

        def listen(rr):
            rr, lslot, ok = tcp_listen(rr, port.astype(_I32))
            return rr

        r = jax.lax.cond(port > 0, listen, lambda rr: rr, r)
        r = r.replace(app_r=rset(rset(r.app_r, 4, _I64(now)), 0, -1))
        return _run_chain(r, hp, sh, now, start_node)

    def on_timer(r):
        return _run_chain(r, hp, sh, now, r.app_r[1].astype(_I32))

    def on_connected(r):
        # our client socket connected; PUT writes now, GET just waits
        tag = r.sk_syn_tag[slot]
        is_put = (tag & TAG_PUT) != 0
        size = (tag & TAG_SIZE_MASK).astype(_I64)

        def put(rr):
            rr = tcp_write(rr, now, slot, size)
            return tcp_close_call(rr, now, slot)

        return jax.lax.cond(is_put & (slot == r.app_r[0].astype(_I32)),
                            put, lambda rr: rr, r)

    def on_accept(r):
        # server child established: serve the request in its SYN tag
        tag = r.sk_syn_tag[slot]
        is_get = (tag & TAG_PUT) == 0
        size = (tag & TAG_SIZE_MASK).astype(_I64)

        def serve_get(rr):
            rr = tcp_write(rr, now, slot, size)
            return tcp_close_call(rr, now, slot)

        return jax.lax.cond(is_get, serve_get, lambda rr: rr, r)

    def on_eof(r):
        is_client = slot == r.app_r[0].astype(_I32)

        def client_done(rr):
            return _finish_transfer(rr, hp, sh, now, slot)

        def other(rr):
            # Count only a PUT-receiving child's stream end as a
            # server-side transfer; EOFs on served-GET children (the
            # client's own close) and on already-finished client
            # sockets are teardown noise.
            is_put_child = (rr.sk_used[slot] & (rr.sk_parent[slot] >= 0) &
                            ((rr.sk_syn_tag[slot] & TAG_PUT) != 0))

            def done_put(r2):
                r2 = tcp_close_call(r2, now, slot)
                return r2.replace(stats=radd(r2.stats, ST_XFER_DONE, 1))

            return jax.lax.cond(is_put_child, done_put, lambda r2: r2, rr)

        return jax.lax.cond(is_client, client_done, other, r)

    def on_sent(r):
        # all written bytes acked. For a client PUT this completes the
        # transfer; server GET children already have close_after set.
        is_client = slot == r.app_r[0].astype(_I32)
        return jax.lax.cond(is_client,
                            lambda rr: _finish_transfer(rr, hp, sh, now,
                                                        slot),
                            lambda rr: rr, r)

    def nop(r):
        return r

    # START=0 TIMER=1 SOCKET=2 CONNECTED=3 EOF=4 ACCEPT=5 SENT=6
    return jax.lax.switch(
        jnp.clip(reason, 0, 6),
        [on_start, on_timer, nop, on_connected, on_eof, on_accept, on_sent],
        row)
