"""apps subpackage."""
