"""App framework: vectorized per-host application state machines.

The reference hosts real ELF binaries in linker namespaces with green
threads (/root/reference/src/main/host/shd-process.c); the TPU-resident
app tier replaces that with fixed state machines dispatched by app kind
through lax.switch — the engine's EV_APP handler calls
:func:`dispatch`, which runs the app registered for this host.

App calling convention (all row-level under vmap):
    app(row, hp, sh, now, wake) -> row
where ``wake`` is a packet-word vector: ACK = wake reason (defs.WAKE_*),
SEQ = socket slot (or -1), and for packet-triggered wakes the original
SRC/SPORT/DPORT/LEN/AUX words are preserved.

Apps keep their dynamic state in row.app_node (phase) and row.app_r
(eight int64 registers); static per-host parameters come from
hp.app_cfg (eight int64s compiled from the scenario config).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import rng as R
from ..core.rowops import rset
from ..net import packet as P
from ..engine import equeue
from ..engine.defs import EV_APP, WAKE_TIMER

# App kind registry. Order is the lax.switch index — append only.
APP_NULL = 0
APP_PING = 1
APP_PING_SERVER = 2
APP_PHOLD = 3
APP_TGEN = 4
APP_BULK = 5
APP_BULK_SERVER = 6
APP_HOSTED = 7    # CPU-hosted real app code (hosting/)
APP_GOSSIP = 8    # block-gossip / tip propagation (apps/gossip.py)
APP_SOCKS_CLIENT = 9  # proxy-chain fetch client (apps/socks.py)
APP_SOCKS_PROXY = 10  # SOCKS relay proxy (apps/socks.py)
N_APP_KINDS = 11


def app_null(row, hp, sh, now, wake):
    return row


def draw(row, hp, sh):
    """Draw one uniform [0,1) float deterministically for this host.
    Returns (row, u). Uses the cheap counter PRNG (core.rng): the
    per-host stream is precomputed in HostParams, so a draw is ~8 ALU
    ops — threefry here dominated the whole window program."""
    u = R.cheap_uniform(hp.rng_stream, row.rng_ctr)
    return row.replace(rng_ctr=row.rng_ctr + 1), u


def schedule_wake(row, t, reason, sock=-1, aux=0, wnd=0, ln=0):
    """Push a future EV_APP (app timer) for this host. `wnd` and `ln`
    ride the wake's WND/LEN words (socket generation + a small payload
    — e.g. the tgen watchdog's progress mark). The SRC word carries
    the scheduling process slot (row.app_proc) so slotless wakes
    (sock=-1) route back to the same process; sock>=0 wakes route by
    the socket's owner instead (engine.window._on_app)."""
    wake = jnp.zeros((P.PKT_WORDS,), jnp.int32)
    wake = rset(wake, P.ACK, jnp.int32(reason))
    wake = rset(wake, P.SEQ, jnp.int32(sock))
    wake = rset(wake, P.AUX, jnp.int32(aux))
    wake = rset(wake, P.WND, jnp.int32(wnd))
    wake = rset(wake, P.LEN, jnp.int32(ln))
    wake = rset(wake, P.SRC, row.app_proc)
    return equeue.q_push(row, t, EV_APP, wake)


def timer(row, t, aux=0):
    return schedule_wake(row, t, WAKE_TIMER, aux=aux)


def _all_apps():
    from .ping import app_ping, app_ping_server
    from .phold import app_phold
    from .tgen import app_tgen
    from .bulk import app_bulk, app_bulk_server
    from .gossip import app_gossip
    from .socks import app_socks_client, app_socks_proxy
    from ..hosting.bridge import hosted_wake

    def app_hosted(row, hp, sh, now, wake):
        return hosted_wake(row, hp, sh, now, wake)

    return [app_null, app_ping, app_ping_server, app_phold, app_tgen,
            app_bulk, app_bulk_server, app_hosted, app_gossip,
            app_socks_client, app_socks_proxy]


def dispatch(row, hp, sh, now, wake, app_kinds=None):
    """EV_APP entry: route to this host's app by kind.

    `app_kinds` (static tuple) prunes the switch to the kinds present
    in the scenario — unused app machinery never reaches XLA.
    """
    all_apps = _all_apps()
    if app_kinds is None:
        app_kinds = tuple(range(len(all_apps)))
    kinds = tuple(sorted(set(app_kinds) | {APP_NULL}))
    if len(kinds) == 1:
        return all_apps[kinds[0]](row, hp, sh, now, wake)
    # static kind -> branch-position table
    pos = np.zeros(N_APP_KINDS, dtype=np.int32)
    for i, k in enumerate(kinds):
        pos[k] = i
    branches = [all_apps[k] for k in kinds]
    idx = jnp.asarray(pos)[jnp.clip(hp.app_kind, 0, N_APP_KINDS - 1)]
    return jax.lax.switch(idx, branches, row, hp, sh, now, wake)
