"""Ping/echo over UDP: the minimum end-to-end workload.

This is the 2-node ping/echo config from BASELINE.json (config #1) and
the vectorized analogue of a trivial tgen client/server pair.

Client config (hp.app_cfg): c0=peer host id, c1=server port,
c2=interval ns, c3=payload bytes, c4=ping count (0 = until sim end).
Client registers: r0=socket, r1=sent, r2=received.
Server config: c1=listen port. Registers: r0=socket.

RTT samples accumulate into stats ST_RTT_SUM_US / ST_RTT_COUNT; the
send timestamp rides the datagram's AUX tag in microseconds (mod 2^31).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.rowops import radd, rset
from ..core.simtime import SIMTIME_ONE_MICROSECOND
from ..engine.defs import (WAKE_START, WAKE_TIMER, WAKE_SOCKET,
                           ST_RTT_SUM_US, ST_RTT_COUNT, ST_XFER_DONE, ST_APP_DONE)
from ..net import packet as P
from ..net.udp import udp_open, udp_sendto
from ..obs import netscope
from .base import timer

_US_MOD = 2**31  # python int: device consts would be hoisted as const_args


def _us31(t_ns):
    return (t_ns // SIMTIME_ONE_MICROSECOND) % _US_MOD


def _send_ping(row, hp, now):
    """Send one ping and arm the next-send timer at a fixed interval —
    the send clock is independent of echo arrival, so a lost packet
    never stalls the client and the send rate is exactly 1/interval."""
    sock = row.app_r[0].astype(jnp.int32)
    row = udp_sendto(row, hp, now, sock,
                     dst_host=hp.app_cfg[0], dst_port=hp.app_cfg[1],
                     nbytes=hp.app_cfg[3], aux=_us31(now))
    row = row.replace(app_r=radd(row.app_r, 1, 1))
    limit = hp.app_cfg[4]
    more = (limit == 0) | (row.app_r[1] < limit)
    return jax.lax.cond(more, lambda r: timer(r, now + hp.app_cfg[2]),
                        lambda r: r, row)


def app_ping(row, hp, sh, now, wake):
    reason = wake[P.ACK]

    def on_start(r):
        r, sock, ok = udp_open(r)
        r = r.replace(app_r=rset(r.app_r, 0, jnp.int64(sock)))
        return _send_ping(r, hp, now)

    def on_timer(r):
        return _send_ping(r, hp, now)

    def on_echo(r):
        rtt_us = (_us31(now) - jnp.int64(wake[P.AUX])) % _US_MOD
        r = r.replace(
            app_r=radd(r.app_r, 2, 1),
            stats=radd(radd(radd(r.stats, ST_RTT_SUM_US, rtt_us),
                            ST_RTT_COUNT, 1), ST_XFER_DONE, 1))
        # a ping's echo is both its RTT sample and its completion
        r = netscope.observe(r, netscope.NS_RTT, rtt_us)
        r = netscope.observe(r, netscope.NS_COMPLETION, rtt_us)
        limit = hp.app_cfg[4]
        done = (limit > 0) & (r.app_r[2] >= limit)
        return r.replace(stats=radd(r.stats, ST_APP_DONE,
                                    jnp.where(done, 1, 0)))

    return jax.lax.switch(
        jnp.clip(reason, 0, 2),
        [on_start, on_timer, on_echo],  # WAKE_START, WAKE_TIMER, WAKE_SOCKET
        row)


def app_ping_server(row, hp, sh, now, wake):
    reason = wake[P.ACK]

    def on_start(r):
        r, sock, ok = udp_open(r, port=hp.app_cfg[1])
        return r.replace(app_r=rset(r.app_r, 0, jnp.int64(sock)))

    def on_dgram(r):
        # echo the payload back to the sender, preserving the AUX tag
        sock = wake[P.SEQ]
        return udp_sendto(r, hp, now, sock,
                          dst_host=wake[P.SRC], dst_port=wake[P.SPORT],
                          nbytes=jnp.int64(wake[P.LEN]), aux=wake[P.AUX])

    is_start = reason == WAKE_START
    return jax.lax.cond(is_start, on_start,
                        lambda r: jax.lax.cond(reason == WAKE_SOCKET,
                                               on_dgram, lambda rr: rr, r),
                        row)
