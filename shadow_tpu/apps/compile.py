"""Compile scenario process specs into per-host app wiring.

The reference launches real plugin binaries with an argv string
(shd-configuration.h process element); the TPU app tier instead maps
each plugin id to a vectorized app kind plus eight int64 config words
(HostParams.app_cfg). Arguments use `key=value` pairs; hostnames
resolve through the virtual DNS.

Builtin plugins:
  ping        peer=<host> port=N interval=<time> size=BYTES count=N
  pingserver  port=N
  phold       port=N mean=<time> size=BYTES init=N
  tgen        <behavior graphml path>   (tgen milestone)
"""

from __future__ import annotations

import os

import numpy as np

from ..core.simtime import parse_time
from .base import (APP_PING, APP_PING_SERVER, APP_PHOLD, APP_TGEN, APP_GOSSIP,
                   APP_BULK, APP_BULK_SERVER, APP_HOSTED,
                   APP_SOCKS_CLIENT, APP_SOCKS_PROXY)


def parse_kv(args: str) -> dict:
    out = {}
    for tok in args.split():
        if "=" in tok:
            k, v = tok.split("=", 1)
            out[k] = v
        else:
            out.setdefault("_positional", []).append(tok)
    return out


def compile_app(plugin: str, args: str, dns, num_hosts: int,
                tgen_tables=None):
    """-> (app_kind, cfg[8] int64) for one process spec."""
    cfg = np.zeros(8, dtype=np.int64)
    kv = parse_kv(args)
    if plugin == "ping":
        cfg[0] = dns.resolve(kv["peer"])
        cfg[1] = int(kv.get("port", 8000))
        cfg[2] = parse_time(kv.get("interval", "1s"))
        cfg[3] = int(kv.get("size", 64))
        cfg[4] = int(kv.get("count", 0))
        return APP_PING, cfg
    if plugin == "pingserver":
        cfg[1] = int(kv.get("port", 8000))
        return APP_PING_SERVER, cfg
    if plugin == "phold":
        cfg[0] = num_hosts
        cfg[1] = int(kv.get("port", 8000))
        cfg[2] = parse_time(kv.get("mean", "100ms"))
        cfg[3] = int(kv.get("size", 64))
        cfg[4] = int(kv.get("init", 1))
        return APP_PHOLD, cfg
    if plugin == "bulk":
        cfg[0] = dns.resolve(kv["peer"])
        cfg[1] = int(kv.get("port", 80))
        cfg[2] = int(kv.get("size", 1 << 20))
        cfg[3] = int(kv.get("count", 1))
        cfg[4] = parse_time(kv.get("pause", "1s"))
        return APP_BULK, cfg
    if plugin == "bulkserver":
        cfg[1] = int(kv.get("port", 80))
        return APP_BULK_SERVER, cfg
    if plugin == "gossip":
        # block-gossip / Bitcoin-style tip propagation (apps/gossip.py).
        # `n` bounds the peer id range for relay draws; it defaults to
        # the whole scenario — in MIXED scenarios set n to the gossip
        # host count and put the gossip hosts first, or a share of
        # relays target non-gossip hosts and silently vanish.
        cfg[0] = int(kv.get("n", num_hosts))
        cfg[1] = int(kv.get("port", 8333))
        cfg[2] = int(kv.get("fanout", 8))
        cfg[3] = parse_time(kv.get("interval", "10s"))
        cfg[4] = int(kv.get("miner", 0))
        cfg[5] = int(kv.get("size", 500))
        return APP_GOSSIP, cfg
    if plugin == "socksclient":
        # proxy-chain fetch client (apps/socks.py). Host-id ranges name
        # the proxy and server pools (hosts are id-ordered by their
        # declaration order in the scenario).
        cfg[0] = int(kv["proxy-lo"])
        cfg[1] = int(kv["proxy-hi"])
        cfg[2] = int(kv.get("proxy-port", 9050))
        cfg[3] = int(kv["server-lo"])
        cfg[4] = int(kv["server-hi"])
        if cfg[4] - 1 > 0xFFFFF:
            # only server ids ride the 20-bit CONNECT-tag host field
            # (relay hops are dialed directly, not packed)
            raise ValueError(
                "socksclient server host ids exceed the 20-bit "
                "CONNECT-tag field (max id 1048575)")
        # sizes round UP to the tag's 4 KiB units (never under-deliver)
        size_u4k = max(1, (int(kv.get("size", 51200)) + 4095) >> 12)
        if size_u4k > 0x1FF:
            # the SYN-tag CONNECT encoding carries 9 bits of 4KiB units
            raise ValueError(
                f"socksclient size {kv.get('size')} exceeds the "
                "~2 MiB per-fetch limit of the tag encoding")
        cfg[5] = size_u4k
        cfg[6] = int(kv.get("count", 0))
        hops = int(kv.get("hops", 1))
        if not 1 <= hops <= 3:
            raise ValueError("socksclient hops must be 1-3 "
                             "(relays per circuit)")
        cfg[7] = parse_time(kv.get("pause", "1s")) | (hops << 56)
        return APP_SOCKS_CLIENT, cfg
    if plugin == "socksproxy":
        cfg[1] = int(kv.get("port", 9050))
        cfg[2] = int(kv.get("server-port", 80))
        # relay pool for multi-hop circuit extension (0,0 = none).
        # Chain extension dials the next relay on THIS relay's own
        # port= value, so every relay in one pool must listen on the
        # same port.
        cfg[3] = int(kv.get("relay-lo", 0))
        cfg[4] = int(kv.get("relay-hi", 0))
        return APP_SOCKS_PROXY, cfg
    if plugin.startswith("hosted:"):
        # CPU-hosted real app code (hosting/): the Simulation builds a
        # HostingRuntime instance per such host; nothing device-side to
        # compile beyond the wake-ring app kind.
        return APP_HOSTED, cfg
    if plugin == "tgen":
        if tgen_tables is None:
            raise ValueError("tgen requires a TgenTables compile context")
        source = args.strip()
        if not source.startswith("<"):
            # not inline graphml: a file path (the reference's argv
            # form). Use the raw argument string, not parse_kv's
            # key=value splitting (paths may contain '=').
            if not source:
                raise ValueError(
                    "tgen requires a behavior graph (a graphml path or "
                    "inline graphml) as its process argument")
            if not os.path.exists(source):
                raise ValueError(
                    f"tgen behavior graph not found: {source!r}")
        cfg[0] = tgen_tables.compile(source, dns)
        return APP_TGEN, cfg
    raise ValueError(f"unknown plugin {plugin!r} "
                     "(builtin: ping, pingserver, phold, bulk, bulkserver, "
                     "tgen, gossip, socksclient, socksproxy)")
