"""Compile scenario process specs into per-host app wiring.

The reference launches real plugin binaries with an argv string
(shd-configuration.h process element); the TPU app tier instead maps
each plugin id to a vectorized app kind plus eight int64 config words
(HostParams.app_cfg). Arguments use `key=value` pairs; hostnames
resolve through the virtual DNS.

Builtin plugins:
  ping        peer=<host> port=N interval=<time> size=BYTES count=N
  pingserver  port=N
  phold       port=N mean=<time> size=BYTES init=N
  tgen        <behavior graphml path>   (tgen milestone)
"""

from __future__ import annotations

import os

import numpy as np

from ..core.simtime import parse_time
from .base import (APP_PING, APP_PING_SERVER, APP_PHOLD, APP_TGEN, APP_GOSSIP,
                   APP_BULK, APP_BULK_SERVER, APP_HOSTED,
                   APP_SOCKS_CLIENT, APP_SOCKS_PROXY)


def parse_kv(args: str) -> dict:
    out = {}
    for tok in args.split():
        if "=" in tok:
            k, v = tok.split("=", 1)
            out[k] = v
        else:
            out.setdefault("_positional", []).append(tok)
    return out


def compile_app(plugin: str, args: str, dns, num_hosts: int,
                tgen_tables=None):
    """-> (app_kind, cfg[8] int64) for one process spec."""
    cfg = np.zeros(8, dtype=np.int64)
    kv = parse_kv(args)
    if plugin == "ping":
        cfg[0] = dns.resolve(kv["peer"])
        cfg[1] = int(kv.get("port", 8000))
        cfg[2] = parse_time(kv.get("interval", "1s"))
        cfg[3] = int(kv.get("size", 64))
        cfg[4] = int(kv.get("count", 0))
        return APP_PING, cfg
    if plugin == "pingserver":
        cfg[1] = int(kv.get("port", 8000))
        return APP_PING_SERVER, cfg
    if plugin == "phold":
        cfg[0] = num_hosts
        cfg[1] = int(kv.get("port", 8000))
        cfg[2] = parse_time(kv.get("mean", "100ms"))
        cfg[3] = int(kv.get("size", 64))
        cfg[4] = int(kv.get("init", 1))
        return APP_PHOLD, cfg
    if plugin == "bulk":
        cfg[0] = dns.resolve(kv["peer"])
        cfg[1] = int(kv.get("port", 80))
        cfg[2] = int(kv.get("size", 1 << 20))
        cfg[3] = int(kv.get("count", 1))
        cfg[4] = parse_time(kv.get("pause", "1s"))
        return APP_BULK, cfg
    if plugin == "bulkserver":
        cfg[1] = int(kv.get("port", 80))
        return APP_BULK_SERVER, cfg
    if plugin == "gossip":
        # block-gossip / Bitcoin-style tip propagation (apps/gossip.py).
        # `n` bounds the peer id range for relay draws; it defaults to
        # the whole scenario — in MIXED scenarios set n to the gossip
        # host count and put the gossip hosts first, or a share of
        # relays target non-gossip hosts and silently vanish.
        cfg[0] = int(kv.get("n", num_hosts))
        cfg[1] = int(kv.get("port", 8333))
        cfg[2] = int(kv.get("fanout", 8))
        cfg[3] = parse_time(kv.get("interval", "10s"))
        cfg[4] = int(kv.get("miner", 0))
        cfg[5] = int(kv.get("size", 500))
        return APP_GOSSIP, cfg
    if plugin == "socksclient":
        # proxy-chain fetch client (apps/socks.py). Host-id ranges name
        # the proxy and server pools (hosts are id-ordered by their
        # declaration order in the scenario).
        cfg[0] = int(kv["proxy-lo"])
        cfg[1] = int(kv["proxy-hi"])
        cfg[2] = int(kv.get("proxy-port", 9050))
        cfg[3] = int(kv["server-lo"])
        cfg[4] = int(kv["server-hi"])
        if cfg[4] - 1 > 0xFFFFF:
            # only server ids ride the 20-bit CONNECT-tag host field
            # (relay hops are dialed directly, not packed)
            raise ValueError(
                "socksclient server host ids exceed the 20-bit "
                "CONNECT-tag field (max id 1048575)")
        # sizes round UP to the tag's 4 KiB units (never under-deliver)
        size_u4k = max(1, (int(kv.get("size", 51200)) + 4095) >> 12)
        if size_u4k > 0x1FF:
            # the SYN-tag CONNECT encoding carries 9 bits of 4KiB units
            raise ValueError(
                f"socksclient size {kv.get('size')} exceeds the "
                "~2 MiB per-fetch limit of the tag encoding")
        cfg[5] = size_u4k
        cfg[6] = int(kv.get("count", 0))
        hops = int(kv.get("hops", 1))
        if not 1 <= hops <= 3:
            raise ValueError("socksclient hops must be 1-3 "
                             "(relays per circuit)")
        cfg[7] = parse_time(kv.get("pause", "1s")) | (hops << 56)
        return APP_SOCKS_CLIENT, cfg
    if plugin == "socksproxy":
        cfg[1] = int(kv.get("port", 9050))
        cfg[2] = int(kv.get("server-port", 80))
        # relay pool for multi-hop circuit extension (0,0 = none).
        # Chain extension dials the next relay on THIS relay's own
        # port= value, so every relay in one pool must listen on the
        # same port.
        cfg[3] = int(kv.get("relay-lo", 0))
        cfg[4] = int(kv.get("relay-hi", 0))
        return APP_SOCKS_PROXY, cfg
    if plugin.startswith("hosted:"):
        # CPU-hosted real app code (hosting/): the Simulation builds a
        # HostingRuntime instance per such host; nothing device-side to
        # compile beyond the wake-ring app kind.
        return APP_HOSTED, cfg
    if plugin == "tgen":
        if tgen_tables is None:
            raise ValueError("tgen requires a TgenTables compile context")
        source = args.strip()
        if not source.startswith("<"):
            # not inline graphml: a file path (the reference's argv
            # form). Use the raw argument string, not parse_kv's
            # key=value splitting (paths may contain '=').
            if not source:
                raise ValueError(
                    "tgen requires a behavior graph (a graphml path or "
                    "inline graphml) as its process argument")
            if not os.path.exists(source):
                raise ValueError(
                    f"tgen behavior graph not found: {source!r}")
        cfg[0] = tgen_tables.compile(source, dns)
        return APP_TGEN, cfg
    raise ValueError(f"unknown plugin {plugin!r} "
                     "(builtin: ping, pingserver, phold, bulk, bulkserver, "
                     "tgen, gossip, socksclient, socksproxy)")


# --- scenario-scaled engine capacities (shrink campaign, lever 3) ---------
#
# Every socket-table row costs ~239 B/host-slot at the narrow layout
# (~364 wide) whether or not a socket ever lives there, and qcap rides
# on scap (one standing RTO timer per live socket). The hand-tuned
# per-config caps in tools/baseline_configs are sized for the WORST
# member of a config family; most scenarios declare enough in their
# process specs to size exactly. peak_sockets() reads those
# declarations; auto_caps() turns them into an EngineConfig with a 2x
# margin. Overflow above a cap defers to the next window (exact), so a
# mis-declared peak costs windows, never correctness.

def _tgen_attr(graphml: str, attr: str):
    """First <data> value for a graphml attr.name, resolving the
    attr -> key-id indirection (<key attr.name=.. id=..>)."""
    import re
    m = re.search(r'<key[^>]*attr\.name="%s"[^>]*id="([^"]+)"' % attr,
                  graphml)
    if not m:
        m = re.search(r'<key[^>]*id="([^"]+)"[^>]*attr\.name="%s"' % attr,
                      graphml)
    if not m:
        return None
    d = re.search(r'<data key="%s">([^<]*)</data>' % re.escape(m.group(1)),
                  graphml)
    return d.group(1) if d else None


def _strip_ordinal(name: str) -> str:
    """'relay37' -> 'relay' — hostnames are spec id + 1-based ordinal
    (core.dns expansion order)."""
    return name.rstrip("0123456789") or name


def peak_sockets(scenario):
    """Per-HostSpec peak concurrent sockets, from the apps' declared
    traffic shape -> {spec_id: peak} — or None with a reason string,
    (None, why), when any process is unbounded (hosted apps, unknown
    plugins, tgen file-path graphs the planner cannot read inline).

    The model: each plugin contributes sockets it OWNS on its host
    (listeners, the one in-flight fetch) plus LOAD it lands on remote
    pools, distributed uniformly over the pool — a socks circuit
    crosses each of its `hops` relays with 2 sockets (in + out leg,
    apps/socks.py), a fetch holds 1 server-side socket."""
    specs = []          # (spec, id_lo, id_hi)
    lo = 0
    for hs in scenario.hosts:
        q = max(int(hs.quantity or 1), 1)
        specs.append((hs, lo, lo + q))
        lo += q

    own = {hs.id: 0 for hs, _, _ in specs}      # per-host owned peak
    loads = []                                  # (id_lo, id_hi, total)
    named_loads = []                            # (spec_id, n_pool, total)

    for hs, s_lo, s_hi in specs:
        q = s_hi - s_lo
        for ps in hs.processes:
            kv = parse_kv(ps.arguments)
            p = ps.plugin
            if p in ("ping", "pingserver", "phold", "gossip",
                     "bulkserver", "socksproxy"):
                own[hs.id] += 1                 # one UDP sock / listener
            elif p == "bulk":
                own[hs.id] += 1                 # serial fetches
                peer = _strip_ordinal(kv["peer"])
                named_loads.append((peer, 1, q))
            elif p == "socksclient":
                own[hs.id] += 1                 # one circuit leg at a time
                hops = int(kv.get("hops", 1))
                rlo, rhi = int(kv["proxy-lo"]), int(kv["proxy-hi"])
                slo, shi = int(kv["server-lo"]), int(kv["server-hi"])
                # 2 sockets on every relay the circuit crosses, 1 on
                # the server; pools absorb the whole client population
                loads.append((rlo, rhi, 2 * hops * q))
                loads.append((slo, shi, 1 * q))
            elif p == "tgen":
                src = ps.arguments.strip()
                if not src.startswith("<"):
                    return None, (f"spec {hs.id!r}: tgen file-path "
                                  "graph — peak not declared inline")
                peers = _tgen_attr(src, "peers")
                if peers:
                    own[hs.id] += 2             # active transfer + churn
                    names = [t.split(":")[0] for t in peers.split(",")
                             if t.strip()]
                    by_spec = {}
                    for nm in names:
                        by_spec[_strip_ordinal(nm)] = \
                            by_spec.get(_strip_ordinal(nm), 0) + 1
                    for spec_id, n_pool in by_spec.items():
                        named_loads.append(
                            (spec_id, n_pool,
                             q * n_pool / max(len(names), 1)))
                else:
                    own[hs.id] += 1             # pure server graph
            else:
                return None, (f"spec {hs.id!r}: plugin {p!r} declares "
                              "no socket peak (hosted/unknown)")

    peaks = {}
    for hs, s_lo, s_hi in specs:
        density = 0.0
        for l_lo, l_hi, total in loads:
            o_lo, o_hi = max(s_lo, l_lo), min(s_hi, l_hi)
            if o_hi > o_lo and l_hi > l_lo:
                density += total / (l_hi - l_lo)
        for spec_id, n_pool, total in named_loads:
            if spec_id == hs.id:
                density += total / max(n_pool, 1)
        peaks[hs.id] = own[hs.id] + int(-(-density // 1))
    return peaks, None


def auto_caps(scenario, base):
    """Scenario-scaled capacities: (EngineConfig, info dict).

    scap = ceil16(2 x max declared peak) — the 2x absorbs TIME_WAIT
    residue and burst skew above the mean the peak model computes.
    qcap preserves the BASE's qcap - scap headroom delta, not a ratio:
    the delta is the arrival budget that keeps one standing RTO-timer
    event per live socket from starving intake
    (tools/baseline_configs.socks_caps round-3 notes). obcap/txqcap
    are per-window throughput budgets, not per-socket needs — they
    keep the base value, clamped to scap (budgeting more emit slots
    than sockets that could emit buys nothing).

    When the scenario declares no computable peak the BASE caps come
    back unchanged with info["applied"] False — the planner never
    guesses."""
    import dataclasses

    peaks, why = peak_sockets(scenario)
    if peaks is None:
        return base, {"applied": False, "why": why}
    mx = max(peaks.values()) if peaks else 1
    scap = max(((2 * mx + 15) // 16) * 16, 16)
    qcap = scap + max(base.qcap - base.scap, 16)
    obcap = min(base.obcap, scap)
    txqcap = min(base.txqcap, scap)
    cfg = dataclasses.replace(base, scap=scap, qcap=qcap, obcap=obcap,
                              txqcap=txqcap)
    return cfg, {
        "applied": True, "peaks": peaks, "max_peak": mx,
        "caps": {"scap": scap, "qcap": qcap, "obcap": obcap,
                 "txqcap": txqcap},
        "base_caps": {"scap": base.scap, "qcap": base.qcap,
                      "obcap": base.obcap, "txqcap": base.txqcap},
        "grew": scap > base.scap,
    }
