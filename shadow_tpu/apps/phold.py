"""PHOLD: the classic parallel-DES stress benchmark.

Mirrors the reference's phold plugin (/root/reference/src/test/phold/
shd-test-phold.c): every host holds messages; on receiving one it
schedules a send to a uniformly random peer after an exponential delay.
Doubles as the scheduler/exchange stress test, exactly as in the
reference's test suite.

Config (hp.app_cfg): c0=num hosts, c1=port, c2=mean delay ns,
c3=payload bytes, c4=initial messages per host.
Registers: r0=socket, r1=messages sent.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.rowops import radd, rset
from ..engine.defs import WAKE_START, WAKE_TIMER, WAKE_SOCKET
from ..net import packet as P
from ..net.udp import udp_open, udp_sendto
from .base import draw, timer


def _exp_delay(row, hp, sh):
    """Exponential delay with mean c2 (ns), minimum 1ns."""
    row, u = draw(row, hp, sh)
    mean = hp.app_cfg[2].astype(jnp.float32)
    d = (-mean * jnp.log1p(-u)).astype(jnp.int64)
    return row, jnp.maximum(d, 1)


def _send_to_random_peer(row, hp, sh, now):
    row, u = draw(row, hp, sh)
    n = hp.app_cfg[0]
    peer = jnp.minimum((u * n).astype(jnp.int64), n - 1)
    # avoid self as the reference does by redrawing — here: shift by one
    peer = jnp.where(peer == hp.hid, (peer + 1) % n, peer)
    sock = row.app_r[0].astype(jnp.int32)
    row = udp_sendto(row, hp, now, sock, dst_host=peer,
                     dst_port=hp.app_cfg[1], nbytes=hp.app_cfg[3])
    return row.replace(app_r=radd(row.app_r, 1, 1))


def app_phold(row, hp, sh, now, wake):
    reason = wake[P.ACK]

    def on_start(r):
        r, sock, ok = udp_open(r, port=hp.app_cfg[1])
        r = r.replace(app_r=rset(r.app_r, 0, jnp.int64(sock)))

        # Seed the system with c4 initial messages at exponential offsets.
        # The bound must be clamped: under vmap every host executes every
        # app branch masked, so an unclamped traced bound would spin on
        # other apps' config words; the queue capacity is the true cap.
        def seed_one(i, rr):
            rr, d = _exp_delay(rr, hp, sh)
            return timer(rr, now + d)
        qcap = r.eq_time.shape[0]
        n0 = jnp.clip(hp.app_cfg[4], 0, qcap).astype(jnp.int32)
        return jax.lax.fori_loop(0, n0, seed_one, r)

    def on_timer(r):
        return _send_to_random_peer(r, hp, sh, now)

    def on_msg(r):
        # a message arrived: schedule the next hop after an exp delay
        r, d = _exp_delay(r, hp, sh)
        return timer(r, now + d)

    return jax.lax.switch(
        jnp.clip(reason, 0, 2),
        [on_start, on_timer, on_msg],
        row)
