"""Multi-chip parallel execution (shard_map window loop)."""

from .shard import make_mesh, run_windows_sharded, device_put_sharded

__all__ = ["make_mesh", "run_windows_sharded", "device_put_sharded"]
