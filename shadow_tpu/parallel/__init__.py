"""parallel subpackage."""
