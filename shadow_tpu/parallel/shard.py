"""Multi-chip execution: the window loop under shard_map.

This is the TPU realization of the reference's parallel-scheduler +
anticipated-multi-slave design (SURVEY §2.9; scheduler barriers
shd-scheduler.c:602-635, the master round handshake shd-master.c:410-440,
and the single cross-machine seam at worker_sendPacket
shd-worker.c:250-252):

- hosts are block-sharded over a 1-D ``Mesh(("hosts",))`` — the analogue
  of host-to-thread assignment (shd-scheduler.c:473-516), except static
  and contiguous so host id -> shard is ``hid // H_local``;
- the conservative window barrier becomes ``lax.pmin`` of each shard's
  earliest pending event time over ICI — the reference's locked global
  min-next-event-time reduction (shd-scheduler.c:379-384);
- cross-shard packet delivery is an all-gather of per-shard outboxes at
  the window boundary, each shard keeping what lands on its hosts —
  the reference's cross-thread scheduler_push at the same seam.

Numerical equivalence: the sharded run reproduces the single-chip run
bit-for-bit (asserted by tests/test_parallel.py). Loss rolls are keyed
by (src, uid) counters, not by execution placement; the gathered global
packet order equals the single-chip outbox order because shards are
contiguous host blocks; and every per-host transition is local.

Two wire protocols (EngineConfig.exchange_a2a selects; both live in
:func:`exchange_sharded`, nothing else changes):

- **v1 all-gather**: every shard receives every shard's whole outbox —
  simple, exact, but per-shard ICI bytes grow as O(shards x outbox).
- **v2 bucketed ragged all-to-all** (default): each shard stable-sorts
  its surviving outbox by destination shard, packs it into fixed
  [shards, B] buckets and `lax.all_to_all`s them — each shard receives
  only traffic addressed to its hosts, so per-shard wire bytes are
  O(shards x B) ~= O(4 x outbox), FLAT in shard count (B defaults to
  4x the uniform-traffic share). Determinism: bucket packing and the
  post-exchange merge are stable sorts keyed exactly like v1, so the
  delivered order (and therefore every downstream bit) matches v1 and
  the single-chip engine. A bucket overflow (one shard bursting more
  than B packets at one other shard in a single window) DEFERS the
  burst tail at the source — exact arrival times, counted in
  ST_DEFER_A2A — where v1/single-chip would have delivered it this
  window, so bit-equality with them holds only under the bucket
  bound; size a2acap for the workload's burst, or set
  exchange_a2a=False for the exact-at-any-burst v1.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as PS

from ..core import rng as R
from ..core.simtime import SIMTIME_MAX
from ..engine import equeue
from ..engine.defs import (EV_PKT, ST_PKTS_DROP_NET,
                           ST_DEFER_FANIN, ST_DEFER_A2A)
from ..engine.state import EngineConfig
from ..engine.window import drain_window, update_cap_peaks
from ..net import packet as P

AXIS = "hosts"


def make_mesh(n_devices: int = None) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(devs, (AXIS,))


def mesh_local_devices(mesh: Mesh) -> list:
    """This process's devices of the mesh, in host-axis order — the
    shard-index order the memory observatory's per-shard watermarks
    (obs.memscope.Watermark.per_device) report in: index i of the
    watermark list is the device holding host block i among the local
    shards."""
    local = {d.id for d in jax.local_devices()}
    return [d for d in mesh.devices.flat if d.id in local]


def exchange_sharded(hosts, hp, sh, cfg: EngineConfig,
                     lcfg: EngineConfig):
    """Window-boundary packet exchange, one shard's view.

    Same program as engine.window.exchange with the routing/loss math
    done source-side (all inputs local) and delivery done after the
    cross-shard hop (v2 bucketed all-to-all or v1 all-gather). `cfg`
    is global sizes, `lcfg` local (per-shard) sizes.

    Deferral (round 3): the destination shard decides which received
    packets fit its hosts' intake this window (engine.window.
    _deliver_dense) and the accept flags travel BACK to the source
    shard — one small reverse collective — so unaccepted packets stay
    in the source outbox and re-exchange next window, exactly like the
    single-chip engine. The v2 bucket-overflow tail (never shipped)
    defers the same way, counted in ST_DEFER_A2A instead of dropped.
    """
    H, Hl, O, IN = cfg.num_hosts, lcfg.num_hosts, cfg.obcap, cfg.incap
    Nl = Hl * O
    n_shards = H // Hl
    my = jax.lax.axis_index(AXIS).astype(jnp.int32)
    lo = my * Hl

    pkts = hosts.ob_pkt.reshape(Nl, P.PKT_WORDS)
    stimes = hosts.ob_time.reshape(Nl)
    valid = (jnp.arange(O)[None, :] < hosts.ob_cnt[:, None]).reshape(Nl)

    src = jnp.clip(pkts[:, P.SRC], 0, H - 1)
    dst = jnp.clip(pkts[:, P.DST], 0, H - 1)
    sv = sh.host_vertex[src]
    dv = sh.host_vertex[dst]
    lat = sh.lat_ns[sv, dv]
    rel = sh.rel[sv, dv]
    arrival = stimes + lat
    # one-way latency stamp on handshake segments (us, SEQ word) —
    # identical to the single-chip exchange (net.tcp._autotune)
    is_syn = (pkts[:, P.FLAGS] & P.F_SYN) != 0
    pkts = pkts.at[:, P.SEQ].set(
        jnp.where(is_syn, (lat // 1000).astype(jnp.int32),
                  pkts[:, P.SEQ]))

    # Loss roll at the source (keyed by the globally unique (src, uid),
    # so placement-independent — same rolls as the single-chip run).
    u = R.cheap_uniform(R.stream_of(sh.seed32, R.DOMAIN_DROP, src),
                        pkts[:, P.UID])

    reachable = rel > 0
    deliver = valid & reachable & (u <= rel)
    net_dropped = valid & ~deliver

    sortkey_l = jnp.where(deliver, dst, H)

    from ..engine.window import (_deliver_dense, _carry_outbox,
                                 _trace_tx, merge_arrivals)

    if cfg.exchange_a2a and n_shards > 1:
        g_key, g_arr, g_pkt, oj, cell_ok = _a2a_hop(
            cfg, lcfg, sortkey_l, arrival, pkts, n_shards)
        # which outbox positions actually shipped in a bucket (the
        # overflow tail did not — it defers via ST_DEFER_A2A)
        tgt = jnp.where(cell_ok, oj, Nl)
        shipped = jnp.zeros((Nl,), jnp.bool_).at[tgt.reshape(-1)].set(
            True, mode="drop")
    else:
        # --- v1: gather all shards' surviving traffic ---
        g_key = jax.lax.all_gather(sortkey_l, AXIS).reshape(n_shards * Nl)
        g_arr = jax.lax.all_gather(arrival, AXIS).reshape(n_shards * Nl)
        g_pkt = jax.lax.all_gather(pkts, AXIS).reshape(n_shards * Nl,
                                                       P.PKT_WORDS)
        shipped = deliver

    # identical group-by-destination + gather-based delivery as the
    # single-chip exchange (engine.window._deliver_dense — ONE
    # implementation keeps the bit-equality contract)
    order = jnp.argsort(g_key, stable=True)
    sdst = g_key[order]
    nfree = jnp.sum(hosts.eq_time == SIMTIME_MAX, axis=1,
                    dtype=jnp.int32)
    in_pkt, in_time, kept_sorted = _deliver_dense(
        nfree, order, sdst, g_pkt, g_arr, IN, cfg, lo=lo)
    hosts = hosts.replace(stats=hosts.stats.at[:, ST_PKTS_DROP_NET].add(
        jnp.sum(net_dropped.reshape(Hl, O), axis=1, dtype=jnp.int64)))

    # accept flags back into the received-list original order, then
    # back to the SOURCE shards
    kept_recv = jnp.zeros(g_key.shape, jnp.bool_).at[order].set(
        kept_sorted)
    if cfg.exchange_a2a and n_shards > 1:
        # reverse hop: [S, B] accept flags per bucket slot I received
        # -> per bucket slot I sent
        acc_bkt = jax.lax.all_to_all(
            kept_recv.reshape(n_shards, -1).astype(jnp.int32),
            AXIS, split_axis=0, concat_axis=0, tiled=False)
        acc_local = jnp.zeros((Nl,), jnp.bool_).at[tgt.reshape(-1)].set(
            acc_bkt.reshape(-1) > 0, mode="drop")
    else:
        # each shard accepted only its own dests; OR across shards,
        # then take my segment of the gathered (source-major) order
        acc_all = jax.lax.psum(kept_recv.astype(jnp.int32), AXIS) > 0
        acc_local = jax.lax.dynamic_slice(
            acc_all, (my.astype(jnp.int32) * Nl,), (Nl,))

    stay = deliver & ~acc_local
    fanin_stay = stay & shipped
    a2a_stay = stay & ~shipped
    hosts = hosts.replace(stats=hosts.stats
                          .at[:, ST_DEFER_FANIN].add(jnp.sum(
                              fanin_stay.reshape(Hl, O), axis=1,
                              dtype=jnp.int64))
                          .at[:, ST_DEFER_A2A].add(jnp.sum(
                              a2a_stay.reshape(Hl, O), axis=1,
                              dtype=jnp.int64)))
    hosts = _trace_tx(hosts, hp, cfg, pkts, stimes,
                      (acc_local | net_dropped).reshape(Hl, O))
    hosts = _carry_outbox(hosts, pkts, stimes, arrival, stay, O)
    return merge_arrivals(hosts, hp, cfg, in_pkt, in_time)


def a2a_bucket_cap(cfg: EngineConfig, lcfg: EngineConfig) -> int:
    """Bucket slots per (src shard, dst shard) pair for the v2
    exchange: explicit cfg.a2acap, else 4x the uniform-traffic share
    of the shard outbox (min 64), never more than the whole outbox."""
    Nl = lcfg.num_hosts * cfg.obcap
    n_shards = cfg.num_hosts // lcfg.num_hosts
    if cfg.a2acap:
        return min(cfg.a2acap, Nl)
    return min(max(64, (4 * Nl) // n_shards), Nl)


def _a2a_hop(cfg, lcfg, sortkey_l, arrival, pkts, n_shards):
    """v2 cross-shard hop (module docstring): bucket by destination
    shard, exchange buckets, return the received (key, arrival, pkt)
    triple in the same global source order v1's gather produces, plus
    the (oj, cell_ok) bucket->outbox-position mapping the caller uses
    to route accept flags back and to identify the overflow tail
    (which now DEFERS at the source — ST_DEFER_A2A — instead of
    dropping).

    Order argument: the local stable sort is keyed by destination
    SHARD only, so packets for one shard stay in local outbox order;
    all_to_all concatenates buckets in source-shard order; hence the
    received sequence is source-shard-major, source-outbox-minor —
    exactly v1's gathered order filtered to this shard's traffic. The
    caller's stable sort by destination then matches v1 bit for bit.
    """
    Hl, O = lcfg.num_hosts, cfg.obcap
    Nl = Hl * O
    B = a2a_bucket_cap(cfg, lcfg)

    dshard = jnp.where(sortkey_l < cfg.num_hosts, sortkey_l // Hl,
                       n_shards)  # n_shards = invalid/dropped bucket
    order_l = jnp.argsort(dshard, stable=True)
    sds = dshard[order_l]

    shards_r = jnp.arange(n_shards, dtype=sds.dtype)
    first_of = jnp.searchsorted(sds, shards_r, side="left")
    count_of = jnp.searchsorted(sds, shards_r, side="right") - first_of

    r = jnp.arange(B)
    j = jnp.clip(first_of[:, None] + r[None, :], 0, Nl - 1)  # [S, B]
    oj = order_l[j]
    cell_ok = r[None, :] < jnp.minimum(count_of, B)[:, None]
    bkt_key = jnp.where(cell_ok, sortkey_l[oj], cfg.num_hosts)
    bkt_arr = jnp.where(cell_ok, arrival[oj], 0)
    bkt_pkt = jnp.where(cell_ok[:, :, None], pkts[oj], jnp.int32(0))

    g_key = jax.lax.all_to_all(bkt_key, AXIS, split_axis=0,
                               concat_axis=0, tiled=False)
    g_arr = jax.lax.all_to_all(bkt_arr, AXIS, split_axis=0,
                               concat_axis=0, tiled=False)
    g_pkt = jax.lax.all_to_all(bkt_pkt, AXIS, split_axis=0,
                               concat_axis=0, tiled=False)
    N2 = n_shards * B
    return (g_key.reshape(N2), g_arr.reshape(N2),
            g_pkt.reshape(N2, P.PKT_WORDS), oj, cell_ok)


def _windows_body(hosts, hp, sh, wstart, wend, cfg, lcfg, max_windows,
                  reduce_pc=False):
    """Per-shard window loop (runs inside shard_map). `reduce_pc`
    psums the pass counters back to a replicated [NR] total (the
    multi-process path: a host-sharded output would be
    non-addressable there, and the per-shard mix is a single-process
    observability feature)."""

    def next_time_global(h):
        return jax.lax.pmin(jnp.min(h.eq_next), AXIS)

    def next_wakeup_global(h):
        # window-advance bound includes source-carried arrivals
        # (engine.window.next_wakeup)
        return jax.lax.pmin(jnp.minimum(jnp.min(h.eq_next),
                                        jnp.min(h.ob_next)), AXIS)

    from ..engine.window import pass_labels
    NR = len(pass_labels(cfg, lcfg.num_hosts))

    def win_cond(carry):
        _, ws, _, i, _ = carry
        return (i < max_windows) & (ws < sh.stop_time) & (ws < SIMTIME_MAX)

    def win_body(carry):
        hosts, ws, we, i, pc = carry
        we_eff = jnp.minimum(we, sh.stop_time)
        ran = next_time_global(hosts) < we_eff

        # the drain loop is SHARD-LOCAL (engine.window.drain_window has
        # no collectives): each shard runs only the passes its own rows
        # need — the reference's per-thread round execution before the
        # barrier (shd-scheduler.c:602-635). Only the window advance
        # below is a global decision. Rung choice and pass counters are
        # per-shard; counters are psum-reduced at return. The hot/cold
        # split applies per shard: drain_window splits the shard-local
        # rows into hot_fields(cfg) and rejoins before the exchange,
        # which (like the checkpoint/digest pulls) stays whole-tree —
        # so the mesh-vs-single digest equality contract is untouched.
        # passcope named_scope stamps (stateflow entry names — see
        # engine.window.win_body; the sharded exchange gets its own
        # label, matching the stateflow ENTRIES row)
        with jax.named_scope("drain"):
            hosts, pc = drain_window(hosts, hp, sh, we_eff, cfg, pc)
        with jax.named_scope("cap_peaks"):
            hosts = update_cap_peaks(hosts)
        ob0 = jax.lax.psum(jnp.sum(hosts.ob_cnt), AXIS)
        with jax.named_scope("exchange.sharded"):
            hosts = exchange_sharded(hosts, hp, sh, cfg, lcfg)
        with jax.named_scope("cap_peaks"):
            hosts = update_cap_peaks(hosts)
        # anti-livelock, global decision (engine.window.win_body)
        with jax.named_scope("advance"):
            ob1 = jax.lax.psum(jnp.sum(hosts.ob_cnt), AXIS)
            progressed = ran | (ob1 < ob0)
            nt = jnp.where(progressed, next_wakeup_global(hosts),
                           next_time_global(hosts))
            we2 = jnp.where(nt == SIMTIME_MAX, SIMTIME_MAX,
                            nt + sh.min_jump)
        return hosts, nt, we2, i + 1, pc

    hosts, ws, we, i, pc = jax.lax.while_loop(
        win_cond, win_body,
        (hosts, wstart, wend, jnp.int32(0), jnp.zeros((NR,), jnp.int64)))
    if reduce_pc:
        return hosts, ws, we, i, jax.lax.psum(pc, AXIS)
    # per-shard rung mix (out_specs shards it into [S, NR]): shards
    # run the same pass COUNT in lockstep but choose rungs
    # independently, so the per-shard mix is the load-imbalance
    # signal — a shard stuck on dense passes while its peers ride the
    # small rungs is the busy shard (obs.metrics `shards` section)
    return hosts, ws, we, i, pc


_RWS_INSTANCES = {}


def run_windows_sharded_aot(cfg: EngineConfig, max_windows: int,
                            mesh: Mesh):
    """The AotJit wrapping the (cfg, max_windows, mesh) sharded chunk
    program — shared by run_windows_sharded and the serving layer's
    pre-warm path. The cache_scope additionally pins the mesh's
    concrete device ids: the persistent executable cache
    (serving.aotcache) must never hand a program compiled for one
    device assignment to another."""
    from ..core.jitcache import AotJit
    from ..engine.window import pass_labels

    n = mesh.shape[AXIS]
    assert cfg.num_hosts % n == 0, (
        f"num_hosts={cfg.num_hosts} not divisible by {n} shards "
        "(Simulation pads automatically)")

    key = (cfg, max_windows, mesh)
    fn = _RWS_INSTANCES.get(key)
    if fn is None:
        # multi-process meshes keep the old replicated pass TOTAL (a
        # host-sharded counter output would be non-addressable across
        # processes); the per-shard mix is a single-process feature
        multiproc = jax.process_count() > 1
        lcfg = dataclasses.replace(cfg, num_hosts=cfg.num_hosts // n)
        NR = len(pass_labels(cfg, lcfg.num_hosts))
        body = partial(_windows_body, cfg=cfg, lcfg=lcfg,
                       max_windows=max_windows, reduce_pc=multiproc)
        in_specs = (PS(AXIS), PS(AXIS), PS(), PS(), PS())
        # pass counters come back sharded: each shard's [NR] mix
        # concatenates to [n * NR], reshaped to [n, NR] below
        out_specs = (PS(AXIS), PS(), PS(), PS(),
                     PS() if multiproc else PS(AXIS))
        try:
            # the row-level engine mixes unvarying constants into
            # sharded state everywhere (e.g. `.at[slot].set(True)`),
            # which trips the strict varying-axes typecheck; the
            # collectives here are hand-placed, so skip it
            smapped = jax.shard_map(
                body, mesh=mesh, in_specs=in_specs,
                out_specs=out_specs, check_vma=False)
        except (AttributeError, TypeError):
            # jax < 0.5 (e.g. the 0.4.37 CPU dev container): the API
            # lives in jax.experimental and the skip-typecheck knob is
            # named check_rep
            from jax.experimental.shard_map import shard_map as _sm
            smapped = _sm(body, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)

        def impl(hosts, hp, sh, wstart, wend):
            h, ws, we, i, pc = smapped(hosts, hp, sh, wstart, wend)
            if not multiproc:
                pc = pc.reshape(n, NR)
            return h, ws, we, i, pc

        impl.__name__ = f"run_windows_sharded_v{len(_RWS_INSTANCES)}"
        impl.__qualname__ = impl.__name__
        from ..obs.ledger import fingerprint_of
        devs = "-".join(str(d.id) for d in mesh.devices.flat)
        fn = AotJit(impl, donate_argnums=(0,),
                    cache_scope=(f"run_windows_sharded.c{max_windows}"
                                 f".s{n}.d{devs}"
                                 f".{fingerprint_of(cfg)}"))
        _RWS_INSTANCES[key] = fn
    return fn


def run_windows_sharded(hosts, hp, sh, wstart, wend, cfg: EngineConfig,
                        max_windows: int, mesh: Mesh):
    """Sharded equivalent of engine.window.run_windows.

    Near-identical contract: returns (hosts, wstart', wend',
    windows_run, pass_counts) with hosts block-sharded over the
    mesh's "hosts" axis — except pass_counts is PER-SHARD, shape
    [n_shards, NR] (each shard's own rung mix; ``pass_counts.sum(0)``
    is the single-chip total). Shards run the same pass COUNT in
    lockstep but pick rungs independently, so the per-shard mix is
    the cross-shard load-imbalance signal the metrics layer publishes
    (engine.sim -> obs.metrics ``shards`` section). On a
    MULTI-PROCESS mesh pass_counts stays the replicated [NR] total
    (sharded counters would be non-addressable). AOT-compiled per
    (cfg, max_windows, mesh) — see core.jitcache for why.
    """
    return run_windows_sharded_aot(cfg, max_windows, mesh)(
        hosts, hp, sh, wstart, wend)


def _put_tree(tree, mesh: Mesh, spec):
    """Place one pytree of HOST-LOCAL (numpy-convertible) arrays with
    the given partition spec; multi-process uses
    make_array_from_callback (every process holds the same full
    arrays — deterministic build), single-process plain device_put."""
    s = NamedSharding(mesh, spec)
    if jax.process_count() > 1:
        import numpy as _np

        def put(x):
            arr = _np.asarray(x)
            return jax.make_array_from_callback(
                arr.shape, s, lambda idx: arr[idx])

        return jax.tree.map(put, tree)
    return jax.tree.map(lambda x: jax.device_put(x, s), tree)


def put_hosts(hosts, mesh: Mesh):
    """Block-shard just the Hosts pytree (e.g. checkpoint-restored
    state; params/shared are already placed)."""
    return _put_tree(hosts, mesh, PS(AXIS))


def put_shared(sh, mesh: Mesh):
    """Replicate just the Shared pytree (e.g. after fault injection
    rewrote the lat/rel tables or a segment stop_time; hosts/params
    are already placed)."""
    return _put_tree(sh, mesh, PS())


def device_put_sharded(hosts, hp, sh, mesh: Mesh):
    """Place the simulation state for a sharded run: Hosts/HostParams
    block-sharded over the hosts axis, Shared replicated."""
    return (_put_tree(hosts, mesh, PS(AXIS)),
            _put_tree(hp, mesh, PS(AXIS)),
            _put_tree(sh, mesh, PS()))
