"""Multi-machine (multi-process) backend: the DCN tier.

This is the realization of the reference's designed-but-stubbed
multi-slave architecture (SURVEY §2.9 item 6): the Master/Slave split
with "once we get multiple slaves" TODOs (shd-master.c:415-416), the
Message stub (core/work/shd-message.h), and the single cross-machine
hook point in worker_sendPacket (shd-worker.c:250-252). Where the
reference anticipated hand-written socket messaging between slave
processes, here a "slave" is a JAX process: the SAME shard_map window
program spans all processes' devices, and the exchange's all_gather
rides ICI within a slice and DCN between processes — no new wire
protocol, no new engine code. The cross-machine seam the reference
left as a TODO is exactly `parallel.shard.exchange_sharded`.

Usage (one call per process, before building the Simulation):

    from shadow_tpu.parallel import dist
    dist.init(coordinator="host0:9999", num_processes=4, process_id=i)
    mesh = dist.global_mesh()
    report = Simulation(scenario).run(mesh=mesh)

Every process executes the same scenario build (deterministic, so all
processes agree on tables and seeds — the reference's equivalent was
the master broadcasting config to slaves) and the same host-side
window loop; device arrays are globally sharded. Results: per-host
stats are gathered to every process at the end (small), so reports
agree everywhere.

Tested without a cluster by spawning N local processes over loopback
TCP with CPU devices (tests/test_distributed.py), the same way the
single-process engine tests shard over 8 virtual CPU devices.
"""

from __future__ import annotations

import numpy as np


_initialized = False


def init(coordinator: str, num_processes: int, process_id: int,
         local_device_count: int = None):
    """Initialize the JAX distributed runtime (idempotent).

    `coordinator` is "host:port" of process 0 — the Master role of the
    reference's Master/Slave seam; all processes block here until the
    full set has joined (the reference's anticipated slave handshake).
    """
    global _initialized
    if _initialized:
        return
    import jax

    if local_device_count is not None:
        # CPU tier: carve this process's virtual device count before
        # backends initialize
        import os
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags +
                f" --xla_force_host_platform_device_count="
                f"{local_device_count}").strip()
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    _initialized = True


def global_mesh():
    """1-D mesh over ALL processes' devices (the "hosts" axis of
    parallel.shard). Within a process the axis rides ICI; between
    processes it rides DCN — XLA places the collectives."""
    import jax
    from .shard import AXIS
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()), (AXIS,))


def is_multiprocess() -> bool:
    import jax

    return jax.process_count() > 1


def gather_stats(stats) -> np.ndarray:
    """Fetch a globally-sharded [H, N] array to every process.

    The end-of-run equivalent of the reference's slave->master result
    handoff: per-host stats shards live on their owning processes;
    this all-gathers them so each process can build the full report.

    Instrumented (obs.trace): the cross-process all-gather is this
    backend's scheduler barrier — the direct analogue of the barrier
    waits the reference self-times (shd-scheduler.c:250-252) — so each
    call records a ``dist.allgather`` span when tracing is on. Every
    process records its own span; only process 0 writes a file.
    """
    import jax

    if not is_multiprocess():
        return np.asarray(stats)
    from jax.experimental import multihost_utils

    from ..obs import trace as TR
    t0 = TR.TRACER.now() if TR.ENABLED else 0
    out = np.asarray(
        multihost_utils.process_allgather(stats, tiled=True))
    if TR.ENABLED:
        TR.TRACER.complete("dist.allgather", t0,
                           args={"bytes": int(out.nbytes)})
    return out
