#!/usr/bin/env python3
"""Fleet chaos smoke (the verify skill's round-10 gate): submit a
small sweep with a planted always-crashing config, SIGKILL one worker
child AND the scheduler mid-flight, restart ``fleet run``, and assert

- the sweep completes (exit 3: drained, poison quarantined),
- the surviving runs' digest chains match an uninterrupted reference
  (tools/divergence.py exit 0),
- the poison ended quarantined with its crash-cause journal, without
  stalling the queue.

~6 CLI child processes, each paying the cold XLA compile on a CPU
box (≈10-15 min there; minutes on chip). Usage:

    python tools/fleet_smoke.py [workdir]    # default /tmp/fleet_smoke
"""

import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TOPO = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="latency" attr.type="double" for="edge" id="d7"/>
  <key attr.name="packetloss" attr.type="double" for="edge" id="d9"/>
  <key attr.name="packetloss" attr.type="double" for="node" id="d0"/>
  <key attr.name="bandwidthup" attr.type="int" for="node" id="d4"/>
  <key attr.name="bandwidthdown" attr.type="int" for="node" id="d3"/>
  <graph edgedefault="undirected">
    <node id="poi"><data key="d0">0.0</data>
      <data key="d3">10240</data><data key="d4">10240</data></node>
    <edge source="poi" target="poi"><data key="d7">25.0</data>
      <data key="d9">0.0</data></edge>
  </graph></graphml>"""

CAPS = "qcap=16,scap=4,obcap=8,incap=16,chunk=8"


def main():
    d = sys.argv[1] if len(sys.argv) > 1 else "/tmp/fleet_smoke"
    os.makedirs(d, exist_ok=True)
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    xml = os.path.join(d, "phold.xml")
    with open(xml, "w") as f:
        f.write(f"""<shadow stoptime="6">
  <topology><![CDATA[{TOPO}]]></topology>
  <host id="node" quantity="8">
    <process plugin="phold" starttime="1"
             arguments="port=9000 mean=300ms size=64 init=1"/>
  </host>
</shadow>""")

    def sh(*a, **kw):
        return subprocess.run(
            [sys.executable, "-m", "shadow_tpu"] + list(a), env=env,
            cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, **kw)

    ref = os.path.join(d, "ref.jsonl")
    r = sh(xml, "--seed", "7", "--engine-caps", CAPS,
           "--digest", ref, "--digest-every", "8")
    assert r.returncode == 0, r.stdout.decode()[-2000:]
    print("reference done", flush=True)

    q = os.path.join(d, "q")
    for s in ("7", "8"):
        r = sh("fleet", "submit", q, xml, "--id", f"m{s}",
               "--checkpoint-every", "1", "--digest-every", "8",
               "--", "--seed", s, "--engine-caps", CAPS)
        assert r.returncode == 0, r.stdout.decode()
    r = sh("fleet", "submit", q, xml, "--id", "poison",
           "--max-retries", "1", "--checkpoint-every", "1",
           "--env", "SHADOW_TPU_CRASH_SIM_NS=2000000000",
           "--", "--seed", "7", "--engine-caps", CAPS)
    assert r.returncode == 0, r.stdout.decode()

    sched_log = os.path.join(d, "sched.log")

    def fleet_run():
        # scheduler output goes to a FILE, not a PIPE nobody drains —
        # a long drain's log would fill the 64 KiB pipe buffer and
        # deadlock the scheduler against our wait()
        with open(sched_log, "ab") as lf:
            return subprocess.Popen(
                [sys.executable, "-m", "shadow_tpu", "fleet", "run",
                 q, "--workers", "2", "--backoff", "0.2"], env=env,
                cwd=REPO, stdout=lf, stderr=subprocess.STDOUT)

    claims = os.path.join(q, "claims")

    def wait_progress(deadline_s=900):
        end = time.time() + deadline_s
        while time.time() < end:
            for fn in (os.listdir(claims)
                       if os.path.isdir(claims) else []):
                rid = fn[:-len(".claim")]
                if rid == "poison":
                    continue
                dg = os.path.join(q, "runs", rid, "digest.jsonl")
                if os.path.exists(dg) and os.path.getsize(dg) > 0:
                    return rid
            time.sleep(0.2)
        raise AssertionError("no run made digest progress in time")

    p = fleet_run()
    rid = wait_progress()            # a real run is mid-flight now
    with open(os.path.join(claims, rid + ".claim")) as f:
        pid = json.load(f)["pid"]
    os.kill(pid, signal.SIGKILL)
    print(f"killed worker {rid} (pid {pid})", flush=True)
    wait_progress()
    os.kill(p.pid, signal.SIGKILL)
    p.wait()
    print("killed scheduler", flush=True)

    p = fleet_run()                  # restart completes the sweep
    rc = p.wait()
    with open(sched_log, "rb") as f:
        out = f.read().decode(errors="replace")
    assert rc == 3, f"fleet run rc={rc} (want 3):\n{out[-3000:]}"

    js = json.loads(sh("fleet", "status", q, "--json").stdout)
    assert js["m7"]["state"] == "done", js["m7"]
    assert js["m8"]["state"] == "done", js["m8"]
    assert js["poison"]["state"] == "quarantined", js["poison"]
    crash_log = os.path.join(q, "runs", "poison", "crash.jsonl")
    assert os.path.getsize(crash_log) > 0, "no crash causes journaled"
    drc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "divergence.py"),
         ref, os.path.join(q, "runs", "m7", "digest.jsonl")],
        env=env).returncode
    assert drc == 0, f"divergence exit {drc} for m7"
    print("FLEET-CHAOS-SMOKE-OK", flush=True)


if __name__ == "__main__":
    main()
