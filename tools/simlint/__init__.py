"""`python -m tools.simlint` — the simlint static-analysis gate.

The implementation lives in shadow_tpu/lint/ (determinism lints, JAX
tracing-hazard lints, shim-protocol conformance, state-access/dtype
flow; see docs/static-analysis.md). This wrapper loads that package
WITHOUT importing the `shadow_tpu` package itself:
shadow_tpu/__init__.py imports jax (seconds of startup and an
accelerator-config side effect), and the lint gate must stay a
few-seconds, dependency-free check — it runs on a CI box with no jax
installed at all (pinned by test_lint.test_gate_runs_without_jax).
"""

import importlib.util
import sys
from pathlib import Path

_LINT_DIR = Path(__file__).resolve().parents[2] / "shadow_tpu" / "lint"


def load():
    """Import shadow_tpu.lint standalone (no parent-package import).

    Registering the module under its real dotted name keeps relative
    imports inside the package working; Python only consults
    sys.modules for the PARENT of a submodule import, so `shadow_tpu`
    itself is never touched.
    """
    name = "shadow_tpu.lint"
    if name not in sys.modules:
        spec = importlib.util.spec_from_file_location(
            name, _LINT_DIR / "__init__.py",
            submodule_search_locations=[str(_LINT_DIR)])
        mod = importlib.util.module_from_spec(spec)
        sys.modules[name] = mod
        try:
            spec.loader.exec_module(mod)
        except BaseException:
            del sys.modules[name]
            raise
    return sys.modules[name]
