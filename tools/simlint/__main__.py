import sys

from . import load

sys.exit(load().main())
