#!/usr/bin/env python3
"""Generate GraphML topologies (the analogue of the reference's
src/tools/topology toolkit generators).

Usage:
  python tools/gen_topology.py single --latency 25 --bw 102400
  python tools/gen_topology.py ring --n 8 --latency 10
  python tools/gen_topology.py star --n 16 --latency 20
  python tools/gen_topology.py er --n 64 --p 0.1 --latency-range 5 80 \
      --loss 0.001 --seed 3     # Erdos-Renyi + spanning tree (connected)

Writes GraphML to stdout (or --out FILE) in the attribute schema the
simulator and the reference both read (latency ms, packetloss,
bandwidthup/down KiB/s).
"""

import argparse
import random
import sys

HEADER = """<?xml version="1.0" encoding="utf-8"?>
<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="packetloss" attr.type="double" for="edge" id="d9" />
  <key attr.name="latency" attr.type="double" for="edge" id="d7" />
  <key attr.name="type" attr.type="string" for="node" id="d5" />
  <key attr.name="bandwidthup" attr.type="int" for="node" id="d4" />
  <key attr.name="bandwidthdown" attr.type="int" for="node" id="d3" />
  <key attr.name="packetloss" attr.type="double" for="node" id="d0" />
  <graph edgedefault="undirected">"""


def node(i, bw, loss=0.0, typ="net"):
    return (f'    <node id="poi-{i}"><data key="d0">{loss}</data>'
            f'<data key="d3">{bw}</data><data key="d4">{bw}</data>'
            f'<data key="d5">{typ}</data></node>')


def edge(a, b, lat, loss=0.0):
    return (f'    <edge source="poi-{a}" target="poi-{b}">'
            f'<data key="d7">{lat}</data>'
            f'<data key="d9">{loss}</data></edge>')


def er_topology(n=64, p=0.1, seed=1, bw=102400, loss=0.0,
                latency=25.0, latency_range=(5.0, 80.0)):
    """Connected Erdős–Rényi GraphML as a string: random graph plus a
    spanning tree (connectivity), 1ms self-loops. The LIBRARY entry
    point — tools.baseline_configs._plab_or_fallback builds the
    at-scale configs' stand-in topology through this when the
    reference PlanetLab file is absent (e.g. the CPU dev container;
    the import was previously broken because only the CLI existed).

    `latency_range=None` gives every edge the fixed `latency` WITHOUT
    consuming randomness (the CLI's --latency mode); a range — even a
    degenerate (x, x) one — draws one uniform per edge. The
    distinction preserves the pre-library CLI's RNG stream in both
    modes: same seed, same edge set."""
    rng = random.Random(seed)

    def lat():
        if latency_range is None:
            return latency
        return round(rng.uniform(*latency_range), 2)

    lines = [HEADER]
    for i in range(n):
        lines.append(node(i, bw))
    for i in range(n):
        lines.append(edge(i, i, 1.0, 0.0))
    for i in range(1, n):
        lines.append(edge(rng.randrange(i), i, lat(), loss))
    for a in range(n):
        for b in range(a + 1, n):
            if rng.random() < p:
                lines.append(edge(a, b, lat(), loss))
    lines.append("  </graph>\n</graphml>")
    return "\n".join(lines)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("kind", choices=["single", "ring", "star", "er"])
    ap.add_argument("--n", type=int, default=8)
    ap.add_argument("--latency", type=float, default=25.0)
    ap.add_argument("--latency-range", type=float, nargs=2)
    ap.add_argument("--bw", type=int, default=102400, help="KiB/s")
    ap.add_argument("--loss", type=float, default=0.0)
    ap.add_argument("--p", type=float, default=0.1,
                    help="er edge probability")
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--out", default="-")
    args = ap.parse_args()

    rng = random.Random(args.seed)

    def lat():
        if args.latency_range:
            lo, hi = args.latency_range
            return round(rng.uniform(lo, hi), 2)
        return args.latency

    lines = [HEADER]
    if args.kind == "single":
        lines.append(node(0, args.bw))
        lines.append(edge(0, 0, lat(), args.loss))
    elif args.kind == "ring":
        for i in range(args.n):
            lines.append(node(i, args.bw))
        for i in range(args.n):
            lines.append(edge(i, i, 1.0, 0.0))
            lines.append(edge(i, (i + 1) % args.n, lat(), args.loss))
    elif args.kind == "star":
        for i in range(args.n):
            lines.append(node(i, args.bw))
        lines.append(edge(0, 0, 1.0, 0.0))
        for i in range(1, args.n):
            lines.append(edge(i, i, 1.0, 0.0))
            lines.append(edge(0, i, lat(), args.loss))
    else:  # er: random graph + spanning tree for connectivity
        text = er_topology(n=args.n, p=args.p, seed=args.seed,
                           bw=args.bw, loss=args.loss,
                           latency=args.latency,
                           latency_range=(tuple(args.latency_range)
                                          if args.latency_range
                                          else None))
        lines = None
    if lines is not None:
        lines.append("  </graph>\n</graphml>")
        text = "\n".join(lines)
    if args.out == "-":
        print(text)
    else:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"wrote {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()
