#!/usr/bin/env python3
"""Plot shadow_tpu heartbeat metrics (the analogue of the reference's
src/tools/plot-shadow.py over parse-shadow output).

Usage:
  python tools/plot_heartbeat.py sim.log --out sim.pdf
  python tools/plot_heartbeat.py sim.log --metric bytes_recv --out x.png
  python tools/plot_heartbeat.py sim.log --netscope run.netscope.jsonl

Produces per-metric time series: one line per host plus the
aggregate. ``--netscope`` appends the network observatory panels
(obs.netscope): per-kind sample counts and the exact p50/p99
percentile curves over simulated time, from the run's JSONL stream.
``--occupancy`` appends the lockstep-waste panel (obs.passcope):
the cumulative wasted-lane fraction per heartbeat, from the
[summary] family's ``waste=`` column.
"""

import argparse
import collections
import csv
import io
import os
import subprocess
import sys

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402

METRICS = ["events", "pkts_sent", "pkts_recv", "bytes_sent",
           "bytes_recv", "retransmits", "drop_net", "transfers_done"]

PARSER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "parse_heartbeat.py")


def load(log_path):
    out = subprocess.run(
        [sys.executable, PARSER, log_path],
        capture_output=True, text=True, check=True).stdout
    rows = list(csv.DictReader(io.StringIO(out)))
    series = collections.defaultdict(lambda: collections.defaultdict(list))
    for r in rows:
        for m in METRICS:
            series[m][r["host"]].append((int(r["time"]),
                                         int(r[m])))
    return series


def load_netscope(path):
    """-> {kind: [(t_s, n, p50_us, p99_us), ...]} via the parser's
    --netscope CSV (one reader for log and stream alike)."""
    out = subprocess.run(
        [sys.executable, PARSER, "--netscope", path],
        capture_output=True, text=True, check=True).stdout
    rows = list(csv.DictReader(io.StringIO(out)))
    kinds = sorted({c[:-2] for c in (rows[0] if rows else {})
                    if c.endswith("_n")})
    series = {k: [] for k in kinds}
    for r in rows:
        for k in kinds:
            series[k].append((float(r["time"]), int(r[f"{k}_n"]),
                              int(r[f"{k}_p50_us"]),
                              int(r[f"{k}_p99_us"])))
    return series


def load_occupancy(log_path):
    """-> [(t_s, waste_frac)] via the parser's --occupancy CSV; rows
    without the waste= column (pre-passcope runs) are skipped."""
    out = subprocess.run(
        [sys.executable, PARSER, "--occupancy", log_path],
        capture_output=True, text=True, check=True).stdout
    return [(float(r["time"]), float(r["waste"]))
            for r in csv.DictReader(io.StringIO(out)) if r["waste"]]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("log")
    ap.add_argument("--out", default="heartbeat.pdf")
    ap.add_argument("--metric", action="append",
                    help=f"subset of {METRICS}")
    ap.add_argument("--netscope", default=None, metavar="JSONL",
                    help="append network observatory panels from this "
                         "netscope stream (per-kind sample counts + "
                         "p50/p99 curves)")
    ap.add_argument("--occupancy", action="store_true",
                    help="append the lockstep-waste panel (the "
                         "[summary] family's waste= column, "
                         "obs.passcope)")
    args = ap.parse_args()

    series = load(args.log)
    metrics = args.metric or METRICS
    ns = load_netscope(args.netscope) if args.netscope else None
    ns_kinds = ([k for k, pts in ns.items()
                 if any(n for _, n, _, _ in pts)] if ns else [])
    occ = load_occupancy(args.log) if args.occupancy else []
    n_panels = (len(metrics) + (2 if ns_kinds else 0)
                + (1 if occ else 0))
    fig, axes = plt.subplots(n_panels, 1,
                             figsize=(8, 2.2 * n_panels),
                             sharex=True, squeeze=False)
    for ax, m in zip(axes[:, 0], metrics):
        total = collections.Counter()
        for host, pts in sorted(series[m].items()):
            xs = [t for t, _ in pts]
            ys = [v for _, v in pts]
            ax.plot(xs, ys, alpha=0.35, linewidth=0.8)
            for t, v in pts:
                total[t] += v
        if total:
            xs = sorted(total)
            ax.plot(xs, [total[t] for t in xs], color="black",
                    linewidth=1.6, label="all hosts")
            ax.legend(loc="upper left", fontsize=7)
        ax.set_ylabel(m, fontsize=8)
        ax.tick_params(labelsize=7)
    if ns_kinds:
        ax_n, ax_p = axes[len(metrics), 0], axes[len(metrics) + 1, 0]
        for k in ns_kinds:
            pts = ns[k]
            xs = [t for t, _, _, _ in pts]
            ax_n.plot(xs, [n for _, n, _, _ in pts], linewidth=1.2,
                      label=k)
            ax_p.plot(xs, [p50 for _, _, p50, _ in pts],
                      linewidth=1.0, label=f"{k} p50")
            ax_p.plot(xs, [p99 for _, _, _, p99 in pts],
                      linewidth=1.0, linestyle="--", label=f"{k} p99")
        ax_n.set_ylabel("net samples (cum)", fontsize=8)
        ax_n.legend(loc="upper left", fontsize=7)
        ax_p.set_yscale("log")
        ax_p.set_ylabel("latency (us)", fontsize=8)
        ax_p.legend(loc="upper left", fontsize=6, ncol=2)
        for ax in (ax_n, ax_p):
            ax.tick_params(labelsize=7)
    if occ:
        # lockstep-waste trend (obs.passcope): cumulative wasted-lane
        # fraction per heartbeat — a curve bending UP mid-run names
        # when the drain's rung selection started overshooting
        ax_o = axes[-1, 0]
        ax_o.plot([t for t, _ in occ], [w for _, w in occ],
                  color="firebrick", linewidth=1.4, label="waste")
        ax_o.set_ylim(0, 1)
        ax_o.set_ylabel("lane waste frac", fontsize=8)
        ax_o.legend(loc="upper left", fontsize=7)
        ax_o.tick_params(labelsize=7)
    axes[-1, 0].set_xlabel("simulated time (s)", fontsize=8)
    fig.tight_layout()
    fig.savefig(args.out)
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
