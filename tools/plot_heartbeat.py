#!/usr/bin/env python3
"""Plot shadow_tpu heartbeat metrics (the analogue of the reference's
src/tools/plot-shadow.py over parse-shadow output).

Usage:
  python tools/plot_heartbeat.py sim.log --out sim.pdf
  python tools/plot_heartbeat.py sim.log --metric bytes_recv --out x.png

Produces per-metric time series: one line per host plus the aggregate.
"""

import argparse
import collections
import csv
import io
import os
import subprocess
import sys

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402

METRICS = ["events", "pkts_sent", "pkts_recv", "bytes_sent",
           "bytes_recv", "retransmits", "drop_net", "transfers_done"]


def load(log_path):
    parser = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "parse_heartbeat.py")
    out = subprocess.run(
        [sys.executable, parser, log_path],
        capture_output=True, text=True, check=True).stdout
    rows = list(csv.DictReader(io.StringIO(out)))
    series = collections.defaultdict(lambda: collections.defaultdict(list))
    for r in rows:
        for m in METRICS:
            series[m][r["host"]].append((int(r["time"]),
                                         int(r[m])))
    return series


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("log")
    ap.add_argument("--out", default="heartbeat.pdf")
    ap.add_argument("--metric", action="append",
                    help=f"subset of {METRICS}")
    args = ap.parse_args()

    series = load(args.log)
    metrics = args.metric or METRICS
    fig, axes = plt.subplots(len(metrics), 1,
                             figsize=(8, 2.2 * len(metrics)),
                             sharex=True, squeeze=False)
    for ax, m in zip(axes[:, 0], metrics):
        total = collections.Counter()
        for host, pts in sorted(series[m].items()):
            xs = [t for t, _ in pts]
            ys = [v for _, v in pts]
            ax.plot(xs, ys, alpha=0.35, linewidth=0.8)
            for t, v in pts:
                total[t] += v
        if total:
            xs = sorted(total)
            ax.plot(xs, [total[t] for t in xs], color="black",
                    linewidth=1.6, label="all hosts")
            ax.legend(loc="upper left", fontsize=7)
        ax.set_ylabel(m, fontsize=8)
        ax.tick_params(labelsize=7)
    axes[-1, 0].set_xlabel("simulated time (s)", fontsize=8)
    fig.tight_layout()
    fig.savefig(args.out)
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
