#!/usr/bin/env python3
"""Generalized perf A/B: any config x any set of EngineConfig knobs,
paired interleaved reps, median/spread significance, ledger entries
and a BASELINE.md-ready table.

Generalizes tools/phold_ab.py (which is now a thin wrapper): instead
of a hard-coded phold variant list, A/B ANY scenario the perf tooling
knows (phold + the baseline_configs names) across ANY set of
EngineConfig overrides. Protocol:

- one short warm-up run per variant (pays each variant's compile
  off the clock; stop_time is a dynamic scalar so the measured run
  reuses the program);
- PAIRED INTERLEAVED reps — rep r runs every variant once before rep
  r+1 starts — so machine drift (thermal, background load) lands on
  all variants equally instead of biasing whoever ran last;
- per variant: sorted rep rates, median, spread; the verdict vs the
  first (baseline) variant is "significant" only when the median gap
  exceeds the two spreads combined — single-rep deltas are not
  evidence (round-3 verdict);
- every variant's event count must be IDENTICAL (the compaction /
  exchange knobs are bit-exact by contract): a mismatch is reported
  loudly as a correctness bug, and that variant's ledger entry is
  withheld;
- results append to the perf ledger (scenario ``<config>+<variant>``,
  fingerprint over the variant's full EngineConfig) and print as a
  markdown table for BASELINE.md, stamped with the platform so
  CPU-container numbers are never mistaken for chip numbers.

Usage:
  python tools/perf_ab.py phold --n 4096 --stop 5 --reps 3 --cpu \
      --variant auto --variant dense:active_block=0 \
      --variant block512:active_block=512
  python tools/perf_ab.py socks10k --n 400 --stop 10 --cpu \
      --runahead-ms 10 --variant auto --variant v1:exchange_a2a=0

With no --variant, the phold regression-suspect set from the round-4
investigation is used (see tools/phold_ab.py).
"""

from __future__ import annotations

import argparse
import copy
import dataclasses
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def parse_variant(spec: str):
    """``name[:k=v[,k=v...]]`` -> (name, overrides). Values are ints
    (EngineConfig knobs are int/bool; bools take 0/1)."""
    name, _, kvs = spec.partition(":")
    overrides = {}
    if kvs:
        for part in kvs.split(","):
            k, eq, v = part.partition("=")
            if not eq:
                raise ValueError(f"variant {spec!r}: {part!r} is "
                                 "not k=v")
            k = k.strip()
            val = int(v)
            if k == "exchange_a2a":
                val = bool(val)
            overrides[k] = val
    return name, overrides


def default_suspects(n: int, obcap: int):
    """The phold-regression suspect set (round-4 verdict item 3 /
    ROADMAP #1): isolates the window/per-pass rung ladder, the
    exchange sort compaction and the destination-compacted merge."""
    return [
        ("auto", {}),                      # the regressed r4 default
        ("dense", {"active_block": 0}),    # all compaction off (r3)
        ("auto_noex", {"exsortcap": n * obcap}),  # full-sort exchange
        ("auto_nodst", {"dstcap": 1}),     # dst compaction off
        ("block512", {"active_block": 512}),
        ("block256", {"active_block": 256}),
    ]


def run_once(scen, cfg, runahead_ms):
    from shadow_tpu.engine.sim import Simulation
    from tools.baseline_configs import apply_runahead
    sim = apply_runahead(Simulation(scen, engine_cfg=cfg), runahead_ms)
    report = sim.run()
    return report


def measure(config, variants, n=None, stop=10, reps=3, runahead_ms=0,
            warm_stop_s=None, seed=None, chunk=0):
    """-> list of per-variant result dicts, baseline (first) variant
    first, plus the shared protocol header."""
    from tools.perf_report import build_config

    scen0, base_cfg, n = build_config(config, n, stop)
    if seed is not None:
        scen0.seed = seed
    if chunk:
        base_cfg = dataclasses.replace(base_cfg, chunk_windows=chunk)
    if warm_stop_s is None:
        # TCP-tier programs need the connect wave inside the warm-up
        warm_stop_s = 1.2 if config == "phold" else 2.4
    cfgs = []
    for name, ov in variants:
        try:
            cfgs.append((name, ov,
                         dataclasses.replace(base_cfg, **ov)))
        except TypeError as e:
            raise SystemExit(f"perf_ab: variant {name!r}: {e}")

    # warm-up: one short run per variant compiles its program
    for name, _, cfg in cfgs:
        warm = copy.deepcopy(scen0)
        warm.stop_time = int(warm_stop_s * 10**9)
        t0 = time.perf_counter()
        run_once(warm, cfg, runahead_ms)
        print(json.dumps({"variant": name, "warmup_wall_s":
                          round(time.perf_counter() - t0, 1)}),
              file=sys.stderr, flush=True)

    rates = {name: [] for name, _, _ in cfgs}
    events = {}
    cost = {}
    for rep in range(max(reps, 1)):
        for name, _, cfg in cfgs:      # paired interleaved
            report = run_once(copy.deepcopy(scen0), cfg, runahead_ms)
            s = report.summary()
            rates[name].append(round(s["events_per_sec"], 1))
            events.setdefault(name, s["events"])
            cost[name] = report.cost_model()
            print(json.dumps({"rep": rep, "variant": name,
                              "events_per_sec": rates[name][-1]}),
                  file=sys.stderr, flush=True)

    from statistics import median

    ev0 = events[cfgs[0][0]]
    out = []
    for name, ov, cfg in cfgs:
        rs = sorted(rates[name])
        med = round(median(rs), 1)
        spread = round(rs[-1] - rs[0], 1)
        out.append({
            "variant": name, "overrides": ov, "rates": rs,
            "median": med, "spread": spread,
            "events": events[name],
            "events_match_baseline": events[name] == ev0,
            "passes": cost[name].get("passes"),
            "cost_model": cost[name],
            "cfg": cfg,
        })
    base = out[0]
    for row in out:
        row["vs_baseline"] = (round(row["median"] / base["median"], 3)
                              if base["median"] else None)
        gap = abs(row["median"] - base["median"])
        row["significant"] = gap > (row["spread"] + base["spread"])
    return out, {"config": config, "hosts": n, "stop_s": stop,
                 "reps": reps, "runahead_ms": runahead_ms,
                 "seed": seed}


def markdown_table(results, header, platform) -> str:
    lines = [
        f"A/B: {header['config']} n={header['hosts']} "
        f"{header['stop_s']} sim-s, {header['reps']} paired "
        f"interleaved reps, platform **{platform}**"
        + (f", runahead {header['runahead_ms']}ms"
           if header["runahead_ms"] else ""),
        "",
        "| variant | overrides | median ev/s | reps (sorted) | "
        "spread | vs baseline | significant |",
        "|---|---|---|---|---|---|---|",
    ]
    for r in results:
        ov = (",".join(f"{k}={v}" for k, v in r["overrides"].items())
              or "(default)")
        note = "" if r["events_match_baseline"] else " **EVENTS DIFFER**"
        lines.append(
            f"| {r['variant']} | `{ov}` | {r['median']:,} | "
            f"{r['rates']} | {r['spread']} | "
            f"{r['vs_baseline']}x | "
            f"{'yes' if r['significant'] else 'no'}{note} |")
    return "\n".join(lines)


def main(argv):
    ap = argparse.ArgumentParser()
    ap.add_argument("config",
                    help="phold | socks10k | tor50k | bulk1k")
    ap.add_argument("--n", type=int, default=None)
    ap.add_argument("--stop", type=int, default=10)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--seed", type=int, default=None)
    ap.add_argument("--runahead-ms", type=int, default=0)
    ap.add_argument("--chunk", type=int, default=0)
    ap.add_argument("--warm-stop-s", type=float, default=None)
    ap.add_argument("--variant", action="append", default=None,
                    metavar="NAME[:K=V,...]",
                    help="repeatable; first is the baseline. Default: "
                         "the phold regression-suspect set")
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--markdown", action="store_true",
                    help="print the BASELINE.md-ready table")
    ap.add_argument("--no-ledger", action="store_true")
    args = ap.parse_args(argv)

    if args.cpu:
        os.environ["PALLAS_AXON_POOL_IPS"] = ""
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
    else:
        from bench import _enable_compile_cache
        _enable_compile_cache()
    import jax

    if args.variant:
        variants = [parse_variant(v) for v in args.variant]
    else:
        from tools.perf_report import build_config
        _, cfg0, n0 = build_config(args.config, args.n, args.stop)
        variants = default_suspects(n0, cfg0.obcap)

    results, header = measure(
        args.config, variants, n=args.n, stop=args.stop,
        reps=args.reps, runahead_ms=args.runahead_ms,
        warm_stop_s=args.warm_stop_s, seed=args.seed,
        chunk=args.chunk)
    platform = jax.default_backend()

    mismatches = [r["variant"] for r in results
                  if not r["events_match_baseline"]]
    if mismatches:
        print(f"perf_ab: WARNING: variants {mismatches} executed a "
              "DIFFERENT event count than the baseline — the knob "
              "broke bit-equality; their ledger entries are withheld "
              "and the table flags them", file=sys.stderr)

    if not args.no_ledger:
        from shadow_tpu.obs import ledger as LG
        for r in results:
            if not r["events_match_baseline"]:
                continue
            entry = LG.make_entry(
                scenario=f"{header['config']}+{r['variant']}",
                fingerprint=LG.fingerprint_of(
                    r["cfg"], stop=header["stop_s"],
                    runahead=header["runahead_ms"],
                    seed=header["seed"]),
                platform=platform,
                summary={"events": r["events"],
                         "events_per_sec": r["median"],
                         "wall_seconds": (r["events"] / r["median"]
                                          if r["median"] else 0.0)},
                cost=r["cost_model"],
                rep_rates=r["rates"], rep_spread=r["spread"],
                note=f"perf_ab vs {results[0]['variant']}",
                cfg=r["cfg"])
            LG.append(entry)

    for r in results:
        r.pop("cfg")  # not JSON-serializable, ledger consumed it
        r.pop("cost_model", None)  # bulky; passes/ledger carry it
        print(json.dumps(r), flush=True)
    if args.markdown:
        print()
        print(markdown_table(results, header, platform))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
