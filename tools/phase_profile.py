#!/usr/bin/env python3
"""Measured (not modeled) per-phase window-loop costs on this backend.

The SimReport cost model (engine.sim.cost_model) prices passes from
array shapes at HBM-roofline rates; this tool complements it by
TIMING the phases as separate device calls at steady state:

  - one lockstep pass per ladder rung (and dense), on the live state
  - the window-boundary exchange
  - the ready-mask / next-event reductions

Method: build one of the baseline configs, run the normal chunked
window loop to a warm-up point, then single-step windows manually —
each phase its own AOT-compiled call, block_until_ready around a
monotonic clock. Per-call dispatch overhead is measured too (an empty
donated identity on the same state), so phase walls can be read net of
it. Results print as one JSON line.

This is the measurement the round-3 verdict asked for ("nobody can say
what fraction of the hardware bound the TCP tier is"): where the
reference self-times its scheduler barriers (shd-scheduler.c:250-252),
the TPU build times its compiled phases.

For attribution INSIDE one compiled window program — per-pass device
self-times keyed by the stateflow entry names, without manual
single-stepping — use the pass-time observatory instead: run with
``--passcope`` (obs.passcope, docs/performance.md "Reading the pass
table") or decode a raw trace with tools/xplane_profile.py.

Usage:
  python tools/phase_profile.py socks10k [--n 10000] [--stop 20]
      [--warm-s 5] [--probe-windows 30] [--runahead-ms 10] [--cpu]
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def profile(name, n=None, stop=20, warm_s=5.0, probe_windows=30,
            runahead_ms=0, chunk=8):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from tools.baseline_configs import CONFIGS
    from shadow_tpu.core.jitcache import AotJit
    from shadow_tpu.engine.sim import Simulation
    from shadow_tpu.core.simtime import SIMTIME_MAX
    from shadow_tpu.engine.window import (exchange, ladder_of,
                                          run_windows, step_window_pass,
                                          next_event_time, next_wakeup,
                                          update_cap_peaks)

    builder, capf, n_default = CONFIGS[name]
    n = n or n_default
    sim = Simulation(builder(n, stop), engine_cfg=capf(n))
    if runahead_ms:
        sim.sh = sim.sh.replace(min_jump=jnp.int64(runahead_ms * 10**6))
    hosts, hp, sh, cfg = sim.hosts, sim.hp, sim.sh, sim.cfg

    # --- warm-up through the normal chunked loop to steady state ---
    t0 = jnp.min(hosts.eq_next)
    ws, we = t0, t0 + sh.min_jump
    while float(ws) / 1e9 < warm_s and int(ws) < int(sh.stop_time):
        hosts, ws, we, _, _ = run_windows(hosts, hp, sh, ws, we, cfg,
                                          chunk)

    ks = ladder_of(cfg)
    labels = [f"k{k}" for k in ks] + ["dense"]

    # --- phase programs, each its own Compiled object ---
    def one_pass(h, wend):
        return step_window_pass(h, hp, sh, wend, cfg)

    def do_exchange(h):
        return exchange(update_cap_peaks(h), hp, sh, cfg)

    def reductions(h):
        return next_event_time(h), next_wakeup(h)

    def identity(h):
        # dispatch-overhead probe: donated pass-through of the state
        return h

    p_pass = AotJit(one_pass, donate_argnums=(0,))
    p_exch = AotJit(do_exchange, donate_argnums=(0,))
    p_red = AotJit(reductions)
    p_id = AotJit(identity, donate_argnums=(0,))

    def timed(fn, *args):
        t = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        return out, time.perf_counter() - t

    # compile everything once off the clock (identity needs real state;
    # run it twice so both come back donated-warm)
    hosts, _ = timed(p_id, hosts)
    nt, wk = p_red(hosts)
    (hosts, _r), _ = timed(p_pass, hosts,
                           jnp.minimum(wk + sh.min_jump, sh.stop_time))
    hosts = p_exch(hosts)
    jax.block_until_ready(hosts)

    walls = {lbl: [] for lbl in labels}
    ev_counts = {lbl: [] for lbl in labels}
    exch_walls, red_walls, id_walls = [], [], []
    ev_stat = 0  # defs.ST_EVENTS == 0

    wins = 0
    while wins < probe_windows:
        (nt, wk), dt = timed(p_red, hosts)
        red_walls.append(dt)
        nt = int(nt)
        if nt >= int(sh.stop_time) or nt >= SIMTIME_MAX:
            break
        wend = jnp.int64(min(nt + int(sh.min_jump), int(sh.stop_time)))
        # drain the window pass by pass
        while True:
            hosts, dt = timed(p_id, hosts)
            id_walls.append(dt)
            ev0 = int(jnp.sum(hosts.stats[:, ev_stat]))
            if int(next_event_time(hosts)) >= int(wend):
                break
            (hosts, rung), dt = timed(p_pass, hosts, wend)
            lbl = labels[int(rung)]
            walls[lbl].append(dt)
            ev_counts[lbl].append(
                int(jnp.sum(hosts.stats[:, ev_stat])) - ev0)
        if int(jnp.sum(hosts.ob_cnt)) > 0:  # real loop skips empty
            hosts, dt = timed(p_exch, hosts)
            exch_walls.append(dt)
        wins += 1

    def ms(xs):
        return round(1e3 * float(np.mean(xs)), 3) if xs else None

    out = {
        "config": name, "hosts": n, "backend": jax.default_backend(),
        "probe_windows": wins,
        "dispatch_ms": ms(id_walls),
        "reductions_ms": ms(red_walls),
        "exchange_ms": ms(exch_walls),
        "passes": {},
    }
    for lbl in labels:
        if walls[lbl]:
            out["passes"][lbl] = {
                "count": len(walls[lbl]),
                "mean_ms": ms(walls[lbl]),
                "mean_events": round(float(np.mean(ev_counts[lbl])), 1),
                "us_per_event": round(
                    1e6 * float(np.sum(walls[lbl])) /
                    max(sum(ev_counts[lbl]), 1), 2),
            }
    return out


def main(argv):
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("config")
    ap.add_argument("--n", type=int, default=None)
    ap.add_argument("--stop", type=int, default=20)
    ap.add_argument("--warm-s", type=float, default=5.0)
    ap.add_argument("--probe-windows", type=int, default=30)
    ap.add_argument("--runahead-ms", type=int, default=0)
    ap.add_argument("--chunk", type=int, default=8)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--active-block", type=int, default=None)
    args = ap.parse_args(argv)
    if args.cpu:
        os.environ["PALLAS_AXON_POOL_IPS"] = ""
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
    else:
        # chip runs reuse the persistent compile cache (bench.py)
        sys.path.insert(0, REPO)
        from bench import _enable_compile_cache
        _enable_compile_cache()
    if args.active_block is not None:
        import dataclasses
        from tools import baseline_configs as bc
        nm = args.config
        b, capf, nd = bc.CONFIGS[nm]
        bc.CONFIGS[nm] = (b, lambda nn: dataclasses.replace(
            capf(nn), active_block=args.active_block), nd)
    print(json.dumps(profile(
        args.config, n=args.n, stop=args.stop, warm_s=args.warm_s,
        probe_windows=args.probe_windows,
        runahead_ms=args.runahead_ms, chunk=args.chunk)))


if __name__ == "__main__":
    main(sys.argv[1:])
