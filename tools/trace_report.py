#!/usr/bin/env python3
"""Summarize a shadow_tpu Chrome trace (the ``--trace FILE`` output of
``python -m shadow_tpu`` / ``Simulation.run(trace=...)``).

Two views, answering "where does the wall time go":

1. top spans by SELF-time — per span name, total wall time minus the
   time spent in nested child spans (so e.g. a ``chunk`` span does not
   double-count the ``tracker.heartbeat`` it contains);
2. per-chunk wall-per-sim-second — each ``chunk`` span carries its
   sim-time range and events-executed in args (obs.trace), so the
   report shows, chunk by chunk, how much wall a simulated second
   cost and how throughput evolved over the run (the in-run
   counterpart of SimReport.speedup, which only reports the mean).

With ``--passcope DIR`` (or when ``<trace-dir>/passcope.json`` from a
``--passcope`` run sits next to the trace) the DEVICE pass table the
pass-time observatory decoded (obs.passcope: per-pass device time
keyed by the stateflow entry names, plus lockstep occupancy) renders
under the host span table — both halves of "where did the time go"
in one report.

Pure stdlib, no jax: runs headless on any trace file in milliseconds.

Usage:
  python tools/trace_report.py trace.json [--top 15] [--json]
      [--passcope DIR]
"""

import argparse
import json
import os
import sys
from collections import defaultdict

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_passcope_mod():
    """obs/passcope.py by file path (no shadow_tpu/jax import — the
    headless-tools convention, tools/perf_report.py's idiom)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_passcope", os.path.join(REPO, "shadow_tpu", "obs",
                                  "passcope.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def load_passcope(trace_path, passcope_dir=None):
    """The decoded device pass table of a --passcope run: explicit
    DIR, else auto-detected as passcope.json beside the trace file.
    -> the {"device_phases", "occupancy"} dict or None."""
    cands = []
    if passcope_dir:
        cands.append(os.path.join(passcope_dir, "passcope.json"))
        cands.append(passcope_dir)  # a passcope.json path directly
    else:
        cands.append(os.path.join(
            os.path.dirname(os.path.abspath(trace_path)),
            "passcope.json"))
    for p in cands:
        if os.path.isfile(p):
            try:
                with open(p) as f:
                    return json.load(f)
            except (OSError, json.JSONDecodeError) as e:
                raise SystemExit(f"trace_report: {p}: {e}")
    if passcope_dir:
        raise SystemExit(
            f"trace_report: no passcope.json under {passcope_dir!r} "
            "(run with --passcope to produce one)")
    return None


def load_events(path):
    """-> (complete events, dropped count). A nonzero dropped count
    means the recorder hit its MAX_EVENTS cap (obs.trace) and the
    timeline is TRUNCATED — totals under-report the run.

    Bad input (missing, empty, truncated or non-trace JSON) exits
    with a one-line diagnosis instead of a traceback."""
    try:
        with open(path) as f:
            text = f.read()
    except OSError as e:
        raise SystemExit(
            f"trace_report: cannot read {path}: {e.strerror or e}")
    if not text.strip():
        raise SystemExit(f"trace_report: {path}: empty file — the run "
                         "may have died before the trace was flushed")
    try:
        doc = json.loads(text)
    except json.JSONDecodeError as e:
        raise SystemExit(
            f"trace_report: {path}: not valid JSON (truncated trace? "
            f"{e.msg} at line {e.lineno})")
    if isinstance(doc, dict):
        evs = doc.get("traceEvents")
        if evs is None:
            raise SystemExit(
                f"trace_report: {path}: no traceEvents key — not a "
                "Chrome trace-event file")
    elif isinstance(doc, list):
        evs = doc
    else:
        raise SystemExit(
            f"trace_report: {path}: not a Chrome trace-event document")
    dropped = (doc.get("otherData", {}).get("dropped_events", 0)
               if isinstance(doc, dict) else 0)
    events = [e for e in evs if e.get("ph") == "X"]
    if not events:
        raise SystemExit(
            f"trace_report: {path}: trace contains no complete spans "
            "(empty or metadata-only timeline)")
    return events, dropped


def self_times(events):
    """Aggregate per span name: count, total µs, self µs (total minus
    directly-nested children), max µs. Nesting is recovered per
    (pid, tid) track with the standard sort-and-stack walk: order by
    (ts, -dur) so an enclosing span precedes the spans it contains."""
    agg = {}  # name -> [count, total_us, self_us, max_us]
    tracks = defaultdict(list)
    for e in events:
        tracks[(e.get("pid", 0), e.get("tid", 0))].append(e)
    for evs in tracks.values():
        evs.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack = []  # [end_ts, child_sum_us, name, dur_us]
        def close(upto):
            while stack and stack[-1][0] <= upto + 1e-9:
                end, child, name, dur = stack.pop()
                a = agg.setdefault(name, [0, 0.0, 0.0, 0.0])
                a[0] += 1
                a[1] += dur
                a[2] += max(dur - child, 0.0)
                a[3] = max(a[3], dur)
                if stack:
                    stack[-1][1] += dur
        for e in evs:
            close(e["ts"])
            stack.append([e["ts"] + e["dur"], 0.0, e["name"], e["dur"]])
        close(float("inf"))
    return agg


def chunk_rows(events):
    """Per-chunk sim<->wall correlation off the ``chunk`` (compiled
    engine) span args; pyengine.window spans aggregate the same way."""
    rows = []
    for e in events:
        if e["name"] != "chunk":
            continue
        a = e.get("args", {})
        if "sim_ns_start" not in a:
            continue
        sim_s = max(a.get("sim_ns_end", 0) - a["sim_ns_start"], 0) / 1e9
        wall_s = e["dur"] / 1e6
        rows.append({
            "sim_start_s": a["sim_ns_start"] / 1e9,
            "sim_s": sim_s,
            "wall_s": wall_s,
            "windows": a.get("windows", 0),
            "events": a.get("events", 0),
            "wall_per_sim_s": (wall_s / sim_s) if sim_s else None,
            "events_per_sec": (a.get("events", 0) / wall_s)
            if wall_s else None,
        })
    return rows


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("trace", help="Chrome trace-event JSON (obs.trace)")
    ap.add_argument("--top", type=int, default=15,
                    help="span names to show (by self-time)")
    ap.add_argument("--json", action="store_true",
                    help="print the report as one JSON object")
    ap.add_argument("--passcope", default=None, metavar="DIR",
                    help="merge the device pass table from this "
                         "--passcope run dir (default: auto-detect "
                         "passcope.json beside the trace)")
    args = ap.parse_args(argv)

    events, dropped = load_events(args.trace)
    agg = self_times(events)
    chunks = chunk_rows(events)
    pscope = load_passcope(args.trace, args.passcope)
    if dropped:
        print(f"WARNING: trace truncated — {dropped} spans dropped at "
              "the recorder's cap (obs.trace.MAX_EVENTS); totals "
              "under-report the run", file=sys.stderr)

    spans = sorted(
        ({"name": n, "count": c, "total_ms": t / 1000.0,
          "self_ms": s / 1000.0, "mean_us": t / c if c else 0.0,
          "max_us": m}
         for n, (c, t, s, m) in agg.items()),
        key=lambda r: -r["self_ms"])[:args.top]

    if args.json:
        out = {"spans": spans, "chunks": chunks,
               "dropped_events": dropped}
        if pscope is not None:
            out["device_phases"] = pscope.get("device_phases")
            out["occupancy"] = pscope.get("occupancy")
        print(json.dumps(out))
        return 0

    print("== top spans by self-time ==")
    print(f"{'name':<24} {'count':>7} {'total_ms':>10} {'self_ms':>10} "
          f"{'mean_us':>10} {'max_us':>10}")
    for r in spans:
        print(f"{r['name']:<24} {r['count']:>7} {r['total_ms']:>10.2f} "
              f"{r['self_ms']:>10.2f} {r['mean_us']:>10.1f} "
              f"{r['max_us']:>10.1f}")

    if chunks:
        print()
        print("== chunks (wall per sim-second) ==")
        print(f"{'#':>4} {'sim_start_s':>12} {'sim_s':>8} {'wall_ms':>10} "
              f"{'windows':>8} {'events':>9} {'wall/sim_s':>11} "
              f"{'events/s':>10}")
        for i, r in enumerate(chunks):
            wps = (f"{r['wall_per_sim_s']:.4f}"
                   if r["wall_per_sim_s"] is not None else "-")
            eps = (f"{r['events_per_sec']:.0f}"
                   if r["events_per_sec"] is not None else "-")
            print(f"{i:>4} {r['sim_start_s']:>12.3f} {r['sim_s']:>8.3f} "
                  f"{r['wall_s'] * 1000:>10.2f} {r['windows']:>8} "
                  f"{r['events']:>9} {wps:>11} {eps:>10}")
        tot_wall = sum(r["wall_s"] for r in chunks)
        tot_sim = sum(r["sim_s"] for r in chunks)
        tot_ev = sum(r["events"] for r in chunks)
        print(f"{'all':>4} {'':>12} {tot_sim:>8.3f} "
              f"{tot_wall * 1000:>10.2f} "
              f"{sum(r['windows'] for r in chunks):>8} {tot_ev:>9} "
              f"{tot_wall / tot_sim if tot_sim else 0:>11.4f} "
              f"{tot_ev / tot_wall if tot_wall else 0:>10.0f}")

    if pscope is not None:
        # the device half: where the DEVICE time went per pass,
        # under the host span table above (obs.passcope)
        PC = _load_passcope_mod()
        print()
        print(PC.format_report(pscope.get("device_phases") or None,
                               pscope.get("occupancy") or None))
    return 0


if __name__ == "__main__":
    sys.exit(main())
