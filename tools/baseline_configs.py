#!/usr/bin/env python3
"""Builders + runner for the BASELINE.json north-star configs.

The five configs (BASELINE.json `configs[]`) map onto the framework's
modeled apps; this module builds them at any scale so the same code
backs bench.py, the at-scale chip runs, and the CPU-mesh smoke tests.

  #1 2-node ping .............. examples/ping.xml (not here)
  #2 1k bulk-transfer ......... build_bulk_1k (tgen web+bulk over an
                                Erdős–Rényi-style multi-PoI topology)
  #3 10k SOCKS chains ......... build_socks (PlanetLab topology,
                                client -> relay -> server fetches)
  #4 50k Tor-shape ............ build_socks(hops=3) (perfclient
                                downloads over 3-relay circuits —
                                the shadow-plugin-tor traffic shape)
  #5 100k Bitcoin gossip ...... examples/gossip-100k.xml (not here)

Engine caps are set EXPLICITLY and lean: auto_engine_config sizes for
link-saturating bursts, which at 10k+ hosts allocates queue arrays in
the GBs and was the round-1 failure mode for big TCP configs on the
chip. Sparse-traffic scenarios need small per-window budgets; overflow
defers to the next window (exact), so lean caps trade only throughput
headroom, never correctness.

Usage (measurement):
  python tools/baseline_configs.py socks10k [--stop 60] [--cpu]
  python tools/baseline_configs.py tor50k   [--stop 60] [--cpu]
  python tools/baseline_configs.py bulk1k   [--stop 60] [--cpu]
Prints one summary JSON line (events, events/s, speedup).
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PLAB = "/root/reference/resource/topology.plab.graphml.xml.xz"


def _plab_or_fallback():
    """The PlanetLab GraphML (BASELINE #3/#4's topology) if the
    reference checkout is present, else a generated stand-in."""
    if os.path.exists(PLAB):
        import lzma
        with lzma.open(PLAB, "rt") as f:
            return f.read()
    from tools.gen_topology import er_topology  # type: ignore
    return er_topology(n=300, p=0.5, seed=7)


def build_socks(n_hosts, hops=1, stop=60, size=49152, count=0, pause="5s",
                relay_frac=0.10, server_frac=0.10):
    """BASELINE #3 (#4 with hops=3) at `n_hosts` total hosts.

    Host ids are declaration-ordered: servers, then relays, then
    clients — the ranges the socks app arguments name.
    """
    from shadow_tpu.core.config import HostSpec, ProcessSpec, Scenario

    n_srv = max(int(n_hosts * server_frac), 1)
    n_rel = max(int(n_hosts * relay_frac), 1)
    n_cli = n_hosts - n_srv - n_rel
    rel_lo, rel_hi = n_srv, n_srv + n_rel
    # bulkserver speaks the same GET-tag wire convention as a tgen
    # server but compiles WITHOUT the tgen walk machinery — at-scale
    # SOCKS/Tor program size (and cold-compile time) drops sharply
    return Scenario(
        stop_time=stop * 10**9,
        topology_graphml=_plab_or_fallback(),
        hosts=[
            HostSpec(id="server", quantity=n_srv, processes=[
                ProcessSpec(plugin="bulkserver", start_time=10**9,
                            arguments="port=80")]),
            HostSpec(id="relay", quantity=n_rel, processes=[
                ProcessSpec(plugin="socksproxy", start_time=10**9,
                            arguments=f"port=9050 server-port=80 "
                                      f"relay-lo={rel_lo} "
                                      f"relay-hi={rel_hi}")]),
            HostSpec(id="client", quantity=n_cli, processes=[
                ProcessSpec(plugin="socksclient", start_time=2 * 10**9,
                            arguments=f"proxy-lo={rel_lo} "
                                      f"proxy-hi={rel_hi} proxy-port=9050 "
                                      f"server-lo=0 server-hi={n_srv} "
                                      f"size={size} hops={hops} "
                                      f"count={count} pause={pause}")]),
        ],
    )


def socks_caps(n_hosts, scap=96, active_block=-1):
    """Lean engine caps for the SOCKS/Tor configs (see module doc).

    scap: each live circuit holds 2 sockets per relay it crosses plus
    TIME_WAIT residue; with clients/relays ≈ 8 and hops<=3 the mean is
    ~50 live sockets per relay — 96 covers bursts, and sock_alloc's
    TIME_WAIT recycling absorbs churn.

    qcap must EXCEED scap by the arrival headroom: every live socket
    keeps one standing RTO-timer event in the queue (net.tcp
    _arm_timer), so a relay with ~scap live sockets and qcap == scap
    has near-zero free slots — intake collapses to the one-packet
    forward-progress floor and deferred arrivals thrash the window
    loop (measured: the 10k run pinned at ~2.7 sim-s). incap 96:
    arrival headroom per window (round 3: arrivals past it defer at
    the source instead of dropping, so undersizing costs windows,
    never packets).

    active_block: active-set compaction block (engine.window.
    step_window_pass) — the at-scale SOCKS/Tor shape is exactly the
    lockstep-skew workload it exists for (a few busy relays, a sea of
    idle clients).
    """
    from shadow_tpu.engine.state import EngineConfig
    return EngineConfig(num_hosts=n_hosts, qcap=scap + 96, scap=scap,
                        obcap=24, incap=96, txqcap=16, chunk_windows=64,
                        active_block=active_block)


_TGEN_KEYS = (
    '<key attr.name="count" attr.type="string" for="node" id="d6"/>'
    '<key attr.name="size" attr.type="string" for="node" id="d5"/>'
    '<key attr.name="type" attr.type="string" for="node" id="d4"/>'
    '<key attr.name="time" attr.type="string" for="node" id="d2"/>'
    '<key attr.name="peers" attr.type="string" for="node" id="d0"/>'
    '<key attr.name="serverport" attr.type="string" for="node" id="d1"/>')


def _tgen_client_graph(peers, ttype, size, pause, count):
    """A web/bulk-style walk: transfer -> end(count) -> pause -> start,
    peers drawn uniformly from the whole server pool (the reference
    example funnels onto 2 servers; at 1k hosts that is a server
    socket-table artifact, not the workload shape)."""
    return (
        '<graphml xmlns="http://graphml.graphdrawing.org/xmlns">'
        f'{_TGEN_KEYS}<graph edgedefault="directed">'
        f'<node id="start"><data key="d0">{peers}</data></node>'
        f'<node id="pause"><data key="d2">{pause}</data></node>'
        '<node id="transfer">'
        f'<data key="d4">{ttype}</data><data key="d5">{size}</data></node>'
        f'<node id="end"><data key="d6">{count}</data></node>'
        '<edge source="start" target="transfer"/>'
        '<edge source="transfer" target="end"/>'
        '<edge source="end" target="pause"/>'
        '<edge source="pause" target="start"/>'
        '</graph></graphml>')


def build_bulk_1k(n_hosts=1000, stop=60):
    """BASELINE #2: 1k-node tgen web+bulk transfers (the reference
    example workload shape, resource/examples/shadow.config.xml:
    50 servers / 50 web / 50 bulk, scaled up) over the PlanetLab
    topology."""
    from shadow_tpu.core.config import HostSpec, ProcessSpec, Scenario

    n_srv = max(n_hosts // 5, 1)
    n_bulk = max(n_hosts // 5, 1)
    n_web = n_hosts - n_srv - n_bulk
    peers = ",".join(f"server{i + 1}:30080" for i in range(n_srv))
    server_graph = (
        '<graphml xmlns="http://graphml.graphdrawing.org/xmlns">'
        f'{_TGEN_KEYS}<graph edgedefault="directed">'
        '<node id="start"><data key="d1">30080</data></node>'
        '</graph></graphml>')
    web_graph = _tgen_client_graph(peers, "get", "100 KiB",
                                   "1,2,3,4,5", 0)
    bulk_graph = _tgen_client_graph(peers, "put", "1 MiB", "1", 0)
    return Scenario(
        stop_time=stop * 10**9,
        topology_graphml=_plab_or_fallback(),
        hosts=[
            HostSpec(id="server", quantity=n_srv, processes=[
                ProcessSpec(plugin="tgen", start_time=10**9,
                            arguments=server_graph)]),
            HostSpec(id="web", quantity=n_web, processes=[
                ProcessSpec(plugin="tgen", start_time=2 * 10**9,
                            arguments=web_graph)]),
            HostSpec(id="bulk", quantity=n_bulk, processes=[
                ProcessSpec(plugin="tgen", start_time=2 * 10**9,
                            arguments=bulk_graph)]),
        ],
    )


def apply_runahead(sim, runahead_ms):
    """Override the lookahead window — exactly the reference's
    --runahead knob (shd-options.c; its no-topology fallback window is
    this same 10ms, shd-master.c:123). plab's 1ms minimum edge
    otherwise forces 60k windows per simulated minute; paths shorter
    than the override see coarser delivery granularity, like the
    reference under the same setting. The ONE definition all
    measurement entry points share (bench.py and run_config) so they
    cannot measure different protocols."""
    if runahead_ms:
        import jax.numpy as jnp
        sim.sh = sim.sh.replace(min_jump=jnp.int64(runahead_ms * 10**6))
    return sim


CONFIGS = {
    # name: (builder, caps, default n). No active_block anywhere: the
    # engine's automatic rung ladder (EngineConfig.active_block = -1,
    # engine.window.ladder_of) replaced the round-3 hand-tuned
    # per-config constants; pass --active-block to override for A/Bs.
    "socks10k": (lambda n, stop: build_socks(n, hops=1, stop=stop,
                                             count=0, pause="5s"),
                 lambda n: socks_caps(n, scap=96),
                 10_000),
    "tor50k": (lambda n, stop: build_socks(n, hops=3, stop=stop,
                                           count=0, pause="10s"),
               lambda n: socks_caps(n, scap=160),
               50_000),
    "bulk1k": (lambda n, stop: build_bulk_1k(n, stop=stop),
               lambda n: socks_caps(n, scap=32),
               1_000),
}


def run_config(name, n=None, stop=60, heartbeat=0.0, verbose=False,
               runahead_ms=0, chunk=0, active_block=None,
               event_batch=None, auto_caps=False, wide_state=False):
    from shadow_tpu.engine.sim import Simulation

    builder, capf, n_default = CONFIGS[name]
    n = n or n_default
    scen = builder(n, stop)
    cfg = capf(n)
    if auto_caps:
        # shrink lever 3 (docs/performance.md "The shrink campaign"):
        # OFF by default here so the measurement baseline and its
        # ledger trajectory stay on the hand-tuned caps; capacity_plan
        # defaults it ON for planning runs
        from shadow_tpu.apps.compile import auto_caps as _ac
        cfg, _ = _ac(scen, cfg)
    if wide_state:
        import dataclasses
        cfg = dataclasses.replace(cfg, wide_state=1)
    if chunk or active_block is not None or event_batch is not None:
        # a wider runahead packs ~runahead/min-latency more event
        # passes into each window — keep one device dispatch (a chunk)
        # short or the axon worker aborts long-running calls
        import dataclasses
        kw = {}
        if chunk:
            kw["chunk_windows"] = chunk
        if active_block is not None:
            kw["active_block"] = active_block
        if event_batch is not None:
            kw["event_batch"] = event_batch
        cfg = dataclasses.replace(cfg, **kw)
    sim = apply_runahead(Simulation(scen, engine_cfg=cfg), runahead_ms)
    report = sim.run(heartbeat_s=heartbeat, verbose=verbose)
    s = report.summary()
    from shadow_tpu.engine import defs
    out = {
        "config": name, "hosts": n,
        "events": s["events"], "windows": s["windows"],
        "sim_seconds": s["sim_seconds"],
        "wall_seconds": round(s["wall_seconds"], 2),
        "events_per_sec": round(s["events_per_sec"], 1),
        "realtime_x": round(s["speedup"], 3),
        "transfers_done": s["transfers_done"],
        "retransmits": s["retransmits"],
        "drop_q": s["drop_q"],
        "defer_fanin": s["defer_fanin"],
        "defer_a2a": s["defer_a2a"],
        "active_block": cfg.active_block,
        "sock_fail": int(report.stats[:, defs.ST_SOCK_FAIL].sum()),
        "capacity": report.capacity_report(),
        "cost": report.cost_model(),
    }
    return out


def emit_xml(name, path, n=None, stop=60):
    """Write the named config as a self-contained shadow.config.xml
    (core.config.Scenario.to_xml) and return the matching
    ``--engine-caps`` string — how a baseline config becomes a fleet
    run (``shadow_tpu fleet submit Q tor.xml -- --engine-caps ...``,
    docs/fleet.md). The XML embeds the topology, so the file is
    submittable from anywhere."""
    builder, capf, n_default = CONFIGS[name]
    n = n or n_default
    scen = builder(n, stop)
    cfg = capf(n)
    with open(path, "w") as f:
        f.write(scen.to_xml())
    return (f"qcap={cfg.qcap},scap={cfg.scap},obcap={cfg.obcap},"
            f"incap={cfg.incap},txqcap={cfg.txqcap},"
            f"chunk={cfg.chunk_windows}")


def main(argv):
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("config", choices=sorted(CONFIGS))
    ap.add_argument("--n", type=int, default=None)
    ap.add_argument("--stop", type=int, default=60)
    ap.add_argument("--emit-xml", default=None, metavar="PATH",
                    help="write the config as shadow.config.xml and "
                         "print the matching --engine-caps string "
                         "instead of running it (fleet submission)")
    ap.add_argument("--cpu", action="store_true",
                    help="force the virtual CPU mesh platform")
    ap.add_argument("--verbose", action="store_true",
                    help="print per-chunk progress")
    ap.add_argument("--runahead-ms", type=int, default=0,
                    help="lookahead window override in ms (0 = the "
                         "topology's true minimum latency)")
    ap.add_argument("--chunk", type=int, default=0,
                    help="windows per device dispatch override")
    ap.add_argument("--active-block", type=int, default=None,
                    help="active-set compaction block override "
                         "(0 = dense)")
    ap.add_argument("--event-batch", type=int, default=None,
                    help="events drained per gathered host per sparse "
                         "pass (A/B the pass-count batching; 1 = "
                         "one event per pass)")
    ap.add_argument("--auto-caps", action="store_true",
                    help="size scap/qcap/obcap/txqcap from the apps' "
                         "declared peaks (shrink lever 3; default "
                         "here is the hand-tuned base caps)")
    ap.add_argument("--wide-state", action="store_true",
                    help="force the wide at-rest socket layout (the "
                         "shrink campaign's A/B escape hatch)")
    args = ap.parse_args(argv)
    if args.emit_xml:
        caps = emit_xml(args.config, args.emit_xml, n=args.n,
                        stop=args.stop)
        print(json.dumps({"config": args.config, "xml": args.emit_xml,
                          "engine_caps": caps}))
        return
    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
    else:
        # persistent compile cache for chip runs (repeat measurements
        # skip the multi-minute cold compile; CPU runs skip it — this
        # build's XLA:CPU AOT loader mismatches its own entries)
        import jax
        try:
            jax.config.update(
                "jax_compilation_cache_dir",
                os.path.join(REPO, ".jax_cache"))
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1.0)
        except Exception:
            pass
    out = run_config(args.config, n=args.n, stop=args.stop,
                     verbose=args.verbose, runahead_ms=args.runahead_ms,
                     chunk=args.chunk, active_block=args.active_block,
                     event_batch=args.event_batch,
                     auto_caps=args.auto_caps,
                     wide_state=args.wide_state)
    if args.runahead_ms:
        out["runahead_ms"] = args.runahead_ms
    if args.auto_caps:
        out["auto_caps"] = True
    if args.wide_state:
        out["wide_state"] = True
    print(json.dumps(out))


if __name__ == "__main__":
    sys.path.insert(0, REPO)
    main(sys.argv[1:])
