"""A/B the phold-4096 regression suspects (now a thin wrapper).

Round-4 shipped the AUTO compaction ladder as default and phold-4096
fell 83k -> 34k ev/s (round-4 verdict item 3). The general machinery
moved to tools/perf_ab.py (any config x any EngineConfig knobs,
paired interleaved reps, ledger + markdown output); this wrapper
keeps the historical entry point and the named suspect set:

  auto        the regressed round-4 default (AUTO ladder)
  dense       compaction fully off (the round-3 default)
  auto_noex   exchange sort-compaction off (full-sort path)
  auto_nodst  destination-compacted merge off
  block512 / block256   one explicit per-pass rung

Usage: python tools/phold_ab.py [variant ...] [--cpu] [--stop S]
Results land in the perf ledger and print a BASELINE.md-ready table
(platform-stamped; CPU-container numbers are labeled as such —
BASELINE.md protocol).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools.perf_ab import default_suspects, main as ab_main  # noqa: E402

N = 4096
OBCAP = 8  # bench._phold_cfg(4096).obcap

VARIANTS = dict(default_suspects(N, OBCAP))

if __name__ == "__main__":
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("variants", nargs="*",
                    help=f"subset of {sorted(VARIANTS)} "
                         "(default: all)")
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--stop", type=int, default=10)
    ap.add_argument("--reps", type=int, default=3)
    a = ap.parse_args()
    unknown = [n for n in a.variants if n not in VARIANTS]
    if unknown:
        sys.exit(f"phold_ab: unknown variant(s) {unknown}; "
                 f"choices: {sorted(VARIANTS)}")
    names = a.variants or list(VARIANTS)
    args = ["phold", "--n", str(N), "--stop", str(a.stop),
            "--reps", str(a.reps), "--markdown"]
    if a.cpu:
        args.append("--cpu")
    for n in names:
        ov = VARIANTS[n]
        spec = n if not ov else (
            n + ":" + ",".join(f"{k}={v}" for k, v in ov.items()))
        args += ["--variant", spec]
    sys.exit(ab_main(args))
