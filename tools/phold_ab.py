"""A/B the phold-4096 regression suspects on the real chip.

Round-4 shipped the AUTO compaction ladder as default and phold-4096
fell 83k -> 34k ev/s (round-4 verdict item 3). Suspects:
  (a) the window rung (window_ladder -> [2048] at H=4096) gathers half
      the state per ~1-pass window;
  (b) dst_cap auto = min(H, 4096) == H at 4096 hosts, making the
      destination-compacted merge a full-width indirect gather.

Usage: python tools/phold_ab.py [variant ...]
Variants: auto, dense, noladder (window rungs off via active_block>0
trick is not possible; we use env-free config fields instead).
"""
import copy
import json
import sys
import time
import os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run(tag, cfg_kwargs):
    import bench
    from shadow_tpu.engine.sim import Simulation
    from shadow_tpu.engine.state import EngineConfig

    scen = bench._phold_scenario(4096, 10)
    cfg = EngineConfig(num_hosts=4096, qcap=16, scap=4, obcap=8,
                       incap=16, chunk_windows=512, **cfg_kwargs)
    warm = copy.deepcopy(scen)
    warm.stop_time = int(1.2 * 10**9)
    t0 = time.perf_counter()
    Simulation(warm, engine_cfg=cfg).run()
    t_cold = time.perf_counter() - t0
    rates = []
    for _ in range(3):
        r = Simulation(scen, engine_cfg=cfg).run()
        s = r.summary()
        rates.append(round(s["events_per_sec"], 1))
    rates.sort()
    cost = r.cost_model()
    print(json.dumps({"variant": tag, "cfg": cfg_kwargs,
                      "warmup_wall_s": round(t_cold, 1),
                      "rates": rates, "median": rates[1],
                      "events": s["events"],
                      "passes": cost.get("passes"),
                      "windows": s["windows"]}), flush=True)


VARIANTS = {
    # round-4 default (the regressed config)
    "auto": {},
    # compaction fully off (the round-3 default): isolates the ladder
    "dense": {"active_block": 0},
    # exchange compaction off (C == N takes the static full-sort path),
    # ladder on: isolates exsort+dst compaction
    "auto_noex": {"exsortcap": 4096 * 8},
    # dst-compaction effectively off (D=1: dst_full on any real window),
    # rest of auto on
    "auto_nodst": {"dstcap": 1},
    # one explicit 512 rung (the quarter-rule window-rung candidate)
    "block512": {"active_block": 512},
    "block256": {"active_block": 256},
}

if __name__ == "__main__":
    import bench
    bench._enable_compile_cache()
    names = sys.argv[1:] or list(VARIANTS)
    for n in names:
        run(n, VARIANTS[n])
