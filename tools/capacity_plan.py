#!/usr/bin/env python3
"""capacity_plan: predict how many hosts fit a chip, from measured
bytes — the planning table for ROADMAP item 2's 100k -> 1M-host push.

The blocker that item names is memory layout ("host-table sharding,
topology-oracle compression") — but before anyone refactors layout,
the repo needs to SEE where the bytes go and how they scale. This
tool closes the loop the memory observatory (obs.memscope,
docs/observability.md) opened:

1. **Measure**: build the scenario at a measurable size, take the
   static byte census of its ``Hosts``/``HostParams``/``Shared``
   pytrees, run it, and capture the compiled window program's XLA
   ``memory_analysis`` (argument/temp/output bytes) plus the live
   device-buffer watermark.
2. **Validate**: the census PREDICTS the program's argument bytes
   (state pytrees + the two window scalars); the run MEASURES them.
   The prediction must land within ``--tolerance`` (default 10%) of
   the measured figure or the tool exits 1 — a planner whose model
   disagrees with the compiler's own accounting plans nothing.
3. **Extrapolate**: per-host bytes (census) + per-host temp/output
   footprint (measured, scaled from the run) + fixed cost (topology
   oracle, generated code) give predicted total bytes at each ladder
   target (default 100k/250k/500k/1M hosts), the max hosts one chip's
   ``--hbm-gb`` budget holds, and the chips needed per target — the
   markdown scale ladder the 1M push is planned from
   (docs/performance.md "Sizing the 1M push").

The linear model is deliberate: every engine array is O(H) with fixed
trailing dims (the census proves it field by field), the topology
oracle is the one O(V^2) fixed cost, and XLA temps for the window
program are gather/scatter buffers sized by H — so bytes(H) =
fixed + per_host * H is not an assumption, it is the layout. What the
model canNOT see (and says so): a future topology whose V grows with
H, and allocator fragmentation above the analytical footprint.

Usage:
  python tools/capacity_plan.py phold --n 1024 --stop 2 --cpu
  python tools/capacity_plan.py socks10k --n 400 --stop 5 --cpu \
      --hbm-gb 16 [--targets 100000,1000000] [--json] [--markdown]

Exit: 0 prediction within tolerance / 1 out of tolerance /
2 usage / 3 backend provides no memory_analysis (nothing to validate
against — the census and ladder still print, labeled unvalidated).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# the scalars the window program takes beside the three state pytrees
# (wstart, wend: two i64)
SCALAR_ARG_BYTES = 16

DEFAULT_TARGETS = (100_000, 250_000, 500_000, 1_000_000)


def _gib(n) -> float:
    return n / (1 << 30)


def measure(config: str, n: int = None, stop: int = 2,
            runahead_ms: int = 0, seed: int = None,
            auto_caps: bool = True, wide_state: bool = False) -> dict:
    """Build, census, run and capture one scenario at a measurable
    size. Returns the raw figures plan() extrapolates from.

    `auto_caps` (shrink lever 3, default ON): size scap/qcap/obcap/
    txqcap from the apps' declared peaks (apps.compile.auto_caps)
    instead of the config family's hand-tuned worst case; the saving
    vs the base caps is reported. `wide_state` (the A/B escape hatch)
    forces the wide at-rest layout — the knob digest-parity runs
    compare against."""
    import dataclasses

    from shadow_tpu.apps import compile as AC
    from shadow_tpu.engine.sim import Simulation
    from shadow_tpu.obs import memscope as MS
    from tools.baseline_configs import apply_runahead
    from tools.perf_report import build_config

    scen, cfg, n = build_config(config, n, stop)
    if seed is not None:
        scen.seed = seed
    caps = {"applied": False, "why": "--no-auto-caps"}
    if auto_caps:
        base = cfg
        cfg, caps = AC.auto_caps(scen, cfg)
        if caps["applied"]:
            # eval_shape censuses (zero allocation) of both layouts:
            # the lever's own saving, independent of the dtype levers
            caps["saved_bytes_per_host"] = (
                MS.state_census(base)["per_host"]
                - MS.state_census(cfg)["per_host"])
    if wide_state:
        cfg = dataclasses.replace(cfg, wide_state=1)
    sim = apply_runahead(Simulation(scen, engine_cfg=cfg), runahead_ms)
    census = MS.state_census(sim.cfg, hosts=sim.hosts, hp=sim.hp,
                             sh=sim.sh)
    report = sim.run()
    return {
        "config": config, "hosts": n, "stop_s": stop,
        "census": census,
        "memory": report.memory,
        "events": report.events,
        "caps": caps,
        "wide_state": bool(wide_state),
        # lever 4's evidence: per-program declared donation vs the
        # aliasing/temps XLA measured (obs.memscope.donation_audit)
        "donation": MS.donation_audit(),
    }


def plan(measured: dict, hbm_gb: float, targets=DEFAULT_TARGETS,
         tolerance: float = 0.10) -> dict:
    """The prediction + validation + ladder, from measure()'s output.

    Pure arithmetic (no jax) so tests can drive it with synthetic
    measurements and the validation semantics stay inspectable."""
    census = measured["census"]
    mem = measured["memory"]
    xla = mem.get("xla") or {}
    H = measured["hosts"]
    budget = int(hbm_gb * (1 << 30))

    per_host_state = census["per_host"]
    fixed = census["fixed_bytes"]

    # validation: the census predicts the compiled program's argument
    # bytes — the compiler's own accounting of the state it was handed
    pred_args = census["bytes"] + SCALAR_ARG_BYTES
    meas_args = xla.get("argument_bytes")
    validation = {"predicted_argument_bytes": pred_args,
                  "measured_argument_bytes": meas_args,
                  "tolerance": tolerance}
    if meas_args is not None:
        # `is not None`, not truthiness: a degenerate backend
        # reporting 0 argument bytes must FAIL validation (exit 1),
        # not sail through as merely "unvalidated" (exit 3)
        err = abs(pred_args - meas_args) / max(meas_args, 1)
        validation["rel_error"] = round(err, 6)
        validation["ok"] = err <= tolerance
    else:
        validation["ok"] = None
        validation["why"] = ("backend provides no memory_analysis — "
                             "census unvalidated "
                             + str((xla.get("errors") or {})
                                   .get("memory_analysis", "")))

    # measured per-host overheads beyond the state census: XLA temp
    # buffers and non-aliased outputs scale with H (gather/scatter
    # workspace over [H,*] arrays); generated code is fixed
    temp_ph = (xla["temp_bytes"] / H
               if xla.get("temp_bytes") is not None else 0.0)
    out_ph = (max(xla["output_bytes"] - (xla.get("alias_bytes") or 0),
                  0) / H
              if xla.get("output_bytes") is not None else 0.0)
    gen = xla.get("generated_code_bytes") or 0
    per_host_total = per_host_state + temp_ph + out_ph
    fixed_total = fixed + gen

    headroom = budget - fixed_total
    max_hosts = int(headroom // per_host_total) if headroom > 0 else 0

    ladder = []
    for tgt in targets:
        total = fixed_total + per_host_total * tgt
        # sharding divides the per-host state/temp across chips but
        # replicates the fixed cost (topology oracle, program) on
        # every chip — chips solve per-chip budget >= fixed +
        # per_host * (H / chips)
        chips = (max(-(-int(per_host_total * tgt) // int(headroom)), 1)
                 if headroom > 0 else None)
        ladder.append({
            "hosts": tgt,
            "state_gib": round(_gib(per_host_state * tgt), 3),
            "temp_gib": round(_gib((temp_ph + out_ph) * tgt), 3),
            "total_gib": round(_gib(total), 3),
            "fits_one_chip": bool(total <= budget),
            "chips_at_budget": chips,
        })

    return {
        "config": measured["config"],
        "caps": measured.get("caps"),
        "wide_state": measured.get("wide_state", False),
        "donation": measured.get("donation"),
        "measured_hosts": H,
        "hbm_budget_gib": round(_gib(budget), 3),
        "per_host_state_bytes": per_host_state,
        "per_host_temp_bytes": round(temp_ph + out_ph, 1),
        "per_host_total_bytes": round(per_host_total, 1),
        "fixed_bytes": fixed_total,
        "hot_state_bytes_per_host":
            census["hosts"]["hot"]["runtime_bytes"] // max(H, 1),
        "watermark": {"peak_bytes": mem.get("peak_bytes"),
                      "source": mem.get("source"),
                      "per_device": mem.get("per_device")},
        "validation": validation,
        "max_hosts_per_chip": max_hosts,
        "ladder": ladder,
    }


def gap_table(census: dict, target: int) -> dict:
    """The per-field shrink gap: where the next bytes must come from
    to reach `target` bytes/host. Pure arithmetic on a census dict.

    Fields (Hosts + HostParams) are ranked fattest-first; each row
    carries its per-host bytes and the running cumulative, and the
    table cuts off once the cumulative covers the gap — i.e. it names
    the smallest fattest-first set whose TOTAL elimination would meet
    the target, the upper bound on what any dtype/cap lever combination
    operating on those fields can recover."""
    per_host = census["per_host"]
    gap = per_host - target
    fields = []
    for f, d in census["hosts"]["fields"].items():
        fields.append({"field": f, "per_host": d["per_host"],
                       "dtype": d["dtype"], "section": d["section"],
                       "table": "hosts"})
    for f, d in census.get("hp", {}).get("fields", {}).items():
        fields.append({"field": f, "per_host": d["per_host"],
                       "dtype": d["dtype"], "section": "params",
                       "table": "hp"})
    fields.sort(key=lambda r: (-r["per_host"], r["field"]))
    rows, cum = [], 0
    for r in fields:
        if gap > 0 and cum >= gap:
            break
        cum += r["per_host"]
        rows.append(dict(r, cumulative=cum,
                         share=round(r["per_host"] / max(per_host, 1),
                                     4)))
        if gap <= 0:
            break       # target already met: show only the fattest
    return {"per_host": per_host, "target": target, "gap": gap,
            "met": gap <= 0, "covered": cum >= gap, "rows": rows}


def render_gap(g: dict) -> str:
    lines = []
    if g["met"]:
        lines.append(
            f"### shrink gap: target {g['target']} B/host MET "
            f"(current {g['per_host']} B/host, "
            f"headroom {-g['gap']} B)")
    else:
        lines.append(
            f"### shrink gap: {g['per_host']} B/host vs target "
            f"{g['target']} — {g['gap']} B/host to recover "
            f"(fattest-first cut set below"
            + (")" if g["covered"] else
               "; ALL fields together do not cover it)"))
    lines += ["", "| field | B/host | dtype | section | cum B |",
              "|---|---|---|---|---|"]
    for r in g["rows"]:
        lines.append(f"| {r['field']} | {r['per_host']} | {r['dtype']} "
                     f"| {r['section']} | {r['cumulative']} |")
    return "\n".join(lines)


def render_donation(rows: list) -> str:
    """Markdown for memscope.donation_audit() — lever 4's worksheet:
    which compiled programs donate their fat arguments, whether XLA
    actually aliased them, and the temp bytes left to attack."""
    lines = ["### donation audit (state-carrying executables)", "",
             "| scope | flag | donated | args B | aliased | temps B |",
             "|---|---|---|---|---|---|"]
    if not rows:
        return lines[0] + "\n\n(no executables captured this run)"
    for r in rows:
        frac = r.get("aliased_frac")
        lines.append(
            f"| {r['scope']} | {r['flag']} | {r['declared']} "
            f"| {r['argument_bytes']} "
            f"| {'—' if frac is None else f'{frac * 100:.0f}%'} "
            f"| {r['temp_bytes']} |")
    return "\n".join(lines)


def render_markdown(p: dict) -> str:
    v = p["validation"]
    lines = [
        f"## capacity plan: {p['config']} "
        f"(measured at H={p['measured_hosts']}, budget "
        f"{p['hbm_budget_gib']} GiB/chip)",
        "",
        f"- per-host state: **{p['per_host_state_bytes']} B** "
        f"(hot working set {p['hot_state_bytes_per_host']} B); "
        f"per-host temp+output: {p['per_host_temp_bytes']} B; "
        f"fixed: {p['fixed_bytes']} B",
        f"- max hosts on one chip: **{p['max_hosts_per_chip']:,}**",
        f"- watermark: {p['watermark']['peak_bytes']} B "
        f"({p['watermark']['source']})",
    ]
    caps = p.get("caps") or {}
    if caps.get("applied"):
        c, b = caps["caps"], caps["base_caps"]
        lines.insert(3, f"- auto-caps: scap {b['scap']}->{c['scap']}, "
                        f"qcap {b['qcap']}->{c['qcap']} (max declared "
                        f"peak {caps['max_peak']} sockets; saves "
                        f"{caps.get('saved_bytes_per_host', '?')} "
                        f"B/host vs the base caps)")
    elif caps:
        lines.insert(3, f"- auto-caps: OFF ({caps.get('why')})")
    if p.get("wide_state"):
        lines.insert(3, "- layout: WIDE (--wide-state A/B escape "
                        "hatch — narrow dtype levers disabled)")
    if v["ok"] is None:
        lines.append(f"- validation: UNVALIDATED — {v.get('why')}")
    else:
        lines.append(
            f"- validation: census predicted "
            f"{v['predicted_argument_bytes']} B of program arguments, "
            f"XLA measured {v['measured_argument_bytes']} B — "
            f"{v['rel_error'] * 100:.2f}% error "
            f"({'within' if v['ok'] else 'OUTSIDE'} the "
            f"{v['tolerance'] * 100:.0f}% tolerance)")
    lines += [
        "",
        "| hosts | state GiB | temp GiB | total GiB | 1 chip? "
        "| chips @ budget |",
        "|---|---|---|---|---|---|",
    ]
    for row in p["ladder"]:
        lines.append(
            f"| {row['hosts']:,} | {row['state_gib']} "
            f"| {row['temp_gib']} | {row['total_gib']} "
            f"| {'yes' if row['fits_one_chip'] else 'no'} "
            f"| {row['chips_at_budget']} |")
    return "\n".join(lines)


def self_check() -> int:
    """No-jax census-exactness smoke for CI's fast lane.

    Builds a synthetic measurement from memscope's stdlib table
    helpers (the same per-field arithmetic the real census uses) and
    asserts: (1) plan() validates at exactly 0.00% when the measured
    argument bytes equal the census's own prediction — the exactness
    contract the shrink campaign gates on; (2) the narrow layout's
    modeled socket rows are strictly under the wide layout's, with the
    engine's NARROW_SPEC and memscope's NARROW_DTYPES projection in
    sync field-for-field; (3) gap_table covers a gap fattest-first
    and reports a met target as met."""
    from shadow_tpu.obs import memscope as MS

    H = 1024

    class _Caps:
        num_hosts, qcap, scap, obcap, txqcap = H, 144, 48, 24, 16
        wide_state = 0

    class _WideCaps(_Caps):
        wide_state = 1

    narrow = MS.table_row_bytes(_Caps)
    wide = MS.table_row_bytes(_WideCaps)
    assert sum(narrow.values()) < sum(wide.values()), \
        "narrow layout models no saving over wide"
    for f in MS.NARROW_DTYPES:
        assert narrow[f] < wide[f], \
            f"narrowed field {f} models no saving"

    hosts_b = sum(narrow.values()) * H
    fields = {f: {"bytes": narrow[f] * H, "per_host": narrow[f],
                  "dtype": MS.DTYPE_NAMES[
                      MS.effective_dtype(f, dt, _Caps)],
                  "shape": [], "section": "synthetic", "hot": False,
                  "hot_runtime": False}
              for f, _, dt in MS.HOSTS_DIMS}
    census = {"H": H, "bytes": hosts_b, "per_host": hosts_b // H,
              "fixed_bytes": 0,
              "hosts": {"fields": fields, "bytes": hosts_b,
                        "per_host": hosts_b // H,
                        "hot": {"runtime_bytes": 0}},
              "hp": {"fields": {}, "bytes": 0, "per_host": 0}}
    measured = {
        "config": "self-check", "hosts": H, "stop_s": 0,
        "census": census,
        "memory": {"xla": {"argument_bytes":
                           census["bytes"] + SCALAR_ARG_BYTES,
                           "temp_bytes": 0, "output_bytes": 0,
                           "alias_bytes": 0,
                           "generated_code_bytes": 0},
                   "peak_bytes": None, "source": "synthetic",
                   "per_device": None},
        "events": 0,
    }
    p = plan(measured, hbm_gb=16.0, targets=(100_000,))
    v = p["validation"]
    assert v["ok"] and v["rel_error"] == 0.0, \
        f"census-exactness broken: {v}"

    g = gap_table(census, census["per_host"] // 2)
    assert not g["met"] and g["covered"] and g["rows"], g
    assert g["rows"] == sorted(g["rows"],
                               key=lambda r: -r["per_host"]), \
        "gap table not fattest-first"
    assert gap_table(census, census["per_host"] + 1)["met"]

    print(f"capacity_plan self-check OK: narrow Hosts rows "
          f"{sum(narrow.values())} B/host vs wide "
          f"{sum(wide.values())} B/host; census-vs-XLA 0.00%")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="predict max hosts per chip from measured bytes "
                    "(docs/performance.md 'Sizing the 1M push')")
    ap.add_argument("config", nargs="?", default=None,
                    help="phold | socks10k | tor50k | bulk1k")
    ap.add_argument("--n", type=int, default=None,
                    help="hosts at the MEASUREMENT scale (default: "
                         "the config's own)")
    ap.add_argument("--self-check", action="store_true",
                    help="no-jax census-exactness + layout-model smoke "
                         "(CI fast lane); ignores the other arguments")
    ap.add_argument("--auto-caps", dest="auto_caps",
                    action=argparse.BooleanOptionalAction, default=True,
                    help="size scap/qcap/obcap/txqcap from the apps' "
                         "declared peaks (shrink lever 3; default ON, "
                         "--no-auto-caps = the config's hand-tuned "
                         "base caps)")
    ap.add_argument("--wide-state", action="store_true",
                    help="force the wide at-rest layout (the shrink "
                         "campaign's A/B escape hatch)")
    ap.add_argument("--target-bytes-per-host", type=int, default=None,
                    help="also print the per-field shrink-gap table "
                         "toward this bytes/host target")
    ap.add_argument("--stop", type=int, default=2)
    ap.add_argument("--seed", type=int, default=None)
    ap.add_argument("--runahead-ms", type=int, default=0)
    ap.add_argument("--hbm-gb", type=float, default=16.0,
                    help="per-chip HBM budget in GiB (default 16, the "
                         "v5e class)")
    ap.add_argument("--targets", default=None,
                    help="comma-separated ladder host counts (default "
                         "100000,250000,500000,1000000)")
    ap.add_argument("--tolerance", type=float, default=0.10,
                    help="relative error the census prediction must "
                         "stay within vs the measured program "
                         "arguments (default 0.10)")
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--markdown", action="store_true",
                    help="markdown only (default prints markdown AND "
                         "a json line)")
    ap.add_argument("-o", "--out", default=None,
                    help="also write the markdown table to a file")
    args = ap.parse_args(argv)

    if args.self_check:
        return self_check()
    if not args.config:
        ap.error("config required (or --self-check)")

    targets = DEFAULT_TARGETS
    if args.targets:
        try:
            targets = tuple(int(t) for t in args.targets.split(",")
                            if t.strip())
        except ValueError:
            ap.error(f"--targets {args.targets!r}: not integers")
        if not targets:
            ap.error("--targets names no host counts")

    if args.cpu:
        os.environ["PALLAS_AXON_POOL_IPS"] = ""
        os.environ["JAX_PLATFORMS"] = "cpu"

    measured = measure(args.config, n=args.n, stop=args.stop,
                       runahead_ms=args.runahead_ms, seed=args.seed,
                       auto_caps=args.auto_caps,
                       wide_state=args.wide_state)
    p = plan(measured, args.hbm_gb, targets=targets,
             tolerance=args.tolerance)
    if args.target_bytes_per_host:
        p["gap"] = gap_table(measured["census"],
                             args.target_bytes_per_host)

    if args.json:
        print(json.dumps(p, indent=1))
    else:
        md = render_markdown(p)
        if p.get("gap"):
            md += "\n\n" + render_gap(p["gap"])
        if p.get("donation") is not None:
            md += "\n\n" + render_donation(p["donation"])
        print(md)
        if not args.markdown:
            print(json.dumps({k: p[k] for k in
                              ("config", "measured_hosts",
                               "max_hosts_per_chip",
                               "per_host_total_bytes")}))
        if args.out:
            with open(args.out, "w") as f:
                f.write(md + "\n")

    ok = p["validation"]["ok"]
    if ok is None:
        return 3
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
