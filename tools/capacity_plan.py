#!/usr/bin/env python3
"""capacity_plan: predict how many hosts fit a chip, from measured
bytes — the planning table for ROADMAP item 2's 100k -> 1M-host push.

The blocker that item names is memory layout ("host-table sharding,
topology-oracle compression") — but before anyone refactors layout,
the repo needs to SEE where the bytes go and how they scale. This
tool closes the loop the memory observatory (obs.memscope,
docs/observability.md) opened:

1. **Measure**: build the scenario at a measurable size, take the
   static byte census of its ``Hosts``/``HostParams``/``Shared``
   pytrees, run it, and capture the compiled window program's XLA
   ``memory_analysis`` (argument/temp/output bytes) plus the live
   device-buffer watermark.
2. **Validate**: the census PREDICTS the program's argument bytes
   (state pytrees + the two window scalars); the run MEASURES them.
   The prediction must land within ``--tolerance`` (default 10%) of
   the measured figure or the tool exits 1 — a planner whose model
   disagrees with the compiler's own accounting plans nothing.
3. **Extrapolate**: per-host bytes (census) + per-host temp/output
   footprint (measured, scaled from the run) + fixed cost (topology
   oracle, generated code) give predicted total bytes at each ladder
   target (default 100k/250k/500k/1M hosts), the max hosts one chip's
   ``--hbm-gb`` budget holds, and the chips needed per target — the
   markdown scale ladder the 1M push is planned from
   (docs/performance.md "Sizing the 1M push").

The linear model is deliberate: every engine array is O(H) with fixed
trailing dims (the census proves it field by field), the topology
oracle is the one O(V^2) fixed cost, and XLA temps for the window
program are gather/scatter buffers sized by H — so bytes(H) =
fixed + per_host * H is not an assumption, it is the layout. What the
model canNOT see (and says so): a future topology whose V grows with
H, and allocator fragmentation above the analytical footprint.

Usage:
  python tools/capacity_plan.py phold --n 1024 --stop 2 --cpu
  python tools/capacity_plan.py socks10k --n 400 --stop 5 --cpu \
      --hbm-gb 16 [--targets 100000,1000000] [--json] [--markdown]

Exit: 0 prediction within tolerance / 1 out of tolerance /
2 usage / 3 backend provides no memory_analysis (nothing to validate
against — the census and ladder still print, labeled unvalidated).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# the scalars the window program takes beside the three state pytrees
# (wstart, wend: two i64)
SCALAR_ARG_BYTES = 16

DEFAULT_TARGETS = (100_000, 250_000, 500_000, 1_000_000)


def _gib(n) -> float:
    return n / (1 << 30)


def measure(config: str, n: int = None, stop: int = 2,
            runahead_ms: int = 0, seed: int = None) -> dict:
    """Build, census, run and capture one scenario at a measurable
    size. Returns the raw figures plan() extrapolates from."""
    from shadow_tpu.engine.sim import Simulation
    from shadow_tpu.obs import memscope as MS
    from tools.baseline_configs import apply_runahead
    from tools.perf_report import build_config

    scen, cfg, n = build_config(config, n, stop)
    if seed is not None:
        scen.seed = seed
    sim = apply_runahead(Simulation(scen, engine_cfg=cfg), runahead_ms)
    census = MS.state_census(sim.cfg, hosts=sim.hosts, hp=sim.hp,
                             sh=sim.sh)
    report = sim.run()
    return {
        "config": config, "hosts": n, "stop_s": stop,
        "census": census,
        "memory": report.memory,
        "events": report.events,
    }


def plan(measured: dict, hbm_gb: float, targets=DEFAULT_TARGETS,
         tolerance: float = 0.10) -> dict:
    """The prediction + validation + ladder, from measure()'s output.

    Pure arithmetic (no jax) so tests can drive it with synthetic
    measurements and the validation semantics stay inspectable."""
    census = measured["census"]
    mem = measured["memory"]
    xla = mem.get("xla") or {}
    H = measured["hosts"]
    budget = int(hbm_gb * (1 << 30))

    per_host_state = census["per_host"]
    fixed = census["fixed_bytes"]

    # validation: the census predicts the compiled program's argument
    # bytes — the compiler's own accounting of the state it was handed
    pred_args = census["bytes"] + SCALAR_ARG_BYTES
    meas_args = xla.get("argument_bytes")
    validation = {"predicted_argument_bytes": pred_args,
                  "measured_argument_bytes": meas_args,
                  "tolerance": tolerance}
    if meas_args is not None:
        # `is not None`, not truthiness: a degenerate backend
        # reporting 0 argument bytes must FAIL validation (exit 1),
        # not sail through as merely "unvalidated" (exit 3)
        err = abs(pred_args - meas_args) / max(meas_args, 1)
        validation["rel_error"] = round(err, 6)
        validation["ok"] = err <= tolerance
    else:
        validation["ok"] = None
        validation["why"] = ("backend provides no memory_analysis — "
                             "census unvalidated "
                             + str((xla.get("errors") or {})
                                   .get("memory_analysis", "")))

    # measured per-host overheads beyond the state census: XLA temp
    # buffers and non-aliased outputs scale with H (gather/scatter
    # workspace over [H,*] arrays); generated code is fixed
    temp_ph = (xla["temp_bytes"] / H
               if xla.get("temp_bytes") is not None else 0.0)
    out_ph = (max(xla["output_bytes"] - (xla.get("alias_bytes") or 0),
                  0) / H
              if xla.get("output_bytes") is not None else 0.0)
    gen = xla.get("generated_code_bytes") or 0
    per_host_total = per_host_state + temp_ph + out_ph
    fixed_total = fixed + gen

    headroom = budget - fixed_total
    max_hosts = int(headroom // per_host_total) if headroom > 0 else 0

    ladder = []
    for tgt in targets:
        total = fixed_total + per_host_total * tgt
        # sharding divides the per-host state/temp across chips but
        # replicates the fixed cost (topology oracle, program) on
        # every chip — chips solve per-chip budget >= fixed +
        # per_host * (H / chips)
        chips = (max(-(-int(per_host_total * tgt) // int(headroom)), 1)
                 if headroom > 0 else None)
        ladder.append({
            "hosts": tgt,
            "state_gib": round(_gib(per_host_state * tgt), 3),
            "temp_gib": round(_gib((temp_ph + out_ph) * tgt), 3),
            "total_gib": round(_gib(total), 3),
            "fits_one_chip": bool(total <= budget),
            "chips_at_budget": chips,
        })

    return {
        "config": measured["config"],
        "measured_hosts": H,
        "hbm_budget_gib": round(_gib(budget), 3),
        "per_host_state_bytes": per_host_state,
        "per_host_temp_bytes": round(temp_ph + out_ph, 1),
        "per_host_total_bytes": round(per_host_total, 1),
        "fixed_bytes": fixed_total,
        "hot_state_bytes_per_host":
            census["hosts"]["hot"]["runtime_bytes"] // max(H, 1),
        "watermark": {"peak_bytes": mem.get("peak_bytes"),
                      "source": mem.get("source"),
                      "per_device": mem.get("per_device")},
        "validation": validation,
        "max_hosts_per_chip": max_hosts,
        "ladder": ladder,
    }


def render_markdown(p: dict) -> str:
    v = p["validation"]
    lines = [
        f"## capacity plan: {p['config']} "
        f"(measured at H={p['measured_hosts']}, budget "
        f"{p['hbm_budget_gib']} GiB/chip)",
        "",
        f"- per-host state: **{p['per_host_state_bytes']} B** "
        f"(hot working set {p['hot_state_bytes_per_host']} B); "
        f"per-host temp+output: {p['per_host_temp_bytes']} B; "
        f"fixed: {p['fixed_bytes']} B",
        f"- max hosts on one chip: **{p['max_hosts_per_chip']:,}**",
        f"- watermark: {p['watermark']['peak_bytes']} B "
        f"({p['watermark']['source']})",
    ]
    if v["ok"] is None:
        lines.append(f"- validation: UNVALIDATED — {v.get('why')}")
    else:
        lines.append(
            f"- validation: census predicted "
            f"{v['predicted_argument_bytes']} B of program arguments, "
            f"XLA measured {v['measured_argument_bytes']} B — "
            f"{v['rel_error'] * 100:.2f}% error "
            f"({'within' if v['ok'] else 'OUTSIDE'} the "
            f"{v['tolerance'] * 100:.0f}% tolerance)")
    lines += [
        "",
        "| hosts | state GiB | temp GiB | total GiB | 1 chip? "
        "| chips @ budget |",
        "|---|---|---|---|---|---|",
    ]
    for row in p["ladder"]:
        lines.append(
            f"| {row['hosts']:,} | {row['state_gib']} "
            f"| {row['temp_gib']} | {row['total_gib']} "
            f"| {'yes' if row['fits_one_chip'] else 'no'} "
            f"| {row['chips_at_budget']} |")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="predict max hosts per chip from measured bytes "
                    "(docs/performance.md 'Sizing the 1M push')")
    ap.add_argument("config", help="phold | socks10k | tor50k | bulk1k")
    ap.add_argument("--n", type=int, default=None,
                    help="hosts at the MEASUREMENT scale (default: "
                         "the config's own)")
    ap.add_argument("--stop", type=int, default=2)
    ap.add_argument("--seed", type=int, default=None)
    ap.add_argument("--runahead-ms", type=int, default=0)
    ap.add_argument("--hbm-gb", type=float, default=16.0,
                    help="per-chip HBM budget in GiB (default 16, the "
                         "v5e class)")
    ap.add_argument("--targets", default=None,
                    help="comma-separated ladder host counts (default "
                         "100000,250000,500000,1000000)")
    ap.add_argument("--tolerance", type=float, default=0.10,
                    help="relative error the census prediction must "
                         "stay within vs the measured program "
                         "arguments (default 0.10)")
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--markdown", action="store_true",
                    help="markdown only (default prints markdown AND "
                         "a json line)")
    ap.add_argument("-o", "--out", default=None,
                    help="also write the markdown table to a file")
    args = ap.parse_args(argv)

    targets = DEFAULT_TARGETS
    if args.targets:
        try:
            targets = tuple(int(t) for t in args.targets.split(",")
                            if t.strip())
        except ValueError:
            ap.error(f"--targets {args.targets!r}: not integers")
        if not targets:
            ap.error("--targets names no host counts")

    if args.cpu:
        os.environ["PALLAS_AXON_POOL_IPS"] = ""
        os.environ["JAX_PLATFORMS"] = "cpu"

    measured = measure(args.config, n=args.n, stop=args.stop,
                       runahead_ms=args.runahead_ms, seed=args.seed)
    p = plan(measured, args.hbm_gb, targets=targets,
             tolerance=args.tolerance)

    if args.json:
        print(json.dumps(p, indent=1))
    else:
        md = render_markdown(p)
        print(md)
        if not args.markdown:
            print(json.dumps({k: p[k] for k in
                              ("config", "measured_hosts",
                               "max_hosts_per_chip",
                               "per_host_total_bytes")}))
        if args.out:
            with open(args.out, "w") as f:
                f.write(md + "\n")

    ok = p["validation"]["ok"]
    if ok is None:
        return 3
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
