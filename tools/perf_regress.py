#!/usr/bin/env python3
"""Perf regression gate over the ledger (obs.ledger): the newest
entry of every (scenario, platform, config-fingerprint) trajectory is
compared against the median of its own history with a noise band —
exit 1 the round a regression lands, instead of two rounds later
("phold fell 83k -> 34k between rounds 3 and 5 and nobody noticed",
ROADMAP #1).

Policy (docs/performance.md):

- the compared figure is WARM events/sec when the entry has a
  cold/warm split (compile time varies with cache state and is not
  the trajectory), else the cold-inclusive rate;
- baseline = median of the history; regression when the candidate
  falls below ``baseline * (1 - band)``;
- the band is ``max(--band, observed history rel-spread)`` capped at
  50%: a trajectory whose own history wobbles 25% cannot honestly
  gate at 15% (CPU-container runs are noisy; chip runs are tight);
- trajectories never mix platforms or fingerprints — a config change
  or a CPU-vs-TPU comparison starts a new series by construction;
- MEMORY gate (obs.memscope, docs/observability.md): entries carrying
  ``mem_peak_bytes`` (the run's device-buffer watermark) are also
  compared against their history median — a peak GROWING past
  ``baseline * (1 + band)`` is a regression exactly like a rate drop
  (the direction flips; the band policy is the same). Entries without
  the field (pre-memscope trajectories) neither gate nor feed a
  baseline, so the committed history stays untouched;
- OCCUPANCY gate (obs.passcope): entries carrying ``waste_frac``
  (the run's lockstep wasted-lane fraction) fail when waste grows
  past ``max(median * (1 + band), median + 0.05)`` — the absolute
  floor keeps near-zero waste medians from making the multiplicative
  band hypersensitive. Same direction-flipped policy as memory;
  pre-passcope entries neither gate nor feed a baseline;
- groups with fewer than ``--min-history + 1`` entries are reported
  as "insufficient history", never failed — but a candidate whose
  rate is zero/absent against REAL history is a failed comparison
  (the most extreme regression), not insufficient history;
- an entry with no warm split whose OWN phase breakdown says the XLA
  compile took more than ``COMPILE_BOUND`` of its wall is
  "compile-bound": its cold-inclusive rate measures compile-cache
  state, not throughput (a 5 sim-s phold on the CPU container is
  99.9% compile), so it is reported but never gated — and never
  counted into another candidate's history median. The throughput
  trajectory for such shapes comes from bench.py's warm-split
  entries. Since the serving layer's executable cache (PR 13,
  docs/serving.md) the phase map distinguishes compile-miss (a real
  XLA build) from compile-hit (a persistent-cache load): only the
  MISS wall argues for the exemption, so a cache-hit run of a
  formerly compile-bound shape becomes a gateable trajectory point
  instead of permanently reported-not-gated.

Pure stdlib + the ledger module loaded by file path (no jax import:
this gate must run headless in the verify skill on any box).

Usage:
  python tools/perf_regress.py [LEDGER] [--band 0.15] [--json]
      [--scenario S] [--platform P] [--min-history 1]
      [--candidate FILE]   # check one entry JSON without appending
Exit: 0 ok / 1 regression / 2 usage or unreadable ledger.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
from statistics import median

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "_perf_ledger", os.path.join(REPO, "shadow_tpu", "obs",
                                 "ledger.py"))
LG = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(LG)

DEFAULT_BAND = 0.15
MAX_BAND = 0.50
# no-warm-split entries whose compile phase exceeds this fraction of
# their wall carry no throughput signal (the rate is compile-cache
# state): reported, never gated, never history
COMPILE_BOUND = 0.5


def compile_bound(e) -> bool:
    if e.get("warm_events_per_sec"):
        return False  # the warm rate already excludes the compile
    wall = e.get("wall_seconds") or 0.0
    phases = e.get("phases") or {}
    # Since the serving layer's executable cache (PR 13), the phase
    # map splits the old monolithic "compile" into compile-miss (a
    # real XLA build) vs compile-hit (a persistent-cache load,
    # obs.perf PHASE_OF). A run that opened warm from the disk cache
    # is NOT compile-bound — its rate is real throughput and it
    # gates — so when the split is present only the MISS wall argues
    # for the exemption. Entries predating the split keep the
    # monolithic reading.
    if "compile-miss" in phases or "compile-hit" in phases:
        comp = phases.get("compile-miss", 0.0)
    else:
        comp = phases.get("compile", 0.0)
    return bool(wall) and comp / wall > COMPILE_BOUND


def check(entries, band=DEFAULT_BAND, min_history=1, candidate=None):
    """-> (results, any_regression). `entries` in append order;
    `candidate` (optional) is checked against ITS key's full ledger
    history instead of the last-vs-rest split."""
    groups = {}
    for e in entries:
        groups.setdefault(LG.key_of(e), []).append(e)
    results = []
    any_reg = False
    if candidate is not None:
        keys = [LG.key_of(candidate)]
        groups.setdefault(keys[0], [])
    else:
        keys = list(groups)
    for key in keys:
        es = groups[key]
        if candidate is not None:
            cand, hist = candidate, es
        else:
            cand, hist = es[-1], es[:-1]
        scenario, platform, fp = key
        row = {"scenario": scenario, "platform": platform,
               "fingerprint": fp, "entries": len(hist) + 1}
        cr = LG.entry_rate(cand) or 0.0
        if compile_bound(cand):
            # no throughput OR memory signal: a compile-bound run's
            # peak bytes measure the XLA build's transient footprint
            # (cache state), not the simulation's
            row["status"] = "compile-bound"
            row["candidate_rate"] = round(cr, 1) if cr else None
            results.append(row)
            continue
        # memory gate: peak-bytes growth past the band is a
        # regression like a rate drop (direction flipped — memory
        # regresses UP). Evaluated independently of the rate gate so
        # a flat-rate run that doubled its footprint still fails.
        cm = cand.get("mem_peak_bytes")
        mems = [m for m in (e.get("mem_peak_bytes") for e in hist
                            if not compile_bound(e)) if m]
        if cm and len(mems) >= min_history:
            mbase = median(mems)
            mspread = ((max(mems) - min(mems)) / mbase
                       if len(mems) >= 2 and mbase else 0.0)
            mband = min(max(band, mspread), MAX_BAND)
            mthresh = mbase * (1.0 + mband)
            mem_reg = cm > mthresh
            row.update({
                "mem_status": "REGRESSION" if mem_reg else "ok",
                "mem_peak_bytes": int(cm),
                "mem_baseline": round(mbase, 1),
                "mem_band": round(mband, 3),
                "mem_threshold": round(mthresh, 1),
                "mem_delta_frac": (round(cm / mbase - 1.0, 4)
                                   if mbase else None),
            })
            any_reg = any_reg or mem_reg
        # occupancy gate (obs.passcope, docs/performance.md): lane
        # waste GROWING past the band is a regression like a rate
        # drop (direction flipped, same band policy as memory).
        # Waste medians sit near 0 on healthy dense scenarios, where
        # a multiplicative band is hypersensitive (0.01 -> 0.012 is
        # noise, not a regression), so the threshold also gets an
        # absolute +0.05 floor. Entries without the field
        # (pre-passcope trajectories) neither gate nor feed a
        # baseline.
        cw = cand.get("waste_frac")
        wastes = [w for w in (e.get("waste_frac") for e in hist
                              if not compile_bound(e))
                  if w is not None]
        if cw is not None and len(wastes) >= min_history:
            wbase = median(wastes)
            wspread = ((max(wastes) - min(wastes)) / wbase
                       if len(wastes) >= 2 and wbase else 0.0)
            wband = min(max(band, wspread), MAX_BAND)
            wthresh = max(wbase * (1.0 + wband), wbase + 0.05)
            waste_reg = cw > wthresh
            row.update({
                "occ_status": "REGRESSION" if waste_reg else "ok",
                "waste_frac": round(cw, 4),
                "occ_baseline": round(wbase, 4),
                "occ_band": round(wband, 3),
                "occ_threshold": round(wthresh, 4),
                "occ_delta": round(cw - wbase, 4),
            })
            if cand.get("top_pass"):
                row["top_pass"] = cand["top_pass"]
            any_reg = any_reg or waste_reg
        rates = [r for r in (LG.entry_rate(e) for e in hist
                             if not compile_bound(e)) if r]
        if len(rates) < min_history or not rates:
            row["status"] = "insufficient-history"
            results.append(row)
            continue
        # NOTE: a zero/absent candidate rate with real history falls
        # through to the comparison and FAILS it (0 < any threshold)
        # — a scenario collapsing to zero events is the most extreme
        # regression, not "insufficient history"
        base = median(rates)
        rel_spread = ((max(rates) - min(rates)) / base
                      if len(rates) >= 2 and base else 0.0)
        band_eff = min(max(band, rel_spread), MAX_BAND)
        threshold = base * (1.0 - band_eff)
        regressed = cr < threshold
        row.update({
            "status": "REGRESSION" if regressed else "ok",
            "candidate_rate": round(cr, 1),
            "baseline_median": round(base, 1),
            "history": [round(r, 1) for r in rates],
            "band": round(band_eff, 3),
            "threshold": round(threshold, 1),
            "delta_frac": round(cr / base - 1.0, 4) if base else None,
            "candidate_git_rev": cand.get("git_rev"),
        })
        any_reg = any_reg or regressed
        results.append(row)
    return results, any_reg


def main(argv):
    ap = argparse.ArgumentParser()
    ap.add_argument("ledger", nargs="?", default=None,
                    help="ledger JSONL (default perf/ledger.jsonl)")
    ap.add_argument("--band", type=float, default=DEFAULT_BAND,
                    help="minimum relative noise band (default 0.15; "
                         "widened to the history's own spread)")
    ap.add_argument("--min-history", type=int, default=1,
                    help="history entries required before gating")
    ap.add_argument("--scenario", default=None)
    ap.add_argument("--platform", default=None)
    ap.add_argument("--candidate", default=None, metavar="FILE",
                    help="check this entry JSON against the ledger "
                         "without appending it")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    path = args.ledger or LG.default_path()
    if path is None or not os.path.exists(path):
        sys.stderr.write(f"perf_regress: no ledger at {path!r}\n")
        return 2
    entries = LG.read(path)
    if args.scenario:
        entries = [e for e in entries
                   if e.get("scenario") == args.scenario]
    if args.platform:
        entries = [e for e in entries
                   if e.get("platform") == args.platform]
    candidate = None
    if args.candidate:
        try:
            with open(args.candidate) as f:
                candidate = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            sys.stderr.write(f"perf_regress: --candidate: {e}\n")
            return 2
    results, any_reg = check(entries, band=args.band,
                             min_history=args.min_history,
                             candidate=candidate)
    if args.json:
        print(json.dumps({"results": results,
                          "regression": any_reg}, indent=1))
    else:
        for r in results:
            if r["status"] == "insufficient-history":
                print(f"~ {r['scenario']} [{r['platform']}] "
                      f"{r['fingerprint']}: insufficient history "
                      f"({r['entries']} entries)")
            elif r["status"] == "compile-bound":
                print(f"~ {r['scenario']} [{r['platform']}] "
                      f"{r['fingerprint']}: compile-bound "
                      f"(rate {r['candidate_rate']} is cache state, "
                      "not throughput — not gated)")
            else:
                reg = (r["status"] == "REGRESSION"
                       or r.get("mem_status") == "REGRESSION")
                mark = "!!" if reg else "ok"
                print(f"{mark} {r['scenario']} [{r['platform']}] "
                      f"{r['fingerprint']}: {r['candidate_rate']} "
                      f"vs median {r['baseline_median']} "
                      f"(band {r['band'] * 100:.0f}%, "
                      f"threshold {r['threshold']}, "
                      f"delta {r['delta_frac'] * 100:+.1f}%)")
            if r.get("mem_status"):
                mmark = "!!" if r["mem_status"] == "REGRESSION" else "ok"
                print(f"   {mmark} memory: peak "
                      f"{r['mem_peak_bytes']} vs median "
                      f"{r['mem_baseline']} (band "
                      f"{r['mem_band'] * 100:.0f}%, delta "
                      f"{r['mem_delta_frac'] * 100:+.1f}%)")
            if r.get("occ_status"):
                omark = "!!" if r["occ_status"] == "REGRESSION" else "ok"
                top = (f", top pass {r['top_pass']}"
                       if r.get("top_pass") else "")
                print(f"   {omark} occupancy: waste "
                      f"{r['waste_frac']} vs median "
                      f"{r['occ_baseline']} (threshold "
                      f"{r['occ_threshold']}, delta "
                      f"{r['occ_delta']:+.4f}{top})")
        if any_reg:
            print("PERF REGRESSION — see rows marked !! "
                  "(docs/performance.md for the protocol)")
    return 1 if any_reg else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
