#!/usr/bin/env python3
"""Diff two determinism digest chains (the ``--digest FILE`` output of
``python -m shadow_tpu`` / ``Simulation.run(digest=...)``) and report
WHERE two runs first diverge.

Without ``--bisect`` this is pure stdlib and runs headless in
milliseconds: it walks the two chains record by record, finds the
first record whose running chain hash differs, and attributes the
divergence — which state *sections* differ (event_queue / tcp / nic /
outbox / rng / app / stats / hosted, see engine.state.STATE_SECTIONS),
which *hosts* differ (when the chains carry per-host digests), and
whether the hosted-channel op stream already diverged (the hosted
child behaved differently) or only engine state did.

With ``--bisect`` the tool replays both runs from their manifests at
digest cadence 1 — from the nearest usable checkpoint when the
manifest records one, else from the start — with the stop time clamped
just past the first divergent record, and pins the EXACT window where
the chains split. The replay imports shadow_tpu (jax required) and
recompiles the window program at chunk 1; everything needed is read
from the ``<chain>.manifest.json`` companions (config path, seed,
engine config, runahead, TCP scalars).

Usage:
  python tools/divergence.py a.digests.jsonl b.digests.jsonl
      [--json] [--bisect] [--use-checkpoint] [--keep-replays DIR]

Exit status: 0 = chains identical, 1 = divergence found (reported),
2 = usage/input error.
"""

import argparse
import json
import os
import sys


def _die(msg):
    print(f"divergence: {msg}", file=sys.stderr)
    raise SystemExit(2)


def load_chain(path):
    """-> (records, manifest or None). One-line diagnosis on bad
    input (missing / empty / truncated chain), never a traceback."""
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError as e:
        _die(f"cannot read {path}: {e.strerror or e}")
    recs = []
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            recs.append(json.loads(line))
        except json.JSONDecodeError:
            _die(f"{path}: line {i + 1} is not valid JSON — chain "
                 "truncated mid-record?")
    if not recs:
        _die(f"{path}: empty digest chain (no records)")
    for r in recs:
        if "chain" not in r or "sections" not in r:
            _die(f"{path}: records lack chain/sections fields — not a "
                 "shadow_tpu digest chain")
    manifest = None
    mp = path + ".manifest.json"
    if os.path.exists(mp):
        try:
            with open(mp) as f:
                manifest = json.load(f)
        except (OSError, json.JSONDecodeError):
            manifest = None
    return recs, manifest


def manifest_deltas(ma, mb):
    """Fields two comparable manifests are allowed to differ in vs the
    ones that explain a divergence (seed, config, versions...)."""
    if not ma or not mb:
        return None
    skip = {"argv", "checkpoint_path"}
    out = {}
    for k in sorted(set(ma) | set(mb)):
        if k in skip:
            continue
        if ma.get(k) != mb.get(k):
            out[k] = {"a": ma.get(k), "b": mb.get(k)}
    return out


def _attribute(ra, rb, ma=None, mb=None):
    """Per-record attribution: divergent sections, hosts, hosted tier."""
    sa, sb = ra.get("sections", {}), rb.get("sections", {})
    sections = sorted(k for k in set(sa) | set(sb)
                      if sa.get(k) != sb.get(k))
    hosts = None
    ha, hb = ra.get("hosts"), rb.get("hosts")
    if ha is not None and hb is not None:
        names = (ma or {}).get("host_names") or (mb or {}).get(
            "host_names")
        hosts = []
        for i in range(min(len(ha), len(hb))):
            if ha[i] != hb[i]:
                hosts.append({"host": i,
                              "name": (names[i] if names and
                                       i < len(names) else None)})
        if len(ha) != len(hb):
            hosts.append({"host": min(len(ha), len(hb)),
                          "name": "(host counts differ)"})
    hosted = None
    if ra.get("hosted") != rb.get("hosted"):
        da, db = ra.get("hosted") or {}, rb.get("hosted") or {}
        hosted = {"ops_diverged": da.get("ops") != db.get("ops"),
                  "shim_hosts": sorted(
                      k for k in set(da.get("shim", {})) |
                      set(db.get("shim", {}))
                      if da.get("shim", {}).get(k) !=
                      db.get("shim", {}).get(k))}
    return {"window": ra.get("window"), "window_b": rb.get("window"),
            "sim_ns": ra.get("sim_ns"), "kind": ra.get("kind"),
            "sections": sections, "hosts": hosts, "hosted": hosted}


def first_divergence(a_recs, b_recs, ma=None, mb=None):
    """-> report dict, or None when the chains are identical."""
    n = min(len(a_recs), len(b_recs))
    for i in range(n):
        ra, rb = a_recs[i], b_recs[i]
        if ra.get("chain") == rb.get("chain"):
            continue
        rep = {"record": i,
               "prev_window": (a_recs[i - 1]["window"] if i else None),
               "prev_sim_ns": (a_recs[i - 1]["sim_ns"] if i else None)}
        rep.update(_attribute(ra, rb, ma, mb))
        return rep
    if len(a_recs) != len(b_recs):
        longer = a_recs if len(a_recs) > len(b_recs) else b_recs
        return {"record": n, "truncated": True,
                "window": longer[n]["window"],
                "sim_ns": longer[n]["sim_ns"], "kind": longer[n]["kind"],
                "sections": [], "hosts": None, "hosted": None,
                "prev_window": a_recs[n - 1]["window"],
                "prev_sim_ns": a_recs[n - 1]["sim_ns"],
                "note": ("one chain ends early — the runs took "
                         "different window counts after this point")}
    return None


# --- bisection: cadence-1 replay from the manifests ----------------------

def _pick_checkpoint(manifest, bound_ns):
    """-> (path, wstart_ns) for a usable checkpoint, else None: in
    the rotating store the manifest records (or a legacy single-file
    snapshot), content-verified, and saved at or before the last
    MATCHING record (`bound_ns`) — a checkpoint inside the divergence
    bracket already embodies the divergence, and resuming from it
    would pin the wrong window. Fault-schedule runs resume fine (the
    snapshot stamps the injector position); hosted manifests never
    resume here (journal replay respawns real children — replay from
    the start instead)."""
    ck = manifest.get("checkpoint_path")
    if not ck or bound_ns is None or manifest.get("hosted"):
        return None
    try:
        import numpy as np
        from shadow_tpu.engine.checkpoint import (CheckpointStore,
                                                  _verify)
        if os.path.isfile(ck) and ck.endswith(".npz"):
            cands = [ck]
        else:
            cands = sorted(CheckpointStore(ck).snapshots(),
                           reverse=True)
        best = None
        for c in cands:
            if not _verify(c):
                continue
            try:
                with np.load(c) as z:
                    ws = int(z["__wstart__"])
            except Exception:
                continue
            if ws <= int(bound_ns) and (best is None or ws > best[1]):
                best = (c, ws)
        return best
    except Exception:
        return None


def replay_digest(manifest, stop_ns, out_path, resume=None):
    """Re-run one manifest's scenario with per-window digests (cadence
    1) up to just past `stop_ns`, writing a fresh chain to `out_path`.
    Reproduces what the manifest records: config XML + seed + engine
    config + runahead window + TCP scalars. CLI flags that mutate the
    scenario elsewhere (per-host buffer defaults, --engine-caps beyond
    the recorded config) are already baked into engine_config; other
    mutations are not replayed — compare manifests first."""
    import dataclasses

    import jax.numpy as jnp

    from shadow_tpu.core.config import load_xml
    from shadow_tpu.engine.sim import Simulation
    from shadow_tpu.engine.state import EngineConfig

    cfg_path = manifest.get("config_path")
    if not cfg_path:
        _die("--bisect needs manifests with a config_path (runs "
             "recorded via the CLI, or load_xml from a file)")
    if not os.path.exists(cfg_path):
        _die(f"--bisect: recorded config {cfg_path} no longer exists")
    scen = load_xml(cfg_path)
    scen.seed = int(manifest["seed"])
    # stop just past the divergent record so its window replays whole
    scen.stop_time = min(int(manifest["stop_time_ns"]),
                         int(stop_ns) + int(manifest["min_jump_ns"]))
    cfgd = dict(manifest["engine_config"])
    if cfgd.get("app_kinds") is not None:
        cfgd["app_kinds"] = tuple(cfgd["app_kinds"])
    cfg = EngineConfig(**cfgd)
    sim = Simulation(scen, engine_cfg=cfg)
    tcp = manifest.get("tcp", {})
    sim.sh = sim.sh.replace(
        min_jump=jnp.int64(int(manifest["min_jump_ns"])),
        cc_kind=jnp.int32(int(tcp.get("cc_kind", int(sim.sh.cc_kind)))),
        tcp_init_wnd=jnp.float32(tcp.get("init_wnd",
                                         float(sim.sh.tcp_init_wnd))),
        tcp_ssthresh0=jnp.float32(tcp.get(
            "ssthresh0", float(sim.sh.tcp_ssthresh0))))
    if cfg.cc_kind != int(tcp.get("cc_kind", cfg.cc_kind)):
        cfg = dataclasses.replace(cfg,
                                  cc_kind=int(tcp["cc_kind"]))
        sim.cfg = cfg
    if resume is not None and sim.hosting is not None:
        resume = None  # hosted replay respawns real children; bisect
        #                replays from the start instead (fault-schedule
        #                resume is supported: the snapshot stamps the
        #                injector's position)
    if resume:
        print(f"divergence: replaying from checkpoint {resume}",
              file=sys.stderr)
    sim.run(digest=out_path, digest_every=1, resume_from=resume,
            resume_unchecked=True, digest_rewind=False)


def bisect(ma, mb, div, workdir, use_checkpoint=False):
    """Replay both runs at cadence 1 and pin the exact window."""
    stop_ns = int(div["sim_ns"])
    pa = os.path.join(workdir, "replay-a.jsonl")
    pb = os.path.join(workdir, "replay-b.jsonl")
    resume_a = resume_b = None
    if use_checkpoint:
        # the replays are compared record by record, so BOTH must
        # resume from the same window or neither — misaligned chains
        # would report a bogus divergence at record 0
        ca = _pick_checkpoint(ma, div.get("prev_sim_ns"))
        cb = _pick_checkpoint(mb, div.get("prev_sim_ns"))
        if ca and cb and ca[1] == cb[1]:
            resume_a, resume_b = ca[0], cb[0]
        elif ca or cb:
            print("divergence: checkpoints unusable or misaligned "
                  "across the two runs — replaying from the start",
                  file=sys.stderr)
    replay_digest(ma, stop_ns, pa, resume=resume_a)
    replay_digest(mb, stop_ns, pb, resume=resume_b)
    ra, _ = load_chain(pa)
    rb, _ = load_chain(pb)
    fine = first_divergence(ra, rb, ma, mb)
    if fine is None:
        return {"note": ("cadence-1 replays are identical up to the "
                         "divergent record — the original divergence "
                         "is not reproducible from the manifests "
                         "(an unrecorded input differs between the "
                         "original runs)")}
    return fine


# --- report rendering ----------------------------------------------------

def _render(div, deltas, bis=None):
    out = []
    w = div.get("window")
    out.append(f"first divergence: record #{div['record']} — window "
               f"{w} (sim {div.get('sim_ns', 0) / 1e9:.9f}s, "
               f"kind={div.get('kind')})")
    if div.get("prev_window") is not None:
        out.append(f"  last matching record: window "
                   f"{div['prev_window']} "
                   f"(sim {div['prev_sim_ns'] / 1e9:.9f}s)")
    if div.get("truncated"):
        out.append(f"  {div['note']}")
    if div.get("window_b") is not None and div["window_b"] != w:
        out.append(f"  (chain B is at window {div['window_b']} here — "
                   "the runs advanced differently)")
    if div.get("sections"):
        out.append("  divergent sections: " + ", ".join(div["sections"]))
    hosts = div.get("hosts")
    if hosts:
        names = ", ".join(
            f"{h['host']}" + (f" ({h['name']})" if h.get("name") else "")
            for h in hosts[:16])
        more = f" (+{len(hosts) - 16} more)" if len(hosts) > 16 else ""
        out.append(f"  divergent hosts: {names}{more}")
    elif hosts is not None:
        out.append("  divergent hosts: none individually (global "
                   "section state only)")
    else:
        out.append("  per-host detail not recorded (host count above "
                   "the digest host_detail cap)")
    hosted = div.get("hosted")
    if hosted:
        if hosted.get("shim_hosts"):
            out.append("  hosted op stream diverged on: "
                       + ", ".join(hosted["shim_hosts"]))
        elif hosted.get("ops_diverged"):
            out.append("  hosted op-batch stream diverged")
    if deltas:
        out.append("  manifest deltas: " + ", ".join(
            f"{k} ({v['a']!r} vs {v['b']!r})" if k == "seed" else k
            for k, v in deltas.items()))
    if bis is not None:
        if "note" in bis and "window" not in bis:
            out.append(f"  bisect: {bis['note']}")
        else:
            out.append(f"  bisect: exact divergent window = "
                       f"{bis.get('window')} (sim "
                       f"{bis.get('sim_ns', 0) / 1e9:.9f}s); sections: "
                       + (", ".join(bis.get("sections") or ["-"])))
            bh = bis.get("hosts")
            if bh:
                out.append("  bisect hosts: " + ", ".join(
                    f"{h['host']}" + (f" ({h['name']})"
                                      if h.get("name") else "")
                    for h in bh[:16]))
    return "\n".join(out)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="diff two shadow_tpu digest chains; report the "
                    "first divergent window with section/host "
                    "attribution")
    ap.add_argument("chain_a")
    ap.add_argument("chain_b")
    ap.add_argument("--json", action="store_true",
                    help="print the report as one JSON object")
    ap.add_argument("--bisect", action="store_true",
                    help="replay both runs from their manifests at "
                         "digest cadence 1 to pin the exact window "
                         "(imports shadow_tpu; recompiles)")
    ap.add_argument("--use-checkpoint", action="store_true",
                    help="with --bisect: resume from the checkpoint "
                         "recorded in the manifest when usable")
    ap.add_argument("--keep-replays", default=None, metavar="DIR",
                    help="with --bisect: write the cadence-1 replay "
                         "chains here instead of a temp dir")
    args = ap.parse_args(argv)

    a_recs, ma = load_chain(args.chain_a)
    b_recs, mb = load_chain(args.chain_b)
    deltas = manifest_deltas(ma, mb)
    if (ma and mb and
            ma.get("digest_every") != mb.get("digest_every")):
        _die("chains were recorded at different cadences "
             f"({ma['digest_every']} vs {mb['digest_every']} windows) "
             "— re-record with matching --digest-every")

    div = first_divergence(a_recs, b_recs, ma, mb)
    if div is None:
        if args.json:
            print(json.dumps({"identical": True,
                              "records": len(a_recs),
                              "manifest_deltas": deltas}))
        else:
            print(f"chains identical ({len(a_recs)} records"
                  + (", manifest deltas: " + ", ".join(deltas)
                     if deltas else "") + ")")
        return 0

    bis = None
    if args.bisect:
        if not (ma and mb):
            _die("--bisect needs both <chain>.manifest.json companions")
        workdir = args.keep_replays
        if workdir:
            os.makedirs(workdir, exist_ok=True)
            bis = bisect(ma, mb, div, workdir,
                         use_checkpoint=args.use_checkpoint)
        else:
            import tempfile
            with tempfile.TemporaryDirectory(
                    prefix="shadow-divergence.") as tmp:
                bis = bisect(ma, mb, div, tmp,
                             use_checkpoint=args.use_checkpoint)

    if args.json:
        print(json.dumps({"identical": False, "first_divergence": div,
                          "manifest_deltas": deltas, "bisect": bis}))
    else:
        print(_render(div, deltas, bis))
    return 1


if __name__ == "__main__":
    sys.exit(main())
