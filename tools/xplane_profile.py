#!/usr/bin/env python3
"""Capture a jax.profiler trace of the window loop and aggregate
device-op durations WITHOUT tensorboard.

The round-4 profiling problem: the tunnel backend adds ~100 ms to
every dispatch, so host-side phase timing (tools/phase_profile.py)
resolves nothing finer than ~10 ms — while the unattributed cost in
the socks10k wall lives somewhere INSIDE the compiled window program.
jax.profiler writes .xplane.pb files locally; this tool decodes the
protobuf wire format directly (XSpace/XPlane/XLine/XEvent — the
schema is tensorflow/tsl's xplane.proto) and prints the top ops by
total self duration per plane, which names the hot HLOs (fusions,
copies, sorts, scatters) exactly.

Usage:
  python tools/xplane_profile.py socks10k [--n ...] [--warm-s 6]
      [--trace-windows 16] [--runahead-ms 10] [--top 40] [--cpu]
"""

from __future__ import annotations

import collections
import glob
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


# --- minimal protobuf wire decoding ---------------------------------------

def _varint(buf, i):
    x = 0
    s = 0
    while True:
        b = buf[i]
        i += 1
        x |= (b & 0x7F) << s
        if not b & 0x80:
            return x, i
        s += 7


def _fields(buf):
    """Yield (field_number, wire_type, value) over a message buffer.
    value: int for varint(0)/fixed(1,5), memoryview for bytes(2)."""
    i, n = 0, len(buf)
    while i < n:
        key, i = _varint(buf, i)
        fn, wt = key >> 3, key & 7
        if wt == 0:
            v, i = _varint(buf, i)
        elif wt == 2:
            ln, i = _varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 1:
            v = int.from_bytes(buf[i:i + 8], "little")
            i += 8
        elif wt == 5:
            v = int.from_bytes(buf[i:i + 4], "little")
            i += 4
        else:  # groups unsupported/absent in xplane
            raise ValueError(f"wire type {wt}")
        yield fn, wt, v


def parse_xspace(path):
    """-> [(plane_name, {op_name: total_duration_ps})]"""
    buf = memoryview(open(path, "rb").read())
    planes = []
    for fn, wt, v in _fields(buf):
        if fn == 1 and wt == 2:             # XSpace.planes
            planes.append(_parse_plane(v))
    return planes


def _parse_plane(buf):
    name = ""
    meta = {}                                # id -> event name
    lines = []
    for fn, wt, v in _fields(buf):
        if fn == 2 and wt == 2:              # XPlane.name
            name = bytes(v).decode("utf-8", "replace")
        elif fn == 3 and wt == 2:            # XPlane.lines
            lines.append(v)
        elif fn == 4 and wt == 2:            # XPlane.event_metadata (map)
            k, m = None, None
            for fn2, wt2, v2 in _fields(v):
                if fn2 == 1:
                    k = v2
                elif fn2 == 2 and wt2 == 2:
                    m = v2
            if k is not None and m is not None:
                mname = ""
                for fn3, wt3, v3 in _fields(m):
                    if fn3 == 2 and wt3 == 2:  # XEventMetadata.name
                        mname = bytes(v3).decode("utf-8", "replace")
                meta[k] = mname
    # Aggregate PER LINE: device traces nest container ops (module,
    # while, conditional) on separate lines above the leaf-op line, so
    # a single merged counter double-counts bodies inside containers
    # and conds "cost" their whole branch. Per-line tops let the
    # reader see both views: containers (where the window time sits
    # structurally) and leaves (which HLOs actually burn it).
    per_line = []                            # (line_name, durs, counts)
    for lbuf in lines:
        lname = ""
        durs = collections.Counter()
        counts = collections.Counter()
        for fn, wt, v in _fields(lbuf):
            if fn == 2 and wt == 2:          # XLine.name
                lname = bytes(v).decode("utf-8", "replace")
            # this build writes XLine.events at field 4 (older schema
            # revisions used 6 — accept both)
            elif fn in (4, 6) and wt == 2:   # XLine.events
                mid, dur = None, 0
                for fn2, wt2, v2 in _fields(v):
                    if fn2 == 1:             # XEvent.metadata_id
                        mid = v2
                    elif fn2 == 3:           # XEvent.duration_ps
                        dur = v2
                if mid is not None:
                    key = meta.get(mid, f"#{mid}")
                    durs[key] += dur
                    counts[key] += 1
        if durs:
            per_line.append((lname, dict(durs), dict(counts)))
    return name, per_line


def aggregate(trace_dir, top=40):
    out = []
    for path in sorted(glob.glob(
            os.path.join(trace_dir, "**", "*.xplane.pb"),
            recursive=True)):
        for name, per_line in parse_xspace(path):
            for lname, durs, counts in per_line:
                total = sum(durs.values())
                if not total:
                    continue
                ops = sorted(durs.items(), key=lambda kv: -kv[1])[:top]
                out.append({
                    "plane": name,
                    "line": lname,
                    "total_ms": round(total / 1e9, 3),
                    "ops": [{"op": k, "ms": round(v / 1e9, 3),
                             "n": counts[k],
                             "pct": round(100 * v / total, 1)}
                            for k, v in ops],
                })
    return out


# --- capture ---------------------------------------------------------------

def capture(name, n=None, warm_s=6.0, trace_windows=16, runahead_ms=0,
            chunk=8, trace_dir="/tmp/shadow_xplane"):
    import jax
    import jax.numpy as jnp
    from tools.baseline_configs import CONFIGS
    from shadow_tpu.engine.sim import Simulation
    from shadow_tpu.engine.window import run_windows

    builder, capf, n_default = CONFIGS[name]
    n = n or n_default
    sim = Simulation(builder(n, 60), engine_cfg=capf(n))
    if runahead_ms:
        sim.sh = sim.sh.replace(min_jump=jnp.int64(runahead_ms * 10**6))
    hosts, hp, sh, cfg = sim.hosts, sim.hp, sim.sh, sim.cfg

    t0 = jnp.min(hosts.eq_next)
    ws, we = t0, t0 + sh.min_jump
    while float(ws) / 1e9 < warm_s:
        hosts, ws, we, _, _ = run_windows(hosts, hp, sh, ws, we, cfg,
                                          chunk)
    ran = 0
    with jax.profiler.trace(trace_dir):
        while ran < trace_windows:
            hosts, ws, we, k, _ = run_windows(hosts, hp, sh, ws, we,
                                              cfg, chunk)
            jax.block_until_ready(hosts.stats)
            ran += int(k)
    return trace_dir, ran


def main(argv):
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("config")
    ap.add_argument("--n", type=int, default=None)
    ap.add_argument("--warm-s", type=float, default=6.0)
    ap.add_argument("--trace-windows", type=int, default=16)
    ap.add_argument("--runahead-ms", type=int, default=0)
    ap.add_argument("--chunk", type=int, default=8)
    ap.add_argument("--top", type=int, default=40)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--parse-only", default=None,
                    help="skip capture; aggregate this trace dir")
    args = ap.parse_args(argv)
    if args.parse_only:
        print(json.dumps(aggregate(args.parse_only, args.top), indent=1))
        return
    if args.cpu:
        os.environ["PALLAS_AXON_POOL_IPS"] = ""
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
    else:
        from bench import _enable_compile_cache
        _enable_compile_cache()
    import shutil
    shutil.rmtree("/tmp/shadow_xplane", ignore_errors=True)
    tdir, ran = capture(args.config, n=args.n, warm_s=args.warm_s,
                        trace_windows=args.trace_windows,
                        runahead_ms=args.runahead_ms, chunk=args.chunk)
    print(json.dumps({"traced_windows": ran,
                      "planes": aggregate(tdir, args.top)}, indent=1))


if __name__ == "__main__":
    main(sys.argv[1:])
