#!/usr/bin/env python3
"""Capture a jax.profiler trace of the window loop and aggregate
device-op durations WITHOUT tensorboard.

The round-4 profiling problem: the tunnel backend adds ~100 ms to
every dispatch, so host-side phase timing (tools/phase_profile.py)
resolves nothing finer than ~10 ms — while the unattributed cost in
the socks10k wall lives somewhere INSIDE the compiled window program.
jax.profiler writes .xplane.pb files locally; this tool decodes the
protobuf wire format directly (XSpace/XPlane/XLine/XEvent — the
schema is tensorflow/tsl's xplane.proto) and prints the top ops by
total self duration per plane, which names the hot HLOs (fusions,
copies, sorts, scatters) exactly.

The wire decoder lives in shadow_tpu/obs/passcope.py (the pass-time
observatory promoted it to an importable module that also maps HLO
self-times back to the named_scope pass labels); this tool is the
thin CLI over it — loaded BY FILE PATH so it works with no jax
installed (the headless-tools convention). For the per-pass table
keyed by stateflow entry names, run the engine with ``--passcope``
or decode a trace dir with ``tools/trace_report.py --passcope``.

Usage:
  python tools/xplane_profile.py socks10k [--n ...] [--warm-s 6]
      [--trace-windows 16] [--runahead-ms 10] [--top 40] [--cpu]
  python tools/xplane_profile.py --self-check   # CI fixture decode
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _load_passcope():
    """Load obs/passcope.py by file path — no shadow_tpu package
    import (which would pull in jax; this tool must run headless)."""
    import importlib.util
    path = os.path.join(REPO, "shadow_tpu", "obs", "passcope.py")
    spec = importlib.util.spec_from_file_location("_passcope", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_PC = _load_passcope()
# re-exported: tests and older callers import the decoder from here
_varint = _PC._varint
_fields = _PC._fields
parse_xspace = _PC.parse_xspace


def aggregate(trace_dir, top=40):
    import glob
    out = []
    for path in sorted(glob.glob(
            os.path.join(trace_dir, "**", "*.xplane.pb"),
            recursive=True)):
        for name, per_line in parse_xspace(path):
            for lname, durs, counts in per_line:
                total = sum(durs.values())
                if not total:
                    continue
                ops = sorted(durs.items(), key=lambda kv: -kv[1])[:top]
                out.append({
                    "plane": name,
                    "line": lname,
                    "total_ms": round(total / 1e9, 3),
                    "ops": [{"op": k, "ms": round(v / 1e9, 3),
                             "n": counts[k],
                             "pct": round(100 * v / total, 1)}
                            for k, v in ops],
                })
    return out


# --- capture ---------------------------------------------------------------

def capture(name, n=None, warm_s=6.0, trace_windows=16, runahead_ms=0,
            chunk=8, trace_dir="/tmp/shadow_xplane"):
    import jax
    import jax.numpy as jnp
    from tools.baseline_configs import CONFIGS
    from shadow_tpu.engine.sim import Simulation
    from shadow_tpu.engine.window import run_windows

    builder, capf, n_default = CONFIGS[name]
    n = n or n_default
    sim = Simulation(builder(n, 60), engine_cfg=capf(n))
    if runahead_ms:
        sim.sh = sim.sh.replace(min_jump=jnp.int64(runahead_ms * 10**6))
    hosts, hp, sh, cfg = sim.hosts, sim.hp, sim.sh, sim.cfg

    t0 = jnp.min(hosts.eq_next)
    ws, we = t0, t0 + sh.min_jump
    while float(ws) / 1e9 < warm_s:
        hosts, ws, we, _, _ = run_windows(hosts, hp, sh, ws, we, cfg,
                                          chunk)
    ran = 0
    with jax.profiler.trace(trace_dir):
        while ran < trace_windows:
            hosts, ws, we, k, _ = run_windows(hosts, hp, sh, ws, we,
                                              cfg, chunk)
            jax.block_until_ready(hosts.stats)
            ran += int(k)
    return trace_dir, ran


def main(argv):
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("config", nargs="?", default=None)
    ap.add_argument("--n", type=int, default=None)
    ap.add_argument("--warm-s", type=float, default=6.0)
    ap.add_argument("--trace-windows", type=int, default=16)
    ap.add_argument("--runahead-ms", type=int, default=0)
    ap.add_argument("--chunk", type=int, default=8)
    ap.add_argument("--top", type=int, default=40)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--parse-only", default=None,
                    help="skip capture; aggregate this trace dir")
    ap.add_argument("--self-check", action="store_true",
                    help="decode the committed fixture trace and "
                         "assert the exact pass table / occupancy "
                         "numbers (obs.passcope.self_check — the CI "
                         "step; stdlib only, no jax needed)")
    args = ap.parse_args(argv)
    if args.self_check:
        _PC.self_check()
        return
    if args.parse_only:
        print(json.dumps(aggregate(args.parse_only, args.top), indent=1))
        return
    if args.config is None:
        ap.error("config is required unless --parse-only/--self-check")
    if args.cpu:
        os.environ["PALLAS_AXON_POOL_IPS"] = ""
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
    else:
        from bench import _enable_compile_cache
        _enable_compile_cache()
    import shutil
    shutil.rmtree("/tmp/shadow_xplane", ignore_errors=True)
    tdir, ran = capture(args.config, n=args.n, warm_s=args.warm_s,
                        trace_windows=args.trace_windows,
                        runahead_ms=args.runahead_ms, chunk=args.chunk)
    print(json.dumps({"traced_windows": ran,
                      "planes": aggregate(tdir, args.top)}, indent=1))


if __name__ == "__main__":
    main(sys.argv[1:])
