#!/usr/bin/env python3
"""Unified per-phase perf report: where every wall-millisecond went.

This is the one entry point the perf workflow starts from
(docs/performance.md), unifying the three previous views:

- the HOST phase attribution (obs.perf over obs.trace spans): setup /
  compile / window chunks / hosting / tracker / pcap / checkpoint /
  digest / faults / finalize, each with wall, fraction and per-event
  cost — and an explicit residual when the named phases sum to less
  than 90% of the measured wall (obs.perf.MIN_ATTRIBUTED);
- the MODELED roofline view (SimReport.cost_model): pass mix,
  estimated HBM traffic, roofline_frac;
- optionally (``--device-phases``) the MEASURED device split of the
  `window` phase — per-rung pass walls, exchange, reductions — via
  tools/phase_profile.py's steady-state probes (the xplane decoder,
  tools/xplane_profile.py, stays the separate deep-dive for naming
  individual HLOs).

Modes:
  python tools/perf_report.py phold --n 1024 --stop 5 --cpu
  python tools/perf_report.py socks10k --n 400 --stop 10 --cpu \
      [--runahead-ms 10] [--device-phases] [--ledger [PATH]]
  python tools/perf_report.py --trace trace.json [--wall SEC]
  python tools/perf_report.py --self-check        # no jax, <1s

Live runs append a perf-ledger entry with ``--ledger`` (obs.ledger;
default path perf/ledger.jsonl) so ad-hoc measurements extend the
same trajectory tools/perf_regress.py gates on.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _load_stdlib_module(relpath, name):
    """Import a pure-stdlib module from the package by FILE PATH —
    shadow_tpu/__init__ imports jax, which the headless modes
    (--self-check, --trace) must not pay (nor risk the ambient
    accelerator env)."""
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, relpath))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def perf_mod():
    return _load_stdlib_module("shadow_tpu/obs/perf.py", "_perf_attr")


def ledger_mod():
    return _load_stdlib_module("shadow_tpu/obs/ledger.py",
                               "_perf_ledger")


# --- scenario builders (shared with tools/perf_ab.py) ---------------------

def build_config(config: str, n: int = None, stop: int = 10):
    """-> (scenario, engine_cfg, n). `config` is `phold` (bench.py's
    DES stress shape) or any tools/baseline_configs name
    (socks10k / tor50k / bulk1k)."""
    if config == "phold":
        import bench
        n = n or 4096
        return bench._phold_scenario(n, stop), bench._phold_cfg(n), n
    from tools.baseline_configs import CONFIGS
    builder, capf, n_default = CONFIGS[config]
    n = n or n_default
    return builder(n, stop), capf(n), n


# --- offline: attribute an existing trace file ----------------------------

def report_trace(path: str, wall_s: float = None, events: int = None):
    PF = perf_mod()
    with open(path) as f:
        doc = json.load(f)
    evs = doc.get("traceEvents", doc) if isinstance(doc, dict) else doc
    spans = [e for e in evs if e.get("ph") == "X"]
    if not spans:
        raise SystemExit(f"perf_report: {path}: no complete spans")
    if wall_s is None:
        t0 = min(e["ts"] for e in spans)
        t1 = max(e["ts"] + e["dur"] for e in spans)
        wall_s = (t1 - t0) / 1e6
    if events is None:
        events = sum(e.get("args", {}).get("events", 0)
                     for e in spans if e["name"] == "chunk") or None
    return PF.attribute(spans, wall_s, events)


# --- live: run a config with the span recorder on -------------------------

def report_live(config, n=None, stop=10, runahead_ms=0, chunk=0,
                device_phases=False, seed=None):
    import jax
    from shadow_tpu.engine.sim import Simulation
    from shadow_tpu.obs import perf as PF
    from shadow_tpu.obs import trace as TR
    from tools.baseline_configs import apply_runahead

    scen, cfg, n = build_config(config, n, stop)
    if seed is not None:
        scen.seed = seed
    if chunk:
        import dataclasses
        cfg = dataclasses.replace(cfg, chunk_windows=chunk)
    TR.install(None)  # collect-only: attribution needs spans, no file
    try:
        sim = apply_runahead(Simulation(scen, engine_cfg=cfg),
                             runahead_ms)
        report = sim.run()
    finally:
        tr = TR.finish()
    s = report.summary()
    att = PF.attribute(tr.events, report.wall_seconds, report.events)
    cost = report.cost_model()
    out = {
        "config": config, "hosts": n, "stop_s": stop,
        "runahead_ms": runahead_ms,
        "platform": jax.default_backend(),
        "events": s["events"],
        "events_per_sec": round(s["events_per_sec"], 1),
        "realtime_x": round(s["speedup"], 4),
        "roofline_frac": round(cost.get("roofline_frac", 0.0), 5),
        # modeled-vs-measured HBM traffic side by side (obs.memscope:
        # XLA bytes-accessed x chunk calls when the backend provides
        # it; `measured` says which figure roofline_frac used)
        "roofline_frac_modeled": round(
            cost.get("roofline_frac_modeled", 0.0), 5),
        "roofline_measured": bool(cost.get("measured")),
        "passes_per_window": round(
            cost.get("passes_per_window", 0.0), 2),
        # the memory section (obs.memscope): watermark + census + the
        # window program's captured XLA analysis — the report's
        # memory table (docs/observability.md "Memory observatory")
        "memory": {
            "peak_bytes": report.memory.get("peak_bytes"),
            "source": report.memory.get("source"),
            "per_device": report.memory.get("per_device"),
            "state_bytes": report.memory.get("state_bytes"),
            "state_bytes_per_host":
                report.memory.get("state_bytes_per_host"),
            "hot_state_bytes": report.memory.get("hot_state_bytes"),
            "cold_state_bytes": report.memory.get("cold_state_bytes"),
            "sections": report.memory.get("sections"),
            "xla": report.memory.get("xla"),
        },
        "attribution": att,
    }
    if device_phases:
        # steady-state device split of the `window` phase (per-rung
        # passes, exchange, reductions) — phase_profile's probes; only
        # baseline_configs names have probe harnesses
        if config == "phold":
            out["device_phases"] = (
                "unavailable for `phold` — use a baseline_configs "
                "name (socks10k/tor50k/bulk1k)")
        else:
            from tools.phase_profile import profile
            out["device_phases"] = profile(
                config, n=n, stop=stop, runahead_ms=runahead_ms)
    return out, report, cfg, att


# --- self-check: the attribution math, no jax -----------------------------

def self_check() -> int:
    """Synthetic-trace check of the attribution contract: nested-span
    self-time, phase mapping, the >=90% floor, residual labeling.
    Wired into the verify skill next to the collect-only gate."""
    PF = perf_mod()

    def ev(name, ts_ms, dur_ms):
        return {"name": name, "ph": "X", "pid": 1, "tid": 0,
                "ts": ts_ms * 1000.0, "dur": dur_ms * 1000.0}

    # 1.0 s wall: setup 100ms, chunk#1 500ms containing a 100ms
    # tracker heartbeat (self 400ms), chunk#2 300ms, finalize 50ms
    # -> attributed 950ms (95%), residual 50ms
    events = [
        ev("run.setup", 0, 100),
        ev("chunk", 100, 500),
        ev("tracker.heartbeat", 300, 100),
        ev("chunk", 600, 300),
        ev("report.finalize", 900, 50),
    ]
    att = PF.attribute(events, 1.0, n_events=1000)
    assert att["ok"], f"95% attributed must pass the floor: {att}"
    assert abs(att["attributed_s"] - 0.95) < 1e-9, att["attributed_s"]
    ph = att["phases"]
    assert abs(ph["window"]["wall_s"] - 0.7) < 1e-9, ph
    assert abs(ph["tracker"]["wall_s"] - 0.1) < 1e-9, ph
    assert abs(ph["setup"]["wall_s"] - 0.1) < 1e-9, ph
    assert ph["window"]["count"] == 2
    assert abs(ph["window"]["us_per_event"] - 700.0) < 1e-6
    assert abs(att["residual_s"] - 0.05) < 1e-9
    assert att["residual_label"], "residual must carry its label"
    # under-attributed trace must flag itself, never silently pass
    att2 = PF.attribute(events[:1], 1.0)
    assert not att2["ok"] and att2["residual_frac"] > 0.85, att2
    # unknown span names stay visible under their own name
    att3 = PF.attribute([ev("mystery.phase", 0, 900)], 1.0)
    assert "mystery.phase" in att3["phases"], att3
    # ledger round-trip sanity rides along (same headless contract)
    LG = ledger_mod()
    e = LG.make_entry("selfcheck", LG.fingerprint_of(None, k=1), "cpu",
                      {"events": 10, "wall_seconds": 1.0,
                       "events_per_sec": 10.0})
    assert LG.entry_rate(e) == 10.0 and LG.key_of(e)[0] == "selfcheck"
    assert (LG.fingerprint_of(None, a=1, b=2) ==
            LG.fingerprint_of(None, b=2, a=1))
    assert (LG.fingerprint_of(None, a=1) != LG.fingerprint_of(None, a=2))
    print("perf_report: self-check OK (attribution + ledger)")
    return 0


def main(argv):
    ap = argparse.ArgumentParser()
    ap.add_argument("config", nargs="?",
                    help="phold | socks10k | tor50k | bulk1k")
    ap.add_argument("--n", type=int, default=None)
    ap.add_argument("--stop", type=int, default=10)
    ap.add_argument("--seed", type=int, default=None)
    ap.add_argument("--runahead-ms", type=int, default=0)
    ap.add_argument("--chunk", type=int, default=0)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--device-phases", action="store_true",
                    help="also run phase_profile's steady-state "
                         "probes to split the window phase on-device")
    ap.add_argument("--ledger", nargs="?", const="", default=None,
                    metavar="PATH",
                    help="append a perf-ledger entry (default "
                         "perf/ledger.jsonl)")
    ap.add_argument("--trace", default=None, metavar="FILE",
                    help="offline: attribute an existing Chrome "
                         "trace instead of running")
    ap.add_argument("--wall", type=float, default=None,
                    help="with --trace: the run's measured wall "
                         "(default: the trace's span extent)")
    ap.add_argument("--events", type=int, default=None)
    ap.add_argument("--self-check", action="store_true",
                    help="verify the attribution math on a synthetic "
                         "trace (no jax; the verify-skill smoke)")
    args = ap.parse_args(argv)

    if args.self_check:
        return self_check()
    if args.trace:
        att = report_trace(args.trace, args.wall, args.events)
        print(json.dumps(att, indent=1))
        return 0 if att["ok"] else 3
    if not args.config:
        ap.error("provide a config, --trace FILE, or --self-check")

    if args.cpu:
        os.environ["PALLAS_AXON_POOL_IPS"] = ""
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
    else:
        from bench import _enable_compile_cache
        _enable_compile_cache()

    out, report, cfg, att = report_live(
        args.config, n=args.n, stop=args.stop,
        runahead_ms=args.runahead_ms, chunk=args.chunk,
        device_phases=args.device_phases, seed=args.seed)
    if args.ledger is not None:
        from shadow_tpu.obs import ledger as LG
        entry = LG.entry_from_report(
            args.config,
            LG.fingerprint_of(cfg, stop=args.stop,
                              runahead=args.runahead_ms,
                              seed=args.seed),
            out["platform"], report, att, cfg=cfg)
        out["ledger"] = LG.append(entry, args.ledger or None)
    print(json.dumps(out, indent=1))
    return 0 if att["ok"] else 3


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
