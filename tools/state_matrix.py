"""state_matrix: the measured pass x field state-access matrix.

Front end for ``shadow_tpu.lint.stateflow`` (docs/static-analysis.md):
prints which ``Hosts``/``HostParams``/``Shared`` columns each jitted
pass reads and writes — the ground truth the ROADMAP item-1 hot/cold
socket-table split is designed from, and the artifact CI uploads so
the split stays reviewable after the fact.

Usage (from the repo root; never imports jax — safe anywhere)::

    python -m tools.state_matrix               # aligned text table
    python -m tools.state_matrix --markdown    # docs-ready table
    python -m tools.state_matrix --json        # machine-readable
    python -m tools.state_matrix --json -o state_matrix.json
    python -m tools.state_matrix --diff tools/state_matrix_snapshot.json

Cells: ``RW`` read+written, ``R`` read, ``W`` written, ``s``
shape/dtype metadata only, blank untouched. A ``*`` after the field
name marks a COLD_FIELDS column (engine/state.py) — the STF303
contract that it stays out of the ``drain`` column. The matrix is the
union over engine configurations (static ``cfg.*`` branches are all
traversed); the per-config drain working-set sizes (the COLD_WHEN
level-2 gates) are summarized under the tables. ``W`` cells on
HostParams/Shared are local VIEW rebinds (the
``hp.replace(app_kind=...)`` per-process view in the app dispatcher),
never persisted state — only Hosts columns carry state across passes.

``--diff`` compares the fresh matrix against a committed ``--json``
snapshot (CI runs it against ``tools/state_matrix_snapshot.json``):
GROWTH of the drain working set, or a changed HOT/COLD declaration,
exits 1 with the column named; shrinkage just suggests refreshing the
snapshot so the gain is pinned.

Exit codes: 0 matrix produced (or --diff clean), 1 --diff found
unreviewed drift, 2 analysis-integrity failure (the violations are
printed; ``python -m tools.simlint`` gates them).
"""

from __future__ import annotations

import argparse
import importlib
import importlib.util
import json
import os
import sys


def _memscope():
    """obs.memscope's pure-stdlib byte tables, loaded by FILE PATH —
    this tool must stay jax-free (module docstring), and importing
    shadow_tpu.obs would trigger the package's jax import. Only the
    stdlib census helpers (table_row_bytes / dims_of) are touched."""
    here = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(os.path.dirname(here), "shadow_tpu", "obs",
                        "memscope.py")
    spec = importlib.util.spec_from_file_location("_memscope", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def field_bytes_per_host() -> dict:
    """{kind: {field: bytes/host}} at the EngineConfig DEFAULTS — the
    bytes column of the tables (obs.memscope's stdlib dims table,
    pinned exact against the real alloc_hosts shapes by
    tests/test_memscope.py). Config-dependent sizes (a run's actual
    qcap/obcap) come from the live census, not this tool."""
    ms = _memscope()
    return {"hosts": ms.table_row_bytes(None, ms.HOSTS_DIMS),
            "hp": ms.table_row_bytes(None, ms.HP_DIMS)}


def build(root: str):
    """-> (matrix, model, violations) via the standalone lint loader
    (no shadow_tpu.__init__, no jax)."""
    from tools.simlint import load
    load()
    stateflow = importlib.import_module("shadow_tpu.lint.stateflow")
    core = importlib.import_module("shadow_tpu.lint.core")
    cache = core.SourceCache(root)
    model = stateflow.load_state_model(cache)
    matrix, violations = stateflow.analyze(cache)
    return matrix, model, violations


def _cell(entry_acc, kind, field):
    r = field in entry_acc[kind]["reads"]
    w = field in entry_acc[kind]["writes"]
    if r and w:
        return "RW"
    if r:
        return "R"
    if w:
        return "W"
    if field in entry_acc[kind]["meta"]:
        return "s"
    return ""


def _rows(matrix, model, kind, bytes_map=None):
    entries = list(matrix)
    byt = (bytes_map or {}).get(kind)
    rows = []
    for field in model.fields[kind]:
        label = field + ("*" if kind == "hosts"
                         and field in model.cold else "")
        rows.append([label, model.dtype_of(kind, field)]
                    + ([model.section_of(field) or "other"]
                       if kind == "hosts" else [])
                    + ([byt.get(field, "?")] if byt is not None
                       else [])
                    + [_cell(matrix[e], kind, field) for e in entries])
    return entries, rows


def _header(matrix, kind, bytes_map=None):
    return (["field", "dtype"]
            + (["section"] if kind == "hosts" else [])
            + (["B/host"] if (bytes_map or {}).get(kind) is not None
               else [])
            + list(matrix))


_KIND_TITLES = (("hosts", "Hosts (mutable per-host state)"),
                ("hp", "HostParams (read-only config)"),
                ("sh", "Shared (replicated tables/scalars)"))


def render_text(matrix, model) -> str:
    bm = field_bytes_per_host()
    out = []
    for kind, title in _KIND_TITLES:
        entries, rows = _rows(matrix, model, kind, bm)
        header = _header(matrix, kind, bm)
        widths = [max(len(str(r[i])) for r in [header] + rows)
                  for i in range(len(header))]
        out.append(f"## {title}")
        out.append("  ".join(h.ljust(w)
                             for h, w in zip(header, widths)))
        for r in rows:
            out.append("  ".join(str(c).ljust(w)
                                 for c, w in zip(r, widths)))
        out.append("")
    bulk = sorted({b for e in matrix.values() for b in e["bulk"]})
    if bulk:
        out.append("whole-tree ops (every column of the named tree; "
                   "hosts-kind ops are what the hot/cold split "
                   "narrows):")
        for tag, file, line in bulk:
            out.append(f"  {file}:{line}: {tag}")
    out.append("")
    out.append(hot_summary_text(matrix, model))
    return "\n".join(out)


def hot_counts(model) -> list:
    """[(label, ncols)] drain working-set sizes: the static hot set
    and the config-gated levels (cumulative per COLD_WHEN guard, in
    declaration order — pure arithmetic on the parsed literals, no
    engine import). The UNION row is every guard active at once: the
    modeled UDP tier's per-pass working set."""
    hot = set(model.hot_set())
    rows = [("static (union over configs)", len(hot))]
    off = set()
    for guard, fields in model.cold_when:
        off |= set(f for f in fields if f in hot)
        rows.append((f"- {guard}", len(hot) - len(set(fields) & hot)))
    rows.append(("all guards (modeled UDP tier)", len(hot - off)))
    return rows


def hot_summary_text(matrix, model) -> str:
    drain = matrix.get("drain", {}).get("hosts", {})
    touched = set(drain.get("reads", {})) | set(drain.get("writes", {}))
    lines = [f"drain hot working set ({len(touched)} columns touched "
             "in the drain subgraph; per-config sizes from the "
             "declared COLD_WHEN gates):"]
    for label, n in hot_counts(model):
        lines.append(f"  {label}: {n}")
    return "\n".join(lines)


def render_markdown(matrix, model) -> str:
    bm = field_bytes_per_host()
    out = []
    for kind, title in _KIND_TITLES:
        entries, rows = _rows(matrix, model, kind, bm)
        header = _header(matrix, kind, bm)
        out.append(f"### {title}\n")
        out.append("| " + " | ".join(header) + " |")
        out.append("|" + "---|" * len(header))
        for r in rows:
            out.append("| " + " | ".join(
                f"`{r[0]}`" if i == 0 else str(c)
                for i, c in enumerate(r)) + " |")
        out.append("")
    return "\n".join(out)


def render_json(matrix, model, root) -> str:
    bm = field_bytes_per_host()
    fields = {}
    for kind, _ in _KIND_TITLES:
        byt = bm.get(kind)
        fields[kind] = {
            name: {"dtype": model.dtype_of(kind, name),
                   **({"bytes_per_host": byt.get(name)}
                      if byt is not None else {}),
                   **({"section": model.section_of(name) or "other",
                       "cold": name in model.cold,
                       "line": model.linenos.get(name, 0)}
                      if kind == "hosts" else {})}
            for name in model.fields[kind]}
    drain = matrix.get("drain", {}).get("hosts", {})
    # per-host byte rollups at the EngineConfig defaults (the memscope
    # census — docs/observability.md "Memory observatory"): total, the
    # declared-hot subset, and what the drain subgraph measured
    hot = set(model.hot_set())
    drain_cols = sorted(set(drain.get("reads", {}))
                        | set(drain.get("writes", {})))
    bytes_per_host = {
        "config": "EngineConfig defaults",
        "hosts": sum(bm["hosts"].values()),
        "hosts_hot": sum(b for f, b in bm["hosts"].items() if f in hot),
        "hosts_drain": sum(b for f, b in bm["hosts"].items()
                           if f in drain_cols),
        "hp": sum(bm["hp"].values()),
    }
    return json.dumps({
        "version": 3,
        "root": root,
        "entries": matrix,
        "fields": fields,
        "bytes_per_host": bytes_per_host,
        "cold_fields": sorted(model.cold),
        "hot_fields": list(model.hot_set()),
        "cold_when": [[g, list(f)] for g, f in model.cold_when],
        "hot_counts": [list(r) for r in hot_counts(model)],
        "drain_hot_columns": drain_cols,
        "sections": [list(s) for s in model.sections],
    }, indent=1, sort_keys=False) + "\n"


def render_top(matrix, model, n: int) -> str:
    """The shrink campaign's targeting report (``--top N``): the N
    fattest Hosts columns by bytes/host at the EngineConfig defaults,
    with their hot/cold/drain membership and at-rest layout — the
    fattest column not yet narrowed or capacity-scaled is the next
    lever. Bytes honor the NARROW_DTYPES overlay (the default layout);
    the `wide` column shows what the --wide-state escape hatch would
    pay, so the per-field saving is the difference."""
    ms = _memscope()
    narrow_bm = ms.table_row_bytes(None, ms.HOSTS_DIMS)

    class _Wide:
        wide_state = 1
    wide_bm = ms.table_row_bytes(_Wide(), ms.HOSTS_DIMS)
    drain = matrix.get("drain", {}).get("hosts", {})
    drain_cols = set(drain.get("reads", {})) | set(drain.get("writes",
                                                             {}))
    hot = set(model.hot_set())
    rows = sorted(narrow_bm.items(), key=lambda kv: (-kv[1], kv[0]))
    header = ["field", "B/host", "wide", "dtype", "layout", "split",
              "drain", "section"]
    table = []
    for field, b in rows[:max(n, 0)]:
        nd = ms.NARROW_DTYPES.get(field)
        table.append([
            field, b, wide_bm[field],
            model.dtype_of("hosts", field),
            (f"narrow:{nd}" if nd else "wide"),
            ("cold" if field in model.cold
             else "hot" if field in hot else "?"),
            ("yes" if field in drain_cols else ""),
            model.section_of(field) or "other",
        ])
    widths = [max(len(str(r[i])) for r in [header] + table)
              for i in range(len(header))]
    out = [f"## top {len(table)} Hosts columns by bytes/host "
           "(EngineConfig defaults; narrow at-rest layout)"]
    out.append("  ".join(h.ljust(w) for h, w in zip(header, widths)))
    for r in table:
        out.append("  ".join(str(c).ljust(w)
                             for c, w in zip(r, widths)))
    shown = sum(r[1] for r in table)
    total = sum(narrow_bm.values())
    out.append("")
    out.append(f"shown {shown} of {total} B/host "
               f"({100.0 * shown / max(total, 1):.1f}%); wide layout "
               f"total {sum(wide_bm.values())} B/host")
    return "\n".join(out)


def diff_snapshot(matrix, model, snap_path: str) -> list:
    """Compare the freshly-built matrix against a committed snapshot
    (render_json output). Returns a list of human-readable failures —
    empty when the drain's working set did not GROW and the declared
    hot/cold partition is unchanged-or-reviewed. Shrinkage is
    reported to stdout but never fails: the snapshot should simply be
    refreshed in the same change (the growth direction is what needs
    a reviewer — a column silently re-entering the per-pass working
    set is exactly the regression the split exists to prevent)."""
    with open(snap_path) as f:
        snap = json.load(f)
    failures = []
    drain = matrix.get("drain", {}).get("hosts", {})
    now = set(drain.get("reads", {})) | set(drain.get("writes", {}))
    base = set(snap.get("drain_hot_columns", []))
    grew = sorted(now - base)
    for col in grew:
        site = (drain.get("reads", {}).get(col)
                or drain.get("writes", {}).get(col))
        failures.append(
            f"drain working set GREW: column `{col}` entered the "
            f"drain subgraph at {site[0]}:{site[1]} but is not in "
            f"the committed snapshot ({snap_path}) — either make it "
            "cold again or refresh the snapshot with the reviewed "
            "growth")
    shrank = sorted(base - now)
    if shrank:
        print(f"state_matrix: drain working set shrank by "
              f"{len(shrank)} columns vs snapshot ({', '.join(shrank)})"
              " — refresh the snapshot to pin the gain")
    for key in ("cold_fields", "hot_fields"):
        if snap.get(key) is not None and \
                list(snap[key]) != list({"cold_fields":
                                         sorted(model.cold),
                                         "hot_fields":
                                         list(model.hot_set())}[key]):
            failures.append(
                f"declared {key.upper().replace('_', '')} changed vs "
                f"snapshot {snap_path} — refresh it in the same "
                "change so the diff is reviewed")
    return failures


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="state_matrix",
        description="pass x field state-access matrix "
                    "(shadow_tpu.lint.stateflow)")
    p.add_argument("--root", default=None,
                   help="repo root (default: auto-detect upward)")
    p.add_argument("--json", action="store_true")
    p.add_argument("--markdown", action="store_true")
    p.add_argument("--diff", metavar="SNAPSHOT", default=None,
                   help="compare against a committed --json snapshot; "
                        "exit 1 when the drain working set grew or "
                        "the declared partition changed (CI gate)")
    p.add_argument("--top", type=int, metavar="N", default=None,
                   help="show only the N fattest Hosts columns by "
                        "bytes/host with their hot/cold/drain "
                        "membership (the shrink campaign's targeting "
                        "report)")
    p.add_argument("-o", "--out", default=None,
                   help="write to a file instead of stdout")
    args = p.parse_args(argv)

    from tools.simlint import load
    load()
    root = args.root or sys.modules["shadow_tpu.lint.cli"].find_root()
    matrix, model, violations = build(root)
    if not matrix:
        for v in violations:
            print(v.render(), file=sys.stderr)
        print("state_matrix: analysis failed (see violations above)",
              file=sys.stderr)
        return 2

    if args.diff:
        failures = diff_snapshot(matrix, model, args.diff)
        for msg in failures:
            print(f"state_matrix: {msg}", file=sys.stderr)
        if failures:
            return 1
        print(f"state_matrix: drain working set within snapshot "
              f"{args.diff}")
        return 0

    if args.top is not None:
        text = render_top(matrix, model, args.top)
    elif args.json:
        text = render_json(matrix, model, root)
    elif args.markdown:
        text = render_markdown(matrix, model)
    else:
        text = render_text(matrix, model)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text if text.endswith("\n") else text + "\n")
        print(f"state_matrix: wrote {args.out}")
    else:
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
