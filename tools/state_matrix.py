"""state_matrix: the measured pass x field state-access matrix.

Front end for ``shadow_tpu.lint.stateflow`` (docs/static-analysis.md):
prints which ``Hosts``/``HostParams``/``Shared`` columns each jitted
pass reads and writes — the ground truth the ROADMAP item-1 hot/cold
socket-table split is designed from, and the artifact CI uploads so
the split stays reviewable after the fact.

Usage (from the repo root; never imports jax — safe anywhere)::

    python -m tools.state_matrix               # aligned text table
    python -m tools.state_matrix --markdown    # docs-ready table
    python -m tools.state_matrix --json        # machine-readable
    python -m tools.state_matrix --json -o state_matrix.json

Cells: ``RW`` read+written, ``R`` read, ``W`` written, ``s``
shape/dtype metadata only, blank untouched. A ``*`` after the field
name marks a COLD_FIELDS column (engine/state.py) — the STF303
contract that it stays out of the ``drain`` column. The matrix is the
union over engine configurations (static ``cfg.*`` branches are all
traversed). ``W`` cells on HostParams/Shared are local VIEW rebinds
(the ``hp.replace(app_kind=...)`` per-process view in the app
dispatcher), never persisted state — only Hosts columns carry state
across passes.

Exit codes: 0 matrix produced, 2 analysis-integrity failure (the
violations are printed; ``python -m tools.simlint`` gates them).
"""

from __future__ import annotations

import argparse
import importlib
import json
import sys


def build(root: str):
    """-> (matrix, model, violations) via the standalone lint loader
    (no shadow_tpu.__init__, no jax)."""
    from tools.simlint import load
    load()
    stateflow = importlib.import_module("shadow_tpu.lint.stateflow")
    core = importlib.import_module("shadow_tpu.lint.core")
    cache = core.SourceCache(root)
    model = stateflow.load_state_model(cache)
    matrix, violations = stateflow.analyze(cache)
    return matrix, model, violations


def _cell(entry_acc, kind, field):
    r = field in entry_acc[kind]["reads"]
    w = field in entry_acc[kind]["writes"]
    if r and w:
        return "RW"
    if r:
        return "R"
    if w:
        return "W"
    if field in entry_acc[kind]["meta"]:
        return "s"
    return ""


def _rows(matrix, model, kind):
    entries = list(matrix)
    rows = []
    for field in model.fields[kind]:
        label = field + ("*" if kind == "hosts"
                         and field in model.cold else "")
        rows.append([label, model.dtype_of(kind, field)]
                    + ([model.section_of(field) or "other"]
                       if kind == "hosts" else [])
                    + [_cell(matrix[e], kind, field) for e in entries])
    return entries, rows


_KIND_TITLES = (("hosts", "Hosts (mutable per-host state)"),
                ("hp", "HostParams (read-only config)"),
                ("sh", "Shared (replicated tables/scalars)"))


def render_text(matrix, model) -> str:
    out = []
    for kind, title in _KIND_TITLES:
        entries, rows = _rows(matrix, model, kind)
        header = (["field", "dtype"]
                  + (["section"] if kind == "hosts" else [])
                  + entries)
        widths = [max(len(str(r[i])) for r in [header] + rows)
                  for i in range(len(header))]
        out.append(f"## {title}")
        out.append("  ".join(h.ljust(w)
                             for h, w in zip(header, widths)))
        for r in rows:
            out.append("  ".join(str(c).ljust(w)
                                 for c, w in zip(r, widths)))
        out.append("")
    bulk = sorted({b for e in matrix.values() for b in e["bulk"]})
    if bulk:
        out.append("whole-tree ops (every column; what the hot/cold "
                   "split narrows):")
        for tag, file, line in bulk:
            out.append(f"  {file}:{line}: {tag}")
    return "\n".join(out)


def render_markdown(matrix, model) -> str:
    out = []
    for kind, title in _KIND_TITLES:
        entries, rows = _rows(matrix, model, kind)
        header = (["field", "dtype"]
                  + (["section"] if kind == "hosts" else [])
                  + entries)
        out.append(f"### {title}\n")
        out.append("| " + " | ".join(header) + " |")
        out.append("|" + "---|" * len(header))
        for r in rows:
            out.append("| " + " | ".join(
                f"`{r[0]}`" if i == 0 else str(c)
                for i, c in enumerate(r)) + " |")
        out.append("")
    return "\n".join(out)


def render_json(matrix, model, root) -> str:
    fields = {}
    for kind, _ in _KIND_TITLES:
        fields[kind] = {
            name: {"dtype": model.dtype_of(kind, name),
                   **({"section": model.section_of(name) or "other",
                       "cold": name in model.cold,
                       "line": model.linenos.get(name, 0)}
                      if kind == "hosts" else {})}
            for name in model.fields[kind]}
    return json.dumps({
        "version": 1,
        "root": root,
        "entries": matrix,
        "fields": fields,
        "cold_fields": sorted(model.cold),
        "sections": [list(s) for s in model.sections],
    }, indent=1, sort_keys=False) + "\n"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="state_matrix",
        description="pass x field state-access matrix "
                    "(shadow_tpu.lint.stateflow)")
    p.add_argument("--root", default=None,
                   help="repo root (default: auto-detect upward)")
    p.add_argument("--json", action="store_true")
    p.add_argument("--markdown", action="store_true")
    p.add_argument("-o", "--out", default=None,
                   help="write to a file instead of stdout")
    args = p.parse_args(argv)

    from tools.simlint import load
    load()
    root = args.root or sys.modules["shadow_tpu.lint.cli"].find_root()
    matrix, model, violations = build(root)
    if not matrix:
        for v in violations:
            print(v.render(), file=sys.stderr)
        print("state_matrix: analysis failed (see violations above)",
              file=sys.stderr)
        return 2

    if args.json:
        text = render_json(matrix, model, root)
    elif args.markdown:
        text = render_markdown(matrix, model)
    else:
        text = render_text(matrix, model)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text if text.endswith("\n") else text + "\n")
        print(f"state_matrix: wrote {args.out}")
    else:
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
