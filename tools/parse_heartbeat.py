#!/usr/bin/env python3
"""Parse shadow_tpu heartbeat logs into CSV (the analogue of the
reference's src/tools/parse-shadow.py over [shadow-heartbeat] lines).

Usage:
  python tools/parse_heartbeat.py sim.log --out nodes.csv
  python tools/parse_heartbeat.py sim.log --summary

Node lines have the schema obs.tracker.HEADER:
  time,host,events,pkts-sent,pkts-recv,bytes-sent,bytes-recv,
  retransmits,drop-net,drop-buf,transfers-done
"""

import argparse
import csv
import re
import sys

NODE_RE = re.compile(r"\[shadow-heartbeat\] \[node\] (.+)$")
SUMMARY_RE = re.compile(r"\[shadow-heartbeat\] \[summary\] (.+)$")

FIELDS = ["time", "host", "events", "pkts_sent", "pkts_recv",
          "bytes_sent", "bytes_recv", "retransmits", "drop_net",
          "drop_buf", "transfers_done"]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("log")
    ap.add_argument("--out", default="-")
    ap.add_argument("--summary", action="store_true",
                    help="print summary lines instead of node CSV")
    args = ap.parse_args()

    out = sys.stdout if args.out == "-" else open(args.out, "w", newline="")
    with open(args.log) as f:
        if args.summary:
            for line in f:
                m = SUMMARY_RE.search(line)
                if m:
                    out.write(m.group(1) + "\n")
        else:
            w = csv.writer(out)
            w.writerow(FIELDS)
            for line in f:
                m = NODE_RE.search(line)
                if m:
                    w.writerow(m.group(1).split(","))
    if out is not sys.stdout:
        out.close()


if __name__ == "__main__":
    main()
