#!/usr/bin/env python3
"""Parse shadow_tpu heartbeat logs into CSV (the analogue of the
reference's src/tools/parse-shadow.py over [shadow-heartbeat] lines).

Usage:
  python tools/parse_heartbeat.py sim.log --out nodes.csv
  python tools/parse_heartbeat.py sim.log --ram --out ram.csv
  python tools/parse_heartbeat.py sim.log --summary
  python tools/parse_heartbeat.py --netscope run.netscope.jsonl

Node lines have the schema obs.tracker.HEADER:
  time,host,interval,events,pkts-sent,pkts-recv,bytes-sent,
  bytes-recv,retransmits,drop-net,drop-buf,transfers-done

[ram] lines are ``time,host,alloc,dealloc,total,sockets`` plus the
optional trailing ``rss=`` (hosted child resident set) and ``dev=``
(device-buffer watermark, obs.memscope) columns — parsed into fixed
``rss``/``dev`` CSV columns, empty when a line doesn't carry them.

``--occupancy`` extracts the per-heartbeat occupancy trend from the
[summary] family: ``time,interval,events,waste`` where ``waste`` is
the optional ``waste=`` column (the cumulative lockstep wasted-lane
fraction, obs.passcope) — empty on runs predating the observatory.

``--netscope`` converts a network observatory time-series stream
(obs.netscope JSONL — ``--netscope FILE`` on a run) into CSV: one row
per chunk record with the interval stat deltas and each kind's
cumulative sample count and exact p50/p99 read-out.
"""

import argparse
import csv
import importlib.util
import os
import re
import sys

NODE_RE = re.compile(r"\[shadow-heartbeat\] \[node\] (.+)$")
RAM_RE = re.compile(r"\[shadow-heartbeat\] \[ram\] (.+)$")
SUMMARY_RE = re.compile(r"\[shadow-heartbeat\] \[summary\] (.+)$")

FIELDS = ["time", "host", "interval", "events", "pkts_sent",
          "pkts_recv", "bytes_sent", "bytes_recv", "retransmits",
          "drop_net", "drop_buf", "transfers_done"]

RAM_FIELDS = ["time", "host", "alloc", "dealloc", "total", "sockets",
              "rss", "dev"]

OCC_FIELDS = ["time", "interval", "events", "waste"]


def node_rows(lines):
    """[node] heartbeat lines -> rows aligned with FIELDS."""
    rows = []
    for line in lines:
        m = NODE_RE.search(line)
        if m:
            rows.append(m.group(1).split(","))
    return rows


def ram_rows(lines):
    """[ram] heartbeat lines -> rows aligned with RAM_FIELDS. The
    trailing ``rss=``/``dev=`` columns are optional per line (only
    hosted hosts carry rss, only memscope runs carry dev) — absent
    values become empty cells so the CSV shape is fixed."""
    rows = []
    for line in lines:
        m = RAM_RE.search(line)
        if not m:
            continue
        cols = m.group(1).split(",")
        fixed, extra = cols[:6], {"rss": "", "dev": ""}
        for c in cols[6:]:
            k, eq, v = c.partition("=")
            if eq and k in extra:
                extra[k] = v
        rows.append(fixed + [extra["rss"], extra["dev"]])
    return rows


def occupancy_rows(lines):
    """[summary] heartbeat lines -> rows aligned with OCC_FIELDS. The
    ``waste=`` column is optional per line (only runs with the
    pass-time observatory's occupancy accounting carry it, like
    ``dev-peak-gib=``) — absent values become empty cells."""
    rows = []
    for line in lines:
        m = SUMMARY_RE.search(line)
        if not m:
            continue
        cols = m.group(1).split(",")
        kv = {}
        for c in cols[1:]:
            k, eq, v = c.partition("=")
            if eq:
                kv[k] = v
        rows.append([cols[0], kv.get("interval", ""),
                     kv.get("events", ""), kv.get("waste", "")])
    return rows


def _netscope_mod():
    # by file path: obs/netscope.py is stdlib-only at module level,
    # and shadow_tpu/__init__ would import jax (the headless-tool
    # convention of tools/perf_report.py)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "_netscope", os.path.join(repo, "shadow_tpu/obs/netscope.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def netscope_fields(kinds):
    return (["window", "time"]
            + [f"d_{k}" for k in ("events", "pkts_sent", "pkts_recv",
                                  "bytes_sent", "bytes_recv",
                                  "retransmits", "xfers_done")]
            + [f"{k}_{c}" for k in kinds
               for c in ("n", "p50_us", "p99_us")])


def netscope_rows(path):
    """A netscope JSONL stream -> (fields, rows): one row per chunk
    record — interval stat deltas plus each kind's cumulative sample
    count and exact percentile read-outs."""
    NS = _netscope_mod()
    header, records = NS.read_stream(path)
    kinds = list(header.get("kinds", NS.KIND_NAMES))
    rows = []
    for r in records:
        d = r.get("delta", {})
        row = [r.get("window", ""), r.get("sim_ns", 0) / 1e9]
        row += [d.get(k, "") for k in ("events", "pkts_sent",
                                       "pkts_recv", "bytes_sent",
                                       "bytes_recv", "retransmits",
                                       "xfers_done")]
        for k, counts in zip(kinds, r.get("hist", [])):
            row += [sum(counts), NS.percentile(counts, 50),
                    NS.percentile(counts, 99)]
        rows.append(row)
    return netscope_fields(kinds), rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("log", nargs="?")
    ap.add_argument("--out", default="-")
    ap.add_argument("--summary", action="store_true",
                    help="print summary lines instead of node CSV")
    ap.add_argument("--ram", action="store_true",
                    help="emit the [ram] family (alloc/dealloc/total/"
                         "sockets + optional rss=/dev= columns)")
    ap.add_argument("--occupancy", action="store_true",
                    help="emit the per-heartbeat occupancy trend "
                         "(time,interval,events,waste from the "
                         "[summary] family's waste= column)")
    ap.add_argument("--netscope", default=None, metavar="JSONL",
                    help="convert a netscope time-series stream to "
                         "CSV instead of parsing a heartbeat log")
    args = ap.parse_args()
    if not args.log and not args.netscope:
        ap.error("provide a heartbeat log or --netscope JSONL")

    out = (sys.stdout if args.out == "-"
           else open(args.out, "w", newline=""))
    if args.netscope:
        fields, rows = netscope_rows(args.netscope)
        w = csv.writer(out)
        w.writerow(fields)
        w.writerows(rows)
    else:
        with open(args.log) as f:
            if args.summary:
                for line in f:
                    m = SUMMARY_RE.search(line)
                    if m:
                        out.write(m.group(1) + "\n")
            elif args.ram:
                w = csv.writer(out)
                w.writerow(RAM_FIELDS)
                w.writerows(ram_rows(f))
            elif args.occupancy:
                w = csv.writer(out)
                w.writerow(OCC_FIELDS)
                w.writerows(occupancy_rows(f))
            else:
                w = csv.writer(out)
                w.writerow(FIELDS)
                w.writerows(node_rows(f))
    if out is not sys.stdout:
        out.close()


if __name__ == "__main__":
    main()
