#!/usr/bin/env python3
"""Topology toolkit: inspect and transform GraphML network topologies.

The TPU-native counterpart of the reference's src/tools/topology
pipeline (readme: full map --prune--> pruned --compute-paths-->
complete --collapse--> clustered), rebuilt on this framework's own
routing oracle (shadow_tpu.routing) instead of networkx/igraph:

  info               vertex/edge/attribute/connectivity summary
  prune              keep a vertex subset (by type / id file), then the
                     largest connected component of what remains
                     (prune-topology-relays.py role)
  compute-paths      emit the COMPLETE graph whose edge (u,v) carries
                     the shortest-path latency and end-to-end
                     reliability-derived packetloss between u and v
                     (compute-topology-paths.py role) — a complete
                     graph needs no Dijkstra at simulation time
  collapse           cluster vertices by geocode/type/asn into one
                     point-of-interest per cluster; inter-cluster edges
                     carry the median of member-pair path latencies
                     (collapse-topology.py role)
  extract-latencies  pairwise shortest-path latency CSV
                     (extract-pairwise-latencies.py role)
  convert            CSV edge list -> GraphML
                     (convert-topology.py role for external formats)

All subcommands read .graphml[.xml][.xz] via shadow_tpu.routing.graphml
and write plain GraphML. Latencies are milliseconds, bandwidths KiB/s,
losses are probabilities — the schema both this framework and the
reference consume.
"""

import argparse
import csv
import sys
from pathlib import Path
from xml.sax.saxutils import escape, quoteattr

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from shadow_tpu.routing.graphml import Graph, parse_graphml  # noqa: E402


# --- GraphML emission -------------------------------------------------------

def write_graphml(g: Graph, out, complete_attrs=False):
    """Serialize a Graph back to GraphML (undirected)."""
    w = out.write
    w('<?xml version="1.0" encoding="utf-8"?>\n')
    w('<graphml xmlns="http://graphml.graphdrawing.org/xmlns">\n')
    w('  <key attr.name="packetloss" attr.type="double" for="edge" id="e0" />\n')
    w('  <key attr.name="latency" attr.type="double" for="edge" id="e1" />\n')
    w('  <key attr.name="jitter" attr.type="double" for="edge" id="e2" />\n')
    w('  <key attr.name="packetloss" attr.type="double" for="node" id="n0" />\n')
    w('  <key attr.name="bandwidthup" attr.type="int" for="node" id="n1" />\n')
    w('  <key attr.name="bandwidthdown" attr.type="int" for="node" id="n2" />\n')
    w('  <key attr.name="type" attr.type="string" for="node" id="n3" />\n')
    w('  <key attr.name="geocode" attr.type="string" for="node" id="n4" />\n')
    w('  <key attr.name="ip" attr.type="string" for="node" id="n5" />\n')
    w('  <key attr.name="asn" attr.type="int" for="node" id="n6" />\n')
    w('  <graph edgedefault="undirected">\n')
    for i, vid in enumerate(g.vertex_ids):
        w(f'    <node id={quoteattr(str(vid))}>\n')
        if g.v_packetloss is not None and g.v_packetloss[i]:
            w(f'      <data key="n0">{g.v_packetloss[i]:g}</data>\n')
        if g.v_bw_up is not None and g.v_bw_up[i]:
            w(f'      <data key="n1">{int(g.v_bw_up[i])}</data>\n')
        if g.v_bw_down is not None and g.v_bw_down[i]:
            w(f'      <data key="n2">{int(g.v_bw_down[i])}</data>\n')
        if g.v_type and g.v_type[i]:
            w(f'      <data key="n3">{escape(str(g.v_type[i]))}</data>\n')
        if g.v_geocode and g.v_geocode[i]:
            w(f'      <data key="n4">{escape(str(g.v_geocode[i]))}</data>\n')
        if g.v_ip and g.v_ip[i]:
            w(f'      <data key="n5">{escape(str(g.v_ip[i]))}</data>\n')
        if g.v_asn is not None and g.v_asn[i]:
            w(f'      <data key="n6">{int(g.v_asn[i])}</data>\n')
        w('    </node>\n')
    E = g.num_edges
    for k in range(E):
        s = quoteattr(str(g.vertex_ids[g.e_src[k]]))
        t = quoteattr(str(g.vertex_ids[g.e_dst[k]]))
        w(f'    <edge source={s} target={t}>\n')
        w(f'      <data key="e1">{g.e_latency_ms[k]:g}</data>\n')
        if g.e_packetloss is not None and g.e_packetloss[k]:
            w(f'      <data key="e0">{g.e_packetloss[k]:g}</data>\n')
        if g.e_jitter_ms is not None and g.e_jitter_ms[k]:
            w(f'      <data key="e2">{g.e_jitter_ms[k]:g}</data>\n')
        w('    </edge>\n')
    w('  </graph>\n</graphml>\n')


def _open_out(path):
    return open(path, "w") if path else sys.stdout


def _subgraph(g: Graph, keep: np.ndarray) -> Graph:
    """Vertex-induced subgraph; `keep` is a bool mask over vertices."""
    idx = np.flatnonzero(keep)
    remap = -np.ones(g.num_vertices, dtype=np.int64)
    remap[idx] = np.arange(len(idx))
    emask = keep[g.e_src] & keep[g.e_dst]
    ng = Graph(vertex_ids=[g.vertex_ids[i] for i in idx],
               directed=g.directed)
    ng.v_ip = [g.v_ip[i] for i in idx]
    ng.v_geocode = [g.v_geocode[i] for i in idx]
    ng.v_type = [g.v_type[i] for i in idx]
    ng.v_asn = g.v_asn[idx]
    ng.v_bw_up = g.v_bw_up[idx]
    ng.v_bw_down = g.v_bw_down[idx]
    ng.v_packetloss = g.v_packetloss[idx]
    ng.e_src = remap[g.e_src[emask]]
    ng.e_dst = remap[g.e_dst[emask]]
    ng.e_latency_ms = g.e_latency_ms[emask]
    ng.e_jitter_ms = g.e_jitter_ms[emask]
    ng.e_packetloss = g.e_packetloss[emask]
    return ng


def _components(g: Graph):
    """Connected-component label per vertex (undirected union-find)."""
    parent = np.arange(g.num_vertices)

    def find(x):
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for s, t in zip(g.e_src, g.e_dst):
        rs, rt = find(s), find(t)
        if rs != rt:
            parent[rs] = rt
    return np.array([find(i) for i in range(g.num_vertices)])


def _apsp(g: Graph):
    """(latency_ms [V,V] with NaN for unreachable, reliability [V,V])
    via the framework oracle."""
    from shadow_tpu.routing.topology import compute_all_pairs
    lat_ms, rel, unreachable = compute_all_pairs(g)
    lat_ms = lat_ms.astype(float).copy()
    lat_ms[unreachable] = np.nan
    return lat_ms, rel


# --- subcommands ------------------------------------------------------------

def cmd_info(args):
    g = parse_graphml(args.input)
    comp = _components(g)
    ncomp = len(np.unique(comp))
    V, E = g.num_vertices, g.num_edges
    types = sorted(set(t for t in g.v_type if t))
    geos = sorted(set(c for c in g.v_geocode if c))
    complete = E >= V * (V - 1) // 2
    print(f"vertices: {V}")
    print(f"edges: {E} ({'complete' if complete else 'sparse'})")
    print(f"connected components: {ncomp}")
    print(f"directed: {g.directed}")
    if E:
        print(f"latency ms: min={g.e_latency_ms.min():g} "
              f"median={np.median(g.e_latency_ms):g} "
              f"max={g.e_latency_ms.max():g}")
        print(f"edge loss: max={g.e_packetloss.max():g}")
    print(f"vertex types: {types}")
    print(f"geocodes: {len(geos)}")


def cmd_prune(args):
    g = parse_graphml(args.input)
    keep = np.ones(g.num_vertices, dtype=bool)
    if args.keep_types:
        allowed = set(args.keep_types.split(","))
        keep &= np.array([t in allowed for t in g.v_type])
    if args.keep_ids:
        with open(args.keep_ids) as f:
            ids = {ln.strip() for ln in f if ln.strip()}
        keep &= np.array([v in ids for v in g.vertex_ids])
    g = _subgraph(g, keep)
    # largest connected component of what remains (a disconnected
    # topology fails validation at load, shd-topology.c:232-474)
    comp = _components(g)
    if g.num_vertices:
        vals, counts = np.unique(comp, return_counts=True)
        g = _subgraph(g, comp == vals[np.argmax(counts)])
    with _open_out(args.out) as f:
        write_graphml(g, f)
    print(f"pruned to {g.num_vertices} vertices / {g.num_edges} edges",
          file=sys.stderr)


def _path_jitter(g: Graph):
    """[V, V] summed jitter along each latency-shortest path (the
    reference's compute-topology-paths.py accumulates jitter the same
    way it accumulates latency)."""
    from scipy.sparse import csr_matrix
    from scipy.sparse.csgraph import dijkstra

    V = g.num_vertices
    und = not g.directed
    s = np.concatenate([g.e_src, g.e_dst]) if und else g.e_src
    d = np.concatenate([g.e_dst, g.e_src]) if und else g.e_dst
    w = np.concatenate([g.e_latency_ms] * 2) if und else g.e_latency_ms
    jv = np.concatenate([g.e_jitter_ms] * 2) if und else g.e_jitter_ms
    # parallel-edge dedup keeping the MIN-latency edge (and ITS jitter)
    # — the same edge selection the latency/loss oracle uses, so the
    # emitted jitter belongs to the path actually chosen (csr_matrix
    # would otherwise SUM duplicates into a different graph)
    best = {}
    for k in range(len(s)):
        if s[k] == d[k]:
            continue
        key = (int(s[k]), int(d[k]))
        if key not in best or w[k] < w[best[key]]:
            best[key] = k
    ks = np.array(sorted(best.values()), dtype=np.int64)
    adj = csr_matrix((w[ks], (s[ks], d[ks])), shape=(V, V))
    _, pred = dijkstra(adj, directed=True, return_predecessors=True)
    ej = np.zeros((V, V))
    ej[s[ks], d[ks]] = jv[ks]
    out = np.zeros((V, V))
    # Accumulate in predecessor-tree depth order (memoized walk):
    # exact even with equal-distance ties, one pass per source.
    # (Tie-breaking among equal-cost paths follows scipy's dijkstra,
    # which the latency/loss oracle also uses on the scipy path; the
    # native oracle can differ only on equal-cost multipaths.)
    for a in range(V):
        pr = pred[a]
        depth = np.full(V, -1, dtype=np.int64)
        depth[a] = 0
        for b in range(V):
            if depth[b] >= 0 or pr[b] < 0:
                continue
            chain = []
            x = b
            while depth[x] < 0 and pr[x] >= 0:
                chain.append(x)
                x = pr[x]
            base = depth[x] if depth[x] >= 0 else 0
            for i, y in enumerate(reversed(chain)):
                depth[y] = base + i + 1
        for b in np.argsort(depth, kind="stable"):
            p = pr[b]
            if b != a and p >= 0:
                out[a, b] = out[a, p] + ej[p, b]
    return out


def cmd_compute_paths(args):
    g = parse_graphml(args.input)
    lat_ms, rel = _apsp(g)
    jit = _path_jitter(g)
    V = g.num_vertices
    ng = Graph(vertex_ids=list(g.vertex_ids), directed=False)
    ng.v_ip, ng.v_geocode, ng.v_type = g.v_ip, g.v_geocode, g.v_type
    ng.v_asn, ng.v_bw_up, ng.v_bw_down = g.v_asn, g.v_bw_up, g.v_bw_down
    # vertex loss folds into the path loss on the complete graph
    ng.v_packetloss = np.zeros(V)
    src, dst, lat, loss, jits = [], [], [], [], []
    for i in range(V):
        for j in range(i, V):
            if not np.isfinite(lat_ms[i, j]):
                continue
            src.append(i)
            dst.append(j)
            lat.append(max(lat_ms[i, j], args.min_latency))
            loss.append(1.0 - float(rel[i, j]))
            jits.append(jit[i, j])
    ng.e_src = np.array(src, dtype=np.int64)
    ng.e_dst = np.array(dst, dtype=np.int64)
    ng.e_latency_ms = np.array(lat)
    ng.e_jitter_ms = np.array(jits)
    ng.e_packetloss = np.array(loss)
    with _open_out(args.out) as f:
        write_graphml(ng, f)
    print(f"complete graph: {V} vertices / {len(lat)} edges",
          file=sys.stderr)


def cmd_collapse(args):
    g = parse_graphml(args.input)
    key_of = {"geocode": g.v_geocode, "type": g.v_type,
              "asn": [str(a) for a in g.v_asn]}[args.by]
    lat_ms, rel = _apsp(g)
    labels = sorted(set(k or "none" for k in key_of))
    group = {lab: np.array([i for i, k in enumerate(key_of)
                            if (k or "none") == lab]) for lab in labels}
    C = len(labels)
    ng = Graph(vertex_ids=[f"poi-{i + 1}" for i in range(C)],
               directed=False)
    ng.v_ip = ["" for _ in range(C)]
    ng.v_geocode = [lab if args.by == "geocode" else "" for lab in labels]
    ng.v_type = ["cluster" for _ in range(C)]
    ng.v_asn = np.zeros(C, dtype=np.int64)
    ng.v_bw_up = np.array([np.median(g.v_bw_up[group[lab]])
                           for lab in labels])
    ng.v_bw_down = np.array([np.median(g.v_bw_down[group[lab]])
                             for lab in labels])
    ng.v_packetloss = np.array([np.median(g.v_packetloss[group[lab]])
                                for lab in labels])
    src, dst, lat, loss = [], [], [], []
    for a in range(C):
        ia = group[labels[a]]
        for b in range(a, C):
            ib = group[labels[b]]
            block_l = lat_ms[np.ix_(ia, ib)]
            block_r = rel[np.ix_(ia, ib)]
            if a == b and len(ia) == 1:
                # self-loop for intra-cluster traffic
                med_l, med_r = args.min_latency, 1.0
            else:
                finite = np.isfinite(block_l)
                if a == b:
                    finite &= ~np.eye(len(ia), dtype=bool)
                if not finite.any():
                    continue
                med_l = max(float(np.median(block_l[finite])),
                            args.min_latency)
                med_r = float(np.median(block_r[finite]))
            src.append(a)
            dst.append(b)
            lat.append(med_l)
            loss.append(max(1.0 - med_r, 0.0))
    ng.e_src = np.array(src, dtype=np.int64)
    ng.e_dst = np.array(dst, dtype=np.int64)
    ng.e_latency_ms = np.array(lat)
    ng.e_jitter_ms = np.zeros(len(lat))
    ng.e_packetloss = np.array(loss)
    with _open_out(args.out) as f:
        write_graphml(ng, f)
    print(f"collapsed {g.num_vertices} vertices into {C} clusters",
          file=sys.stderr)


def cmd_extract_latencies(args):
    g = parse_graphml(args.input)
    lat_ms, _ = _apsp(g)
    with _open_out(args.out) as f:
        wr = csv.writer(f)
        wr.writerow(["source", "target", "latency_ms"])
        for i in range(g.num_vertices):
            for j in range(g.num_vertices):
                if i != j and np.isfinite(lat_ms[i, j]):
                    wr.writerow([g.vertex_ids[i], g.vertex_ids[j],
                                 f"{lat_ms[i, j]:g}"])


def cmd_convert(args):
    """CSV edge list (source,target,latency_ms[,packetloss]) -> GraphML."""
    rows = []
    with open(args.input) as f:
        for rec in csv.reader(f):
            if not rec or rec[0].startswith("#") or rec[0] == "source":
                continue
            rows.append(rec)
    ids = []
    index = {}
    for rec in rows:
        for v in rec[:2]:
            if v not in index:
                index[v] = len(ids)
                ids.append(v)
    V = len(ids)
    g = Graph(vertex_ids=ids, directed=False)
    g.v_ip = ["" for _ in range(V)]
    g.v_geocode = ["" for _ in range(V)]
    g.v_type = ["" for _ in range(V)]
    g.v_asn = np.zeros(V, dtype=np.int64)
    g.v_bw_up = np.full(V, float(args.bw))
    g.v_bw_down = np.full(V, float(args.bw))
    g.v_packetloss = np.zeros(V)
    g.e_src = np.array([index[r[0]] for r in rows], dtype=np.int64)
    g.e_dst = np.array([index[r[1]] for r in rows], dtype=np.int64)
    g.e_latency_ms = np.array([float(r[2]) for r in rows])
    g.e_jitter_ms = np.zeros(len(rows))
    g.e_packetloss = np.array([float(r[3]) if len(r) > 3 else 0.0
                               for r in rows])
    with _open_out(args.out) as f:
        write_graphml(g, f)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("info")
    p.add_argument("input")
    p.set_defaults(fn=cmd_info)

    p = sub.add_parser("prune")
    p.add_argument("input")
    p.add_argument("--keep-types", help="comma list of vertex types")
    p.add_argument("--keep-ids", help="file of vertex ids, one per line")
    p.add_argument("--out")
    p.set_defaults(fn=cmd_prune)

    p = sub.add_parser("compute-paths")
    p.add_argument("input")
    p.add_argument("--min-latency", type=float, default=1.0,
                   help="floor for emitted latencies, ms")
    p.add_argument("--out")
    p.set_defaults(fn=cmd_compute_paths)

    p = sub.add_parser("collapse")
    p.add_argument("input")
    p.add_argument("--by", choices=["geocode", "type", "asn"],
                   default="geocode")
    p.add_argument("--min-latency", type=float, default=1.0)
    p.add_argument("--out")
    p.set_defaults(fn=cmd_collapse)

    p = sub.add_parser("extract-latencies")
    p.add_argument("input")
    p.add_argument("--out")
    p.set_defaults(fn=cmd_extract_latencies)

    p = sub.add_parser("convert")
    p.add_argument("input", help="CSV: source,target,latency_ms[,loss]")
    p.add_argument("--bw", type=int, default=102400,
                   help="vertex bandwidth KiB/s for converted graphs")
    p.add_argument("--out")
    p.set_defaults(fn=cmd_convert)

    args = ap.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
