#!/usr/bin/env python3
"""netreport: fold netscope time-series streams into ensemble
percentile curves.

A sweep leaves one network observatory stream per run
(``<run>/netscope.jsonl`` — ``fleet submit --netscope``, ``python -m
shadow_tpu CONF --netscope FILE``, or ``batch --netscope-dir``). Each
stream's last record carries the run's cumulative device histogram
([NS_KINDS][NS_BUCKETS] integer counts); this tool folds any number
of them into the cross-run view ``obs.netscope.ensemble`` computes:
pooled p50/p90/p99 per kind, per-run tails (the spread the means
hide), and the pooled CDF curve — the figure-ready "ensemble
percentile curves" of the observability roadmap item.

``fleet status --ensemble`` prints the same fold for a live queue;
netreport is the offline/archival half: point it at stream files (or
a runs directory) from any mix of queues, batches and single runs.

Usage:
  python tools/netreport.py runs/*/netscope.jsonl [--json] [--out F]
  python tools/netreport.py --runs-dir q/runs        # scans */netscope.jsonl
  python tools/netreport.py --self-check             # no jax, <1s

Headless by design: loads obs/netscope.py by file path (stdlib-only
module level), so no jax import and no accelerator env is touched.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def netscope_mod():
    """obs/netscope.py by FILE PATH — shadow_tpu/__init__ imports jax,
    which this tool must not pay (the perf_report.py convention)."""
    spec = importlib.util.spec_from_file_location(
        "_netscope", os.path.join(REPO, "shadow_tpu/obs/netscope.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def collect(paths, runs_dir=None):
    """-> (names, tables): one final cumulative histogram per readable
    stream; unreadable/empty streams are reported and skipped, never a
    crash (a sweep with one crashed run must still fold)."""
    NS = netscope_mod()
    paths = list(paths or [])
    if runs_dir:
        for rid in sorted(os.listdir(runs_dir)):
            p = os.path.join(runs_dir, rid, "netscope.jsonl")
            if os.path.exists(p):
                paths.append(p)
    names, tables = [], []
    for p in paths:
        try:
            _, recs = NS.read_stream(p)
        except (OSError, json.JSONDecodeError) as e:
            sys.stderr.write(f"netreport: {p}: unreadable ({e}) — "
                             "skipped\n")
            continue
        if not recs:
            sys.stderr.write(f"netreport: {p}: no records — skipped\n")
            continue
        names.append(p)
        tables.append(recs[-1]["hist"])
    return names, tables


def render(ens, names) -> str:
    lines = [f"netscope ensemble: {ens['runs']} runs"]
    for n in names:
        lines.append(f"  {n}")
    lines.append(f"{'kind':<14}{'n':<10}{'p50':<10}{'p90':<10}"
                 f"{'p99':<10}per-run p99 (us)")
    for name, k in ens["kinds"].items():
        lanes = " ".join(str(v) for v in k["lane_p99_us"])
        lines.append(f"{name:<14}{k['count']:<10}{k['p50_us']:<10}"
                     f"{k['p90_us']:<10}{k['p99_us']:<10}{lanes}")
    return "\n".join(lines)


# --- self-check: the fold/percentile math, no jax -------------------------

def self_check() -> int:
    """Synthetic-stream check of the ensemble contract: bucket math,
    fold over every accepted nesting, exact percentile ranks, CDF
    monotonicity, stream round-trip. Wired into the verify flow next
    to perf_report's."""
    import tempfile
    NS = netscope_mod()
    K, B = NS.NS_KINDS, NS.NS_BUCKETS

    # bucketing: host ladder is the device comparison-sum ladder
    for v, want in ((0, 0), (1, 1), (2, 2), (3, 2), (1024, 11),
                    (1500, 11), (1 << 29, 30), (1 << 30, 31),
                    (1 << 40, 31)):
        got = NS.bucket_of(v)
        assert got == want, (v, got, want)
        idx = sum(v >= b for b in NS.BOUNDS_US)
        assert idx == got, (v, idx, got)

    # exact percentiles: 100 samples in bucket 3, 1 in bucket 10
    row = [0] * B
    row[3], row[10] = 100, 1
    assert NS.percentile(row, 50) == 1 << 3
    assert NS.percentile(row, 99) == 1 << 3      # rank 100 of 101
    assert NS.percentile(row, 100) == 1 << 10
    assert NS.percentile([0] * B, 99) == 0

    # fold accepts [K][B], [H][K][B], [L][H][K][B] and agrees
    t = [[i * B + j for j in range(B)] for i in range(K)]
    assert NS.fold(t) == t
    assert NS.fold([t, t]) == [[2 * c for c in r] for r in t]
    assert NS.fold([[t, t], [t, t]]) == [[4 * c for c in r]
                                         for r in t]

    # ensemble: pooled count sums lanes; lane tails match per-lane
    # percentiles; CDF is monotone and ends at 1
    a = [[0] * B for _ in range(K)]
    b = [[0] * B for _ in range(K)]
    a[0][2] = 10                      # lane a: rtt all ~4us
    b[0][8] = 30                      # lane b: rtt all ~256us
    ens = NS.ensemble([a, b])
    r = ens["kinds"]["rtt"]
    assert r["count"] == 40
    assert r["lane_p99_us"] == [1 << 2, 1 << 8]
    assert r["p50_us"] == 1 << 8      # pooled median sits in lane b
    cdf = r["cdf"]
    assert all(x <= y + 1e-12 for x, y in zip(cdf, cdf[1:]))
    assert abs(cdf[-1] - 1.0) < 1e-9

    # stream round-trip: header + records -> collect() takes the LAST
    # record's cumulative table
    with tempfile.TemporaryDirectory() as td:
        p = os.path.join(td, "netscope.jsonl")
        with open(p, "w") as f:
            f.write(json.dumps({"format": NS.FORMAT,
                                "kinds": list(NS.KIND_NAMES),
                                "bounds_us": list(NS.BOUNDS_US)}) + "\n")
            f.write(json.dumps({"window": 8, "sim_ns": 10 ** 9,
                                "totals": {}, "delta": {},
                                "hist": a, "hist_delta": a}) + "\n")
            f.write(json.dumps({"window": 16, "sim_ns": 2 * 10 ** 9,
                                "totals": {}, "delta": {},
                                "hist": b, "hist_delta": b}) + "\n")
        names, tables = collect([p])
        assert names == [p] and tables == [b], (names, tables)
        # empty stream is skipped, not fatal
        empty = os.path.join(td, "empty.jsonl")
        open(empty, "w").close()
        names, tables = collect([empty, p])
        assert names == [p], names

    print("netreport: self-check OK (buckets + fold + ensemble + "
          "stream)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="fold netscope JSONL streams into cross-run "
                    "percentile curves (obs.netscope.ensemble)")
    ap.add_argument("streams", nargs="*",
                    help="netscope JSONL stream paths")
    ap.add_argument("--runs-dir", default=None, metavar="DIR",
                    help="also scan DIR/*/netscope.jsonl (a fleet "
                         "queue's runs directory)")
    ap.add_argument("--json", action="store_true",
                    help="print the full ensemble JSON (with CDF and "
                         "buckets) instead of the table")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="also write the ensemble JSON to FILE")
    ap.add_argument("--self-check", action="store_true",
                    help="headless math check (no jax, no inputs)")
    args = ap.parse_args(argv)

    if args.self_check:
        return self_check()
    if not args.streams and not args.runs_dir:
        ap.error("provide stream paths, --runs-dir, or --self-check")

    names, tables = collect(args.streams, runs_dir=args.runs_dir)
    if not tables:
        sys.stderr.write("netreport: no usable streams\n")
        return 1
    NS = netscope_mod()
    ens = NS.ensemble(tables)
    ens["members"] = names
    if args.out:
        with open(args.out, "w") as f:
            json.dump(ens, f, indent=1, sort_keys=True)
    if args.json:
        print(json.dumps(ens, indent=1, sort_keys=True))
    else:
        print(render(ens, names))
    return 0


if __name__ == "__main__":
    sys.exit(main())
