/* minides: dependency-free compiled-C discrete-event baseline.
 *
 * The reference C engine cannot be built in this image (GLib/igraph
 * absent — see BASELINE.md), so the bench's primary denominator is the
 * pure-Python reference engine, which understates compiled-code speed.
 * This program is the honesty check: a minimal binary-heap DES running
 * the PHOLD shape (the classic DES benchmark the reference ships as a
 * plugin, /root/reference/src/test/phold/shd-test-phold.c) with the
 * same workload parameters bench.py uses — N hosts, one initial timer
 * each, exponential(mean) re-arm, fixed-latency message to a uniform
 * random peer. It does LESS per-event work than either real engine
 * (no NIC model, no sockets, no per-packet state), so its events/sec
 * is an UPPER bound on any full engine's compiled-C throughput —
 * making the bench's vs-compiled-C ratio conservative.
 *
 * Usage: minides <num_hosts> <stop_seconds> [mean_ms] [latency_ms]
 * Prints one line: events=<N> wall_s=<S> events_per_sec=<R>
 */

#include <stdio.h>
#include <stdlib.h>
#include <stdint.h>
#include <math.h>
#include <time.h>

typedef struct {
    int64_t t;      /* ns */
    int32_t seq;    /* (time, seq) total order, matching event_compare */
    int32_t host;
    int32_t kind;   /* 0 = timer fire, 1 = message arrival */
} Ev;

static Ev *heap;
static size_t heap_n, heap_cap;

static int ev_lt(const Ev *a, const Ev *b) {
    if (a->t != b->t) return a->t < b->t;
    return a->seq < b->seq;
}

static void heap_push(Ev e) {
    if (heap_n == heap_cap) {
        heap_cap *= 2;
        heap = realloc(heap, heap_cap * sizeof(Ev));
        if (!heap) { perror("realloc"); exit(1); }
    }
    size_t i = heap_n++;
    heap[i] = e;
    while (i > 0) {
        size_t p = (i - 1) / 2;
        if (!ev_lt(&heap[i], &heap[p])) break;
        Ev tmp = heap[p]; heap[p] = heap[i]; heap[i] = tmp;
        i = p;
    }
}

static Ev heap_pop(void) {
    Ev top = heap[0];
    heap[0] = heap[--heap_n];
    size_t i = 0;
    for (;;) {
        size_t l = 2 * i + 1, r = l + 1, m = i;
        if (l < heap_n && ev_lt(&heap[l], &heap[m])) m = l;
        if (r < heap_n && ev_lt(&heap[r], &heap[m])) m = r;
        if (m == i) break;
        Ev tmp = heap[m]; heap[m] = heap[i]; heap[i] = tmp;
        i = m;
    }
    return top;
}

/* xorshift128+ — fast deterministic PRNG (public-domain algorithm) */
static uint64_t rs[2] = {0x9E3779B97F4A7C15ull, 0xBF58476D1CE4E5B9ull};
static uint64_t rnext(void) {
    uint64_t x = rs[0], y = rs[1];
    rs[0] = y;
    x ^= x << 23;
    rs[1] = x ^ y ^ (x >> 17) ^ (y >> 26);
    return rs[1] + y;
}
static double runif(void) { return (rnext() >> 11) * (1.0 / 9007199254740992.0); }

int main(int argc, char **argv) {
    if (argc < 3) {
        fprintf(stderr, "usage: %s <hosts> <stop_s> [mean_ms] [lat_ms]\n",
                argv[0]);
        return 2;
    }
    int n = atoi(argv[1]);
    double stop_s = atof(argv[2]);
    double mean_ms = argc > 3 ? atof(argv[3]) : 500.0;
    double lat_ms = argc > 4 ? atof(argv[4]) : 25.0;
    int64_t stop = (int64_t)(stop_s * 1e9);
    int64_t lat = (int64_t)(lat_ms * 1e6);
    int32_t seq = 0;

    heap_cap = (size_t)n * 4 + 64;
    heap_n = 0;
    heap = malloc(heap_cap * sizeof(Ev));
    if (!heap) { perror("malloc"); return 1; }

    /* init=1: one initial timer per host at start + exp(mean) */
    for (int h = 0; h < n; h++) {
        int64_t d = (int64_t)(-mean_ms * 1e6 * log(1.0 - runif()));
        Ev e = {1000000000LL + (d > 0 ? d : 1), seq++, h, 0};
        heap_push(e);
    }

    struct timespec t0, t1;
    clock_gettime(CLOCK_MONOTONIC, &t0);
    long long events = 0;
    while (heap_n > 0) {
        Ev e = heap_pop();
        if (e.t >= stop) break;
        events++;
        if (e.kind == 0) {
            /* timer fire: send a message to a uniform random peer */
            int peer = (int)(runif() * n);
            if (peer >= n) peer = n - 1;
            if (peer == e.host) peer = (peer + 1) % n;
            Ev m = {e.t + lat, seq++, peer, 1};
            heap_push(m);
        } else {
            /* arrival: re-arm the exponential timer */
            int64_t d = (int64_t)(-mean_ms * 1e6 * log(1.0 - runif()));
            Ev m = {e.t + (d > 0 ? d : 1), seq++, e.host, 0};
            heap_push(m);
        }
    }
    clock_gettime(CLOCK_MONOTONIC, &t1);
    double wall = (t1.tv_sec - t0.tv_sec) + (t1.tv_nsec - t0.tv_nsec) * 1e-9;
    printf("events=%lld wall_s=%.6f events_per_sec=%.1f\n",
           events, wall, wall > 0 ? events / wall : 0.0);
    free(heap);
    return 0;
}
