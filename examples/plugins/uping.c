/* uping: a plain, UNMODIFIED UDP ping client (sendto/recvfrom).
 *
 * Sends <count> datagrams of <bytes> to <host>:<port> and waits for
 * each echo — ordinary libc only (getaddrinfo, sendto, recvfrom,
 * epoll). The same binary runs:
 *   natively:   ./uping <host> <port> <bytes> <count>
 *               against any UDP echo server;
 *   simulated:  plugin="hosted:shim" cmd=.../uping ... against the
 *               simulator's modeled pingserver app.
 * Prints: uping done echoes=N bytes=B
 */
#include <errno.h>
#include <netdb.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <unistd.h>
#include <fcntl.h>

static int fatal(const char *msg) { perror(msg); exit(1); }

int main(int argc, char **argv) {
    if (argc < 5) {
        fprintf(stderr, "usage: %s <host> <port> <bytes> <count>\n",
                argv[0]);
        return 2;
    }
    const char *host = argv[1], *port = argv[2];
    long nbytes = atol(argv[3]);
    int count = atoi(argv[4]);

    struct addrinfo hints, *ai;
    memset(&hints, 0, sizeof hints);
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_DGRAM;
    if (getaddrinfo(host, port, &hints, &ai) != 0) fatal("getaddrinfo");

    int fd = socket(AF_INET, SOCK_DGRAM, 0);
    if (fd < 0) fatal("socket");
    fcntl(fd, F_SETFL, O_NONBLOCK);

    int ep = epoll_create1(0);
    if (ep < 0) fatal("epoll_create1");
    struct epoll_event ev, out;
    ev.events = EPOLLIN;
    ev.data.fd = fd;
    if (epoll_ctl(ep, EPOLL_CTL_ADD, fd, &ev) < 0) fatal("epoll_ctl");

    char *buf = calloc(1, 65536);
    long total = 0;
    int echoes = 0;
    for (int i = 0; i < count; i++) {
        if (sendto(fd, buf, (size_t)nbytes, 0, ai->ai_addr,
                   ai->ai_addrlen) < 0)
            fatal("sendto");
        for (;;) {
            ssize_t n = recvfrom(fd, buf, 65536, 0, NULL, NULL);
            if (n >= 0) { total += n; echoes++; break; }
            if (errno != EAGAIN) fatal("recvfrom");
            if (epoll_wait(ep, &out, 1, 30000) < 1)
                fatal("epoll_wait(echo timeout)");
        }
    }
    printf("uping done echoes=%d bytes=%ld\n", echoes, total);
    freeaddrinfo(ai);
    free(buf);
    close(fd);
    return echoes == count ? 0 : 1;
}
