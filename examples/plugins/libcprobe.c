/* libcprobe: an UNMODIFIED binary exercising the non-socket libc
 * surface the simulator must virtualize (reference equivalents:
 * shd-process.c:3055 nanosleep, :4329-4389 clocks, shd-host.c:574
 * entropy; determinism dual-run shd-test-determinism.c:15-60).
 *
 *   ./libcprobe <sleep_ms> <nrandom>
 *
 * 1. reads all three clock surfaces (clock_gettime, gettimeofday,
 *    time) — under the sim they must agree on SIMULATED time;
 * 2. sleeps sleep_ms via nanosleep + usleep + sleep (one third each)
 *    and reports the clock delta — under the sim the delta is SIM
 *    time (the process never burns wallclock);
 * 3. draws nrandom bytes from getrandom() AND /dev/urandom (raw
 *    open/read AND stdio fopen/fread — glibc's fopen bypasses the
 *    open() interposition via an internal open, so the shim backs it
 *    with fopencookie; ADVICE r5) and prints them as hex — under the
 *    sim these come from the host's deterministic PRNG, so two runs
 *    print IDENTICAL lines;
 * 4. tries pthread_create — under the sim it must FAIL (EAGAIN), not
 *    silently spawn a real thread;
 * 5. write()s to /dev/urandom — under the sim this must fail cleanly
 *    (EBADF), not crash the simulator's protocol handler;
 * 6. sleeps via poll(NULL,0,ms) + select(0,...,&tv) — the portable
 *    sleep idioms — and reports the clock delta, which under the sim
 *    must be SIMULATED time (OP_SLEEP), not frozen.
 *
 * Output (one line each):
 *   clocks mono=<s> real=<s> tod=<s> time=<s>
 *   slept requested=<s> measured=<s>
 *   entropy getrandom=<hex> urandom=<hex>
 *   fentropy fopen=<hex>
 *   threads pthread_create=<rc>
 *   urandomwrite rc=<rc> errno=<errno>
 *   pollsleep requested=<s> measured=<s>
 */
#define _GNU_SOURCE
#include <errno.h>
#include <fcntl.h>
#include <poll.h>
#include <pthread.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/random.h>
#include <sys/select.h>
#include <sys/time.h>
#include <time.h>
#include <unistd.h>

static void *thread_main(void *arg) { (void)arg; return NULL; }

static void hex(const unsigned char *b, int n, char *out) {
    for (int i = 0; i < n; i++) sprintf(out + 2 * i, "%02x", b[i]);
    out[2 * n] = 0;
}

int main(int argc, char **argv) {
    long sleep_ms = argc > 1 ? atol(argv[1]) : 900;
    int nrand = argc > 2 ? atoi(argv[2]) : 16;
    if (nrand > 64) nrand = 64;

    struct timespec mono, real;
    struct timeval tod;
    clock_gettime(CLOCK_MONOTONIC, &mono);
    clock_gettime(CLOCK_REALTIME, &real);
    gettimeofday(&tod, NULL);
    time_t tt = time(NULL);
    printf("clocks mono=%.3f real=%.3f tod=%.3f time=%ld\n",
           mono.tv_sec + mono.tv_nsec / 1e9,
           real.tv_sec + real.tv_nsec / 1e9,
           tod.tv_sec + tod.tv_usec / 1e6, (long)tt);

    struct timespec t0, t1;
    clock_gettime(CLOCK_MONOTONIC, &t0);
    long third_ns = sleep_ms * 1000000L / 3;
    struct timespec req = {third_ns / 1000000000L,
                           third_ns % 1000000000L};
    nanosleep(&req, NULL);
    usleep(third_ns / 1000);
    if (third_ns >= 1000000000L) sleep(third_ns / 1000000000L);
    else usleep(third_ns / 1000);
    clock_gettime(CLOCK_MONOTONIC, &t1);
    double measured = (t1.tv_sec - t0.tv_sec) +
                      (t1.tv_nsec - t0.tv_nsec) / 1e9;
    printf("slept requested=%.3f measured=%.3f\n",
           sleep_ms / 1000.0, measured);

    unsigned char gr[64], ur[64];
    char grh[129], urh[129];
    memset(gr, 0, sizeof gr);
    memset(ur, 0, sizeof ur);
    if (getrandom(gr, nrand, 0) != nrand) perror("getrandom");
    int fd = open("/dev/urandom", O_RDONLY);
    if (fd < 0 || read(fd, ur, nrand) != nrand) perror("urandom");
    if (fd >= 0) close(fd);
    hex(gr, nrand, grh);
    hex(ur, nrand, urh);
    printf("entropy getrandom=%s urandom=%s\n", grh, urh);

    /* the stdio path: glibc's fopen never reaches the open()
     * interposition (internal __open), so this is the one entropy
     * route only the fopen/fopen64 interposition covers */
    unsigned char fe[64];
    char feh[129];
    memset(fe, 0, sizeof fe);
    FILE *sf = fopen("/dev/urandom", "r");
    if (!sf || fread(fe, 1, (size_t)nrand, sf) != (size_t)nrand)
        perror("fopen urandom");
    if (sf) fclose(sf);
    hex(fe, nrand, feh);
    printf("fentropy fopen=%s\n", feh);

    pthread_t th;
    int rc = pthread_create(&th, NULL, thread_main, NULL);
    if (rc == 0) pthread_join(th, NULL);
    printf("threads pthread_create=%d\n", rc);

    errno = 0;
    int wfd = open("/dev/urandom", O_RDWR);
    long wrc = wfd >= 0 ? (long)write(wfd, gr, 8) : -2;
    int werr = errno;
    if (wfd >= 0) close(wfd);
    printf("urandomwrite rc=%ld errno=%d\n", wrc, werr);

    struct timespec p0, p1;
    clock_gettime(CLOCK_MONOTONIC, &p0);
    poll(NULL, 0, 150);
    struct timeval ptv = {0, 150 * 1000};
    select(0, NULL, NULL, NULL, &ptv);
    clock_gettime(CLOCK_MONOTONIC, &p1);
    printf("pollsleep requested=0.300 measured=%.3f\n",
           (p1.tv_sec - p0.tv_sec) + (p1.tv_nsec - p0.tv_nsec) / 1e9);
    return 0;
}
