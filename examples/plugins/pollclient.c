/* pollclient: a plain, UNMODIFIED poll()/select()-based TCP upload
 * client — the wait-styles epclient does NOT cover (the reference
 * interposes poll and select for exactly this class of binary,
 * process_emu_poll/select, shd-process.c:2606-2899).
 *
 * Uses only ordinary libc networking: getaddrinfo, nonblocking
 * connect completed via poll(POLLOUT), send gated by poll(POLLOUT),
 * then recv-until-EOF gated by select(readfds). getsockname() is
 * called on every established connection and its port must be
 * nonzero (round-5 shim: real simulated identity, not zeros).
 *
 * The same binary runs:
 *   natively:   ./pollclient <host> <port> <bytes> <count>
 *   simulated:  plugin="hosted:shim" cmd=.../pollclient <server> ...
 *
 * Prints one summary line:
 *   pollclient done transfers=N bytes=B ports_ok=N secs=S
 */
#include <errno.h>
#include <netdb.h>
#include <netinet/in.h>
#include <poll.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/select.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>
#include <fcntl.h>

static int fatal(const char *msg) { perror(msg); exit(1); }

int main(int argc, char **argv) {
    if (argc < 5) {
        fprintf(stderr,
                "usage: %s <host> <port> <bytes-per-transfer> <count>\n",
                argv[0]);
        return 2;
    }
    const char *host = argv[1], *port = argv[2];
    long nbytes = atol(argv[3]);
    int count = atoi(argv[4]);

    struct addrinfo hints, *ai;
    memset(&hints, 0, sizeof hints);
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    if (getaddrinfo(host, port, &hints, &ai) != 0)
        fatal("getaddrinfo");

    char *buf = calloc(1, 65536);
    long total = 0;
    int done = 0, ports_ok = 0;

    struct timespec t0;
    clock_gettime(CLOCK_MONOTONIC, &t0);

    for (int i = 0; i < count; i++) {
        int fd = socket(AF_INET, SOCK_STREAM, 0);
        if (fd < 0) fatal("socket");
        fcntl(fd, F_SETFL, O_NONBLOCK);
        if (connect(fd, ai->ai_addr, ai->ai_addrlen) < 0 &&
            errno != EINPROGRESS)
            fatal("connect");

        /* completion via poll(POLLOUT) */
        struct pollfd p = {fd, POLLOUT, 0};
        if (poll(&p, 1, 30000) <= 0) fatal("poll connect");
        int err = 0;
        socklen_t el = sizeof err;
        getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &el);
        if (err) { errno = err; fatal("SO_ERROR"); }

        struct sockaddr_in self;
        socklen_t sl = sizeof self;
        if (getsockname(fd, (struct sockaddr *)&self, &sl) == 0 &&
            ntohs(self.sin_port) != 0)
            ports_ok++;

        long left = nbytes;
        while (left > 0) {
            struct pollfd w = {fd, POLLOUT, 0};
            if (poll(&w, 1, 30000) <= 0) fatal("poll send");
            ssize_t k = send(fd, buf, left > 65536 ? 65536 : left, 0);
            if (k < 0) {
                if (errno == EAGAIN) continue;
                fatal("send");
            }
            left -= k;
            total += k;
        }
        shutdown(fd, SHUT_WR);

        /* wait for the server's close with select() */
        for (;;) {
            fd_set rs;
            FD_ZERO(&rs);
            FD_SET(fd, &rs);
            struct timeval tv = {30, 0};
            int rc = select(fd + 1, &rs, NULL, NULL, &tv);
            if (rc <= 0) fatal("select eof");
            char tmp[4096];
            ssize_t k = recv(fd, tmp, sizeof tmp, 0);
            if (k < 0) {
                if (errno == EAGAIN) continue;
                fatal("recv");
            }
            if (k == 0) break;           /* EOF: server closed */
        }
        close(fd);
        done++;
    }

    struct timespec t1;
    clock_gettime(CLOCK_MONOTONIC, &t1);
    double secs = (t1.tv_sec - t0.tv_sec) +
                  (t1.tv_nsec - t0.tv_nsec) / 1e9;
    printf("pollclient done transfers=%d bytes=%ld ports_ok=%d "
           "secs=%.3f\n", done, total, ports_ok, secs);
    freeaddrinfo(ai);
    free(buf);
    return done == count ? 0 : 1;
}
