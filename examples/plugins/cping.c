/* Native hosted plugin: UDP ping client in plain C.
 *
 * The counterpart of writing a Shadow plugin against libc
 * (LD_PRELOAD-interposed) in the reference — here the plugin is built
 * against the explicit shadow_os_api vtable (hosting/cplugin.py) and
 * every host instance gets its own state struct (the role the
 * reference's dlmopen linker namespaces played).
 *
 * Args: "peer=<hostname> port=<p> count=<n> interval_ms=<ms> size=<b>"
 */

#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct {
    long long (*now)(void* os);
    double    (*rnd)(void* os);
    int  (*udp_open)(void* os, int port);
    int  (*tcp_connect)(void* os, int dst_host, int port, int tag);
    int  (*tcp_listen)(void* os, int port);
    void (*send_to)(void* os, int sock, int dst_host, int port,
                    long long nbytes, int aux);
    void (*write_sk)(void* os, int sock, long long nbytes);
    void (*close_sk)(void* os, int sock);
    void (*timer)(void* os, long long delay_ns, int tag);
    int  (*resolve)(void* os, const char* name);
} shadow_os_api;

typedef struct {
    char peer[64];
    int port, count, size;
    long long interval_ns;
    int sock;
    int sent, echoed;
} state_t;

static const char* kv(const char* args, const char* key, char* out,
                      int cap, const char* dflt) {
    const char* p = strstr(args, key);
    if (!p) { snprintf(out, cap, "%s", dflt); return out; }
    p += strlen(key);
    int i = 0;
    while (*p && *p != ' ' && i < cap - 1) out[i++] = *p++;
    out[i] = 0;
    return out;
}

void* plugin_create(const char* args) {
    state_t* st = (state_t*)calloc(1, sizeof(state_t));
    char buf[64];
    kv(args, "peer=", st->peer, sizeof(st->peer), "server");
    st->port = atoi(kv(args, "port=", buf, sizeof(buf), "8000"));
    st->count = atoi(kv(args, "count=", buf, sizeof(buf), "3"));
    st->size = atoi(kv(args, "size=", buf, sizeof(buf), "64"));
    st->interval_ns =
        atoll(kv(args, "interval_ms=", buf, sizeof(buf), "1000")) *
        1000000LL;
    return st;
}

void plugin_destroy(void* p) { free(p); }

static void send_ping(state_t* st, void* os, const shadow_os_api* api) {
    int dst = api->resolve(os, st->peer);
    api->send_to(os, st->sock, dst, st->port, st->size, 4242);
    st->sent++;
    if (st->sent < st->count)
        api->timer(os, st->interval_ns, 0);
}

/* reasons: 0 start, 1 timer, 2 dgram, 3 connected, 4 eof, 5 accept,
 * 6 sent */
void plugin_on_wake(void* p, void* os, const shadow_os_api* api,
                    int reason, int a, int b, long long c) {
    state_t* st = (state_t*)p;
    switch (reason) {
    case 0:
        st->sock = api->udp_open(os, 0);
        send_ping(st, os, api);
        break;
    case 1:
        send_ping(st, os, api);
        break;
    case 2:  /* datagram: a=sock handle, b=src host, c=(aux<<32)|len */
        if ((int)(c >> 32) == 4242) st->echoed++;
        break;
    default:
        break;
    }
}

/* test hook: expose counters */
int plugin_get_sent(void* p) { return ((state_t*)p)->sent; }
int plugin_get_echoed(void* p) { return ((state_t*)p)->echoed; }

#ifdef __cplusplus
}
#endif
