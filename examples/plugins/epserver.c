/* epserver: a plain, UNMODIFIED epoll-based TCP sink server.
 *
 * Uses only ordinary libc networking (socket, bind, listen, accept4,
 * epoll, recv-until-EOF) — no simulator headers. The same binary runs:
 *   natively:   ./epserver <port> <count>
 *               serving any TCP uploader (e.g. epclient) on localhost;
 *   simulated:  plugin="hosted:shim" cmd=.../epserver <port> <count>
 *               via the LD_PRELOAD shim (shadow_tpu/hosting/shim*),
 *               serving SIMULATED clients.
 *
 * Serves exactly <count> connections: accept, read until EOF, close.
 * Prints one summary line:
 *   epserver done transfers=N bytes=B
 * which must match between native and simulated runs — the server half
 * of the reference's dual-build test pattern (SURVEY §4; the reference
 * builds every test as a native binary AND a shadow plugin).
 */
#define _GNU_SOURCE      /* accept4 */
#include <errno.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <unistd.h>
#include <fcntl.h>

static int fatal(const char *msg) { perror(msg); exit(1); }

int main(int argc, char **argv) {
    if (argc < 3) {
        fprintf(stderr, "usage: %s <port> <count>\n", argv[0]);
        return 2;
    }
    int port = atoi(argv[1]);
    int count = atoi(argv[2]);

    int ls = socket(AF_INET, SOCK_STREAM, 0);
    if (ls < 0) fatal("socket");
    int one = 1;
    setsockopt(ls, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
    struct sockaddr_in addr;
    memset(&addr, 0, sizeof addr);
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons((uint16_t)port);
    if (bind(ls, (struct sockaddr *)&addr, sizeof addr) < 0) fatal("bind");
    if (listen(ls, 64) < 0) fatal("listen");
    fcntl(ls, F_SETFL, O_NONBLOCK);

    int ep = epoll_create1(0);
    if (ep < 0) fatal("epoll_create1");
    struct epoll_event ev;
    ev.events = EPOLLIN;
    ev.data.fd = ls;
    if (epoll_ctl(ep, EPOLL_CTL_ADD, ls, &ev) < 0) fatal("epoll_ctl");

    char *buf = malloc(65536);
    long total = 0;
    int served = 0;

    struct epoll_event evs[8];
    while (served < count) {
        int n = epoll_wait(ep, evs, 8, -1);
        if (n < 0) fatal("epoll_wait");
        for (int i = 0; i < n; i++) {
            int fd = evs[i].data.fd;
            if (fd == ls) {
                for (;;) {
                    int c = accept4(ls, NULL, NULL, SOCK_NONBLOCK);
                    if (c < 0) {
                        if (errno == EAGAIN || errno == EWOULDBLOCK)
                            break;
                        fatal("accept4");
                    }
                    ev.events = EPOLLIN | EPOLLRDHUP;
                    ev.data.fd = c;
                    if (epoll_ctl(ep, EPOLL_CTL_ADD, c, &ev) < 0)
                        fatal("epoll_ctl(child)");
                }
                continue;
            }
            for (;;) {
                ssize_t m = recv(fd, buf, 65536, 0);
                if (m > 0) { total += m; continue; }
                if (m == 0) {                     /* clean EOF */
                    epoll_ctl(ep, EPOLL_CTL_DEL, fd, NULL);
                    close(fd);
                    served++;
                    break;
                }
                if (errno == EAGAIN || errno == EWOULDBLOCK) break;
                fatal("recv");
            }
        }
    }
    printf("epserver done transfers=%d bytes=%ld\n", served, total);
    free(buf);
    close(ls);
    close(ep);
    return served == count ? 0 : 1;
}
