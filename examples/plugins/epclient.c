/* epclient: a plain, UNMODIFIED epoll-based TCP upload client.
 *
 * Uses only ordinary libc networking (getaddrinfo, nonblocking
 * connect, epoll, send, shutdown, recv-until-EOF) — no simulator
 * headers. The same binary runs:
 *   natively:   ./epclient <host> <port> <bytes> <count>
 *               against any TCP sink that closes after EOF;
 *   simulated:  plugin="hosted:shim" cmd=.../epclient <server> <port>...
 *               via the LD_PRELOAD shim (shadow_tpu/hosting/shim*).
 *
 * Per transfer: connect, send <bytes>, shutdown(WR), wait for the
 * server's close (recv == 0), close. Prints one summary line:
 *   epclient done transfers=N bytes=B
 * which must match between native and simulated runs — the dual-run
 * check the reference applies to its own test plugins (SURVEY §4).
 */
#include <errno.h>
#include <netdb.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>
#include <fcntl.h>

static int fatal(const char *msg) { perror(msg); exit(1); }

int main(int argc, char **argv) {
    if (argc < 5) {
        fprintf(stderr,
                "usage: %s <host> <port> <bytes-per-transfer> <count>\n",
                argv[0]);
        return 2;
    }
    const char *host = argv[1], *port = argv[2];
    long nbytes = atol(argv[3]);
    int count = atoi(argv[4]);

    struct addrinfo hints, *ai;
    memset(&hints, 0, sizeof hints);
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    if (getaddrinfo(host, port, &hints, &ai) != 0)
        fatal("getaddrinfo");

    int ep = epoll_create1(0);
    if (ep < 0) fatal("epoll_create1");

    char *buf = calloc(1, 65536);
    long total = 0;
    int done = 0;

    struct timespec t0;
    clock_gettime(CLOCK_MONOTONIC, &t0);

    for (int i = 0; i < count; i++) {
        int fd = socket(AF_INET, SOCK_STREAM, 0);
        if (fd < 0) fatal("socket");
        fcntl(fd, F_SETFL, O_NONBLOCK);
        int rc = connect(fd, ai->ai_addr, ai->ai_addrlen);
        if (rc < 0 && errno != EINPROGRESS) fatal("connect");

        struct epoll_event ev, out;
        ev.events = EPOLLOUT;
        ev.data.fd = fd;
        if (epoll_ctl(ep, EPOLL_CTL_ADD, fd, &ev) < 0) fatal("epoll_ctl");
        if (epoll_wait(ep, &out, 1, -1) != 1) fatal("epoll_wait(conn)");
        int soerr = 0;
        socklen_t slen = sizeof soerr;
        getsockopt(fd, SOL_SOCKET, SO_ERROR, &soerr, &slen);
        if (soerr) { errno = soerr; fatal("connect(completion)"); }

        long sent = 0;
        while (sent < nbytes) {
            long want = nbytes - sent;
            if (want > 65536) want = 65536;
            ssize_t n = send(fd, buf, (size_t)want, 0);
            if (n < 0) {
                if (errno == EAGAIN) {          /* wait for writability */
                    if (epoll_wait(ep, &out, 1, -1) != 1)
                        fatal("epoll_wait(send)");
                    continue;
                }
                fatal("send");
            }
            sent += n;
        }
        shutdown(fd, SHUT_WR);

        /* wait for the server to consume everything and close */
        ev.events = EPOLLIN;
        ev.data.fd = fd;
        epoll_ctl(ep, EPOLL_CTL_MOD, fd, &ev);
        for (;;) {
            if (epoll_wait(ep, &out, 1, -1) != 1) fatal("epoll_wait(eof)");
            ssize_t n = recv(fd, buf, 65536, 0);
            if (n == 0) break;                   /* clean EOF */
            if (n < 0 && errno != EAGAIN) fatal("recv");
        }
        epoll_ctl(ep, EPOLL_CTL_DEL, fd, NULL);
        close(fd);
        total += sent;
        done++;
    }

    struct timespec t1;
    clock_gettime(CLOCK_MONOTONIC, &t1);
    double secs = (double)(t1.tv_sec - t0.tv_sec) +
                  (double)(t1.tv_nsec - t0.tv_nsec) / 1e9;
    printf("epclient done transfers=%d bytes=%ld secs=%.3f\n",
           done, total, secs);
    freeaddrinfo(ai);
    free(buf);
    return done == count ? 0 : 1;
}
