"""Pass-time observatory tests (obs.passcope + the occupancy gate).

Decoder: the committed CI fixture (tests/data/passcope_fixture.xplane.pb,
hand-built varint records from tests/helpers/xplane_encode.py) must
decode to an EXACT pass table — every number asserted, no tolerance.
Occupancy: the lockstep waste math, recounted independently by the
pure-Python engine's pass mirror (PyEngine(count_passes=True)).
Gate: tools/perf_regress.py's occupancy column fails synthetic waste
regressions and passes flat trajectories.

Compiled-engine items (the device pass table on a live run, digest
identity with the profiler armed, the compiled-vs-python pass-mix
differential) are @slow: each adds a cold XLA compile.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from shadow_tpu.obs import metrics as MT
from shadow_tpu.obs import passcope as PC

HELPERS = Path(__file__).resolve().parent / "helpers"
sys.path.insert(0, str(HELPERS))
import xplane_encode as XE  # noqa: E402

REPO = Path(__file__).resolve().parent.parent
PERF_REGRESS = REPO / "tools" / "perf_regress.py"

MS = 10**9  # picoseconds per millisecond


# --- decoder: the committed fixture, exactly -------------------------------

def test_fixture_file_in_sync():
    """The committed fixture IS make_fixture() — regenerating must be
    part of any encoder change (CI decodes the committed bytes)."""
    committed = Path(PC.fixture_path()).read_bytes()
    assert committed == XE.make_fixture()


def test_self_check_passes():
    assert PC.self_check() == 0


def test_fixture_decodes_to_exact_pass_table():
    scopes = PC.hlo_scope_map(PC.fixture_path())
    selfs = PC.device_self_times(PC.fixture_path())
    # the non-XLA python-thread line is ignored wholesale
    assert "python-thread" not in selfs
    dev = PC.attribute(selfs, scopes)
    assert dev["phases"]["drain"]["ms"] == 40.0
    assert dev["phases"]["exchange"]["ms"] == 30.0
    assert dev["phases"]["tcp.rx"]["ms"] == 20.0
    assert dev["phases"]["advance"]["ms"] == 5.0
    assert dev["rungs"]["w512"]["ms"] == 90.0
    assert dev["residual_ms"] == 3.0          # copy.5, unscoped HLO
    assert dev["runtime_ms"] == 2.0           # thunk glue, excluded
    assert dev["total_ms"] == 98.0
    assert dev["attributed_frac"] == round(95 / 98, 4)
    assert dev["ok"]
    assert dev["residual_top"][0] == {"op": "copy.5", "ms": 3.0}


def test_innermost_label_wins_and_rung_implies_drain(tmp_path):
    """An op under .../drain/k32/nic.tx/... is nic.tx (not drain);
    an op under a rung scope with NO handler label is drain."""
    instrs = [
        ("fusion.9", "jit(f)/jit(main)/drain/k32/nic.tx/fma"),
        ("add.1", "jit(f)/jit(main)/drain/k32/while/add"),
        ("mul.2", "jit(f)/jit(main)/cap_peaks/mul"),
    ]
    meta = XE.xplane("/host:metadata", {
        1: XE.xevent_metadata("jit_f(1)", XE.hlo_proto(instrs))}, [])
    ops = {10: XE.xevent_metadata("fusion.9"),
           11: XE.xevent_metadata("add.1"),
           12: XE.xevent_metadata("mul.2")}
    cpu = XE.xplane("/host:CPU", ops, [XE.xline(
        "tf_XLATfrtCpuClient/0",
        [(10, 0, 7 * MS), (11, 7 * MS, 2 * MS), (12, 9 * MS, MS)])])
    p = tmp_path / "t.xplane.pb"
    p.write_bytes(XE.xspace([meta, cpu]))
    dev = PC.attribute(PC.device_self_times(str(p)),
                       PC.hlo_scope_map(str(p)))
    assert dev["phases"]["nic.tx"]["ms"] == 7.0
    assert dev["phases"]["drain"]["ms"] == 2.0
    assert dev["phases"]["cap_peaks"]["ms"] == 1.0
    assert dev["rungs"]["k32"]["ms"] == 9.0   # handler time included
    assert dev["attributed_frac"] == 1.0


def test_self_times_are_stack_based(tmp_path):
    """A parent op's time excludes its nested children — only SELF
    time lands in the table (no double counting)."""
    instrs = [("fusion.1", "jit(f)/jit(main)/drain/x"),
              ("sort.2", "jit(f)/jit(main)/exchange/x")]
    meta = XE.xplane("/host:metadata", {
        1: XE.xevent_metadata("jit_f(1)", XE.hlo_proto(instrs))}, [])
    ops = {10: XE.xevent_metadata("fusion.1"),
           11: XE.xevent_metadata("sort.2")}
    # sort.2 nested wholly inside fusion.1's span
    cpu = XE.xplane("/host:CPU", ops, [XE.xline(
        "tf_XLATfrtCpuClient/0",
        [(10, 0, 10 * MS), (11, 2 * MS, 4 * MS)])])
    p = tmp_path / "t.xplane.pb"
    p.write_bytes(XE.xspace([meta, cpu]))
    selfs = PC.device_self_times(str(p))
    assert selfs["fusion.1"] == 6 * MS
    assert selfs["sort.2"] == 4 * MS


def test_runtime_scaffolding_excluded_from_denominator():
    selfs = {"ThunkExecutor::Execute (wait for completion)": 900 * MS,
             "fusion.1": 100 * MS}
    dev = PC.attribute(selfs, {"fusion.1": "jit(f)/jit(main)/drain/x"})
    assert dev["total_ms"] == 100.0
    assert dev["runtime_ms"] == 900.0
    assert dev["attributed_frac"] == 1.0
    assert dev["ok"]


def test_decode_dir_missing_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        PC.decode_dir(str(tmp_path / "nope"))


# --- occupancy math --------------------------------------------------------

def test_occupancy_arithmetic_exact():
    occ = PC.occupancy({"k32": (32, 10), "dense": (64, 2)},
                       events=200, batch=4)
    # 10 sparse passes x 32 lanes x batch 4 + 2 dense x 64 x 1
    assert occ["lane_steps"] == 1408
    assert occ["passes"] == 12
    assert occ["events"] == 200
    assert occ["utilization"] == round(200 / 1408, 4)
    assert occ["waste_frac"] == round(1 - 200 / 1408, 4)
    # rung floors: k32 fires from 1 ready host; dense only past the
    # largest ladder rung
    assert occ["per_rung"]["k32"]["min_fill"] == round(1 / 32, 4)
    assert occ["per_rung"]["dense"]["min_fill"] == round(33 / 64, 4)


def test_occupancy_utilization_clamped():
    # chained NIC-TX events can exceed lane-step slots; clamp at 1.0
    occ = PC.occupancy({"dense": (4, 1)}, events=100, batch=1)
    assert occ["utilization"] == 1.0
    assert occ["waste_frac"] == 0.0


def test_shard_occupancy_skew():
    sh = PC.shard_occupancy([[10, 2], [2, 0]], [200, 40],
                            [("k32", 32), ("dense", 64)], 4)
    assert len(sh["per_shard"]) == 2
    assert sh["skew"] >= 1.0
    assert all(0.0 <= w <= 1.0 for w in sh["per_shard"])


def test_top_pass():
    dev = {"phases": {"drain": {"ms": 10.0, "frac": 0.5},
                      "exchange": {"ms": 30.0, "frac": 0.3}}}
    assert PC.top_pass(dev) == ("exchange", 0.3)
    assert PC.top_pass({}) == (None, 0.0)
    assert PC.top_pass(None) == (None, 0.0)


# --- capture lifecycle (no real profiler) ----------------------------------

def test_capture_arms_after_first_chunk(monkeypatch, tmp_path):
    calls = []
    import jax
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: calls.append(("start", d)))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.append(("stop",)))
    c = PC.Capture(str(tmp_path / "tr"), max_chunks=2)
    for _ in range(5):
        c.chunk_done()
    # armed at the FIRST boundary (compile excluded), stopped after
    # its 2-chunk budget, and never re-armed
    assert [k for k, *_ in calls] == ["start", "stop"]
    assert c.chunks == 2 and c.stopped


def test_capture_degrades_when_profiler_refuses(monkeypatch, tmp_path):
    import jax

    def boom(d):
        raise RuntimeError("profiler refused")

    monkeypatch.setattr(jax.profiler, "start_trace", boom)
    c = PC.Capture(str(tmp_path / "tr"))
    c.chunk_done()   # arming fails -> degrade, never raise
    out = c.result()
    assert out["available"] is False
    assert "profiler refused" in out["error"]


def test_capture_result_without_trace(tmp_path):
    c = PC.Capture(str(tmp_path / "tr"))
    out = c.result()   # never armed -> no xplane files
    assert out["available"] is False


# --- publishing + format ---------------------------------------------------

def test_publish_lands_metrics_sections():
    reg = MT.Registry()
    occ = PC.occupancy({"k32": (32, 10), "dense": (64, 2)},
                       events=200, batch=4)
    dev = PC.attribute(
        {"fusion.1": 10 * MS}, {"fusion.1": "jit(f)/jit(main)/drain/x"})
    dev["available"] = True
    PC.publish(reg, occ=occ, dev=dev,
               shards={"skew": 1.5, "per_shard": [0.1, 0.9],
                       "utilization": [0.9, 0.1]})
    snap = reg.snapshot()
    assert snap["occupancy"]["waste_frac"] == occ["waste_frac"]
    # non-digit suffixes stay flat; per-shard indices fold to a list
    assert snap["occupancy"]["rung_passes.k32"] == 10
    assert snap["occupancy"]["shard_skew"] == 1.5
    assert snap["occupancy"]["shard_waste"] == [0.1, 0.9]
    assert snap["device_phases"]["total_ms"] == 10.0
    assert snap["device_phases"]["phase_ms.drain"] == 10.0


def test_format_report_warns_below_floor():
    dev = PC.attribute(
        {"fusion.1": 10 * MS, "mystery.2": 90 * MS},
        {"fusion.1": "jit(f)/jit(main)/drain/x"})
    dev.update(available=True, chunks_traced=3)
    occ = PC.occupancy({"dense": (16, 4)}, events=20, batch=1)
    txt = PC.format_report(dev, occ)
    assert "WARNING" in txt and "mystery.2" in txt
    assert "waste_frac" in txt and "rung dense" in txt
    bad = PC.format_report({"available": False, "error": "nope"}, None)
    assert "unavailable" in bad and "nope" in bad


# --- the pyengine lockstep recount -----------------------------------------

def _recount_scen(n=8, stop=3):
    from test_phold import phold_scenario
    return phold_scenario(n=n, stop=stop)


def test_pyengine_recount_is_state_identical():
    """count_passes only reorders the drain into lockstep passes —
    hosts interact solely at the exchange, so stats must not move."""
    from shadow_tpu.engine.pyengine import PyEngine
    from shadow_tpu.engine.sim import Simulation
    plain = PyEngine(Simulation(_recount_scen())).run()
    eng = PyEngine(Simulation(_recount_scen()), count_passes=True)
    lock = eng.run()
    assert np.array_equal(plain, lock)
    assert eng.pass_mix and sum(eng.pass_mix.values()) > 0
    # 8 hosts: no ladder rung fits (4*32 > 8) -> dense-only passes
    assert set(eng.pass_mix) == {"dense"}


def test_pyengine_recount_occupancy_bounds():
    from shadow_tpu.engine.pyengine import PyEngine
    from shadow_tpu.engine.sim import Simulation
    from shadow_tpu.engine.window import pass_labels, sparse_batch
    sim = Simulation(_recount_scen())
    cfg = sim.cfg
    eng = PyEngine(sim, count_passes=True)
    stats = eng.run()
    widths = dict(pass_labels(cfg, cfg.num_hosts))
    occ = PC.occupancy(
        {lbl: (widths[lbl], n) for lbl, n in eng.pass_mix.items()},
        int(stats[:, 0].sum()), sparse_batch(cfg))
    assert 0.0 <= occ["waste_frac"] <= 1.0
    # a lockstep pass can never run more events than lane-steps
    assert occ["utilization"] <= 1.0


# --- the waste-aware regression gate ---------------------------------------

def _entry(waste=None, rate=1000.0, scenario="s", **kw):
    e = {"scenario": scenario, "platform": "cpu", "fingerprint": "f",
         "events_per_sec": rate, "wall_seconds": 10.0,
         "phases": {"run": 10.0}, "mem_peak_bytes": 10**9}
    if waste is not None:
        e["waste_frac"] = waste
    e.update(kw)
    return e


def _gate(tmp_path, entries, extra=()):
    p = tmp_path / "ledger.jsonl"
    with open(p, "w") as f:
        for e in entries:
            f.write(json.dumps(e) + "\n")
    r = subprocess.run(
        [sys.executable, str(PERF_REGRESS), str(p), "--json",
         *extra], capture_output=True, text=True)
    rows = json.loads(r.stdout)["results"] if r.stdout else []
    return r.returncode, rows


def test_occupancy_gate_flat_trajectory_passes(tmp_path):
    rc, rows = _gate(tmp_path, [_entry(waste=0.30)] * 4)
    assert rc == 0
    assert rows[0]["occ_status"] == "ok"


def test_occupancy_gate_fails_waste_regression(tmp_path):
    # 0.30 history; candidate 0.60 > max(0.30*1.15, 0.35)
    rc, rows = _gate(tmp_path,
                     [_entry(waste=0.30)] * 3 + [_entry(waste=0.60)])
    assert rc == 1
    assert rows[0]["occ_status"] == "REGRESSION"
    assert rows[0]["occ_baseline"] == 0.30


def test_occupancy_gate_absolute_floor_near_zero(tmp_path):
    # near-zero medians: multiplicative band alone would flag 0.01 ->
    # 0.03; the +0.05 absolute floor keeps that noise out
    rc, rows = _gate(tmp_path,
                     [_entry(waste=0.01)] * 3 + [_entry(waste=0.03)])
    assert rc == 0
    assert rows[0]["occ_status"] == "ok"


def test_occupancy_gate_band_widens_with_history_spread(tmp_path):
    # spread [0.2,0.4] -> band capped at 0.5 -> threshold
    # max(0.3*1.5, 0.35) = 0.45: 0.44 passes, 0.46 fails
    hist = [_entry(waste=w) for w in (0.2, 0.3, 0.4)]
    rc, _ = _gate(tmp_path, hist + [_entry(waste=0.44)])
    assert rc == 0
    rc, rows = _gate(tmp_path, hist + [_entry(waste=0.46)])
    assert rc == 1
    assert rows[0]["occ_status"] == "REGRESSION"


def test_occupancy_gate_ignores_pre_passcope_history(tmp_path):
    # waste-less history neither gates nor feeds a baseline; the
    # candidate's own waste waits for a measured trajectory
    rc, rows = _gate(tmp_path,
                     [_entry()] * 3 + [_entry(waste=0.95)])
    assert rc == 0
    assert "occ_status" not in rows[0]


def test_occupancy_gate_compile_bound_exempt(tmp_path):
    # compile-bound entries carry no occupancy signal either
    hist = [_entry(waste=0.30)] * 3
    cand = _entry(waste=0.90, phases={"compile": 9.0},
                  wall_seconds=10.0)
    rc, rows = _gate(tmp_path, hist + [cand])
    assert rc == 0
    assert rows[0]["status"] == "compile-bound"


# --- compiled engine (slow: each adds a cold XLA compile) ------------------

@pytest.mark.slow
def test_passcope_run_emits_pass_table_and_digest_identical(
        tmp_path, monkeypatch):
    """One compiled phold: (a) --passcope produces a decoded pass
    table with stateflow labels or degrades cleanly; (b) the digest
    chain with the profiler armed is byte-identical to a plain run's
    (observation only); (c) occupancy rides the report and summary."""
    from shadow_tpu.engine.sim import Simulation
    monkeypatch.setenv("SHADOW_TPU_PASSCOPE_CHUNKS", "2")
    da, db = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    ra = Simulation(_recount_scen()).run(digest=str(da))
    rb = Simulation(_recount_scen()).run(
        digest=str(db), passcope=str(tmp_path / "tr"))
    assert np.array_equal(ra.stats, rb.stats)
    assert da.read_bytes() == db.read_bytes()
    assert rb.occupancy and 0.0 <= rb.occupancy["waste_frac"] <= 1.0
    assert rb.summary()["waste_frac"] == rb.occupancy["waste_frac"]
    dev = rb.device_phases
    assert dev and "available" in dev
    if dev["available"]:
        assert set(dev["phases"]) <= set(PC.PASS_LABELS)
        # the run dir carries the decoded table for trace_report
        merged = PC.load_json(str(tmp_path / "tr" / "passcope.json"))
        assert merged["device_phases"]["available"] is True
        assert merged["occupancy"]["waste_frac"] == \
            rb.occupancy["waste_frac"]


@pytest.mark.slow
def test_compiled_pass_mix_matches_pyengine_recount():
    """Skewed phold wide enough for the k32 rung: the compiled
    drain's per-rung pass counts equal the python mirror's, so the
    occupancy table is provably the drain's own accounting."""
    from shadow_tpu.engine.pyengine import PyEngine
    from shadow_tpu.engine.sim import Simulation
    from shadow_tpu.engine.window import pass_labels, sparse_batch
    scen = _recount_scen(n=128, stop=2)
    rep = Simulation(scen).run()
    eng = PyEngine(Simulation(scen), count_passes=True)
    py_stats = eng.run()
    assert np.array_equal(rep.stats, py_stats)
    compiled = {lbl: r["passes"]
                for lbl, r in rep.occupancy["per_rung"].items()
                if r["passes"]}
    assert compiled == eng.pass_mix
    cfg = eng.cfg
    widths = dict(pass_labels(cfg, cfg.num_hosts))
    occ = PC.occupancy(
        {lbl: (widths[lbl], n) for lbl, n in eng.pass_mix.items()},
        int(py_stats[:, 0].sum()), sparse_batch(cfg))
    assert occ["waste_frac"] == rep.occupancy["waste_frac"]
