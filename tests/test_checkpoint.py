"""Checkpoint/resume and CLI smoke tests."""

import os

import numpy as np
import pytest

from shadow_tpu.core.config import HostSpec, ProcessSpec, Scenario
from shadow_tpu.engine.sim import Simulation
from shadow_tpu.engine.state import EngineConfig

from test_phold import MESH_TOPO


def scen(stop=6):
    return Scenario(
        stop_time=stop * 10**9,
        topology_graphml=MESH_TOPO,
        hosts=[HostSpec(id="node", quantity=8, processes=[
            ProcessSpec(plugin="phold", start_time=10**9,
                        arguments="port=9000 mean=300ms size=64 init=1")])],
    )


CFG = dict(qcap=16, scap=4, obcap=8, incap=16, chunk_windows=8)


def test_checkpoint_resume_exact(tmp_path):
    path = str(tmp_path / "ck.npz")

    # uninterrupted run
    full = Simulation(scen(), engine_cfg=EngineConfig(num_hosts=8, **CFG)).run()

    # checkpoint mid-run (every simulated 2s), then resume the latest
    first = Simulation(scen(), engine_cfg=EngineConfig(num_hosts=8, **CFG))
    first.run(checkpoint_path=path, checkpoint_every_s=2)

    resumed = Simulation(scen(), engine_cfg=EngineConfig(num_hosts=8, **CFG))
    report = resumed.run(resume_from=path)
    assert np.array_equal(report.stats, full.stats)


def test_checkpoint_rejects_other_scenario(tmp_path):
    path = str(tmp_path / "ck.npz")
    sim = Simulation(scen(), engine_cfg=EngineConfig(num_hosts=8, **CFG))
    sim.run(checkpoint_path=path, checkpoint_every_s=2)

    other = Simulation(scen(stop=9),
                       engine_cfg=EngineConfig(num_hosts=8, **CFG))
    with pytest.raises(ValueError, match="fingerprint"):
        other.run(resume_from=path)


def test_resume_rewinds_digest_chain(tmp_path):
    """Interrupted ≡ uninterrupted at digest-chain level, in-process:
    an uninterrupted run records chain A; a checkpointed run records
    chain B, which we then truncate to the position a crash just
    after a mid-run snapshot would leave; a resumed run rewinds B to
    the snapshot's stamped record count and re-produces the rest —
    the final B must equal A byte for byte. (The subprocess SIGKILL
    variants live in tests/test_until_complete.py.)"""
    dg_a = str(tmp_path / "a.jsonl")
    Simulation(scen(), engine_cfg=EngineConfig(num_hosts=8, **CFG)).run(
        digest=dg_a, digest_every=8)

    base = str(tmp_path / "ck")
    dg_b = str(tmp_path / "b.jsonl")
    Simulation(scen(), engine_cfg=EngineConfig(num_hosts=8, **CFG)).run(
        digest=dg_b, digest_every=8, checkpoint_path=base,
        checkpoint_every_s=2, checkpoint_keep=8)

    # pick a MID-RUN snapshot (not the newest) and cut the chain to
    # one record past its stamped position — the state a kill shortly
    # after that save leaves behind
    from shadow_tpu.engine import checkpoint as ck
    store = ck.CheckpointStore(base)
    snap_path = sorted(store.snapshots())[0]
    n_recs = int(np.load(snap_path)["__digest_records__"])
    lines = open(dg_b).read().splitlines()
    assert n_recs + 1 < len(lines), "snapshot too late for this test"
    with open(dg_b, "w") as f:
        f.write("\n".join(lines[:n_recs + 1]) + "\n")

    resumed = Simulation(scen(),
                         engine_cfg=EngineConfig(num_hosts=8, **CFG))
    report = resumed.run(digest=dg_b, digest_every=8,
                         resume_from=snap_path)
    assert report.windows > 0
    assert open(dg_a, "rb").read() == open(dg_b, "rb").read(), (
        "resumed digest chain differs from the uninterrupted run's")


def test_resume_fresh_chain_opts_out_of_rewind(tmp_path):
    """A divergence replay resumes the SIMULATION from a snapshot but
    records a FRESH chain of the tail only (tools/divergence.py
    --bisect --use-checkpoint). The snapshot stamps the original
    run's record count, so the default rewind must refuse the empty
    file loudly, and `digest_rewind=False` must instead arm the
    cadence from the restored window and record a correct tail."""
    import json

    dg_a = str(tmp_path / "a.jsonl")
    Simulation(scen(), engine_cfg=EngineConfig(num_hosts=8, **CFG)).run(
        digest=dg_a, digest_every=8)

    base = str(tmp_path / "ck")
    Simulation(scen(), engine_cfg=EngineConfig(num_hosts=8, **CFG)).run(
        digest=str(tmp_path / "b.jsonl"), digest_every=8,
        checkpoint_path=base, checkpoint_every_s=2, checkpoint_keep=8)

    from shadow_tpu.engine import checkpoint as ck
    store = ck.CheckpointStore(base)
    snap_path = sorted(store.snapshots())[0]
    snap_w = int(np.load(snap_path)["__windows__"])
    assert int(np.load(snap_path)["__digest_records__"]) > 0

    # default rewind treats the chain as the crashed attempt's own
    # file — a fresh file with a stamped count > 0 must fail loud
    fresh = str(tmp_path / "fresh.jsonl")
    with pytest.raises(ValueError, match="does not belong"):
        Simulation(scen(), engine_cfg=EngineConfig(num_hosts=8, **CFG)).run(
            digest=fresh, digest_every=8, resume_from=snap_path)

    # the replay opt-out: fresh tail-only chain, no rewind
    assert not os.path.exists(fresh)
    report = Simulation(
        scen(), engine_cfg=EngineConfig(num_hosts=8, **CFG)).run(
        digest=fresh, digest_every=8, resume_from=snap_path,
        digest_rewind=False)
    assert report.windows > 0
    recs = [json.loads(l) for l in open(fresh).read().splitlines()]
    assert recs, "replay recorded no tail records"
    assert all(r["window"] > snap_w for r in recs), (
        "a fresh tail chain must not contain pre-snapshot records")
    # the tail's end-of-run record hashes the same final state as the
    # uninterrupted run's (alignment-free equivalence check)
    end_a = [json.loads(l) for l in open(dg_a).read().splitlines()
             if json.loads(l)["kind"] == "final"][-1]
    end_c = [r for r in recs if r["kind"] == "final"][-1]
    assert (end_c["window"], end_c["sim_ns"]) == (
        end_a["window"], end_a["sim_ns"])
    assert end_c["sections"] == end_a["sections"], (
        "replayed tail reached a different final state")


# --- checkpoint store unit tests (no window program: alloc only) ---

def _tiny_hosts():
    from shadow_tpu.engine.state import alloc_hosts
    return alloc_hosts(EngineConfig(num_hosts=2, qcap=4, scap=2,
                                    obcap=4, incap=8))


def test_store_atomicity_kill_mid_save(tmp_path):
    """A kill mid-save leaves only a .tmp (os.replace never ran):
    `latest` still resolves to the prior good snapshot, and the stray
    temp neither resolves nor survives the next save's prune."""
    from shadow_tpu.engine import checkpoint as ck
    hosts = _tiny_hosts()
    store = ck.CheckpointStore(str(tmp_path / "ck.npz"), keep=3)
    good = store.save(hosts, 100, 200, 1, "fp")
    # simulate the torn write a SIGKILL inside save() leaves behind
    torn = str(tmp_path / "ck.w0000000099.npz.tmp")
    with open(torn, "wb") as f:
        f.write(b"\x50\x4b\x03\x04 truncated npz")
    assert ck.resolve_latest(str(tmp_path / "ck.npz")) == good
    snap = ck.load(str(tmp_path / "ck"), hosts, "fp")
    assert (snap.wstart, snap.windows) == (100, 1)
    store.save(hosts, 300, 400, 2, "fp")
    assert not os.path.exists(torn)      # prune collected the stray


def test_store_corrupt_head_falls_back(tmp_path, capsys):
    """A corrupted newest snapshot (hash mismatch) is skipped LOUDLY
    and resume falls back to the previous good one."""
    from shadow_tpu.engine import checkpoint as ck
    hosts = _tiny_hosts()
    store = ck.CheckpointStore(str(tmp_path / "ck"), keep=3)
    prev = store.save(hosts, 100, 200, 1, "fp")
    head = store.save(hosts, 300, 400, 2, "fp")
    with open(head, "r+b") as f:
        f.truncate(64)
    assert ck.resolve_latest(str(tmp_path / "ck")) == prev
    snap = ck.load(str(tmp_path / "ck"), hosts, "fp")
    assert snap.wstart == 100
    err = capsys.readouterr().err
    assert "content hash" in err and "falling back" in err


def test_store_retention(tmp_path):
    from shadow_tpu.engine import checkpoint as ck
    hosts = _tiny_hosts()
    store = ck.CheckpointStore(str(tmp_path / "ck"), keep=2)
    paths = [store.save(hosts, 100 * i, 0, i, "fp")
             for i in range(1, 4)]
    assert not os.path.exists(paths[0])
    assert os.path.exists(paths[1]) and os.path.exists(paths[2])
    assert ck.resolve_latest(str(tmp_path / "ck")) == paths[2]


def test_store_hosted_sidecar_verified(tmp_path):
    """The npz stamps its hosted sidecar's sha (__hosted_sha__): a
    snapshot whose .hosted is corrupted — or deleted, the state a
    kill between sidecar and npz publication can never leave but
    bit-rot can — fails verification and resolve_latest falls back
    to the previous good snapshot instead of letting a hosted resume
    crash-loop on it."""
    from shadow_tpu.engine import checkpoint as ck
    hosts = _tiny_hosts()
    store = ck.CheckpointStore(str(tmp_path / "ck"), keep=3)
    prev = store.save(hosts, 100, 200, 1, "fp", hosted_blob=b"ok-1")
    head = store.save(hosts, 300, 400, 2, "fp", hosted_blob=b"ok-2")
    with open(head + ".hosted", "wb") as f:
        f.write(b"corrupted")
    assert ck.resolve_latest(str(tmp_path / "ck")) == prev
    os.unlink(head + ".hosted")
    assert ck.resolve_latest(str(tmp_path / "ck")) == prev
    snap = ck.load(str(tmp_path / "ck"), hosts, "fp")
    assert snap.wstart == 100 and snap.hosted_blob == b"ok-1"
    # a save without hosted state scrubs any stale sidecar of the
    # same snapshot name and verifies clean
    os.unlink(head)
    again = store.save(hosts, 300, 400, 2, "fp")
    assert again == head and not os.path.exists(head + ".hosted")
    assert ck.resolve_latest(str(tmp_path / "ck")) == head


def test_load_truncated_snapshot_is_diagnosed(tmp_path):
    """A truncated .npz passed DIRECTLY (no sidecar, no store) must
    fail with a clear 'unreadable or truncated' error, not a raw
    zipfile traceback."""
    from shadow_tpu.engine import checkpoint as ck
    hosts = _tiny_hosts()
    store = ck.CheckpointStore(str(tmp_path / "ck"), keep=3)
    f = store.save(hosts, 100, 200, 1, "fp")
    os.unlink(f + ".sha256")             # direct load path, unverified
    with open(f, "r+b") as fh:
        fh.truncate(128)
    with pytest.raises(ValueError, match="unreadable or truncated"):
        ck.load(f, hosts, "fp")


def test_shape_mismatch_always_hard_error(tmp_path):
    """The layout check precedes the fingerprint check: even with
    strict=False (resume_unchecked), a snapshot from a different
    engine shape errors with BOTH shapes in the message — never a
    softened warning."""
    from shadow_tpu.engine import checkpoint as ck
    from shadow_tpu.engine.state import alloc_hosts
    hosts = _tiny_hosts()
    store = ck.CheckpointStore(str(tmp_path / "ck"), keep=3)
    f = store.save(hosts, 100, 200, 1, "fp")
    other = alloc_hosts(EngineConfig(num_hosts=2, qcap=8, scap=2,
                                     obcap=4, incap=8))
    with pytest.raises(ValueError) as ei:
        ck.load(f, other, "DIFFERENT-FP", strict=False)
    msg = str(ei.value)
    assert "layout mismatch" in msg
    assert "(2, 4" in msg and "(2, 8" in msg    # both shapes named


def test_cli_test_scenario_smoke(capsys):
    """`python -m shadow_tpu --test` at reduced scale."""
    from shadow_tpu.__main__ import main

    rc = main(["--test", "--test-clients", "4", "--stop-time", "12s",
               "--heartbeat-frequency", "5", "--summary-json"])
    assert rc == 0
    out = capsys.readouterr().out
    import json
    summary = json.loads(out.strip().splitlines()[-1])
    assert summary["transfers_done"] > 0
    assert "[shadow-heartbeat]" in out


def test_presplit_checkpoint_resumes_into_split_layout(tmp_path):
    """Cross-version determinism across the hot/cold split: a
    checkpoint written by the PRE-split engine (hot_split=0 full-tree
    drain, the old event_batch=8 default — bit-exact stand-in for the
    pre-split binary) must load into the split engine (same array
    layout, same semantic fingerprint: both knobs are in
    checkpoint._PERF_ONLY_KNOBS) and the resumed digest chain must
    byte-equal an uninterrupted SPLIT run's chain."""
    import numpy as np

    pre_cfg = EngineConfig(num_hosts=8, hot_split=0, event_batch=8,
                           **CFG)
    post_cfg = EngineConfig(num_hosts=8, **CFG)

    # uninterrupted run on the SPLIT engine records chain A
    dg_a = str(tmp_path / "a.jsonl")
    Simulation(scen(), engine_cfg=post_cfg).run(digest=dg_a,
                                                digest_every=8)

    # the pre-split engine checkpoints mid-run, recording chain B
    base = str(tmp_path / "ck")
    dg_b = str(tmp_path / "b.jsonl")
    Simulation(scen(), engine_cfg=pre_cfg).run(
        digest=dg_b, digest_every=8, checkpoint_path=base,
        checkpoint_every_s=2, checkpoint_keep=8)

    from shadow_tpu.engine import checkpoint as ck
    store = ck.CheckpointStore(base)
    snap_path = sorted(store.snapshots())[0]
    n_recs = int(np.load(snap_path)["__digest_records__"])
    lines = open(dg_b).read().splitlines()
    assert n_recs + 1 < len(lines), "snapshot too late for this test"
    with open(dg_b, "w") as f:
        f.write("\n".join(lines[:n_recs + 1]) + "\n")

    # resume on the SPLIT engine: the semantic fingerprint must match
    # (no strict=False escape hatch involved) and the finished chain
    # must equal the uninterrupted split run's byte for byte
    report = Simulation(scen(), engine_cfg=post_cfg).run(
        digest=dg_b, digest_every=8, resume_from=snap_path)
    assert report.windows > 0
    assert open(dg_a, "rb").read() == open(dg_b, "rb").read(), (
        "pre-split checkpoint resumed under the split engine diverged")


def test_fingerprint_ignores_perf_only_knobs():
    """The checkpoint fingerprint binds to shapes and semantics, not
    to the bit-exact perf knobs — and DOES bind to everything else."""
    import dataclasses as dc

    from shadow_tpu.engine.checkpoint import (_PERF_ONLY_KNOBS,
                                              scenario_fingerprint)

    s = scen()
    base_cfg = EngineConfig(num_hosts=8, **CFG)
    fp = scenario_fingerprint(s, base_cfg, 1)
    for knob, val in (("hot_split", 0), ("event_batch", 32),
                      ("active_block", 512), ("exsortcap", 64),
                      ("dstcap", 4)):
        assert knob in _PERF_ONLY_KNOBS
        cfg2 = dc.replace(base_cfg, **{knob: val})
        assert scenario_fingerprint(s, cfg2, 1) == fp, knob
    # semantic knobs still bind
    assert scenario_fingerprint(
        s, dc.replace(base_cfg, qcap=CFG["qcap"] * 2), 1) != fp
    assert scenario_fingerprint(
        s, dc.replace(base_cfg, uses_tcp=False), 1) != fp
    assert scenario_fingerprint(s, base_cfg, 2) != fp
