"""Checkpoint/resume and CLI smoke tests."""

import numpy as np
import pytest

from shadow_tpu.core.config import HostSpec, ProcessSpec, Scenario
from shadow_tpu.engine.sim import Simulation
from shadow_tpu.engine.state import EngineConfig

from test_phold import MESH_TOPO


def scen(stop=6):
    return Scenario(
        stop_time=stop * 10**9,
        topology_graphml=MESH_TOPO,
        hosts=[HostSpec(id="node", quantity=8, processes=[
            ProcessSpec(plugin="phold", start_time=10**9,
                        arguments="port=9000 mean=300ms size=64 init=1")])],
    )


CFG = dict(qcap=16, scap=4, obcap=8, incap=16, chunk_windows=8)


def test_checkpoint_resume_exact(tmp_path):
    path = str(tmp_path / "ck.npz")

    # uninterrupted run
    full = Simulation(scen(), engine_cfg=EngineConfig(num_hosts=8, **CFG)).run()

    # checkpoint mid-run (every simulated 2s), then resume the latest
    first = Simulation(scen(), engine_cfg=EngineConfig(num_hosts=8, **CFG))
    first.run(checkpoint_path=path, checkpoint_every_s=2)

    resumed = Simulation(scen(), engine_cfg=EngineConfig(num_hosts=8, **CFG))
    report = resumed.run(resume_from=path)
    assert np.array_equal(report.stats, full.stats)


def test_checkpoint_rejects_other_scenario(tmp_path):
    path = str(tmp_path / "ck.npz")
    sim = Simulation(scen(), engine_cfg=EngineConfig(num_hosts=8, **CFG))
    sim.run(checkpoint_path=path, checkpoint_every_s=2)

    other = Simulation(scen(stop=9),
                       engine_cfg=EngineConfig(num_hosts=8, **CFG))
    with pytest.raises(ValueError, match="fingerprint"):
        other.run(resume_from=path)


def test_cli_test_scenario_smoke(capsys):
    """`python -m shadow_tpu --test` at reduced scale."""
    from shadow_tpu.__main__ import main

    rc = main(["--test", "--test-clients", "4", "--stop-time", "12s",
               "--heartbeat-frequency", "5", "--summary-json"])
    assert rc == 0
    out = capsys.readouterr().out
    import json
    summary = json.loads(out.strip().splitlines()[-1])
    assert summary["transfers_done"] > 0
    assert "[shadow-heartbeat]" in out
