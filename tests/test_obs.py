"""Observability tests: heartbeat tracker, pcap capture, logger."""

import struct

import numpy as np

from shadow_tpu.core.config import HostSpec, ProcessSpec, Scenario
from shadow_tpu.engine.sim import Simulation
from shadow_tpu.engine.state import EngineConfig
from shadow_tpu.obs.logger import SimLogger

from test_phold import MESH_TOPO


def scen(pcap=False, stop=4):
    return Scenario(
        stop_time=stop * 10**9,
        topology_graphml=MESH_TOPO,
        hosts=[
            HostSpec(id="srv", pcap=pcap, processes=[
                ProcessSpec(plugin="pingserver", start_time=10**9,
                            arguments="port=8000")]),
            HostSpec(id="cli", pcap=pcap, processes=[
                ProcessSpec(plugin="ping", start_time=2 * 10**9,
                            arguments="peer=srv port=8000 interval=500ms "
                                      "size=100 count=3")]),
        ],
    )


CFG = dict(qcap=16, scap=4, obcap=8, incap=16, chunk_windows=8)


def test_heartbeat_lines():
    sim = Simulation(scen(stop=6), engine_cfg=EngineConfig(num_hosts=2, **CFG))
    report = sim.run(heartbeat_s=1.0)
    node_lines = [l for l in report.heartbeats if "[node]" in l]
    summaries = [l for l in report.heartbeats if "[summary]" in l]
    # several intervals may elapse within one window chunk; each
    # summary then carries the covered span in its interval= field,
    # and the spans tile the whole simulated time
    assert summaries
    spans = [int(l.split("interval=")[1].split(",")[0]) for l in summaries]
    assert sum(spans) >= 5
    assert any(",cli," in l for l in node_lines)
    # [socket] lines: ping's UDP sockets appear with peer and buffers
    sock_lines = [l for l in report.heartbeats if "[socket]" in l]
    assert any(",cli," in l and "udp" in l for l in sock_lines)


def test_heartbeat_socket_ram_tcp():
    """TCP heartbeats carry tcp [socket] segments and, while the send
    buffer holds unacked bytes, per-host [ram] occupancy lines
    (the reference's per-socket buffer-fill + allocated-RAM heartbeat,
    shd-tracker.c:449-546)."""
    from test_tcp import bulk_scenario, poi_topology
    sim = Simulation(
        bulk_scenario(poi_topology(bw_up=1024), size=400_000, count=1,
                      stop=8),
        engine_cfg=EngineConfig(num_hosts=2, qcap=16, scap=4, obcap=32,
                                incap=32, chunk_windows=8))
    report = sim.run(heartbeat_s=0.5)
    sock_lines = [l for l in report.heartbeats if "[socket]" in l]
    assert any("tcp" in l for l in sock_lines)
    ram_lines = [l for l in report.heartbeats if "[ram]" in l]
    # the 400 KB push over a 1 MB/s uplink keeps unacked bytes in the
    # send buffer across several 0.5s intervals
    assert ram_lines
    # schema: t,host,alloc,dealloc,total,sockets — total > 0 somewhere
    assert any(int(l.split(",")[4]) > 0 for l in ram_lines)
    # parse tool roundtrip
    import subprocess, sys, tempfile, os
    with tempfile.NamedTemporaryFile("w", suffix=".log", delete=False) as f:
        f.write("\n".join(report.heartbeats))
        path = f.name
    out = subprocess.run(
        [sys.executable, "tools/parse_heartbeat.py", path],
        capture_output=True, text=True, check=True).stdout
    assert out.splitlines()[0].startswith("time,host")
    assert any("cli" in l for l in out.splitlines()[1:])
    os.unlink(path)


def test_pcap_capture(tmp_path):
    sim = Simulation(scen(pcap=True),
                     engine_cfg=EngineConfig(num_hosts=2, **CFG))
    assert sim.cfg.tracecap > 0  # auto-sized because logpcap is set
    sim.run(pcap_dir=str(tmp_path))

    cli = tmp_path / "cli-eth0.pcap"
    srv = tmp_path / "srv-eth0.pcap"
    assert cli.exists() and srv.exists()

    data = cli.read_bytes()
    magic, _, _, _, _, snaplen, network = struct.unpack("<IHHiIII",
                                                        data[:24])
    assert magic == 0xA1B2C3D4
    assert network == 1  # Ethernet
    # walk the records: client sent 3 pings (tx) and got 3 echoes (rx)
    off, n, lens = 24, 0, []
    while off < len(data):
        ts, tus, incl, orig = struct.unpack("<IIII", data[off:off + 16])
        lens.append(orig)
        off += 16 + incl
        n += 1
    assert n == 6
    # udp: 14 eth + 20 ip + 8 udp + 100 payload
    assert all(l == 142 for l in lens)


def test_logger_levels(capsys):
    lg = SimLogger(level="message")
    lg.message(1_500_000_000, "hostA", "hello")
    lg.debug(2_000_000_000, "hostA", "invisible")
    lg.set_host_level("chatty", "debug")
    lg.debug(2_000_000_000, "chatty", "visible")
    out = capsys.readouterr().out
    assert "hello" in out and "0:00:01.500000000" in out
    assert "invisible" not in out
    assert "visible" in out


def test_capacity_report(simple_topology_xml):
    """End-of-run capacity accounting (the ObjectCounter analogue):
    peaks reflect real occupancy and no overflow on a healthy run."""
    from shadow_tpu.core.config import HostSpec, ProcessSpec, Scenario
    from shadow_tpu.engine.sim import Simulation

    scen = Scenario(
        stop_time=5 * 10**9,
        topology_graphml=simple_topology_xml,
        hosts=[
            HostSpec(id="server", processes=[
                ProcessSpec(plugin="pingserver", start_time=10**9,
                            arguments="port=9000")]),
            HostSpec(id="client", processes=[
                ProcessSpec(plugin="ping", start_time=10**9,
                            arguments="peer=server port=9000 "
                                      "interval=100ms count=10")]),
        ],
    )
    report = Simulation(scen).run()
    rows = {r["array"]: r for r in report.capacity_report()}
    assert set(rows) == {"event_queue", "socket_table", "outbox",
                         "nic_txq"}
    # the ping exchange touched the queue, sockets and outbox
    assert rows["event_queue"]["peak"] >= 1
    assert rows["socket_table"]["peak"] >= 1
    assert rows["outbox"]["peak"] >= 1
    for r in rows.values():
        assert r["peak"] <= r["capacity"]
        assert r["overflow"] == 0


def test_delivery_status_trail(tmp_path):
    """Packets carry the reference's delivery-status trail
    (shd-packet.h:15-36 recast as a bitmask word): trace records show
    the lifecycle stages each packet passed through."""
    import numpy as np
    from shadow_tpu.net import packet as P

    sim = Simulation(scen(pcap=True),
                     engine_cfg=None)
    sim.run()  # no pcap_dir: trace rings retain the records
    h = sim.final_hosts
    cnt = np.asarray(h.tr_cnt)
    assert cnt.sum() > 0
    pkts = np.asarray(h.tr_pkt)
    dirs = np.asarray(h.tr_dir)
    saw_tx = saw_rx = False
    for hid in range(cnt.shape[0]):
        for k in range(cnt[hid]):
            st = int(pkts[hid, k, P.STATUS])
            names = P.status_names(st)
            assert "created" in names
            assert "nic-sent" in names
            assert "inet" in names  # exchange-traced = cross-host
            if dirs[hid, k] == 1:
                saw_tx = True
            else:
                saw_rx = True
    assert saw_tx and saw_rx
