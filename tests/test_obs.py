"""Observability tests: span tracing, metrics registry, heartbeat
tracker, pcap capture, logger."""

import json
import struct

import numpy as np
import pytest

from shadow_tpu.core.config import HostSpec, ProcessSpec, Scenario
from shadow_tpu.engine.sim import Simulation
from shadow_tpu.engine.state import EngineConfig
from shadow_tpu.obs import metrics as M
from shadow_tpu.obs import trace as T
from shadow_tpu.obs.logger import SimLogger

from test_phold import MESH_TOPO


@pytest.fixture(autouse=True)
def _obs_globals_reset():
    """The trace/metrics recorders are process-global; a test that
    fails mid-install must not leak an enabled recorder into the next
    test."""
    yield
    T.finish()
    M.finish()


def scen(pcap=False, stop=4):
    return Scenario(
        stop_time=stop * 10**9,
        topology_graphml=MESH_TOPO,
        hosts=[
            HostSpec(id="srv", pcap=pcap, processes=[
                ProcessSpec(plugin="pingserver", start_time=10**9,
                            arguments="port=8000")]),
            HostSpec(id="cli", pcap=pcap, processes=[
                ProcessSpec(plugin="ping", start_time=2 * 10**9,
                            arguments="peer=srv port=8000 interval=500ms "
                                      "size=100 count=3")]),
        ],
    )


CFG = dict(qcap=16, scap=4, obcap=8, incap=16, chunk_windows=8)


def test_heartbeat_lines():
    sim = Simulation(scen(stop=6), engine_cfg=EngineConfig(num_hosts=2, **CFG))
    report = sim.run(heartbeat_s=1.0)
    node_lines = [l for l in report.heartbeats if "[node]" in l]
    summaries = [l for l in report.heartbeats if "[summary]" in l]
    # several intervals may elapse within one window chunk; each
    # summary then carries the covered span in its interval= field,
    # and the spans tile the whole simulated time
    assert summaries
    spans = [int(l.split("interval=")[1].split(",")[0]) for l in summaries]
    assert sum(spans) >= 5
    assert any(",cli," in l for l in node_lines)
    # [socket] lines: ping's UDP sockets appear with peer and buffers
    sock_lines = [l for l in report.heartbeats if "[socket]" in l]
    assert any(",cli," in l and "udp" in l for l in sock_lines)


def test_heartbeat_socket_ram_tcp():
    """TCP heartbeats carry tcp [socket] segments and, while the send
    buffer holds unacked bytes, per-host [ram] occupancy lines
    (the reference's per-socket buffer-fill + allocated-RAM heartbeat,
    shd-tracker.c:449-546)."""
    from test_tcp import bulk_scenario, poi_topology
    sim = Simulation(
        bulk_scenario(poi_topology(bw_up=1024), size=400_000, count=1,
                      stop=8),
        engine_cfg=EngineConfig(num_hosts=2, qcap=16, scap=4, obcap=32,
                                incap=32, chunk_windows=8))
    report = sim.run(heartbeat_s=0.5)
    sock_lines = [l for l in report.heartbeats if "[socket]" in l]
    assert any("tcp" in l for l in sock_lines)
    ram_lines = [l for l in report.heartbeats if "[ram]" in l]
    # the 400 KB push over a 1 MB/s uplink keeps unacked bytes in the
    # send buffer across several 0.5s intervals
    assert ram_lines
    # schema: t,host,alloc,dealloc,total,sockets — total > 0 somewhere
    assert any(int(l.split(",")[4]) > 0 for l in ram_lines)
    # parse tool roundtrip
    import subprocess, sys, tempfile, os
    with tempfile.NamedTemporaryFile("w", suffix=".log", delete=False) as f:
        f.write("\n".join(report.heartbeats))
        path = f.name
    out = subprocess.run(
        [sys.executable, "tools/parse_heartbeat.py", path],
        capture_output=True, text=True, check=True).stdout
    assert out.splitlines()[0].startswith("time,host")
    assert any("cli" in l for l in out.splitlines()[1:])
    os.unlink(path)


def test_pcap_capture(tmp_path):
    sim = Simulation(scen(pcap=True),
                     engine_cfg=EngineConfig(num_hosts=2, **CFG))
    assert sim.cfg.tracecap > 0  # auto-sized because logpcap is set
    sim.run(pcap_dir=str(tmp_path))

    cli = tmp_path / "cli-eth0.pcap"
    srv = tmp_path / "srv-eth0.pcap"
    assert cli.exists() and srv.exists()

    data = cli.read_bytes()
    magic, _, _, _, _, snaplen, network = struct.unpack("<IHHiIII",
                                                        data[:24])
    assert magic == 0xA1B2C3D4
    assert network == 1  # Ethernet
    # walk the records: client sent 3 pings (tx) and got 3 echoes (rx)
    off, n, lens = 24, 0, []
    while off < len(data):
        ts, tus, incl, orig = struct.unpack("<IIII", data[off:off + 16])
        lens.append(orig)
        off += 16 + incl
        n += 1
    assert n == 6
    # udp: 14 eth + 20 ip + 8 udp + 100 payload
    assert all(l == 142 for l in lens)


def test_trace_span_nesting(tmp_path):
    """Nested spans flush as valid Chrome trace-event JSON: complete
    ("X") events with µs ts/dur, children contained in parents, args
    preserved."""
    path = str(tmp_path / "t.json")
    T.install(path)
    with T.span("outer", kind="test"):
        with T.span("inner"):
            pass
        t0 = T.TRACER.now()
        T.TRACER.complete("hot", t0, args={"n": 3})
    T.finish()
    assert not T.ENABLED and T.TRACER is None

    doc = json.load(open(path))
    evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    by_name = {e["name"]: e for e in evs}
    assert set(by_name) == {"outer", "inner", "hot"}
    outer, inner = by_name["outer"], by_name["inner"]
    assert outer["ts"] <= inner["ts"]
    assert (inner["ts"] + inner["dur"]
            <= outer["ts"] + outer["dur"] + 1e-6)
    assert outer["args"] == {"kind": "test"}
    assert by_name["hot"]["args"] == {"n": 3}
    # metadata names the process for Perfetto
    assert any(e.get("ph") == "M" for e in doc["traceEvents"])


def test_trace_disabled_span_is_noop(tmp_path):
    """With nothing installed the module stays disabled and span() is
    a pass-through — the contract the hot-loop boolean guards rely
    on."""
    assert not T.ENABLED
    with T.span("never"):
        pass
    assert T.TRACER is None


def test_metrics_registry_semantics():
    """Counter/gauge/histogram semantics and the snapshot shape."""
    reg = M.install()
    try:
        reg.counter("a").inc()
        reg.counter("a").inc(4)
        reg.gauge("g").set(2.5)
        h = reg.histogram("h", bounds=(10, 100))
        for v in (1, 9, 10, 11, 250):
            h.observe(v)
        M.shim_op("send", 5_000)     # 5 µs
        M.shim_op("send", 7_000)
        snap = reg.snapshot()
    finally:
        M.finish()
    assert snap["counters"]["a"] == 5
    assert snap["gauges"]["g"] == 2.5
    hs = snap["histograms"]["h"]
    assert hs["count"] == 5 and hs["min"] == 1 and hs["max"] == 250
    assert hs["sum"] == 281
    # bisect_left semantics: <=10 in the first bucket, 11 in the
    # second, 250 overflows
    assert hs["buckets"] == {"le_10": 3, "le_100": 1, "overflow": 1}
    # the shim per-op aggregation view
    assert snap["shim"]["ops"] == {"send": 2}
    lat = snap["shim"]["op_latency_us"]["send"]
    assert lat["count"] == 2 and 5 <= lat["mean"] <= 7


def test_run_trace_metrics_smoke(tmp_path):
    """A small ping run with trace+metrics produces (a) a loadable
    trace with >= 4 distinct span names whose chunk spans carry
    sim_ns_start/sim_ns_end/events args, and (b) a metrics snapshot
    with events/sec, wall per sim-second and the shim section — the
    PR's acceptance shape."""
    tr_path = str(tmp_path / "trace.json")
    mt_path = str(tmp_path / "metrics.json")
    sim = Simulation(scen(stop=6),
                     engine_cfg=EngineConfig(num_hosts=2, **CFG))
    report = sim.run(heartbeat_s=1.0, trace=tr_path, metrics=mt_path)
    # recorders are torn down with the run
    assert not T.ENABLED and not M.ENABLED

    doc = json.load(open(tr_path))
    evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    names = {e["name"] for e in evs}
    assert {"chunk", "compile+first_chunk", "run.setup",
            "report.finalize", "tracker.heartbeat"} <= names
    assert len(names) >= 4, names
    chunks = [e for e in evs if e["name"] == "chunk"]
    assert chunks
    for c in chunks:
        a = c["args"]
        assert {"sim_ns_start", "sim_ns_end", "windows",
                "events"} <= set(a)
        assert a["sim_ns_end"] >= a["sim_ns_start"]
    # chunk events tally with the report
    assert sum(c["args"]["events"] for c in chunks) == report.events
    assert sum(c["args"]["windows"] for c in chunks) == report.windows

    snap = json.load(open(mt_path))
    assert snap["sim"]["events"] == report.events
    assert snap["sim"]["events_per_sec"] > 0
    assert "wall_per_sim_second" in snap["sim"]
    assert "ops" in snap["shim"]            # present (empty: no shim)
    assert snap["counters"]["engine.windows"] == report.windows
    # tracker heartbeats surface through the registry
    assert snap["counters"]["tracker.heartbeats"] >= 1
    assert snap["counters"]["tracker.lines"] == len(report.heartbeats)

    # per-chunk JSON lines parse and tile the run
    lines = [json.loads(l) for l in
             open(mt_path + ".chunks.jsonl").read().splitlines()]
    assert len(lines) == snap["counters"]["engine.chunks"]
    assert sum(l["events"] for l in lines) == report.events


def test_trace_report_tool(tmp_path):
    """tools/trace_report.py end-to-end on a real run's trace: the
    headless CPU path the CI satellite asks for."""
    import os
    import subprocess
    import sys
    tr_path = str(tmp_path / "trace.json")
    sim = Simulation(scen(stop=4),
                     engine_cfg=EngineConfig(num_hosts=2, **CFG))
    sim.run(trace=tr_path)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "tools/trace_report.py"),
         tr_path],
        capture_output=True, text=True, check=True).stdout
    assert "top spans by self-time" in out
    assert "chunk" in out
    assert "wall per sim-second" in out
    # --json mode round-trips
    js = subprocess.run(
        [sys.executable, os.path.join(repo, "tools/trace_report.py"),
         tr_path, "--json"],
        capture_output=True, text=True, check=True).stdout
    rep = json.loads(js)
    assert rep["chunks"] and rep["spans"]
    assert any(s["name"] == "chunk" for s in rep["spans"])


def test_trace_report_bad_input(tmp_path):
    """Missing / empty / truncated / non-trace input: a one-line
    diagnosis on stderr and a nonzero exit — never a traceback
    (headless tool robustness satellite)."""
    import os
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tool = os.path.join(repo, "tools/trace_report.py")
    empty = tmp_path / "empty.json"
    empty.write_text("")
    trunc = tmp_path / "trunc.json"
    trunc.write_text('{"traceEvents": [{"name": "chunk", "ph": "X"')
    nontrace = tmp_path / "nontrace.json"
    nontrace.write_text('{"foo": 1}')
    noevents = tmp_path / "noevents.json"
    noevents.write_text('{"traceEvents": []}')
    for bad in (str(tmp_path / "missing.json"), str(empty),
                str(trunc), str(nontrace), str(noevents)):
        out = subprocess.run([sys.executable, tool, bad],
                             capture_output=True, text=True)
        assert out.returncode != 0, bad
        assert "Traceback" not in out.stderr, (bad, out.stderr)
        msg = out.stderr.strip()
        assert msg.startswith("trace_report:") and "\n" not in msg, bad


def test_pyengine_trace_and_metrics(tmp_path):
    """The differential oracle's event loop shows up on the same
    timeline (pyengine.window spans) and in the registry."""
    from shadow_tpu.engine.pyengine import PyEngine
    path = str(tmp_path / "py.json")
    T.install(path)
    reg = M.install()
    try:
        sim = Simulation(scen(stop=4),
                         engine_cfg=EngineConfig(num_hosts=2, **CFG))
        stats = PyEngine(sim).run()
    finally:
        tr = T.finish()
        M.finish()
    names = [e["name"] for e in tr.events]
    assert "pyengine.window" in names
    from shadow_tpu.engine import defs
    ev = int(stats[:, defs.ST_EVENTS].sum())
    assert reg.counters["pyengine.events"].n == ev
    assert reg.counters["pyengine.windows"].n > 0


def test_logger_levels(capsys):
    lg = SimLogger(level="message")
    lg.message(1_500_000_000, "hostA", "hello")
    lg.debug(2_000_000_000, "hostA", "invisible")
    lg.set_host_level("chatty", "debug")
    lg.debug(2_000_000_000, "chatty", "visible")
    out = capsys.readouterr().out
    assert "hello" in out and "0:00:01.500000000" in out
    assert "invisible" not in out
    assert "visible" in out


def test_capacity_report(simple_topology_xml):
    """End-of-run capacity accounting (the ObjectCounter analogue):
    peaks reflect real occupancy and no overflow on a healthy run."""
    from shadow_tpu.core.config import HostSpec, ProcessSpec, Scenario
    from shadow_tpu.engine.sim import Simulation

    scen = Scenario(
        stop_time=5 * 10**9,
        topology_graphml=simple_topology_xml,
        hosts=[
            HostSpec(id="server", processes=[
                ProcessSpec(plugin="pingserver", start_time=10**9,
                            arguments="port=9000")]),
            HostSpec(id="client", processes=[
                ProcessSpec(plugin="ping", start_time=10**9,
                            arguments="peer=server port=9000 "
                                      "interval=100ms count=10")]),
        ],
    )
    report = Simulation(scen).run()
    rows = {r["array"]: r for r in report.capacity_report()}
    assert set(rows) == {"event_queue", "socket_table", "outbox",
                         "nic_txq"}
    # the ping exchange touched the queue, sockets and outbox
    assert rows["event_queue"]["peak"] >= 1
    assert rows["socket_table"]["peak"] >= 1
    assert rows["outbox"]["peak"] >= 1
    for r in rows.values():
        assert r["peak"] <= r["capacity"]
        assert r["overflow"] == 0


def test_delivery_status_trail(tmp_path):
    """Packets carry the reference's delivery-status trail
    (shd-packet.h:15-36 recast as a bitmask word): trace records show
    the lifecycle stages each packet passed through."""
    import numpy as np
    from shadow_tpu.net import packet as P

    sim = Simulation(scen(pcap=True),
                     engine_cfg=None)
    sim.run()  # no pcap_dir: trace rings retain the records
    h = sim.final_hosts
    cnt = np.asarray(h.tr_cnt)
    assert cnt.sum() > 0
    pkts = np.asarray(h.tr_pkt)
    dirs = np.asarray(h.tr_dir)
    saw_tx = saw_rx = False
    for hid in range(cnt.shape[0]):
        for k in range(cnt[hid]):
            st = int(pkts[hid, k, P.STATUS])
            names = P.status_names(st)
            assert "created" in names
            assert "nic-sent" in names
            assert "inet" in names  # exchange-traced = cross-host
            if dirs[hid, k] == 1:
                saw_tx = True
            else:
                saw_rx = True
    assert saw_tx and saw_rx
