"""Unit tests for the SACK scoreboard range-set primitives (net.sack)
— the vectorized redesign of the reference's shd-tcp-scoreboard.c."""

import jax.numpy as jnp
import numpy as np
import pytest

from shadow_tpu.net import sack
from shadow_tpu.core.constants import TCP_MSS


def ranges(s, e):
    """Concrete [(start, end), ...] of non-empty slots."""
    s, e = np.asarray(s), np.asarray(e)
    return [(int(a), int(b)) for a, b in zip(s, e) if a >= 0]


def build(*rs):
    s, e = sack.empty()
    for a, b in rs:
        s, e = sack.insert(s, e, jnp.int64(a), jnp.int64(b))
    return s, e


def test_insert_disjoint_sorted():
    s, e = build((300, 400), (100, 200))
    assert ranges(s, e) == [(100, 200), (300, 400)]


def test_insert_merges_overlap_and_touch():
    s, e = build((100, 200), (200, 250))          # touching merges
    assert ranges(s, e) == [(100, 250)]
    s, e = build((100, 200), (300, 400), (150, 350))  # bridges both
    assert ranges(s, e) == [(100, 400)]


def test_insert_noop_on_empty_range():
    s, e = build((100, 200))
    s2, e2 = sack.insert(s, e, jnp.int64(-1), jnp.int64(-2))
    assert ranges(s2, e2) == [(100, 200)]


def test_insert_overflow_drops_highest():
    s, e = build((100, 110), (200, 210), (300, 310), (400, 410),
                 (500, 510))
    assert len(ranges(s, e)) == sack.K
    assert ranges(s, e)[0] == (100, 110)
    assert (500, 510) not in ranges(s, e)


def test_consume_chain():
    s, e = build((200, 300), (400, 500))
    s2, e2, rcv = sack.consume(s, e, jnp.int64(250))
    # cursor lands inside the first range: absorbs it, stops before 400
    assert int(rcv) == 300
    assert ranges(s2, e2) == [(400, 500)]
    # an arrival bridging into the second range absorbs it too
    s3, e3, rcv2 = sack.consume(s2, e2, jnp.int64(420))
    assert int(rcv2) == 500
    assert ranges(s3, e3) == []


def test_drop_below_prunes_and_clips():
    s, e = build((100, 200), (300, 400))
    s2, e2 = sack.drop_below(s, e, jnp.int64(350))
    assert ranges(s2, e2) == [(350, 400)]


def test_skip_and_next_start():
    s, e = build((100, 200), (300, 400))
    assert int(sack.skip(jnp.int64(150), s, e)) == 200
    assert int(sack.skip(jnp.int64(250), s, e)) == 250
    assert int(sack.next_start_after(jnp.int64(150), s, e)) == 300
    assert int(sack.next_start_after(jnp.int64(350), s, e)) > 10**17


def test_wire_roundtrip_aligned():
    m = TCP_MSS
    ack = jnp.int64(10 * m)
    s, e = build((12 * m, 14 * m), (20 * m, 21 * m))
    b1, b2 = sack.encode2(s, e, ack)
    hi = jnp.int64(100 * m)
    d1s, d1e = sack.decode(jnp.int32(b1), ack, hi)
    d2s, d2e = sack.decode(jnp.int32(b2), ack, hi)
    assert (int(d1s), int(d1e)) == (12 * m, 14 * m)
    assert (int(d2s), int(d2e)) == (20 * m, 21 * m)


def test_wire_never_overclaims_when_misaligned():
    m = TCP_MSS
    ack = jnp.int64(0)
    true_s, true_e = 3 * m + 7, 6 * m + 11   # misaligned edges
    s, e = build((true_s, true_e))
    b1, _ = sack.encode2(s, e, ack)
    ds, de = sack.decode(jnp.int32(b1), ack, jnp.int64(100 * m))
    assert int(ds) >= true_s            # never claims earlier bytes
    assert int(de) <= true_e            # never claims later bytes
    assert int(de) > int(ds)            # still useful


def test_wire_finack_bit_does_not_corrupt_block():
    m = TCP_MSS
    s, e = build((2 * m, 4 * m))
    b1, _ = sack.encode2(s, e, jnp.int64(0))
    word = jnp.int32(b1 | 1)            # FINACK flag shares the word
    ds, de = sack.decode(word, jnp.int64(0), jnp.int64(100 * m))
    assert (int(ds), int(de)) == (2 * m, 4 * m)


def test_wire_no_block_beyond_offset_field():
    """A range starting beyond the 15-bit MSS offset field must emit NO
    block — a clipped start would advertise bytes the receiver lacks."""
    m = TCP_MSS
    far = (0x7FFF + 100) * m
    s, e = build((far, far + 10 * m))
    b1, b2 = sack.encode2(s, e, jnp.int64(0))
    assert int(b1) == 0 and int(b2) == 0


def test_lost_bound():
    m = TCP_MSS
    s, e = build((5 * m, 8 * m))
    una = jnp.int64(2 * m)
    hole = jnp.int64(50 * m)
    assert int(sack.lost_bound(s, e, una, hole)) == 8 * m
    s0, e0 = sack.empty()
    assert int(sack.lost_bound(s0, e0, una, hole)) == 3 * m
    assert int(sack.lost_bound(s, e, una, jnp.int64(6 * m))) == 6 * m


def test_batched_skip_matches_rowwise():
    m = TCP_MSS
    s1, e1 = build((100, 200))
    s2, e2 = build((300, 400), (500, 600))
    S = jnp.stack([s1, s2])
    E = jnp.stack([e1, e2])
    x = jnp.asarray([150, 350], jnp.int64)
    out = sack.skip(x, S, E)
    assert out.tolist() == [200, 400]
