"""Round-5 shim libc surface: poll/select, virtual time & sleep,
deterministic entropy, loud pthread_create refusal.

The reference's general libc emulation (process_emu_* backends,
/root/reference/src/main/host/shd-process.c:1821-7449) is what lets
arbitrary unmodified binaries run deterministically inside the sim.
These tests drive the round-5 additions through REAL compiled binaries
(examples/plugins/pollclient.c, libcprobe.c — plain libc, no simulator
headers), mirroring the reference's dual-build test pattern (SURVEY §4)
and its determinism dual-run
(src/test/determinism/shd-test-determinism.c:15-60).
"""

import os
import subprocess

import pytest

from shadow_tpu.core.config import HostSpec, ProcessSpec, Scenario
from shadow_tpu.engine import defs
from shadow_tpu.engine.sim import Simulation
from shadow_tpu.engine.state import EngineConfig

from test_shim import run_native_argv, TRANSFERS, NBYTES

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
POLLCLIENT_C = os.path.join(REPO, "examples/plugins/pollclient.c")
LIBCPROBE_C = os.path.join(REPO, "examples/plugins/libcprobe.c")


@pytest.fixture(scope="module")
def pollclient_bin(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("shim") / "pollclient")
    subprocess.run(["cc", "-O2", "-o", out, POLLCLIENT_C], check=True)
    return out


@pytest.fixture(scope="module")
def libcprobe_bin(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("shim") / "libcprobe")
    subprocess.run(["cc", "-O2", "-o", out, LIBCPROBE_C, "-lpthread"],
                   check=True)
    return out


def _cfg(n=2):
    return EngineConfig(num_hosts=n, qcap=32, scap=8, obcap=16, incap=32,
                        txqcap=16, hostedcap=16, chunk_windows=8)


def test_poll_select_client(pollclient_bin, tmp_path,
                            simple_topology_xml):
    """A poll()/select()-waiting binary — the wait style the round-4
    verdict called out as unsupported ('any poll()-based client
    fails') — completes the same transfers natively and simulated,
    and getsockname() reports real nonzero ports (not the round-4
    zeros)."""
    native = run_native_argv([pollclient_bin, "127.0.0.1", "{port}",
                              str(NBYTES), str(TRANSFERS)])
    assert f"transfers={TRANSFERS} bytes={NBYTES * TRANSFERS}" in native

    out_path = str(tmp_path / "pollclient.out")
    scen = Scenario(
        stop_time=60 * 10**9,
        topology_graphml=simple_topology_xml,
        hosts=[
            HostSpec(id="server", processes=[
                ProcessSpec(plugin="bulkserver", start_time=10**9,
                            arguments="port=8080")]),
            HostSpec(id="client", processes=[
                ProcessSpec(plugin="hosted:shim", start_time=2 * 10**9,
                            arguments=f"out={out_path} "
                                      f"cmd={pollclient_bin} "
                                      f"server 8080 {NBYTES} "
                                      f"{TRANSFERS}")]),
        ],
    )
    report = Simulation(scen, engine_cfg=_cfg()).run()
    with open(out_path) as f:
        sim_out = f.read()
    assert (f"transfers={TRANSFERS} bytes={NBYTES * TRANSFERS}"
            in sim_out), sim_out
    assert f"ports_ok={TRANSFERS}" in sim_out, sim_out
    assert report.stats[0, defs.ST_XFER_DONE] == TRANSFERS
    assert report.stats[0, defs.ST_BYTES_RECV] == NBYTES * TRANSFERS


def _run_probe(libcprobe_bin, out_path, simple_topology_xml,
               sleep_ms=900, nrand=16, seed=1):
    scen = Scenario(
        stop_time=30 * 10**9,
        topology_graphml=simple_topology_xml,
        hosts=[HostSpec(id="probe", processes=[
            ProcessSpec(plugin="hosted:shim", start_time=10**9,
                        arguments=f"out={out_path} cmd={libcprobe_bin} "
                                  f"{sleep_ms} {nrand}")])],
    )
    report = Simulation(scen, engine_cfg=_cfg(1), seed=seed).run()
    with open(out_path) as f:
        return f.read(), report


def _parse(out):
    d = {}
    for line in out.splitlines():
        parts = line.split()
        for p in parts[1:]:
            k, _, v = p.partition("=")
            d[parts[0] + "." + k] = v
    return d


def test_sleep_advances_sim_time_and_clocks_agree(libcprobe_bin, tmp_path,
                                                  simple_topology_xml):
    """sleep()/usleep()/nanosleep() advance SIMULATED time (reference
    process_emu_nanosleep, shd-process.c:3055) and all three clock
    surfaces (clock_gettime / gettimeofday / time) read the same
    simulated clock (shd-process.c:4329-4389)."""
    import time as _t
    t0 = _t.perf_counter()
    out, _ = _run_probe(libcprobe_bin, str(tmp_path / "probe.out"),
                        simple_topology_xml, sleep_ms=900)
    wall = _t.perf_counter() - t0
    d = _parse(out)
    # the measured (simulated) sleep covers the request
    assert 0.85 <= float(d["slept.measured"]) <= 1.1, out
    # all clock surfaces agree on sim time (start_time = 1s)
    mono, real, tod = (float(d["clocks.mono"]), float(d["clocks.real"]),
                       float(d["clocks.tod"]))
    assert abs(mono - real) < 0.05 and abs(real - tod) < 0.05, out
    assert 0.9 <= mono <= 1.5, out
    assert int(d["clocks.time"]) in (0, 1, 2), out
    # ...and essentially none of it was wallclock: the 0.9s of
    # simulated sleeping must not burn 0.9s of real time sleeping
    # (generous bound — the run includes XLA dispatch overhead, but a
    # REAL sleep chain would add the full 0.9s on top)
    assert wall < 60, f"simulated sleep appears to burn wallclock: {wall}"


def test_entropy_determinism_dual_run(libcprobe_bin, tmp_path,
                                      simple_topology_xml):
    """The reference's determinism test, realized: an entropy-drawing
    binary (getrandom + /dev/urandom) runs TWICE under the sim with
    identical output — hosted entropy comes from the per-host seeded
    PRNG, not the kernel (shd-host.c:574,
    shd-test-determinism.c:15-60). A different seed changes the bytes
    (it is entropy, not zeros)."""
    out1, _ = _run_probe(libcprobe_bin, str(tmp_path / "p1.out"),
                         simple_topology_xml, seed=7)
    out2, _ = _run_probe(libcprobe_bin, str(tmp_path / "p2.out"),
                         simple_topology_xml, seed=7)
    assert out1 == out2, (out1, out2)
    d = _parse(out1)
    assert d["entropy.getrandom"] != "00" * 16, out1
    assert d["entropy.urandom"] != "00" * 16, out1
    assert d["entropy.getrandom"] != d["entropy.urandom"]
    # the stdio route (fopen/fread): glibc's fopen calls an INTERNAL
    # open, so only the fopen/fopen64 -> fopencookie interposition
    # keeps it deterministic (ADVICE r5); real PRNG bytes, advancing
    # the same host stream as the other draws, identical across the
    # dual run (out1 == out2 above covers the fentropy line too)
    assert d["fentropy.fopen"] != "00" * 16, out1
    assert d["fentropy.fopen"] != d["entropy.urandom"], out1

    out3, _ = _run_probe(libcprobe_bin, str(tmp_path / "p3.out"),
                         simple_topology_xml, seed=8)
    d3 = _parse(out3)
    assert d3["entropy.getrandom"] != d["entropy.getrandom"]


def test_urandom_write_refused_and_poll_sleep(libcprobe_bin, tmp_path,
                                              simple_topology_xml):
    """Round-5 advisor fixes, driven through a real binary: (a)
    write() to an entropy vfd fails cleanly with EBADF instead of
    forwarding OP_SEND and crashing shim.py with a KeyError; (b) the
    poll(NULL,0,ms) / select(0,...,&tv) sleep idioms advance
    SIMULATED time via OP_SLEEP (a real poll would freeze the virtual
    clock and wedge deadline loops)."""
    out, _ = _run_probe(libcprobe_bin, str(tmp_path / "uw.out"),
                        simple_topology_xml)
    d = _parse(out)
    assert int(d["urandomwrite.rc"]) == -1, out
    assert int(d["urandomwrite.errno"]) == 9, out   # EBADF
    # 150ms poll + 150ms select, measured on the simulated clock
    assert 0.25 <= float(d["pollsleep.measured"]) <= 0.45, out


def test_shim_op_metrics(libcprobe_bin, tmp_path, simple_topology_xml):
    """The preload protocol is metered: with the metrics registry on,
    a hosted run records per-op counts and latency histograms
    (obs.metrics shim section)."""
    from shadow_tpu.obs import metrics as M
    reg = M.install()
    try:
        out, _ = _run_probe(libcprobe_bin, str(tmp_path / "mt.out"),
                            simple_topology_xml)
        snap = reg.snapshot()
    finally:
        M.finish()
    assert "measured" in out
    ops = snap["shim"]["ops"]
    # the probe reads clocks, sleeps and draws entropy
    assert ops.get("clock", 0) > 0, ops
    assert ops.get("sleep", 0) > 0, ops
    assert ops.get("random", 0) > 0, ops
    lat = snap["shim"]["op_latency_us"]["clock"]
    assert lat["count"] == ops["clock"] and lat["mean"] > 0


def test_pthread_create_refused(libcprobe_bin, tmp_path,
                                simple_topology_xml):
    """pthread_create fails LOUDLY under the sim (EAGAIN=11) instead
    of silently spawning a real thread that would corrupt lockstep
    semantics (round-4 verdict item 9; the reference runs threads as
    rpth green threads, shd-process.c:5074-7449 — unimplemented
    here, so refusal is the only correct answer)."""
    out, _ = _run_probe(libcprobe_bin, str(tmp_path / "pt.out"),
                        simple_topology_xml)
    d = _parse(out)
    assert int(d["threads.pthread_create"]) == 11, out
