"""Multi-chip sharding tests on the virtual 8-device CPU mesh.

The sharded window loop (parallel.shard) must reproduce the single-chip
run bit-for-bit: same loss rolls (placement-independent counter PRNG),
same exchange order (contiguous block sharding), same stats.
"""

import numpy as np
import jax
import pytest

from shadow_tpu.engine.sim import Simulation
from shadow_tpu.parallel.shard import make_mesh

from test_phold import phold_scenario
from test_tgen import tgen_scenario


@pytest.fixture(scope="module")
def mesh8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    return make_mesh(8)


def test_phold_sharded_matches_single(mesh8):
    single = Simulation(phold_scenario(n=16, stop=5)).run()
    sharded = Simulation(phold_scenario(n=16, stop=5)).run(mesh=mesh8)
    assert np.array_equal(single.stats, sharded.stats)
    assert single.windows == sharded.windows


def test_phold_sharded_padding(mesh8):
    """Host count not divisible by the mesh: inert padding, same stats."""
    single = Simulation(phold_scenario(n=13, stop=3)).run()
    sharded = Simulation(phold_scenario(n=13, stop=3)).run(mesh=mesh8)
    assert sharded.stats.shape[0] == 13
    assert np.array_equal(single.stats, sharded.stats)


def test_tgen_sharded_matches_single(mesh8, simple_topology_xml):
    scen = tgen_scenario(simple_topology_xml, n_web=2, n_bulk=1, stop=40)
    single = Simulation(scen).run()
    scen2 = tgen_scenario(simple_topology_xml, n_web=2, n_bulk=1, stop=40)
    sharded = Simulation(scen2).run(mesh=mesh8)
    assert np.array_equal(single.stats, sharded.stats)
