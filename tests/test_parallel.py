"""Multi-chip sharding tests on the virtual 8-device CPU mesh.

The sharded window loop (parallel.shard) must reproduce the single-chip
run bit-for-bit: same loss rolls (placement-independent counter PRNG),
same exchange order (contiguous block sharding), same stats.
"""

import numpy as np
import jax
import pytest

from shadow_tpu.engine.sim import Simulation
from shadow_tpu.parallel.shard import make_mesh

from test_phold import phold_scenario
from test_tgen import tgen_scenario


@pytest.fixture(scope="module")
def mesh8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    return make_mesh(8)


def test_phold_sharded_matches_single(mesh8):
    single = Simulation(phold_scenario(n=16, stop=5)).run()
    sharded = Simulation(phold_scenario(n=16, stop=5)).run(mesh=mesh8)
    assert np.array_equal(single.stats, sharded.stats)
    assert single.windows == sharded.windows


def test_phold_sharded_padding(mesh8):
    """Host count not divisible by the mesh: inert padding, same stats."""
    single = Simulation(phold_scenario(n=13, stop=3)).run()
    sharded = Simulation(phold_scenario(n=13, stop=3)).run(mesh=mesh8)
    assert sharded.stats.shape[0] == 13
    assert np.array_equal(single.stats, sharded.stats)


def test_tgen_sharded_matches_single(mesh8, simple_topology_xml):
    scen = tgen_scenario(simple_topology_xml, n_web=2, n_bulk=1, stop=40)
    single = Simulation(scen).run()
    scen2 = tgen_scenario(simple_topology_xml, n_web=2, n_bulk=1, stop=40)
    sharded = Simulation(scen2).run(mesh=mesh8)
    assert np.array_equal(single.stats, sharded.stats)


def test_digest_sharded_matches_single(mesh8, tmp_path):
    """The determinism digest chain (obs.digest) extends the v1≡v2 /
    sharded≡single claim from stats to the WHOLE live state: a mesh
    run (including inert padding rows, sliced off before hashing) must
    produce a byte-identical chain to the single-chip run."""
    single = str(tmp_path / "single.jsonl")
    mesh = str(tmp_path / "mesh.jsonl")
    Simulation(phold_scenario(n=13, stop=3)).run(digest=single)
    Simulation(phold_scenario(n=13, stop=3)).run(mesh=mesh8,
                                                 digest=mesh)
    assert (open(single, "rb").read() == open(mesh, "rb").read())


def test_exchange_v1_matches_v2(mesh8):
    """The v1 all-gather and v2 bucketed all-to-all wire protocols are
    bit-identical (and both equal the single-chip run — covered by the
    tests above, which run the default v2)."""
    import dataclasses

    def run(a2a):
        scen = phold_scenario(n=16, stop=5)
        sim = Simulation(scen)
        sim.cfg = dataclasses.replace(sim.cfg, exchange_a2a=a2a)
        return sim.run(mesh=mesh8)

    v2 = run(True)
    v1 = run(False)
    assert np.array_equal(v1.stats, v2.stats)
    assert v1.windows == v2.windows


def test_a2a_wire_bytes_flat_in_shard_count():
    """The point of v2: TOTAL exchanged slots across the mesh stay
    ~flat (bounded by 4x the global outbox) as the shard count grows,
    where v1's all_gather totals grow linearly with shard count
    (every shard receives every outbox) — VERDICT round-1 item:
    exchange bytes scaling."""
    import dataclasses
    from shadow_tpu.engine.state import EngineConfig
    from shadow_tpu.parallel.shard import a2a_bucket_cap

    H, O = 4096, 16
    global_outbox = H * O
    totals = {}
    for n_shards in (2, 8, 64):
        cfg = EngineConfig(num_hosts=H, obcap=O)
        lcfg = dataclasses.replace(cfg, num_hosts=H // n_shards)
        B = a2a_bucket_cap(cfg, lcfg)
        # v1: each of n shards all-gathers the whole global outbox
        totals[("v1", n_shards)] = n_shards * global_outbox
        # v2: each of n shards sends n buckets of B slots
        totals[("v2", n_shards)] = n_shards * n_shards * B
    # v2 total is bounded by 4x the global outbox (+ the 64-slot
    # per-pair floor) at EVERY shard count — flat
    for n in (2, 8, 64):
        assert totals[("v2", n)] <= 4 * global_outbox + 64 * n * n
    # v1 total grows linearly: 32x more at 64 shards than at 2
    assert totals[("v1", 64)] == 32 * totals[("v1", 2)]
    # and at pod scale v2 moves an order of magnitude less than v1
    assert totals[("v2", 64)] * 10 <= totals[("v1", 64)]
