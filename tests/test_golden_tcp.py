"""Golden-vector tests for the TCP protocol kernels.

The differential harness (tests/test_differential.py) runs the same
kernels in both engines, so it cannot catch a SPEC bug in
net/congestion.py or net/sack.py — both engines would faithfully
reproduce it. These tests check the kernels against expectations
derived INDEPENDENTLY from the reference C:

- the loss-response formulas of cubic/reno/aimd
  (/root/reference/src/main/host/descriptor/shd-tcp-cubic.c:224-236,
  shd-tcp-aimd.c:38-60, shd-tcp-reno.c:42-66), hand-transcribed here
  as plain Python arithmetic;
- the cubic growth curve: a full pure-Python reimplementation of the
  reference's integer _cubic_update mechanics (shd-tcp-cubic.c:
  112-220) drives the same ACK schedule as our float kernel, and the
  trajectories must stay within a tight envelope;
- the SACK scoreboard range algebra: an independent set-of-integers
  model checks insert/consume/skip/drop_below exactly, and
  hand-computed retransmit-selection scenarios check the recovery
  rules against shd-tcp-scoreboard.c:187-281 (with the one designed
  divergence — the FACK-style "everything below the highest sacked
  run is lost" rule vs the reference's fack-4 holdoff — asserted
  explicitly so it cannot drift silently).

Nothing in this file calls into net/ to COMPUTE an expectation; net/
functions are only ever the system under test.
"""

import math

import numpy as np
import pytest

import jax.numpy as jnp

from shadow_tpu.net import congestion as CC
from shadow_tpu.net import sack
from shadow_tpu.core.constants import TCP_MSS


# ---------------------------------------------------------------------------
# Reference constants, transcribed from shd-tcp-cubic.c cubic_new
# (beta=819, scalingFactor=41, BETA_SCALE=1024, BICTCP_HZ=10, time in
# milliseconds) — NOT taken from net.congestion.
REF_BETA = 819
REF_BETA_SCALE = 1024
REF_RTT_SCALE = 41 * 10
REF_CUBE_FACTOR = (1 << (10 + 3 * 10)) // REF_RTT_SCALE


def test_cubic_loss_golden():
    """cubic_packetLoss: new window = max(W*819/1024, 2)."""
    for w in [2.0, 5.0, 10.0, 37.0, 100.0, 1000.0, 10000.0]:
        expected = max(w * REF_BETA / REF_BETA_SCALE, 2.0)
        got, thresh, _, epoch = CC.on_loss(
            jnp.int32(CC.CC_CUBIC), jnp.float32(w), jnp.float32(0.0),
            jnp.float32(0.0))
        assert got == pytest.approx(expected, rel=1e-5), w
        assert thresh == pytest.approx(expected, rel=1e-5)
        assert int(epoch) == -1          # epochStart reset on loss


def test_cubic_fast_convergence_golden():
    """cubic_packetLoss wmax update: W < lastMax -> lastMax' =
    W*(1024+819)/(2*1024), else lastMax' = W (shd-tcp-cubic.c:228-233)."""
    for w, wmax in [(50.0, 100.0), (10.0, 12.0), (99.0, 100.0)]:
        expected = w * (REF_BETA_SCALE + REF_BETA) / (2 * REF_BETA_SCALE)
        _, _, wmax2, _ = CC.on_loss(jnp.int32(CC.CC_CUBIC),
                                    jnp.float32(w), jnp.float32(0.0),
                                    jnp.float32(wmax))
        assert wmax2 == pytest.approx(expected, rel=1e-5), (w, wmax)
    for w, wmax in [(100.0, 50.0), (100.0, 100.0), (5.0, 0.0)]:
        _, _, wmax2, _ = CC.on_loss(jnp.int32(CC.CC_CUBIC),
                                    jnp.float32(w), jnp.float32(0.0),
                                    jnp.float32(wmax))
        assert wmax2 == pytest.approx(w, rel=1e-5), (w, wmax)


def test_aimd_reno_loss_golden():
    """aimd/reno packetLoss: ceil(W/2), floor 1 (RFC5681 note in
    shd-tcp-aimd.c:50-60)."""
    for kind in (CC.CC_AIMD, CC.CC_RENO):
        for w in [1.0, 2.0, 3.0, 7.0, 100.0, 12345.0]:
            expected = max(math.ceil(w / 2.0), 1.0)
            got, thresh, _, _ = CC.on_loss(jnp.int32(kind),
                                           jnp.float32(w),
                                           jnp.float32(0.0),
                                           jnp.float32(0.0))
            assert got == pytest.approx(expected, rel=1e-6), (kind, w)


def test_slow_start_and_additive_increase_golden():
    """Slow start adds packetsAcked; avoidance adds n^2/W per ack
    (aimd/reno shared shape, shd-tcp-aimd.c:16-36)."""
    # slow start: threshold unset (0)
    w2, _, _ = CC.on_ack(jnp.int32(CC.CC_RENO), jnp.float32(10.0),
                         jnp.float32(0.0), jnp.float32(0.0),
                         jnp.int64(-1), jnp.float32(0.0),
                         jnp.int32(3), jnp.int64(10**9),
                         jnp.int64(100 * 10**6))
    assert w2 == pytest.approx(13.0)
    # avoidance: W=20 above threshold 10, 1 pkt acked -> +1/20
    w2, _, _ = CC.on_ack(jnp.int32(CC.CC_RENO), jnp.float32(20.0),
                         jnp.float32(10.0), jnp.float32(0.0),
                         jnp.int64(-1), jnp.float32(0.0),
                         jnp.int32(1), jnp.int64(10**9),
                         jnp.int64(100 * 10**6))
    assert w2 == pytest.approx(20.0 + 1.0 / 20.0, rel=1e-6)


# ---------------------------------------------------------------------------
# Reference cubic mechanics, reimplemented in full from
# shd-tcp-cubic.c:112-220 (integer count/windowCount pacing, ms time
# base, >>40 scaling). Hystart is inert under a constant-RTT ACK clock
# (its "found" conditions need sub-2ms ack spacing or RTT inflation),
# so it is omitted; the slow-start branch is included.

class RefCubic:
    def __init__(self, window, threshold):
        self.window = window
        self.threshold = threshold if threshold else 0x7FFFFFFF
        self.lastMaxWindow = 0
        self.lossWindow = 0
        self.epochStart = 0
        self.lastTime = 0
        self.originPoint = 0
        self.delayMin = 0
        self.tcpWindowEst = 0
        self.k = 0
        self.ackCount = 0
        self.count = 0
        self.windowCount = 0
        self.betaScale = 8 * (REF_BETA_SCALE + REF_BETA) // 3 \
            // (REF_BETA_SCALE - REF_BETA)

    def _update(self, now_ms, rtt_ms):
        if self.delayMin:
            self.delayMin = min(self.delayMin, rtt_ms)
        else:
            self.delayMin = rtt_ms
        self.ackCount += 1
        if now_ms - self.lastTime <= 1024 // 32:
            return
        self.lastTime = now_ms
        if not self.epochStart:
            self.epochStart = now_ms
            if self.window < self.lastMaxWindow:
                self.k = int((REF_CUBE_FACTOR *
                              (self.lastMaxWindow - self.window))
                             ** (1.0 / 3.0))
                self.originPoint = self.lastMaxWindow
            else:
                self.k = 0
                self.originPoint = self.window
            self.ackCount = 1
            self.tcpWindowEst = self.window
        timeOffset = now_ms + self.delayMin - self.epochStart
        offset = abs(timeOffset - self.k)
        originDelta = (REF_RTT_SCALE * offset * offset * offset) >> 40
        if timeOffset < self.k:
            target = self.originPoint - originDelta
        else:
            target = self.originPoint + originDelta
        if target > self.window:
            self.count = self.window // (target - self.window)
        else:
            self.count = self.window * 100
        if self.delayMin > 0:
            minCount = (self.window * 1000 * 8) // (10 * 16 * self.delayMin)
            if self.count < minCount and timeOffset >= self.k:
                self.count = minCount
        delta = (self.window * self.betaScale) >> 3
        while self.ackCount > delta:
            self.ackCount -= delta
            self.tcpWindowEst += 1
        self.ackCount = 0
        if self.tcpWindowEst > self.window:
            maxCount = self.window // (self.tcpWindowEst - self.window)
            if self.count > maxCount:
                self.count = maxCount
        self.count //= 2
        if self.count == 0:
            self.count = 1

    def avoidance(self, now_ms, rtt_ms):
        if self.window <= self.threshold:
            self.window += 1
        else:
            self._update(now_ms, rtt_ms)
            if self.windowCount > self.count:
                self.window += 1
                self.windowCount = 0
            else:
                self.windowCount += 1

    def packet_loss(self):
        self.epochStart = 0
        if self.window < self.lastMaxWindow:
            self.lastMaxWindow = (self.window *
                                  (REF_BETA_SCALE + REF_BETA)) \
                // (2 * REF_BETA_SCALE)
        else:
            self.lastMaxWindow = self.window
        self.lossWindow = self.window
        new = max((self.window * REF_BETA) // REF_BETA_SCALE, 2)
        # caller contract (shd-tcp.c:1063-1064): threshold = loss
        # return; window = threshold
        self.threshold = new
        self.window = new


import jax as _jax


@_jax.jit
def _round_of_acks(cwnd, ssthresh, wmax, epoch, k, t0, spacing, acks,
                   srtt_ns):
    """One RTT worth of per-packet on_ack calls as a scanned kernel
    (the eager per-ack loop took minutes on a 1-core box)."""
    def body(carry, i):
        cwnd, epoch, k = carry
        now = t0 + (i + 1) * spacing
        cwnd, epoch, k = CC.on_ack(jnp.int32(CC.CC_CUBIC), cwnd,
                                   ssthresh, wmax, epoch, k,
                                   jnp.int32(1), now, srtt_ns)
        return (cwnd, epoch, k), 0

    idx = jnp.arange(4096, dtype=jnp.int64)
    def step(carry, i):
        do = i < acks
        new, _ = body(carry, i)
        out = _jax.tree.map(lambda a, b: jnp.where(do, a, b), new, carry)
        return out, 0

    (cwnd, epoch, k), _ = _jax.lax.scan(step, (cwnd, epoch, k), idx)
    return cwnd, epoch, k


def _run_ours(w0, thresh0, wmax0, rtt_ms, seconds, loss_times_s):
    """Drive net.congestion's cubic with one on_ack per packet, window
    acks per RTT (the same ACK clock RefCubic gets)."""
    cwnd = jnp.float32(w0)
    ssthresh = jnp.float32(thresh0)
    wmax = jnp.float32(wmax0)
    epoch = jnp.int64(-1)
    k = jnp.float32(0.0)
    now_ns = 0
    losses = sorted(loss_times_s)
    samples = []
    while now_ns < seconds * 10**9:
        acks = max(int(cwnd), 1)
        spacing = int(rtt_ms * 10**6) // acks
        cwnd, epoch, k = _round_of_acks(
            cwnd, ssthresh, wmax, epoch, k, jnp.int64(now_ns),
            jnp.int64(spacing), jnp.int64(acks),
            jnp.int64(rtt_ms * 10**6))
        now_ns += spacing * acks
        while losses and now_ns >= losses[0] * 10**9:
            losses.pop(0)
            cwnd, ssthresh, wmax, epoch = CC.on_loss(
                jnp.int32(CC.CC_CUBIC), cwnd, ssthresh, wmax)
        samples.append((now_ns / 1e9, float(cwnd)))
    return samples


def _run_ref(w0, thresh0, rtt_ms, seconds, loss_times_s):
    ref = RefCubic(w0, thresh0)
    now_ms = 0
    losses = sorted(loss_times_s)
    samples = []
    while now_ms < seconds * 1000:
        acks = max(ref.window, 1)
        spacing = rtt_ms / acks
        t = now_ms
        for i in range(acks):
            t = now_ms + (i + 1) * spacing
            ref.avoidance(int(t), rtt_ms)
        now_ms = int(now_ms + rtt_ms)
        while losses and now_ms >= losses[0] * 1000:
            losses.pop(0)
            ref.packet_loss()
        samples.append((now_ms / 1000.0, float(ref.window)))
    return samples


def test_cubic_trajectory_vs_reference_mechanics():
    """After a loss from W=120, both implementations must (a) drop to
    ~0.8W, (b) grow back toward wmax ~ the pre-loss window along the
    cubic, (c) plateau near wmax around t=K, with the windows staying
    within a modest envelope of each other throughout."""
    rtt_ms = 100
    seconds = 40
    # start both at W=120 in avoidance and take a loss at t=2s
    ours = _run_ours(120.0, 60.0, 0.0, rtt_ms, seconds, [2.0])
    ref = _run_ref(120, 60, rtt_ms, seconds, [2.0])

    def at(samples, t):
        return min(samples, key=lambda p: abs(p[0] - t))[1]

    # (a) the multiplicative decrease: the first post-loss sample is
    # ~0.8x the pre-loss window in both (119/1024 slack for the growth
    # between sample points)
    pre_o, pre_r = at(ours, 1.9), at(ref, 1.9)
    assert at(ours, 2.2) <= pre_o * 0.9
    assert at(ref, 2.2) <= pre_r * 0.9
    assert at(ours, 2.2) >= pre_o * (819 / 1024) * 0.95
    assert at(ref, 2.2) >= pre_r * (819 / 1024) * 0.95
    # (b)+(c): windows track within a 30% envelope at every sampled
    # second after recovery starts (mechanics differ — float target
    # chase with the minCount rate cap vs integer count pacing — but
    # the curve and the post-plateau linear rate are the same)
    for t in range(4, seconds, 2):
        o, r = at(ours, t), at(ref, t)
        assert 0.70 <= o / r <= 1.30, (t, o, r)
    # post-plateau probing is RATE-BOUNDED: the reference's minCount
    # floor caps growth at 0.04*delayMin packets per RTT = ~40/s here;
    # the runaway-chase bug (window doubling per RTT) blows far past
    # this within a few seconds
    for t in (20, 30, 38):
        dt_rate_o = (at(ours, t) - at(ours, t - 4)) / 4.0
        dt_rate_r = (at(ref, t) - at(ref, t - 4)) / 4.0
        assert dt_rate_o <= 60.0, (t, dt_rate_o)
        assert dt_rate_r <= 60.0, (t, dt_rate_r)


def test_cubic_k_formula_golden():
    """Our K (seconds to plateau) must equal the reference's
    k = cbrt(cubeFactor * (lastMax - W)) milliseconds
    (shd-tcp-cubic.c:137-139) for the same deficit."""
    for w, wmax in [(50.0, 100.0), (80.0, 100.0), (10.0, 400.0)]:
        ref_k_ms = (REF_CUBE_FACTOR * (wmax - w)) ** (1.0 / 3.0)
        # probe our kernel: first avoidance ack sets k (epoch < 0)
        _, _, k = CC.on_ack(jnp.int32(CC.CC_CUBIC), jnp.float32(w),
                            jnp.float32(w / 2), jnp.float32(wmax),
                            jnp.int64(-1), jnp.float32(0.0),
                            jnp.int32(1), jnp.int64(10**9),
                            jnp.int64(100 * 10**6))
        assert float(k) == pytest.approx(ref_k_ms / 1000.0, rel=0.01), \
            (w, wmax)


# ---------------------------------------------------------------------------
# SACK scoreboard: independent set-of-integers model.

class SetModel:
    """Byte ranges as a plain Python set of byte offsets."""

    def __init__(self):
        self.bytes = set()

    def insert(self, s, e):
        self.bytes |= set(range(s, e))

    def drop_below(self, lo):
        self.bytes = {b for b in self.bytes if b >= lo}

    def consume(self, rcv):
        """TCP semantics (and the kernel's): any stored range whose
        START the cursor has reached is absorbed WHOLE — in real use
        rcv_nxt never sits inside a stored out-of-order run, and a run
        starting at/below the cursor is by construction fully
        receivable."""
        changed = True
        while changed:
            changed = False
            for (s, e) in self.ranges():
                if s <= rcv:
                    self.bytes -= set(range(s, e))
                    rcv = max(rcv, e)
                    changed = True
                    break
        return rcv

    def skip(self, x):
        while x in self.bytes:
            x += 1
        return x

    def ranges(self):
        out = []
        for b in sorted(self.bytes):
            if out and out[-1][1] == b:
                out[-1][1] = b + 1
            else:
                out.append([b, b + 1])
        return [(s, e) for s, e in out]


def _ranges_of(s, e):
    s = np.asarray(s)
    e = np.asarray(e)
    return sorted((int(a), int(b)) for a, b in zip(s, e) if a >= 0)


def test_sack_ops_match_set_model():
    """Randomized op sequences: as long as the model never exceeds K
    disjoint ranges, the kernel must agree exactly."""
    rng = np.random.default_rng(7)
    for trial in range(50):
        s, e = sack.empty()
        model = SetModel()
        for _ in range(30):
            op = rng.integers(0, 4)
            if op == 0:
                a = int(rng.integers(0, 400))
                ln = int(rng.integers(1, 60))
                model.insert(a, a + ln)
                if len(model.ranges()) > sack.K:
                    # out of model scope (kernel K-truncates); restart
                    break
                s, e = sack.insert(s, e, jnp.int64(a), jnp.int64(a + ln))
            elif op == 1:
                lo = int(rng.integers(0, 400))
                model.drop_below(lo)
                s, e = sack.drop_below(s, e, jnp.int64(lo))
            elif op == 2:
                x = int(rng.integers(0, 400))
                assert int(sack.skip(jnp.int64(x), s, e)) == model.skip(x)
                continue
            else:
                rcv = int(rng.integers(0, 400))
                want = model.consume(rcv)
                s, e, got = sack.consume(s, e, jnp.int64(rcv))
                assert int(got) == want
            # ranges agree after each mutating op. The kernel merges
            # ADJACENT ranges (non-adjacency invariant) which the set
            # model reproduces by construction of ranges().
            assert _ranges_of(s, e) == model.ranges(), trial


def test_sack_insert_merges_touching():
    """[0,10) + [10,20) must merge into one range — non-adjacency is
    an invariant the wire encoder relies on."""
    s, e = sack.empty()
    s, e = sack.insert(s, e, jnp.int64(0), jnp.int64(10))
    s, e = sack.insert(s, e, jnp.int64(10), jnp.int64(20))
    assert _ranges_of(s, e) == [(0, 20)]


def test_sack_overflow_drops_highest():
    s, e = sack.empty()
    for a in (0, 100, 200, 300):
        s, e = sack.insert(s, e, jnp.int64(a), jnp.int64(a + 10))
    s2, e2, dropped = sack.insert_counted(s, e, jnp.int64(400),
                                          jnp.int64(410))
    assert int(dropped) == 1
    # the highest range (the new [400,410)) was the one discarded
    assert _ranges_of(s2, e2) == [(0, 10), (100, 110), (200, 210),
                                  (300, 310)]


def test_encode_decode_subset_invariant():
    """Wire rounding must advertise a SUBSET of the true range
    (over-claim would stall recovery until RTO — module docstring)."""
    ack = 1000
    cases = [(ack + 3, ack + 3 * TCP_MSS + 7),
             (ack + TCP_MSS, ack + 2 * TCP_MSS),
             (ack + 1, ack + TCP_MSS)]       # sub-MSS: nothing to say
    for (ts, te) in cases:
        s, e = sack.empty()
        s, e = sack.insert(s, e, jnp.int64(ts), jnp.int64(te))
        w1, _ = sack.encode2(s, e, jnp.int64(ack))
        ds, de = sack.decode(jnp.int32(w1), jnp.int64(ack),
                             jnp.int64(te))
        if int(ds) >= 0:
            assert ts <= int(ds) <= int(de) <= te
        # FINACK bit (bit 0 of the AUX word) must stay clear
        assert (int(w1) & 1) == 0


def test_retransmit_selection_hand_vectors():
    """Hand-computed recovery scenario against the reference
    scoreboard's selection (shd-tcp-scoreboard.c:187-281), packets
    mapped to MSS-sized byte ranges.

    Sent packets 0..9, una=0; peer SACKed {3,4,5} and {7,8}:
    - reference: fack=8; INFLIGHT 0,1,2 are <= fack-4 -> LOST;
      getNextRetransmit = 0 (= una). Packets 6 and 9 stay INFLIGHT
      (within 3 of fack / above fack).
    - ours (FACK-style, documented divergence): every un-sacked byte
      below the highest sacked run (9*MSS) is inferably lost, so the
      recovery bound is lost_bound = min(hole_end, max_end) and the
      cursor visits 0,1,2 AND 6; bytes >= 9*MSS are never touched.
    Both agree on the first retransmission (una) and on never
    resending sacked bytes — the invariants that matter for
    correctness; the fack-4 holdoff only affects aggressiveness.
    """
    M = TCP_MSS
    s, e = sack.empty()
    s, e = sack.insert(s, e, jnp.int64(3 * M), jnp.int64(6 * M))
    s, e = sack.insert(s, e, jnp.int64(7 * M), jnp.int64(9 * M))
    una, hole_end = 0, 10 * M

    # first retransmit = una (reference: block 0 is LOST, lowest)
    first = int(sack.skip(jnp.int64(una), s, e))
    assert first == 0
    # the cursor never lands inside a sacked run
    assert int(sack.skip(jnp.int64(3 * M), s, e)) == 6 * M
    assert int(sack.skip(jnp.int64(7 * M + 1), s, e)) == 9 * M
    # recovery bound: the highest sacked end, clipped to the recovery
    # point — bytes at/above 9*MSS are in flight, NOT retransmittable
    bound = int(sack.lost_bound(s, e, jnp.int64(una),
                                jnp.int64(hole_end)))
    assert bound == 9 * M
    # and a retransmission starting below a sacked run must stop at it
    assert int(sack.next_start_after(jnp.int64(0), s, e)) == 3 * M


def test_lost_bound_no_sack_is_classic_fast_retransmit():
    """With no SACK info, 3 dupacks retransmit exactly one segment
    past una (classic fast retransmit)."""
    s, e = sack.empty()
    bound = int(sack.lost_bound(s, e, jnp.int64(5000),
                                jnp.int64(10**9)))
    assert bound == 5000 + TCP_MSS
