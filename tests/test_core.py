"""Unit tests for core types: time parsing, config, RNG tree."""

import numpy as np

from shadow_tpu.core import simtime as T
from shadow_tpu.core import rng as R
from shadow_tpu.core.config import load_xml


def test_parse_time_units():
    assert T.parse_time(5) == 5 * T.SIMTIME_ONE_SECOND
    assert T.parse_time("10 ms") == 10 * T.SIMTIME_ONE_MILLISECOND
    assert T.parse_time("1.5s") == 1_500_000_000
    assert T.parse_time("250us") == 250_000
    assert T.parse_time("2 minutes") == 120 * T.SIMTIME_ONE_SECOND


def test_format_time():
    assert T.format_time(3 * T.SIMTIME_ONE_SECOND + 5) == "00:00:03.000000005"


def test_config_xml_roundtrip():
    xml = """
    <shadow stoptime="60">
      <topology path="topo.graphml"/>
      <plugin id="tgen" path="x.so"/>
      <host id="server" quantity="3" bandwidthdown="2048" bandwidthup="1024">
        <process plugin="pingserver" starttime="1" arguments="port=8000"/>
      </host>
      <host id="client" iphint="11.0.0.5">
        <process plugin="ping" starttime="2" arguments="peer=server1 port=8000"/>
      </host>
    </shadow>
    """
    scen = load_xml(xml)
    assert scen.stop_time == 60 * T.SIMTIME_ONE_SECOND
    assert scen.total_hosts() == 4
    names = [n for _, n, _ in scen.expand_hosts()]
    assert names == ["server1", "server2", "server3", "client"]
    srv = scen.hosts[0]
    assert srv.bandwidth_down == 2048 * 1024
    assert srv.processes[0].start_time == T.SIMTIME_ONE_SECOND
    assert scen.hosts[1].ip_hint == "11.0.0.5"


def test_rng_determinism_and_independence():
    root = R.root_key(42)
    k1 = R.host_key(root, 7)
    k2 = R.host_key(root, 8)
    assert not np.array_equal(np.asarray(k1), np.asarray(k2))
    # same seed -> identical keys
    again = R.host_key(R.root_key(42), 7)
    assert np.array_equal(np.asarray(k1), np.asarray(again))
    u1 = float(R.uniform_from(R.counter_key(k1, 0)))
    u2 = float(R.uniform_from(R.counter_key(k1, 1)))
    assert u1 != u2
    assert 0.0 <= u1 < 1.0


def test_multi_process_host_sizes_slots(simple_topology_xml):
    """Multiple processes per host are supported (round 3): the engine
    sizes its process slots from the scenario's max process count.
    tests/test_multiproc.py covers the behavior; this guards the
    config plumbing."""
    from shadow_tpu.core.config import HostSpec, ProcessSpec, Scenario
    from shadow_tpu.engine.sim import Simulation

    scen = Scenario(
        stop_time=10**9,
        topology_graphml=simple_topology_xml,
        hosts=[HostSpec(id="h", processes=[
            ProcessSpec(plugin="pingserver", start_time=0,
                        arguments="port=1"),
            ProcessSpec(plugin="pingserver", start_time=0,
                        arguments="port=2"),
        ])],
    )
    sim = Simulation(scen)
    assert sim.cfg.procs_per_host == 2
    assert sim.hp.app_kind.shape == (1, 2)


def test_engine_caps_cli_parsing(simple_topology_xml, tmp_path):
    """--engine-caps overrides array capacities; malformed input gets a
    clean argparse error, not a traceback."""
    import pytest
    from shadow_tpu.__main__ import main

    cfgfile = tmp_path / "c.xml"
    cfgfile.write_text(f"""<shadow stoptime="1">
      <topology><![CDATA[{simple_topology_xml}]]></topology>
      <host id="a"><process plugin="pingserver" starttime="0"
          arguments="port=1"/></host>
    </shadow>""")
    # valid overrides run end to end
    rc = main([str(cfgfile), "--engine-caps",
               "qcap=32,scap=4,obcap=16,incap=32,chunk=8"])
    assert rc == 0
    # unknown key and non-integer value both exit via argparse
    with pytest.raises(SystemExit):
        main([str(cfgfile), "--engine-caps", "bogus=1"])
    with pytest.raises(SystemExit):
        main([str(cfgfile), "--engine-caps", "qcap=abc"])
