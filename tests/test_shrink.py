"""The shrink campaign (docs/performance.md): narrow at-rest socket
layout, delta-encoded scoreboards, auto-caps and the proof obligations
around them.

Four layers, mirroring the digest/stateflow test philosophy:

1. range audit — every NARROW_SPEC bound is re-derived from the OWNING
   module's constants (MAX_PORT, TCPS_*, buf_cap, the wire's i32 SEQ
   words) and checked against the narrow dtype's range, failing BY
   FIELD NAME, so a constant bump that invalidates a shrink fails the
   suite before it corrupts a run;
2. codec unit — widen/narrow round-trips bit-exactly on live values
   and sentinels, and is the identity (zero traced conversions) on a
   --wide-state tree;
3. lint — STF404 fires on every malformed NARROW_SPEC shape, and the
   memscope NARROW_DTYPES mirror cannot drift from the engine spec;
4. acceptance — same-seed digest chains are byte-identical between a
   narrowed run and its --wide-state twin on the differential
   scenarios (phold, lossy bulk, socks, tgen), pinning that
   canonicalization masks freed slots of relative-encoded scoreboard
   columns exactly like absolute ones.
"""

import importlib
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from shadow_tpu.engine.sim import Simulation          # noqa: E402
from shadow_tpu.engine.state import (                 # noqa: E402
    NARROW_ABS, NARROW_REL, NARROW_SPEC, EngineConfig, alloc_hosts,
    narrow_dtypes)
from shadow_tpu.obs import digest as D                # noqa: E402

_NARROW_MAX = {"i8": 127, "i16": 32767, "i32": 2147483647,
               "u8": 255, "u16": 65535, "u32": 4294967295}

SMALL = dict(qcap=16, scap=4, obcap=8, incap=16, txqcap=8,
             chunk_windows=8)


@pytest.fixture(autouse=True)
def _digest_global_reset():
    yield
    D.finish()


# --- 1. per-field range audit ----------------------------------------------

def _documented_maxima():
    """The largest value each narrowed column can hold at the
    documented maximum scenario parameters, re-derived from the owning
    modules — NOT copied from NARROW_SPEC."""
    from shadow_tpu.core.constants import MAX_PORT
    from shadow_tpu.net.channel import PROTO_PIPE
    from shadow_tpu.net.packet import PROTO_TCP, PROTO_UDP
    from shadow_tpu.net.socket import (CTL_ACKNOW, CTL_FIN, CTL_RST,
                                       CTL_SYN, CTL_SYNACK,
                                       TCPS_TIME_WAIT)

    buf_cap = 1 << 30          # net/tcp.py _apply_buffer_sizes
    wire_seq = 2 ** 31 - 1     # int32 SEQ/ACK/WND packet words
    return {
        # delta-encoded scoreboards: offsets from their window anchor
        # never exceed the buffer that admits the ranges
        "sk_ooo_s": buf_cap, "sk_ooo_e": buf_cap,
        "sk_sack_s": buf_cap, "sk_sack_e": buf_cap,
        # absolute stream offsets ride the wire's int32 words
        "sk_snd_una": wire_seq, "sk_snd_nxt": wire_seq,
        "sk_snd_max": wire_seq, "sk_snd_end": wire_seq,
        "sk_rcv_nxt": wire_seq, "sk_hole_end": wire_seq,
        "sk_rex_nxt": wire_seq, "sk_peer_fin": wire_seq,
        "sk_rtt_seq": wire_seq,
        # buffers/windows are clamped at buf_cap
        "sk_peer_rwnd": buf_cap, "sk_sndbuf": buf_cap,
        "sk_rcvbuf": buf_cap,
        # enums / flags / ports
        "sk_proto": max(PROTO_PIPE, PROTO_TCP, PROTO_UDP),
        "sk_state": TCPS_TIME_WAIT,
        "sk_ctl": CTL_SYN | CTL_SYNACK | CTL_ACKNOW | CTL_FIN | CTL_RST,
        "sk_lport": MAX_PORT, "sk_rport": MAX_PORT,
    }


def test_narrow_spec_range_audit():
    """Every narrowed column's documented maximum fits its NARROW_SPEC
    bound, and the bound fits the narrow dtype — per field, failing by
    field name."""
    maxima = _documented_maxima()
    spec = {e[0]: e for e in NARROW_SPEC}
    assert set(spec) == set(maxima), (
        "NARROW_SPEC and the range audit disagree on WHICH columns "
        f"are narrowed: {set(spec) ^ set(maxima)}")
    for field, (_, wide, narrow, enc, bound, why) in spec.items():
        mx = maxima[field]
        assert mx <= bound, (
            f"{field}: documented maximum {mx} exceeds the NARROW_SPEC "
            f"bound {bound} — the shrink's proof no longer holds")
        assert bound <= _NARROW_MAX[narrow], (
            f"{field}: bound {bound} does not fit {narrow} "
            f"(max {_NARROW_MAX[narrow]})")
        assert why.strip(), f"{field}: empty invariant note"


def test_excluded_columns_stay_wide():
    """Columns the campaign deliberately does NOT narrow: nanosecond
    times/durations exceed i32 (RTO_MAX alone is 1.2e12), and
    sk_dupacks has no provable < 2^15 bound. Their absence from
    NARROW_SPEC is a decision, not an oversight — pin it."""
    narrowed = {e[0] for e in NARROW_SPEC}
    for f in ("sk_rto", "sk_rto_deadline", "sk_srtt", "sk_rttvar",
              "sk_rtt_min", "sk_hs_time", "sk_last_tx", "sk_rtt_time",
              "sk_cc_epoch", "sk_dupacks", "sk_timer_gen"):
        assert f not in narrowed, f"{f} must stay wide (see ISSUE 17)"


# --- 2. the codec ----------------------------------------------------------

def _named(tree):
    from shadow_tpu.engine.checkpoint import named_leaves
    return {k: np.array(v) for k, v in named_leaves(tree)}


def test_codec_round_trip_bit_exact():
    """narrow -> widen -> narrow is the identity on live values,
    sentinels (-1) and anchors; widen reconstructs the absolute
    scoreboard offsets exactly."""
    from shadow_tpu.engine.state import narrow_state, widen_state

    cfg = EngineConfig(num_hosts=2, **SMALL)
    hosts = alloc_hosts(cfg)
    nd = narrow_dtypes(cfg)
    assert nd, "default layout must be narrow"
    assert str(hosts.sk_snd_una.dtype) == "int32"
    assert str(hosts.sk_proto.dtype) == "int8"
    assert str(hosts.sk_lport.dtype) == "uint16"

    import jax.numpy as jnp
    rcv = jnp.array([[123_456_789, 0, 7, 0], [5, 0, 0, 0]], jnp.int32)
    ooo_rel = jnp.full((2, 4, 4), -1, jnp.int32)
    ooo_rel = ooo_rel.at[0, 0, 0].set(1434)       # abs 123_458_223
    ooo_rel = ooo_rel.at[0, 0, 1].set(2 ** 30 - 1)
    hosts = hosts.replace(
        sk_rcv_nxt=rcv, sk_ooo_s=ooo_rel,
        sk_lport=jnp.full((2, 4), 65535, jnp.uint16))

    wide, was_narrow = widen_state(hosts)
    assert was_narrow is True
    assert str(wide.sk_ooo_s.dtype) == "int64"
    w = _named(wide)
    assert w["sk_ooo_s"][0, 0, 0] == 123_456_789 + 1434
    assert w["sk_ooo_s"][0, 0, 1] == 123_456_789 + 2 ** 30 - 1
    assert (w["sk_ooo_s"][1] == -1).all()          # sentinel survives
    assert w["sk_lport"].dtype == np.dtype("int32")
    assert (w["sk_lport"] == 65535).all()

    back = narrow_state(wide)
    a, b = _named(hosts), _named(back)
    for k in a:
        assert a[k].dtype == b[k].dtype, k
        assert np.array_equal(a[k], b[k]), k


def test_widen_is_identity_on_wide_layout():
    """A --wide-state tree passes through untouched: was_narrow False,
    the SAME arrays (no conversion traced at all)."""
    from shadow_tpu.engine.state import widen_state

    cfg = EngineConfig(num_hosts=2, wide_state=1, **SMALL)
    assert narrow_dtypes(cfg) == {}
    hosts = alloc_hosts(cfg)
    assert str(hosts.sk_snd_una.dtype) == "int64"
    out, was_narrow = widen_state(hosts)
    assert was_narrow is False
    assert out.sk_snd_una is hosts.sk_snd_una


def test_canonicalize_masks_freed_rel_slots_like_abs():
    """The satellite-f fix: freed socket rows carrying garbage
    RELATIVE scoreboard values canonicalize identically to a wide
    run's garbage ABSOLUTE values, and live rows decode to the same
    canonical absolutes."""
    from shadow_tpu.engine.window import canonicalize_state

    ncfg = EngineConfig(num_hosts=2, **SMALL)
    wcfg = EngineConfig(num_hosts=2, wide_state=1, **SMALL)
    na, wa = _named(alloc_hosts(ncfg)), _named(alloc_hosts(wcfg))

    # freed rows (sk_used False): DIFFERENT garbage in each encoding
    na["sk_ooo_s"][0, 1, 0] = 55          # stale relative offset
    wa["sk_ooo_s"][0, 1, 0] = 99_999      # stale absolute offset
    na["sk_sack_e"][1, 0, 2] = 7
    wa["sk_sack_e"][1, 0, 2] = -3

    # one LIVE row with equivalent values in both encodings
    for a in (na, wa):
        a["sk_used"][0, 2] = True
        a["sk_rcv_nxt"][0, 2] = 1000
        a["sk_snd_una"][0, 2] = 500
    na["sk_ooo_s"][0, 2, 0] = 34          # rel:  rcv_nxt + 34
    wa["sk_ooo_s"][0, 2, 0] = 1034        # abs
    na["sk_sack_s"][0, 2, 0] = 16         # rel:  snd_una + 16
    wa["sk_sack_s"][0, 2, 0] = 516        # abs

    cn, cw = canonicalize_state(na), canonicalize_state(wa)
    assert set(cn) == set(cw)
    for k in cn:
        assert cn[k].dtype == cw[k].dtype, k
        assert np.array_equal(cn[k], cw[k]), k
    assert cn["sk_ooo_s"][0, 2, 0] == 1034


# --- 3. lint + mirror pins -------------------------------------------------

def test_memscope_narrow_dtypes_mirror_spec():
    """obs.memscope.NARROW_DTYPES is a literal mirror of NARROW_SPEC's
    (field -> narrow dtype) projection — field-for-field."""
    from shadow_tpu.obs import memscope as MS
    assert MS.NARROW_DTYPES == {e[0]: e[2] for e in NARROW_SPEC}


def test_narrow_maps_cover_spec():
    assert set(NARROW_ABS) | set(NARROW_REL) == \
        {e[0] for e in NARROW_SPEC}
    for f, (_, _, anchor) in NARROW_REL.items():
        assert anchor in NARROW_ABS, (f, anchor)


def _stf404(narrow_entries):
    """STF404 violations for a mutated NARROW_SPEC over the real
    repo's state model."""
    from tools.simlint import load
    load()
    core = sys.modules["shadow_tpu.lint.core"]
    stateflow = importlib.import_module("shadow_tpu.lint.stateflow")
    m = stateflow.load_state_model(core.SourceCache(REPO))
    assert not m.errors, m.errors
    m.narrow = narrow_entries
    vs = stateflow._contract_violations(m, {}, None)
    return [v for v in vs if v.rule == "STF404"]


def test_stf404_clean_on_repo_spec():
    assert _stf404([tuple(e) for e in NARROW_SPEC]) == []


def test_stf404_fires_on_malformed_entries():
    ok = ("sk_snd_una", "i64", "i32", "abs", 2147483647, "wire i32")
    cases = [
        (("sk_snd_una", "i64", "i32", "abs", 2147483647), "6-tuple"),
        ([ok, ok], "twice"),
        (("sk_ghost", "i64", "i32", "abs", 1, "x"), "not a Hosts"),
        (("sk_snd_una", "i32", "i8", "abs", 1, "x"), "must agree"),
        (("sk_snd_una", "i64", "i77", "abs", 1, "x"), "unknown dtype"),
        (("sk_snd_una", "i32", "i32", "abs", 1, "x"),
         "not strictly narrower"),
        (("sk_snd_una", "i64", "i32", "abs", 2147483648, "x"),
         "does not fit"),
        (("sk_snd_una", "i64", "i32", "zigzag", 1, "x"),
         "neither 'abs'"),
        (("sk_ooo_s", "i64", "i32", "rel:sk_rto", 1, "x"),
         "not an abs-narrowed"),
        (("sk_snd_una", "i64", "i32", "abs", 2147483647, "  "),
         "empty invariant"),
    ]
    for entry, needle in cases:
        vs = _stf404(entry if isinstance(entry, list) else [entry])
        assert vs, f"no STF404 for {entry!r}"
        assert any(needle in v.message for v in vs), (
            needle, [v.message for v in vs])


# --- auto-caps (lever 3) ---------------------------------------------------

def test_auto_caps_baseline_configs():
    """The declared-peak model on the three baseline families: the
    relay is the fattest spec, and the derived caps keep the base's
    qcap-scap RTO-timer headroom delta."""
    from shadow_tpu.apps.compile import auto_caps
    from tools.baseline_configs import CONFIGS

    expect = {"socks10k": (17, 48, 144), "tor50k": (49, 112, 208),
              "bulk1k": (5, 16, 112)}
    for name, (peak, scap, qcap) in expect.items():
        builder, capf, nd = CONFIGS[name]
        base = capf(nd)
        cfg, info = auto_caps(builder(nd, 60), base)
        assert info["applied"], (name, info)
        assert info["max_peak"] == peak, (name, info["peaks"])
        assert (cfg.scap, cfg.qcap) == (scap, qcap), name
        assert cfg.qcap - cfg.scap >= 16
        assert cfg.obcap <= base.obcap and cfg.txqcap <= base.txqcap


def test_auto_caps_bails_on_unbounded_apps():
    from shadow_tpu.apps.compile import auto_caps
    from shadow_tpu.core.config import HostSpec, ProcessSpec, Scenario

    scen = Scenario(stop_time=10 ** 9, hosts=[
        HostSpec(id="h", processes=[
            ProcessSpec(plugin="hosted:tor", arguments="")])])
    base = EngineConfig(num_hosts=1, **SMALL)
    cfg, info = auto_caps(scen, base)
    assert not info["applied"] and "hosted" in info["why"]
    assert cfg is base


def test_capacity_plan_self_check_and_gap_table():
    from tools import capacity_plan as CP
    assert CP.self_check() == 0
    census = {"per_host": 100, "hosts": {"fields": {
        "fat": {"bytes": 0, "per_host": 60, "dtype": "int64",
                "section": "s"},
        "thin": {"bytes": 0, "per_host": 40, "dtype": "int32",
                 "section": "s"}}}}
    g = CP.gap_table(census, 50)
    assert [r["field"] for r in g["rows"]] == ["fat"]  # 60 covers 50
    assert g["covered"] and not g["met"]
    assert CP.gap_table(census, 200)["met"]


# --- 4. acceptance: wide-vs-narrow digest parity ---------------------------

def _parity(tmp_path, name, scen_fn, n_hosts, cfg_kwargs, stop_hint=""):
    """Same-seed, same-scenario runs at the two layouts must produce
    byte-identical digest chains (the canonical form is the wide
    layout, by construction)."""
    chains = []
    for tag, wide in (("narrow", 0), ("wide", 1)):
        p = tmp_path / f"{name}-{tag}.jsonl"
        sim = Simulation(scen_fn(),
                         engine_cfg=EngineConfig(num_hosts=n_hosts,
                                                 wide_state=wide,
                                                 **cfg_kwargs))
        sim.run(digest=str(p), digest_every=4)
        chains.append(open(p, "rb").read())
    assert chains[0], f"{name}: empty digest chain"
    assert chains[0] == chains[1], (
        f"{name}: digest chain differs between the narrow layout and "
        "its --wide-state twin")


def test_parity_phold(tmp_path):
    from test_phold import phold_scenario
    _parity(tmp_path, "phold", lambda: phold_scenario(n=8, stop=4), 8,
            SMALL)


def test_parity_lossy_bulk(tmp_path):
    """The satellite-f dual-run pin: loss creates OOO/SACK scoreboard
    churn AND freed socket rows with stale relative offsets — parity
    proves canonicalization masks them like the wide run's stale
    absolutes."""
    from test_differential import _bulk_scen
    _parity(tmp_path, "lossy-bulk",
            _bulk_scen(loss=0.05, size=120_000, count=2, stop=40), 2,
            SMALL)


@pytest.mark.slow
def test_parity_socks(tmp_path):
    from test_differential import SOCKS_CFG, _socks_scen
    _parity(tmp_path, "socks", _socks_scen(hops=2, clients=3, stop=40),
            8, SOCKS_CFG)


@pytest.mark.slow
def test_parity_tgen(tmp_path, simple_topology_xml):
    from test_tgen import tgen_scenario

    lossy = simple_topology_xml.replace('<data key="d9">0.0</data>',
                                        '<data key="d9">0.03</data>')
    _parity(tmp_path, "tgen",
            lambda: tgen_scenario(lossy, n_web=2, n_bulk=1, stop=30), 5,
            dict(qcap=24, scap=6, obcap=12, incap=16, txqcap=8,
                 chunk_windows=8))
