"""TCP end-to-end tests over the bulk-transfer app pair.

Mirrors the reference's TCP test matrix idea
(/root/reference/src/test/tcp/CMakeLists.txt: blocking/epoll x
loopback/lossless/lossy): the same transfer scenario is run over a
lossless and a lossy link, asserting full delivery (retransmission
recovers every dropped segment) and determinism.
"""

import numpy as np
import pytest

from shadow_tpu.core.config import HostSpec, ProcessSpec, Scenario
from shadow_tpu.engine import defs
from shadow_tpu.engine.sim import Simulation
from shadow_tpu.engine.state import EngineConfig


def poi_topology(loss=0.0, bw_down=20480, bw_up=10240, latency_ms=20.0):
    return f"""
<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="latency" attr.type="double" for="edge" id="d7"/>
  <key attr.name="packetloss" attr.type="double" for="edge" id="d9"/>
  <key attr.name="packetloss" attr.type="double" for="node" id="d0"/>
  <key attr.name="bandwidthup" attr.type="int" for="node" id="d4"/>
  <key attr.name="bandwidthdown" attr.type="int" for="node" id="d3"/>
  <graph edgedefault="undirected">
    <node id="poi"><data key="d0">0.0</data>
      <data key="d3">{bw_down}</data><data key="d4">{bw_up}</data></node>
    <edge source="poi" target="poi"><data key="d7">{latency_ms}</data>
      <data key="d9">{loss}</data></edge>
  </graph>
</graphml>
"""


def bulk_scenario(topology, size=1_000_000, count=2, stop=120, clients=1,
                  seed=1):
    return Scenario(
        stop_time=stop * 10**9,
        seed=seed,
        topology_graphml=topology,
        hosts=[
            HostSpec(id="server", processes=[
                ProcessSpec(plugin="bulkserver", start_time=10**9,
                            arguments="port=80")]),
            HostSpec(id="client", quantity=clients, processes=[
                ProcessSpec(plugin="bulk", start_time=2 * 10**9,
                            arguments=f"peer=server port=80 size={size} "
                                      f"count={count} pause=1s")]),
        ],
    )


def test_bulk_lossless():
    """All bytes transfer, both ends count completion, no drops."""
    rep = Simulation(bulk_scenario(poi_topology())).run()
    s = rep.summary()
    assert s["bytes_recv"] == 2_000_000
    assert s["transfers_done"] == 4          # 2 client-side + 2 server-side
    assert s["drop_net"] == 0 and s["drop_buf"] == 0 and s["drop_q"] == 0
    assert s["retransmits"] == 0
    # both apps reached DONE... client counts APP_DONE; server never ends
    assert rep.stats[1, defs.ST_APP_DONE] == 1


def test_bulk_lossy_recovers():
    """On a 2%-loss link every dropped segment is retransmitted and the
    stream still completes in full — the lossy-link test of the
    reference matrix."""
    rep = Simulation(bulk_scenario(poi_topology(loss=0.02),
                                   size=300_000, count=1)).run()
    s = rep.summary()
    assert s["drop_net"] > 0                 # losses actually happened
    assert s["retransmits"] > 0              # and were recovered
    assert s["bytes_recv"] == 300_000        # in full
    assert s["transfers_done"] == 2


def test_bulk_multi_client():
    """Several clients against one server: per-connection demux into
    child sockets must keep streams independent."""
    rep = Simulation(bulk_scenario(poi_topology(bw_down=102400),
                                   size=100_000, count=1, clients=4)).run()
    s = rep.summary()
    assert s["bytes_recv"] == 400_000
    # 4 client completions + 4 server-side EOFs
    assert s["transfers_done"] == 8


def test_bulk_deterministic():
    a = Simulation(bulk_scenario(poi_topology(loss=0.02), size=200_000)).run()
    b = Simulation(bulk_scenario(poi_topology(loss=0.02), size=200_000)).run()
    assert np.array_equal(a.stats, b.stats)
    assert a.windows == b.windows


def test_bulk_seed_changes_loss_pattern():
    a = Simulation(bulk_scenario(poi_topology(loss=0.05), size=200_000,
                                 seed=1)).run()
    b = Simulation(bulk_scenario(poi_topology(loss=0.05), size=200_000,
                                 seed=2)).run()
    # different loss rolls => different retransmit counts (overwhelmingly
    # likely at 5% loss over ~140 segments each way)
    assert not np.array_equal(a.stats, b.stats)


@pytest.mark.parametrize("cc", [0, 1, 2], ids=["aimd", "reno", "cubic"])
def test_bulk_all_congestion_kinds(cc):
    scen = bulk_scenario(poi_topology(loss=0.01), size=200_000, count=1)
    cfg = EngineConfig(num_hosts=scen.total_hosts(), cc_kind=cc)
    rep = Simulation(scen, engine_cfg=cfg).run()
    assert rep.summary()["bytes_recv"] == 200_000


def test_bulk_throughput_tracks_bandwidth():
    """Sanity-check the NIC pacing: a 10 KiB/s uplink moving 1 MB with
    cubic should take roughly bytes/bandwidth seconds, not complete
    near-instantly nor stall."""
    rep = Simulation(bulk_scenario(poi_topology(), size=500_000, count=1,
                                   stop=300)).run()
    # client uplink 10240*1024 B/s? bandwidths in the graphml are KiB/s
    # (reference semantics); transfer must complete within the sim.
    assert rep.summary()["transfers_done"] == 2


def test_odd_bw_stamp_does_not_fake_finack():
    """Regression: handshake segments carry the peer's bandwidths in
    AUX, so a peer whose bw_down>>10 is odd (e.g. 12207 KiB/s ~ 100
    Mbit/s) used to flip AUX bit 0 = AUX_FINACK on its SYN|ACK, and the
    active opener spuriously marked its (never-sent) FIN as acked.
    With the ~syn guard, no established-but-open socket may have
    fin_acked set."""
    topo = poi_topology(bw_down=977, bw_up=977, latency_ms=20.0)
    # stop mid-transfer so connections are still open at snapshot time
    # (977 KiB/s ~ 1 MB/s moves ~3 MB of the 5 MB by the 5 s stop)
    scen = bulk_scenario(topo, size=5_000_000, count=1, stop=5)
    sim = Simulation(scen, engine_cfg=EngineConfig(num_hosts=2, qcap=64,
                                                   scap=4, obcap=32,
                                                   incap=64,
                                                   chunk_windows=8))
    sim.run()
    import numpy as np
    from shadow_tpu.net.tcp import TCPS_ESTABLISHED
    states = np.asarray(sim.final_hosts.sk_state)
    fin_acked = np.asarray(sim.final_hosts.sk_fin_acked)
    assert (states == TCPS_ESTABLISHED).sum() >= 2   # both ends open
    assert not fin_acked.any(), "FINACK leaked from a handshake bw stamp"
