"""TCP buffer autotuning (reference shd-tcp.c:340-433)."""

import numpy as np

from shadow_tpu.core.config import HostSpec, ProcessSpec, Scenario
from shadow_tpu.engine.sim import Simulation
from shadow_tpu.engine.state import EngineConfig
from shadow_tpu.core.constants import RECV_BUFFER_MIN_SIZE

import test_tcp as T

CFG = dict(qcap=64, scap=4, obcap=32, incap=64, chunk_windows=8)


def test_autotune_sizes_buffers_from_bdp():
    # 100ms latency x 50 MiB/s bottleneck -> BDP ~5.2MB; default fixed
    # buffers (174760) would cap the window far below that.
    topo = T.poi_topology(bw_down=51200, bw_up=51200, latency_ms=100.0)
    scen = T.bulk_scenario(topo, size=400_000, count=1, stop=60)
    sim = Simulation(scen, engine_cfg=EngineConfig(num_hosts=2, **CFG))
    rep = sim.run()
    assert rep.summary()["bytes_recv"] == 400_000
    rcvbuf = np.asarray(sim.final_hosts.sk_rcvbuf)
    # the server-side child's receive buffer autotuned to ~1.25x BDP
    # (rtt 200ms x min-bw 52428800 B/s x 1.25 ~ 13.1 MB)
    assert rcvbuf.max() > 10_000_000, rcvbuf.max()


def test_explicit_buffer_disables_autotune():
    topo = T.poi_topology(bw_down=51200, bw_up=51200, latency_ms=100.0)
    scen = T.bulk_scenario(topo, size=200_000, count=1, stop=60)
    for h in scen.hosts:
        h.socket_recv_buffer = RECV_BUFFER_MIN_SIZE
    sim = Simulation(scen, engine_cfg=EngineConfig(num_hosts=2, **CFG))
    rep = sim.run()
    rcvbuf = np.asarray(sim.final_hosts.sk_rcvbuf)
    assert rep.summary()["bytes_recv"] == 200_000
    # no socket ballooned to the BDP — autotuning stayed off
    # (unestablished sockets keep the allocation default)
    assert rcvbuf.max() <= max(RECV_BUFFER_MIN_SIZE, 174760)
