"""Native (C ABI) hosted plugin test — the analogue of the reference's
plugin-hosting tests (a real compiled .so drives simulated sockets)."""

import ctypes
import os

import pytest

from shadow_tpu.core.config import HostSpec, ProcessSpec, Scenario
from shadow_tpu.engine import defs
from shadow_tpu.engine.sim import Simulation
from shadow_tpu.engine.state import EngineConfig
from shadow_tpu.hosting.cplugin import build_plugin, register_c_plugin

from test_phold import MESH_TOPO

C_SRC = os.path.join(os.path.dirname(__file__), "..", "examples",
                     "plugins", "cping.c")


@pytest.fixture(scope="module")
def cping_registered():
    try:
        build_plugin(C_SRC)
    except Exception as e:
        pytest.skip(f"no native toolchain: {e}")
    register_c_plugin("cping", C_SRC)
    return True


def test_c_plugin_pings(cping_registered):
    scen = Scenario(
        stop_time=8 * 10**9,
        topology_graphml=MESH_TOPO,
        hosts=[
            HostSpec(id="server", processes=[
                ProcessSpec(plugin="pingserver", start_time=10**9,
                            arguments="port=8000")]),
            HostSpec(id="cli", processes=[
                ProcessSpec(plugin="hosted:cping", start_time=2 * 10**9,
                            arguments="peer=server port=8000 count=4 "
                                      "interval_ms=800 size=100")]),
        ],
    )
    sim = Simulation(scen, engine_cfg=EngineConfig(
        num_hosts=2, qcap=32, scap=8, obcap=16, incap=32, txqcap=8))
    app = sim.hosting.apps[1]
    report = sim.run()

    lib = app.lib
    lib.plugin_get_sent.restype = ctypes.c_int
    lib.plugin_get_sent.argtypes = [ctypes.c_void_p]
    lib.plugin_get_echoed.restype = ctypes.c_int
    lib.plugin_get_echoed.argtypes = [ctypes.c_void_p]
    assert lib.plugin_get_sent(app.state) == 4
    assert lib.plugin_get_echoed(app.state) == 4
    # the server saw all four datagrams (100 bytes each)
    assert report.stats[0, defs.ST_BYTES_RECV] == 400
