"""PHOLD stress test: N hosts randomly messaging each other.

Mirrors the role of the reference's phold plugin test
(src/test/phold/shd-test-phold.c): exercises the scheduler/exchange
machinery under all-to-all random traffic, and doubles as the
determinism check (any divergence changes message counts).
"""

import numpy as np

from shadow_tpu.core.config import HostSpec, ProcessSpec, Scenario
from shadow_tpu.engine import defs
from shadow_tpu.engine.sim import Simulation

MESH_TOPO = """
<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="latency" attr.type="double" for="edge" id="d7"/>
  <key attr.name="packetloss" attr.type="double" for="edge" id="d9"/>
  <key attr.name="packetloss" attr.type="double" for="node" id="d0"/>
  <key attr.name="bandwidthup" attr.type="int" for="node" id="d4"/>
  <key attr.name="bandwidthdown" attr.type="int" for="node" id="d3"/>
  <graph edgedefault="undirected">
    <node id="poi"><data key="d0">0.0</data>
      <data key="d3">10240</data><data key="d4">10240</data></node>
    <edge source="poi" target="poi"><data key="d7">25.0</data>
      <data key="d9">0.0</data></edge>
  </graph>
</graphml>
"""


def phold_scenario(n=16, stop=5):
    return Scenario(
        stop_time=stop * 10**9,
        topology_graphml=MESH_TOPO,
        hosts=[HostSpec(id="node", quantity=n, processes=[
            ProcessSpec(plugin="phold", start_time=10**9,
                        arguments="port=9000 mean=200ms size=64 init=2")])],
    )


def test_phold_runs_and_conserves_messages():
    report = Simulation(phold_scenario()).run()
    s = report.summary()
    # traffic flowed across many hosts
    assert s["pkts_sent"] > 100
    assert s["drop_net"] == 0
    # lossless network: everything sent before the horizon is received;
    # allow in-flight messages at the stop time
    assert 0 <= s["pkts_sent"] - s["pkts_recv"] <= report.stats.shape[0] * 4
    # every host participated
    per_host_events = report.stats[:, defs.ST_EVENTS]
    assert (per_host_events > 0).all()


def test_phold_deterministic_and_seed_sensitive():
    r1 = Simulation(phold_scenario()).run()
    r2 = Simulation(phold_scenario()).run()
    assert np.array_equal(r1.stats, r2.stats)
    r3 = Simulation(phold_scenario(), seed=99).run()
    assert not np.array_equal(r1.stats, r3.stats)
