"""Memory-observatory tests (obs.memscope + tools/capacity_plan.py):

- census EXACTNESS: the stdlib dims table == eval_shape over the real
  alloc_hosts == live array bytes, field by field, plus hand-computed
  spot checks — the pin that keeps the jax-free byte table honest;
- hot/cold rollup parity with the HOT_FIELDS/COLD_WHEN declaration;
- the unified HBM-peak constant: a custom SHADOW_TPU_HBM_GBPS reaches
  both the run's cost bookkeeping and the cost_model report;
- compiled-program capture: cost/memory analysis on CPU, graceful
  absence on refusing executables;
- the run-wired record: SimReport.memory, summary/ledger fields, the
  tracker's dev watermark column, the metrics.json `memory` section;
- the perf_regress MEMORY gate: flat history exit 0, synthetic peak
  regression exit 1, pre-memscope history untouched;
- the capacity planner: plan() arithmetic on synthetic measurements
  and predict-vs-measure within tolerance on a real run;
- observation-does-not-perturb-digest for a fully-observed run.

The run-based tests share one tiny phold shape so the process pays
one window-program compile (the AotJit memoizes per (cfg, chunk)).
Like test_perf, this file sorts past the compile-bound tier-1 horizon
on the CPU container; the pure-unit tests up top cost milliseconds.
"""

import importlib.util
import json
import os
import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent

import jax  # noqa: E402

from shadow_tpu.engine.state import (COLD_FIELDS, HOT_FIELDS,  # noqa: E402
                                     EngineConfig, Hosts, alloc_hosts,
                                     hot_fields, shape_census)
from shadow_tpu.obs import ledger as LG  # noqa: E402
from shadow_tpu.obs import memscope as MS  # noqa: E402


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, REPO / "tools" / f"{name}.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


SMALL = dict(qcap=8, scap=4, obcap=8, incap=8, txqcap=4)


# --- census exactness -------------------------------------------------------

def test_census_exactness_small_config():
    """The stdlib dims table == eval_shape over the real alloc_hosts
    == live array bytes, for EVERY field — plus hand-computed spot
    checks, so a wrong table AND a wrong alloc cannot cancel out."""
    cfg = EngineConfig(num_hosts=4, **SMALL)
    sc = shape_census(cfg)
    assert set(sc) == set(Hosts.__dataclass_fields__)
    table = MS.table_row_bytes(cfg)
    np_bytes = {"int64": 8, "int32": 4, "uint32": 4, "float32": 4,
                "bool": 1, "int16": 2, "uint16": 2, "int8": 1}
    for f, (shape, dt) in sc.items():
        n = np_bytes[dt]
        for d in shape:
            n *= d
        assert table[f] == n // 4, \
            f"{f}: stdlib table {table[f]} != eval_shape {n // 4}"
    # live arrays agree (the census's hosts= path)
    hosts = alloc_hosts(cfg)
    census = MS.state_census(cfg, hosts=hosts)
    live = sum(int(np.prod(a.shape)) * a.dtype.itemsize
               for a in jax.tree.leaves(hosts))
    assert census["hosts"]["bytes"] == live
    # cfg-only path (eval_shape) computes the same totals
    census2 = MS.state_census(cfg)
    assert census2["hosts"]["bytes"] == census["hosts"]["bytes"]
    # hand-computed spot checks: eq_time [4, 8] i64, eq_pkt
    # [4, 8, 13] i32, sk_ooo_s [4, 4, 4] i32 at rest (delta-encoded
    # narrow layout — i64 under the --wide-state escape hatch),
    # stats [4, 24] i64
    fl = census["hosts"]["fields"]
    assert fl["eq_time"]["bytes"] == 4 * 8 * 8
    assert fl["eq_pkt"]["bytes"] == 4 * 8 * 13 * 4
    assert fl["sk_ooo_s"]["bytes"] == 4 * 4 * 4 * 4
    assert fl["stats"]["bytes"] == 4 * 24 * 8
    import dataclasses
    wcfg = dataclasses.replace(cfg, wide_state=1)
    wfl = MS.state_census(wcfg)["hosts"]["fields"]
    assert wfl["sk_ooo_s"]["bytes"] == 4 * 4 * 4 * 8
    assert fl["eq_time"]["section"] == "event_queue"
    # HostParams table matches the real thing too (via a built sim in
    # the run tests; here the dims): hid i32 -> 4 B/host
    assert MS.table_row_bytes(cfg, MS.HP_DIMS)["hid"] == 4
    assert MS.table_row_bytes(cfg, MS.HP_DIMS)["app_cfg"] == 8 * 8


def test_census_constants_match_modules():
    """The stdlib table's literal constants mirror their owning
    modules — the drift pin the module docstring promises."""
    from shadow_tpu.engine.defs import N_STATS
    from shadow_tpu.net.packet import PKT_WORDS
    from shadow_tpu.net.sack import K
    assert MS.PKT_WORDS == PKT_WORDS
    assert MS.SACK_K == K
    assert MS.N_STATS == N_STATS


def test_census_hot_cold_rollup_parity():
    """The census's hot/cold rollup is EXACTLY the HOT_FIELDS /
    COLD_FIELDS partition, and the runtime rollup follows
    hot_fields(cfg) — so the split's HBM saving is the number the
    declaration implies, not an independent re-derivation."""
    cfg = EngineConfig(num_hosts=8, **SMALL)
    c = MS.state_census(cfg)
    fl = c["hosts"]["fields"]
    hot_b = sum(v["bytes"] for f, v in fl.items() if f in HOT_FIELDS)
    cold_b = sum(v["bytes"] for f, v in fl.items() if f in COLD_FIELDS)
    assert c["hosts"]["hot"]["static_bytes"] == hot_b
    assert c["hosts"]["hot"]["static_cold_bytes"] == cold_b
    assert hot_b + cold_b == c["hosts"]["bytes"]
    rt = set(hot_fields(cfg))
    rt_b = sum(v["bytes"] for f, v in fl.items() if f in rt)
    assert c["hosts"]["hot"]["runtime_bytes"] == rt_b
    assert c["hosts"]["hot"]["runtime_columns"] == len(rt)
    # a no-TCP config's runtime working set is much smaller: the
    # level-2 split's saving as bytes
    import dataclasses
    udp = dataclasses.replace(cfg, uses_tcp=False, app_kinds=(0,))
    cu = MS.state_census(udp)
    assert (cu["hosts"]["hot"]["runtime_bytes"]
            < c["hosts"]["hot"]["runtime_bytes"])
    # sections rollup covers every byte exactly once
    assert sum(c["hosts"]["sections"].values()) == c["hosts"]["bytes"]


def test_shared_per_host_classification_by_name():
    """The Shared fixed-vs-per-host split is classified by the
    DECLARED names, pinned against the live tree: exactly the
    [H]-replicated tables scale, and each really has leading dim H —
    a shape[0]==H coincidence (e.g. an [H,H] oracle of a
    one-vertex-per-host topology) must never reclassify the fixed
    tables as linear."""
    from shadow_tpu.engine.sim import Simulation
    from test_phold import phold_scenario

    sim = Simulation(phold_scenario(n=4, stop=1),
                     engine_cfg=EngineConfig(num_hosts=4, **SMALL))
    c = MS.state_census(sim.cfg, hosts=sim.hosts, hp=sim.hp,
                        sh=sim.sh)
    scaling = {f for f, v in sorted(c["shared"]["fields"].items())
               if v["scales_with_h"]}
    assert scaling == set(MS.SHARED_PER_HOST_FIELDS)
    for f in MS.SHARED_PER_HOST_FIELDS:
        assert getattr(sim.sh, f).shape[0] == 4, \
            f"declared per-host Shared field {f} is not [H]"
    # the oracle tables stay fixed cost
    assert not c["shared"]["fields"]["lat_ns"]["scales_with_h"]


# --- HBM peak unification ---------------------------------------------------

def test_hbm_peak_env(monkeypatch):
    monkeypatch.delenv("SHADOW_TPU_HBM_GBPS", raising=False)
    assert MS.hbm_peak_gbps() == MS.DEFAULT_HBM_GBPS
    monkeypatch.setenv("SHADOW_TPU_HBM_GBPS", "500")
    assert MS.hbm_peak_gbps() == 500.0
    monkeypatch.setenv("SHADOW_TPU_HBM_GBPS", "not-a-number")
    assert MS.hbm_peak_gbps() == MS.DEFAULT_HBM_GBPS


def test_hbm_peak_reaches_cost_model_and_report(monkeypatch):
    """Satellite: a custom SHADOW_TPU_HBM_GBPS reaches BOTH the run's
    pass-cost bookkeeping (cost dict) and the cost_model report — the
    two sites that used to carry their own 819."""
    from shadow_tpu.engine.sim import SimReport, Simulation
    from test_phold import phold_scenario

    monkeypatch.setenv("SHADOW_TPU_HBM_GBPS", "500")
    report = Simulation(phold_scenario(n=16, stop=5)).run()
    assert report.cost["hbm_peak_gbps"] == 500.0
    cm = report.cost_model()
    assert cm["hbm_peak_gbps"] == 500.0
    # the roofline fraction divides by the custom peak
    assert cm["roofline_frac"] == pytest.approx(
        cm["achieved_gbps_est"] / 500.0)
    # the fallback path (a cost dict that predates the key) reads the
    # same definition
    r2 = SimReport(stats=report.stats, host_names=report.host_names,
                   sim_time_ns=report.sim_time_ns, wall_seconds=1.0,
                   windows=report.windows,
                   cost={k: v for k, v in report.cost.items()
                         if k != "hbm_peak_gbps"})
    assert r2.cost_model()["hbm_peak_gbps"] == 500.0


# --- compiled-program capture ----------------------------------------------

def test_capture_smoke_cpu():
    """CPU provides both analyses in this build: flops/bytes-accessed
    and argument/output/temp bytes all land."""
    import jax.numpy as jnp
    comp = jax.jit(lambda x: x * 2 + 1).lower(
        jnp.zeros((8, 8), jnp.float32)).compile()
    a = MS.observe_executable("smoke", comp)
    assert a["available"]
    assert a["bytes_accessed"] and a["bytes_accessed"] > 0
    assert a["argument_bytes"] == 8 * 8 * 4
    assert a["output_bytes"] == 8 * 8 * 4
    assert MS.program_footprint(a) is not None
    assert MS.CAPTURED["smoke"] is a


def test_capture_graceful_absence():
    """Backends/executables that refuse either analysis record the
    error and carry None — never an exception (the contract for TPU
    variants and disk-loaded executables)."""

    class Refuses:
        def cost_analysis(self):
            raise NotImplementedError("no cost analysis on this "
                                      "backend")

        def memory_analysis(self):
            return None

    a = MS.observe_executable("refuses", Refuses())
    assert not a["available"]
    assert a["flops"] is None and a["argument_bytes"] is None
    assert "cost_analysis" in a["errors"]
    assert "memory_analysis" in a["errors"]
    assert MS.program_footprint(a) is None
    assert MS.observe_executable("none", None)["available"] is False


# --- watermark --------------------------------------------------------------

def test_watermark_rss_fallback():
    wm = MS.Watermark()
    p1 = wm.sample()
    assert p1 > 0
    # monotone peak
    big = np.ones(1 << 22, np.int64)  # ~32 MB
    p2 = wm.sample()
    assert p2 >= p1
    del big
    snap = wm.snapshot()
    assert snap["peak_bytes"] == p2
    assert snap["source"] in ("rss", "device")
    assert snap["samples"] >= 2
    assert snap["lifetime_peak_bytes"] >= snap["peak_bytes"] or \
        snap["source"] == "device"
    if snap["source"] == "rss":
        assert snap["per_device"] is None


def test_watermark_is_per_run_not_process_lifetime():
    """The gated peak is the RUN's high water, not the process's: a
    watermark created after a large allocation died must not inherit
    its peak (ru_maxrss would — bench.py's 4-config matrix runs in
    one process, and a small scenario after a large one would record
    the large one's bytes as its own and poison the memory gate)."""
    big = np.ones(1 << 25, np.int64)  # ~256 MB, mmap-backed
    big[::4096] = 2                   # fault the pages in
    lifetime_with_big = MS.rss_bytes()
    del big
    # current RSS dropped well below the lifetime peak once the block
    # was unmapped...
    assert MS.current_rss_bytes() < lifetime_with_big - (1 << 27)
    # ...and a fresh watermark reports the CURRENT level, not the
    # lifetime one
    wm = MS.Watermark()
    wm.sample()
    snap = wm.snapshot()
    if snap["source"] == "rss":
        assert snap["peak_bytes"] < lifetime_with_big - (1 << 27)
        assert snap["lifetime_peak_bytes"] >= lifetime_with_big


# --- the run-wired record ---------------------------------------------------

def test_run_memory_record(tmp_path):
    """A real run carries the full observatory record: watermark,
    census totals, captured XLA analysis (argument bytes == census +
    the two window scalars), summary/ledger fields, the tracker's dev
    watermark, and the metrics.json memory section."""
    from shadow_tpu.engine.sim import Simulation
    from shadow_tpu.obs import metrics as MT
    from test_phold import phold_scenario

    mpath = str(tmp_path / "metrics.json")
    sim = Simulation(phold_scenario(n=16, stop=5))
    report = sim.run(heartbeat_s=1.0, metrics=mpath)
    mem = report.memory
    assert mem["peak_bytes"] > 0
    assert mem["source"] in ("rss", "device")
    assert mem["state_bytes"] > 0
    assert 0 < mem["hot_state_bytes"] <= mem["state_bytes"]
    census = MS.state_census(sim.cfg, hosts=sim.final_hosts,
                             hp=sim.hp, sh=sim.sh)
    assert mem["state_bytes"] == census["bytes"]
    assert mem["state_bytes_per_host"] == census["per_host"]
    xla = mem["xla"]
    if xla["argument_bytes"] is not None:  # CPU provides it here
        assert xla["argument_bytes"] == census["bytes"] + 16
        cm = report.cost_model()
        assert cm["measured"]
        assert cm["roofline_frac"] == pytest.approx(
            cm["roofline_frac_measured"])
        assert "roofline_frac_modeled" in cm
    # summary carries the ledger fields
    s = report.summary()
    assert s["mem_peak_bytes"] == mem["peak_bytes"]
    assert s["state_bytes_per_host"] == mem["state_bytes_per_host"]
    # ledger entry round-trip
    e = LG.make_entry("memscope-test", "fp", "cpu", s)
    assert e["mem_peak_bytes"] == mem["peak_bytes"]
    assert e["state_bytes_per_host"] == mem["state_bytes_per_host"]
    # tracker: the summary heartbeat carries the watermark column
    summaries = [l for l in report.heartbeats if "[summary]" in l]
    assert summaries and all("dev-peak-gib=" in l for l in summaries)
    # metrics.json memory section
    m = json.load(open(mpath))
    assert "memory" in m
    assert m["memory"]["peak_bytes"] == mem["peak_bytes"]
    assert m["memory"]["state_bytes_per_host"] == \
        mem["state_bytes_per_host"]
    assert m["memory"]["cost"].get("bytes_accessed") is not None


def test_tracker_ram_dev_column():
    """[ram] lines (buffered-bytes hosts) gain the trailing dev=
    watermark column beside the modeled bytes and rss=."""
    from shadow_tpu.obs.tracker import Tracker

    tr = Tracker(10**9, ["a", "b"])
    socks = {
        "sk_used": np.array([[True], [False]]),
        "sk_proto": np.array([[6], [0]]),
        "sk_rhost": np.array([[1], [-1]]),
        "sk_rport": np.array([[80], [0]]),
        "sk_snd_una": np.array([[100], [0]]),
        "sk_snd_end": np.array([[500], [0]]),
        "sk_sndbuf": np.array([[4096], [4096]]),
        "sk_rcv_nxt": np.array([[0], [0]]),
        "sk_rcvbuf": np.array([[4096], [4096]]),
        "ooo_held": np.array([[0], [0]]),
    }
    stats = np.zeros((2, 24), np.int64)
    stats[0, 0] = 5
    tr.maybe_heartbeat(2 * 10**9, stats, socks=socks,
                       hosted_rss={0: 12345}, dev_peak=777)
    ram = [l for l in tr.lines if "[ram]" in l]
    assert ram
    assert any("rss=12345" in l and "dev=777" in l for l in ram)


# --- the memory regression gate --------------------------------------------

def _entry(rate=100.0, mem=None, fp="f0"):
    s = {"events": 1000, "wall_seconds": 1000 / rate,
         "events_per_sec": rate, "sim_seconds": 5.0, "windows": 10}
    if mem is not None:
        s["mem_peak_bytes"] = mem
        s["mem_source"] = "rss"
        s["state_bytes_per_host"] = 4510
    return LG.make_entry("memgate", fp, "cpu", s)


def test_memory_gate_flat_history_ok(tmp_path):
    pr = _load_tool("perf_regress")
    path = str(tmp_path / "l.jsonl")
    for r, m in ((100, 10_000), (101, 10_100), (99, 9_900),
                 (100, 10_050)):
        LG.append(_entry(rate=r, mem=m), path)
    assert pr.main([path]) == 0


def test_memory_gate_synthetic_regression_exits_1(tmp_path):
    """Acceptance: a synthetic memory regression (peak doubles at a
    flat rate) exits 1 with the memory row marked."""
    pr = _load_tool("perf_regress")
    path = str(tmp_path / "l.jsonl")
    for r, m in ((100, 10_000), (101, 10_100), (99, 9_900)):
        LG.append(_entry(rate=r, mem=m), path)
    LG.append(_entry(rate=100, mem=20_000), path)
    results, reg = pr.check(LG.read(path))
    assert reg
    assert results[0]["mem_status"] == "REGRESSION"
    assert results[0]["status"] == "ok"  # the RATE did not regress
    assert pr.main([path]) == 1


def test_memory_gate_band_and_direction(tmp_path):
    """Memory regresses UP: a peak DROP never gates, and growth
    within the band passes."""
    pr = _load_tool("perf_regress")
    path = str(tmp_path / "l.jsonl")
    for m in (10_000, 10_200, 9_800):
        LG.append(_entry(mem=m), path)
    LG.append(_entry(mem=5_000), path)        # big drop: fine
    assert pr.main([path]) == 0
    LG.append(_entry(mem=11_000), path)       # +10% < 15% band: fine
    assert pr.main([path]) == 0


def test_memory_gate_ignores_pre_memscope_history(tmp_path):
    """Entries without mem_peak_bytes (the committed pre-PR-15 ledger)
    neither gate nor feed a baseline — the first memscope-carrying
    entry starts the byte trajectory without failing it."""
    pr = _load_tool("perf_regress")
    path = str(tmp_path / "l.jsonl")
    for r in (100, 101, 99):
        LG.append(_entry(rate=r), path)       # no mem fields
    LG.append(_entry(rate=100, mem=50_000_000), path)
    results, reg = pr.check(LG.read(path))
    assert not reg
    assert "mem_status" not in results[0]
    assert pr.main([path]) == 0


# --- fleet admission from measured bytes ------------------------------------

def test_fleet_rss_weight_from_measured_bytes():
    """fleet submit --mem-bytes-per-host: the admission RSS weight
    becomes hosts x measured per-host bytes (MiB, rounded up);
    explicit --rss-mb always wins."""
    import types

    from shadow_tpu.fleet.cli import _rss_weight

    a = types.SimpleNamespace(rss_mb=0, mem_bytes_per_host=102_471)
    # 10_000 hosts x ~100 KB = ~977 MiB
    assert _rss_weight(a, 10_000) == -(-10_000 * 102_471 // (1 << 20))
    assert _rss_weight(a, 10_000) == 978
    a2 = types.SimpleNamespace(rss_mb=512, mem_bytes_per_host=102_471)
    assert _rss_weight(a2, 10_000) == 512
    a3 = types.SimpleNamespace(rss_mb=0, mem_bytes_per_host=0)
    assert _rss_weight(a3, 10_000) == 0


# --- the capacity planner ---------------------------------------------------

def _fake_measured(H=100, per_host=1000, fixed=5000, temp_ph=500,
                   arg_err=0.0):
    state = per_host * H + fixed
    return {
        "config": "synthetic", "hosts": H, "stop_s": 1,
        "census": {
            "H": H, "bytes": state, "per_host": per_host,
            "fixed_bytes": fixed,
            "hosts": {"hot": {"runtime_bytes": per_host * H // 2}},
        },
        "memory": {
            "peak_bytes": 2 * state, "source": "rss",
            "per_device": None,
            "xla": {"argument_bytes":
                    int((state + 16) * (1 + arg_err)),
                    "temp_bytes": temp_ph * H, "output_bytes": 0,
                    "alias_bytes": 0, "generated_code_bytes": 100,
                    "errors": {}},
        },
        "events": 1,
    }


def test_planner_arithmetic_and_tolerance():
    cp = _load_tool("capacity_plan")
    p = cp.plan(_fake_measured(), hbm_gb=1.0,
                targets=(1000, 10**6), tolerance=0.10)
    v = p["validation"]
    assert v["ok"] is True and v["rel_error"] == 0.0
    # per-host: 1000 state + 500 temp; fixed 5000 + 100 code
    assert p["per_host_total_bytes"] == 1500.0
    assert p["fixed_bytes"] == 5100
    budget = 1 << 30
    assert p["max_hosts_per_chip"] == (budget - 5100) // 1500
    row = p["ladder"][0]
    assert row["hosts"] == 1000 and row["fits_one_chip"]
    big = p["ladder"][1]
    assert big["total_gib"] == pytest.approx(
        (5100 + 1500 * 10**6) / (1 << 30), rel=1e-3)
    assert big["chips_at_budget"] >= 2
    # out-of-tolerance prediction fails validation
    p2 = cp.plan(_fake_measured(arg_err=0.25), hbm_gb=1.0,
                 tolerance=0.10)
    assert p2["validation"]["ok"] is False
    # a backend with no memory_analysis: unvalidated, never a crash
    m3 = _fake_measured()
    m3["memory"]["xla"] = {"argument_bytes": None, "errors":
                           {"memory_analysis": "refused"}}
    p3 = cp.plan(m3, hbm_gb=1.0)
    assert p3["validation"]["ok"] is None
    assert p3["ladder"]  # the census ladder still renders
    assert "unvalidated" in cp.render_markdown(p3).lower() or \
        "UNVALIDATED" in cp.render_markdown(p3)
    # a DEGENERATE measurement (0 argument bytes) FAILS validation —
    # it must never be misfiled as merely "unvalidated"
    m4 = _fake_measured()
    m4["memory"]["xla"]["argument_bytes"] = 0
    p4 = cp.plan(m4, hbm_gb=1.0)
    assert p4["validation"]["ok"] is False


def test_planner_predict_vs_measure_real_run():
    """Acceptance (in-process): the census prediction lands within
    tolerance of the XLA-measured argument bytes on a real run."""
    cp = _load_tool("capacity_plan")
    measured = cp.measure("phold", n=16, stop=5)
    p = cp.plan(measured, hbm_gb=16.0, tolerance=0.10)
    assert p["validation"]["ok"] is True, p["validation"]
    assert p["max_hosts_per_chip"] > 1000
    md = cp.render_markdown(p)
    assert "| hosts |" in md and "1,000,000" in md


# --- observation must not perturb determinism -------------------------------

def test_memscope_observation_does_not_perturb_digest(tmp_path):
    """Acceptance: a fully-observed run (metrics + trace + heartbeat +
    the always-on watermark/census/capture) produces a digest chain
    byte-identical to a plain run's."""
    from shadow_tpu.engine.sim import Simulation
    from shadow_tpu.obs import trace as TR
    from test_phold import phold_scenario

    plain = str(tmp_path / "plain.jsonl")
    observed = str(tmp_path / "observed.jsonl")
    Simulation(phold_scenario(n=16, stop=5)).run(digest=plain)
    TR.install(None)
    try:
        Simulation(phold_scenario(n=16, stop=5)).run(
            digest=observed, heartbeat_s=1.0,
            metrics=str(tmp_path / "m.json"))
    finally:
        TR.finish()
    assert (open(plain, "rb").read() == open(observed, "rb").read()), \
        "memory observation perturbed the digest chain"
