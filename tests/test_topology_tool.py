"""Topology toolkit tests (tools/topology_tool.py): the reference's
src/tools/topology pipeline (prune -> compute-paths -> collapse)
rebuilt on the framework's own routing oracle."""

import csv
import importlib.util
import sys
from pathlib import Path

import numpy as np
import pytest

_spec = importlib.util.spec_from_file_location(
    "topology_tool",
    Path(__file__).resolve().parent.parent / "tools" / "topology_tool.py")
ttool = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(ttool)

from shadow_tpu.routing.graphml import parse_graphml  # noqa: E402

# two geocode clusters (us: a,b / eu: c,d), chain a-b-c-d plus a 'relay'
# that prune removes
CHAIN = """<?xml version="1.0"?>
<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="latency" attr.type="double" for="edge" id="d7"/>
  <key attr.name="packetloss" attr.type="double" for="edge" id="d9"/>
  <key attr.name="geocode" attr.type="string" for="node" id="d1"/>
  <key attr.name="type" attr.type="string" for="node" id="d2"/>
  <key attr.name="bandwidthup" attr.type="int" for="node" id="d4"/>
  <key attr.name="bandwidthdown" attr.type="int" for="node" id="d3"/>
  <graph edgedefault="undirected">
    <node id="a"><data key="d1">us</data><data key="d2">server</data>
      <data key="d4">1000</data><data key="d3">1000</data></node>
    <node id="b"><data key="d1">us</data><data key="d2">server</data>
      <data key="d4">1000</data><data key="d3">1000</data></node>
    <node id="c"><data key="d1">eu</data><data key="d2">server</data>
      <data key="d4">2000</data><data key="d3">2000</data></node>
    <node id="d"><data key="d1">eu</data><data key="d2">server</data>
      <data key="d4">2000</data><data key="d3">2000</data></node>
    <node id="x"><data key="d1">as</data><data key="d2">relay</data>
      <data key="d4">500</data><data key="d3">500</data></node>
    <edge source="a" target="b"><data key="d7">5.0</data></edge>
    <edge source="b" target="c"><data key="d7">40.0</data>
      <data key="d9">0.01</data></edge>
    <edge source="c" target="d"><data key="d7">5.0</data></edge>
    <edge source="d" target="x"><data key="d7">100.0</data></edge>
  </graph>
</graphml>"""


@pytest.fixture
def chain_file(tmp_path):
    p = tmp_path / "chain.graphml.xml"
    p.write_text(CHAIN)
    return str(p)


def test_prune_by_type(chain_file, tmp_path, capsys):
    out = tmp_path / "pruned.graphml.xml"
    ttool.main(["prune", chain_file, "--keep-types", "server",
                "--out", str(out)])
    g = parse_graphml(str(out))
    assert sorted(g.vertex_ids) == ["a", "b", "c", "d"]
    assert g.num_edges == 3  # d-x edge dropped with x


def test_compute_paths_complete(chain_file, tmp_path):
    out = tmp_path / "complete.graphml.xml"
    ttool.main(["compute-paths", chain_file, "--out", str(out)])
    g = parse_graphml(str(out))
    V = g.num_vertices
    assert V == 5
    # complete: every unordered pair + self loops
    assert g.num_edges == V * (V + 1) // 2
    lookup = {}
    for k in range(g.num_edges):
        s, t = g.vertex_ids[g.e_src[k]], g.vertex_ids[g.e_dst[k]]
        lookup[frozenset((s, t))] = (g.e_latency_ms[k], g.e_packetloss[k])
    lat_ad, loss_ad = lookup[frozenset(("a", "d"))]
    assert lat_ad == pytest.approx(50.0)          # 5 + 40 + 5
    assert loss_ad == pytest.approx(0.01)         # the b-c lossy hop
    # feeding the complete graph back into the simulator's loader gives
    # the same pairwise table (no Dijkstra needed at load time)
    from shadow_tpu.routing.topology import build_topology
    topo = build_topology(str(out))
    ia, idd = g.vertex_ids.index("a"), g.vertex_ids.index("d")
    # original graph through the oracle:
    topo0 = build_topology(chain_file)
    assert topo.latency_ns[ia, idd] == topo0.latency_ns[ia, idd]


def test_collapse_by_geocode(chain_file, tmp_path):
    pruned = tmp_path / "pruned.graphml.xml"
    ttool.main(["prune", chain_file, "--keep-types", "server",
                "--out", str(pruned)])
    out = tmp_path / "collapsed.graphml.xml"
    ttool.main(["collapse", str(pruned), "--by", "geocode",
                "--out", str(out)])
    g = parse_graphml(str(out))
    assert g.num_vertices == 2  # us + eu clusters
    assert set(g.v_geocode) == {"us", "eu"}
    # inter-cluster latency = median of {a,b}x{c,d} path latencies
    # paths: a-c 45, a-d 50, b-c 40, b-d 45 -> median 45
    inter = [g.e_latency_ms[k] for k in range(g.num_edges)
             if g.e_src[k] != g.e_dst[k]]
    assert inter == [pytest.approx(45.0)]
    # bandwidth = cluster median
    assert set(g.v_bw_up.tolist()) == {1000.0, 2000.0}


def test_extract_latencies_csv(chain_file, tmp_path):
    out = tmp_path / "lat.csv"
    ttool.main(["extract-latencies", chain_file, "--out", str(out)])
    with open(out) as f:
        rows = list(csv.DictReader(f))
    d = {(r["source"], r["target"]): float(r["latency_ms"]) for r in rows}
    assert d[("a", "c")] == pytest.approx(45.0)
    assert len(rows) == 5 * 4


def test_convert_csv_roundtrip(tmp_path):
    src = tmp_path / "edges.csv"
    src.write_text("source,target,latency_ms,loss\n"
                   "n1,n2,12.5,0.001\nn2,n3,30,\n".replace(",\n", ",0\n"))
    out = tmp_path / "conv.graphml.xml"
    ttool.main(["convert", str(src), "--out", str(out)])
    g = parse_graphml(str(out))
    assert g.vertex_ids == ["n1", "n2", "n3"]
    assert g.e_latency_ms.tolist() == [12.5, 30.0]
    assert g.e_packetloss[0] == pytest.approx(0.001)


def test_info_runs(chain_file, capsys):
    ttool.main(["info", chain_file])
    out = capsys.readouterr().out
    assert "vertices: 5" in out
    assert "connected components: 1" in out


def test_compute_paths_jitter_sums(tmp_path):
    """Jitter accumulates along the shortest path, like the reference's
    compute-topology-paths tool."""
    src = tmp_path / "j.graphml.xml"
    src.write_text("""<?xml version="1.0"?>
<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="latency" attr.type="double" for="edge" id="d7"/>
  <key attr.name="jitter" attr.type="double" for="edge" id="d8"/>
  <graph edgedefault="undirected">
    <node id="a"/><node id="b"/><node id="c"/>
    <edge source="a" target="b"><data key="d7">10</data>
      <data key="d8">1.5</data></edge>
    <edge source="b" target="c"><data key="d7">10</data>
      <data key="d8">2.0</data></edge>
  </graph>
</graphml>""")
    out = tmp_path / "jc.graphml.xml"
    ttool.main(["compute-paths", str(src), "--out", str(out)])
    g = parse_graphml(str(out))
    jit = {}
    for k in range(g.num_edges):
        a, b = g.vertex_ids[g.e_src[k]], g.vertex_ids[g.e_dst[k]]
        jit[frozenset((a, b))] = g.e_jitter_ms[k]
    assert jit[frozenset(("a", "b"))] == pytest.approx(1.5)
    assert jit[frozenset(("a", "c"))] == pytest.approx(3.5)
