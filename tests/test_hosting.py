"""Hosted (CPU-side real code) application tests.

The hosting path is the analogue of the reference's plugin tests
(src/test/preload, src/test/tcp with real binaries): app logic runs as
real Python code against HostOS syscalls while all transport runs in
the device engine.
"""

import numpy as np
import pytest

from shadow_tpu.core.config import HostSpec, ProcessSpec, Scenario
from shadow_tpu.engine import defs
from shadow_tpu.engine.sim import Simulation
from shadow_tpu.engine.state import EngineConfig
from shadow_tpu.hosting import HostedApp, register


class HostedPinger(HostedApp):
    """UDP ping client in real Python code."""

    def __init__(self, args):
        kv = dict(tok.split("=") for tok in args.split())
        self.peer = kv["peer"]
        self.port = int(kv.get("port", 8000))
        self.count = int(kv.get("count", 5))
        self.interval = int(float(kv.get("interval_s", 1)) * 10**9)
        self.size = int(kv.get("size", 64))
        self.sent = 0
        self.echoed = 0

    def on_start(self, os):
        self.sock = os.udp_open()
        self._send(os)

    def _send(self, os):
        os.sendto(self.sock, self.peer, self.port, self.size, aux=42)
        self.sent += 1
        if self.sent < self.count:
            os.timer(self.interval)

    def on_timer(self, os, tag):
        self._send(os)

    def on_dgram(self, os, sock, src, sport, nbytes, aux):
        assert aux == 42
        self.echoed += 1


class HostedPutter(HostedApp):
    """TCP PUT client in real Python code (against bulkserver)."""

    def __init__(self, args):
        kv = dict(tok.split("=") for tok in args.split())
        self.peer = kv["peer"]
        self.port = int(kv.get("port", 80))
        self.size = int(kv.get("size", 50 * 1024))
        self.done = 0

    def on_start(self, os):
        self.sock = os.tcp_connect(self.peer, self.port)

    def on_connected(self, os, sock, **_identity):
        os.write(sock, self.size)
        os.close(sock)

    def on_sent(self, os, sock):
        self.done += 1


register("test-pinger", HostedPinger)
register("test-putter", HostedPutter)

CFG = dict(qcap=32, scap=8, obcap=16, incap=32, txqcap=8)


def test_hosted_udp_ping(simple_topology_xml):
    scen = Scenario(
        stop_time=10 * 10**9,
        topology_graphml=simple_topology_xml,
        hosts=[
            HostSpec(id="srv", processes=[
                ProcessSpec(plugin="pingserver", start_time=10**9,
                            arguments="port=8000")]),
            HostSpec(id="cli", processes=[
                ProcessSpec(plugin="hosted:test-pinger", start_time=2 * 10**9,
                            arguments="peer=srv port=8000 count=4 "
                                      "interval_s=1 size=64")]),
        ],
    )
    sim = Simulation(scen, engine_cfg=EngineConfig(num_hosts=2, **CFG))
    app = sim.hosting.apps[1]
    report = sim.run()
    assert app.sent == 4
    assert app.echoed == 4
    # the server echoed all four datagrams back
    assert report.stats[1, defs.ST_BYTES_RECV] == 4 * 64


def test_hosted_tcp_put(simple_topology_xml):
    scen = Scenario(
        stop_time=15 * 10**9,
        topology_graphml=simple_topology_xml,
        hosts=[
            HostSpec(id="srv", processes=[
                ProcessSpec(plugin="bulkserver", start_time=10**9,
                            arguments="port=80")]),
            HostSpec(id="cli", processes=[
                ProcessSpec(plugin="hosted:test-putter", start_time=2 * 10**9,
                            arguments="peer=srv port=80 size=51200")]),
        ],
    )
    sim = Simulation(scen, engine_cfg=EngineConfig(num_hosts=2, **CFG))
    app = sim.hosting.apps[1]
    report = sim.run()
    assert app.done == 1
    # server counted the inbound transfer and got every byte
    assert report.stats[0, defs.ST_XFER_DONE] == 1
    assert report.stats[0, defs.ST_BYTES_RECV] == 51200


def test_hosted_plus_modeled_one_host(simple_topology_xml):
    """The reference's canonical host shape (tor + tgen on ONE host,
    shd-configuration.h:36-95): a hosted process sharing its host with
    a modeled process. The hosted putter runs in process slot 1; its
    sockets must wake IT (sk_proc routing through the op replay), while
    the modeled pinger in slot 0 runs its own state machine."""
    scen = Scenario(
        stop_time=15 * 10**9,
        topology_graphml=simple_topology_xml,
        hosts=[
            HostSpec(id="srv", processes=[
                ProcessSpec(plugin="bulkserver", start_time=10**9,
                            arguments="port=80"),
                ProcessSpec(plugin="pingserver", start_time=10**9,
                            arguments="port=8000")]),
            HostSpec(id="cli", processes=[
                ProcessSpec(plugin="ping", start_time=2 * 10**9,
                            arguments="peer=srv port=8000 count=3 "
                                      "interval=1s size=64"),
                ProcessSpec(plugin="hosted:test-putter",
                            start_time=3 * 10**9,
                            arguments="peer=srv port=80 size=51200")]),
        ],
    )
    sim = Simulation(scen, engine_cfg=EngineConfig(num_hosts=2,
                                                   procs_per_host=2,
                                                   **CFG))
    assert sim.hosting.procs[1] == 1   # hosted app sits in slot 1
    app = sim.hosting.apps[1]
    report = sim.run()
    # hosted TCP put completed and woke the hosted process (on_sent)
    assert app.done == 1
    assert report.stats[0, defs.ST_XFER_DONE] == 1
    # the modeled pinger in slot 0 ran alongside: 3 echoed pings
    assert report.stats[1, defs.ST_RTT_COUNT] == 3
    # server got the put bytes plus the ping datagrams
    assert report.stats[0, defs.ST_BYTES_RECV] >= 51200 + 3 * 64


def test_hosted_under_mesh(simple_topology_xml):
    """Hosted apps under mesh sharding: wake rings shard with the host
    rows; results match the unsharded run bit-for-bit.

    Known-failing on jax 0.4.37 since PR 2 (`jax.shard_map` did not
    exist there); fixed by the parallel/shard.py experimental-API
    fallback, so the whole mesh tier — this test included — runs
    everywhere again."""
    from shadow_tpu.parallel.shard import make_mesh

    def build():
        scen = Scenario(
            stop_time=10 * 10**9,
            topology_graphml=simple_topology_xml,
            hosts=[
                HostSpec(id="srv", processes=[
                    ProcessSpec(plugin="pingserver", start_time=10**9,
                                arguments="port=8000")]),
                HostSpec(id="cli", processes=[
                    ProcessSpec(plugin="hosted:test-pinger",
                                start_time=2 * 10**9,
                                arguments="peer=srv port=8000 count=4 "
                                          "interval_s=1 size=64")]),
            ],
        )
        return Simulation(scen,
                          engine_cfg=EngineConfig(num_hosts=2, **CFG))

    ref = build().run()

    sim = build()
    app = sim.hosting.apps[1]
    rep = sim.run(mesh=make_mesh(2))
    assert app.sent == 4 and app.echoed == 4
    assert np.array_equal(rep.stats, ref.stats)


def test_hosted_deterministic(simple_topology_xml):
    def go():
        scen = Scenario(
            stop_time=8 * 10**9,
            topology_graphml=simple_topology_xml,
            hosts=[
                HostSpec(id="srv", processes=[
                    ProcessSpec(plugin="pingserver", start_time=10**9,
                                arguments="port=8000")]),
                HostSpec(id="cli", processes=[
                    ProcessSpec(plugin="hosted:test-pinger",
                                start_time=2 * 10**9,
                                arguments="peer=srv port=8000 count=3 "
                                          "interval_s=1 size=32")]),
            ],
        )
        sim = Simulation(scen, engine_cfg=EngineConfig(num_hosts=2, **CFG))
        return sim.run()

    r1, r2 = go(), go()
    assert np.array_equal(r1.stats, r2.stats)


def test_hosted_hot_split_bit_identical(simple_topology_xml, tmp_path):
    """The hosted tier under the hot/cold split: a hosted TCP put
    produces byte-identical digest chains under the gated drain
    (default) and the full-tree drain (hot_split=0, the pre-split
    engine). Hosted configs pin hw_* hot (hostedcap > 1) and the app
    set pins the socket table hot — the split here is the static cold
    boundary columns plus the slimmer loop carry."""
    def chain(name, hot_split):
        scen = Scenario(
            stop_time=15 * 10**9,
            topology_graphml=simple_topology_xml,
            hosts=[
                HostSpec(id="srv", processes=[
                    ProcessSpec(plugin="bulkserver", start_time=10**9,
                                arguments="port=80")]),
                HostSpec(id="cli", processes=[
                    ProcessSpec(plugin="hosted:test-putter",
                                start_time=2 * 10**9,
                                arguments="peer=srv port=80 "
                                          "size=51200")]),
            ],
        )
        path = str(tmp_path / f"{name}.jsonl")
        sim = Simulation(scen, engine_cfg=EngineConfig(
            num_hosts=2, hot_split=hot_split, **CFG))
        sim.run(digest=path, digest_every=8)
        return open(path, "rb").read()

    assert chain("gated", 1) == chain("full", 0), (
        "hosted digest chain diverged between gated and full-tree "
        "drains")
