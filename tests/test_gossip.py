"""Block-gossip app tests (apps/gossip.py): the modeled counterpart of
the Bitcoin block-propagation workload (BASELINE.json config #5)."""

import numpy as np
import pytest

from shadow_tpu.core.config import HostSpec, ProcessSpec, Scenario
from shadow_tpu.engine import defs
from shadow_tpu.engine.pyengine import PyEngine
from shadow_tpu.engine.sim import Simulation
from shadow_tpu.engine.state import EngineConfig

from test_phold import MESH_TOPO


def gossip_scenario(n=64, stop=22, fanout=6, interval="2s",
                    topo=None):
    return Scenario(
        stop_time=stop * 10**9,
        topology_graphml=topo or MESH_TOPO,
        hosts=[
            HostSpec(id="miner", processes=[
                ProcessSpec(plugin="gossip", start_time=10**9,
                            arguments=f"port=8333 fanout={fanout} "
                                      f"interval={interval} miner=1 "
                                      "size=500")]),
            HostSpec(id="node", quantity=n - 1, processes=[
                ProcessSpec(plugin="gossip", start_time=10**9,
                            arguments=f"port=8333 fanout={fanout} "
                                      f"interval={interval} size=500")]),
        ],
    )


def test_gossip_propagates_to_all():
    """Blocks mined every 2s starting t=3s reach (essentially) every
    node well before the stop time; propagation delay is a few network
    hops, not the mining interval."""
    n = 64
    cfg = EngineConfig(num_hosts=n, qcap=32, scap=4, obcap=16, incap=32,
                       chunk_windows=32)
    r = Simulation(gossip_scenario(n=n), engine_cfg=cfg).run()
    s = r.summary()
    # miner produced blocks at 3,5,...,21s = 10 heights
    xf = r.stats[1:, defs.ST_XFER_DONE]
    assert xf.max() == 10
    # flood with fanout 6 over 64 nodes: everyone hears nearly all
    # blocks (late blocks may still be in flight at the stop time)
    assert (xf >= 8).all(), xf
    # mean propagation delay: a few 25ms hops, far below the interval
    assert 0 < s["mean_rtt_us"] < 1_000_000, s["mean_rtt_us"]
    assert s["drop_net"] == 0


def test_gossip_deterministic():
    cfg = EngineConfig(num_hosts=32, qcap=32, scap=4, obcap=16, incap=32,
                       chunk_windows=32)
    r1 = Simulation(gossip_scenario(n=32, stop=12), engine_cfg=cfg).run()
    r2 = Simulation(gossip_scenario(n=32, stop=12), engine_cfg=cfg).run()
    assert np.array_equal(r1.stats, r2.stats)


def test_differential_gossip():
    """Compiled engine vs the pure-Python heap engine, bit for bit
    (the dual-run pattern, SURVEY §4) on the gossip workload."""
    from test_differential import CFG, COMPARE

    n = 16

    def scen():
        return gossip_scenario(n=n, stop=10, fanout=4, interval="1500ms")

    jax_stats = Simulation(scen(), engine_cfg=EngineConfig(
        num_hosts=n, **CFG)).run().stats
    py_stats = PyEngine(Simulation(scen(), engine_cfg=EngineConfig(
        num_hosts=n, **CFG))).run()
    for st in COMPARE:
        assert np.array_equal(jax_stats[:, st], py_stats[:, st]), (
            f"stat {st} diverges:\n jax={jax_stats[:, st]}\n "
            f"py={py_stats[:, st]}")
