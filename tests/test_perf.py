"""Perf-observatory tests (PR 6): ledger round-trip + fingerprint
keying, the regression gate's exit semantics, phase attribution
(synthetic spans and a real compiled phold run), the per-shard
imbalance gauges on the virtual mesh, and the observability-must-not-
perturb-determinism contract for --perf runs.

Note on tier-1: this file sorts after test_parallel, past the
compile-bound tier-1 horizon on the CPU dev container — the pure-unit
tests up top cost milliseconds anyway; the compiled-run tests at the
bottom are for file-by-file validation (and the CLI one is `slow`).
"""

import importlib.util
import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent

from shadow_tpu.obs import ledger as LG  # noqa: E402
from shadow_tpu.obs import perf as PF  # noqa: E402
from shadow_tpu.obs.metrics import Registry, _assemble_indexed  # noqa: E402


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, REPO / "tools" / f"{name}.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# --- ledger ---------------------------------------------------------------

def _entry(scenario="phold-64", rate=100.0, platform="cpu", fp="f0",
           warm=None, phases=None):
    s = {"events": 1000, "wall_seconds": 1000 / rate,
         "events_per_sec": rate, "sim_seconds": 5.0, "windows": 10}
    return LG.make_entry(scenario, fp, platform, s, phases=phases,
                         warm_wall=(1000 / warm if warm else None))


def test_ledger_roundtrip(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    e1 = _entry(rate=100.0)
    e2 = _entry(rate=105.0)
    assert LG.append(e1, path) == path
    LG.append(e2, path)
    got = LG.read(path)
    assert len(got) == 2
    assert got[0]["events_per_sec"] == 100.0
    assert got[1]["events_per_sec"] == 105.0
    assert got[0]["format"] == LG.FORMAT
    # grouping key: same scenario/platform/fingerprint -> same series
    assert LG.key_of(got[0]) == LG.key_of(got[1])
    # warm rate preferred by the gate when present
    ew = _entry(rate=50.0, warm=200.0)
    assert LG.entry_rate(ew) == ew["warm_events_per_sec"]
    assert LG.entry_rate(e1) == 100.0


def test_ledger_skips_torn_line(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    LG.append(_entry(), path)
    with open(path, "a") as f:
        f.write('{"format": "shadow_tpu.perf.led')  # torn append
    got = LG.read(path)
    assert len(got) == 1  # torn line skipped, not a crash


def test_fingerprint_keying():
    from shadow_tpu.engine.state import EngineConfig
    a = EngineConfig(num_hosts=64, qcap=16)
    b = EngineConfig(num_hosts=64, qcap=32)
    assert LG.fingerprint_of(a) != LG.fingerprint_of(b)
    assert LG.fingerprint_of(a) == LG.fingerprint_of(
        EngineConfig(num_hosts=64, qcap=16))
    # extras change the key; kwarg order does not
    assert (LG.fingerprint_of(a, stop=10, runahead=5) ==
            LG.fingerprint_of(a, runahead=5, stop=10))
    assert (LG.fingerprint_of(a, stop=10) !=
            LG.fingerprint_of(a, stop=20))


def test_ledger_disabled_by_env(tmp_path, monkeypatch):
    monkeypatch.setenv("SHADOW_TPU_LEDGER", "off")
    assert LG.default_path() is None
    assert LG.append(_entry()) is None
    monkeypatch.setenv("SHADOW_TPU_LEDGER", str(tmp_path / "l.jsonl"))
    assert LG.append(_entry()) == str(tmp_path / "l.jsonl")


# --- phase attribution (synthetic) ----------------------------------------

def _ev(name, ts_ms, dur_ms):
    return {"name": name, "ph": "X", "pid": 1, "tid": 0,
            "ts": ts_ms * 1000.0, "dur": dur_ms * 1000.0}


def test_attribute_nested_self_time():
    # a 500ms chunk containing a 100ms heartbeat: window self = 400ms
    events = [_ev("chunk", 0, 500), _ev("tracker.heartbeat", 200, 100)]
    att = PF.attribute(events, 0.5, n_events=100)
    assert abs(att["phases"]["window"]["wall_s"] - 0.4) < 1e-9
    assert abs(att["phases"]["tracker"]["wall_s"] - 0.1) < 1e-9
    assert att["attributed_frac"] == 1.0 and att["ok"]
    assert att["phases"]["window"]["us_per_event"] == pytest.approx(
        4000.0)


def test_attribute_residual_flagged():
    att = PF.attribute([_ev("chunk", 0, 100)], 1.0)
    assert not att["ok"]
    assert att["residual_frac"] == pytest.approx(0.9)
    assert att["residual_label"]  # explicit, never a silent gap
    # unknown spans attribute under their own name, never dropped
    att2 = PF.attribute([_ev("surprise", 0, 950)], 1.0)
    assert att2["ok"] and "surprise" in att2["phases"]


# --- regression gate ------------------------------------------------------

def _regress(tmp_path, rates, band=0.15, **kw):
    pr = _load_tool("perf_regress")
    path = str(tmp_path / "l.jsonl")
    for r in rates:
        LG.append(_entry(rate=r, **kw), path)
    return pr.main([path, "--band", str(band)])


def test_regress_exit0_on_flat_trajectory(tmp_path):
    assert _regress(tmp_path, [100, 102, 98, 101]) == 0


def test_regress_exit1_on_synthetic_regression(tmp_path):
    assert _regress(tmp_path, [100, 102, 98, 50]) == 1


def test_regress_band_widen_with_noisy_history(tmp_path):
    # history wobbles 40%: a 25% dip must NOT gate at the 15% band
    assert _regress(tmp_path, [80, 120, 100, 75]) == 0


def test_regress_insufficient_history(tmp_path):
    assert _regress(tmp_path, [100]) == 0  # nothing to compare yet


def test_regress_zero_rate_candidate_fails(tmp_path):
    """A scenario collapsing to zero events/sec against real history
    is the most extreme regression — it must exit 1, never be
    misfiled as insufficient history."""
    pr = _load_tool("perf_regress")
    path = str(tmp_path / "l.jsonl")
    for r in (100, 102, 98):
        LG.append(_entry(rate=r), path)
    e = _entry(rate=1.0)
    e["events_per_sec"] = 0.0
    LG.append(e, path)
    results, reg = pr.check(LG.read(path))
    assert reg and results[0]["status"] == "REGRESSION"
    assert pr.main([path]) == 1


def test_regress_platform_and_fingerprint_split(tmp_path):
    pr = _load_tool("perf_regress")
    path = str(tmp_path / "l.jsonl")
    # cpu history at 100, a "tpu" entry at 10: different platform,
    # different trajectory — never compared
    LG.append(_entry(rate=100.0, platform="cpu"), path)
    LG.append(_entry(rate=101.0, platform="cpu"), path)
    LG.append(_entry(rate=10.0, platform="tpu"), path)
    assert pr.main([path]) == 0
    # same platform but a config change (new fingerprint): new series
    LG.append(_entry(rate=10.0, platform="cpu", fp="f-new"), path)
    assert pr.main([path]) == 0
    # an actual same-key regression still fires
    LG.append(_entry(rate=10.0, platform="cpu"), path)
    assert pr.main([path]) == 1


def test_regress_compile_bound_not_gated(tmp_path):
    """A no-warm-split entry whose own phase breakdown says the XLA
    compile dominated its wall carries no throughput signal — its
    cold-inclusive rate is compile-cache state (a 5 sim-s phold on
    the CPU container is 99.9% compile). Reported, never gated, and
    never counted into another candidate's history median."""
    pr = _load_tool("perf_regress")
    path = str(tmp_path / "l.jsonl")

    def cb(rate):  # wall = 1000/rate, compile = 99% of it
        return _entry(rate=rate,
                      phases={"compile": 0.99 * 1000 / rate,
                              "window": 0.005 * 1000 / rate})

    # a 40% "drop" across compile-bound entries: cache state, exit 0
    for r in (100.0, 95.0, 60.0):
        LG.append(cb(r), path)
    results, reg = pr.check(LG.read(path))
    assert not reg
    assert results[0]["status"] == "compile-bound"
    assert pr.main([path]) == 0
    # compile-bound history is excluded from a REAL candidate's
    # median: two warm entries at ~100 gate the 50-rate candidate
    # against 100, not against the compile-bound 60
    LG.append(_entry(rate=30.0, warm=100.0), path)
    LG.append(_entry(rate=30.0, warm=101.0), path)
    LG.append(_entry(rate=30.0, warm=50.0), path)
    assert pr.main([path]) == 1
    # a warm split always wins over the phase heuristic
    assert not pr.compile_bound(_entry(rate=30.0, warm=100.0))


def test_regress_candidate_mode(tmp_path):
    pr = _load_tool("perf_regress")
    path = str(tmp_path / "l.jsonl")
    for r in (100, 102, 98):
        LG.append(_entry(rate=r), path)
    cand = tmp_path / "cand.json"
    cand.write_text(json.dumps(_entry(rate=40.0)))
    assert pr.main([path, "--candidate", str(cand)]) == 1
    cand.write_text(json.dumps(_entry(rate=99.0)))
    assert pr.main([path, "--candidate", str(cand)]) == 0


# --- metrics shard assembly ----------------------------------------------

def test_metrics_shard_section_assembly():
    assert _assemble_indexed(
        {"events.0": 5, "events.1": 7, "imbalance": 1.2}) == {
        "events": [5, 7], "imbalance": 1.2}
    r = Registry()
    r.gauge("shard.events.0").set(3)
    r.gauge("shard.events.2").set(9)  # sparse: missing index -> None
    r.gauge("shard.imbalance").set(1.5)
    r.gauge("perf.attributed_frac").set(0.97)
    snap = r.snapshot()
    assert snap["shards"]["events"] == [3, None, 9]
    assert snap["shards"]["imbalance"] == 1.5
    assert snap["perf"]["attributed_frac"] == 0.97


def test_perf_publish_gauges():
    att = PF.attribute([_ev("chunk", 0, 900)], 1.0, n_events=10)
    r = Registry()
    PF.publish(att, r)
    snap = r.snapshot()
    assert snap["perf"]["phase.window_s"] == pytest.approx(0.9)
    assert snap["perf"]["attributed_frac"] == pytest.approx(0.9)


# --- compiled-run coverage (file-by-file validation tier) -----------------

def test_phase_attribution_on_phold_run():
    """Acceptance: a real run's spans attribute >= 90% of its wall."""
    from shadow_tpu.engine.sim import Simulation
    from shadow_tpu.obs import trace as TR
    from test_phold import phold_scenario

    TR.install(None)
    try:
        report = Simulation(phold_scenario(n=16, stop=5)).run()
    finally:
        tr = TR.finish()
    att = PF.attribute(tr.events, report.wall_seconds, report.events)
    assert att["ok"], f"attribution below the 90% floor: {att}"
    assert "window" in att["phases"]
    assert "compile" in att["phases"]
    # per-event cost present and sane
    assert att["phases"]["window"]["us_per_event"] > 0


def test_shard_imbalance_gauges_on_mesh(tmp_path):
    """Acceptance: per-shard load + imbalance visible in metrics.json
    on a mesh run (VERDICT r5 missing #4)."""
    from shadow_tpu.engine.sim import Simulation
    from shadow_tpu.parallel.shard import make_mesh
    from test_phold import phold_scenario

    mpath = str(tmp_path / "metrics.json")
    report = Simulation(phold_scenario(n=16, stop=5)).run(
        mesh=make_mesh(8), metrics=mpath)
    assert report.events > 0
    m = json.load(open(mpath))
    sh = m.get("shards")
    assert sh, "mesh run must publish the shards section"
    assert len(sh["events"]) == 8
    assert sum(e or 0 for e in sh["events"]) == report.events
    assert sh["imbalance"] >= 1.0  # max/mean, 1.0 = balanced
    assert len(sh["passes"]) == 8
    # per-shard rung mix sums to the global pass total
    mix_total = sum(
        sum(v or 0 for v in vals) for k, vals in sh.items()
        if k.startswith("pass_mix."))
    assert mix_total == sum(sh["passes"])


def test_perf_observation_does_not_perturb_digest(tmp_path):
    """Acceptance: observing a run (--perf's in-memory tracer +
    metrics) must not change a single simulated bit — the digest
    chain of an observed run equals an unobserved run's."""
    from shadow_tpu.engine.sim import Simulation
    from shadow_tpu.obs import trace as TR
    from test_phold import phold_scenario

    plain = str(tmp_path / "plain.jsonl")
    observed = str(tmp_path / "observed.jsonl")
    Simulation(phold_scenario(n=16, stop=5)).run(digest=plain)
    TR.install(None)
    try:
        Simulation(phold_scenario(n=16, stop=5)).run(
            digest=observed,
            metrics=str(tmp_path / "m.json"))
    finally:
        TR.finish()
    assert (open(plain, "rb").read() == open(observed, "rb").read()), \
        "observation perturbed the digest chain"


@pytest.mark.slow
def test_perf_cli_dual_run_ledger(tmp_path):
    """The end-to-end CLI contract: two same-seed --perf runs produce
    byte-identical digest chains AND two ledger entries under one
    (scenario, platform, fingerprint) key."""
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="",
               JAX_PLATFORMS="cpu")
    led = str(tmp_path / "ledger.jsonl")
    chains = []
    for tag in ("a", "b"):
        dg = str(tmp_path / f"{tag}.jsonl")
        r = subprocess.run(
            [sys.executable, "-m", "shadow_tpu", "examples/ping.xml",
             "--stop-time", "5s", "--perf", led, "--digest", dg],
            cwd=str(REPO), env=env, capture_output=True, text=True,
            timeout=600)
        assert r.returncode == 0, r.stderr[-2000:]
        assert "perf: phase attribution" in r.stdout
        chains.append(open(dg, "rb").read())
    assert chains[0] == chains[1]
    entries = LG.read(led)
    assert len(entries) == 2
    assert LG.key_of(entries[0]) == LG.key_of(entries[1])
