"""Serving-layer tests (shadow_tpu/serving/ — PR 13).

Four contracts under test:

- the CACHE KEY (obs.ledger.fingerprint_of x AotJit._sig x
  jax/platform x source digest) is stable where it must be stable and
  distinct where it must be distinct — including the PR 13 regression
  fix for unhashable shardings aliasing two signatures onto one
  executable, and the structural stale-rejection of version/platform
  skew;
- the DISK TIER round-trips executables crash-safely: a fresh AotJit
  loads instead of compiling, torn/corrupt entries fall back LOUDLY
  to recompile (never load), retention bounds the directory;
- the PRE-WARM pipeline probes, dedups and warms shapes without ever
  wedging admission (failed probes/warms admit; hung children are
  killed) — driven with jax-free fake children;
- DETERMINISM is untouched: digest chains are byte-identical for
  cached-vs-uncached runs and for a vmapped batch of N scenarios vs
  the same N run individually (tools/divergence.py exit 0 — the
  ISSUE 13 acceptance proof).

Engine shapes mirror tests/test_digest.py (2-host ping, chunk 8) so
the compiled window program is shared across the files.
"""

import dataclasses
import json
import os
import subprocess
import sys
import time

import jax.numpy as jnp
import pytest

from shadow_tpu.core.jitcache import AotJit
from shadow_tpu.engine.sim import Simulation
from shadow_tpu.engine.state import EngineConfig
from shadow_tpu.fleet.queue import Queue, make_spec
from shadow_tpu.fleet.worker import build_batch_argv, build_child_argv
from shadow_tpu.obs.ledger import fingerprint_of
from shadow_tpu.serving import aotcache as AC
from shadow_tpu.serving import batch as BT
from shadow_tpu.serving.prewarm import Prewarmer

from test_digest import CFG, LOSSY_TOPO, ping_scen

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DIVERGENCE = os.path.join(REPO, "tools", "divergence.py")


@pytest.fixture(autouse=True)
def _aot_reset():
    """The disk tier is process-global (aotcache.ACTIVE, STATS);
    every test starts with NO cache installed and leaks nothing —
    including into the other test files of this pytest process."""
    saved_stats = dict(AC.STATS)
    saved = (AC.ACTIVE, AC._ENV_CHECKED)
    AC.uninstall()
    yield
    AC.ACTIVE, AC._ENV_CHECKED = saved
    AC.STATS.clear()
    AC.STATS.update(saved_stats)


def _delta(before, *keys):
    return {k: AC.STATS[k] - before[k] for k in keys}


# ---------------------------------------------------------------------
# the argument signature (AotJit._sig)
# ---------------------------------------------------------------------

class FakeSharding:
    """A sharding whose rich __eq__ made it unhashable (the NamedSharding
    failure mode the old code degraded on)."""
    __hash__ = None

    def __init__(self, ids, text):
        self._ids, self._text = tuple(ids), text

    @property
    def device_set(self):
        class Dev:
            def __init__(self, i):
                self.id = i
        return {Dev(i) for i in self._ids}

    def __str__(self):
        return self._text

    memory_kind = "device"


class FakeLeaf:
    """Array-shaped leaf carrying an arbitrary sharding (jax's
    shaped_abstractify duck-types shape/dtype/weak_type)."""

    def __init__(self, sharding):
        self.shape = (4,)
        self.dtype = jnp.float32.dtype
        self.weak_type = False
        self.sharding = sharding


def test_unhashable_sharding_keys_distinct():
    """REGRESSION (ISSUE 13 satellite 1): an unhashable sharding used
    to degrade to ``sh = None`` in the signature, aliasing two
    different-sharding signatures onto ONE executable — the exact
    wrong-buffers failure mode AotJit exists to prevent. The
    structural key must be distinct per sharding, stable per
    structure, and never the None degradation."""
    k_hosts = AotJit._sharding_key(FakeSharding((0, 1), "P('hosts')"))
    k_repl = AotJit._sharding_key(FakeSharding((0, 1), "P(None)"))
    k_dev = AotJit._sharding_key(FakeSharding((2, 3), "P('hosts')"))
    assert k_hosts is not None and k_repl is not None
    assert k_hosts != k_repl            # same devices, different layout
    assert k_hosts != k_dev             # same layout, different devices
    # stable: an equal-structure sharding keys identically
    assert k_hosts == AotJit._sharding_key(
        FakeSharding((0, 1), "P('hosts')"))
    # and hashable None stays None (plain host arrays)
    assert AotJit._sharding_key(None) is None


def test_sig_distinguishes_unhashable_shardings():
    """End to end through _sig: two pytrees differing ONLY in an
    unhashable sharding must produce different (and hashable —
    they're dict keys) signatures."""
    sig_a = AotJit._sig((FakeLeaf(FakeSharding((0,), "P('hosts')")),))
    sig_b = AotJit._sig((FakeLeaf(FakeSharding((0,), "P(None)")),))
    assert sig_a != sig_b
    assert {sig_a: 1, sig_b: 2}[sig_a] == 1
    # identical structure -> identical signature (the memo must HIT)
    assert sig_a == AotJit._sig(
        (FakeLeaf(FakeSharding((0,), "P('hosts')")),))


# ---------------------------------------------------------------------
# the config fingerprint as a cache key (obs.ledger.fingerprint_of)
# ---------------------------------------------------------------------

def test_fingerprint_stable_across_field_order():
    a = fingerprint_of({"qcap": 16, "scap": 4}, seed=7, stop_ns=10)
    b = fingerprint_of({"scap": 4, "qcap": 16}, stop_ns=10, seed=7)
    assert a == b
    assert len(a) == 16 and int(a, 16) >= 0


def _perturb(v):
    if isinstance(v, bool):
        return not v
    if isinstance(v, int):
        return v + 1
    if isinstance(v, tuple):
        return v + (99,)
    if v is None:
        return (0, 99)
    return f"{v}-perturbed"


def test_fingerprint_distinguishes_every_engineconfig_field():
    """EVERY EngineConfig field changes compiled code (shapes, pruned
    branches, pass structure) — so every field must change the
    fingerprint, including the PR 12 knobs the issue names."""
    names = {f.name for f in dataclasses.fields(EngineConfig)}
    assert {"hot_split", "event_batch"} <= names
    cfg = EngineConfig(num_hosts=2, **CFG)
    base = fingerprint_of(cfg)
    for f in dataclasses.fields(EngineConfig):
        changed = dataclasses.replace(
            cfg, **{f.name: _perturb(getattr(cfg, f.name))})
        assert fingerprint_of(changed) != base, (
            f"EngineConfig.{f.name} does not reach the cache key — a "
            "stale executable for a different config could load")


def test_entry_key_components(tmp_path, monkeypatch):
    """Stale rejection is STRUCTURAL: a different scope, argument
    signature, jax/XLA version, platform or source digest computes a
    different entry key, so the stale executable is unreachable —
    never loaded-and-wrong."""
    sig = AotJit._sig((jnp.arange(4),))
    base = AC.entry_key("run_windows.c8.aabb", sig)
    assert base != AC.entry_key("run_windows.c16.aabb", sig)
    assert base != AC.entry_key(
        "run_windows.c8.aabb", AotJit._sig((jnp.arange(5),)))

    real = AC.platform_key()
    monkeypatch.setattr(
        AC, "platform_key", lambda: {**real, "jax": "999.0.0"})
    skewed_jax = AC.entry_key("run_windows.c8.aabb", sig)
    assert skewed_jax != base
    monkeypatch.setattr(
        AC, "platform_key", lambda: {**real, "n_devices": 1 + real["n_devices"]})
    assert AC.entry_key("run_windows.c8.aabb", sig) not in (base,
                                                            skewed_jax)
    monkeypatch.setattr(AC, "platform_key", lambda: real)
    assert AC.entry_key("run_windows.c8.aabb", sig) == base

    monkeypatch.setattr(AC, "_SOURCE_DIGEST", "feedfacefeedface")
    assert AC.entry_key("run_windows.c8.aabb", sig) != base


# ---------------------------------------------------------------------
# the disk tier (round-trip, corruption, skew, retention)
# ---------------------------------------------------------------------

def _supports_serialization():
    return AC.serialize_support()


def test_disk_roundtrip_fresh_aotjit_loads(tmp_path):
    """A fresh AotJit (fresh process stand-in: empty memory tier) of
    a known scope+signature must LOAD from disk, not recompile — and
    compute the same values."""
    if not _supports_serialization():
        pytest.skip("backend cannot serialize executables")
    AC.install(str(tmp_path / "cache"))

    def f(x):
        return x * 2 + 1

    before = dict(AC.STATS)
    a1 = AotJit(f, cache_scope="test.roundtrip.v1")
    y1 = a1(jnp.arange(4))
    d = _delta(before, "compiles", "disk_stores", "disk_hits")
    assert d == {"compiles": 1, "disk_stores": 1, "disk_hits": 0}

    before = dict(AC.STATS)
    a2 = AotJit(f, cache_scope="test.roundtrip.v1")
    y2 = a2(jnp.arange(4))
    d = _delta(before, "compiles", "disk_hits")
    assert d == {"compiles": 0, "disk_hits": 1}
    assert jnp.array_equal(y1, y2)
    # sidecars published with the payload (the PR 5 store shape)
    cache = AC.active()
    keys = cache.entries()
    assert len(keys) == 1
    assert os.path.exists(cache.exec_path(keys[0]) + ".sha256")
    meta = json.load(open(cache.meta_path(keys[0])))
    assert meta["scope"] == "test.roundtrip.v1"
    assert meta["platform"]["jax"] == AC.platform_key()["jax"]


def test_no_scope_stays_memory_only(tmp_path):
    """Programs without a stable identity (cache_scope=None) never
    touch the disk tier, even with a cache installed."""
    AC.install(str(tmp_path / "cache"))
    before = dict(AC.STATS)
    a = AotJit(lambda x: x - 3)
    a(jnp.arange(4))
    d = _delta(before, "compiles", "disk_stores", "disk_hits",
               "disk_misses")
    assert d == {"compiles": 1, "disk_stores": 0, "disk_hits": 0,
                 "disk_misses": 0}
    assert AC.active().entries() == []


def test_corrupt_entry_falls_back_to_recompile(tmp_path):
    """EVERY corrupt shape — flipped payload bytes, missing hash
    sidecar — is a loud miss that recompiles and DROPS the entry;
    a torn entry can never load."""
    if not _supports_serialization():
        pytest.skip("backend cannot serialize executables")
    AC.install(str(tmp_path / "cache"))
    cache = AC.active()

    def f(x):
        return x + 7

    AotJit(f, cache_scope="test.corrupt.v1")(jnp.arange(4))
    [key] = cache.entries()

    # bit rot: flip one payload byte behind the published hash
    p = cache.exec_path(key)
    blob = bytearray(open(p, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(p, "wb").write(bytes(blob))
    before = dict(AC.STATS)
    y = AotJit(f, cache_scope="test.corrupt.v1")(jnp.arange(4))
    d = _delta(before, "compiles", "disk_hits", "rejected")
    assert d == {"compiles": 1, "disk_hits": 0, "rejected": 1}
    assert jnp.array_equal(y, jnp.arange(4) + 7)

    # torn write: payload visible without its hash sidecar
    [key] = cache.entries()
    os.unlink(cache.exec_path(key) + ".sha256")
    before = dict(AC.STATS)
    AotJit(f, cache_scope="test.corrupt.v1")(jnp.arange(4))
    d = _delta(before, "compiles", "disk_hits", "rejected")
    assert d == {"compiles": 1, "disk_hits": 0, "rejected": 1}


def test_version_skew_never_loads_stale_entry(tmp_path, monkeypatch):
    """An entry stored by a 'different jax' must MISS (its key is
    unreachable), recompile, and leave the alien entry untouched —
    the version/platform components of the key are the stale-
    executable gate the issue requires."""
    if not _supports_serialization():
        pytest.skip("backend cannot serialize executables")
    AC.install(str(tmp_path / "cache"))
    cache = AC.active()

    def f(x):
        return x * 5

    AotJit(f, cache_scope="test.skew.v1")(jnp.arange(4))
    [stale_key] = cache.entries()

    real = AC.platform_key()
    monkeypatch.setattr(
        AC, "platform_key", lambda: {**real, "jax": "999.0.0",
                                     "xla": "other-xla"})
    before = dict(AC.STATS)
    AotJit(f, cache_scope="test.skew.v1")(jnp.arange(4))
    d = _delta(before, "compiles", "disk_hits", "disk_misses")
    assert d["compiles"] == 1 and d["disk_hits"] == 0
    assert d["disk_misses"] == 1
    assert cache.has(stale_key)     # not loaded, not clobbered


def test_retention_prunes_oldest(tmp_path):
    if not _supports_serialization():
        pytest.skip("backend cannot serialize executables")
    AC.install(str(tmp_path / "cache"), keep=2)
    cache = AC.active()
    import jax
    compiled = jax.jit(lambda x: x + 1).lower(jnp.arange(4)).compile()
    for i, key in enumerate(["aa" * 16, "bb" * 16, "cc" * 16]):
        cache.store(key, compiled, meta={"n": i})
        t = time.time() - 100 + i
        os.utime(cache.exec_path(key), (t, t))
        cache._retain()
    assert cache.entries() == ["bb" * 16, "cc" * 16]
    assert not os.path.exists(cache.meta_path("aa" * 16))


def test_cached_programs_run_donation_free(tmp_path):
    """REGRESSION: a donated program resolved through the disk tier
    must compile/store/execute its donation-free twin. A serialize
    round trip of a DONATED executable is unsound on the XLA:CPU
    client — the loaded executable's outputs alias the donated input
    buffers, whose memory the runtime frees; once the allocator
    reuses the block the results silently corrupt (reproduced as
    event-queue digest divergence on warm runs). Observable contract:
    with a cache active the donated input SURVIVES the call (the
    undonated twin ran); without one, donation applies untouched."""
    def f(x):
        return x * 2

    x = jnp.arange(1024)
    y = AotJit(f, cache_scope="test.donate.v1",
               donate_argnums=(0,))(x)
    assert x.is_deleted(), (
        "donation should apply on the no-cache path (if this backend "
        "ignores donation the regression below is vacuous)")

    if not _supports_serialization():
        pytest.skip("backend cannot serialize executables — the "
                    "disk tier (and with it the undonated swap) "
                    "stays off, donation untouched")
    AC.install(str(tmp_path / "cache"))
    x2 = jnp.arange(1024)
    y2 = AotJit(f, cache_scope="test.donate.v1",
                donate_argnums=(0,))(x2)
    assert not x2.is_deleted(), (
        "a cache-scoped donated program executed its DONATED build "
        "through the disk tier — the use-after-free hazard is back")
    assert jnp.array_equal(y2, jnp.asarray(y))
    if _supports_serialization():
        cache = AC.active()
        [key] = cache.entries()
        assert json.load(open(cache.meta_path(key)))["donated"] is False


def test_store_is_first_writer_wins(tmp_path):
    """Racing same-key stores (fleet children finishing the same
    compile together) must serialize: a held lock skips the store, a
    stale lock (dead writer) is broken, and a complete entry is never
    overwritten — interleaved sidecar/payload writes from two
    processes would read as corruption and get DELETED."""
    if not _supports_serialization():
        pytest.skip("backend cannot serialize executables")
    AC.install(str(tmp_path / "cache"))
    cache = AC.active()
    import jax
    compiled = jax.jit(lambda x: x + 1).lower(jnp.arange(4)).compile()

    key = "ab" * 16
    lock = cache.exec_path(key) + ".lock"
    os.makedirs(cache.root, exist_ok=True)
    open(lock, "w").close()                       # a LIVE writer
    assert cache.store(key, compiled) is None
    assert not cache.has(key)

    os.utime(lock, (1, 1))                        # a DEAD writer
    assert cache.store(key, compiled) is not None
    assert cache.has(key) and not os.path.exists(lock)

    before = dict(AC.STATS)
    assert cache.store(key, compiled) is None     # already published
    assert _delta(before, "disk_stores")["disk_stores"] == 0


def test_env_var_activates_cache(tmp_path, monkeypatch):
    """Fleet children enable the tier via SHADOW_TPU_AOT_CACHE, no
    CLI plumbing (serving.aotcache.active)."""
    monkeypatch.setenv("SHADOW_TPU_AOT_CACHE", str(tmp_path / "envc"))
    AC.ACTIVE, AC._ENV_CHECKED = None, False
    cache = AC.active()
    assert cache is not None and cache.root == str(tmp_path / "envc")


# ---------------------------------------------------------------------
# the pre-warm pipeline (jax-free fake children)
# ---------------------------------------------------------------------

def _fake_probe(python, spec):
    """Prints the fingerprint encoded in the spec's config path
    ('name~FINGERPRINT'), like the real --shape-fingerprint child."""
    fp = spec["config"].split("~")[-1]
    return [sys.executable, "-c",
            "import json; print(json.dumps("
            f"{{'shape_fingerprint': {fp!r}}}))"]


def _drive(pw, timeout_s=30.0):
    t0 = time.monotonic()
    while not pw.done():
        pw.tick()
        if time.monotonic() - t0 > timeout_s:
            pw.shutdown()
            raise AssertionError("prewarm pipeline did not drain")
        time.sleep(0.02)
    pw.tick()


def test_probe_and_warm_argv_mirror_worker_chunk():
    """The probe/warm children must see the digest flags a worker
    attempt runs with — the cadence sets the compiled chunk, so
    probing without them would fingerprint (and warm) the WRONG
    program."""
    from shadow_tpu.serving.prewarm import probe_argv, warm_argv

    spec = {"id": "r1", "config": "/tmp/a.xml", "args": ["--seed", "3"],
            "digest": True, "digest_every": 8}
    p = " ".join(probe_argv(None, spec))
    assert p.endswith("--shape-fingerprint")
    assert "--digest " in p and "--digest-every 8" in p
    assert "--seed 3" in p
    w = " ".join(warm_argv(None, spec, "/tmp/cache"))
    assert "--prewarm" in w and "--aot-cache" in w
    assert "--digest-every 8" in w
    nodigest = dict(spec, digest=False)
    assert "--digest" not in " ".join(probe_argv(None, nodigest))


def test_prewarmer_dedups_shapes_and_gates(tmp_path):
    """3 runs, 2 shapes: every run gates until its shape warms, and
    each DISTINCT shape warms exactly once."""
    marks = tmp_path / "warms"
    marks.mkdir()

    def warm_fn(python, spec, cache_dir):
        fp = spec["config"].split("~")[-1]
        return [sys.executable, "-c",
                f"open({str(marks / fp)!r}, 'a').write('x')"]

    specs = [{"id": "r1", "config": "a~shapeX"},
             {"id": "r2", "config": "b~shapeX"},
             {"id": "r3", "config": "c~shapeY"},
             {"id": "cmd1", "config": None, "cmd": ["true"]}]
    records = []
    pw = Prewarmer(specs, str(tmp_path / "cache"), jobs=2,
                   log=lambda m: None,
                   journal=lambda **kw: records.append(kw),
                   probe_fn=_fake_probe, warm_fn=warm_fn)
    assert pw.ready("cmd1")            # cmd runs never gate
    assert not pw.ready("r1") and not pw.ready("r3")
    _drive(pw)
    assert pw.ready("r1") and pw.ready("r2") and pw.ready("r3")
    # dedup: one warm child per DISTINCT shape
    assert sorted(os.listdir(marks)) == ["shapeX", "shapeY"]
    warmed = [r for r in records if r.get("state") == "warmed"]
    assert {r["shape"] for r in warmed} == {"shapeX", "shapeY"}
    resolved = [r for r in records if r.get("state") == "resolved"]
    assert {r["run"] for r in resolved} == {"r1", "r2", "r3"}
    assert pw.counts() == {"warmed": 2, "failed": 0, "warming": 0,
                           "probing": 0}


def test_prewarmer_failures_never_wedge_admission(tmp_path):
    """A failed probe or a failed warm admits the run anyway (it pays
    its own compile) — pre-warm is an optimization, never a gate that
    can starve the queue."""
    def bad_probe(python, spec):
        return [sys.executable, "-c", "raise SystemExit(3)"]

    pw = Prewarmer([{"id": "r1", "config": "a~x"}],
                   str(tmp_path / "c"), log=lambda m: None,
                   probe_fn=bad_probe, warm_fn=_fake_probe)
    _drive(pw)
    assert pw.ready("r1")

    def bad_warm(python, spec, cache_dir):
        return [sys.executable, "-c", "raise SystemExit(2)"]

    records = []
    pw = Prewarmer([{"id": "r2", "config": "b~shapeZ"}],
                   str(tmp_path / "c"), log=lambda m: None,
                   journal=lambda **kw: records.append(kw),
                   probe_fn=_fake_probe, warm_fn=bad_warm)
    _drive(pw)
    assert pw.ready("r2")
    assert [r["state"] for r in records
            if r["shape"] == "shapeZ"][-1] == "failed"


def test_prewarmer_children_get_spec_env(tmp_path):
    """Probe/warm children run under the run's --env overrides (the
    worker attempt applies them) — a probe under the scheduler's own
    environment could fingerprint a different backend's program."""
    def env_probe(python, spec):
        return [sys.executable, "-c",
                "import os, json; print(json.dumps("
                "{'shape_fingerprint': "
                "os.environ.get('SHADOW_TPU_TEST_MARK', 'MISSING')}))"]

    marks = []

    def warm_fn(python, spec, cache_dir):
        return [sys.executable, "-c", "pass"]

    pw = Prewarmer(
        [{"id": "r1", "config": "a.xml",
          "env": {"SHADOW_TPU_TEST_MARK": "from-spec"}}],
        str(tmp_path / "c"), log=lambda m: None,
        journal=lambda **kw: marks.append(kw),
        probe_fn=env_probe, warm_fn=warm_fn)
    _drive(pw)
    assert pw._shape_of["r1"] == "from-spec"


def test_batch_cli_refuses_duplicate_seeds(tmp_path, capsys):
    """Duplicate seeds would name two lanes (and their digest
    chains) identically — interleaving one chain file."""
    xml = tmp_path / "s.xml"
    xml.write_text("<shadow stoptime='1'/>")
    with pytest.raises(SystemExit):
        BT.main([str(xml), "--seeds", "3,3"])
    assert "duplicates" in capsys.readouterr().err


def test_prewarmer_kills_hung_probe(tmp_path):
    """A hung probe child is SIGKILLed past its deadline and counted
    failed — the scheduler-watchdog contract one level down."""
    def hung_probe(python, spec):
        return [sys.executable, "-c", "import time; time.sleep(600)"]

    pw = Prewarmer([{"id": "r1", "config": "a~x"}],
                   str(tmp_path / "c"), log=lambda m: None,
                   probe_fn=hung_probe, warm_fn=_fake_probe,
                   probe_timeout_s=0.2)
    _drive(pw, timeout_s=30.0)
    assert pw.ready("r1")


# ---------------------------------------------------------------------
# fleet wiring: batch specs, argv builders, shape journal fold
# ---------------------------------------------------------------------

def test_make_spec_batch_is_config_only():
    with pytest.raises(ValueError, match="config runs"):
        make_spec("x", cmd=["true"], batch="grp")
    spec = make_spec("x-s7", config="a.xml", batch="grp", batch_seed=7)
    assert spec["batch"] == "grp" and spec["batch_seed"] == 7


def test_build_batch_argv_forms(tmp_path):
    q = Queue(str(tmp_path / "q")).ensure()
    # one XML x N seeds
    specs = [make_spec(f"g-s{s}", config="/tmp/a.xml", batch="g",
                       batch_seed=s, digest_every=8, perf="")
             for s in (1, 2)]
    argv = build_batch_argv(q, specs, aot_cache=str(tmp_path / "c"))
    s = " ".join(argv)
    assert " batch " in s and "--seeds 1,2" in s
    assert s.count("a.xml") == 1
    assert "--digest-paths" in s
    assert os.path.abspath(q.digest_path("g-s1")) in s
    assert "--digest-every 8" in s and "--perf" in s
    assert "--aot-cache" in s
    # one XML per member
    specs = [make_spec("m1", config="/tmp/a.xml", batch="g"),
             make_spec("m2", config="/tmp/b.xml", batch="g")]
    argv = build_batch_argv(q, specs)
    s = " ".join(argv)
    assert "a.xml" in s and "b.xml" in s and "--seeds" not in s
    # single runs get the cache as an explicit flag too
    spec = make_spec("solo", config="/tmp/a.xml")
    argv = build_child_argv(q, spec, resume=False,
                            aot_cache=str(tmp_path / "c"))
    assert "--aot-cache" in argv


def test_build_batch_argv_refuses_malformed_groups(tmp_path):
    """Backstop for the submit-time gate: a group mixing seeded and
    unseeded members, or seeded members resolving DIFFERENT XMLs,
    must refuse to spawn (OSError -> per-member spawn failure) —
    never silently drop seeds or run the wrong config."""
    q = Queue(str(tmp_path / "q")).ensure()
    xa, xb = tmp_path / "a.xml", tmp_path / "b.xml"
    xa.write_text("<shadow stoptime='1'/>")
    xb.write_text("<shadow stoptime='2'/>")
    mixed = [make_spec("m1", config=str(xa), batch="g", batch_seed=1),
             make_spec("m2", config=str(xa), batch="g")]
    with pytest.raises(OSError, match="mixes seeded"):
        build_batch_argv(q, mixed)
    divergent = [
        make_spec("d1", config=str(xa), batch="g", batch_seed=1),
        make_spec("d2", config=str(xb), batch="g", batch_seed=2)]
    with pytest.raises(OSError, match="ONE config"):
        build_batch_argv(q, divergent)
    # same CONTENT under different paths (the queue's per-member
    # copies) is the valid seeded form
    xc = tmp_path / "c.xml"
    xc.write_text(xa.read_text())
    ok = [make_spec("k1", config=str(xa), batch="g", batch_seed=1),
          make_spec("k2", config=str(xc), batch="g", batch_seed=2)]
    assert "--seeds" in " ".join(build_batch_argv(q, ok))


def test_submit_refuses_inconsistent_batch_group(tmp_path):
    """The submit-time gate: a later submission cannot change an
    existing group's form (seeded vs per-XML) or, in the seeded form,
    its one XML."""
    from shadow_tpu.fleet.cli import main as fleet_main

    qdir = str(tmp_path / "q")
    xa, xb = tmp_path / "a.xml", tmp_path / "b.xml"
    xa.write_text('<shadow stoptime="6"><host id="h1"/></shadow>')
    xb.write_text('<shadow stoptime="9"><host id="h1"/></shadow>')
    assert fleet_main(["submit", qdir, str(xa), "--batch", "g",
                       "--seeds", "1,2"]) == 0
    with pytest.raises(SystemExit):        # form change: unseeded
        fleet_main(["submit", qdir, str(xa), "--batch", "g",
                    "--id", "late"])
    with pytest.raises(SystemExit):        # different XML content
        fleet_main(["submit", qdir, str(xb), "--batch", "g",
                    "--id", "late2", "--seeds", "3"])
    with pytest.raises(SystemExit):        # per-member knob drift
        fleet_main(["submit", qdir, str(xa), "--batch", "g",
                    "--id", "late3", "--seeds", "4", "--perf"])
    # same form + same content + same knobs extends the group
    assert fleet_main(["submit", qdir, str(xa), "--batch", "g",
                       "--id", "more", "--seeds", "3"]) == 0
    # per-XML form: a colliding config BASENAME would only fail at
    # run time (the batch child names outputs by stem) — refused here
    qdir2 = str(tmp_path / "q2")
    sub = tmp_path / "sub"
    sub.mkdir()
    xa2 = sub / "a.xml"                    # same stem, other content
    xa2.write_text('<shadow stoptime="7"><host id="h1"/></shadow>')
    assert fleet_main(["submit", qdir2, str(xa),
                       "--batch", "h"]) == 0
    with pytest.raises(SystemExit):
        fleet_main(["submit", qdir2, str(xa2), "--batch", "h",
                    "--id", "dup"])


def test_queue_prewarm_fold(tmp_path):
    """Shape records fold separately from run states: fleet status
    reports shapes warmed vs pending, and fold() never mistakes a
    prewarm record for a run transition."""
    q = Queue(str(tmp_path / "q")).ensure()
    q.submit(make_spec("r1", cmd=["true"]))
    q.append("prewarm", shape="fpA", state="resolved", run="r1")
    q.append("prewarm", shape="fpB", state="resolved", run="r2")
    q.append("prewarm", shape="fpA", state="warming", run="r1")
    q.append("prewarm", shape="fpA", state="warmed")
    pw = q.prewarm_fold()
    assert pw["shapes"] == {"fpA": "warmed", "fpB": "pending"}
    assert pw["runs"] == {"r1": "fpA", "r2": "fpB"}
    states = q.fold()
    assert set(states) == {"r1"} and states["r1"].state == "queued"


# ---------------------------------------------------------------------
# determinism proofs (the ISSUE 13 acceptance criteria)
# ---------------------------------------------------------------------

def _run_individual(path, scen, every=8):
    sim = Simulation(scen, engine_cfg=EngineConfig(num_hosts=2, **CFG))
    rep = sim.run(digest=str(path), digest_every=every)
    return str(path), rep


def _divergence_rc(a, b):
    return subprocess.run(
        [sys.executable, DIVERGENCE, str(a), str(b)],
        capture_output=True, text=True).returncode


def test_batch_chains_byte_identical_to_individual(tmp_path):
    """THE batching determinism proof: a vmapped batch of N scenarios
    emits N digest chains byte-identical to the same N scenarios run
    individually (and per-lane summaries match), while genuinely
    different lanes stay different."""
    seeds = (7, 8)
    indiv = {}
    for seed in seeds:
        indiv[seed] = _run_individual(
            tmp_path / f"ind-{seed}.jsonl",
            ping_scen(seed=seed, topo=LOSSY_TOPO))

    sims = [Simulation(ping_scen(seed=s, topo=LOSSY_TOPO),
                       engine_cfg=EngineConfig(num_hosts=2, **CFG))
            for s in seeds]
    paths = [str(tmp_path / f"bat-{s}.jsonl") for s in seeds]
    reports = BT.run_batch(sims, names=[f"s{s}" for s in seeds],
                           digest_paths=paths, digest_every=8)

    for seed, bpath, rep in zip(seeds, paths, reports):
        ipath, irep = indiv[seed]
        assert open(bpath, "rb").read() == open(ipath, "rb").read(), (
            f"seed {seed}: batch lane chain differs from its "
            "individual run")
        assert _divergence_rc(bpath, ipath) == 0
        assert rep.summary()["events"] == irep.summary()["events"]
        assert rep.windows == irep.windows
    # the lanes are real per-scenario chains, not copies of lane 0
    assert (open(paths[0], "rb").read()
            != open(paths[1], "rb").read())


def test_batch_refuses_mixed_shapes():
    a = Simulation(ping_scen(seed=1),
                   engine_cfg=EngineConfig(num_hosts=2, **CFG))
    b = Simulation(ping_scen(seed=2),
                   engine_cfg=EngineConfig(num_hosts=2, **{
                       **CFG, "qcap": 32}))
    with pytest.raises(BT.BatchShapeError, match="EngineConfig"):
        BT.check_same_shape([a, b])


def test_cached_chains_byte_identical_to_uncached(tmp_path):
    """THE cache determinism proof: the same scenario run (a) with no
    cache, (b) cold through the cache (compile + store), (c) fresh
    AotJit loading from disk, yields byte-identical digest chains —
    the executable the disk hands back IS the program that was
    compiled."""
    from shadow_tpu.engine import window as W

    saved = dict(W._RW_INSTANCES)
    try:
        a, _ = _run_individual(tmp_path / "a.jsonl",
                               ping_scen(seed=7, topo=LOSSY_TOPO))

        AC.install(str(tmp_path / "cache"))
        W._RW_INSTANCES.clear()         # fresh-process stand-in
        before = dict(AC.STATS)
        b, _ = _run_individual(tmp_path / "b.jsonl",
                               ping_scen(seed=7, topo=LOSSY_TOPO))
        if _supports_serialization():
            assert _delta(before, "disk_stores")["disk_stores"] >= 1

        W._RW_INSTANCES.clear()
        before = dict(AC.STATS)
        c, _ = _run_individual(tmp_path / "c.jsonl",
                               ping_scen(seed=7, topo=LOSSY_TOPO))
        if _supports_serialization():
            d = _delta(before, "compiles", "disk_hits")
            assert d["compiles"] == 0 and d["disk_hits"] >= 1, (
                "the warm run recompiled instead of disk-loading")

        ab = open(a, "rb").read()
        assert ab == open(b, "rb").read()
        assert ab == open(c, "rb").read()
        assert _divergence_rc(a, c) == 0
    finally:
        W._RW_INSTANCES.clear()
        W._RW_INSTANCES.update(saved)


# ---------------------------------------------------------------------
# process-fresh CLI round trip (slow: subprocess jax imports)
# ---------------------------------------------------------------------

def _cli_env():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("SHADOW_TPU_AOT_CACHE", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    return env


def _cli(args, timeout=900):
    return subprocess.run(
        [sys.executable, "-m", "shadow_tpu"] + args,
        capture_output=True, text=True, timeout=timeout,
        env=_cli_env(), cwd=REPO)


def _last_json(out):
    """The probe/prewarm JSON line (logger lines surround it — the
    same scan the real Prewarmer does on its probe children)."""
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict):
            return rec
    raise AssertionError(f"no JSON line in stdout:\n{out.stdout}")


@pytest.mark.slow
def test_cli_process_fresh_warm_roundtrip(tmp_path):
    """The acceptance shape end to end, across real process
    boundaries: probe the shape fingerprint (no compile), pre-warm
    cold (compile_cache=miss), pre-warm again process-fresh
    (compile_cache=hit), then two full runs through the cache whose
    digest chains are byte-identical."""
    if not _supports_serialization():
        pytest.skip("backend cannot serialize executables")
    xml = tmp_path / "ping.xml"
    xml.write_text(ping_scen(seed=7, topo=LOSSY_TOPO).to_xml())
    cache = str(tmp_path / "cache")
    caps = "qcap=16,scap=4,obcap=8,incap=16,chunk=8"
    base = [str(xml), "--seed", "7", "--engine-caps", caps,
            "--digest-every", "8"]

    out = _cli(base + ["--shape-fingerprint"])
    assert out.returncode == 0, out.stderr
    probe = _last_json(out)
    assert int(probe["shape_fingerprint"], 16) >= 0
    # the dedup key is chunk- and mesh-qualified: same config
    # fingerprint at a different cadence or worker count is a
    # different compiled program
    assert probe["shape"] == f"c8.w0.{probe['shape_fingerprint']}"

    d1, d2 = str(tmp_path / "d1.jsonl"), str(tmp_path / "d2.jsonl")
    out = _cli(base + ["--aot-cache", cache, "--prewarm",
                       "--digest", d1])
    assert out.returncode == 0, out.stderr
    cold = _last_json(out)
    assert cold["compile_cache"] == "miss"
    assert cold["fingerprint"] == probe["shape_fingerprint"]

    out = _cli(base + ["--aot-cache", cache, "--prewarm",
                       "--digest", d1])
    assert out.returncode == 0, out.stderr
    warm = _last_json(out)
    assert warm["compile_cache"] == "hit", (
        "a process-fresh pre-warm of a cached shape recompiled")

    for d in (d1, d2):
        out = _cli(base + ["--aot-cache", cache, "--digest", d])
        assert out.returncode == 0, out.stderr
    assert open(d1, "rb").read() == open(d2, "rb").read()
    assert _divergence_rc(d1, d2) == 0
    assert any(n.endswith(".exec") for n in os.listdir(cache))
