"""Multi-process (DCN-tier) backend test.

Realizes the reference's anticipated multi-slave deployment
(shd-master.c:415-416 "once we get multiple slaves", shd-message.h):
two OS processes, each contributing 2 virtual CPU devices, join one
JAX distributed runtime over loopback TCP and run the SAME shard_map
window program on a 4-device global mesh. The result must be
bit-identical to the single-process run — the same contract the
single-process sharded path already guarantees vs single-chip.

Slow (~1 min): spawns two fresh JAX processes that each compile the
window program; it is the only coverage of the DCN tier, so it stays
in the default suite.
"""

import os
import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

HELPERS = Path(__file__).resolve().parent / "helpers"


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


_PROBE = """\
import os, sys
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
coord, n, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coord, n, pid)
import jax.numpy as jnp
from jax.experimental import multihost_utils
x = multihost_utils.process_allgather(jnp.ones(2) * (pid + 1))
assert float(x.sum()) == 6.0
print("probe ok", pid)
"""

_PROBE_RESULT = None   # None = not probed; "" = supported; else error


def _multiprocess_cpu_error():
    """One cached 2-process probe of the jax runtime: some CPU
    backends (e.g. jax 0.4.37's: "Multiprocess computations aren't
    implemented on the CPU backend") cannot run multi-process
    computations at all. Returns "" when supported, else the error
    tail — so every test in this file SKIPS cleanly on such a box
    instead of burning its 540s worker timeouts on guaranteed
    failures."""
    global _PROBE_RESULT
    if _PROBE_RESULT is not None:
        return _PROBE_RESULT
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    procs = [subprocess.Popen(
        [sys.executable, "-c", _PROBE, coord, "2", str(pid)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for pid in range(2)]
    outs = []
    for p in procs:
        try:
            stdout, _ = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            _PROBE_RESULT = "probe timed out (distributed init hang)"
            return _PROBE_RESULT
        outs.append(stdout.decode(errors="replace"))
    if all(p.returncode == 0 for p in procs):
        _PROBE_RESULT = ""
    else:
        bad = next(t for p, t in zip(procs, outs) if p.returncode)
        lines = bad.strip().splitlines() or ["(no output)"]
        _PROBE_RESULT = lines[-1][-300:]
    return _PROBE_RESULT


@pytest.fixture(autouse=True)
def _require_multiprocess_cpu():
    err = _multiprocess_cpu_error()
    if err:
        pytest.skip(f"multi-process mesh unsupported here: {err}")


def test_two_process_mesh_matches_single(tmp_path):
    """Stats AND the determinism digest chain: the 2-process mesh run
    must be bit-identical to the single-process run, record for
    record (the chain is recorded via the per-record allgather,
    process 0 writing — the lifted digest+multi-process gate)."""
    sys.path.insert(0, str(HELPERS))
    try:
        from scenario_phold import make_scenario, make_cfg
    finally:
        sys.path.pop(0)
    from shadow_tpu.engine.sim import Simulation

    # ground truth: single-process run (virtual 8-device CPU already
    # configured by conftest; mesh=None = single chip)
    dg_single = str(tmp_path / "dg_single.jsonl")
    truth = Simulation(make_scenario(), engine_cfg=make_cfg()).run(
        digest=dg_single, digest_every=8)
    assert truth.events > 0

    out = tmp_path / "stats.npy"
    dg_multi = str(tmp_path / "dg_multi.jsonl")
    _spawn_workers(out, ["--digest", dg_multi], "fresh")
    stats = np.load(out)
    assert np.array_equal(stats, truth.stats), (
        "multi-process stats diverge from single-process run")
    a = Path(dg_single).read_bytes()
    b = Path(dg_multi).read_bytes()
    assert a and a == b, (
        "2-process digest chain differs from the single-process "
        "chain — run tools/divergence.py on the two files")


def _spawn_workers(out, extra, tag, expect_signal=None):
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    procs = [
        subprocess.Popen(
            [sys.executable, str(HELPERS / "dist_worker.py"),
             coord, "2", str(pid), str(out)] + extra,
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for pid in range(2)
    ]
    # reap ALL workers before asserting: an early assert would leak
    # the peer (blocked on the distributed barrier) as an orphan
    outputs = []
    for p in procs:
        try:
            stdout, _ = p.communicate(timeout=540)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outputs.append(stdout.decode(errors="replace"))
    want = -expect_signal if expect_signal else 0
    for pid, (p, text) in enumerate(zip(procs, outputs)):
        assert p.returncode == want, (
            f"{tag} proc {pid} exited {p.returncode} "
            f"(wanted {want}):\n{text[-3000:]}")


def test_multiprocess_pcap_matches_single(tmp_path):
    """pcap under the multi-process mesh (round 4 — the last
    stats-only gate on the DCN tier): the rings allgather per chunk
    and process 0 writes the files; captures must equal the
    single-process run's byte for byte."""
    sys.path.insert(0, str(HELPERS))
    try:
        from scenario_phold import make_scenario, make_cfg
    finally:
        sys.path.pop(0)
    from shadow_tpu.engine.sim import Simulation

    single_dir = tmp_path / "pcap_single"
    truth = Simulation(make_scenario(pcap=True),
                       engine_cfg=make_cfg()).run(
        pcap_dir=str(single_dir))
    ref_files = sorted(os.listdir(single_dir))
    assert ref_files, "single-process run captured nothing"

    multi_dir = tmp_path / "pcap_multi"
    out = tmp_path / "stats.npy"
    _spawn_workers(out, ["--pcap", str(multi_dir)], "pcap")
    assert np.array_equal(np.load(out), truth.stats)
    assert sorted(os.listdir(multi_dir)) == ref_files
    for name in ref_files:
        a = (single_dir / name).read_bytes()
        b = (multi_dir / name).read_bytes()
        assert a == b, f"{name} diverges between single and DCN runs"


def test_multiprocess_checkpoint_resume(tmp_path):
    """DCN-tier checkpoint/resume (round 3): a 2-process mesh
    checkpoints mid-run (process 0 writes ONE global snapshot), a
    fresh 2-process mesh resumes from it, and the resumed run's final
    stats equal both the uninterrupted multi-process run's and the
    single-process truth."""
    sys.path.insert(0, str(HELPERS))
    try:
        from scenario_phold import make_scenario, make_cfg
    finally:
        sys.path.pop(0)
    from shadow_tpu.engine.sim import Simulation

    truth = Simulation(make_scenario(), engine_cfg=make_cfg()).run()

    ckpt = str(tmp_path / "snap.npz")
    out_a = tmp_path / "stats_a.npy"
    _spawn_workers(out_a, ["--ckpt", ckpt], "checkpointing")
    from shadow_tpu.engine.checkpoint import resolve_latest
    assert resolve_latest(ckpt), "process 0 never wrote a snapshot"
    stats_a = np.load(out_a)
    assert np.array_equal(stats_a, truth.stats)

    out_b = tmp_path / "stats_b.npy"
    _spawn_workers(out_b, ["--ckpt", ckpt, "--resume"], "resuming")
    stats_b = np.load(out_b)
    assert np.array_equal(stats_b, truth.stats), (
        "resumed multi-process run diverges from the uninterrupted run")


def test_multiprocess_digest_resume_matches_single(tmp_path):
    """resume + digest + multi-process mesh — the last residual PR 5
    gate, lifted: a 2-process mesh run recording a digest chain is
    SIGKILLed deterministically mid-run (the durability CrashHook
    fires in BOTH processes at the same chunk boundary), a fresh
    2-process mesh resumes from the global snapshot — every process
    reads the chain file to refold the kept prefix and re-arm the
    cadence in lockstep, process 0 truncates/appends — and the final
    chain is byte-identical to the single-process uninterrupted
    chain (and the stats match)."""
    sys.path.insert(0, str(HELPERS))
    try:
        from scenario_phold import make_scenario, make_cfg
    finally:
        sys.path.pop(0)
    from shadow_tpu.engine.sim import Simulation

    dg_single = str(tmp_path / "dg_single.jsonl")
    truth = Simulation(make_scenario(), engine_cfg=make_cfg()).run(
        digest=dg_single, digest_every=8)
    assert truth.events > 0

    ckpt = str(tmp_path / "snap.npz")
    dg_multi = str(tmp_path / "dg_multi.jsonl")
    out_a = tmp_path / "stats_a.npy"
    # phase A: checkpoint every simulated second, die at 2.0 sim-s —
    # after at least one snapshot, with live chain records past it
    _spawn_workers(out_a, ["--ckpt", ckpt, "--digest", dg_multi,
                           "--crash-ns", "2000000000"],
                   "crashing", expect_signal=9)
    from shadow_tpu.engine.checkpoint import resolve_latest
    assert resolve_latest(ckpt), "crashed before the first snapshot"
    assert Path(dg_multi).read_bytes(), (
        "crashed run recorded no chain records to rewind")

    out_b = tmp_path / "stats_b.npy"
    _spawn_workers(out_b, ["--ckpt", ckpt, "--resume",
                           "--digest", dg_multi], "resuming")
    assert np.array_equal(np.load(out_b), truth.stats), (
        "resumed multi-process stats diverge from single-process run")
    a = Path(dg_single).read_bytes()
    b = Path(dg_multi).read_bytes()
    assert a and a == b, (
        "resumed 2-process digest chain differs from the "
        "single-process uninterrupted chain — run "
        "tools/divergence.py on the two files")
