"""Multi-process (DCN-tier) backend test.

Realizes the reference's anticipated multi-slave deployment
(shd-master.c:415-416 "once we get multiple slaves", shd-message.h):
two OS processes, each contributing 2 virtual CPU devices, join one
JAX distributed runtime over loopback TCP and run the SAME shard_map
window program on a 4-device global mesh. The result must be
bit-identical to the single-process run — the same contract the
single-process sharded path already guarantees vs single-chip.

Slow (~1 min): spawns two fresh JAX processes that each compile the
window program; it is the only coverage of the DCN tier, so it stays
in the default suite.
"""

import os
import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

HELPERS = Path(__file__).resolve().parent / "helpers"


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_mesh_matches_single(tmp_path):
    """Stats AND the determinism digest chain: the 2-process mesh run
    must be bit-identical to the single-process run, record for
    record (the chain is recorded via the per-record allgather,
    process 0 writing — the lifted digest+multi-process gate)."""
    sys.path.insert(0, str(HELPERS))
    try:
        from scenario_phold import make_scenario, make_cfg
    finally:
        sys.path.pop(0)
    from shadow_tpu.engine.sim import Simulation

    # ground truth: single-process run (virtual 8-device CPU already
    # configured by conftest; mesh=None = single chip)
    dg_single = str(tmp_path / "dg_single.jsonl")
    truth = Simulation(make_scenario(), engine_cfg=make_cfg()).run(
        digest=dg_single, digest_every=8)
    assert truth.events > 0

    out = tmp_path / "stats.npy"
    dg_multi = str(tmp_path / "dg_multi.jsonl")
    _spawn_workers(out, ["--digest", dg_multi], "fresh")
    stats = np.load(out)
    assert np.array_equal(stats, truth.stats), (
        "multi-process stats diverge from single-process run")
    a = Path(dg_single).read_bytes()
    b = Path(dg_multi).read_bytes()
    assert a and a == b, (
        "2-process digest chain differs from the single-process "
        "chain — run tools/divergence.py on the two files")


def _spawn_workers(out, extra, tag):
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    procs = [
        subprocess.Popen(
            [sys.executable, str(HELPERS / "dist_worker.py"),
             coord, "2", str(pid), str(out)] + extra,
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for pid in range(2)
    ]
    # reap ALL workers before asserting: an early assert would leak
    # the peer (blocked on the distributed barrier) as an orphan
    outputs = []
    for p in procs:
        try:
            stdout, _ = p.communicate(timeout=540)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outputs.append(stdout.decode(errors="replace"))
    for pid, (p, text) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, (
            f"{tag} proc {pid} failed:\n{text[-3000:]}")


def test_multiprocess_pcap_matches_single(tmp_path):
    """pcap under the multi-process mesh (round 4 — the last
    stats-only gate on the DCN tier): the rings allgather per chunk
    and process 0 writes the files; captures must equal the
    single-process run's byte for byte."""
    sys.path.insert(0, str(HELPERS))
    try:
        from scenario_phold import make_scenario, make_cfg
    finally:
        sys.path.pop(0)
    from shadow_tpu.engine.sim import Simulation

    single_dir = tmp_path / "pcap_single"
    truth = Simulation(make_scenario(pcap=True),
                       engine_cfg=make_cfg()).run(
        pcap_dir=str(single_dir))
    ref_files = sorted(os.listdir(single_dir))
    assert ref_files, "single-process run captured nothing"

    multi_dir = tmp_path / "pcap_multi"
    out = tmp_path / "stats.npy"
    _spawn_workers(out, ["--pcap", str(multi_dir)], "pcap")
    assert np.array_equal(np.load(out), truth.stats)
    assert sorted(os.listdir(multi_dir)) == ref_files
    for name in ref_files:
        a = (single_dir / name).read_bytes()
        b = (multi_dir / name).read_bytes()
        assert a == b, f"{name} diverges between single and DCN runs"


def test_multiprocess_checkpoint_resume(tmp_path):
    """DCN-tier checkpoint/resume (round 3): a 2-process mesh
    checkpoints mid-run (process 0 writes ONE global snapshot), a
    fresh 2-process mesh resumes from it, and the resumed run's final
    stats equal both the uninterrupted multi-process run's and the
    single-process truth."""
    sys.path.insert(0, str(HELPERS))
    try:
        from scenario_phold import make_scenario, make_cfg
    finally:
        sys.path.pop(0)
    from shadow_tpu.engine.sim import Simulation

    truth = Simulation(make_scenario(), engine_cfg=make_cfg()).run()

    ckpt = str(tmp_path / "snap.npz")
    out_a = tmp_path / "stats_a.npy"
    _spawn_workers(out_a, ["--ckpt", ckpt], "checkpointing")
    from shadow_tpu.engine.checkpoint import resolve_latest
    assert resolve_latest(ckpt), "process 0 never wrote a snapshot"
    stats_a = np.load(out_a)
    assert np.array_equal(stats_a, truth.stats)

    out_b = tmp_path / "stats_b.npy"
    _spawn_workers(out_b, ["--ckpt", ckpt, "--resume"], "resuming")
    stats_b = np.load(out_b)
    assert np.array_equal(stats_b, truth.stats), (
        "resumed multi-process run diverges from the uninterrupted run")
