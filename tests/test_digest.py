"""Determinism flight-recorder tests (obs.digest + tools/divergence).

The contract under test: same-seed dual runs produce byte-identical
digest chains (faults included); a genuinely divergent pair of runs is
reported with window / section / host attribution; and --bisect pins
the exact window by cadence-1 replay from the manifests.

Engine shapes mirror tests/test_obs.py (2-host ping, chunk 8) so the
compiled window program is shared across both files.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from shadow_tpu.core.config import HostSpec, ProcessSpec, Scenario, load_xml
from shadow_tpu.engine.sim import Simulation
from shadow_tpu.engine.state import EngineConfig
from shadow_tpu.obs import digest as D

from test_phold import MESH_TOPO

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DIVERGENCE = os.path.join(REPO, "tools", "divergence.py")

CFG = dict(qcap=16, scap=4, obcap=8, incap=16, chunk_windows=8)

# MESH_TOPO with loss on every edge: the drop rolls come from the
# counter PRNG keyed by the scenario seed, so different seeds make the
# ping runs genuinely diverge (a lossless ping pair is seed-INsensitive
# — deterministic apps, placement hints, no RNG draws — and its digest
# chains are legitimately identical across seeds)
LOSSY_TOPO = MESH_TOPO.replace(
    '<data key="d9">0.0</data>', '<data key="d9">0.4</data>')


@pytest.fixture(autouse=True)
def _digest_global_reset():
    """The digest recorder is process-global; a test failing
    mid-install must not leak an enabled recorder into the next test
    (the obs.trace/metrics fixture contract)."""
    yield
    D.finish()


def ping_scen(stop=6, seed=1, topo=MESH_TOPO, count=3):
    s = Scenario(
        stop_time=stop * 10**9,
        topology_graphml=topo,
        hosts=[
            HostSpec(id="srv", processes=[
                ProcessSpec(plugin="pingserver", start_time=10**9,
                            arguments="port=8000")]),
            HostSpec(id="cli", processes=[
                ProcessSpec(plugin="ping", start_time=2 * 10**9,
                            arguments="peer=srv port=8000 "
                                      "interval=500ms "
                                      f"size=100 count={count}")]),
        ])
    s.seed = seed
    return s


def run_digest(path, scen, every=8):
    sim = Simulation(scen, engine_cfg=EngineConfig(num_hosts=2, **CFG))
    sim.run(digest=str(path), digest_every=every)
    assert not D.ENABLED  # run() owns the recorder it installed
    return str(path)


def test_dual_run_chain_identical(tmp_path):
    a = run_digest(tmp_path / "a.jsonl", ping_scen(seed=7))
    b = run_digest(tmp_path / "b.jsonl", ping_scen(seed=7))
    assert open(a, "rb").read() == open(b, "rb").read()

    recs = [json.loads(l) for l in open(a).read().splitlines()]
    assert recs
    assert recs[-1]["kind"] == "final"
    windows = [r["window"] for r in recs]
    assert windows == sorted(windows)
    for r in recs:
        assert set(r) >= {"window", "sim_ns", "kind", "sections",
                          "chain"}
        # every state section present, none bucketed as "other"
        assert {"event_queue", "tcp", "nic", "outbox", "rng", "app",
                "stats"} <= set(r["sections"])
        assert "other" not in r["sections"]
        assert len(r["hosts"]) == 2      # per-host detail at tiny H

    mf = json.load(open(a + ".manifest.json"))
    assert mf["seed"] == 7
    assert mf["hosts"] == 2 and mf["host_names"] == ["srv", "cli"]
    assert mf["digest_every"] == 8
    assert mf["engine_config"]["qcap"] == CFG["qcap"]
    assert mf["versions"]["jax"] and mf["platform"]
    # run-mode stamps: pcap changes digested state (trace-ring
    # draining), faults/hosted gate --use-checkpoint replay — a pair
    # differing here must show a manifest delta, not a mystery
    assert (mf["pcap"], mf["faults"], mf["hosted"]) == (False,) * 3


def test_faults_demo_dual_run_identical(tmp_path):
    """The acceptance scenario: same-seed dual runs of
    examples/faults-demo.xml produce byte-identical chains, with
    records at every fault boundary."""
    def go(name):
        scen = load_xml(os.path.join(REPO, "examples/faults-demo.xml"))
        scen.seed = 3
        path = tmp_path / name
        sim = Simulation(scen,
                         engine_cfg=EngineConfig(num_hosts=2, **CFG))
        sim.run(digest=str(path), digest_every=8)
        return str(path)

    a, b = go("fa.jsonl"), go("fb.jsonl")
    assert open(a, "rb").read() == open(b, "rb").read()
    assert json.load(open(a + ".manifest.json"))["faults"] is True
    kinds = [json.loads(l)["kind"] for l in open(a).read().splitlines()]
    # the demo schedules a link flap and a host kill/restart: each
    # applied fault batch lands one record
    assert kinds.count("fault") >= 3
    assert kinds[-1] == "final"


def test_divergence_tool_reports_window_section_host(tmp_path):
    """Different-seed lossy runs: tools/divergence.py (headless, no
    jax) reports the first divergent window with per-section and
    per-host attribution and exits 1; identical chains exit 0."""
    a = run_digest(tmp_path / "a.jsonl",
                   ping_scen(seed=101, topo=LOSSY_TOPO, count=8))
    b = run_digest(tmp_path / "b.jsonl",
                   ping_scen(seed=202, topo=LOSSY_TOPO, count=8))
    assert open(a, "rb").read() != open(b, "rb").read()

    out = subprocess.run(
        [sys.executable, DIVERGENCE, a, b, "--json"],
        capture_output=True, text=True)
    assert out.returncode == 1, out.stderr
    rep = json.loads(out.stdout)
    div = rep["first_divergence"]
    assert isinstance(div["window"], int)
    assert div["sections"]                  # section attribution
    names = {h["name"] for h in div["hosts"]}
    assert names & {"srv", "cli"}           # host attribution
    assert rep["manifest_deltas"]["seed"] == {"a": 101, "b": 202}

    # human rendering: one readable line-oriented report
    txt = subprocess.run(
        [sys.executable, DIVERGENCE, a, b],
        capture_output=True, text=True)
    assert txt.returncode == 1
    assert "first divergence" in txt.stdout
    assert "divergent sections" in txt.stdout

    same = subprocess.run(
        [sys.executable, DIVERGENCE, a, a, "--json"],
        capture_output=True, text=True)
    assert same.returncode == 0
    assert json.loads(same.stdout)["identical"] is True


def test_divergence_tool_bad_input(tmp_path):
    """Missing / empty / truncated chains: one-line diagnosis, exit 2,
    no traceback."""
    missing = str(tmp_path / "nope.jsonl")
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    trunc = tmp_path / "trunc.jsonl"
    trunc.write_text('{"window": 0, "sections": {"a": "b"}, "chain')
    for bad in (missing, str(empty), str(trunc)):
        out = subprocess.run(
            [sys.executable, DIVERGENCE, bad, bad],
            capture_output=True, text=True)
        assert out.returncode == 2, (bad, out.stderr)
        assert "Traceback" not in out.stderr
        assert out.stderr.strip().startswith("divergence:")


def test_hosted_op_stream_in_chain(tmp_path):
    """Hosted apps: records carry the hosted-channel op-stream digest
    (hosting.runtime op batches) as its own section, and same-seed
    dual runs stay byte-identical THROUGH the hosted tier — the
    'bit-identical, hosted children included' contract."""
    from test_hosting import CFG as HCFG  # registers test-pinger

    def go(name):
        scen = Scenario(
            stop_time=6 * 10**9,
            topology_graphml=MESH_TOPO,
            hosts=[
                HostSpec(id="srv", processes=[
                    ProcessSpec(plugin="pingserver", start_time=10**9,
                                arguments="port=8000")]),
                HostSpec(id="cli", processes=[
                    ProcessSpec(plugin="hosted:test-pinger",
                                start_time=2 * 10**9,
                                arguments="peer=srv port=8000 count=3 "
                                          "interval_s=1 size=64")]),
            ])
        scen.seed = 5
        path = tmp_path / name
        sim = Simulation(scen,
                         engine_cfg=EngineConfig(num_hosts=2, **HCFG))
        sim.run(digest=str(path), digest_every=4)
        return str(path)

    a, b = go("ha.jsonl"), go("hb.jsonl")
    assert open(a, "rb").read() == open(b, "rb").read()
    recs = [json.loads(l) for l in open(a).read().splitlines()]
    assert len(recs) >= 3     # cadence records across the op activity
    assert all("hosted" in r["sections"] for r in recs)
    assert all("ops" in r["hosted"] for r in recs)
    # the op stream actually advanced (the pinger issued socket ops)
    assert recs[0]["hosted"]["ops"] != recs[-1]["hosted"]["ops"]


def test_recorder_cadence_is_per_run():
    """One recorder may span several runs (an outer harness extending
    one chain), but each run's window counter restarts at 0 — or
    jumps, on resume. begin_run() must re-arm next_due, else the clock
    left by run 1's last record suppresses every cadence sample of
    run 2."""
    r = D.DigestRecorder(None, every=8)
    r.next_due = 104          # as left by a previous run's last record
    r.begin_run(0)
    assert not r.due(7) and r.due(8)
    r.begin_run(500)          # resumed run: the counter jumps forward
    assert not r.due(507) and r.due(508)


def test_canonicalize_state_masks_dead_slots():
    """Unit: two host-side states that differ ONLY in dead-slot
    garbage (freed queue slots, outbox tail, ring tail, closed socket
    rows) canonicalize to identical arrays; live differences
    survive."""
    from shadow_tpu.core.simtime import SIMTIME_MAX
    from shadow_tpu.engine.state import alloc_hosts
    from shadow_tpu.engine.checkpoint import named_leaves
    from shadow_tpu.engine.window import canonicalize_state

    cfg = EngineConfig(num_hosts=2, **CFG)

    def arrs():
        return {k: np.array(v) for k, v in
                named_leaves(alloc_hosts(cfg))}

    a, b = arrs(), arrs()
    # dead garbage: a freed queue slot's payload, the outbox tail, a
    # NIC-ring slot outside [head, head+cnt), an unused socket row
    b["eq_pkt"][0, 3] = 77            # eq_time stays SIMTIME_MAX: free
    b["ob_pkt"][1, 5] = 9             # ob_cnt is 0: tail garbage
    b["txq_pkt"][0, 2] = 5            # txq_cnt is 0: dead ring slot
    b["sk_rcv_nxt"][1, 2] = 123       # sk_used false: closed row
    b["tr_time"][0, 0] = 42           # tr_cnt is 0: dead trace slot
    ca, cb = canonicalize_state(a), canonicalize_state(b)
    for k in ca:
        assert np.array_equal(ca[k], cb[k]), k

    # a LIVE difference is preserved: occupy the slot, then differ
    c = arrs()
    c["eq_time"][0, 3] = 5            # slot live now
    c["eq_pkt"][0, 3] = 77
    d = {k: v.copy() for k, v in c.items()}
    d["eq_pkt"][0, 3] = 78
    cc, cd = canonicalize_state(c), canonicalize_state(d)
    assert not np.array_equal(cc["eq_pkt"], cd["eq_pkt"])
    assert np.array_equal(cc["eq_time"], cd["eq_time"])


@pytest.mark.slow
def test_bisect_pins_exact_window(tmp_path):
    """--bisect replays both runs from their manifests at cadence 1
    (XML config + recorded engine config) and pins the exact divergent
    window. Slow: the cadence-1 replay compiles a chunk-1 window
    program."""
    xml = tmp_path / "lossy-ping.xml"
    xml.write_text(f"""<shadow stoptime="6">
  <topology><![CDATA[{LOSSY_TOPO}]]></topology>
  <host id="srv">
    <process plugin="pingserver" starttime="1" arguments="port=8000"/>
  </host>
  <host id="cli">
    <process plugin="ping" starttime="2"
      arguments="peer=srv port=8000 interval=500ms size=100 count=8"/>
  </host>
</shadow>
""")

    def go(name, seed):
        scen = load_xml(str(xml))          # source_path -> manifest
        scen.seed = seed
        path = tmp_path / name
        sim = Simulation(scen,
                         engine_cfg=EngineConfig(num_hosts=2, **CFG))
        sim.run(digest=str(path), digest_every=8)
        return str(path)

    a, b = go("a.jsonl", 101), go("b.jsonl", 202)

    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import divergence
    finally:
        sys.path.pop(0)
    import contextlib
    import io
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = divergence.main([a, b, "--bisect", "--json",
                              "--keep-replays",
                              str(tmp_path / "replays")])
    assert rc == 1
    rep = json.loads(buf.getvalue())
    coarse = rep["first_divergence"]
    fine = rep["bisect"]
    # cadence-1 pins a window at or before the coarse record, and
    # after the last matching coarse record
    assert isinstance(fine["window"], int)
    assert fine["window"] <= coarse["window"]
    if coarse["prev_window"] is not None:
        assert fine["window"] > coarse["prev_window"]
    assert fine["sections"]
    # the replay chains were kept where we asked
    assert (tmp_path / "replays" / "replay-a.jsonl").exists()


# The canonical Hosts layout existing digest chains and checkpoints
# were written against. The hot/cold split must never move it: digest
# sections hash fields in THIS declaration order, and checkpoints
# verify leaf-for-leaf against it. Renaming, reordering, adding or
# removing a field invalidates every committed chain — do it only
# with a digest format-version bump, and update this pin in the same
# reviewed change.
CANONICAL_HOSTS_LAYOUT = (
    "eq_time", "eq_seq", "eq_kind", "eq_pkt", "eq_ctr", "eq_next",
    "rng_ctr", "cpu_avail", "nic_busy", "nic_sched", "nic_rr",
    "nic_rx_until", "txq_pkt", "txq_head", "txq_cnt", "pkt_ctr",
    "next_eport", "sk_used", "sk_proto", "sk_state", "sk_lport",
    "sk_rport", "sk_rhost", "sk_parent", "sk_snd_una", "sk_snd_nxt",
    "sk_snd_max", "sk_snd_end", "sk_rcv_nxt", "sk_ooo_s", "sk_ooo_e",
    "sk_sack_s", "sk_sack_e", "sk_hole_end", "sk_rex_nxt",
    "sk_peer_fin", "sk_fin_acked", "sk_close_after", "sk_cwnd",
    "sk_ssthresh", "sk_srtt", "sk_rtt_min", "sk_rttvar", "sk_rto",
    "sk_rto_deadline", "sk_timer_on", "sk_timer_gen", "sk_dupacks",
    "sk_rtt_seq", "sk_rtt_time", "sk_ctl", "sk_peer_rwnd",
    "sk_sndbuf", "sk_rcvbuf", "sk_hs_time", "sk_last_tx",
    "sk_syn_tag", "sk_proc", "sk_app_ref", "sk_cc_wmax",
    "sk_cc_epoch", "sk_cc_k", "app_node", "app_r", "app_proc",
    "tgen_sync", "ob_pkt", "ob_time", "ob_cnt", "ob_next", "hw_time",
    "hw_pkt", "hw_cnt", "hw_drop", "tr_time", "tr_pkt", "tr_dir",
    "tr_cnt", "tr_drop", "stats", "cap_peaks",
)


def test_digest_section_layout_pinned():
    """The hot/cold split is a drain-side carry optimization — the
    at-rest layout the digest chain and checkpoints hash is pinned
    unchanged (field set, declaration order, section mapping)."""
    from shadow_tpu.engine.state import Hosts, section_of

    assert tuple(Hosts.__dataclass_fields__) == CANONICAL_HOSTS_LAYOUT
    sections = {f: section_of(f, strict=True)
                for f in CANONICAL_HOSTS_LAYOUT}
    assert sorted(set(sections.values())) == [
        "app", "cpu", "event_queue", "hosted_wakes", "nic", "outbox",
        "rng", "stats", "tcp", "trace_ring"]
    # checkpoint leaf enumeration = digest enumeration, same order
    from shadow_tpu.engine.checkpoint import named_leaves
    from shadow_tpu.engine.state import EngineConfig, alloc_hosts
    hosts = alloc_hosts(EngineConfig(num_hosts=2, qcap=4, scap=2,
                                     obcap=4, incap=4))
    assert tuple(n for n, _ in named_leaves(hosts)) \
        == CANONICAL_HOSTS_LAYOUT
