"""Active-set compaction (EngineConfig.active_block) — the TPU-native
analogue of the reference's host-steal load balancing
(/root/reference/src/main/core/scheduler/shd-scheduler-policy-host-steal.c:
163-191): a lockstep pass steps only the ready hosts instead of paying
a full all-hosts pass per busiest-host event.

The contract under test: compaction changes WHICH rows a pass touches,
never the per-host (time, seq) execution order — so every run must be
bit-identical to the dense engine, including under sharding and in the
differential harness.
"""

import dataclasses

import numpy as np
import jax
import pytest

from shadow_tpu.core.config import HostSpec, ProcessSpec, Scenario
from shadow_tpu.engine.sim import Simulation
from shadow_tpu.engine.state import EngineConfig
from shadow_tpu.engine.pyengine import PyEngine
from shadow_tpu.parallel.shard import make_mesh

from test_phold import phold_scenario
from test_tcp import poi_topology


def _skewed_scen(stop=40):
    """One busy server, many mostly-idle clients — the lockstep-skew
    shape compaction exists for."""
    return Scenario(
        stop_time=stop * 10**9,
        topology_graphml=poi_topology(),
        hosts=[
            HostSpec(id="server", processes=[
                ProcessSpec(plugin="bulkserver", start_time=10**9,
                            arguments="port=80")]),
            HostSpec(id="client", quantity=7, processes=[
                ProcessSpec(plugin="bulk", start_time=2 * 10**9,
                            arguments="peer=server port=80 size=150000 "
                                      "count=2 pause=3s")]),
        ],
    )


CFG = dict(qcap=32, scap=12, obcap=16, incap=24, txqcap=12,
           chunk_windows=8)


def _run(scen, block, mesh=None):
    sim = Simulation(scen, engine_cfg=EngineConfig(
        num_hosts=8, active_block=block, **CFG))
    return sim.run(mesh=mesh)


def test_compaction_bit_identical_dense_vs_sparse():
    dense = _run(_skewed_scen(), 0)
    sparse = _run(_skewed_scen(), 3)      # < busy-host count: exercises
    # both the K-cap (more ready than block) and the dummy-slot path
    assert np.array_equal(dense.stats, sparse.stats)
    assert dense.windows == sparse.windows


def test_compaction_block_exceeds_hosts():
    """block >= H degenerates gracefully (K clamped to H)."""
    dense = _run(_skewed_scen(stop=20), 0)
    sparse = _run(_skewed_scen(stop=20), 64)
    assert np.array_equal(dense.stats, sparse.stats)


def test_compaction_differential():
    """The differential harness holds with compaction on: the compiled
    engine with active-set gathering still matches the heap engine bit
    for bit."""
    from test_differential import TCP_COMPARE

    cfg = EngineConfig(num_hosts=8, active_block=4, **CFG)
    jax_stats = Simulation(_skewed_scen(), engine_cfg=cfg).run().stats
    py_stats = PyEngine(Simulation(_skewed_scen(), engine_cfg=cfg)).run()
    for st in TCP_COMPARE:
        assert np.array_equal(jax_stats[:, st], py_stats[:, st]), st


def test_idle_step_identity():
    """Pin the invariant compaction's exactness rests on: a not-ready
    row's step is the IDENTITY (engine.window.step_window_pass
    docstring). Dummy gather slots duplicate a not-ready host, so a
    handler that mutated state before its ready gate (e.g. an
    unconditional rng_ctr bump) would corrupt state at scale in ways
    only end-to-end equality tests could catch — this pins it at the
    unit level: stepping a host set whose every event lies past the
    window bound must leave every array bit-identical, dense and
    sparse alike."""
    import jax.numpy as jnp
    from shadow_tpu.engine.window import step_all_hosts, step_window_pass

    sim = Simulation(_skewed_scen(), engine_cfg=EngineConfig(
        num_hosts=8, active_block=3, **CFG))
    hosts, hp, sh = sim.hosts, sim.hp, sim.sh
    wend = jnp.int64(0)  # every pending start event is at >= 1s

    def assert_identity(out, label):
        for (path, a), (_, b) in zip(
                jax.tree_util.tree_flatten_with_path(hosts)[0],
                jax.tree_util.tree_flatten_with_path(out)[0]):
            assert np.array_equal(np.asarray(a), np.asarray(b)), (
                f"{label}: leaf {jax.tree_util.keystr(path)} mutated "
                "by an all-idle step")

    assert_identity(step_all_hosts(hosts, hp, sh, wend, sim.cfg),
                    "dense")
    out, rung = step_window_pass(hosts, hp, sh, wend, sim.cfg)
    assert int(rung) == 0  # 0 ready -> smallest rung
    assert_identity(out, "sparse")


def test_event_batch_bit_identical():
    """Draining up to B consecutive due events per gathered host in one
    sparse pass (EngineConfig.event_batch) is a pass-schedule change
    only — per-host (time, seq) order is preserved — so stats must be
    bit-identical to the one-event-per-pass engine."""
    sim1 = Simulation(_skewed_scen(), engine_cfg=EngineConfig(
        num_hosts=8, active_block=3, event_batch=1, **CFG))
    simB = Simulation(_skewed_scen(), engine_cfg=EngineConfig(
        num_hosts=8, active_block=3, event_batch=8, **CFG))
    r1, rB = sim1.run(), simB.run()
    assert np.array_equal(r1.stats, rB.stats)
    assert r1.windows == rB.windows
    # batching may only LOWER the pass count
    assert (rB.cost_model()["passes_total"] <=
            r1.cost_model()["passes_total"])


def test_exchange_sort_compaction_bit_identical():
    """The exchange's sort compaction (EngineConfig.exsortcap) is a
    sort-input change only: a stable sort of the compacted survivor
    list equals the full stable sort filtered to survivors, so stats
    must match bit for bit. A tiny cap forces BOTH branches over the
    run (small windows compact, burst windows fall back)."""
    full = _run(_skewed_scen(), 0)
    sim = Simulation(_skewed_scen(), engine_cfg=EngineConfig(
        num_hosts=8, active_block=0, exsortcap=16, **CFG))
    compact = sim.run()
    assert np.array_equal(full.stats, compact.stats)
    assert full.windows == compact.windows
    # tiny dstcap exercises BOTH dest-merge branches too (windows with
    # <= 2 receiving hosts merge compacted, busier ones fall back)
    sim2 = Simulation(_skewed_scen(), engine_cfg=EngineConfig(
        num_hosts=8, active_block=0, exsortcap=16, dstcap=2, **CFG))
    compact2 = sim2.run()
    assert np.array_equal(full.stats, compact2.stats)


def test_compaction_sharded_matches_dense_single():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    mesh = make_mesh(8)
    single = Simulation(phold_scenario(n=16, stop=5)).run()
    scen = phold_scenario(n=16, stop=5)
    sim = Simulation(scen)
    sim.cfg = dataclasses.replace(sim.cfg, active_block=2)
    sharded = sim.run(mesh=mesh)
    assert np.array_equal(single.stats, sharded.stats)
    assert single.windows == sharded.windows
