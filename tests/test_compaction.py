"""Active-set compaction (EngineConfig.active_block) — the TPU-native
analogue of the reference's host-steal load balancing
(/root/reference/src/main/core/scheduler/shd-scheduler-policy-host-steal.c:
163-191): a lockstep pass steps only the ready hosts instead of paying
a full all-hosts pass per busiest-host event.

The contract under test: compaction changes WHICH rows a pass touches,
never the per-host (time, seq) execution order — so every run must be
bit-identical to the dense engine, including under sharding and in the
differential harness.
"""

import dataclasses

import numpy as np
import jax
import pytest

from shadow_tpu.core.config import HostSpec, ProcessSpec, Scenario
from shadow_tpu.engine.sim import Simulation
from shadow_tpu.engine.state import EngineConfig
from shadow_tpu.engine.pyengine import PyEngine
from shadow_tpu.parallel.shard import make_mesh

from test_phold import phold_scenario
from test_tcp import poi_topology


def _skewed_scen(stop=40):
    """One busy server, many mostly-idle clients — the lockstep-skew
    shape compaction exists for."""
    return Scenario(
        stop_time=stop * 10**9,
        topology_graphml=poi_topology(),
        hosts=[
            HostSpec(id="server", processes=[
                ProcessSpec(plugin="bulkserver", start_time=10**9,
                            arguments="port=80")]),
            HostSpec(id="client", quantity=7, processes=[
                ProcessSpec(plugin="bulk", start_time=2 * 10**9,
                            arguments="peer=server port=80 size=150000 "
                                      "count=2 pause=3s")]),
        ],
    )


CFG = dict(qcap=32, scap=12, obcap=16, incap=24, txqcap=12,
           chunk_windows=8)


def _run(scen, block, mesh=None):
    sim = Simulation(scen, engine_cfg=EngineConfig(
        num_hosts=8, active_block=block, **CFG))
    return sim.run(mesh=mesh)


def test_compaction_bit_identical_dense_vs_sparse():
    dense = _run(_skewed_scen(), 0)
    sparse = _run(_skewed_scen(), 3)      # < busy-host count: exercises
    # both the K-cap (more ready than block) and the dummy-slot path
    assert np.array_equal(dense.stats, sparse.stats)
    assert dense.windows == sparse.windows


def test_compaction_block_exceeds_hosts():
    """block >= H degenerates gracefully (K clamped to H)."""
    dense = _run(_skewed_scen(stop=20), 0)
    sparse = _run(_skewed_scen(stop=20), 64)
    assert np.array_equal(dense.stats, sparse.stats)


def test_compaction_differential():
    """The differential harness holds with compaction on: the compiled
    engine with active-set gathering still matches the heap engine bit
    for bit."""
    from test_differential import TCP_COMPARE

    cfg = EngineConfig(num_hosts=8, active_block=4, **CFG)
    jax_stats = Simulation(_skewed_scen(), engine_cfg=cfg).run().stats
    py_stats = PyEngine(Simulation(_skewed_scen(), engine_cfg=cfg)).run()
    for st in TCP_COMPARE:
        assert np.array_equal(jax_stats[:, st], py_stats[:, st]), st


def test_idle_step_identity():
    """Pin the invariant compaction's exactness rests on: a not-ready
    row's step is the IDENTITY (engine.window.step_window_pass
    docstring). Dummy gather slots duplicate a not-ready host, so a
    handler that mutated state before its ready gate (e.g. an
    unconditional rng_ctr bump) would corrupt state at scale in ways
    only end-to-end equality tests could catch — this pins it at the
    unit level: stepping a host set whose every event lies past the
    window bound must leave every array bit-identical, dense and
    sparse alike."""
    import jax.numpy as jnp
    from shadow_tpu.engine.window import step_all_hosts, step_window_pass

    sim = Simulation(_skewed_scen(), engine_cfg=EngineConfig(
        num_hosts=8, active_block=3, **CFG))
    hosts, hp, sh = sim.hosts, sim.hp, sim.sh
    wend = jnp.int64(0)  # every pending start event is at >= 1s

    def assert_identity(out, label):
        for (path, a), (_, b) in zip(
                jax.tree_util.tree_flatten_with_path(hosts)[0],
                jax.tree_util.tree_flatten_with_path(out)[0]):
            assert np.array_equal(np.asarray(a), np.asarray(b)), (
                f"{label}: leaf {jax.tree_util.keystr(path)} mutated "
                "by an all-idle step")

    assert_identity(step_all_hosts(hosts, hp, sh, wend, sim.cfg),
                    "dense")
    out, rung = step_window_pass(hosts, hp, sh, wend, sim.cfg)
    assert int(rung) == 0  # 0 ready -> smallest rung
    assert_identity(out, "sparse")


def test_event_batch_bit_identical():
    """Draining up to B consecutive due events per gathered host in one
    sparse pass (EngineConfig.event_batch) is a pass-schedule change
    only — per-host (time, seq) order is preserved — so stats must be
    bit-identical to the one-event-per-pass engine."""
    sim1 = Simulation(_skewed_scen(), engine_cfg=EngineConfig(
        num_hosts=8, active_block=3, event_batch=1, **CFG))
    simB = Simulation(_skewed_scen(), engine_cfg=EngineConfig(
        num_hosts=8, active_block=3, event_batch=8, **CFG))
    r1, rB = sim1.run(), simB.run()
    assert np.array_equal(r1.stats, rB.stats)
    assert r1.windows == rB.windows
    # batching may only LOWER the pass count
    assert (rB.cost_model()["passes_total"] <=
            r1.cost_model()["passes_total"])


def test_exchange_sort_compaction_bit_identical():
    """The exchange's sort compaction (EngineConfig.exsortcap) is a
    sort-input change only: a stable sort of the compacted survivor
    list equals the full stable sort filtered to survivors, so stats
    must match bit for bit. A tiny cap forces BOTH branches over the
    run (small windows compact, burst windows fall back)."""
    full = _run(_skewed_scen(), 0)
    sim = Simulation(_skewed_scen(), engine_cfg=EngineConfig(
        num_hosts=8, active_block=0, exsortcap=16, **CFG))
    compact = sim.run()
    assert np.array_equal(full.stats, compact.stats)
    assert full.windows == compact.windows
    # tiny dstcap exercises BOTH dest-merge branches too (windows with
    # <= 2 receiving hosts merge compacted, busier ones fall back)
    sim2 = Simulation(_skewed_scen(), engine_cfg=EngineConfig(
        num_hosts=8, active_block=0, exsortcap=16, dstcap=2, **CFG))
    compact2 = sim2.run()
    assert np.array_equal(full.stats, compact2.stats)


def test_compaction_sharded_matches_dense_single():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    mesh = make_mesh(8)
    single = Simulation(phold_scenario(n=16, stop=5)).run()
    scen = phold_scenario(n=16, stop=5)
    sim = Simulation(scen)
    sim.cfg = dataclasses.replace(sim.cfg, active_block=2)
    sharded = sim.run(mesh=mesh)
    assert np.array_equal(single.stats, sharded.stats)
    assert single.windows == sharded.windows


def test_hot_split_gating_bit_identical(tmp_path):
    """The hot/cold split's exactness proof: the gated drain (default
    hot_split=1, config-gated COLD_WHEN columns excluded from every
    gather/carry) produces byte-identical digest chains to the
    full-tree drain (hot_split=0, the pre-split engine) — on a no-TCP
    scenario (the 38-column `no_tcp` gate active) AND on a TCP
    scenario (socket table pinned hot, boundary columns still cold)."""
    from test_checkpoint import scen as phold_scen, CFG as PH_CFG

    def chain(name, scenario, cfg):
        path = str(tmp_path / f"{name}.jsonl")
        Simulation(scenario, engine_cfg=cfg).run(digest=path,
                                                 digest_every=8)
        return open(path, "rb").read()

    # UDP/phold tier: cpu_model off, no hosted, no tgen, no TCP —
    # every COLD_WHEN guard active, drain working set 29 columns
    base = dict(num_hosts=8, **PH_CFG)
    a = chain("ph_gated", phold_scen(), EngineConfig(**base))
    b = chain("ph_full", phold_scen(),
              EngineConfig(hot_split=0, **base))
    assert a == b, "no-TCP gated drain diverged from full-tree drain"

    # TCP tier: the skewed bulk shape (the lockstep-skew scenario the
    # compaction ladder exists for), socket table hot
    tcp = dict(num_hosts=8, **CFG)
    a = chain("tcp_gated", _skewed_scen(), EngineConfig(**tcp))
    b = chain("tcp_full", _skewed_scen(),
              EngineConfig(hot_split=0, **tcp))
    assert a == b, "TCP gated drain diverged from full-tree drain"


def test_hot_fields_gating_per_config():
    """hot_fields(cfg) activates exactly the declared COLD_WHEN gates
    for a config, and hot_split=0 restores the full pytree."""
    import dataclasses as dc

    from shadow_tpu.engine.state import (COLD_FIELDS, HOT_FIELDS,
                                         Hosts, hot_fields)

    # phold-style: no TCP, no hosted, no tgen, single process
    udp = EngineConfig(num_hosts=4, app_kinds=(0, 3), uses_tcp=False)
    hot = hot_fields(udp)
    assert "sk_sack_s" not in hot and "sk_cwnd" not in hot
    assert "sk_proc" not in hot          # single-process gate
    assert "cpu_avail" not in hot and "hw_cnt" not in hot
    assert "tgen_sync" not in hot
    # UDP-touched socket columns stay hot
    for f in ("sk_used", "sk_proto", "sk_lport", "sk_snd_end",
              "sk_rcv_nxt", "sk_timer_gen"):
        assert f in hot, f
    assert len(hot) == 29

    # multi-process UDP: wake routing reads sk_proc — pinned hot
    assert "sk_proc" in hot_fields(dc.replace(udp, procs_per_host=2))

    # TCP tier (tgen absent): socket table hot, boundary gates active
    tcp = EngineConfig(num_hosts=4, app_kinds=(0, 9, 10),
                       uses_tcp=True)
    hot = hot_fields(tcp)
    assert "sk_sack_s" in hot and "sk_cwnd" in hot
    assert "cpu_avail" not in hot and "tgen_sync" not in hot

    # hosted / cpu-model / unknown app set pin their columns hot
    assert "hw_cnt" in hot_fields(dc.replace(udp, hostedcap=32))
    assert "cpu_avail" in hot_fields(dc.replace(udp, cpu_model=True))
    assert "tgen_sync" in hot_fields(EngineConfig(num_hosts=4))

    # the escape hatch carries everything, static cold included
    allf = hot_fields(EngineConfig(num_hosts=4, hot_split=0))
    assert set(allf) == set(Hosts.__dataclass_fields__)
    assert set(HOT_FIELDS) | COLD_FIELDS == set(allf)
