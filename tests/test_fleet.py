"""Fleet supervisor tests: queue durability, scheduler machinery, and
the sweep-level interrupted ≡ uninterrupted proof.

Two tiers:

- the FAST tests drive the queue/claims/fold/backoff/quarantine/
  admission/watchdog/preemption machinery with throwaway ``cmd``-mode
  children (plain ``python -c``) — no jax, no compiles, seconds total;
- the SLOW tests (``-m slow``) put real simulator runs under the
  scheduler: scheduling-order independence (digest chains must not
  depend on worker count or queue order) and the acceptance chaos
  sweep (ISSUE 7) — a ≥12-scenario sweep (modeled + fault-schedule +
  hosted + one planted poison config) SIGKILLed at random instants
  (workers AND scheduler) must complete on restart with every run's
  digest chain byte-identical to an uninterrupted reference sweep,
  the poison quarantined with its crash-cause journal, and the queue
  never stalled. Each child CLI pays the cold XLA compile on the CPU
  dev box — drive these in the background, never inside tier-1.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent

sys.path.insert(0, str(REPO))

from shadow_tpu.engine.supervisor import (      # noqa: E402
    EXIT_PREEMPTED, CrashLog, backoff_delay, classify_exit)
from shadow_tpu.fleet.queue import Queue, make_spec  # noqa: E402
from shadow_tpu.fleet.scheduler import (        # noqa: E402
    EXIT_DRAINED, EXIT_QUARANTINED, Scheduler, SchedulerLockError)


def _env(extra=None):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(REPO)
    env.update(extra or {})
    return env


def quiet_log(_msg):
    pass


def sleeper_cmd(seconds, marker=None):
    """A fake run: optionally touch `marker`, sleep, exit 0."""
    body = f"import time; time.sleep({seconds})"
    if marker:
        body = (f"open({str(marker)!r}, 'a').write('x'); " + body)
    return [sys.executable, "-c", body]


# ---------------------------------------------------------------------
# queue durability
# ---------------------------------------------------------------------

def test_journal_fold_and_torn_line(tmp_path):
    """The queue state is a fold over the fsync'd journal; a torn
    final line (writer SIGKILLed mid-append) is skipped, never a
    crash, and every prior record survives."""
    q = Queue(str(tmp_path / "q")).ensure()
    q.submit(make_spec("a", cmd=["true"]))
    q.submit(make_spec("b", cmd=["true"]))
    q.append("start", id="a", attempt=1, pid=1234)
    q.append("exit", id="a", attempt=1, rc=-9, kind="crash",
             cause="killed by SIGKILL")
    q.append("start", id="a", attempt=2, pid=1235)
    q.append("exit", id="a", attempt=2, rc=0, kind="done",
             cause="completed")
    with open(q.journal, "a") as f:
        f.write('{"op": "start", "id": "b", "att')   # torn append
    st = q.fold()
    assert st["a"].state == "done" and st["a"].crashes == 1
    assert st["a"].started == 2
    assert st["b"].state == "queued" and st["b"].started == 0
    # records for unknown runs and unknown ops are skipped loudly,
    # not fatal (an older reader on a newer journal)
    q.append("exit", id="ghost", rc=0, kind="done", cause="x")
    q.append("frobnicate", id="a")
    assert q.fold()["a"].state == "done"


def test_duplicate_submit_refused(tmp_path):
    q = Queue(str(tmp_path / "q")).ensure()
    q.submit(make_spec("a", cmd=["true"]))
    with pytest.raises(ValueError, match="already queued"):
        q.submit(make_spec("a", cmd=["true"]))


def test_spec_validation():
    with pytest.raises(ValueError, match="path-safe"):
        make_spec("../escape", cmd=["true"])
    with pytest.raises(ValueError, match="exactly one"):
        make_spec("x", config="a.xml", cmd=["true"])
    with pytest.raises(ValueError, match="exactly one"):
        make_spec("x")


def test_claim_atomicity_and_release(tmp_path):
    q = Queue(str(tmp_path / "q")).ensure()
    assert q.claim("r1", {"pid": 1}) is True
    assert q.claim("r1", {"pid": 2}) is False     # O_EXCL holds
    assert q.read_claim("r1")["pid"] == 1
    assert q.claimed_ids() == ["r1"]
    q.release("r1")
    assert q.read_claim("r1") is None
    assert q.claim("r1", {"pid": 3}) is True


def test_run_store_namespacing(tmp_path):
    """Per-run checkpoint stores can never collide or escape the
    runs root (engine.checkpoint.run_store_base)."""
    from shadow_tpu.engine.checkpoint import run_store_base
    q = Queue(str(tmp_path / "q"))
    a = q.store_base("run-a")
    b = q.store_base("run-b")
    assert a != b and a.startswith(q.runs_dir)
    for bad in ("../up", "a/b", "", ".hidden", "x" * 101):
        with pytest.raises(ValueError):
            run_store_base(str(tmp_path), bad)


def test_crash_log_atomic_and_torn_tolerant(tmp_path):
    """Satellite: crash-cause journals are fsync'd appends and
    torn-line-tolerant reads (the obs.ledger pattern) — a kill
    mid-append can no longer tear the journal the fleet reads."""
    log = CrashLog(str(tmp_path / "crash.jsonl"))
    log.append({"attempt": 1, "exit_status": -9,
                "cause": "killed by SIGKILL"})
    log.append({"attempt": 2, "exit_status": 0, "cause": "completed"})
    with open(log.path, "a") as f:
        f.write('{"attempt": 3, "exit_st')          # torn
    recs = log.read()
    assert [r["attempt"] for r in recs] == [1, 2]
    assert recs[0]["cause"] == "killed by SIGKILL"


def test_backoff_and_classify():
    assert backoff_delay(1.0, 1) == 1.0
    assert backoff_delay(1.0, 3) == 4.0
    assert backoff_delay(1.0, 30, cap_s=60.0) == 60.0
    assert classify_exit(0) == "completed"
    assert classify_exit(-signal.SIGKILL) == "killed by SIGKILL"
    assert classify_exit(3) == "exited status=3"


# ---------------------------------------------------------------------
# scheduler machinery (cmd-mode children: no jax, no compiles)
# ---------------------------------------------------------------------

def test_scheduler_drains_and_quarantines_poison(tmp_path):
    """A deterministic crasher is retried with backoff, then parked
    in quarantine with its crash-cause journal — and the rest of the
    queue drains to completion around it."""
    q = Queue(str(tmp_path / "q")).ensure()
    for i in range(3):
        q.submit(make_spec(
            f"ok{i}", cmd=sleeper_cmd(0.1, tmp_path / f"done{i}")))
    q.submit(make_spec("poison",
                       cmd=[sys.executable, "-c", "raise SystemExit(9)"],
                       max_retries=2))
    rc = Scheduler(q, workers=2, backoff_s=0.05, backoff_cap_s=0.1,
                   log=quiet_log).run()
    assert rc == EXIT_QUARANTINED
    st = q.fold()
    assert all(st[f"ok{i}"].state == "done" for i in range(3))
    assert all((tmp_path / f"done{i}").exists() for i in range(3))
    assert st["poison"].state == "quarantined"
    assert st["poison"].crashes == 3          # 1 + max_retries
    assert "crashes" in st["poison"].quarantine_cause
    recs = CrashLog(q.crash_log_path("poison")).read()
    assert len(recs) == 3
    assert all(r["cause"] == "exited status=9" for r in recs)


def test_scheduler_spawn_failure_is_a_run_crash(tmp_path):
    """An unspawnable child (bad executable) is a crash of THAT run —
    retried, then quarantined — never a scheduler death: the rest of
    the queue keeps draining (the isolation guarantee)."""
    q = Queue(str(tmp_path / "q")).ensure()
    q.submit(make_spec("ghost", cmd=["/no/such/executable-xyz"],
                       max_retries=1))
    q.submit(make_spec("ok", cmd=sleeper_cmd(0.1, tmp_path / "done")))
    rc = Scheduler(q, workers=2, backoff_s=0.05, log=quiet_log).run()
    assert rc == EXIT_QUARANTINED
    st = q.fold()
    assert st["ok"].state == "done"
    assert st["ghost"].state == "quarantined"
    assert st["ghost"].crashes == 2
    # the exec failure is journaled per attempt (via the claim-gate
    # wrapper's crash exit, or _handle_spawn_failure for a Popen-time
    # OSError) and the cause is in the run's log/crash journal
    recs = CrashLog(q.crash_log_path("ghost")).read()
    assert len(recs) == 2, recs
    log_text = Path(q.log_path("ghost")).read_text(errors="replace")
    assert ("No such file" in log_text
            or any("spawn failed" in r["cause"] for r in recs))
    assert q.claimed_ids() == []     # no claim leaked


def test_scheduler_spontaneous_75_is_capped(tmp_path):
    """A child that always exits 75 (EX_TEMPFAIL) without any
    scheduler preemption is requeued with backoff and CAPPED — it
    must not livelock the drain loop."""
    q = Queue(str(tmp_path / "q")).ensure()
    q.submit(make_spec("tempfail",
                       cmd=[sys.executable, "-c",
                            "raise SystemExit(75)"]))
    rc = Scheduler(q, workers=1, backoff_s=0.02, backoff_cap_s=0.05,
                   max_spont_preempts=2, log=quiet_log).run()
    assert rc == EXIT_QUARANTINED
    st = q.fold()["tempfail"]
    assert st.state == "quarantined"
    assert st.preemptions == 3           # cap + the final one
    assert st.crashes == 0               # never miscounted as crashes
    assert "livelock" in st.quarantine_cause


def test_to_xml_refuses_inexpressible_bandwidth():
    """Sub-KiB / non-KiB-multiple bandwidths cannot round-trip
    through the whole-KiB XML schema — to_xml must fail loud instead
    of silently simulating different bandwidths in the fleet's XML
    copy."""
    from shadow_tpu.core.config import HostSpec, Scenario
    scen = Scenario(stop_time=10**9, topology_path="t.graphml",
                    hosts=[HostSpec(id="a", bandwidth_down=1500)])
    with pytest.raises(ValueError, match="whole-KiB"):
        scen.to_xml()
    scen.hosts[0].bandwidth_down = 2048
    assert 'bandwidthdown="2"' in scen.to_xml()


def test_to_xml_roundtrips_cpu_model():
    """Scenario-level CPU-model overrides must survive the XML copy
    the fleet queue runs (silently reverting to defaults would make
    the fleet run simulate a different machine); the CLI only
    overrides them when its flags depart from their defaults."""
    from shadow_tpu.core.config import HostSpec, Scenario, load_xml
    scen = Scenario(stop_time=10**9, topology_path="t.graphml",
                    hosts=[HostSpec(id="a")],
                    cpu_event_cost_ns=50_000, cpu_precision_ns=500,
                    cpu_threshold_ns=2_000_000,
                    cpu_raw_frequency_khz=1_000_000)
    back = load_xml(scen.to_xml())
    assert back.cpu_event_cost_ns == 50_000
    assert back.cpu_precision_ns == 500
    assert back.cpu_threshold_ns == 2_000_000
    assert back.cpu_raw_frequency_khz == 1_000_000
    # defaults stay implicit: a default scenario emits none of the
    # extension attributes (reference-style files stay reference-style)
    plain = Scenario(stop_time=10**9, topology_path="t.graphml",
                     hosts=[HostSpec(id="a")])
    assert "cpueventcostns" not in plain.to_xml()


def test_scheduler_usage_error_quarantines_immediately(tmp_path):
    """rc=2 is a deterministic usage error: retrying reproduces the
    same message max_retries times over — quarantine on sight (the
    engine.supervisor rule, fleet-side)."""
    q = Queue(str(tmp_path / "q")).ensure()
    q.submit(make_spec("usage",
                       cmd=[sys.executable, "-c", "raise SystemExit(2)"],
                       max_retries=5))
    rc = Scheduler(q, workers=1, backoff_s=0.05, log=quiet_log).run()
    assert rc == EXIT_QUARANTINED
    st = q.fold()["usage"]
    assert st.state == "quarantined" and st.crashes == 1
    assert "usage error" in st.quarantine_cause


def test_scheduler_admission_bounds_concurrency(tmp_path):
    """Admission control: concurrent host-weight never exceeds the
    budget, an oversized run degrades to 'queued' while the box is
    busy — and still runs (alone) once it is free."""
    q = Queue(str(tmp_path / "q")).ensure()
    trace = tmp_path / "trace"

    def tracked(rid, hosts):
        body = (f"import time; f=open({str(trace)!r},'a'); "
                f"f.write('+{hosts}\\n'); f.flush(); time.sleep(0.4); "
                f"f.write('-{hosts}\\n'); f.flush()")
        q.submit(make_spec(rid, cmd=[sys.executable, "-c", body],
                           hosts=hosts))

    tracked("small1", 4)
    tracked("small2", 4)
    tracked("oversized", 50)     # alone exceeds the budget
    tracked("small3", 4)
    rc = Scheduler(q, workers=3, max_hosts=10, backoff_s=0.05,
                   log=quiet_log).run()
    assert rc == EXIT_DRAINED
    assert all(s.state == "done" for s in q.fold().values())
    load = peak = 0
    peaks = []
    for line in trace.read_text().splitlines():
        load += int(line) if line[0] == "+" else int(line)
        peak = max(peak, load)
        peaks.append(load)
    # two smalls may overlap (8 <= 10); the oversized one must have
    # run with nothing else on the box
    assert peak <= 50, peaks
    lines = trace.read_text().splitlines()
    start50 = lines.index("+50")
    assert sum(int(l) for l in lines[:start50]) == 0, (
        "oversized run started while something else was running")
    assert "-50" == lines[start50 + 1], (
        "another run started while the oversized one was running")


def test_scheduler_watchdog_kills_hung_run(tmp_path):
    """A run with no progress signals is diagnosed hung and
    SIGKILLed instead of wedging its slot forever."""
    q = Queue(str(tmp_path / "q")).ensure()
    q.submit(make_spec("hung", cmd=sleeper_cmd(60), max_retries=0))
    q.submit(make_spec("ok", cmd=sleeper_cmd(0.1, tmp_path / "done")))
    t0 = time.time()
    rc = Scheduler(q, workers=2, hang_timeout_s=1.0, backoff_s=0.05,
                   log=quiet_log).run()
    assert time.time() - t0 < 30, "watchdog never fired"
    assert rc == EXIT_QUARANTINED
    st = q.fold()
    assert st["ok"].state == "done"
    assert st["hung"].state == "quarantined"
    assert "hung" in st["hung"].last_cause
    recs = CrashLog(q.crash_log_path("hung")).read()
    assert any("watchdog" in r["cause"] for r in recs)


def test_scheduler_preempt_requeues_and_resumes(tmp_path):
    """SIGTERM-driven preemption: running children are stopped, their
    runs requeued (never counted as crashes), the scheduler exits 75
    — and a fresh scheduler completes the sweep."""
    q = Queue(str(tmp_path / "q")).ensure()
    marker = tmp_path / "attempt2"
    # first attempt sleeps forever; after the marker exists (second
    # attempt) it completes instantly — distinguishes re-dispatch
    body = (f"import os, time, sys; "
            f"sys.exit(0) if os.path.exists({str(marker)!r}) else None; "
            f"open({str(marker)!r}, 'w').write('x'); time.sleep(60)")
    q.submit(make_spec("r", cmd=[sys.executable, "-c", body]))
    sched = Scheduler(q, workers=1, grace_s=2.0, backoff_s=0.05,
                      log=quiet_log)
    timer = threading.Timer(1.0, sched.request_preempt)
    timer.start()
    rc = sched.run()
    timer.cancel()
    assert rc == EXIT_PREEMPTED
    st = q.fold()["r"]
    assert st.state == "queued" and st.crashes == 0
    assert st.preemptions == 1
    rc = Scheduler(q, workers=1, backoff_s=0.05, log=quiet_log).run()
    assert rc == EXIT_DRAINED
    assert q.fold()["r"].state == "done"


def test_scheduler_lock_excludes_second_scheduler(tmp_path):
    q = Queue(str(tmp_path / "q")).ensure()
    q.submit(make_spec("r", cmd=["true"]))
    s1 = Scheduler(q, log=quiet_log)
    s1._acquire_lock()            # we are the live "first" scheduler
    try:
        with pytest.raises(SchedulerLockError, match="one scheduler"):
            Scheduler(q, log=quiet_log).run()
    finally:
        s1._release_lock()


def test_scheduler_sigkill_recovery_cli(tmp_path):
    """Crash-safety of the scheduler itself, end to end through the
    CLI: SIGKILL `fleet run` mid-sweep, restart it, and the sweep
    completes — in-flight runs are reclaimed (NOT counted as
    crashes) via their stale claims, orphans killed."""
    qdir = tmp_path / "q"

    def fleet(*args, **kw):
        return subprocess.run(
            [sys.executable, "-m", "shadow_tpu", "fleet"] + list(args),
            env=_env(), capture_output=True, text=True, **kw)

    for i in range(3):
        r = fleet("submit", str(qdir), "--cmd", "--id", f"s{i}", "--",
                  sys.executable, "-c",
                  "import time, sys; time.sleep(1.5); "
                  f"open({str(tmp_path / f'done{i}')!r}, 'w')")
        assert r.returncode == 0, r.stderr
    p = subprocess.Popen(
        [sys.executable, "-m", "shadow_tpu", "fleet", "run",
         str(qdir), "--workers", "1", "--backoff", "0.05"],
        env=_env(), stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    q = Queue(str(qdir))
    deadline = time.time() + 60
    # wait for a JOURNALED start (a claim alone can precede it): the
    # kill must interrupt a run the journal believes is running for
    # the restart to exercise the reclaim path
    while time.time() < deadline and not any(
            st.state == "running" for st in q.fold().values()):
        time.sleep(0.05)
    assert any(st.state == "running" for st in q.fold().values()), (
        "no run ever started")
    os.kill(p.pid, signal.SIGKILL)
    p.wait(timeout=30)
    r = fleet("run", str(qdir), "--workers", "2", "--backoff", "0.05",
              timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    st = q.fold()
    assert all(st[f"s{i}"].state == "done" for i in range(3))
    assert all(st[f"s{i}"].crashes == 0 for i in range(3)), (
        "a reclaimed in-flight run was miscounted as a crash")
    assert sum(st[f"s{i}"].reclaims for i in range(3)) >= 1
    assert all((tmp_path / f"done{i}").exists() for i in range(3))


def test_fleet_cli_status_and_xml_roundtrip(tmp_path):
    """submit parses host counts from the XML for admission weights;
    status folds; Scenario.to_xml round-trips through load_xml (the
    fleet's self-contained-queue contract)."""
    from shadow_tpu.core.config import load_xml
    from shadow_tpu.fleet.cli import _count_hosts, main as fleet_main
    xml = tmp_path / "scen.xml"
    xml.write_text("""<shadow stoptime="6">
  <topology path="nope.graphml"/>
  <host id="a" quantity="5"><process plugin="phold" starttime="1"/></host>
  <host id="b"><process plugin="phold" starttime="1"/></host>
</shadow>""")
    assert _count_hosts(str(xml)) == 6
    qdir = str(tmp_path / "q")
    assert fleet_main(["submit", qdir, str(xml), "--",
                       "--seed", "9"]) == 0
    st = Queue(qdir).fold()["scen"]
    assert st.spec["hosts"] == 6
    assert st.spec["args"] == ["--seed", "9"]
    # the queue stored its own ABSOLUTE copy — the submitted file can
    # vanish, and a later `fleet run` may start from a different cwd
    assert st.spec["config"] != str(xml)
    assert os.path.isabs(st.spec["config"])
    assert os.path.exists(st.spec["config"])
    assert fleet_main(["status", qdir]) == 0
    # cmd-mode refuses the managed durability/perf flags instead of
    # silently dropping them
    with pytest.raises(SystemExit):
        fleet_main(["submit", qdir, "--cmd", "--perf", "--", "true"])
    # ...and config-mode refuses managed flags smuggled into the `--`
    # tail (the worker's appended args would silently override them)
    with pytest.raises(SystemExit):
        fleet_main(["submit", qdir, str(xml), "--id", "clash", "--",
                    "--digest", "/my/chain.jsonl"])

    # to_xml round-trip on a representative scenario (faults, args,
    # quantities, buffers)
    scen = load_xml(str(xml))
    scen2 = load_xml(scen.to_xml())
    assert scen2.stop_time == scen.stop_time
    assert [(h.id, h.quantity) for h in scen2.hosts] == [
        ("a", 5), ("b", 1)]
    from shadow_tpu.core.config import FaultSpec
    scen.faults.append(FaultSpec(kind="loss", at=2 * 10**9,
                                 until=4 * 10**9, rate=0.25,
                                 src="a", dst="b"))
    scen.hosts[0].processes[0].arguments = "port=9000 mean=300ms"
    scen.hosts[0].socket_recv_buffer = 4096
    scen3 = load_xml(scen.to_xml())
    f = scen3.faults[0]
    assert (f.kind, f.at, f.until, f.rate, f.src, f.dst) == (
        "loss", 2 * 10**9, 4 * 10**9, 0.25, "a", "b")
    assert scen3.hosts[0].processes[0].arguments == "port=9000 mean=300ms"
    assert scen3.hosts[0].socket_recv_buffer == 4096


# ---------------------------------------------------------------------
# slow tier: real simulator runs under the scheduler
# ---------------------------------------------------------------------

TOPO = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="latency" attr.type="double" for="edge" id="d7"/>
  <key attr.name="packetloss" attr.type="double" for="edge" id="d9"/>
  <key attr.name="packetloss" attr.type="double" for="node" id="d0"/>
  <key attr.name="bandwidthup" attr.type="int" for="node" id="d4"/>
  <key attr.name="bandwidthdown" attr.type="int" for="node" id="d3"/>
  <graph edgedefault="undirected">
    <node id="poi"><data key="d0">0.0</data>
      <data key="d3">10240</data><data key="d4">10240</data></node>
    <edge source="poi" target="poi"><data key="d7">25.0</data>
      <data key="d9">0.0</data></edge>
  </graph>
</graphml>"""

PHOLD_XML = f"""<shadow stoptime="6">
  <topology><![CDATA[{TOPO}]]></topology>
  <host id="node" quantity="8">
    <process plugin="phold" starttime="1"
             arguments="port=9000 mean=300ms size=64 init=1"/>
  </host>
</shadow>"""

PHOLD_CAPS = "qcap=16,scap=4,obcap=8,incap=16,chunk=8"

UPLOADER_SRC = """\
import socket, time
s = socket.create_connection(("server", 8080))
for i in range(40):
    s.send(b"x" * 4000)
    time.sleep(0.25)
s.close()
print("done")
"""

HOSTED_CAPS = "qcap=32,scap=8,obcap=16,incap=32,hostedcap=16"

FAULT_ARGS = ["--fault",
              "kind=loss,at=2s,until=4s,rate=0.3,src=node1,dst=node2",
              "--fault",
              "kind=latency,at=4.5s,until=5.5s,extra=10ms,"
              "src=node1,dst=node2"]


def hosted_xml(tmp_path, tag):
    script = tmp_path / "upload.py"
    if not script.exists():
        script.write_text(UPLOADER_SRC)
    out = tmp_path / f"upload-{tag}.out"
    xml = tmp_path / f"hosted-{tag}.xml"
    xml.write_text(f"""<shadow stoptime="14">
  <topology><![CDATA[{TOPO}]]></topology>
  <host id="server">
    <process plugin="bulkserver" starttime="1" arguments="port=8080"/>
  </host>
  <host id="client">
    <process plugin="hosted:shim" starttime="2"
             arguments="out={out} cmd={sys.executable} {script}"/>
  </host>
</shadow>""")
    return xml, out


def run_cli(args, extra_env=None, timeout=900):
    p = subprocess.run(
        [sys.executable, "-m", "shadow_tpu"] + args,
        env=_env(extra_env), cwd=str(REPO),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        timeout=timeout)
    text = p.stdout.decode(errors="replace")
    assert p.returncode == 0, f"CLI rc={p.returncode}:\n{text[-4000:]}"
    return text


def sweep_scenarios(tmp_path, tag, n_modeled=3, n_fault=1, n_hosted=1):
    """(run_id, xml_path, extra_args, env) per scenario. `tag` keeps
    the hosted out= files of two sweeps distinct (digest chains carry
    no paths, so chains stay comparable)."""
    phold = tmp_path / "phold.xml"
    if not phold.exists():
        phold.write_text(PHOLD_XML)
    runs = []
    for i in range(n_modeled):
        runs.append((f"m{i}", phold,
                     ["--seed", str(7 + i),
                      "--engine-caps", PHOLD_CAPS], {}))
    for i in range(n_fault):
        runs.append((f"f{i}", phold,
                     ["--seed", str(7 + i), "--engine-caps",
                      PHOLD_CAPS] + FAULT_ARGS, {}))
    for i in range(n_hosted):
        xml, _out = hosted_xml(tmp_path, f"{tag}-{i}")
        runs.append((f"h{i}", xml,
                     ["--seed", str(7 + i),
                      "--engine-caps", HOSTED_CAPS], {}))
    return runs


def reference_chains(tmp_path, runs):
    """Uninterrupted single-CLI reference chain per scenario."""
    chains = {}
    for rid, xml, args, env in runs:
        dg = tmp_path / f"ref-{rid}.jsonl"
        run_cli([str(xml), "--digest", str(dg), "--digest-every", "8"]
                + args, extra_env=env)
        chains[rid] = dg.read_bytes()
        assert chains[rid], f"reference {rid} recorded nothing"
    return chains


def submit_sweep(qdir, runs, order=None, max_retries=5):
    q = Queue(str(qdir)).ensure()
    items = [runs[i] for i in order] if order else runs
    for rid, xml, args, env in items:
        q.submit(make_spec(rid, config=str(xml), args=list(args),
                           env=dict(env), checkpoint_every=1.0,
                           digest_every=8, max_retries=max_retries))
    return q


def assert_chains_match(q, runs, reference):
    sys.path.insert(0, str(REPO / "tools"))
    try:
        import divergence
    finally:
        sys.path.pop(0)
    for rid, _xml, _args, _env in runs:
        got = Path(q.digest_path(rid)).read_bytes()
        assert got == reference[rid], (
            f"run {rid}: sweep digest chain diverges from the "
            "uninterrupted reference (tools/divergence.py the two "
            "files)")
        # and the structured verdict agrees (exit 0)
        ref = Path(q.run_dir(rid)) / "_ref.jsonl"
        ref.write_bytes(reference[rid])
        assert divergence.main([str(ref), q.digest_path(rid)]) == 0


@pytest.mark.slow
def test_fleet_scheduling_order_independence(tmp_path):
    """The same submitted sweep, shuffled queue order and different
    worker counts, yields byte-identical per-run digest chains —
    scheduling must not leak into results."""
    runs = sweep_scenarios(tmp_path, "a", n_modeled=2, n_fault=1,
                           n_hosted=0)
    reference = reference_chains(tmp_path, runs)

    q1 = submit_sweep(tmp_path / "q1", runs)
    rc = Scheduler(q1, workers=1, backoff_s=0.1,
                   log=quiet_log).run()
    assert rc == EXIT_DRAINED
    assert_chains_match(q1, runs, reference)

    runs_b = sweep_scenarios(tmp_path, "b", n_modeled=2, n_fault=1,
                             n_hosted=0)
    q2 = submit_sweep(tmp_path / "q2", runs_b, order=[2, 0, 1])
    rc = Scheduler(q2, workers=2, backoff_s=0.1,
                   log=quiet_log).run()
    assert rc == EXIT_DRAINED
    assert_chains_match(q2, runs_b, reference)


def _fleet_run_proc(qdir, workers=2):
    # scheduler output to a FILE: an undrained PIPE would deadlock a
    # long chaos drain against the 64 KiB pipe buffer
    with open(str(qdir) + ".sched.log", "ab") as lf:
        return subprocess.Popen(
            [sys.executable, "-m", "shadow_tpu", "fleet", "run",
             str(qdir), "--workers", str(workers), "--backoff", "0.2",
             "--hang-timeout", "900"],
            env=_env(), cwd=str(REPO),
            stdout=lf, stderr=subprocess.STDOUT)


def _wait_any_progress(q, exclude, timeout=900):
    """Block until some claimed run (not in `exclude`) has digest
    records — a kill landing then is guaranteed mid-run."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        for rid in q.claimed_ids():
            if rid in exclude:
                continue
            try:
                if os.path.getsize(q.digest_path(rid)) > 0:
                    return rid
            except OSError:
                continue
        time.sleep(0.2)
    raise AssertionError("no claimed run ever made digest progress")


@pytest.mark.slow
def test_fleet_chaos_sweep_equivalence(tmp_path):
    """ISSUE 7 acceptance: a ≥12-scenario sweep (modeled +
    fault-schedule + hosted mix) under random worker and scheduler
    SIGKILLs completes after restarts with every run's digest chain
    byte-identical to an uninterrupted reference sweep; one planted
    always-crashing scenario ends quarantined after max retries with
    its crash-cause journaled, and the other runs all complete.

    ~12 child compiles + the reference sweep: background-only on the
    CPU dev box (SHADOW_TPU_FLEET_CHAOS_SMALL=1 shrinks it for
    iterating on the harness itself)."""
    import random
    rnd = random.Random(7)
    small = os.environ.get("SHADOW_TPU_FLEET_CHAOS_SMALL") == "1"
    runs = sweep_scenarios(
        tmp_path, "chaos",
        n_modeled=2 if small else 6,
        n_fault=1 if small else 3,
        n_hosted=1 if small else 2)
    reference = reference_chains(tmp_path, runs)

    qdir = tmp_path / "q"
    q = submit_sweep(qdir, runs, max_retries=5)
    # the planted poison: a deterministic crasher (the durability
    # CrashHook with no fire-once guard SIGKILLs it every attempt)
    phold = tmp_path / "phold.xml"
    q.submit(make_spec(
        "poison", config=str(phold),
        args=["--seed", "7", "--engine-caps", PHOLD_CAPS],
        env={"SHADOW_TPU_CRASH_SIM_NS": "2000000000"},
        checkpoint_every=1.0, digest_every=8, max_retries=1))

    kills = {"worker": 1 if small else 3,
             "scheduler": 1 if small else 2}
    proc = _fleet_run_proc(qdir)
    killed_pids = set()
    while True:
        rc = proc.poll()
        if rc is not None:
            states = q.fold()
            live = [s for s in states.values()
                    if s.state not in ("done", "quarantined")]
            if not live:
                break
            assert rc != 0, "scheduler claimed success with live runs"
            # scheduler died (we killed it): restart — the sweep must
            # resume exactly where it stopped
            proc = _fleet_run_proc(qdir)
            continue
        if kills["worker"] > 0:
            rid = _wait_any_progress(q, exclude={"poison"})
            claim = q.read_claim(rid) or {}
            pid = claim.get("pid")
            if pid and pid not in killed_pids:
                time.sleep(rnd.uniform(0.0, 2.0))
                try:
                    os.kill(int(pid), signal.SIGKILL)
                    killed_pids.add(pid)
                    kills["worker"] -= 1
                except OSError:
                    pass
            continue
        if kills["scheduler"] > 0:
            _wait_any_progress(q, exclude={"poison"})
            time.sleep(rnd.uniform(0.0, 2.0))
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait(timeout=30)
            kills["scheduler"] -= 1
            continue
        time.sleep(0.5)
    # final drain may end 3 (poison quarantined)
    states = q.fold()
    for rid, _xml, _args, _env in runs:
        assert states[rid].state == "done", (
            rid, states[rid].last_cause)
    assert states["poison"].state == "quarantined", (
        states["poison"].state, states["poison"].last_cause)
    assert states["poison"].crashes == 2      # 1 + max_retries
    recs = CrashLog(q.crash_log_path("poison")).read()
    assert recs and all("SIGKILL" in r["cause"] for r in recs), recs
    assert_chains_match(q, runs, reference)
    # hosted children really re-ran to completion
    for rid, xml, _args, _env in runs:
        if rid.startswith("h"):
            outs = list(tmp_path.glob(f"upload-chaos-*.out"))
            assert outs and all("done" in o.read_text()
                                for o in outs), outs
