"""Test configuration: run the engine on a virtual 8-device CPU mesh.

Must set platform flags before the first jax import anywhere in the
test process, mirroring how the driver validates multi-chip sharding
without real chips.
"""

import os

# Force the CPU mesh even when the ambient environment preselects a
# real accelerator platform (e.g. JAX_PLATFORMS=axon): the test suite
# validates sharding semantics on 8 virtual devices, not chip perf.
# Set SHADOW_TPU_TEST_PLATFORM to override (e.g. to run on real TPU).
_platform = os.environ.get("SHADOW_TPU_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# The env var alone is not enough here: the ambient axon TPU plugin
# overrides JAX_PLATFORMS during its entry-point initialization, so pin
# the platform through the config API as well (wins over the plugin).
import jax  # noqa: E402

jax.config.update("jax_platforms", _platform)

# NOTE: the persistent compilation cache (jax_compilation_cache_dir) is
# deliberately NOT enabled: this environment's XLA:CPU AOT loader
# rejects/mismatches its own cache entries (machine-feature drift), and
# stale entries have produced wrong-buffer-count executions. Dead-branch
# pruning (EngineConfig.app_kinds/uses_tcp) keeps compiles fast instead.

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 run (-m 'not slow'); "
        "compile-heavy or long-wall tests")


@pytest.fixture(scope="session")
def simple_topology_xml():
    """A 2-PoI topology equivalent to resource/topology.simple.graphml:
    20ms intra-vertex self-loops, 50ms inter-vertex link, no loss."""
    return SIMPLE_TOPOLOGY


SIMPLE_TOPOLOGY = """<?xml version="1.0" encoding="utf-8"?>
<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="packetloss" attr.type="double" for="edge" id="d9" />
  <key attr.name="jitter" attr.type="double" for="edge" id="d8" />
  <key attr.name="latency" attr.type="double" for="edge" id="d7" />
  <key attr.name="type" attr.type="string" for="node" id="d5" />
  <key attr.name="bandwidthup" attr.type="int" for="node" id="d4" />
  <key attr.name="bandwidthdown" attr.type="int" for="node" id="d3" />
  <key attr.name="geocode" attr.type="string" for="node" id="d2" />
  <key attr.name="ip" attr.type="string" for="node" id="d1" />
  <key attr.name="packetloss" attr.type="double" for="node" id="d0" />
  <graph edgedefault="undirected">
    <node id="poi-1">
      <data key="d0">0.0</data><data key="d1">0.0.0.0</data>
      <data key="d2">US</data><data key="d3">2048</data>
      <data key="d4">1024</data><data key="d5">net</data>
    </node>
    <node id="poi-2">
      <data key="d0">0.0</data><data key="d1">0.0.0.0</data>
      <data key="d2">US</data><data key="d3">2048</data>
      <data key="d4">1024</data><data key="d5">net</data>
    </node>
    <edge source="poi-1" target="poi-1">
      <data key="d7">20.0</data><data key="d8">0.0</data><data key="d9">0.0</data>
    </edge>
    <edge source="poi-1" target="poi-2">
      <data key="d7">50.0</data><data key="d8">0.0</data><data key="d9">0.0</data>
    </edge>
    <edge source="poi-2" target="poi-2">
      <data key="d7">20.0</data><data key="d8">0.0</data><data key="d9">0.0</data>
    </edge>
  </graph>
</graphml>
"""
