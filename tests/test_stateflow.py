"""stateflow: the pass x field access matrix and its STF contracts.

Three layers of proof, mirroring the simlint test philosophy (every
check family must demonstrably FIRE, and the repo itself must be
clean):

1. hand-derived access matrices — the expected read/write sets of
   small passes (NIC rx admission, the UDP deliver -> q_push chain,
   cap-peak sampling) are derived by reading the source and pinned
   exactly; SACK-scoreboard invariants are pinned on the tcp.timer
   and nic.tx columns;
2. fixture repos where a cold-column drain read, a dead column, an
   unsectioned field and an unwidened i32->i64 flow each produce
   exactly one NAMED violation;
3. acceptance — a cold-column read PLANTED into the real engine's
   drain subgraph fails `python -m tools.simlint` by rule name, and
   engine.state.section_of covers every live Hosts field (strict
   mode raises on anything else).

Everything except the section_of test is jax-free (the analyzer is
pure stdlib AST; the loader never touches shadow_tpu.__init__).
"""

import json
import os
import shutil
import subprocess
import sys
import importlib

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.simlint import load  # noqa: E402

lint = load()
core = sys.modules["shadow_tpu.lint.core"]
stateflow = importlib.import_module("shadow_tpu.lint.stateflow")


@pytest.fixture(scope="module")
def repo_matrix():
    """The analyzer's output on the repo itself (shared: one ~1.5s
    _Project build for the whole module)."""
    cache = core.SourceCache(REPO)
    matrix, violations = stateflow.analyze(cache)
    return matrix, violations


def make_repo(tmp_path, files):
    for rel, content in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(content)
    return str(tmp_path)


def run_cli(args, cwd=REPO):
    return subprocess.run([sys.executable, "-m", *args],
                          cwd=cwd, capture_output=True, text=True)


# --- the state model --------------------------------------------------

def test_model_parses_fields_dtypes_sections():
    cache = core.SourceCache(REPO)
    m = stateflow.load_state_model(cache)
    assert not m.errors, m.errors
    # the socket table alone is ~45 columns; every field gets a dtype
    assert len(m.fields["hosts"]) > 60
    assert sum(1 for f in m.fields["hosts"] if f.startswith("sk_")) \
        >= 40
    for kind in ("hosts", "hp", "sh"):
        unknown = [f for f, dt in m.fields[kind].items()
                   if dt == "?" and f != "rng_root"]
        assert not unknown, (kind, unknown)
    assert m.fields["hosts"]["eq_time"] == "i64"
    assert m.fields["hosts"]["sk_cwnd"] == "f32"
    assert m.fields["hp"]["pcap_on"] == "bool"
    assert m.fields["sh"]["seed32"] == "u32"
    # every Hosts field sectioned, cold fields are real fields
    assert all(m.section_of(f) for f in m.fields["hosts"])
    assert m.cold and m.cold <= set(m.fields["hosts"])


def test_section_of_strict_and_all_fields_sectioned():
    """Satellite: section_of fails loudly in strict mode, and every
    LIVE Hosts field (via the dataclass, not the parsed model) maps
    to a section."""
    import dataclasses
    from shadow_tpu.engine.state import Hosts, section_of
    for f in dataclasses.fields(Hosts):
        assert section_of(f.name, strict=True) != "other"
    assert section_of("no_such_field") == "other"
    with pytest.raises(KeyError):
        section_of("no_such_field", strict=True)


# --- the repo's own matrix: hand-derived expectations ----------------

def test_repo_scan_is_clean(repo_matrix):
    _, violations = repo_matrix
    assert violations == [], [v.render() for v in violations]


def test_hand_derived_nic_rx_admit(repo_matrix):
    """nic.rx_admit (net/nic.py): reads the rx busy horizon, rolls
    the backlog against the buffer, counts drops, and observes the
    queue delay into the netscope histogram (obs.netscope.observe —
    the analyzer must follow the cross-module call). Derived by hand
    from the function body — stateflow must reproduce it exactly."""
    matrix, _ = repo_matrix
    acc = matrix["nic.rx_admit"]
    assert sorted(acc["hosts"]["reads"]) == [
        "nic_rx_until", "ns_hist", "stats"]
    assert sorted(acc["hosts"]["writes"]) == [
        "nic_rx_until", "ns_hist", "stats"]
    assert sorted(acc["hp"]["reads"]) == ["bw_down", "nic_buf"]
    assert acc["sh"]["reads"] == {}


def test_hand_derived_udp_deliver(repo_matrix):
    """udp.deliver (net/udp.py): advances the stream cursor, counts
    bytes, reads the socket generation for the wake, and pushes an
    EV_APP through equeue.q_push (which touches every eq_* column
    plus the overflow stat). Derived by hand across the helper
    boundary — the analyzer must follow q_push."""
    matrix, _ = repo_matrix
    acc = matrix["udp.deliver"]
    eq = ["eq_ctr", "eq_kind", "eq_next", "eq_pkt", "eq_seq",
          "eq_time"]
    assert sorted(acc["hosts"]["reads"]) == sorted(
        eq + ["sk_rcv_nxt", "sk_timer_gen", "stats"])
    assert sorted(acc["hosts"]["writes"]) == sorted(
        eq + ["sk_rcv_nxt", "stats"])


def test_hand_derived_cap_peaks(repo_matrix):
    """update_cap_peaks samples four occupancy gauges and the peak
    table — and touches nothing else (that is WHY cap_peaks can be a
    cold column)."""
    matrix, _ = repo_matrix
    acc = matrix["cap_peaks"]
    assert sorted(acc["hosts"]["reads"]) == [
        "cap_peaks", "eq_time", "ob_cnt", "sk_used", "txq_cnt"]
    assert sorted(acc["hosts"]["writes"]) == ["cap_peaks"]


def test_sack_scoreboard_update_invariants(repo_matrix):
    """The SACK scoreboard's access contract across passes:

    - tcp.rx accumulates peer SACK blocks and consumes the receive
      scoreboard: all four range tables are read AND written;
    - the RTO path (tcp.timer) CLEARS the sender scoreboard (RFC 2018
      s8 renege rule) and rewinds snd_nxt, but must never touch the
      receive scoreboard (sk_ooo_*) and never take an RTT sample
      (Karn: sk_srtt/sk_rttvar are not written);
    - the NIC pull encodes the two most urgent receive ranges on
      every ACK-bearing segment: sk_ooo_* are read, never written.
    """
    matrix, _ = repo_matrix
    rx, timer, tx = matrix["tcp.rx"], matrix["tcp.timer"], \
        matrix["nic.tx"]
    for f in ("sk_ooo_s", "sk_ooo_e", "sk_sack_s", "sk_sack_e"):
        assert f in rx["hosts"]["reads"]
        assert f in rx["hosts"]["writes"]
    for f in ("sk_sack_s", "sk_sack_e", "sk_snd_nxt", "sk_hole_end"):
        assert f in timer["hosts"]["writes"], f
    for f in ("sk_ooo_s", "sk_ooo_e", "sk_srtt", "sk_rttvar",
              "sk_rcv_nxt"):
        assert f not in timer["hosts"]["writes"], f
    for f in ("sk_ooo_s", "sk_ooo_e"):
        assert f in tx["hosts"]["reads"]
        assert f not in tx["hosts"]["writes"]


def test_drain_subgraph_covers_the_event_machine(repo_matrix):
    """Vacuity guard on the guard: the drain entry must traverse the
    handlers into TCP/NIC/app code (the cold-column gate is only as
    strong as this reach)."""
    matrix, _ = repo_matrix
    drain = matrix["drain"]["hosts"]
    for f in ("eq_time", "sk_state", "sk_sack_s", "txq_pkt",
              "app_r", "rng_ctr", "nic_busy", "hw_cnt"):
        assert f in drain["reads"], f
    # and the declared cold columns are genuinely out of it
    cache = core.SourceCache(REPO)
    model = stateflow.load_state_model(cache)
    for f in sorted(model.cold):
        assert f not in drain["reads"], f
        assert f not in drain["writes"], f


def test_drain_excludes_exchange_only_columns(repo_matrix):
    """ob_next is written by the exchange carry and read by the
    window advance — never inside the drain. tr_* only move in the
    exchange (trace records). This is the measured basis of
    COLD_FIELDS."""
    matrix, _ = repo_matrix
    assert "ob_next" in matrix["exchange"]["hosts"]["writes"]
    assert "ob_next" in matrix["advance"]["hosts"]["reads"]
    assert "tr_pkt" in matrix["exchange"]["hosts"]["writes"]


# --- fixture repos: each rule fires exactly once, by name ------------

FIX_STATE = '''\
import chex
import jax.numpy as jnp

STATE_SECTIONS = (
    ("eq_", "event_queue"),
    ("sk_", "tcp"),
    ("tr_", "trace_ring"),
    ("stats", "stats"),
)

COLD_FIELDS = frozenset({"tr_cnt"})


@chex.dataclass
class Hosts:
    eq_time: jnp.ndarray   # [H, Q] i64
    eq_ctr: jnp.ndarray    # [H] i32
    sk_cwnd: jnp.ndarray   # [H, S] f32
    tr_cnt: jnp.ndarray    # [H] i32
    stats: jnp.ndarray     # [H, N] i64
@EXTRA@

@chex.dataclass
class HostParams:
    hid: jnp.ndarray       # [H] i32


@chex.dataclass
class Shared:
    stop_time: jnp.ndarray  # i64
'''

FIX_WINDOW = '''\
import jax.numpy as jnp


def drain_window(hosts, hp, sh, wend, cfg, pc):
    ctr = hosts.eq_ctr.astype(jnp.int64)
    cw = hosts.sk_cwnd * 2.0
@PLANT@
    return hosts.replace(
        eq_time=hosts.eq_time + ctr,
        eq_ctr=hosts.eq_ctr + 1,
        sk_cwnd=cw,
        stats=hosts.stats + hp.hid.astype(jnp.int64)[:, None],
    ), pc


def exchange(hosts, hp, sh, cfg):
    return hosts.replace(tr_cnt=hosts.tr_cnt + 1)


def update_cap_peaks(hosts):
    return hosts


def next_wakeup(hosts):
    return hosts.eq_time
'''


def fixture_violations(tmp_path, state_extra="", plant="    pass"):
    root = make_repo(tmp_path, {
        "shadow_tpu/engine/state.py": FIX_STATE.replace(
            "@EXTRA@", state_extra),
        "shadow_tpu/engine/window.py": FIX_WINDOW.replace(
            "@PLANT@", plant),
    })
    return stateflow.check(core.SourceCache(root))


def test_fixture_clean_base(tmp_path):
    assert fixture_violations(tmp_path) == []


def test_fixture_cold_column_drain_read(tmp_path):
    vs = fixture_violations(
        tmp_path, plant="    cold = hosts.tr_cnt + 0")
    assert len(vs) == 1 and vs[0].rule == "STF303", vs
    assert "tr_cnt" in vs[0].message
    assert vs[0].file == "shadow_tpu/engine/window.py"


def test_fixture_dead_column(tmp_path):
    vs = fixture_violations(
        tmp_path, state_extra="    sk_ghost: jnp.ndarray  # [H] i32\n")
    assert len(vs) == 1 and vs[0].rule == "STF302", vs
    assert "sk_ghost" in vs[0].message
    assert vs[0].file == "shadow_tpu/engine/state.py"


def test_fixture_unsectioned_field(tmp_path):
    # read it in the drain so the ONLY failure is the missing section
    vs = fixture_violations(
        tmp_path,
        state_extra="    zz_mystery: jnp.ndarray  # [H] i64\n",
        plant="    m = hosts.zz_mystery + jnp.int64(1)")
    assert len(vs) == 1 and vs[0].rule == "STF301", vs
    assert "zz_mystery" in vs[0].message


def test_fixture_unwidened_i32_flow(tmp_path):
    vs = fixture_violations(
        tmp_path, plant="    t = hosts.eq_time + hosts.eq_ctr")
    assert len(vs) == 1 and vs[0].rule == "STF401", vs
    assert "eq_ctr" in vs[0].message


def test_fixture_f32_vs_i64_compare(tmp_path):
    vs = fixture_violations(
        tmp_path, plant="    c = hosts.sk_cwnd > hosts.eq_time")
    assert len(vs) == 1 and vs[0].rule == "STF402", vs
    assert "sk_cwnd" in vs[0].message


def test_fixture_simtime_sentinel(tmp_path):
    plant = ("    from shadow_tpu.core.simtime import SIMTIME_MAX\n"
             "    s = hosts.eq_ctr == SIMTIME_MAX")
    vs = fixture_violations(tmp_path, plant=plant)
    assert len(vs) == 1 and vs[0].rule == "STF403", vs


def test_renamed_entry_pass_fails_loudly(tmp_path):
    """A pass function that disappears from a module that still
    exists is a RENAME — silently dropping its matrix column would
    shrink the STF302 read census and the CI artifact unnoticed, so
    it must be an STF300."""
    root = make_repo(tmp_path, {
        "shadow_tpu/engine/state.py": FIX_STATE.replace("@EXTRA@", ""),
        "shadow_tpu/engine/window.py": FIX_WINDOW
        .replace("@PLANT@", "    pass")
        .replace("def update_cap_peaks", "def update_cap_peaks_v2"),
    })
    vs = stateflow.check(core.SourceCache(root))
    assert len(vs) == 1 and vs[0].rule == "STF300", vs
    assert "update_cap_peaks" in vs[0].message
    assert "cap_peaks" in vs[0].message


# --- acceptance: planting a cold read in the REAL drain fails the
# gate by name ---------------------------------------------------------

def test_planted_cold_read_fails_gate_by_name(tmp_path):
    root = str(tmp_path / "repo")
    shutil.copytree(os.path.join(REPO, "shadow_tpu"),
                    os.path.join(root, "shadow_tpu"),
                    ignore=shutil.ignore_patterns("__pycache__"))
    shutil.copytree(os.path.join(REPO, "tools"),
                    os.path.join(root, "tools"),
                    ignore=shutil.ignore_patterns("__pycache__"))
    window = os.path.join(root, "shadow_tpu/engine/window.py")
    with open(window) as f:
        src = f.read()
    anchor = "    slot, t = equeue.q_min(row)\n"
    assert anchor in src
    with open(window, "w") as f:
        f.write(src.replace(
            anchor, anchor + "    _cold = jnp.minimum(row.tr_cnt, 1)\n",
            1))
    r = subprocess.run(
        [sys.executable, "-m", "tools.simlint", "--root", root],
        cwd=REPO, capture_output=True, text=True)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "STF303" in r.stdout and "tr_cnt" in r.stdout, r.stdout


# --- the front-end tool ------------------------------------------------

def test_state_matrix_json_and_markdown(tmp_path):
    out = str(tmp_path / "m.json")
    r = run_cli(["tools.state_matrix", "--json", "-o", out])
    assert r.returncode == 0, r.stdout + r.stderr
    with open(out) as f:
        data = json.load(f)
    assert sorted(data) == ["bytes_per_host", "cold_fields",
                            "cold_when", "drain_hot_columns",
                            "entries", "fields", "hot_counts",
                            "hot_fields", "root", "sections",
                            "version"]
    # the memscope-sourced bytes column (obs.memscope stdlib dims
    # table, pinned exact by tests/test_memscope.py): per-field and
    # rolled up, at the EngineConfig defaults
    assert data["fields"]["hosts"]["eq_time"]["bytes_per_host"] == 256
    assert data["fields"]["hp"]["hid"]["bytes_per_host"] == 4
    bph = data["bytes_per_host"]
    assert bph["hosts"] == sum(
        v["bytes_per_host"] for v in data["fields"]["hosts"].values())
    assert 0 < bph["hosts_hot"] <= bph["hosts"]
    assert bph["hosts_drain"] == bph["hosts_hot"]
    # the drain's measured working set is exactly the declared hot set
    assert data["drain_hot_columns"] == sorted(data["hot_fields"])
    assert "drain" in data["entries"]
    drain = data["entries"]["drain"]["hosts"]
    assert "sk_state" in drain["reads"]
    # access sites are (file, line) pairs
    f0, l0 = next(iter(drain["reads"].values()))
    assert f0.endswith(".py") and isinstance(l0, int)
    assert data["fields"]["hosts"]["tr_cnt"]["cold"] is True
    assert data["fields"]["hosts"]["eq_time"]["section"] \
        == "event_queue"
    assert sorted(data["cold_fields"]) == sorted(
        stateflow.load_state_model(core.SourceCache(REPO)).cold)

    r = run_cli(["tools.state_matrix", "--markdown"])
    assert r.returncode == 0
    assert "| `eq_time` | i64 | event_queue | 256 |" in r.stdout


# --- the hot/cold split declaration (HOT_FIELDS / COLD_WHEN) ---------

def test_drain_hot_set_equals_declaration(repo_matrix):
    """The drain's measured working set IS the declared HOT_FIELDS
    partition — the split's machine-checked contract: reads/writes
    recorded in the drain subgraph cover exactly the static hot set
    (cold columns untouched), and every config-gated COLD_WHEN column
    is a member of it."""
    matrix, _ = repo_matrix
    model = stateflow.load_state_model(core.SourceCache(REPO))
    assert model.hot, "real repo must declare HOT_FIELDS"
    drain = matrix["drain"]["hosts"]
    touched = set(drain["reads"]) | set(drain["writes"])
    assert touched == set(model.hot)
    gated = {f for _, flds in model.cold_when for f in flds}
    assert gated and gated <= set(model.hot)
    assert not (gated & model.cold)


def test_fixture_hot_partition_must_cover(tmp_path):
    """A declared HOT_FIELDS that does not partition the Hosts
    columns against COLD_FIELDS is an integrity failure (never
    baselined)."""
    vs = fixture_violations(
        tmp_path,
        state_extra="\nHOT_FIELDS = (\"eq_time\", \"eq_ctr\")\n")
    assert vs and all(v.rule == "STF300" for v in vs), vs
    missing = {m for v in vs for m in ("sk_cwnd", "stats")
               if m in v.message}
    assert missing == {"sk_cwnd", "stats"}, vs


def test_fixture_cold_when_overlap_is_stf304(tmp_path):
    vs = fixture_violations(
        tmp_path,
        state_extra="\nCOLD_WHEN = ((\"no_tcp\", (\"tr_cnt\",)),)\n")
    assert len(vs) == 1 and vs[0].rule == "STF304", vs
    assert "tr_cnt" in vs[0].message
    assert "statically cold" in vs[0].message
