"""CPU delay model tests (reference shd-cpu.c semantics)."""

import numpy as np

from shadow_tpu.core.config import HostSpec, ProcessSpec, Scenario
from shadow_tpu.engine import defs
from shadow_tpu.engine.sim import Simulation
from shadow_tpu.engine.state import EngineConfig

from test_phold import MESH_TOPO

CFG = dict(qcap=16, scap=4, obcap=8, incap=16, chunk_windows=8)


def scen(freq_khz=None, threshold_ns=-1, stop=6):
    s = Scenario(
        stop_time=stop * 10**9,
        topology_graphml=MESH_TOPO,
        hosts=[
            HostSpec(id="srv", cpu_frequency=freq_khz, processes=[
                ProcessSpec(plugin="pingserver", start_time=10**9,
                            arguments="port=8000")]),
            HostSpec(id="cli", processes=[
                ProcessSpec(plugin="ping", start_time=2 * 10**9,
                            arguments="peer=srv port=8000 interval=500ms "
                                      "size=64 count=5")]),
        ],
    )
    s.cpu_threshold_ns = threshold_ns
    return s


def test_cpu_model_off_by_default():
    sim = Simulation(scen(), engine_cfg=EngineConfig(num_hosts=2, **CFG))
    assert not sim.cfg.cpu_model
    r = sim.run()
    assert r.summary()["transfers_done"] == 5


def test_cpu_cost_scales_with_frequency():
    # slow CPU (500 MHz vs raw 3 GHz): ratio 6 -> cost 60us rounded to
    # precision 200us -> 0? rounded 60us -> 0.2ms buckets: 60_000 ->
    # round(60000/200000)=0 -> cost 0. Use finer precision scenario.
    s = scen(freq_khz=500_000)
    s.cpu_precision_ns = 1000
    sim = Simulation(s, engine_cfg=EngineConfig(num_hosts=2, **CFG))
    assert sim.cfg.cpu_model
    assert int(np.asarray(sim.hp.cpu_cost)[0]) == 60_000  # 10us x6
    assert int(np.asarray(sim.hp.cpu_cost)[1]) == 0       # unset host
    r = sim.run()
    # ping still completes; the server's processing adds delay, not loss
    assert r.summary()["transfers_done"] == 5
    assert r.summary()["mean_rtt_us"] >= 50_000  # >= network-only RTT


def test_cpu_threshold_blocks_and_recovers():
    # Huge per-event cost + tiny threshold: server events get deferred
    # to the CPU horizon; everything still completes, later.
    fast = Simulation(scen(freq_khz=3_000_000, threshold_ns=10**9),
                      engine_cfg=EngineConfig(num_hosts=2, **CFG)).run()
    s = scen(freq_khz=30_000, threshold_ns=1000)  # ratio 100 -> 1ms/evt
    s.cpu_precision_ns = 1000
    slow = Simulation(s, engine_cfg=EngineConfig(num_hosts=2, **CFG)).run()
    assert slow.summary()["transfers_done"] == 5
    assert (slow.summary()["mean_rtt_us"] >
            fast.summary()["mean_rtt_us"])
