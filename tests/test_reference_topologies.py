"""The reference's bundled topology files load directly (COVERAGE
claims parity with the igraph GraphML import, shd-topology.c:95-123).

Skipped when the reference mount is absent — the repo stands alone."""

import os

import numpy as np
import pytest

REF = "/root/reference/resource"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF), reason="reference mount not present")


def test_simple_topology_loads_and_routes():
    from shadow_tpu.routing.topology import build_topology

    topo = build_topology(f"{REF}/topology.simple.graphml.xml.xz")
    V = topo.num_vertices
    assert V > 0
    # validated like the reference: strongly connected, positive
    # latencies, sane reliability
    assert topo.min_latency_ns > 0
    lat = np.asarray(topo.latency_ns)
    rel = np.asarray(topo.reliability)
    assert (lat > 0).all()
    assert ((rel > 0) & (rel <= 1.0)).all()


def test_plab_topology_loads():
    from shadow_tpu.routing.graphml import parse_graphml

    g = parse_graphml(f"{REF}/topology.plab.graphml.xml.xz")
    assert g.num_vertices > 100          # PlanetLab-scale PoI graph
    assert g.num_edges > g.num_vertices  # complete-ish graph
    assert (g.e_latency_ms > 0).all()
