"""Topology/DNS tests: path semantics must match the reference
(see shadow_tpu.routing.topology docstring for the spec)."""

import numpy as np
import pytest

from shadow_tpu.core.simtime import SIMTIME_ONE_MILLISECOND
from shadow_tpu.routing.dns import DNS
from shadow_tpu.routing.graphml import parse_graphml
from shadow_tpu.routing.topology import build_topology, attach_hosts

TRIANGLE = """
<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="latency" attr.type="double" for="edge" id="d7"/>
  <key attr.name="packetloss" attr.type="double" for="edge" id="d9"/>
  <key attr.name="packetloss" attr.type="double" for="node" id="d0"/>
  <key attr.name="type" attr.type="string" for="node" id="d5"/>
  <graph edgedefault="undirected">
    <node id="a"><data key="d0">0.1</data><data key="d5">client</data></node>
    <node id="b"><data key="d0">0.0</data><data key="d5">relay</data></node>
    <node id="c"><data key="d0">0.2</data><data key="d5">server</data></node>
    <edge source="a" target="b"><data key="d7">10.0</data><data key="d9">0.05</data></edge>
    <edge source="b" target="c"><data key="d7">20.0</data><data key="d9">0.0</data></edge>
    <edge source="a" target="c"><data key="d7">100.0</data><data key="d9">0.0</data></edge>
    <edge source="a" target="a"><data key="d7">5.0</data><data key="d9">0.0</data></edge>
  </graph>
</graphml>
"""


def test_parse_graphml(simple_topology_xml):
    g = parse_graphml(simple_topology_xml)
    assert g.num_vertices == 2
    assert g.num_edges == 3
    assert g.v_bw_down[0] == 2048


def test_shortest_path_latency():
    topo = build_topology(TRIANGLE)
    ms = SIMTIME_ONE_MILLISECOND
    # a->c goes via b (30ms) not direct (100ms)
    assert topo.latency_ns[0, 2] == 30 * ms
    assert topo.latency_ns[2, 0] == 30 * ms
    assert topo.latency_ns[0, 1] == 10 * ms
    # self-loop on a: 5ms; no self-loop on b: reference 1ms fallback
    assert topo.latency_ns[0, 0] == 5 * ms
    assert topo.latency_ns[1, 1] == 1 * ms
    assert topo.min_latency_ns == 1 * ms


def test_path_reliability_matches_reference_formula():
    topo = build_topology(TRIANGLE)
    # a->c via b: (1-.1)src * (1-.05)(1-0) edges * (1-.2)dst; b's vertex
    # loss (intermediate) is NOT applied, matching the reference.
    expect = 0.9 * 0.95 * 1.0 * 0.8
    assert topo.reliability[0, 2] == pytest.approx(expect, rel=1e-6)
    # a->a: src vertex loss once * self-loop edge loss
    assert topo.reliability[0, 0] == pytest.approx(0.9, rel=1e-6)


def test_attach_hosts_type_hint():
    topo = build_topology(TRIANGLE)
    hints = [(None, None, "server")] * 5 + [(None, None, "client")] * 3
    v = attach_hosts(topo, hints, seed=3)
    assert (v[:5] == 2).all()
    assert (v[5:] == 0).all()


def test_dns_registry():
    dns = DNS()
    ip1 = dns.register(0, "alpha")
    ip2 = dns.register(1, "beta")
    assert ip1 != ip2
    assert dns.resolve("alpha") == 0
    assert dns.resolve(dns.ip_str(1)) == 1
    assert dns.reverse(1) == "beta"
    with pytest.raises(ValueError):
        dns.register(2, "alpha")
    arr = dns.ip_array(2)
    assert arr[0] == ip1
