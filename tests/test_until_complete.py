"""Durability proof: interrupted ≡ uninterrupted, byte for byte.

The acceptance tests of the durable-runs tentpole: a simulator
process SIGKILLed mid-run — deterministically at a simulated time
(engine.faults.CrashHook) or at an arbitrary wall-clock instant — and
brought back by the auto-resume supervisor (``--until-complete`` /
``--resume latest``) must produce a determinism digest chain
(obs.digest) byte-identical to an uninterrupted same-seed run's
(tools/divergence.py exit 0), for modeled-only, fault-schedule, and
hosted-app (journal-replay) scenarios.

Each scenario spawns fresh CLI processes (a kill must hit a REAL
process) — compile-heavy on the CPU dev box; the file name sorts near
the end of the suite deliberately.
"""

import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent

TOPO = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="latency" attr.type="double" for="edge" id="d7"/>
  <key attr.name="packetloss" attr.type="double" for="edge" id="d9"/>
  <key attr.name="packetloss" attr.type="double" for="node" id="d0"/>
  <key attr.name="bandwidthup" attr.type="int" for="node" id="d4"/>
  <key attr.name="bandwidthdown" attr.type="int" for="node" id="d3"/>
  <graph edgedefault="undirected">
    <node id="poi"><data key="d0">0.0</data>
      <data key="d3">10240</data><data key="d4">10240</data></node>
    <edge source="poi" target="poi"><data key="d7">25.0</data>
      <data key="d9">0.0</data></edge>
  </graph>
</graphml>"""

PHOLD_XML = f"""<shadow stoptime="6">
  <topology><![CDATA[{TOPO}]]></topology>
  <host id="node" quantity="8">
    <process plugin="phold" starttime="1"
             arguments="port=9000 mean=300ms size=64 init=1"/>
  </host>
</shadow>"""

PHOLD_CAPS = "qcap=16,scap=4,obcap=8,incap=16,chunk=8"

# a paced uploader: sim-time sleeps spread the transfer over ~10 sim
# seconds so the crash reliably lands mid-transfer with the child
# parked mid-protocol
UPLOADER_SRC = """\
import socket, time
s = socket.create_connection(("server", 8080))
for i in range(40):
    s.send(b"x" * 4000)
    time.sleep(0.25)
s.close()
print("done")
"""


def _env(extra=None):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra or {})
    return env


def run_cli(args, extra_env=None, check=True, timeout=900):
    p = subprocess.run(
        [sys.executable, "-m", "shadow_tpu"] + args,
        env=_env(extra_env), cwd=str(REPO),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        timeout=timeout)
    text = p.stdout.decode(errors="replace")
    if check:
        assert p.returncode == 0, (
            f"CLI failed rc={p.returncode}:\n{text[-4000:]}")
    return p.returncode, text


def chains_identical(a, b):
    """tools/divergence.py verdict (the acceptance oracle) + raw
    bytes (the stronger claim)."""
    sys.path.insert(0, str(REPO / "tools"))
    try:
        import divergence
    finally:
        sys.path.pop(0)
    rc = divergence.main([str(a), str(b)])
    same_bytes = Path(a).read_bytes() == Path(b).read_bytes()
    return rc == 0 and same_bytes


def common_args(xml, dg, ck, extra=()):
    return [str(xml), "--seed", "7", "--digest", str(dg),
            "--digest-every", "8", "--checkpoint", str(ck),
            "--checkpoint-every", "1"] + list(extra)


def supervised(args, crash_ns, guard, extra_env=None):
    env = {"SHADOW_TPU_CRASH_SIM_NS": str(crash_ns),
           "SHADOW_TPU_CRASH_GUARD": str(guard)}
    env.update(extra_env or {})
    return run_cli(["--until-complete", "--max-retries", "3",
                    "--retry-backoff", "0.1"] + args, extra_env=env)


def read_supervisor_log(ck):
    import json
    log = Path(str(ck) + ".supervisor.jsonl")
    assert log.exists(), "supervisor wrote no crash-cause log"
    return [json.loads(l) for l in log.read_text().splitlines()]


def test_sigkill_resume_modeled(tmp_path):
    """(a) modeled-only: deterministic SIGKILL mid-run via the fault
    machinery's crash hook, supervised resume, digest chains byte-
    identical; the supervisor log names the crash cause."""
    xml = tmp_path / "phold.xml"
    xml.write_text(PHOLD_XML)
    dg_a = tmp_path / "a.jsonl"
    run_cli(common_args(xml, dg_a, tmp_path / "ck_a",
                        ["--engine-caps", PHOLD_CAPS]))

    dg_b = tmp_path / "b.jsonl"
    ck_b = tmp_path / "ck_b"
    supervised(common_args(xml, dg_b, ck_b,
                           ["--engine-caps", PHOLD_CAPS]),
               crash_ns=3_500_000_000, guard=tmp_path / "guard")
    recs = read_supervisor_log(ck_b)
    assert recs[0]["exit_status"] == -signal.SIGKILL
    assert "SIGKILL" in recs[0]["cause"]
    assert recs[-1]["cause"] == "completed" and recs[-1]["resumed"]
    assert chains_identical(dg_a, dg_b), (
        "resumed modeled run's digest chain diverges from the "
        "uninterrupted run")


def test_sigkill_resume_fault_schedule(tmp_path):
    """(b) fault schedule: the kill lands INSIDE a loss episode; the
    resumed run must re-arm the injector (schedule position + active
    episode bookkeeping) from the snapshot."""
    xml = tmp_path / "phold.xml"
    xml.write_text(PHOLD_XML)
    faults = ["--fault", "kind=loss,at=2s,until=4s,rate=0.3,"
                         "src=node1,dst=node2",
              "--fault", "kind=latency,at=4.5s,until=5.5s,extra=10ms,"
                         "src=node1,dst=node2",
              "--engine-caps", PHOLD_CAPS]
    dg_a = tmp_path / "a.jsonl"
    run_cli(common_args(xml, dg_a, tmp_path / "ck_a", faults))

    dg_b = tmp_path / "b.jsonl"
    supervised(common_args(xml, dg_b, tmp_path / "ck_b", faults),
               crash_ns=3_000_000_000, guard=tmp_path / "guard")
    assert chains_identical(dg_a, dg_b), (
        "resumed fault-schedule run's digest chain diverges from the "
        "uninterrupted run")


HOSTED_CAPS = "qcap=32,scap=8,obcap=16,incap=32,hostedcap=16"


def hosted_xml(tmp_path, tag):
    script = tmp_path / "upload.py"
    script.write_text(UPLOADER_SRC)
    out = tmp_path / f"upload-{tag}.out"
    xml = tmp_path / f"hosted-{tag}.xml"
    xml.write_text(f"""<shadow stoptime="14">
  <topology><![CDATA[{TOPO}]]></topology>
  <host id="server">
    <process plugin="bulkserver" starttime="1" arguments="port=8080"/>
  </host>
  <host id="client">
    <process plugin="hosted:shim" starttime="2"
             arguments="out={out} cmd={sys.executable} {script}"/>
  </host>
</shadow>""")
    return xml, out


def test_sigkill_resume_hosted(tmp_path):
    """(c) hosted apps: the simulator (and with it the real child
    process) is SIGKILLed mid-transfer; resume respawns the child and
    fast-forwards it by journal replay. Chains byte-identical,
    including the per-child protocol-stream digests; the child's
    stdout proves it really re-ran to completion."""
    xml_a, out_a = hosted_xml(tmp_path, "a")
    dg_a = tmp_path / "a.jsonl"
    run_cli(common_args(xml_a, dg_a, tmp_path / "ck_a",
                        ["--engine-caps", HOSTED_CAPS,
                         "--checkpoint-every", "2"]))
    assert "done" in out_a.read_text()

    xml_b, out_b = hosted_xml(tmp_path, "b")
    dg_b = tmp_path / "b.jsonl"
    supervised(common_args(xml_b, dg_b, tmp_path / "ck_b",
                           ["--engine-caps", HOSTED_CAPS,
                            "--checkpoint-every", "2"]),
               crash_ns=7_000_000_000, guard=tmp_path / "guard")
    assert "done" in out_b.read_text(), (
        "respawned child never finished its transfer after replay")
    # chains must match EXCEPT the manifest argv/config path (the two
    # runs use distinct XML copies so each child writes its own out=);
    # rewrite is not needed — records carry no paths
    assert chains_identical(dg_a, dg_b), (
        "resumed hosted run's digest chain diverges from the "
        "uninterrupted run")


def test_wall_clock_kill_resume_latest(tmp_path):
    """SIGKILL at an ARBITRARY instant (no sim-time hook): launch the
    CLI, kill -9 as soon as the store's `latest` pointer exists, then
    finish with `--resume latest`. Whatever the kill interrupted —
    including a checkpoint write — the store must yield a usable
    snapshot and the final chain must match the uninterrupted run."""
    xml = tmp_path / "phold.xml"
    xml.write_text(PHOLD_XML)
    dg_a = tmp_path / "a.jsonl"
    run_cli(common_args(xml, dg_a, tmp_path / "ck_a",
                        ["--engine-caps", PHOLD_CAPS]))

    dg_b = tmp_path / "b.jsonl"
    ck_b = tmp_path / "ck_b"
    proc = subprocess.Popen(
        [sys.executable, "-m", "shadow_tpu"]
        + common_args(xml, dg_b, ck_b, ["--engine-caps", PHOLD_CAPS]),
        env=_env(), cwd=str(REPO),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    pointer = Path(str(ck_b) + ".latest")
    deadline = time.time() + 600
    while time.time() < deadline:
        if pointer.exists():
            break
        if proc.poll() is not None:
            pytest.fail("run finished before the kill: "
                        + proc.stdout.read().decode(errors="replace")
                        [-2000:])
        time.sleep(0.05)
    assert pointer.exists(), "no checkpoint appeared within 600s"
    proc.kill()
    proc.wait(timeout=30)

    run_cli(common_args(xml, dg_b, ck_b,
                        ["--engine-caps", PHOLD_CAPS,
                         "--resume", "latest"]))
    assert chains_identical(dg_a, dg_b), (
        "wall-clock-killed + --resume latest chain diverges from the "
        "uninterrupted run")


def test_usage_error_not_retried(tmp_path):
    """A deterministic usage error (argparse rc=2) is not a crash:
    the supervisor must surface it immediately instead of paying
    max_retries re-execs to reproduce the same message. Driven at the
    Supervisor level — the CLI parent's own argparse would reject the
    argv before ever spawning, so only a direct embedder (or a
    child-only validation) can hit this path."""
    from shadow_tpu.engine.supervisor import Supervisor
    ck = tmp_path / "ck"
    msgs = []
    sup = Supervisor(["--bogus-flag", "nonsense"], str(ck),
                     max_retries=3, backoff_s=0.1, log=msgs.append)
    rc = sup.run()
    assert rc == 2
    assert any("not retrying" in m for m in msgs), msgs
    recs = read_supervisor_log(ck)
    assert len(recs) == 1 and recs[0]["exit_status"] == 2
