"""Differential test: C++ routing oracle vs the scipy/numpy path.

The native oracle replaces the reference's igraph (SURVEY §2.8); both
implementations must produce identical all-pairs tables (graphs are
generated with irrational-ish random weights so no equal-cost
multipaths exist to make predecessor choice ambiguous).
"""

import numpy as np
import pytest

from shadow_tpu.routing import native
from shadow_tpu.routing.graphml import Graph
from shadow_tpu.routing.topology import compute_all_pairs, build_topology


def random_graph(V, extra_edges, seed, with_loss=True, self_loops=True):
    rng = np.random.RandomState(seed)
    # random spanning tree (connected) + extra random edges
    src, dst = [], []
    for v in range(1, V):
        src.append(rng.randint(v))
        dst.append(v)
    for _ in range(extra_edges):
        a, b = rng.randint(V), rng.randint(V)
        if a != b:
            src.append(a)
            dst.append(b)
    if self_loops:
        for v in range(0, V, 3):
            src.append(v)
            dst.append(v)
    E = len(src)
    return Graph(
        vertex_ids=[f"v{i}" for i in range(V)],
        directed=False,
        v_ip=[""] * V,
        v_geocode=[""] * V,
        v_type=[""] * V,
        v_packetloss=(rng.rand(V) * 0.05 if with_loss
                      else np.zeros(V)),
        v_bw_up=np.full(V, 1024.0),
        v_bw_down=np.full(V, 1024.0),
        e_src=np.array(src, dtype=np.int64),
        e_dst=np.array(dst, dtype=np.int64),
        e_latency_ms=rng.rand(E) * 100 + 0.5,
        e_jitter_ms=np.zeros(E),
        e_packetloss=(rng.rand(E) * 0.1 if with_loss
                      else np.zeros(E)),
    )


@pytest.mark.skipif(not native.available(),
                    reason="native oracle unavailable (no g++?)")
@pytest.mark.parametrize("V,extra,seed", [(8, 10, 0), (40, 120, 1),
                                          (100, 50, 2)])
def test_native_matches_python(V, extra, seed):
    g = random_graph(V, extra, seed)
    lat_py, rel_py, un_py = compute_all_pairs(g, native=False)
    lat_cc, rel_cc, un_cc = compute_all_pairs(g, native=True)
    np.testing.assert_allclose(lat_cc, lat_py, rtol=0, atol=1e-9)
    np.testing.assert_allclose(rel_cc, rel_py, rtol=0, atol=1e-9)
    assert (un_cc == un_py).all()


@pytest.mark.skipif(not native.available(),
                    reason="native oracle unavailable (no g++?)")
def test_native_disconnected_pairs():
    # two components: cross-pairs unreachable in both implementations
    g = random_graph(6, 0, 3, self_loops=False)
    # sever: rebuild edges to make vertex 5 isolated
    keep = (g.e_src != 5) & (g.e_dst != 5)
    g.e_src, g.e_dst = g.e_src[keep], g.e_dst[keep]
    g.e_latency_ms = g.e_latency_ms[keep]
    g.e_jitter_ms = g.e_jitter_ms[keep]
    g.e_packetloss = g.e_packetloss[keep]
    lat_py, rel_py, un_py = compute_all_pairs(g, native=False)
    lat_cc, rel_cc, un_cc = compute_all_pairs(g, native=True)
    assert un_cc[0, 5] and un_cc[5, 0]
    np.testing.assert_allclose(rel_cc, rel_py, atol=1e-9)
    np.testing.assert_allclose(lat_cc, lat_py, atol=1e-9)


@pytest.mark.skipif(not native.available(),
                    reason="native oracle unavailable (no g++?)")
def test_native_perf_1k_vertices():
    """The native oracle must handle reference-scale PoI graphs (the
    bundled PlanetLab topology has ~1k vertices) in seconds."""
    import time

    g = random_graph(1000, 4000, 4)
    t0 = time.perf_counter()
    lat, rel, un = compute_all_pairs(g, native=True)
    dt = time.perf_counter() - t0
    assert lat.shape == (1000, 1000)
    assert not un.all()
    assert dt < 30, f"native APSP took {dt:.1f}s"
