"""Hosted-process supervision (hosting.shim + hosting.runtime).

The robustness tier's hosted half: a real child that crashes, hangs,
is SIGKILLed mid-transfer, or tries to fork must become a diagnosed,
per-host-reported simulated event — never a wedged or crashed
simulator. The reference gets the equivalent guarantees from owning
the process teardown path (shd-process.c:3195-3234); here the
LD_PRELOAD seam needs an explicit supervisor.
"""

import os
import sys

import numpy as np
import pytest

from shadow_tpu.core.config import (FaultSpec, HostSpec, ProcessSpec,
                                    Scenario)
from shadow_tpu.engine import defs
from shadow_tpu.engine.sim import Simulation
from shadow_tpu.engine.state import EngineConfig

ENGINE_CFG = dict(num_hosts=2, qcap=32, scap=8, obcap=16, incap=32,
                  txqcap=16, hostedcap=16, chunk_windows=8)

# a paced uploader: sim-time sleeps spread the transfer over ~20 sim
# seconds so a mid-run fault reliably lands mid-transfer
SLOW_UPLOADER_SRC = """\
import socket, time
s = socket.create_connection(("server", 8080))
for i in range(100):
    s.send(b"x" * 10000)
    time.sleep(0.2)
s.close()
print("done")
"""

BUSY_LOOP_SRC = """\
import socket
s = socket.create_connection(("server", 8080))
s.send(b"x" * 1000)
while True:      # no syscalls ever again: wall-clock watchdog bait
    pass
"""

FORKER_SRC = """\
import os, sys
try:
    os.fork()
    print("fork-succeeded")
except OSError as e:
    print("fork-refused errno=%d" % e.errno)
sys.stdout.flush()
"""

FOPEN_ENTROPY_SRC = """\
f = open("/dev/urandom", "rb", buffering=0)
data = f.read(16)
f.close()
print("entropy=" + data.hex())
"""


def hosted_scenario(script_path, out_path, faults=(), stop_s=30):
    return Scenario(
        stop_time=stop_s * 10**9,
        topology_graphml=TOPOLOGY,
        hosts=[
            HostSpec(id="server", processes=[
                ProcessSpec(plugin="bulkserver", start_time=10**9,
                            arguments="port=8080")]),
            HostSpec(id="client", processes=[
                ProcessSpec(plugin="hosted:shim", start_time=2 * 10**9,
                            arguments=f"out={out_path} "
                                      f"cmd={sys.executable} "
                                      f"{script_path}")]),
        ],
        faults=list(faults),
    )


TOPOLOGY = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="packetloss" attr.type="double" for="edge" id="d9" />
  <key attr.name="latency" attr.type="double" for="edge" id="d7" />
  <key attr.name="bandwidthup" attr.type="int" for="node" id="d4" />
  <key attr.name="bandwidthdown" attr.type="int" for="node" id="d3" />
  <key attr.name="packetloss" attr.type="double" for="node" id="d0" />
  <graph edgedefault="undirected">
    <node id="poi-1"><data key="d0">0.0</data>
      <data key="d3">17038</data><data key="d4">2251</data></node>
    <edge source="poi-1" target="poi-1">
      <data key="d7">20.0</data><data key="d9">0.0</data></edge>
  </graph>
</graphml>"""


def _write(tmp_path, name, src):
    p = str(tmp_path / name)
    with open(p, "w") as f:
        f.write(src)
    return p


def test_child_sigkill_mid_transfer(tmp_path):
    """Acceptance scenario's hosted leg: the child is SIGKILLed mid-
    transfer (host_down fault at a sim time while bytes are moving).
    The sim completes, the exit report names the cause, and the peer's
    accepted connection is reset (only its listener row survives)."""
    script = _write(tmp_path, "slow.py", SLOW_UPLOADER_SRC)
    out = str(tmp_path / "slow.out")
    scen = hosted_scenario(script, out, faults=[
        FaultSpec(kind="host_down", at=8 * 10**9, host="client")])
    sim = Simulation(scen, engine_cfg=EngineConfig(**ENGINE_CFG))
    r = sim.run()
    assert r.sim_time_ns == 30 * 10**9      # simulator survived
    info = r.hosted["client"]
    assert info["exit_status"] == -9        # SIGKILL
    assert "host_down" in info["cause"]
    assert info["clean"] is False
    assert r.stats[1, defs.ST_FAULTS] == 1
    # the server's accepted child connection was torn down by the
    # radiated RST; only the listener remains in its table
    assert np.asarray(sim.final_hosts.sk_used)[0].sum() == 1
    # the child never printed its completion line
    assert "done" not in open(out).read()


def test_hung_child_watchdog(tmp_path, monkeypatch):
    """A child that stops making RPC progress (busy loop in real code)
    is detected by the wall-clock watchdog, SIGKILLed, and diagnosed —
    instead of wedging the window loop inside _read_req forever."""
    monkeypatch.setenv("SHADOW_SHIM_WATCHDOG_S", "3")
    script = _write(tmp_path, "hang.py", BUSY_LOOP_SRC)
    out = str(tmp_path / "hang.out")
    scen = hosted_scenario(script, out, stop_s=20)
    r = Simulation(scen, engine_cfg=EngineConfig(**ENGINE_CFG)).run()
    assert r.sim_time_ns == 20 * 10**9
    info = r.hosted["client"]
    assert info["exit_status"] == -9
    assert info["cause"].startswith("hung:")
    assert info["clean"] is False


def test_fork_refused_with_diagnostic(tmp_path):
    """A forking binary cannot escape the sandbox: fork() returns
    ENOSYS in the child AND the refusal is recorded host-side in the
    exit report (the OP_VIOLATION diagnostic), so the escape attempt
    is visible without reading the child's stderr."""
    script = _write(tmp_path, "forker.py", FORKER_SRC)
    out = str(tmp_path / "fork.out")
    scen = hosted_scenario(script, out, stop_s=20)
    r = Simulation(scen, engine_cfg=EngineConfig(**ENGINE_CFG)).run()
    info = r.hosted["client"]
    assert "fork" in info["violations"]
    assert info["clean"] is True            # refusal is survivable
    text = open(out).read()
    import errno
    assert f"fork-refused errno={errno.ENOSYS}" in text


def test_hosted_restart_respawns_child(tmp_path):
    """host_down + host_up on a hosted host respawns a FRESH child:
    the final exit record shows a healthy end-of-run termination, not
    the fault kill (which a dead-only host would report)."""
    script = _write(tmp_path, "slow.py", SLOW_UPLOADER_SRC)
    out = str(tmp_path / "slow.out")
    scen = hosted_scenario(script, out, faults=[
        FaultSpec(kind="host_down", at=6 * 10**9, host="client",
                  until=10 * 10**9)])
    r = Simulation(scen, engine_cfg=EngineConfig(**ENGINE_CFG)).run()
    assert r.sim_time_ns == 30 * 10**9
    assert [f["kind"] for f in r.faults] == ["host_down", "host_up"]
    info = r.hosted["client"]
    # the LIVE (restarted) instance was reaped at end of run — proof
    # the respawn happened and ran past the kill
    assert info["cause"] == "terminated at end of run"
    assert info["clean"] is True


def test_acceptance_robustness_scenario(tmp_path):
    """The issue's acceptance schedule, verbatim: a mid-run hosted-
    child SIGKILL, one host kill/restart, and one link-down episode —
    completes without simulator crash, reports per-host exit causes in
    SimReport, and is bit-identical across two same-seed runs."""
    script = _write(tmp_path, "slow.py", SLOW_UPLOADER_SRC)
    faults = [
        FaultSpec(kind="link_down", at=4 * 10**9, until=5 * 10**9,
                  src="server", dst="client"),
        FaultSpec(kind="host_down", at=8 * 10**9, host="client"),
        FaultSpec(kind="host_down", at=12 * 10**9, host="server",
                  until=14 * 10**9),
    ]

    def run(i):
        out = str(tmp_path / f"acc{i}.out")
        scen = hosted_scenario(script, out, faults=faults, stop_s=20)
        sim = Simulation(scen, engine_cfg=EngineConfig(**ENGINE_CFG))
        return sim.run()

    r1, r2 = run(1), run(2)
    assert r1.sim_time_ns == 20 * 10**9           # no crash, full run
    assert np.array_equal(r1.stats, r2.stats)     # bit-identical
    assert [f["kind"] for f in r1.faults] == [
        "link_down", "link_up", "host_down", "host_down", "host_up"]
    for r in (r1, r2):
        info = r.hosted["client"]                 # per-host exit cause
        assert info["exit_status"] == -9
        assert "host_down" in info["cause"]
    # both hosts took fault events (client kill; server kill+restart)
    assert r1.stats[1, defs.ST_FAULTS] == 1
    assert r1.stats[0, defs.ST_FAULTS] == 2


def test_hosted_checkpoint_resume_replay(tmp_path):
    """Hosted resume in-process (the subprocess SIGKILL variant lives
    in tests/test_until_complete.py): a checkpointed hosted run's
    mid-run snapshot — pickled hosting tier + protocol journal — is
    restored into a FRESH Simulation, the child is respawned and
    fast-forwarded by journal replay, and the completed digest chain
    is byte-identical to an uninterrupted run's."""
    import numpy as np
    script = _write(tmp_path, "slow.py", SLOW_UPLOADER_SRC)

    dg_a = str(tmp_path / "a.jsonl")
    out_a = str(tmp_path / "a.out")
    Simulation(hosted_scenario(script, out_a, stop_s=26),
               engine_cfg=EngineConfig(**ENGINE_CFG)).run(
        digest=dg_a, digest_every=8)
    assert "done" in open(out_a).read()

    out_b = str(tmp_path / "b.out")
    dg_b = str(tmp_path / "b.jsonl")
    base = str(tmp_path / "ck")
    scen_b = hosted_scenario(script, out_b, stop_s=26)
    Simulation(scen_b, engine_cfg=EngineConfig(**ENGINE_CFG)).run(
        digest=dg_b, digest_every=8, checkpoint_path=base,
        checkpoint_every_s=2, checkpoint_keep=16)

    # rewind the world to a mid-run snapshot: truncate the chain to
    # the stamped position (as a crash just after that save would
    # leave it) and resume a fresh Simulation from it
    from shadow_tpu.engine import checkpoint as ck
    snaps = sorted(ck.CheckpointStore(base).snapshots())
    snap_path = snaps[len(snaps) // 2]
    z = np.load(snap_path)
    n_recs = int(z["__digest_records__"])
    assert os.path.exists(snap_path + ".hosted"), "no hosted sidecar"
    lines = open(dg_b).read().splitlines()
    assert 0 < n_recs < len(lines)
    with open(dg_b, "w") as f:
        f.write("\n".join(lines[:n_recs]) + "\n")
    open(out_b, "w").close()             # the crash also loses stdout

    scen_c = hosted_scenario(script, out_b, stop_s=26)
    sim_c = Simulation(scen_c, engine_cfg=EngineConfig(**ENGINE_CFG))
    r = sim_c.run(
        digest=dg_b, digest_every=8, resume_from=snap_path)
    assert r.sim_time_ns == 26 * 10**9
    # the resumed run takes no snapshots of its own: restore() must
    # drop the replayed journals instead of buffering traffic forever
    assert all(getattr(a, "_journal", None) is None
               for a in sim_c.hosting.apps.values())
    # the respawned child replayed its journal and then really
    # finished the transfer
    assert "done" in open(out_b).read()
    assert open(dg_a, "rb").read() == open(dg_b, "rb").read(), (
        "resumed hosted digest chain differs from uninterrupted run")


def test_fopen_urandom_deterministic(tmp_path):
    """fopen("/dev/urandom") serves host-PRNG bytes (glibc fopen
    bypasses the open() interposition — ADVICE r5): same seed, same
    bytes, across two full simulator runs."""
    script = _write(tmp_path, "fop.py", FOPEN_ENTROPY_SRC)
    outs = []
    for i in range(2):
        out = str(tmp_path / f"fop{i}.out")
        scen = hosted_scenario(script, out, stop_s=10)
        Simulation(scen, engine_cfg=EngineConfig(**ENGINE_CFG)).run()
        outs.append(open(out).read().strip())
    assert outs[0].startswith("entropy=") and len(outs[0]) > 10
    assert outs[0] == outs[1]
