"""Deterministic fault injection (engine.faults).

The robustness tier's modeled half: host kill/restart, link flaps and
loss/latency episodes compile from config to a seed-stable schedule
executed at exact sim times — a scenario with faults must complete
without simulator crash, report what it did (SimReport.faults /
ST_FAULTS / hosted causes), and be bit-identical across same-seed dual
runs (the reference's determinism contract, shd-test-determinism.c,
extended to hostile schedules).
"""

import numpy as np
import pytest

from shadow_tpu.core.config import (FaultSpec, HostSpec, ProcessSpec,
                                    Scenario, load_xml)
from shadow_tpu.engine import defs
from shadow_tpu.engine.sim import Simulation
from shadow_tpu.engine.state import EngineConfig


def ping_scenario(faults=(), stop_s=10):
    return Scenario(
        stop_time=stop_s * 10**9,
        topology_graphml=PING_TOPOLOGY,
        hosts=[
            HostSpec(id="server", processes=[
                ProcessSpec(plugin="pingserver", start_time=10**9,
                            arguments="port=8000")]),
            HostSpec(id="client", processes=[
                ProcessSpec(plugin="ping", start_time=2 * 10**9,
                            arguments="peer=server port=8000 "
                                      "interval=1s size=64 count=5")]),
        ],
        faults=list(faults),
    )


PING_TOPOLOGY = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="packetloss" attr.type="double" for="edge" id="d9" />
  <key attr.name="latency" attr.type="double" for="edge" id="d7" />
  <key attr.name="bandwidthup" attr.type="int" for="node" id="d4" />
  <key attr.name="bandwidthdown" attr.type="int" for="node" id="d3" />
  <key attr.name="packetloss" attr.type="double" for="node" id="d0" />
  <graph edgedefault="undirected">
    <node id="poi-1"><data key="d0">0.0</data>
      <data key="d3">17038</data><data key="d4">2251</data></node>
    <edge source="poi-1" target="poi-1">
      <data key="d7">25.0</data><data key="d9">0.0</data></edge>
  </graph>
</graphml>"""


# --- schedule compilation: bad configs fail at build, loudly ------------

def test_compile_validation():
    from shadow_tpu.engine.faults import compile_faults

    name_to_idx = {"a": 0, "b": 1}
    vertex = np.zeros(2, np.int32)
    with pytest.raises(ValueError, match="unknown kind"):
        compile_faults([FaultSpec(kind="meteor", at=1)],
                       name_to_idx, vertex)
    with pytest.raises(ValueError, match="unknown host"):
        compile_faults([FaultSpec(kind="link_down", at=1,
                                  src="a", dst="nope")],
                       name_to_idx, vertex)
    with pytest.raises(ValueError, match="host="):
        compile_faults([FaultSpec(kind="host_down", at=1, host="nope")],
                       name_to_idx, vertex)
    with pytest.raises(ValueError, match="until > at"):
        compile_faults([FaultSpec(kind="loss", at=5, rate=0.5,
                                  src="a", dst="b")],
                       name_to_idx, vertex)
    with pytest.raises(ValueError, match="rate"):
        compile_faults([FaultSpec(kind="loss", at=1, until=2, rate=1.5,
                                  src="a", dst="b")],
                       name_to_idx, vertex)
    with pytest.raises(ValueError, match="extra > 0"):
        compile_faults([FaultSpec(kind="latency", at=1, until=2,
                                  src="a", dst="b")],
                       name_to_idx, vertex)
    # a valid episode expands into a begin/end pair, time-sorted
    evs = compile_faults(
        [FaultSpec(kind="loss", at=5, until=9, rate=0.5,
                   src="a", dst="b"),
         FaultSpec(kind="host_down", at=3, host="a", until=7)],
        name_to_idx, vertex)
    assert [(e.t, e.kind) for e in evs] == [
        (3, "host_down"), (5, "loss_begin"), (7, "host_up"),
        (9, "loss_end")]


def test_xml_fault_parsing():
    scen = load_xml("""<shadow stoptime="10">
      <topology path="unused.graphml"/>
      <host id="a"><process plugin="ping" arguments=""/></host>
      <fault kind="host_down" at="3s" host="a" until="7s"/>
      <fault kind="loss" at="5" until="9" rate="0.25" src="a" dst="a"/>
      <fault kind="latency" at="2" until="4" extra="30ms" src="a" dst="a"/>
    </shadow>""")
    assert len(scen.faults) == 3
    assert scen.faults[0].kind == "host_down"
    assert scen.faults[0].at == 3 * 10**9
    assert scen.faults[0].until == 7 * 10**9
    assert scen.faults[1].rate == 0.25
    assert scen.faults[2].extra_ns == 30 * 10**6


# --- executed schedules -------------------------------------------------

def test_churn_and_flap_deterministic():
    """The acceptance schedule's modeled core: one host kill/restart
    plus one link-down episode completes without a crash, records the
    applied faults, and dual same-seed runs are bit-identical."""
    faults = [
        FaultSpec(kind="link_down", at=4 * 10**9, until=6 * 10**9,
                  src="server", dst="client"),
        FaultSpec(kind="host_down", at=7 * 10**9, host="server",
                  until=8 * 10**9),
    ]
    r1 = Simulation(ping_scenario(faults)).run()
    r2 = Simulation(ping_scenario(faults)).run()
    assert np.array_equal(r1.stats, r2.stats)
    assert [f["kind"] for f in r1.faults] == [
        "link_down", "link_up", "host_down", "host_up"]
    # kill + restart both landed on the server
    assert r1.stats[0, defs.ST_FAULTS] == 2
    assert r1.sim_time_ns == 10 * 10**9
    # pings during the dead link window were dropped on the floor
    assert r1.total(defs.ST_PKTS_DROP_NET) > 0
    assert r1.stats[1, defs.ST_RTT_COUNT] < 5


def test_loss_episode_drops_and_restores():
    """A rate-1.0 loss episode blacks the path out for its window and
    composes back to the base reliability after ``until``."""
    faults = [FaultSpec(kind="loss", at=3500 * 10**6, until=5500 * 10**6,
                        rate=1.0, src="server", dst="client")]
    r = Simulation(ping_scenario(faults)).run()
    base = Simulation(ping_scenario()).run()
    assert base.total(defs.ST_PKTS_DROP_NET) == 0
    assert base.stats[1, defs.ST_RTT_COUNT] == 5
    assert r.total(defs.ST_PKTS_DROP_NET) > 0
    # echoes outside the episode still complete
    assert 0 < r.stats[1, defs.ST_RTT_COUNT] < 5


def test_latency_episode_raises_rtt():
    """Added path latency during the episode shows up in the measured
    RTTs; the restore returns later pings to the base RTT (the mean
    sits strictly between base and base+2*extra)."""
    extra_ms = 40
    faults = [FaultSpec(kind="latency", at=3500 * 10**6,
                        until=6500 * 10**6, extra_ns=extra_ms * 10**6,
                        src="server", dst="client")]
    r = Simulation(ping_scenario(faults)).run()
    mean_us = r.summary()["mean_rtt_us"]
    assert 50_000 < mean_us < 50_000 + 2 * extra_ms * 1000
    assert r.stats[1, defs.ST_RTT_COUNT] == 5   # nothing lost


def test_host_kill_rst_frees_tcp_peer():
    """Killing a host mid-TCP-transfer converts its connections to
    RSTs toward the peer: the peer's socket frees instead of
    retransmitting into the void, and the sim completes."""
    scen = Scenario(
        stop_time=40 * 10**9,
        topology_graphml=PING_TOPOLOGY,
        hosts=[
            HostSpec(id="server", processes=[
                ProcessSpec(plugin="bulkserver", start_time=10**9,
                            arguments="port=80")]),
            HostSpec(id="client", processes=[
                ProcessSpec(plugin="bulk", start_time=2 * 10**9,
                            arguments="peer=server port=80 "
                                      "size=5000000 count=1")]),
        ],
        faults=[FaultSpec(kind="host_down", at=4 * 10**9,
                          host="server")],
    )
    sim = Simulation(scen)
    r = sim.run()
    assert r.sim_time_ns == 40 * 10**9       # no crash, ran to stop
    assert r.stats[0, defs.ST_FAULTS] == 1
    # the transfer was cut short...
    assert 0 < r.total(defs.ST_BYTES_RECV) < 5_000_000
    # ...and the RST freed the client's socket (no zombie retransmit
    # loop: its whole table is empty at end of run)
    assert not np.asarray(sim.final_hosts.sk_used)[1].any()
